"""Block-quantized wire codec — int8/bf16 payloads for collectives.

ROADMAP "Quantized wire formats for collectives" (EQuARX,
arXiv:2506.17615): the redistribution planner's chunked
all-to-alls/all-gathers/rings (PRs 3-6) and the ``optim/`` DP gradient
all-reduces ship full-width f32 payloads, and on every ICI-bound row
the ``wire`` leg of the ``max(wire, copy)`` critical-path model is the
binding term. Halving (int8: quartering) the bytes on the wire halves
that leg directly. This module is the codec; the redistribution
planner/executor thread it through the collective schedules
(``quantize``/``dequantize`` step kinds, ``HEAT_TPU_WIRE_QUANT`` gate)
and ``optim.DataParallelOptimizer`` exposes it as an opt-in
quantized-gradient DP mode with an error-feedback carry.

Wire format (mode ``"int8"``)
-----------------------------
The flat row-major payload is tiled in **1024-element blocks** — one
f32 ``(8, 128)`` VREG tile of the flat buffer — and each tile carries
one f32 scale:

* scale = finite-absmax(tile) / 126 (0-tiles get scale 1), stored as
  raw f32 bytes appended after the int8 payload;
* finite values quantize to ``round(x / scale)`` clipped to
  ``[-126, 126]`` — max elementwise error ``scale/2 = absmax/252``,
  i.e. relative to the tile absmax strictly under the pinned
  ``tolerance("int8") = 2**-7``;
* the three reserved codes make the codec **NaN/inf-safe** (payloads
  survive the round trip exactly): ``-128`` = NaN, ``127`` = +inf,
  ``-127`` = -inf;
* ``-0.0`` collapses to ``+0.0`` (int8 has no signed zero) — the same
  documented tie-class collapse as the sort kernels' monotone
  transforms.

Wire bytes for ``n`` f32 elements: ``pad1024(n) + 4*pad1024(n)/1024``
= 1028/4096 ≈ 0.251 of the raw 4n — comfortably under the acceptance ceiling of
0.5.

Mode ``"bf16"`` is the round-to-nearest-even f32→bf16 cast shipped as
raw bytes (ratio exactly 0.5). bf16 shares f32's exponent range, so
per-tile scaling buys nothing — no scales travel, and ±0/±inf/NaN are
preserved bit-exactly by the format itself. Max relative error is a
half-ulp of the 8-bit significand: the pinned ``tolerance("bf16") =
2**-8``.

Integer/bool payloads are **rejected** by :func:`encode_blocks`
(callers keep them lossless — the planner's admissibility policy never
routes them here), and the escape hatch / non-admissible paths ship
raw bytes exact-bit.

Every encode/decode body runs under ``jax.named_scope("wire_codec_
<mode>")``: the stamp lands in the trace the same way the executor's
``redist_plan_<id>`` scopes do, and shardlint's SL104 narrowing arm
keys on it — a *stamped* f32→int8 convert before a collective is the
sanctioned codec, an unstamped one is an accident that trips at error
severity (``tests/analysis_fixtures.int8_wire_program``).

The formulations are pure XLA (reshape/clip/round/bitcast — all
VPU-friendly, no gather/scatter), so there is no Pallas path to gate:
the codec compiles into the same jitted shard_map programs as the
collectives it feeds and fuses with the chunk slicing/scatter copies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from typing import Dict, Optional, Tuple

__all__ = [
    "MODES",
    "TILE",
    "compose_tolerance",
    "dp_step_model",
    "dp_step_model_2tier",
    "decode_blocks",
    "encode_blocks",
    "hierarchical_allreduce_sum",
    "quantized_allreduce_sum",
    "tolerance",
    "wire_bytes",
    "wire_ratio",
]

#: elements per scale tile: one f32 (8, 128) VREG tile of the flat buffer
TILE = 1024

#: supported wire codecs
MODES = ("int8", "bf16")

# int8 code points: normal range +/-126, three reserved specials
_QMAX = 126
_NAN = -128
_PINF = 127
_NINF = -127

#: pinned numerics tolerance per mode: max |x - roundtrip(x)| relative
#: to the governing absmax (the scale tile for int8, |x| for bf16).
#: The planner's admissibility policy quotes these; tests pin them.
_TOL = {"int8": 2.0 ** -7, "bf16": 2.0 ** -8}


def _check_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(f"unknown wire codec {mode!r} (modes: {MODES})")
    return mode


def tolerance(mode: str) -> float:
    """The pinned numerics tolerance of ``mode``: the codec guarantees
    ``|x - decode(encode(x))| <= tolerance(mode) * absmax`` per scale
    tile (int8) / per element (bf16) for finite payloads, and exact
    round-trip for ±inf/NaN."""
    return _TOL[_check_mode(mode)]


def compose_tolerance(tols) -> float:
    """The end-to-end relative error bound of a payload element that
    traverses codec legs with per-leg tolerances ``tols``: first-order
    composition ``sum(tols)`` (each leg adds at most its tol relative
    to the governing absmax; cross terms are O(tol²), below the pinned
    bounds' resolution). An element that crosses the wire once under
    one mode therefore composes to exactly ``tolerance(mode)`` — the
    identity the ``tolerance`` plan invariant
    (:func:`ht.analysis.check_tolerance`) proves against the
    schedule-level ``quant.tol`` annotation. Cross-ITERATION
    composition is the DP optimizer's error-feedback contract
    (optim/dp_optimizer.py keeps the residual carry in f32), not a
    plan property. Empty ``tols`` (no codec leg) compose to 0.0:
    staging/relayout/overlap steps are exact-bit."""
    return float(sum(float(t) for t in tols))


def _pad_tiles(n: int) -> int:
    return -(-int(n) // TILE) * TILE


def wire_bytes(n_elems: int, mode: str) -> int:
    """Encoded bytes for ``n_elems`` float32 elements (raw = 4·n)."""
    _check_mode(mode)
    n = int(n_elems)
    if n <= 0:
        return 0
    if mode == "bf16":
        return 2 * n
    npad = _pad_tiles(n)
    return npad + 4 * (npad // TILE)


def wire_ratio(n_elems: int, mode: str) -> float:
    """``wire_bytes / raw_bytes`` for ``n_elems`` f32 elements
    (≈ 0.251 for int8, exactly 0.5 for bf16)."""
    n = int(n_elems)
    if n <= 0:
        return 1.0
    return wire_bytes(n, mode) / (4.0 * n)


# --------------------------------------------------------------------- #
# the codec                                                             #
# --------------------------------------------------------------------- #
def _reject_non_float(x) -> None:
    if jnp.dtype(x.dtype) != jnp.float32:
        raise TypeError(
            f"wire codec encodes float32 payloads only, got {x.dtype} — "
            "integer/bool/wide-float buffers stay lossless on the wire "
            "(the planner's admissibility policy never quantizes them)"
        )


def _encode_int8(x: jax.Array) -> jax.Array:
    """(B, n) f32 → (B, wire_bytes(n)) int8: per-1024-tile scaled int8
    payload + the f32 scales as trailing raw bytes."""
    B, n = x.shape
    npad = _pad_tiles(n)
    nt = npad // TILE
    xp = jnp.pad(x, ((0, 0), (0, npad - n))) if npad != n else x
    xt = xp.reshape(B, nt, TILE)
    finite = jnp.isfinite(xt)
    amax = jnp.max(jnp.where(finite, jnp.abs(xt), 0.0), axis=-1)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0).astype(jnp.float32)
    safe = jnp.where(finite, xt, 0.0)
    q = jnp.clip(jnp.round(safe / scale[..., None]), -_QMAX, _QMAX).astype(jnp.int8)
    q = jnp.where(jnp.isnan(xt), jnp.int8(_NAN), q)
    q = jnp.where(xt == jnp.inf, jnp.int8(_PINF), q)
    q = jnp.where(xt == -jnp.inf, jnp.int8(_NINF), q)
    sbytes = lax.bitcast_convert_type(scale, jnp.int8).reshape(B, 4 * nt)
    return jnp.concatenate([q.reshape(B, npad), sbytes], axis=1)


def _decode_int8(w: jax.Array, n: int) -> jax.Array:
    B = w.shape[0]
    npad = _pad_tiles(n)
    nt = npad // TILE
    q = w[:, :npad].reshape(B, nt, TILE)
    scale = lax.bitcast_convert_type(
        w[:, npad : npad + 4 * nt].reshape(B, nt, 4), jnp.float32
    )
    vals = q.astype(jnp.float32) * scale[..., None]
    vals = jnp.where(q == _NAN, jnp.float32(jnp.nan), vals)
    vals = jnp.where(q == _PINF, jnp.float32(jnp.inf), vals)
    vals = jnp.where(q == _NINF, jnp.float32(-jnp.inf), vals)
    return vals.reshape(B, npad)[:, :n]


def encode_blocks(x: jax.Array, mode: str) -> jax.Array:
    """Encode a ``(B, n)`` float32 block batch to its ``(B,
    wire_bytes(n))`` int8 wire buffer — row ``d`` is one independently
    decodable payload (the executor's per-destination collective
    block). Pure permutation/round/bitcast XLA: fuses into the
    surrounding shard_map program."""
    _check_mode(mode)
    _reject_non_float(x)
    if x.ndim != 2:
        raise ValueError(f"encode_blocks expects (B, n), got {x.shape}")
    with jax.named_scope(f"wire_codec_{mode}"):
        if mode == "bf16":
            return lax.bitcast_convert_type(
                x.astype(jnp.bfloat16), jnp.int8
            ).reshape(x.shape[0], 2 * x.shape[1])
        return _encode_int8(x)


def decode_blocks(w: jax.Array, n: int, mode: str) -> jax.Array:
    """Inverse of :func:`encode_blocks`: ``(B, wire_bytes(n))`` int8 →
    ``(B, n)`` float32."""
    _check_mode(mode)
    n = int(n)
    with jax.named_scope(f"wire_codec_{mode}"):
        if mode == "bf16":
            h = lax.bitcast_convert_type(
                w.reshape(w.shape[0], n, 2), jnp.bfloat16
            )
            return h.astype(jnp.float32)
        return _decode_int8(w, n)


# --------------------------------------------------------------------- #
# quantized all-reduce (the DP gradient wire) + error feedback          #
# --------------------------------------------------------------------- #
def quantized_allreduce_sum(
    h: jax.Array, axis_name: str, p: int, mode: str
) -> Tuple[jax.Array, jax.Array]:
    """Sum a per-device flat f32 vector over ``axis_name`` with
    block-quantized wire payloads. shard_map-internal (needs the bound
    axis); census: ONE all-to-all (the reduce-scatter leg: every device
    receives the p encoded partials of its block and sums them
    full-width) + ONE all-gather (the encoded reduced blocks) — the
    decomposed all-reduce at ``wire_ratio`` of the psum bytes.

    Returns ``(global_sum, residual)``: ``residual`` is THIS device's
    error-feedback carry — the stage-1 encode error of its own
    contribution plus (on the block it owns) the stage-2 encode error
    of the reduced block. Feeding ``residual`` back into the next
    step's ``h`` is the standard EF-compression iteration: the
    compression error is re-injected instead of lost, so iterative
    consumers (SGD) see an unbiased long-run gradient.
    """
    _check_mode(mode)
    _reject_non_float(h)
    (n,) = h.shape
    k = -(-n // p)
    npad = k * p
    hp = jnp.pad(h, (0, npad - n)) if npad != n else h
    blocks = hp.reshape(p, k)
    wire = encode_blocks(blocks, mode)
    dechat = decode_blocks(wire, k, mode)
    resid = (blocks - dechat).reshape(npad)[:n]
    # reduce-scatter leg: block d of every device lands on device d
    recv = lax.all_to_all(wire, axis_name, 0, 0, tiled=True)
    red = jnp.sum(decode_blocks(recv, k, mode), axis=0)
    # gather leg: the reduced blocks travel encoded too
    wire2 = encode_blocks(red[None], mode)
    red_hat = decode_blocks(wire2, k, mode)[0]
    gathered = lax.all_gather(wire2[0], axis_name)
    out = decode_blocks(gathered, k, mode).reshape(npad)[:n]
    # stage-2 residual: the owner of block i re-injects the encode
    # error of the reduced block it shipped
    i = lax.axis_index(axis_name)
    r2 = lax.dynamic_update_slice(jnp.zeros(npad, h.dtype), red - red_hat, (i * k,))
    return out, resid + r2[:n]


def hierarchical_allreduce_sum(
    h: jax.Array, axis_name: str, n_slices: int, chips_per_slice: int, mode: str
) -> Tuple[jax.Array, jax.Array]:
    """Two-tier quantized all-reduce (ISSUE 8): intra-slice
    reduce-scatter → inter-slice exchange of the reduced+ENCODED shard →
    intra-slice all-gather. shard_map-internal over a slice-major mesh
    of ``n_slices × chips_per_slice`` devices.

    Census: ONE intra-slice all-to-all (full-width f32 — the ICI tier
    is wire-cheap, keeping it exact halves the codec error for free),
    ONE inter-slice all-gather of the encoded slice-reduced blocks (the
    only DCN traffic: ``(S-1)·wire_bytes(n/C)`` per chip — ~1/(C·4) of
    what a flat f32 all-reduce would push across DCN at int8), and ONE
    intra-slice all-gather of the globally reduced blocks (f32).

    Returns ``(global_sum, residual)`` like
    :func:`quantized_allreduce_sum`: ``residual`` is this device's
    error-feedback carry — the encode error of the slice-reduced block
    it shipped across DCN, placed at that block's offset. Each chip
    position's S owners inject disjoint per-slice errors whose sum is
    the total compression error, so feeding the carry back next step
    keeps the long-run gradient unbiased (the same EF iteration as the
    flat wire).
    """
    _check_mode(mode)
    _reject_non_float(h)
    from ..core.communication import Topology

    S, C = int(n_slices), int(chips_per_slice)
    (n,) = h.shape
    k = -(-n // C)
    npad = k * C
    hp = jnp.pad(h, (0, npad - n)) if npad != n else h
    blocks = hp.reshape(C, k)
    topo = Topology(S, C)
    g_chip = topo.chip_axis_groups()
    g_slice = topo.slice_axis_groups()
    # stage 1 (ICI, exact): intra-slice reduce-scatter via a2a — chip c
    # of each slice collects its slice-mates' block-c partials and sums
    recv = lax.all_to_all(blocks, axis_name, 0, 0, tiled=True, axis_index_groups=g_chip)
    red_s = jnp.sum(recv, axis=0)  # (k,): this chip's block, slice-reduced
    # stage 2 (DCN, encoded): gather the S slice-partials of this block
    # across slices, decode, sum — the reduced+encoded shard exchange.
    # The gather runs under the wire-codec named scope: shardlint's
    # SL107 recognizes the stamp as the sanctioned (encoded, decomposed)
    # cross-tier wire and reports it at info severity.
    wire = encode_blocks(red_s[None], mode)
    resid = red_s - decode_blocks(wire, k, mode)[0]  # EF: my encode error
    with jax.named_scope(f"wire_codec_{mode}"):
        gath = lax.all_gather(wire[0], axis_name, axis_index_groups=g_slice)
    red_g = jnp.sum(decode_blocks(gath, k, mode), axis=0)  # (k,): global
    # stage 3 (ICI, exact): intra-slice all-gather of the C reduced blocks
    full = lax.all_gather(red_g, axis_name, axis_index_groups=g_chip)
    out = full.reshape(npad)[:n]
    c_idx = lax.axis_index(axis_name) % C
    r = lax.dynamic_update_slice(jnp.zeros(npad, h.dtype), resid, (c_idx * k,))
    return out, r[:n]


# --------------------------------------------------------------------- #
# analytic v5e-64 DP-step model (no multi-chip hardware attached)       #
# --------------------------------------------------------------------- #
#: v5e per-chip bidirectional ICI (docs/PERF.md multi-chip model)
V5E_ICI_BPS = 200e9

#: per-chip DCN bandwidth across slices (core.communication.DCN_BPS)
V5E_DCN_BPS = 25e9


def dp_step_model(
    param_bytes: int,
    compute_s: float,
    p: int = 64,
    ici_bps: float = V5E_ICI_BPS,
    mode: str = "int8",
) -> Dict[str, float]:
    """Modeled DP step time on the analytic v5e-64 cost model
    (docs/PERF.md): the gradient all-reduce moves ``2·(p-1)/p·B`` bytes
    per chip over ICI, the step costs ``max(compute, wire)`` (XLA
    overlaps the collective with compute — PR 6's critical-path
    arithmetic), and the codec scales only the wire term. For an
    ICI-bound layer (wire > compute) the int8 codec's ~3.94× wire
    reduction converts directly into step time until compute binds —
    the acceptance criterion pins ≥ 1.5× on such layers."""
    _check_mode(mode)
    param_bytes = int(param_bytes)
    crossing = 2.0 * (p - 1) / p * param_bytes
    wire_raw = crossing / ici_bps
    ratio = wire_ratio(param_bytes // 4, mode)
    wire_q = wire_raw * ratio
    step_raw = max(float(compute_s), wire_raw)
    step_q = max(float(compute_s), wire_q)
    return {
        "param_bytes": param_bytes,
        "mesh": p,
        "mode": mode,
        "wire_ratio": round(ratio, 4),
        "wire_s_raw": wire_raw,
        "wire_s_quant": wire_q,
        "step_s_raw": step_raw,
        "step_s_quant": step_q,
        "model_speedup": round(step_raw / step_q, 3) if step_q > 0 else 1.0,
        "ici_bound": wire_raw > float(compute_s),
    }


def dp_step_model_2tier(
    param_bytes: int,
    compute_s: float,
    n_slices: int = 2,
    chips_per_slice: int = 8,
    ici_bps: float = V5E_ICI_BPS,
    dcn_bps: float = V5E_DCN_BPS,
    mode: str = "int8",
) -> Dict[str, float]:
    """Modeled DP step time at a TWO-TIER mesh (ISSUE 8), analytic like
    :func:`dp_step_model` — no DCN hardware is attached.

    Baseline (``flat+f32``): a topology-blind gradient all-reduce whose
    replica group spans slices completes at the DCN tier — every one of
    its ``2·(p-1)/p·B`` per-chip bytes is priced at ``dcn_bps``.

    Hierarchical+codec (:func:`hierarchical_allreduce_sum`): the two
    intra-slice legs move ``2·(C-1)/C·B`` at ICI speed, and the only
    DCN traffic is the encoded slice-reduced shard —
    ``(S-1)·wire_bytes(B/C)`` per chip. The step costs
    ``max(compute, wire)``; ``model_speedup`` is the flat/hierarchical
    step-time ratio (the ``dp_step_quant_2x8`` bench row pins ≥ 2× on
    DCN-bound layers)."""
    _check_mode(mode)
    S, C = int(n_slices), int(chips_per_slice)
    p = S * C
    param_bytes = int(param_bytes)
    wire_flat = 2.0 * (p - 1) / p * param_bytes / dcn_bps
    shard = param_bytes // C
    dcn_bytes = (S - 1) * wire_bytes(shard // 4, mode)
    ici_bytes = 2 * (C - 1) * param_bytes // C
    wire_hier = ici_bytes / ici_bps + dcn_bytes / dcn_bps
    step_flat = max(float(compute_s), wire_flat)
    step_hier = max(float(compute_s), wire_hier)
    return {
        "param_bytes": param_bytes,
        "mesh": p,
        "topology": f"{S}x{C}",
        "mode": mode,
        "dcn_bytes": int(dcn_bytes),
        "ici_bytes": int(ici_bytes),
        "wire_s_flat": wire_flat,
        "wire_s_hier": wire_hier,
        "step_s_flat": step_flat,
        "step_s_hier": step_hier,
        "model_speedup": round(step_flat / step_hier, 3) if step_hier > 0 else 1.0,
        "dcn_bound": wire_flat > float(compute_s),
    }
