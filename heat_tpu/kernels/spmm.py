"""Brick SpMM / SDDMM kernels for the DBCSR format.

The compute unit is the DBCSR brick — one (8, 128) f32 VREG tile
(sparse/dbcsr_matrix.py) — and both contraction families reduce to a
stream of dense (8,128)x(128,k) brick matmuls plus one masked
segment-sum over brick rows:

* **SpMM** ``y = A @ x``: per stored brick ``t``, ``contrib[t] =
  bdata[t] @ xb[bcol[t]]`` where ``xb`` is the dense operand viewed as
  (nb, 128, k) brick slabs; contributions land on the brick's 8 output
  rows via ``segment_sum``. Straddle/pad bricks route their non-owned
  rows to a dropped segment through the precomputed ``bmask``.
* **SDDMM** ``C = S \\circ (U @ V^T)``: per stored brick, ``out[t] =
  sdata[t] * (ub[brow[t]] @ vb[bcol[t]]^T)`` — the sampled dense-dense
  product that only ever computes the stored tiles.

Two implementations per family, dispatched by ``HEAT_TPU_SPMM_KERNEL``
(core/gates.py):

* ``xla`` (the oracle/floor, gate ``0``): brick-level ``take`` of the
  dense operand — a coarse-grained (128*k)-element contiguous gather
  per brick, NOT a per-element gather — followed by one batched matmul
  and the segment-sum. Pure XLA, runs anywhere, and is the
  bit-identity reference.
* ``pallas`` (gate ``1``): a scalar-prefetch brick kernel — the brick
  column map rides ``PrefetchScalarGridSpec`` so each grid step DMAs
  exactly the X (or U/V) brick it needs straight into VMEM and issues
  one MXU matmul. Gather-free by construction: the index never touches
  the vector units. On CPU the same kernel runs under
  ``interpret=True`` (the ci.sh forced leg), so the path is testable
  off-TPU; the accumulation stays in the SAME XLA segment-sum as the
  oracle, which is what makes kernel-on == kernel-off bit-identical.

``auto`` resolves to the oracle off-TPU and to a per-signature
autotune on TPU (the PR 4/5 pattern: eager, timed with a scalar
read-back, cached per (family, B, k, dtype) signature). Telemetry:
``sparse.kernel.hit`` counts brick-kernel dispatches,
``sparse.kernel.fallback`` oracle dispatches.

Distribution: the per-device slab layout makes every device's bricks
sufficient for its canonical output rows, so the distributed programs
are ``shard_map`` LOCAL programs — 0 collectives, pinned by
tests/test_spmm.py's census. A split dense operand is resharded to
replicated BEFORE the local program through ``comm.reshard_phys`` (the
redistribution planner: plan-stamped, shardlint info-downgraded).

Accumulation dtype: low-precision brick data (bf16/f16) is widened to
f32 for the brick matmuls and the segment-sum, cast back at the end —
SL601-clean by construction.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import gates as _gates
from ..core import _padding

try:  # Pallas is optional at import time (CPU-only wheels)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - toolchain without pallas
    pl = None
    pltpu = None

__all__ = [
    "spmm_kernel_mode",
    "decide",
    "last_decisions",
    "spmm_bcsr_program",
    "sddmm_bcsr_program",
]

BR, BC = 8, 128  # brick sublanes x lanes (sparse.dbcsr_matrix.BRICK_SHAPE)


# --------------------------------------------------------------------- #
# gate / dispatch                                                       #
# --------------------------------------------------------------------- #
def _mode() -> str:
    v = _gates.get("HEAT_TPU_SPMM_KERNEL", "auto").strip().lower()
    if v in ("0", "off", "false"):
        return "0"
    if v in ("1", "on", "true", "force"):
        return "1"
    return "auto"


def spmm_kernel_mode() -> str:
    """The resolved ``HEAT_TPU_SPMM_KERNEL`` mode (``"0"``/``"1"``/
    ``"auto"``) — introspection for tests and bench records. Cache
    staleness on env flips is handled by keying the compiled programs
    on the DECIDED path string this mode feeds (see :func:`decide`)."""
    return _mode()


def _inc(name: str) -> None:
    from ..observability import telemetry

    telemetry.inc(name)


#: last dispatch decision per signature — bench/test introspection
_DECISIONS: dict = {}

#: autotune winners per signature (TPU only; only autotuned entries
#: may answer ``auto`` mode)
_AUTOTUNE: dict = {}


def last_decisions() -> dict:
    return dict(_DECISIONS)


def _acc_dtype(jt: jnp.dtype) -> jnp.dtype:
    """f32 accumulation for sub-f32 brick data (SL601 by construction)."""
    if jnp.dtype(jt) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.dtype(jnp.float32)
    return jnp.dtype(jt)


def _pallas_available() -> bool:
    return pl is not None and pltpu is not None


def decide(family: str, B: int, k: int, jdtype: str) -> str:
    """Resolve the implementation path (``"xla"``/``"pallas"``) for one
    (family, bricks, dense-cols, dtype) signature under the gate."""
    mode = _mode()
    sig = (family, int(B), int(k), str(jdtype))
    if mode == "0" or not _pallas_available():
        d = {"path": "xla", "why": "gate=0" if mode == "0" else "no-pallas"}
    elif mode == "1":
        d = {"path": "pallas", "why": "gate=1"}
    elif jax.default_backend() != "tpu":
        # auto off-TPU: the interpreted kernel is a debugging vehicle,
        # never a performance one — oracle wins without measurement
        d = {"path": "xla", "why": "auto:cpu-oracle"}
    else:
        d = _AUTOTUNE.get(sig)
        if d is None:
            d = _autotune(sig)
    _DECISIONS[sig] = d
    _inc("sparse.kernel.hit" if d["path"] == "pallas" else "sparse.kernel.fallback")
    return d["path"]


def _autotune(sig) -> dict:
    """Time both paths on synthetic operands of this signature (TPU
    only, eager — never under a trace) and cache the winner. The PR 4/5
    autotune shape: scalar read-back forces completion, median of 3."""
    family, B, k, jdtype = sig
    jt = jnp.dtype(jdtype)
    nb = max(2, min(B, 64))
    key = jax.random.key(7)
    bdata = jax.random.normal(key, (B, BR, BC), dtype=jnp.float32).astype(jt)
    bcol = (jnp.arange(B, dtype=jnp.int32) * 7) % nb
    if family == "spmm":
        xb = jax.random.normal(key, (nb, BC, k), dtype=jnp.float32).astype(jt)

        def run_xla():
            return _contrib_xla(bdata, xb, bcol, jt)

        def run_pallas():
            return _brick_spmm_call(B, nb, k, jt.name, False)(bcol, bdata, xb)
    else:
        mb = max(2, min(B, 64))
        brow = (jnp.arange(B, dtype=jnp.int32) * 3) % mb
        ub = jax.random.normal(key, (mb, BR, k), dtype=jnp.float32).astype(jt)
        vb = jax.random.normal(key, (nb, BC, k), dtype=jnp.float32).astype(jt)

        def run_xla():
            return _sddmm_xla(bdata, ub, vb, brow, bcol, jt)

        def run_pallas():
            return _brick_sddmm_call(B, mb, nb, k, jt.name, False)(
                brow, bcol, bdata, ub, vb
            )

    def _time(fn) -> float:
        fn()  # compile + warm
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn()
            float(jnp.asarray(out).ravel()[0])  # sync read-back
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[1]

    try:
        t_k = _time(run_pallas)
        t_o = _time(run_xla)
        d = {
            "path": "pallas" if t_k < t_o else "xla",
            "why": f"autotune:{t_k * 1e6:.0f}us-vs-{t_o * 1e6:.0f}us",
            "autotuned": True,
        }
    except Exception as e:  # pragma: no cover - TPU-side failure
        d = {"path": "xla", "why": f"autotune-error:{type(e).__name__}"}
    _AUTOTUNE[sig] = d
    return d


# --------------------------------------------------------------------- #
# brick contraction implementations                                     #
# --------------------------------------------------------------------- #
def _contrib_xla(bdata, xb, bcol, jt):
    """Oracle SpMM contributions: brick-level take + batched matmul.
    The take moves contiguous (128, k) slabs — XLA's coarse dynamic
    gather, nothing per-element."""
    xg = jnp.take(xb, bcol, axis=0)
    return jax.vmap(lambda a, b: jnp.dot(a, b, preferred_element_type=jt))(
        bdata, xg
    )


def _sddmm_xla(sdata, ub, vb, brow, bcol, jt):
    """Oracle SDDMM bricks: take the U/V bricks, one batched matmul,
    scale by the stored values (the Hadamard/sampled form)."""
    ug = jnp.take(ub, brow, axis=0)
    vg = jnp.take(vb, bcol, axis=0)
    prod = jax.vmap(lambda a, b: jnp.dot(a, b.T, preferred_element_type=jt))(
        ug, vg
    )
    return sdata.astype(jt) * prod


@functools.lru_cache(maxsize=128)
def _brick_spmm_call(B: int, nb: int, k: int, jdtype: str, interpret: bool):
    """The scalar-prefetch SpMM brick kernel: grid over the B slab
    bricks; the prefetched ``bcol`` drives the X-brick index map, so the
    needed (128, k) brick is DMA'd per step — no gather instruction."""
    jt = jnp.dtype(jdtype)

    def kernel(bcol_ref, bdata_ref, xb_ref, out_ref):
        out_ref[0] = jnp.dot(bdata_ref[0], xb_ref[0], preferred_element_type=jt)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, BR, BC), lambda i, bcol: (i, 0, 0)),
            pl.BlockSpec((1, BC, k), lambda i, bcol: (bcol[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BR, k), lambda i, bcol: (i, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, BR, k), jt),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=128)
def _brick_sddmm_call(
    B: int, mb: int, nb: int, d: int, jdtype: str, interpret: bool
):
    """The scalar-prefetch SDDMM brick kernel: ``brow``/``bcol`` drive
    the U-/V-brick index maps; each step computes one stored tile."""
    jt = jnp.dtype(jdtype)

    def kernel(brow_ref, bcol_ref, sdata_ref, ub_ref, vb_ref, out_ref):
        prod = jnp.dot(ub_ref[0], vb_ref[0].T, preferred_element_type=jt)
        out_ref[0] = sdata_ref[0].astype(jt) * prod

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, BR, BC), lambda i, brow, bcol: (i, 0, 0)),
            pl.BlockSpec((1, BR, d), lambda i, brow, bcol: (brow[i], 0, 0)),
            pl.BlockSpec((1, BC, d), lambda i, brow, bcol: (bcol[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BR, BC), lambda i, brow, bcol: (i, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, BR, BC), jt),
        interpret=interpret,
    )


# --------------------------------------------------------------------- #
# distributed programs                                                  #
# --------------------------------------------------------------------- #
def _local_spmm(bdata, bcol, brow, bmask, x, r, *, nb, B, c, jt, acc, path):
    """One device's SpMM: brick contractions + masked segment-sum into
    the device's canonical c output rows. Collective-free."""
    k = x.shape[1]
    # k == 1 hits XLA:CPU's matvec special case, whose reduction order
    # differs between the batched (oracle) and per-brick (interpret
    # kernel) contractions — zero-pad to k=2 so both take the bitwise-
    # identical matmul path (the pad column contributes exact zeros)
    kk = max(k, 2)
    if kk != k:
        x = jnp.pad(x, ((0, 0), (0, kk - k)))
    xp = jnp.pad(x.astype(acc), ((0, nb * BC - x.shape[0]), (0, 0)))
    xb = xp.reshape(nb, BC, kk)
    bd = bdata.astype(acc)
    if path == "pallas":
        interpret = jax.default_backend() != "tpu"
        contrib = _brick_spmm_call(B, nb, kk, acc.name, interpret)(bcol, bd, xb)
    else:
        contrib = _contrib_xla(bd, xb, bcol, acc)
    if kk != k:
        contrib = contrib[..., :k]
    rows = (
        brow[:, None].astype(jnp.int32) * BR
        + jnp.arange(BR, dtype=jnp.int32)[None, :]
        - r * c
    )
    rows = jnp.where(bmask, rows, c)  # non-owned / pad rows -> dropped
    y = jax.ops.segment_sum(
        contrib.reshape(-1, k), rows.reshape(-1), num_segments=c + 1
    )[:c]
    return y.astype(jt)


@functools.lru_cache(maxsize=256)
def spmm_bcsr_program(comm, m: int, nb: int, B: int, split, out_ndim: int,
                      jdtype: str, path: str):
    """(bdata, bcol, brow, bmask, x2d) -> y physical. ``split == 0`` on
    a real mesh runs as a shard_map LOCAL program — each device computes
    exactly its canonical output rows from its own brick slab and the
    replicated dense operand: 0 collectives (the pinned census)."""
    jt = jnp.dtype(jdtype)
    acc = _acc_dtype(jt)
    p = comm.size if split == 0 else 1
    c = _padding.pad_extent(m, p) // p if (split == 0 and p > 1) else max(m, 1)
    kw = dict(nb=nb, B=B, c=c, jt=jt, acc=acc, path=path)

    if split == 0 and p > 1:
        from ..core._jax_compat import shard_map

        ax = comm.axis_name

        def local(bdata, bcol, brow, bmask, x):
            r = lax.axis_index(ax)
            return _local_spmm(bdata, bcol, brow, bmask, x, r, **kw)

        fn = shard_map(
            local,
            mesh=comm.mesh,
            in_specs=(P(ax, None, None), P(ax), P(ax), P(ax, None), P(None, None)),
            out_specs=P(ax, None),
        )

        def run(bdata, bcol, brow, bmask, x):
            y = fn(bdata, bcol, brow, bmask, x)
            return y if out_ndim == 2 else y[:, 0]

        return jax.jit(run)  # shardlint: ignore[SL202] -- lru-cached brick program keyed on the gate-decided path; operands are reused across calls so donation is unwanted, and the sharded path routes through comm.jit_sharded

    def run(bdata, bcol, brow, bmask, x):
        y = _local_spmm(bdata, bcol, brow, bmask, x, 0, **kw)[:m]
        return y if out_ndim == 2 else y[:, 0]

    return comm.jit_sharded(run, out_ndim, split)


def _local_sddmm(sdata, bcol, brow, u, v, *, mb, nb, B, jt, acc, path):
    """One device's SDDMM bricks. Collective-free: U/V arrive
    replicated, the takes are brick-level and local."""
    d = u.shape[1]
    # same k==1 matvec-codepath hazard as _local_spmm: zero-pad the
    # contraction dim to 2 (pad terms are exact zeros)
    dd = max(d, 2)
    if dd != d:
        u = jnp.pad(u, ((0, 0), (0, dd - d)))
        v = jnp.pad(v, ((0, 0), (0, dd - d)))
    up = jnp.pad(u.astype(acc), ((0, mb * BR - u.shape[0]), (0, 0)))
    vp = jnp.pad(v.astype(acc), ((0, nb * BC - v.shape[0]), (0, 0)))
    ub = up.reshape(mb, BR, dd)
    vb = vp.reshape(nb, BC, dd)
    sd = sdata.astype(acc)
    if path == "pallas":
        interpret = jax.default_backend() != "tpu"
        out = _brick_sddmm_call(B, mb, nb, dd, acc.name, interpret)(
            brow, bcol, sd, ub, vb
        )
    else:
        out = _sddmm_xla(sd, ub, vb, brow, bcol, acc)
    return out.astype(jt)


@functools.lru_cache(maxsize=256)
def sddmm_bcsr_program(comm, mb: int, nb: int, B: int, split, jdtype: str,
                       path: str):
    """(sdata, bcol, brow, u, v) -> new brick data physical, same slab
    layout as the pattern operand. shard_map local on a real mesh —
    0 collectives, same census pin as SpMM."""
    jt = jnp.dtype(jdtype)
    acc = _acc_dtype(jt)
    p = comm.size if split == 0 else 1
    kw = dict(mb=mb, nb=nb, B=B, jt=jt, acc=acc, path=path)

    if split == 0 and p > 1:
        from ..core._jax_compat import shard_map

        ax = comm.axis_name

        def local(sdata, bcol, brow, u, v):
            return _local_sddmm(sdata, bcol, brow, u, v, **kw)

        fn = shard_map(
            local,
            mesh=comm.mesh,
            in_specs=(P(ax, None, None), P(ax), P(ax), P(None, None), P(None, None)),
            out_specs=P(ax, None, None),
        )
        return jax.jit(fn)  # shardlint: ignore[SL202] -- lru-cached brick program (see spmm_bcsr_program); sharded path routes through comm.jit_sharded

    def run(sdata, bcol, brow, u, v):
        return _local_sddmm(sdata, bcol, brow, u, v, **kw)

    return comm.jit_sharded(run, 3, split)


from ..core.communication import register_mesh_cache

# program entries bake mesh geometry: cleared when the world rebuilds
register_mesh_cache(spmm_bcsr_program)
register_mesh_cache(sddmm_bcsr_program)
