"""Collective-matmul primitives: ppermute-ring forms of the linalg
collectives, consumed block-by-block as they land.

The TPU distributed-linalg playbook (arXiv:2112.09017) gets its
latency hiding from *collective matmuls*: a gathered/reduced operand is
never waited on as one barrier — the collective is decomposed into a
ring of ``ppermute`` hops and every landed block is consumed (placed,
multiplied, accumulated) while the next hop is on the wire. This module
is that decomposition, shared by the TSQR merge (``core/linalg/qr.py``)
and the split matmul (``core/linalg/basics.py``), gated by the same
``HEAT_TPU_REDIST_OVERLAP`` knob as the redistribution executor's
pipelined programs:

- ``ring_all_gather`` — the R-factor all-gather of TSQR (flat and both
  levels of the two-level group tree) as ``size-1`` neighbor hops, each
  landed block written straight into the stacked buffer. The assembled
  array is element-identical to ``lax.all_gather``'s, so the merge QR
  consuming it is **bit-identical** to the barrier form for any input —
  the consumable work is the assembly copy, which is exactly what
  overlaps the wire.
- ``ring_matmul_reduce`` — the contraction-split matmul
  ``C = Σ_q A_q B_q`` as a reduce-scatter ring whose per-hop partial
  block matmul (MXU) overlaps the ppermute (ICI), then a ring gather of
  the reduced row blocks. Each output chunk is accumulated in ONE fixed
  ring order on one device and then copied, so the replicated result is
  consistent across devices and bit-identical between the sequential
  and pipelined issue orders (same adds, same order).

Sequential-vs-pipelined contract (the redistribution executor's): the
sequential oracle pins compute behind wire with
``lax.optimization_barrier`` (identity on values), the pipelined form
frees XLA's latency-hiding scheduler / prefetch-issues the next hop.
Both launch the same collectives — the census trades the one
all-gather/all-reduce for a byte-equivalent ppermute chain, pinned in
``tests/test_overlap.py``.

Programs run under ``jax.named_scope("cmatmul_ring_<tag>")`` so
shardlint recognizes the ppermute chains as planned collective-matmul
movement (``analysis/boundaries.PLANNER_MODULES``) and reports them at
info severity instead of flagging the subsystem's own schedules.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax import lax

from typing import List, Tuple

__all__ = [
    "grouped_ring_perm",
    "ring_enabled",
    "ring_all_gather",
    "ring_bcast",
    "ring_matmul_reduce",
    "stamp_scope",
]


def grouped_ring_perm(
    n_groups: int, group_size: int, across: bool = False
) -> List[Tuple[int, int]]:
    """The COMPLETE +1 ring permutation of a grouped gather — every
    device of the mesh appears exactly once as a source and once as a
    target, which is the congruence contract commcheck's SL502 rule
    verifies (a dropped pair leaves one device waiting on a block that
    never leaves: a silent hang, not an error).

    ``across=False`` rotates WITHIN each of the ``n_groups`` contiguous
    groups of ``group_size`` (the two-level TSQR's level-1 member
    gather); ``across=True`` rotates same-position members ACROSS the
    groups (the level-2 group-R gather). ``grouped_ring_perm(1, p)`` is
    the flat p-ring."""
    if across:
        return [
            (g * group_size + j, ((g + 1) % n_groups) * group_size + j)
            for g in range(n_groups)
            for j in range(group_size)
        ]
    return [
        (g * group_size + j, g * group_size + (j + 1) % group_size)
        for g in range(n_groups)
        for j in range(group_size)
    ]


def ring_enabled() -> bool:
    """Do the linalg paths run their collective-matmul (ppermute-ring)
    forms? ``HEAT_TPU_REDIST_OVERLAP=1`` forces them everywhere (the CI
    leg), ``=0`` restores the barrier collectives (all-gather /
    GSPMD-scheduled reduction — the oracle), and the default ``auto``
    engages them only on the TPU backend: unlike the redistribution
    pipelining (a free reorder), the ring decomposition changes the
    collective pattern, and only TPU's async collective engine turns
    the per-hop consume into hidden time.

    Two-tier audit (ISSUE 8): the ring's ``(s, s+1 mod p)`` neighbor
    permutation crosses the slice boundary on the wraparound edges of a
    tiered mesh — EVERY hop then completes at DCN speed, turning the
    byte-equivalent trade into a (p-1)·(dcn/ici) ≈ 8(p-1)/p loss. Under
    ``auto`` a tiered topology therefore keeps the barrier collectives
    (XLA lowers those hierarchically on real multi-slice deployments);
    the forced ``=1`` leg still runs the rings — they stay
    bit-identical, only the modeled wire price changes."""
    from ..redistribution import planner as _planner

    mode = _planner.overlap_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    from ..core import communication as _comm

    if _comm.get_comm().topology.tiered:
        return False
    return jax.default_backend() == "tpu"


def stamp_scope(tag: str):
    """The ``cmatmul_ring_<tag>`` named scope collective-matmul program
    bodies run under — the stamp lands in the HLO ``op_name`` of every
    ppermute the ring launches, which is how shardlint downgrades the
    chain to info severity (see ``analysis/boundaries``)."""
    return jax.named_scope(f"cmatmul_ring_{tag}")


def ring_all_gather(
    x: jax.Array,
    axis_name: str,
    size: int,
    pos,
    perm: List[Tuple[int, int]],
    pipelined: bool = True,
):
    """Assemble ``lax.all_gather(x, axis_name)``'s ``(size,) + x.shape``
    stack with ``size - 1`` ppermute hops, placing each block as it
    lands.

    ``pos`` is this device's (traced) index within its gather group and
    ``perm`` the +1 ring permutation of the group (possibly grouped —
    the two-level TSQR tree passes within-group and across-group
    rings). After ``d`` forward hops a device holds the block of the
    member ``d`` positions behind it, so the landed block's stack slot
    is ``(pos - d) mod size`` — identical to the all-gather layout, for
    any data, which is what makes the consuming merge bit-identical to
    the barrier form.

    ``pipelined=False`` is the sequential oracle: each hop's placement
    is ``optimization_barrier``-pinned before the next hop issues.
    """
    if size <= 1:
        return x[None]
    out = jnp.zeros((size,) + x.shape, x.dtype)
    zero = jnp.zeros((), jnp.int32)

    def place(out, blk, d):
        slot = (jnp.asarray(pos, jnp.int32) - d) % size
        return lax.dynamic_update_slice(out, blk[None], (slot,) + (zero,) * x.ndim)

    out = place(out, x, 0)
    if pipelined:
        prev = lax.ppermute(x, axis_name, perm)
        for d in range(1, size - 1):
            nxt = lax.ppermute(prev, axis_name, perm)  # hop d+1 flies ...
            out = place(out, prev, d)  # ... while hop d's block is placed
            prev = nxt
        out = place(out, prev, size - 1)
    else:
        cur = x
        for d in range(1, size):
            cur = lax.ppermute(cur, axis_name, perm)
            out = place(out, cur, d)
            out, cur = lax.optimization_barrier((out, cur))
    return out


def ring_bcast(
    x: jax.Array,
    axis_name: str,
    size: int,
    root,
    perm: List[Tuple[int, int]],
    pipelined: bool = True,
):
    """Broadcast device ``root``'s block around the +1 ring: ``size - 1``
    neighbor hops, each device adopting the landed block exactly when its
    ring distance from ``root`` equals the hop count — the row-panel
    broadcast of the blocked LU trailing update (ScaLAPACK's ``Ibcast``
    ring expressed as ppermutes, so the factorization census stays
    ppermute-only and the shardlint stamp machinery applies unchanged).

    ``root`` may be traced (the panel step index). Every device launches
    every hop (SPMD congruence — the SL502 contract); non-root sources
    forward zeros until the payload reaches them, after which they
    forward the payload. The adopted value is selected by ring distance,
    never accumulated, so the result is exact for any float payload and
    bit-identical between the sequential and pipelined issue orders
    (``pipelined=False`` pins each hop's adoption before the next hop
    issues — the redistribution executor's sequential-oracle form).
    """
    if size <= 1:
        return x
    i = lax.axis_index(axis_name)
    rel = (i - jnp.asarray(root, jnp.int32)) % size
    v = jnp.where(rel == 0, x, jnp.zeros_like(x))
    for d in range(1, size):
        recv = lax.ppermute(v, axis_name, perm)
        v = jnp.where(rel == d, recv, v)
        if not pipelined:
            (v,) = lax.optimization_barrier((v,))
    return v


def ring_matmul_reduce(
    a_loc: jax.Array,
    b_loc: jax.Array,
    axis_name: str,
    p: int,
    precision=None,
    pipelined: bool = True,
):
    """The contraction-split matmul ``C = Σ_q A_q B_q`` as a collective
    matmul: reduce-scatter ring with on-demand partial blocks, then a
    ring gather of the reduced row chunks.

    ``a_loc`` is the local ``(m, K/p)`` column block of A, ``b_loc`` the
    local ``(K/p, n)`` row block of B (the physical shards of
    ``a.split == 1`` / ``b.split == 0`` — zero pads on the contraction
    axis contribute exact zeros). Output: the replicated
    ``(pad(m, p), n)`` product (caller slices the row pad).

    Movement: each output row chunk ``j`` (of ``p``) is accumulated
    around the ring in the fixed order ``P_{j-1}, P_j, …, P_{j-2}`` —
    one well-defined float addition order per chunk, computed once,
    then ring-gathered — so every device ends with the same bits and
    the sequential/pipelined issue orders agree exactly. Per hop the
    partial block matmul (one ``(mc, K/p) @ (K/p, n)`` MXU call) is
    independent of the in-flight ppermute: that is the compute the ring
    hides under the wire (sequential oracle: pinned behind it).
    """
    m = a_loc.shape[0]
    n = b_loc.shape[1]
    mc = -(-m // p)
    if mc * p != m:
        a_loc = jnp.pad(a_loc, ((0, mc * p - m), (0, 0)))
    if p <= 1:
        return jnp.matmul(a_loc, b_loc, precision=precision)
    r = lax.axis_index(axis_name)
    perm = [(s, (s + 1) % p) for s in range(p)]

    def partial(j):
        rows = lax.dynamic_slice_in_dim(a_loc, j * mc, mc, axis=0)
        return jnp.matmul(rows, b_loc, precision=precision)

    # reduce-scatter: at step t device r contributes its partial for
    # chunk (r + 1 - t) mod p and forwards the accumulator
    acc = partial((r + 1) % p)
    for t in range(1, p):
        if not pipelined:
            # oracle: the hop may not leave before this step's partial
            # is computed — wire strictly serialized with compute
            acc, a_loc = lax.optimization_barrier((acc, a_loc))
        recv = lax.ppermute(acc, axis_name, perm)
        acc = recv + partial((r + 1 - t) % p)
    # device r now holds chunk (r + 2) mod p fully reduced; ring-gather
    # the chunks into the replicated product — the same assembly ring as
    # the TSQR merge, stacked by chunk slot so the row order is global
    own = (r + 2) % p
    stacked = ring_all_gather(acc, axis_name, p, own, perm, pipelined=pipelined)
    return stacked.reshape(mc * p, n)
