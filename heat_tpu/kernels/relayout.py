"""Lane-packing relayout engine: repartition copies at full VREG width.

ROADMAP ``reshape``: the split=1 1 GB repartition reads/writes its
operand at ~0.09 of HBM because the (10M, 25) target's minor dimension
fills only 25/128 lanes of every output VREG tile — the copy streams
(8, 128) tiles that are 80% pad. The fix is the tile-level instance of
arXiv:2112.01075's layout-vs-movement separation: plan the relayout as
cheap LOCAL layout changes around minimal collectives, where "cheap"
means every heavy copy runs on a lane-full representation.

This module is the layout half. A narrow-minor-dim shard ``(R, C)``
(``C`` ≪ 128 lanes) is *packed* by a tile-transposing copy that folds
rows into the lane axis — the flat row-major bytes are regrouped into a
``(p, R·C/p)``-shaped buffer whose minor dimension is huge, so every
VREG the collective and relayout steps touch is full. The
redistribution planner's chunked all-to-all / pivot / local-reshape
steps then run on the packed bytes, and the destination layout is
materialized by ONE unpack copy (the single lane-amplified write the
user's requested layout makes unavoidable).

Two primitives, each a pure permutation + zero-pad (bit-identical
between formulations by construction):

* ``pack_rows(x, rows, c_in, c_out, p)`` — flat ``(rows·c_in,)`` →
  grouped ``(p, rows·c_out/p)``: right-pad every ``c_in``-element row
  to ``c_out`` and gather each of the ``p`` column blocks contiguous
  (the send layout of a split-0 → split-last all-to-all).
* ``unpack_rows(x, rows, c_in, c_out, p)`` — the inverse: ungroup the
  ``p`` column blocks back into full-width rows and drop the per-row
  pad tail.

Each primitive has an **XLA formulation** (reshape/pad/transpose — the
portable reference) and a **Pallas tiled-copy kernel** that streams
flat VMEM blocks and performs the narrow-shape reinterpretation in
registers, so both HBM faces of the copy are full-lane 1-D streams
(``interpret=True`` runs the identical kernel logic on CPU, so tier-1
exercises it without a TPU). Dispatch follows the PR-4 sort-kernel
pattern: ``HEAT_TPU_RELAYOUT_KERNEL=0`` forces the XLA formulation
everywhere (the escape hatch), ``=1`` forces the Pallas kernel where
serviceable, and the default ``auto`` keeps XLA off-TPU and AUTOTUNES
on TPU with the XLA formulation as the oracle/floor — a kernel that
loses on the real chip can never regress a workload.

``lane_fill`` is the cost-model term the redistribution planner learns
from this module: the fraction of VREG lanes a buffer with the given
minor dimension fills (``minor / pad128(minor)``), i.e. the reciprocal
of the HBM amplification a copy through that layout pays.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core import gates as _gates

try:  # pragma: no cover — present in all TPU-capable jax builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pl = None
    _VMEM = None

__all__ = [
    "LANES",
    "SUBLANES",
    "PACK_FILL_THRESHOLD",
    "kernel_mode",
    "lane_fill",
    "last_decisions",
    "pack_rows",
    "pallas_serviceable",
    "unpack_rows",
]

#: VREG lane width (f32): the minor-dim quantum of TPU tiled layouts
LANES = 128
#: VREG sublane count (f32): the second-minor quantum
SUBLANES = 8

#: a relayout stage engages the packed form only when its buffer fills
#: less than this fraction of the lane axis — near-full minors gain
#: nothing from a repack and would pay the extra pack/unpack pass
PACK_FILL_THRESHOLD = 0.5

#: elements per Pallas block (both faces), bounding VMEM residency
_BLOCK_ELEMS = 1 << 16
_MAX_BLOCK_ROWS = 4096


def _mode() -> str:
    v = _gates.get("HEAT_TPU_RELAYOUT_KERNEL", "auto").strip().lower()
    if v in ("0", "off", "false"):
        return "0"
    if v in ("1", "on", "true", "force"):
        return "1"
    return "auto"


def kernel_mode() -> str:
    """The resolved ``HEAT_TPU_RELAYOUT_KERNEL`` mode (``"0"``/``"1"``/
    ``"auto"``) — introspection for tests and bench records. (Cache
    staleness on env flips is handled one level down: the executor keys
    its packed programs on the DECIDED ``impl_in``/``impl_out`` strings
    from :func:`decide`, which this mode feeds.)"""
    return _mode()


def _inc(name: str) -> None:
    from ..observability import telemetry

    telemetry.inc(name)


def lane_fill(minor: int) -> float:
    """Fraction of VREG lanes a buffer with minor dimension ``minor``
    fills once tiled to the 128-lane quantum — the planner's lane-fill
    cost term (``minor_dim/128`` below one full tile). 1/fill is the
    HBM amplification a copy through that layout pays."""
    minor = int(minor)
    if minor <= 0:
        return 1.0
    padded = -(-minor // LANES) * LANES
    return minor / padded


# ---------------------------------------------------------------------- #
# XLA formulations (the portable reference and the autotune floor)       #
# ---------------------------------------------------------------------- #
def _pack_rows_xla(x: jax.Array, rows: int, c_in: int, c_out: int, p: int):
    cpp = c_out // p
    xb = x.reshape(rows, c_in)
    if c_out != c_in:
        xb = jnp.pad(xb, ((0, 0), (0, c_out - c_in)))
    return jnp.transpose(xb.reshape(rows, p, cpp), (1, 0, 2)).reshape(p, rows * cpp)


def _unpack_rows_xla(x: jax.Array, rows: int, c_in: int, c_out: int, p: int):
    cpp = c_in // p
    xb = jnp.transpose(x.reshape(p, rows, cpp), (1, 0, 2)).reshape(rows, c_in)
    if c_out != c_in:
        xb = xb[:, :c_out]
    return xb.reshape(rows * c_out)


# ---------------------------------------------------------------------- #
# Pallas tiled-copy kernels                                              #
# ---------------------------------------------------------------------- #
def _block_rows(rows: int, c_max: int) -> int:
    """Largest divisor of ``rows`` whose block stays VMEM-resident.
    The grid iterates ``rows // B`` blocks; equal blocks keep the
    BlockSpecs static."""
    cap = max(1, min(rows, _MAX_BLOCK_ROWS, _BLOCK_ELEMS // max(c_max, 1)))
    best = 1
    for b in range(1, cap + 1):
        if rows % b == 0:
            best = b
    return best


@functools.lru_cache(maxsize=32)
def _pack_call(n_blocks: int, b: int, c_in: int, c_out: int, p: int, dtype_name: str, interpret: bool):
    """Tile-transposing pack: every grid step streams one flat
    ``(1, b·c_in)`` VMEM block in and one ``(p, b·c_out/p)`` block out —
    both HBM faces are wide; the narrow ``(b, c_in)`` shape exists only
    in registers."""
    cpp = c_out // p
    dt = jnp.dtype(dtype_name)

    def kernel(i_ref, o_ref):
        xb = i_ref[...].reshape(b, c_in)
        if c_out != c_in:
            xb = jnp.concatenate([xb, jnp.zeros((b, c_out - c_in), dt)], axis=1)
        o_ref[...] = jnp.transpose(xb.reshape(b, p, cpp), (1, 0, 2)).reshape(p, b * cpp)

    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((1, b * c_in), lambda g: (g, 0), memory_space=_VMEM)],
        out_specs=pl.BlockSpec((p, b * cpp), lambda g: (0, g), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((p, n_blocks * b * cpp), dt),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=32)
def _unpack_call(n_blocks: int, b: int, c_in: int, c_out: int, p: int, dtype_name: str, interpret: bool):
    cpp = c_in // p
    dt = jnp.dtype(dtype_name)

    def kernel(i_ref, o_ref):
        xb = jnp.transpose(i_ref[...].reshape(p, b, cpp), (1, 0, 2)).reshape(b, c_in)
        if c_out != c_in:
            xb = xb[:, :c_out]
        o_ref[...] = xb.reshape(1, b * c_out)

    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((p, b * cpp), lambda g: (0, g), memory_space=_VMEM)],
        out_specs=pl.BlockSpec((1, b * c_out), lambda g: (g, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((n_blocks, b * c_out), dt),
        interpret=interpret,
    )


def pallas_serviceable(rows: int, c_in: int, c_out: int, p: int) -> bool:
    """Shape-level predicate: would the Pallas tiled-copy kernel serve
    this pack/unpack? (A 1-row block always divides ``rows``, so this
    is mostly a ``pl``-availability and VMEM-residency gate.)"""
    if pl is None or rows <= 0 or p <= 0:
        return False
    c_max = max(c_in, c_out)
    return 0 < c_max <= _BLOCK_ELEMS


def _pack_rows_pallas(x, rows, c_in, c_out, p):
    b = _block_rows(rows, max(c_in, c_out))
    interpret = jax.default_backend() != "tpu"
    return _pack_call(rows // b, b, c_in, c_out, p, jnp.dtype(x.dtype).name, interpret)(
        x.reshape(rows // b, b * c_in)
    )


def _unpack_rows_pallas(x, rows, c_in, c_out, p):
    b = _block_rows(rows, max(c_in, c_out))
    interpret = jax.default_backend() != "tpu"
    out = _unpack_call(rows // b, b, c_in, c_out, p, jnp.dtype(x.dtype).name, interpret)(x)
    return out.reshape(rows * c_out)


# ---------------------------------------------------------------------- #
# dispatch (HEAT_TPU_RELAYOUT_KERNEL + TPU autotune, XLA as the floor)   #
# ---------------------------------------------------------------------- #
_DECISIONS: dict = {}


def last_decisions() -> dict:
    """Copy of the dispatcher's cached path decisions (and autotune
    timings where one ran): {(op, rows, c_in, c_out, p, dtype): {...}}."""
    return {k: dict(v) for k, v in _DECISIONS.items()}


def _sync_scalar(x) -> None:
    np.asarray(jax.device_get(x[(0,) * x.ndim] if x.ndim else x))


def _autotune(op: str, rows: int, c_in: int, c_out: int, p: int, dtype_name: str) -> str:
    """Time the XLA formulation against the Pallas kernel once per
    shape signature on the real chip and cache the winner. The XLA
    formulation (the current direct path) is the oracle/floor: ties and
    lowering failures keep it."""
    key = (op, rows, c_in, c_out, p, dtype_name)
    if key in _DECISIONS:
        return _DECISIONS[key]["impl"]
    if op == "pack":
        x = jnp.zeros((rows * c_in,), jnp.dtype(dtype_name))
        forms = {"xla": _pack_rows_xla, "pallas": _pack_rows_pallas}
    else:
        x = jnp.zeros((p, rows * (c_in // p)), jnp.dtype(dtype_name))
        forms = {"xla": _unpack_rows_xla, "pallas": _unpack_rows_pallas}
    timings = {}
    for impl, form in forms.items():
        if impl == "pallas" and not pallas_serviceable(rows, c_in, c_out, p):
            continue
        try:
            fn = jax.jit(functools.partial(form, rows=rows, c_in=c_in, c_out=c_out, p=p))
            _sync_scalar(fn(x))  # compile + warm
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                _sync_scalar(fn(x))
                best = min(best, time.perf_counter() - t0)
            timings[impl] = best
        except Exception:  # pragma: no cover — lowering failed on this backend
            timings[impl] = float("inf")
    impl = "pallas" if timings.get("pallas", float("inf")) < timings.get("xla", float("inf")) else "xla"
    _DECISIONS[key] = {"impl": impl, "timings": timings, "autotuned": True}
    return impl


def decide(op: str, rows: int, c_in: int, c_out: int, p: int, dtype_name: str, concrete: bool = True) -> str:
    """The implementation (``"xla"``/``"pallas"``) serving this
    pack/unpack signature under the current mode. Called eagerly by the
    executor at program-build time so the decision is fixed before the
    body traces (autotune never runs under a trace)."""
    mode = _mode()
    serviceable = pallas_serviceable(rows, c_in, c_out, p)
    if mode == "0":
        return "xla"
    if mode == "1":
        if not serviceable:
            _inc("relayout.kernel.fallback")
            return "xla"
        return "pallas"
    # auto: XLA off-TPU; autotuned on TPU (32-bit words only — the
    # kernel's VMEM blocks are sized for 4-byte lanes)
    if jax.default_backend() != "tpu" or not serviceable or jnp.dtype(dtype_name).itemsize != 4:
        return "xla"
    key = (op, rows, c_in, c_out, p, dtype_name)
    if key in _DECISIONS and _DECISIONS[key].get("autotuned"):
        return _DECISIONS[key]["impl"]
    if not concrete:
        return "xla"  # tracing: no autotune possible, stay on the floor
    return _autotune(op, rows, c_in, c_out, p, dtype_name)


def pack_rows(x: jax.Array, rows: int, c_in: int, c_out: int, p: int, impl: str | None = None) -> jax.Array:
    """Flat ``(rows·c_in,)`` → grouped ``(p, rows·c_out/p)``: every
    ``c_in``-element row is right-padded with zeros to ``c_out`` and
    the ``p`` column blocks are gathered contiguous (the send layout of
    the packed split-0 → split-minor all-to-all). ``c_out % p == 0``,
    ``c_out ≥ c_in``. Pure permutation + zero-pad: the XLA and Pallas
    formulations are bit-identical by construction."""
    if c_out % p or c_out < c_in:
        raise ValueError(f"pack_rows: need p | c_out and c_out >= c_in, got {c_in}->{c_out} over p={p}")
    if impl is None:
        impl = decide("pack", rows, c_in, c_out, p, jnp.dtype(x.dtype).name,
                      concrete=not isinstance(x, jax.core.Tracer))
    if impl == "pallas":
        _inc("relayout.kernel.hit")
        return _pack_rows_pallas(x, rows, c_in, c_out, p)
    return _pack_rows_xla(x, rows, c_in, c_out, p)


def unpack_rows(x: jax.Array, rows: int, c_in: int, c_out: int, p: int, impl: str | None = None) -> jax.Array:
    """Inverse of :func:`pack_rows`: grouped ``(p, rows·c_in/p)`` →
    flat ``(rows·c_out,)`` with the per-row pad tail dropped
    (``c_in % p == 0``, ``c_out ≤ c_in``)."""
    if c_in % p or c_out > c_in:
        raise ValueError(f"unpack_rows: need p | c_in and c_out <= c_in, got {c_in}->{c_out} over p={p}")
    if impl is None:
        impl = decide("unpack", rows, c_in, c_out, p, jnp.dtype(x.dtype).name,
                      concrete=not isinstance(x, jax.core.Tracer))
    if impl == "pallas":
        _inc("relayout.kernel.hit")
        return _unpack_rows_pallas(x, rows, c_in, c_out, p)
    return _unpack_rows_xla(x, rows, c_in, c_out, p)
