"""Gaussian naive Bayes.

API parity with /root/reference/heat/naive_bayes/gaussianNB.py
(``GaussianNB`` :25: distributed ``partial_fit`` merging per-class
count/mean/var across batches :127-381, ``logsumexp``-based joint
log-likelihood :398). The per-class statistics are masked sharded
reductions; the streaming mean/var merge follows the same
Chan/Golub/LeVeque update the reference uses.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional

from ..core import factories, types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core.communication import place as _place

__all__ = ["GaussianNB"]


class GaussianNB(BaseEstimator, ClassificationMixin):
    """Gaussian naive Bayes classifier (reference: gaussianNB.py:25)."""

    def __init__(self, priors=None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.epsilon_ = None
        self._epsilon_prev = 0.0

    def fit(self, x: DNDarray, y: DNDarray, sample_weight: Optional[DNDarray] = None) -> "GaussianNB":
        """Fit from scratch (reference: gaussianNB.py fit → partial_fit)."""
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self._epsilon_prev = 0.0
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(
        self,
        x: DNDarray,
        y: DNDarray,
        classes: Optional[DNDarray] = None,
        sample_weight: Optional[DNDarray] = None,
    ) -> "GaussianNB":
        """Incremental fit on a batch (reference: gaussianNB.py:127-381)."""
        sanitize_in(x)
        sanitize_in(y)
        if x.ndim != 2:
            raise ValueError(f"expected x to be 2-dimensional, got {x.ndim}")
        arr = x.larray.astype(jnp.float64 if x.dtype is types.float64 else jnp.float32)
        labels = y.larray.ravel()
        w = None
        if sample_weight is not None:
            w = sample_weight.larray.astype(arr.dtype)

        if classes is not None:
            cls = jnp.asarray(
                classes.larray if isinstance(classes, DNDarray) else np.asarray(classes)
            )
        elif self.classes_ is not None:
            cls = jnp.asarray(self.classes_.larray if isinstance(self.classes_, DNDarray) else self.classes_)
        else:
            cls = jnp.unique(labels)
        n_classes = int(cls.shape[0])
        n_features = x.shape[1]

        # variance floor from the data spread (reference: epsilon_)
        self.epsilon_ = float(self.var_smoothing * jnp.var(arr, axis=0).max())

        onehot = (labels[:, None] == cls[None, :]).astype(arr.dtype)  # (n, C)
        if w is not None:
            onehot = onehot * w[:, None]
        counts = jnp.sum(onehot, axis=0)  # (C,)
        sums = onehot.T @ arr  # (C, F)
        means = sums / jnp.maximum(counts[:, None], 1e-30)
        sq = onehot.T @ (arr * arr)
        variances = sq / jnp.maximum(counts[:, None], 1e-30) - means**2

        if self.theta_ is None or self.classes_ is None:
            new_theta, new_var, new_counts = means, variances, counts
        else:
            # streaming merge of old and batch statistics (reference
            # _update_mean_variance, gaussianNB.py:~300); the stored var_
            # includes the previous epsilon floor — strip it before merging
            # (reference gaussianNB.py:326/371)
            old_counts = jnp.asarray(self.class_count_.larray)
            old_theta = jnp.asarray(self.theta_.larray)
            old_var = jnp.asarray(self.var_.larray) - self._epsilon_prev
            total = old_counts + counts
            new_theta = (
                old_theta * old_counts[:, None] + means * counts[:, None]
            ) / jnp.maximum(total[:, None], 1e-30)
            ssd_old = old_var * old_counts[:, None]
            ssd_new = variances * counts[:, None]
            correction = (
                jnp.where(
                    (old_counts[:, None] > 0) & (counts[:, None] > 0),
                    (old_counts[:, None] * counts[:, None])
                    / jnp.maximum(total[:, None], 1e-30)
                    * (old_theta - means) ** 2,
                    0.0,
                )
            )
            new_var = (ssd_old + ssd_new + correction) / jnp.maximum(total[:, None], 1e-30)
            new_counts = total

        comm, device = x.comm, x.device
        mk = lambda a: DNDarray(
            _place(a, comm.sharding(a.ndim, None)),
            tuple(int(s) for s in a.shape),
            types.canonical_heat_type(a.dtype),
            None,
            device,
            comm,
        )
        self.classes_ = mk(cls)
        self.class_count_ = mk(new_counts)
        self.theta_ = mk(new_theta)
        self.var_ = mk(new_var + self.epsilon_)
        self._epsilon_prev = self.epsilon_
        if self.priors is not None:
            priors = jnp.asarray(
                self.priors.larray if isinstance(self.priors, DNDarray) else np.asarray(self.priors)
            )
            if priors.shape[0] != n_classes:
                raise ValueError("Number of priors must match number of classes.")
            if not np.isclose(float(jnp.sum(priors)), 1.0):
                raise ValueError("The sum of the priors should be 1.")
            if bool(jnp.any(priors < 0)):
                raise ValueError("Priors must be non-negative.")
            self.class_prior_ = mk(priors)
        else:
            self.class_prior_ = mk(new_counts / jnp.maximum(jnp.sum(new_counts), 1e-30))
        return self

    def _joint_log_likelihood(self, x: DNDarray) -> jax.Array:
        """Unnormalized posterior log-probabilities (reference:
        gaussianNB.py:~390)."""
        arr = x.larray.astype(jnp.asarray(self.theta_.larray).dtype)
        theta = jnp.asarray(self.theta_.larray)  # (C, F)
        var = jnp.asarray(self.var_.larray)
        prior = jnp.log(jnp.maximum(jnp.asarray(self.class_prior_.larray), 1e-30))
        n_ij = -0.5 * jnp.sum(jnp.log(2.0 * np.pi * var), axis=1)  # (C,)
        diff = arr[:, None, :] - theta[None, :, :]  # (n, C, F)
        ll = n_ij[None, :] - 0.5 * jnp.sum(diff**2 / var[None, :, :], axis=2)
        return ll + prior[None, :]

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample."""
        sanitize_in(x)
        if self.theta_ is None:
            raise RuntimeError("fit needs to be called before predict")
        jll = self._joint_log_likelihood(x)
        winners = jnp.argmax(jll, axis=1)
        labels = jnp.take(jnp.asarray(self.classes_.larray), winners)
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            labels = x.comm.shard(labels, split)
        return DNDarray(
            labels, gshape, types.canonical_heat_type(labels.dtype), split, x.device, x.comm
        )

    def logsumexp(self, a, axis=None, b=None, keepdims=False, return_sign=False):
        """log(sum(b * exp(a))) computed stably (reference gaussianNB.py:398,
        adapted from scikit-learn). Returns (out, sign) when
        ``return_sign=True``."""
        from ..core.dndarray import DNDarray
        from ..core import types as _types

        arr = a.larray if isinstance(a, DNDarray) else jnp.asarray(a)
        bw = None
        if b is not None:
            bw = b.larray if isinstance(b, DNDarray) else jnp.asarray(b)
        out = jax.scipy.special.logsumexp(
            arr, axis=axis, b=bw, keepdims=keepdims, return_sign=return_sign
        )
        def wrap(v):
            v = jnp.asarray(v)
            ref = a if isinstance(a, DNDarray) else None
            if ref is None:
                return v
            split = ref.split
            if split is not None:
                axes = (
                    tuple(range(ref.ndim)) if axis is None
                    else (axis,) if isinstance(axis, int) else tuple(axis)
                )
                axes = tuple(ax % ref.ndim for ax in axes)
                if split in axes:
                    split = None  # reduced away
                elif not keepdims:
                    split -= sum(1 for ax in axes if ax < split)
            phys = ref.comm.shard(v, split) if split is not None else v
            return DNDarray(
                phys, tuple(int(s) for s in v.shape),
                _types.canonical_heat_type(v.dtype), split, ref.device, ref.comm,
            )
        if return_sign:
            out, sign = out
            return wrap(out), wrap(sign)
        return wrap(out)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        """Normalized class log-probabilities (reference logsumexp at
        gaussianNB.py:398)."""
        sanitize_in(x)
        jll = self._joint_log_likelihood(x)
        log_prob = jll - jax.scipy.special.logsumexp(jll, axis=1, keepdims=True)
        gshape = tuple(int(s) for s in log_prob.shape)
        split = 0 if x.split is not None else None
        if split is not None:
            log_prob = x.comm.shard(log_prob, split)
        return DNDarray(
            log_prob, gshape, types.canonical_heat_type(log_prob.dtype), split, x.device, x.comm
        )

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities."""
        lp = self.predict_log_proba(x)
        probs = jnp.exp(lp.larray)
        return DNDarray(
            x.comm.shard(probs, lp.split) if lp.split is not None else probs,
            lp.shape,
            lp.dtype,
            lp.split,
            lp.device,
            lp.comm,
        )
