"""Distributed naive Bayes (reference: /root/reference/heat/naive_bayes/)."""

from .gaussianNB import *
