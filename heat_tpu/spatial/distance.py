"""Pairwise distance computations.

API parity with /root/reference/heat/spatial/distance.py (``cdist`` :135,
``rbf`` :158, ``manhattan`` :185). The reference's ``_dist`` (:208-477) is
a **ring pipeline**: each rank keeps a stationary block of X and passes a
moving block of Y around the ring for (size+1)//2 iterations, exploiting
symmetry when X ≡ Y — exactly the ring-attention schedule. On TPU the
same dataflow comes out of one sharded matmul-based distance expression:
GSPMD partitions the (n × m) distance computation over the row shards and
emits the rotating collectives on ICI; the quadratic-expansion form
(‖x‖² + ‖y‖² − 2x·yᵀ) maps the inner product onto the MXU.

Both schedules are available: the default lets GSPMD choose; ``ring=True``
runs the explicit ``ppermute`` ring program (``core.parallel.ring_pairwise``),
including the reference's symmetry-skipping half-ring when X ≡ Y.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core.communication import place as _place

__all__ = ["cdist", "manhattan", "rbf"]


def _prepare(X: DNDarray, Y: Optional[DNDarray]):
    """Validate operands and resolve the compute dtype WITHOUT touching
    array data — the ring path casts physical arrays itself, so eager
    logical-array casts here would be two wasted full-array passes."""
    sanitize_in(X)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-dimensional, got {X.ndim}")
    promoted = types.float32 if not types.heat_type_is_inexact(X.dtype) else X.dtype
    if Y is not None:
        sanitize_in(Y)
        if Y.ndim != 2:
            raise ValueError(f"Y must be 2-dimensional, got {Y.ndim}")
        if X.shape[1] != Y.shape[1]:
            raise ValueError(
                f"X and Y must have the same feature dimension, got {X.shape[1]} != {Y.shape[1]}"
            )
        if types.heat_type_is_inexact(Y.dtype):
            promoted = types.promote_types(promoted, Y.dtype)
    if promoted is not types.float64:
        promoted = types.float32
    return promoted


def _cast(X: DNDarray, Y: Optional[DNDarray], dtype):
    jt = dtype.jax_type()
    x = X.larray.astype(jt)
    y = x if Y is None else Y.larray.astype(jt)
    return x, y


def _wrap(result: jax.Array, X: DNDarray, Y: Optional[DNDarray], dtype) -> DNDarray:
    # result split rule (reference distance.py: output split follows X's
    # sample axis; Y split along axis 0 maps to output axis 1)
    split = 0 if X.split == 0 else (1 if (Y is not None and Y.split == 0) else None)
    gshape = tuple(int(s) for s in result.shape)
    if split is not None:
        result = X.comm.shard(result, split)
    return DNDarray(result, gshape, dtype, split, X.device, X.comm)


def _ring_path(X: DNDarray, Y: Optional[DNDarray], metric: str, dtype) -> Optional[DNDarray]:
    """Explicit ppermute-ring schedule (reference distance.py:208-477) —
    usable when both operands are split along axis 0. X ≡ Y (Y=None) runs
    the symmetry-skipping half ring. Returns None when the layout does not
    admit the ring (caller falls back to GSPMD)."""
    from ..core import parallel

    comm = X.comm
    if comm.size <= 1 or X.split != 0 or (Y is not None and Y.split != 0):
        return None
    jt = dtype.jax_type()
    x_phys = X._phys.astype(jt)
    y_phys = x_phys if Y is None else Y._phys.astype(jt)
    out = parallel.ring_pairwise(
        x_phys, y_phys, comm.mesh, comm.axis_name, metric=metric, symmetric=Y is None
    )
    from ..core import _padding

    n_y = X.shape[0] if Y is None else Y.shape[0]
    gshape = (X.shape[0], n_y)
    # the ring output's ROW extent is already the canonical physical layout
    # (pad_extent rows, split 0); only the column dim needs its logical
    # slice (shard-local — columns are unsplit) and the pad rows re-zeroing
    # (they hold distances computed against pad zeros). No unpad/repad
    # round trip of the n×m matrix.
    phys = _padding.mask_phys(out[:, : gshape[1]], gshape, 0)
    phys = _place(phys, comm.sharding(2, 0))
    return DNDarray(phys, gshape, dtype, 0, X.device, comm)


def cdist(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    quadratic_expansion: bool = False,
    ring: bool = False,
) -> DNDarray:
    """Pairwise Euclidean distances (reference: distance.py:135).

    ``ring=True`` selects the explicit ppermute-ring schedule (half ring
    with symmetric fill when ``Y is None``) instead of GSPMD's derived
    collectives; results are identical."""
    dtype = _prepare(X, Y)
    if ring:
        metric = "euclidean" if quadratic_expansion else "euclidean_direct"
        out = _ring_path(X, Y, metric, dtype)
        if out is not None:
            return out
    x, y = _cast(X, Y, dtype)
    if quadratic_expansion:
        # MXU form: ‖x‖² + ‖y‖² − 2 x·yᵀ
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        y2 = jnp.sum(y * y, axis=1, keepdims=True).T
        d2 = jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0)
        result = jnp.sqrt(d2)
    else:
        diff = x[:, None, :] - y[None, :, :]
        result = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    return _wrap(result, X, Y, dtype)


def manhattan(
    X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False, ring: bool = False
) -> DNDarray:
    """Pairwise L1 distances (reference: distance.py:185)."""
    dtype = _prepare(X, Y)
    if ring:
        out = _ring_path(X, Y, "manhattan", dtype)
        if out is not None:
            return out
    x, y = _cast(X, Y, dtype)
    diff = jnp.abs(x[:, None, :] - y[None, :, :])
    result = jnp.sum(diff, axis=-1)
    return _wrap(result, X, Y, dtype)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
    ring: bool = False,
) -> DNDarray:
    """RBF kernel exp(−d²/(2σ²)) (reference: distance.py:158)."""
    dtype = _prepare(X, Y)
    if ring:
        metric = "sqeuclidean" if quadratic_expansion else "sqeuclidean_direct"
        d2_arr = _ring_path(X, Y, metric, dtype)
        if d2_arr is not None:
            from ..core import _padding

            scale = -1.0 / (2.0 * sigma * sigma)
            # exp(0)=1 would poison the pad region — restore the zero-pad
            # invariant (_padding docstring) before wrapping
            vals = _padding.mask_phys(
                jnp.exp(d2_arr._phys * scale), d2_arr.gshape, d2_arr.split
            )
            return DNDarray(
                vals, d2_arr.gshape, d2_arr.dtype, d2_arr.split, d2_arr.device, d2_arr.comm
            )
    x, y = _cast(X, Y, dtype)
    if quadratic_expansion:
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        y2 = jnp.sum(y * y, axis=1, keepdims=True).T
        d2 = jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0)
    else:
        diff = x[:, None, :] - y[None, :, :]
        d2 = jnp.sum(diff * diff, axis=-1)
    result = jnp.exp(-d2 / (2.0 * sigma * sigma))
    return _wrap(result, X, Y, dtype)
