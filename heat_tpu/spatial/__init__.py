"""Distributed spatial algorithms (reference: /root/reference/heat/spatial/)."""

from .distance import *
