"""Async micro-batching dispatcher — many callers, one accelerator.

Every entry point today handles exactly one caller at a time: a process
serving a thousand concurrent ``predict`` calls would run a thousand
bucket-1 programs back to back, paying per-dispatch overhead on each
and leaving the MXU idle between them. The dispatcher closes that gap
with the standard serving shape:

- **bounded queue** (:class:`~heat_tpu.serving.admission.AdmissionControl`):
  submit returns a ``Future`` immediately or raises the typed
  :class:`~heat_tpu.serving.admission.ServingOverloaded` — overload is
  backpressure, never an unbounded backlog;
- **pad-to-bucket coalescing**: the worker drains whatever is queued,
  concatenates it into one batch, and pads up to the smallest declared
  bucket size — so the accelerator sees a handful of fixed shapes (each
  AOT-cacheable, see ``aot_cache``) instead of one program per request
  count;
- **donation-aware double buffering**: each batch stages into a fresh
  host buffer and device placement while the previous batch executes,
  and the worker issues batch k+1 BEFORE fencing batch k — depth-2
  pipelining, so an endpoint program that donates its input slab
  (buffer reuse) never races the staging of the next batch;
- **per-request latency + queue-depth telemetry**: ``serving.request.
  latency`` (p50/p95/p99 via the sharded registry) and ``serving.queue.
  depth`` samples, plus always-on local tallies in
  :meth:`Dispatcher.stats`.

Span tracing (ISSUE 15, ``HEAT_TPU_TRACE``): the full request
lifecycle — ``serving.submit`` (validation + enqueue), ``serving.queue``
(enqueue → batch collection), ``serving.batch`` (a detached span
bracketing one batch dispatch → resolve, parenting its
``serving.dispatch`` / ``serving.fence`` / ``serving.resolve`` phase
spans), and ``serving.request`` (submit → future resolution, per
request). Every probe is one module-bool read when the gate is off.
Shed and drain events additionally land in the always-on flight
recorder, and a shed request's :class:`ServingOverloaded` carries the
recorder tail (``exc.flight_tail``) for post-mortems.

Host-sync budget (shardlint SL106/SL201): the dispatch→result hot path
contains ZERO ``jax.device_get`` — futures resolve with device arrays
(lazy per-request slices of the batch result) after a completion FENCE
(``block_until_ready``), which synchronizes but never transfers. The
caller decides if and when values cross to the host.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
import weakref

from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .admission import AdmissionControl, ServingOverloaded
from . import aot_cache as _aot
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from ..resilience import elastic as _elastic

__all__ = [
    "Dispatcher", "Endpoint", "estimator_endpoint", "live_dispatchers",
    "program_endpoint",
]

_LAT_CAP = 4096  # local latency reservoir (stats() works with telemetry off)

#: every started dispatcher, weakly — what `ht.observability.
#: prometheus_text()` walks to render per-dispatcher gauges without the
#: serving layer handing it a handle
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


def live_dispatchers() -> List["Dispatcher"]:
    """The currently-running dispatchers (weakly tracked from
    :meth:`Dispatcher.start`), name-sorted — the Prometheus exposition
    walks this."""
    return sorted((d for d in list(_LIVE) if d.running), key=lambda d: d.name)


class _Request:
    __slots__ = ("payload", "rows", "future", "t_submit", "t_submit_pc", "deadline")

    def __init__(self, payload, rows, future, t_submit, deadline, t_submit_pc=None):
        self.payload = payload
        self.rows = rows
        self.future = future
        self.t_submit = t_submit
        # perf_counter twin of t_submit, taken only when tracing is live
        # (span timestamps must share tracing's clock domain)
        self.t_submit_pc = t_submit_pc
        self.deadline = deadline


class Endpoint:
    """A servable program family: one callable per declared bucket size
    over ``(bucket, *feature_shape)`` batches.

    Parameters
    ----------
    programs : ``{bucket: callable}`` — each maps a placed
        ``(bucket, *feature_shape)`` device array (plus ``extra_args``)
        to an array/pytree whose every leaf has leading dim ``bucket``.
    feature_shape / dtype : per-sample trailing shape and input dtype
        (requests are cast on submit).
    extra_args : arrays appended to every program call (e.g. the fitted
        cluster centers) — replicated model state, not batched data.
    place : host batch -> device array (default: ``jnp.asarray``); an
        estimator endpoint shards over its communicator's mesh here.
    static_peak_bytes : optional static peak-HBM estimate of the
        endpoint's largest-bucket program (``ht.analysis.memcheck`` →
        ``context["static_peak_bytes"]``). When set, the dispatcher's
        admission control rejects submissions whose program statically
        cannot fit with a typed
        ``ServingOverloaded(reason="hbm-estimate")`` instead of letting
        the dispatch OOM; ``None`` (the default) skips the check.
    """

    def __init__(self, programs: Dict[int, Callable], feature_shape: Tuple[int, ...],
                 dtype, extra_args: tuple = (), place: Optional[Callable] = None,
                 name: str = "endpoint", static_peak_bytes: Optional[int] = None):
        if not programs:
            raise ValueError("an Endpoint needs at least one bucket program")
        self.programs = dict(programs)
        self.buckets = tuple(sorted(int(b) for b in programs))
        if any(b < 1 for b in self.buckets):
            raise ValueError(f"bucket sizes must be >= 1, got {self.buckets}")
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.dtype = np.dtype(dtype)
        self.extra_args = tuple(extra_args)
        self.place = place if place is not None else (lambda batch: jnp.asarray(batch))
        self.name = name
        self.static_peak_bytes = (
            None if static_peak_bytes is None else int(static_peak_bytes)
        )
        # epoch fence (ISSUE 14, commcheck SL504): the bucket programs
        # are compiled against THIS world — record its epoch so a
        # dispatch racing a world re-resolution fails typed
        # (WorldChangedError) instead of hanging on devices that are
        # gone. Zero-cost until the elastic runtime engages; the
        # drain/resume contract swaps in a re-warmed Endpoint whose
        # token is fresh.
        self._world_token = _elastic.capture_epoch()

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        raise ValueError(f"{rows} rows exceed the largest bucket {self.max_rows}")

    def run(self, batch: np.ndarray):
        """Pad to bucket, place, and issue (asynchronously) the bucket's
        program. Returns ``(out, rows)``."""
        _elastic.check_epoch(self._world_token, what=f"endpoint {self.name!r}")
        rows = batch.shape[0]
        bucket = self.bucket_for(rows)
        if bucket > rows:
            pad = np.zeros((bucket - rows,) + self.feature_shape, dtype=self.dtype)
            batch = np.concatenate([batch, pad], axis=0)
        placed = self.place(batch)
        return self.programs[bucket](placed, *self.extra_args), bucket


class Dispatcher:
    """The micro-batching request loop over one :class:`Endpoint`.

    Use as a context manager (or ``start()``/``stop()``)::

        with ht.serving.Dispatcher(endpoint, max_queue=128) as d:
            fut = d.submit(x_batch)          # (n, *feature_shape), n >= 1
            labels = fut.result(timeout=5)   # device array, n rows

    ``submit`` raises :class:`ServingOverloaded` when the bounded queue
    is full; requests whose deadline passes while queued are shed with
    the same exception on their future.
    """

    def __init__(self, endpoint: Endpoint, admission: Optional[AdmissionControl] = None,
                 max_queue: int = 64, poll_s: float = 0.02, name: Optional[str] = None):
        self.endpoint = endpoint
        self.admission = admission or AdmissionControl(max_queue=max_queue)
        self.name = name or endpoint.name
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=self.admission.max_queue)
        # worker-owned batching state; the only client touches are the
        # post-join sweep in stop() and the raced-stop sweep in submit(),
        # both of which run strictly AFTER the worker exited
        self._carry: collections.deque = collections.deque()  # racecheck: guarded-by(worker-loop; clients sweep only after join)
        self._poll_s = float(poll_s)
        # monotonic shutdown flag: written by stop() BEFORE _stop.set(),
        # read by the worker only after it observes _stop — the Event is
        # the fence
        self._drain_on_stop = True  # racecheck: guarded-by(_stop event ordering)
        # elastic failover (ISSUE 13): drain() pauses collection, the
        # worker fences the in-flight batch, sheds the queue typed, and
        # parks until resume(). The reason is written by drain() BEFORE
        # _pause.set() and read by the worker only after it observes
        # _pause — same fence discipline as _drain_on_stop.
        self._pause = threading.Event()
        self._drained = threading.Event()
        self._pause_reason = "resize"  # racecheck: guarded-by(_pause event ordering)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lat: collections.deque = collections.deque(maxlen=_LAT_CAP)
        self._counts = {"requests": 0, "batches": 0, "rejected": 0, "shed": 0,
                        "padded_rows": 0, "rows": 0}
        self._counts_lock = threading.Lock()
        self._depth_max = 0

    # ------------------------------------------------------------------ #
    # lifecycle                                                          #
    # ------------------------------------------------------------------ #
    def start(self) -> "Dispatcher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name=f"ht-serving-{self.name}", daemon=True
        )
        self._thread.start()
        _LIVE.add(self)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the worker; with ``drain`` (default) queued requests are
        served first, otherwise they fail with
        :class:`ServingOverloaded` (``reason="shutdown"``)."""
        self._drain_on_stop = drain  # racecheck: guarded-by(_stop event ordering)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # drain still in progress past the timeout: keep the
                # handle (a later stop() can join again) and do NOT
                # sweep — the live worker still owns the queue
                return
            self._thread = None
        # post-join sweep: a submit() that raced the worker's final
        # drain pass may have enqueued after the last get — its future
        # would otherwise never resolve
        self._fail_queued("shutdown")

    def _fail_queued(self, reason: str = "shutdown") -> int:
        leftovers = list(self._carry)
        self._carry.clear()
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        if leftovers:
            # post-mortem breadcrumb + tail: a mass shed is exactly the
            # moment the last-N-things record matters
            _tracing.flight_record("serving.shed", reason, len(leftovers))
            tail = _tracing.flight_tail()
            for r in leftovers:
                if not r.future.done():
                    exc = ServingOverloaded(reason, queue_depth=len(leftovers))
                    exc.flight_tail = tail
                    r.future.set_exception(exc)
        return len(leftovers)

    # ------------------------------------------------------------------ #
    # elastic failover (ISSUE 13)                                        #
    # ------------------------------------------------------------------ #
    def drain(self, reason: str = "resize", timeout: float = 30.0) -> bool:
        """Fence and shed for a world change: the worker completes (and
        resolves) the in-flight batch, every QUEUED request's future
        fails typed — ``ServingOverloaded(reason="resize")`` by default,
        which load balancers treat as "fail over to another replica",
        extending the PR 9 shutdown contract — and the worker parks.
        New ``submit`` calls are rejected with the same reason until
        :meth:`resume`. Returns True once the worker confirms the drain
        (False on timeout; the pause stays armed either way)."""
        self._pause_reason = reason  # racecheck: guarded-by(_pause event ordering)
        self._drained.clear()
        self._pause.set()
        _tracing.flight_record("serving.drain", reason, self._q.qsize())
        if _telemetry._ENABLED:
            _telemetry.inc("serving.drain.count")
        if not self.running:
            # no worker to confirm: sweep here (nothing can be in flight)
            self._fail_queued(reason)
            self._drained.set()
            return True
        return self._drained.wait(timeout)

    def resume(self, endpoint: Optional[Endpoint] = None) -> None:
        """Unpark after a :meth:`drain` — optionally swapping in an
        endpoint rebuilt against the re-resolved world (its bucket
        programs come through ``aot_cache.ensure_program``, so a store
        warmed for that world serves them without compiling)."""
        if endpoint is not None:
            # written only while the worker is parked behind _pause
            self.endpoint = endpoint  # racecheck: guarded-by(_pause event ordering)
        self._drained.clear()
        self._pause.clear()

    def __enter__(self) -> "Dispatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------------ #
    # client side                                                        #
    # ------------------------------------------------------------------ #
    def submit(self, x, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one request: ``x`` is ``(n, *feature_shape)`` (or one
        unbatched sample) with ``1 <= n <=`` the largest bucket. Returns
        a ``Future`` resolving to the n-row device-array result."""
        if not self.running:
            raise RuntimeError("dispatcher is not running — call start() or use a with block")
        if self._pause.is_set():
            # draining for a world change: fail fast with the drain
            # reason so the load balancer fails over immediately
            with self._counts_lock:
                self._counts["rejected"] += 1
            if _telemetry._ENABLED:
                _telemetry.inc("serving.admission.rejected")
            raise ServingOverloaded(self._pause_reason, queue_depth=self._q.qsize())
        x = np.asarray(x, dtype=self.endpoint.dtype)
        if x.shape == self.endpoint.feature_shape:
            x = x[None]
        if x.shape[1:] != self.endpoint.feature_shape:
            raise ValueError(
                f"request shape {x.shape} does not match endpoint feature shape "
                f"(n, {', '.join(map(str, self.endpoint.feature_shape))})"
            )
        rows = int(x.shape[0])
        if rows < 1 or rows > self.endpoint.max_rows:
            raise ValueError(
                f"request rows {rows} outside [1, {self.endpoint.max_rows}] "
                "(the endpoint's largest bucket)"
            )
        # memory admission (ISSUE 10): an endpoint that DECLARES its
        # static peak (ht.analysis.memcheck) is rejected typed when the
        # program cannot fit the per-device HBM budget — a dispatch that
        # would OOM must never reach the accelerator
        peak = self.endpoint.static_peak_bytes
        if self.admission.over_memory(peak):
            with self._counts_lock:
                self._counts["rejected"] += 1
            if _telemetry._ENABLED:
                _telemetry.inc("serving.admission.rejected")
            raise self.admission.reject_memory(peak)
        now = time.monotonic()
        sp = _tracing.start_span(
            "serving.submit", endpoint=self.name, rows=rows
        ) if _tracing._ENABLED else None
        req = _Request(
            x, rows, Future(), now, self.admission.deadline_for(now, deadline_s),
            t_submit_pc=(time.perf_counter() if sp is not None else None),
        )
        try:
            try:
                self._q.put_nowait(req)
            except queue.Full:
                with self._counts_lock:
                    self._counts["rejected"] += 1
                if _telemetry._ENABLED:
                    _telemetry.inc("serving.admission.rejected")
                raise self.admission.reject(self._q.qsize()) from None
            if not self.running:
                # TOCTOU with stop(): the worker exited (and its post-stop
                # sweep may already have run) between the running check
                # above and the put — sweep our own enqueue so the future
                # resolves typed instead of hanging. If the final drain
                # already served it, the future holds a result and passes
                # through untouched.
                self._fail_queued("shutdown")  # submit raced stop()
                exc = req.future.exception() if req.future.done() else None
                if exc is not None:
                    raise exc
            depth = self._q.qsize()
            with self._counts_lock:
                self._counts["requests"] += 1
                if depth > self._depth_max:
                    self._depth_max = depth
            if _telemetry._ENABLED:
                _telemetry.inc("serving.requests")
                _telemetry.observe("serving.queue.depth", float(depth))
            return req.future
        finally:
            _tracing.end_span(sp)

    def call(self, x, timeout: Optional[float] = 60.0, deadline_s: Optional[float] = None):
        """``submit(...).result(timeout)`` convenience."""
        return self.submit(x, deadline_s=deadline_s).result(timeout=timeout)

    def stats(self) -> dict:
        """Always-on local tallies (works with global telemetry off):
        counters plus p50/p95/p99 request latency and max observed
        depth."""
        with self._counts_lock:
            lat = sorted(self._lat)
            out = dict(self._counts)
            out["queue_depth_max"] = self._depth_max
        # the SAME nearest-rank rule the telemetry registry uses, so
        # stats() and serving.request.latency report identical
        # percentiles over identical samples
        out["p50_s"] = _telemetry._percentile(lat, 0.50)
        out["p95_s"] = _telemetry._percentile(lat, 0.95)
        out["p99_s"] = _telemetry._percentile(lat, 0.99)
        return out

    # ------------------------------------------------------------------ #
    # worker side                                                        #
    # ------------------------------------------------------------------ #
    def _collect(self, block: bool = True):
        """Drain up to one max-bucket's worth of queued requests (deadline
        shedding applied at dequeue), or ``None`` this poll. With
        ``block=False`` (a batch is in flight) an empty queue returns
        immediately so the fence never waits out a poll interval."""
        reqs, rows = [], 0
        limit = self.endpoint.max_rows
        while self._carry and rows + self._carry[0].rows <= limit:
            r = self._carry.popleft()
            reqs.append(r)
            rows += r.rows
        if not reqs:
            try:
                r = self._q.get(timeout=self._poll_s) if block else self._q.get_nowait()
                reqs.append(r)
                rows += r.rows
            except queue.Empty:
                return None
        while rows < limit:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if rows + r.rows > limit:
                self._carry.append(r)  # head of the NEXT batch
                break
            reqs.append(r)
            rows += r.rows
        now = time.monotonic()
        live = []
        for r in reqs:
            if self.admission.expired(r.deadline, now):
                with self._counts_lock:
                    self._counts["shed"] += 1
                _tracing.flight_record("serving.shed", "deadline", self._q.qsize())
                if _telemetry._ENABLED:
                    _telemetry.inc("serving.admission.shed")
                exc = self.admission.shed(r.deadline, self._q.qsize())
                exc.flight_tail = _tracing.flight_tail()
                r.future.set_exception(exc)
            else:
                live.append(r)
        return live or None

    def _dispatch(self, reqs):
        """Stage (fresh host buffer + device placement) and ISSUE one
        padded batch — asynchronous: the fence happens in ``_resolve``,
        after the NEXT batch has been issued (depth-2 double buffering;
        a donated input slab is therefore never re-staged while its
        program still runs)."""
        batch_sp = None
        if _tracing._ENABLED:
            # detached: the batch lifecycle outlives this call frame —
            # _resolve closes it after the fence, with another batch's
            # dispatch span possibly opening in between
            batch_sp = _tracing.start_span(
                "serving.batch", detached=True, endpoint=self.name, n_reqs=len(reqs)
            )
            now_pc = time.perf_counter()
            for r in reqs:
                if r.t_submit_pc is not None:
                    _tracing.add_span(
                        "serving.queue", r.t_submit_pc, now_pc,
                        parent_id=batch_sp.id, rows=r.rows,
                    )
        batch = np.concatenate([r.payload for r in reqs], axis=0)
        rows = batch.shape[0]
        try:
            with _tracing.span(
                "serving.dispatch",
                parent_id=None if batch_sp is None else batch_sp.id,
                endpoint=self.name, rows=rows,
            ):
                out, bucket = self.endpoint.run(batch)
        except Exception as e:  # program build/placement failure: fail the batch, not the loop
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            _tracing.end_span(batch_sp, status="error")
            return None
        if batch_sp is not None:
            batch_sp.attrs["bucket"] = bucket
            batch_sp.attrs["rows"] = rows
        with self._counts_lock:
            self._counts["batches"] += 1
            self._counts["rows"] += rows
            self._counts["padded_rows"] += bucket - rows
        if _telemetry._ENABLED:
            _telemetry.inc("serving.batches")
            _telemetry.inc("serving.batch.rows", rows)
            _telemetry.inc("serving.batch.padded_rows", bucket - rows)
            _telemetry.observe("serving.queue.depth", float(self._q.qsize()))
        return (out, reqs, batch_sp)

    def _resolve(self, inflight) -> None:
        """Fence the batch (completion, not transfer — no device_get) and
        resolve each request's future with its lazy device-array slice.
        A poisoned batch (execution error surfacing at the fence) fails
        its own requests, never the worker loop."""
        out, reqs, batch_sp = inflight
        parent = None if batch_sp is None else batch_sp.id
        try:
            with _tracing.span("serving.fence", parent_id=parent, endpoint=self.name):
                jax.block_until_ready(out)
        except Exception as e:
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            _tracing.end_span(batch_sp, status="error")
            return
        t_done = time.monotonic()
        t_done_pc = time.perf_counter() if _tracing._ENABLED else 0.0
        resolve_sp = _tracing.start_span(
            "serving.resolve", parent_id=parent, endpoint=self.name
        ) if _tracing._ENABLED else None
        off = 0
        for r in reqs:
            lo, hi = off, off + r.rows
            off = hi
            try:
                sl = jax.tree.map(lambda a: a[lo:hi], out)
                if not r.future.done():  # client may have cancel()ed
                    r.future.set_result(sl)
            except Exception as e:  # a bad output leaf fails ITS request only
                if not r.future.done():
                    r.future.set_exception(e)
                continue
            lat = t_done - r.t_submit
            if r.t_submit_pc is not None:
                _tracing.add_span(
                    "serving.request", r.t_submit_pc, t_done_pc,
                    parent_id=parent, endpoint=self.name, rows=r.rows,
                )
            with self._counts_lock:
                self._lat.append(lat)
            if _telemetry._ENABLED:
                _telemetry.observe("serving.request.latency", lat)
        _tracing.end_span(resolve_sp)
        _tracing.end_span(batch_sp)

    def _worker(self) -> None:
        inflight = None
        while True:
            if self._pause.is_set() and not self._stop.is_set():
                # elastic drain: fence the in-flight batch (its futures
                # RESOLVE — work already on the accelerator completes),
                # shed the backlog typed with the drain reason, confirm,
                # and park until resume() or stop()
                if inflight is not None:
                    self._resolve(inflight)
                    inflight = None
                n = self._fail_queued(self._pause_reason)
                if n:
                    with self._counts_lock:
                        self._counts["shed"] += n
                    if _telemetry._ENABLED:
                        _telemetry.inc("serving.drain.shed", n)
                self._drained.set()
                self._stop.wait(self._poll_s)  # parked; re-checks both events
                continue
            # stop(drain=False): collect nothing more — still-queued
            # requests fail typed below; the in-flight batch completes
            draining = not (
                self._stop.is_set() and not self._drain_on_stop
            )
            # non-blocking collect while a batch is in flight: the fence
            # must run as soon as there is nothing to stage, not after a
            # poll interval — every trailing batch's latency depends on it
            batch = self._collect(block=inflight is None) if draining else None
            staged = self._dispatch(batch) if batch else None
            if inflight is not None:
                self._resolve(inflight)
            inflight = staged
            if self._stop.is_set() and inflight is None and not batch:
                if self._drain_on_stop:
                    if self._carry or not self._q.empty():
                        continue  # keep serving until the backlog is gone
                else:
                    self._fail_queued("shutdown")
                break


# ---------------------------------------------------------------------- #
# endpoint builders                                                      #
# ---------------------------------------------------------------------- #
def program_endpoint(build, example_feature_shape, dtype, buckets: Sequence[int],
                     key: tuple, extra_args: tuple = (), place: Optional[Callable] = None,
                     input_sharding=None, donate: bool = False,
                     name: str = "program",
                     static_peak_bytes: Optional[int] = None) -> Endpoint:
    """An :class:`Endpoint` over an arbitrary program builder.

    ``build()`` returns the jitted program ``(batch, *extra_args) ->
    result``; each bucket's callable is resolved through the persistent
    AOT cache (:func:`heat_tpu.serving.aot_cache.ensure_program`) under
    ``key + (bucket,)`` — a warm process loads every bucket without
    tracing. ``donate=True`` donates the batch slab (argument 0).
    ``static_peak_bytes`` (optional, from ``ht.analysis.memcheck``)
    arms the dispatcher's HBM admission check."""
    feature_shape = tuple(int(s) for s in example_feature_shape)
    dtype = np.dtype(dtype)
    extra_sds = _aot._input_sds(extra_args)
    programs = {}
    for b in sorted(set(int(x) for x in buckets)):
        sds = jax.ShapeDtypeStruct((b,) + feature_shape, dtype, sharding=input_sharding)
        call, _status = _aot.ensure_program(
            tuple(key) + (("bucket", b),), build, (sds, *extra_sds),
            donate_argnums=(0,) if donate else (),
        )
        programs[b] = call
    return Endpoint(programs, feature_shape, dtype, extra_args=extra_args,
                    place=place, name=name, static_peak_bytes=static_peak_bytes)


def estimator_endpoint(estimator, buckets: Sequence[int] = (8, 32, 128),
                       donate: bool = False, name: Optional[str] = None) -> Endpoint:
    """An :class:`Endpoint` over a fitted estimator's serving program
    (``predict`` for the k-cluster family and KNeighborsClassifier —
    the estimator exposes it via ``serving_program()``). Batches are
    placed split-0 over the estimator's mesh; model state (centers /
    training set) rides as replicated ``extra_args``."""
    spec = estimator.serving_program()
    comm = spec.get("comm")
    place = None
    input_sharding = None
    if comm is not None and comm.is_distributed():
        ndim = 1 + len(spec["feature_shape"])
        input_sharding = comm.sharding(ndim, 0)

        def place(batch, _comm=comm):
            return _comm.shard(jnp.asarray(batch), 0)

    return program_endpoint(
        spec["build"], spec["feature_shape"], spec["dtype"], buckets,
        key=spec["key"], extra_args=spec["args"], place=place,
        input_sharding=input_sharding, donate=donate,
        name=name or spec.get("name", type(estimator).__name__.lower()),
    )


def transform_endpoint(transformer, buckets: Sequence[int] = (8, 32, 128),
                       donate: bool = False, name: Optional[str] = None) -> Endpoint:
    """An :class:`Endpoint` over a fitted transformer's serving program
    (one-hot / TF-IDF — ``preprocessing.sparse_encoders``). Same
    ``serving_program()`` contract as :func:`estimator_endpoint`; split
    out so warmup manifests and dashboards can tell ``transform``
    endpoints (feature pipelines) from ``predict`` endpoints (models),
    and so transformers without a distributed mesh stay replicated."""
    return estimator_endpoint(transformer, buckets=buckets, donate=donate,
                              name=name)
