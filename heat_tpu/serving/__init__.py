"""heat_tpu.serving — production serving runtime (ISSUE 9).

The north star is heavy traffic from millions of users, but the library
 entry points (``predict``/``transform``/``ht.jit`` programs) were
built for one caller and a warm process. This package adds the three
pieces a request path needs, on top of the jit/donation/telemetry
substrate of PRs 1–8:

- :mod:`~heat_tpu.serving.aot_cache` — persistent AOT program cache
  (``jax.export`` artifacts keyed by the existing (comm, spec, impl,
  donation, env-gate) signatures + version stamps): cold start is
  load-not-compile, with corruption/version mismatch falling back to
  recompile. Gates: ``HEAT_TPU_SERVING_AOT=0/1/auto``,
  ``HEAT_TPU_SERVING_CACHE=<dir>``.
- :mod:`~heat_tpu.serving.dispatcher` — async micro-batching: bounded
  queue, pad-to-bucket coalescing into the fixed batch shapes the
  programs (and the AOT store) already know, donation-aware depth-2
  double buffering, per-request p50/p95 + queue-depth telemetry.
- :mod:`~heat_tpu.serving.admission` — explicit backpressure: bounded
  depth and deadline shedding with the typed :class:`ServingOverloaded`
  rejection.

Quick start::

    import heat_tpu as ht
    ht.serving.configure(cache_dir="/var/cache/heat_tpu")   # or env gates
    model = ht.cluster.KMeans(n_clusters=8).fit(x)
    ep = ht.serving.estimator_endpoint(model, buckets=(32, 128))
    with ht.serving.Dispatcher(ep, max_queue=256) as d:
        labels = d.call(batch)      # micro-batched with concurrent callers

``scripts/warmup.py`` pre-compiles and exports the declared program set
(:data:`WARMUP_PROGRAMS`) so a fleet rollout ships a hot cache.
"""

from __future__ import annotations

import time

from typing import Dict, Optional

import numpy as np

import jax.numpy as jnp

from .admission import AdmissionControl, ServingOverloaded
from .aot_cache import (
    AOTStore,
    active_store,
    cache_dir,
    configure,
    enabled,
    ensure_program,
)
from .dispatcher import (
    Dispatcher,
    Endpoint,
    estimator_endpoint,
    program_endpoint,
    transform_endpoint,
)

__all__ = [
    "AOTStore",
    "AdmissionControl",
    "Dispatcher",
    "Endpoint",
    "ServingOverloaded",
    "WARMUP_PROGRAMS",
    "active_store",
    "cache_dir",
    "configure",
    "enabled",
    "ensure_program",
    "estimator_endpoint",
    "program_endpoint",
    "transform_endpoint",
    "warmup",
]


# ---------------------------------------------------------------------- #
# declared warmup set                                                    #
# ---------------------------------------------------------------------- #
# The canonical serving programs a fleet pre-exports before rollout:
# estimator predict programs at their bucket shapes plus a representative
# ht.jit pipeline. Each entry is a callable returning {variant: status}
# with ensure_program-style statuses ("hit" on a warm store, "store" on
# first export, "off"/"bypass" otherwise).


def _warm_kcluster() -> Dict[str, str]:
    from ..cluster import _kcluster

    k, d = 8, 16
    centers = jnp.linspace(0.0, 1.0, k * d, dtype=jnp.float32).reshape(k, d)
    spec = _kcluster.serving_spec("euclidean", centers)
    out = {}
    for bucket in (16, 64):
        import jax as _jax

        sds = _jax.ShapeDtypeStruct((bucket, d), np.float32)
        _call, status = ensure_program(
            tuple(spec["key"]) + (("bucket", bucket),), spec["build"], (sds, *spec["args"])
        )
        out[f"b{bucket}"] = status
    return out


def _warm_knn() -> Dict[str, str]:
    from ..classification import kneighborsclassifier as _knn

    n_train, d, n_classes = 32, 8, 3
    xt = jnp.linspace(0.0, 1.0, n_train * d, dtype=jnp.float32).reshape(n_train, d)
    onehot = jnp.eye(n_classes, dtype=jnp.float32)[jnp.arange(n_train) % n_classes]
    classes = jnp.arange(n_classes, dtype=jnp.int32)
    spec = _knn.serving_spec(5, xt, onehot, classes)
    out = {}
    for bucket in (16,):
        import jax as _jax

        sds = _jax.ShapeDtypeStruct((bucket, d), np.float32)
        _call, status = ensure_program(
            tuple(spec["key"]) + (("bucket", bucket),), spec["build"], (sds, *spec["args"])
        )
        out[f"b{bucket}"] = status
    return out


def _gram_norms_pipeline(x):
    """The declared ht.jit warmup program: a fused matmul+reduction
    chain over a split array — representative of the linalg entry
    points a serving pipeline composes."""
    import heat_tpu as ht

    g = ht.matmul(x, ht.transpose(x))
    return ht.sqrt(ht.sum(g * g, axis=1))


def _warm_htjit() -> Dict[str, str]:
    import heat_tpu as ht

    store = active_store()
    before = dict(store.stats) if store is not None else {}
    x = ht.ones((64, 16), split=0, dtype=ht.float32)
    jitted = ht.jit(_gram_norms_pipeline)
    jitted(x)
    if store is None:
        return {"pipeline": "off"}
    # order matters for the --expect-hits reload proof: an envelope-level
    # hit whose artifact then failed to deserialize ALSO bumps bypass and
    # recompiles (store) — that run must not report "hit"
    if store.stats.get("store", 0) > before.get("store", 0):
        return {"pipeline": "store"}
    if store.stats.get("bypass", 0) > before.get("bypass", 0):
        return {"pipeline": "bypass"}
    if store.stats.get("hit", 0) > before.get("hit", 0):
        return {"pipeline": "hit"}
    return {"pipeline": "bypass"}


WARMUP_PROGRAMS = {
    "kcluster_predict": _warm_kcluster,
    "knn_predict": _warm_knn,
    "htjit_gram_norms": _warm_htjit,
}


def warmup(names: Optional[list] = None) -> Dict[str, dict]:
    """Pre-compile and export the declared program set (``names`` =
    subset of :data:`WARMUP_PROGRAMS`, default all). Returns
    ``{name: {"variants": {variant: status}, "seconds": t}}`` — on a
    warm store every status is ``"hit"`` and nothing was traced."""
    import heat_tpu as ht

    if names:
        unknown = sorted(set(names) - set(WARMUP_PROGRAMS))
        if unknown:
            raise ValueError(
                f"unknown warmup programs {unknown} — declared set: "
                f"{sorted(WARMUP_PROGRAMS)}"
            )
    # resolve the platform dtype policy (x64/complex, core/devices)
    # BEFORE any persistent key is derived: the x64 flag is part of
    # every key, and it must match what a serving process (which builds
    # arrays before programs) will see
    ht.zeros(1)
    results: Dict[str, dict] = {}
    for name, thunk in WARMUP_PROGRAMS.items():
        if names and name not in names:
            continue
        t0 = time.perf_counter()
        variants = thunk()
        results[name] = {
            "variants": variants,
            "seconds": round(time.perf_counter() - t0, 4),
        }
    return results
