"""Admission control — explicit backpressure for the serving dispatcher.

A production endpoint under heavy traffic has exactly three honest
options when work arrives faster than the accelerator drains it: queue
it (bounded — an unbounded queue converts overload into latency and
then into OOM), shed it (deadline-aware — a result delivered after the
caller's deadline is wasted accelerator time), or reject it at the door
(typed, observable — so the load balancer can back off). This module is
that policy, factored out of the dispatcher so tests and operators can
reason about it in one place:

- :class:`ServingOverloaded` — the typed rejection every shed/reject
  path raises, carrying the reason and the queue state that triggered
  it (callers pattern-match on the class, dashboards on the fields).
- :class:`AdmissionControl` — bounded queue depth at submit time plus
  deadline-aware shedding at dequeue time.

Telemetry: the dispatcher records ``serving.admission.rejected`` /
``serving.admission.shed`` counters for every decision made here.
"""

from __future__ import annotations

import time

from typing import Optional

__all__ = ["AdmissionControl", "ServingOverloaded"]


class ServingOverloaded(RuntimeError):
    """Typed rejection: the serving runtime refused or shed a request.

    Attributes
    ----------
    reason : ``"queue-full"`` (rejected at submit: the bounded queue is
        at depth limit), ``"deadline"`` (shed at dequeue: the request's
        deadline passed while it waited), ``"shutdown"`` (the
        dispatcher stopped before serving the queued request — retry
        against a live replica, do NOT back off as if overloaded),
        ``"resize"`` (ISSUE 13: the dispatcher is draining for a world
        change — ``Dispatcher.drain``; like shutdown, FAIL OVER to
        another replica immediately instead of backing off: this
        replica re-warms against the re-resolved world and comes back),
        or ``"hbm-estimate"`` (rejected at submit: the endpoint
        program's STATIC peak-HBM estimate — ``ht.analysis.memcheck``'s
        ``static_peak_bytes`` — exceeds the per-device budget, so the
        request would OOM, not queue; route it to a bigger replica).
    queue_depth : observed queue depth at decision time.
    limit : the configured bound that was hit (queue capacity, the
        deadline in seconds for shed requests, or the HBM budget in
        bytes for memory rejections; ``None`` for shutdown).
    static_peak_bytes : the program's static peak-HBM estimate, set on
        ``"hbm-estimate"`` rejections only.
    """

    def __init__(self, reason: str, queue_depth: Optional[int] = None,
                 limit: Optional[float] = None,
                 static_peak_bytes: Optional[int] = None):
        self.reason = reason
        self.queue_depth = queue_depth
        self.limit = limit
        self.static_peak_bytes = static_peak_bytes
        detail = f"serving overloaded ({reason})"
        if queue_depth is not None:
            detail += f": queue depth {queue_depth}"
        if static_peak_bytes is not None:
            detail += f": static peak-HBM estimate {static_peak_bytes} B"
        if limit is not None:
            detail += f" >= limit {limit}"
        super().__init__(detail)


class AdmissionControl:
    """Bounded-queue + deadline admission policy.

    Parameters
    ----------
    max_queue : maximum number of requests allowed to wait (the
        dispatcher sizes its queue with this; submit past it raises
        :class:`ServingOverloaded` immediately instead of blocking the
        client thread behind an unbounded backlog).
    default_deadline_s : deadline applied to requests that do not carry
        their own (``None`` = no deadline: never shed).
    hbm_limit_bytes : per-device HBM budget an endpoint program's STATIC
        peak estimate (``ht.analysis.memcheck`` → ``static_peak_bytes``,
        carried by the endpoint) must fit under; default ``None``
        resolves ``HEAT_TPU_HBM_BYTES`` (v5e 16 GiB) lazily. The check
        only engages for endpoints that DECLARE an estimate — with no
        estimate every code path is exactly the pre-memcheck one.
    """

    def __init__(self, max_queue: int = 64,
                 default_deadline_s: Optional[float] = None,
                 hbm_limit_bytes: Optional[int] = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.default_deadline_s = default_deadline_s
        self.hbm_limit_bytes = None if hbm_limit_bytes is None else int(hbm_limit_bytes)

    def deadline_for(self, t_submit: float, deadline_s: Optional[float]) -> Optional[float]:
        """Absolute deadline timestamp for a request submitted at
        ``t_submit`` (monotonic seconds), or ``None``."""
        rel = deadline_s if deadline_s is not None else self.default_deadline_s
        return None if rel is None else t_submit + float(rel)

    def reject(self, queue_depth: int) -> ServingOverloaded:
        """The typed rejection for a submit that found the queue full."""
        return ServingOverloaded(
            "queue-full", queue_depth=queue_depth, limit=self.max_queue
        )

    def expired(self, deadline: Optional[float], now: Optional[float] = None) -> bool:
        """Deadline-aware shedding predicate: has this request's
        absolute deadline passed? (Called at dequeue time — a request
        that waited out its deadline is dropped before it wastes a
        batch slot.)"""
        if deadline is None:
            return False
        return (time.monotonic() if now is None else now) > deadline

    def shed(self, deadline: float, queue_depth: int) -> ServingOverloaded:
        """The typed rejection delivered to a shed request's future."""
        return ServingOverloaded("deadline", queue_depth=queue_depth, limit=deadline)

    def _hbm_budget(self) -> int:
        if self.hbm_limit_bytes is not None:
            return self.hbm_limit_bytes
        from ..analysis.memcheck import hbm_budget_bytes

        return hbm_budget_bytes()

    def over_memory(self, static_peak_bytes: Optional[int]) -> bool:
        """Memory admission predicate: does the endpoint program's
        static peak-HBM estimate exceed the budget? ``None`` (no
        estimate declared) never rejects — the check is opt-in per
        endpoint."""
        if static_peak_bytes is None:
            return False
        return int(static_peak_bytes) > self._hbm_budget()

    def reject_memory(self, static_peak_bytes: int) -> ServingOverloaded:
        """The typed rejection for a program that statically cannot fit:
        ``reason="hbm-estimate"``, ``limit`` = the HBM budget in bytes.
        Load balancers route these to a bigger replica instead of
        backing off."""
        return ServingOverloaded(
            "hbm-estimate",
            limit=self._hbm_budget(),
            static_peak_bytes=int(static_peak_bytes),
        )
