"""Persistent AOT program cache — cold start becomes load-not-compile.

Every entry point today (``predict``/``transform``/``ht.jit`` linalg
programs) pays full trace + XLA compile on the first dispatch of every
process. For a serving fleet that restarts, autoscales, and rolls out
continuously, that cost is paid per replica per program — minutes of
accelerator idle at production program sizes. This module closes it
with a two-layer on-disk cache:

1. **``jax.export`` artifacts** (this module's own store): the traced,
   lowered StableHLO of a compiled program plus the ht-level output
   metadata, keyed by the SAME signature the in-process caches use —
   ``(comm, spec, impl, donation, env-gate)`` — extended with
   jax/heat_tpu version stamps, backend platform and device count. A
   warm process deserializes instead of re-tracing user code.
2. **the XLA persistent compilation cache** (``jax_compilation_cache_dir``
   pointed under the same root): on backends that support it (TPU/GPU)
   the post-optimization XLA executable is reused too, so the wrapper
   compile around a deserialized artifact is a disk read, not an XLA
   optimization pass. (CPU in this jax has no executable cache; the
   export layer still removes tracing there.)

Failure policy — the cache must NEVER be a correctness or availability
hazard: any corrupt file, version mismatch, unsupported program shape
or serialization error falls back to the normal trace-and-compile path
(counted, not raised). ``HEAT_TPU_SERVING_AOT=0`` is the escape hatch:
the hooks are never installed and ``core/jit.py`` runs its exact
pre-serving code paths.

TRUST BOUNDARY — the store directory is executable input, same class
as the Python code directory: envelopes are unpickled and their
program artifacts dispatched to the accelerator, so a writer of the
cache dir can execute code in every process that reads it. Point
``HEAT_TPU_SERVING_CACHE`` only at paths with the same write
permissions as the deployment's code (bake it into the image with the
wheels, as ``scripts/warmup.py`` is built for); never at
world-writable or untrusted shared storage. The corruption/version
checks defend against ACCIDENTS (torn writes, stale rollouts), not
against a malicious writer.

Gates
-----
- ``HEAT_TPU_SERVING_AOT``: ``0`` off (escape hatch), ``1`` on,
  unset/``auto`` = on iff ``HEAT_TPU_SERVING_CACHE`` names a directory.
- ``HEAT_TPU_SERVING_CACHE``: store root (default
  ``~/.cache/heat_tpu/aot``).

Telemetry (when enabled): ``serving.aot.{hit,miss,bypass,store,corrupt,
version_mismatch}`` counters + ``serving.aot.{load,export}`` timers.
The store keeps the same tallies in ``AOTStore.stats`` unconditionally
(the warmup CLI reports them without flipping the global switch).
"""

from __future__ import annotations

import hashlib
import inspect
import os
import pickle
import time

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

import jax

import importlib

# the module, not the public `jit` function that shadows it in the
# core package namespace
_ht_jit = importlib.import_module(__name__.rsplit(".", 2)[0] + ".core.jit")

from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from ..version import __version__

__all__ = [
    "AOTStore",
    "cache_dir",
    "configure",
    "enabled",
    "ensure_program",
    "active_store",
]

_FORMAT = 1

# env gates whose value changes the PROGRAMS the library builds are part
# of every persistent key, so a cache written under one gate combination
# never serves a process running another. Which gates those ARE is no
# longer a hand-listed prefix scan: the set derives from the registry's
# ``affects_programs`` declarations (heat_tpu/core/gates.py) — the
# serving and telemetry switches are the registered
# ``affects_programs=False`` entries the old exclusion list spelled by
# prefix. Byte-compatible with the PR 9 filter at every combination.
from ..core import gates as _gates


# the truthy spellings are the telemetry module's — one definition,
# one set of accepted values across every HEAT_TPU_* switch
_env_truthy = _telemetry._env_truthy


def _env_falsy(value: Optional[str]) -> bool:
    return (value or "").strip().lower() in ("0", "false", "off", "no")


def cache_dir() -> str:
    """The store root: ``HEAT_TPU_SERVING_CACHE`` or the user default."""
    return _gates.get(
        "HEAT_TPU_SERVING_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "heat_tpu", "aot"),
    )


def _gate_fingerprint() -> Tuple[Tuple[str, str], ...]:
    """(name, raw value) of every program-affecting gate that is set —
    registry-derived (``gates.aot_fingerprint``), empty at defaults."""
    return _gates.aot_fingerprint()


def _runtime_stamps() -> Dict[str, Any]:
    """Version/platform stamps: hashed into every key AND stored in each
    entry's meta (the load path re-verifies them — defense in depth
    against key truncation and hand-copied cache dirs)."""
    return {
        "format": _FORMAT,
        "heat_tpu": __version__,
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "devices": int(jax.device_count()),
    }


def _envelope_stamps() -> Dict[str, Any]:
    """What every stored envelope's meta must match at load: the runtime
    stamps PLUS the registered program-affecting gate ROSTER
    (``gates.program_gate_roster``). The roster rides in the meta, never
    the key: registering a new program-affecting gate in a later version
    changes the roster, so every envelope written under the old one is
    refused as ``version_mismatch`` — the old artifacts may predate the
    gate's subsystem entirely, and a recompile is the only safe answer
    (never a stale hit)."""
    stamps = _runtime_stamps()
    stamps["gate_roster"] = _gates.program_gate_roster()
    return stamps


def _key_stamps() -> tuple:
    stamps = _runtime_stamps()
    return (
        tuple(sorted(stamps.items())),
        ("x64", bool(jax.config.jax_enable_x64)),
        ("gates", _gate_fingerprint()),
    )


def _stable_static(leaf) -> Optional[str]:
    """A process-independent string for a static leaf, or ``None`` when
    the leaf has no stable serialization (object reprs carry addresses —
    such signatures bypass the persistent cache rather than risk a
    collision)."""
    if leaf is None or isinstance(leaf, (bool, int, float, str, bytes)):
        return repr(leaf)
    if isinstance(leaf, (tuple, frozenset)):
        items = sorted(leaf, key=repr) if isinstance(leaf, frozenset) else leaf
        parts = [_stable_static(v) for v in items]
        if any(p is None for p in parts):
            return None
        return f"{type(leaf).__name__}({','.join(parts)})"
    return None


def _comm_desc(comm) -> tuple:
    """Stable communicator descriptor: what the program's collectives
    depend on (world size, axis name, tier topology) — never the
    process-local object identity the in-memory key uses."""
    try:
        size = int(comm.size)
    except Exception:
        size = -1
    axis = getattr(comm, "axis_name", None)
    try:
        topo = str(comm.topology)
    except Exception:
        topo = "flat"
    return (type(comm).__name__, size, axis, topo)


def _fn_ident(fn) -> tuple:
    """(module.qualname, source sha1) — the ``impl`` part of the key.
    The source hash invalidates entries when the function body changes
    between deployments even though the qualname did not."""
    name = f"{getattr(fn, '__module__', '?')}.{getattr(fn, '__qualname__', repr(fn))}"
    try:
        src = inspect.getsource(inspect.unwrap(fn))
        sha = hashlib.sha1(src.encode()).hexdigest()
    except (TypeError, OSError):
        sha = "nosource"
    return (name, sha)


def _input_sds(traced_in: Sequence) -> list:
    """ShapeDtypeStructs (with shardings) for ``jax.export`` tracing,
    read off the concrete arrays of the first dispatch."""
    out = []
    for a in traced_in:
        if isinstance(a, jax.ShapeDtypeStruct):
            out.append(a)
            continue
        a = np.asarray(a) if not hasattr(a, "dtype") else a
        out.append(jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=getattr(a, "sharding", None)))
    return out


class AOTStore:
    """The on-disk artifact store: one pickle envelope per program key
    (``<root>/<sha256[:40]>.aot``) holding the serialized ``jax.export``
    blob, the ht-level output metadata, and the version stamps."""

    def __init__(self, root: str):
        self.root = root
        self.stats: Dict[str, int] = {
            "hit": 0, "miss": 0, "bypass": 0, "store": 0,
            "corrupt": 0, "version_mismatch": 0,
        }

    # ------------------------------------------------------------------ #
    # keys / paths                                                       #
    # ------------------------------------------------------------------ #
    def key(self, parts: tuple) -> str:
        return hashlib.sha256(repr(parts).encode()).hexdigest()[:40]

    def path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.aot")

    def entries(self) -> list:
        try:
            return sorted(f for f in os.listdir(self.root) if f.endswith(".aot"))
        except OSError:
            return []

    def _count(self, name: str) -> None:
        self.stats[name] = self.stats.get(name, 0) + 1
        if _telemetry._ENABLED:
            _telemetry.inc(f"serving.aot.{name}")

    # ------------------------------------------------------------------ #
    # load / store                                                       #
    # ------------------------------------------------------------------ #
    def load(self, key: str) -> Optional[dict]:
        """The stored envelope for ``key``, or ``None`` (counted as
        ``miss``, ``corrupt`` — file removed best-effort — or
        ``version_mismatch``). Never raises."""
        sp = _tracing.start_span("aot.load", key=key) if _tracing._ENABLED else None
        outcome = "miss"
        try:
            path = self.path(key)
            if not os.path.exists(path):
                self._count("miss")
                return None
            t0 = time.perf_counter()
            try:
                with open(path, "rb") as f:
                    rec = pickle.load(f)
                if not isinstance(rec, dict) or "exported" not in rec or "meta" not in rec:
                    raise ValueError("malformed envelope")
            except Exception:
                outcome = "corrupt"
                self._count("corrupt")
                try:
                    os.remove(path)
                except OSError:
                    pass
                return None
            stamps = _envelope_stamps()
            if {k: rec["meta"].get(k) for k in stamps} != stamps:
                # written by another jax/heat_tpu version, platform, world
                # size, or program-affecting gate roster: recompile (and
                # overwrite) rather than trust it
                outcome = "version_mismatch"
                self._count("version_mismatch")
                return None
            outcome = "hit"
            self._count("hit")
            if _telemetry._ENABLED:
                _telemetry.observe("serving.aot.load", time.perf_counter() - t0)
            return rec
        finally:
            _tracing.end_span(sp, outcome=outcome)

    def store(self, key: str, exported_bytes: bytes, out: Optional[dict],
              extra_meta: Optional[dict] = None) -> bool:
        """Atomically persist one envelope; never raises."""
        sp = _tracing.start_span(
            "aot.store", key=key, bytes=len(exported_bytes)
        ) if _tracing._ENABLED else None
        try:
            os.makedirs(self.root, exist_ok=True)
            meta = _envelope_stamps()
            if extra_meta:
                meta.update(extra_meta)
            rec = {"format": _FORMAT, "meta": meta, "exported": exported_bytes, "out": out}
            tmp = self.path(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(rec, f)
            os.replace(tmp, self.path(key))
            self._count("store")
            _tracing.end_span(sp, outcome="store")
            return True
        except Exception:
            self._count("bypass")
            _tracing.end_span(sp, outcome="bypass")
            return False


def _wrap_exported(exported, donate_positions: Tuple[int, ...]):
    """The dispatchable callable over a deserialized artifact. The
    ``jax.jit`` wrapper re-applies the caller's donation (buffer reuse
    must survive the round trip) and, where the backend has a persistent
    executable cache, compiles from disk."""
    if donate_positions:
        return jax.jit(exported.call, donate_argnums=tuple(donate_positions))  # shardlint: ignore[SL202] -- AOT load wrapper, private by construction
    return jax.jit(exported.call)  # shardlint: ignore[SL202] -- AOT load wrapper, private by construction


# ---------------------------------------------------------------------- #
# ht.jit hooks                                                           #
# ---------------------------------------------------------------------- #
class JitHooks:
    """The object ``core/jit.py`` consults on ht-level cache misses.
    Both methods are contractually non-raising: any failure means
    "behave as if the cache did not exist"."""

    def __init__(self, store: AOTStore):
        self.aot = store

    # -- key ----------------------------------------------------------- #
    def _key_parts(self, fn, treedef, specs, donate_user) -> Optional[tuple]:
        parts = [("htjit", _FORMAT), _fn_ident(fn), ("treedef", str(treedef))]
        for kind, spec in specs:
            if kind == "dnd":
                parts.append((
                    "dnd", tuple(spec.gshape), spec.dtype.__name__, spec.split,
                    str(spec.device), _comm_desc(spec.comm),
                ))
            elif kind in ("jax", "np"):
                parts.append((kind,) + tuple(spec))
            else:
                stable = _stable_static(spec)
                if stable is None:
                    return None
                parts.append(("static", stable))
        parts.append(("donate", tuple(donate_user)))
        return tuple(parts) + _key_stamps()

    def _rebuild_context(self, specs):
        """device/comm for rebuilding output DNDarrays in the loading
        process — taken from the first DNDarray input (outputs live on
        the same mesh the inputs do)."""
        for kind, spec in specs:
            if kind == "dnd":
                return spec.device, spec.comm
        return None

    # -- load ---------------------------------------------------------- #
    def load(self, fn, treedef, specs, donate_user, donate_positions, jit_kwargs):
        try:
            if jit_kwargs:
                self.aot._count("bypass")
                return None
            parts = self._key_parts(fn, treedef, specs, donate_user)
            if parts is None:
                self.aot._count("bypass")
                return None
            rec = self.aot.load(self.aot.key(parts))
            if rec is None:
                return None
            from jax import export as _export

            exported = _export.deserialize(rec["exported"])
            if jax.default_backend() not in exported.platforms:
                self.aot._count("bypass")
                return None
            out = rec["out"]
            out_meta = []
            ctx = self._rebuild_context(specs)
            from ..core import types as _types

            for desc in out["meta"]:
                if desc is None:
                    out_meta.append(None)
                    continue
                _tag, gshape, dtype_name, split = desc
                if ctx is None:
                    # a DNDarray output with no DNDarray input to borrow
                    # device/comm from — unreachable for stored entries
                    # (store() bypasses this shape), guarded for safety
                    self.aot._count("bypass")
                    return None
                device, comm = ctx
                out_meta.append(
                    _ht_jit._DndSpec.from_meta(
                        gshape, getattr(_types, dtype_name), split, device, comm
                    )
                )
            call = _wrap_exported(exported, donate_positions)
            return (call, [(out["treedef"], out_meta)])
        except Exception:
            self.aot._count("bypass")
            return None

    # -- store --------------------------------------------------------- #
    def store_entry_shape_ok(self, specs, out_meta) -> bool:
        if any(m is not None for m in out_meta):
            return self._rebuild_context(specs) is not None
        return True

    def store(self, fn, treedef, specs, donate_user, donate_positions,
              jit_kwargs, jitted, traced_in, out_box):
        try:
            if jit_kwargs or not out_box:
                self.aot._count("bypass")
                return
            parts = self._key_parts(fn, treedef, specs, donate_user)
            if parts is None:
                self.aot._count("bypass")
                return
            out_treedef, out_meta = out_box[-1]
            if not self.store_entry_shape_ok(specs, out_meta):
                self.aot._count("bypass")
                return
            out_desc = [
                None if m is None else ("dnd", tuple(m.gshape), m.dtype.__name__, m.split)
                for m in out_meta
            ]
            from jax import export as _export

            t0 = time.perf_counter()
            exported = _export.export(jitted)(*_input_sds(traced_in))
            blob = exported.serialize()
            if _telemetry._ENABLED:
                _telemetry.observe("serving.aot.export", time.perf_counter() - t0)
            self.aot.store(
                self.aot.key(parts), blob,
                {"treedef": out_treedef, "meta": out_desc},
                extra_meta={"kind": "htjit", "fn": _fn_ident(fn)[0]},
            )
        except Exception:
            self.aot._count("bypass")


# ---------------------------------------------------------------------- #
# generic program-level API (estimator endpoints, warmup)                #
# ---------------------------------------------------------------------- #
def ensure_program(key_parts: tuple, build, example_args: Sequence,
                   donate_argnums: Tuple[int, ...] = ()):
    """A compiled callable for the program identified by ``key_parts``.

    On a store hit the serialized artifact is deserialized (no tracing
    of ``build``'s function at all); on a miss ``build()`` supplies the
    jitted program, which is exported against ``example_args``'s
    avals/shardings and persisted for the next process. With the cache
    disabled this is exactly ``build()``.

    ``example_args`` may be concrete arrays or ``jax.ShapeDtypeStruct``s.
    Returns ``(callable, "hit"|"store"|"off"|"bypass")``.
    """
    store = active_store()
    if store is None:
        return build(), "off"
    sds_in = _input_sds(example_args)
    # donation and input avals/shardings are key material exactly as in
    # JitHooks._key_parts: a donating variant or a differently-sharded
    # endpoint must never be served the other's artifact
    key = store.key(
        (("program", _FORMAT),) + tuple(key_parts)
        + (("donate", tuple(donate_argnums)),)
        + tuple(
            ("in", tuple(s.shape), str(s.dtype), str(getattr(s, "sharding", None)))
            for s in sds_in
        )
        + _key_stamps()
    )
    rec = store.load(key)
    if rec is not None:
        try:
            from jax import export as _export

            exported = _export.deserialize(rec["exported"])
            if jax.default_backend() in exported.platforms:
                return _wrap_exported(exported, donate_argnums), "hit"
            store._count("bypass")
        except Exception:
            store._count("bypass")
    jitted = build()
    if donate_argnums:
        # symmetric with the loaded path: the fresh program donates the
        # same buffers the _wrap_exported wrapper would
        jitted = jax.jit(jitted, donate_argnums=tuple(donate_argnums))  # shardlint: ignore[SL202] -- donation wrapper over an already-built program
    try:
        from jax import export as _export

        t0 = time.perf_counter()
        exported = _export.export(jitted)(*sds_in)
        blob = exported.serialize()
        if _telemetry._ENABLED:
            _telemetry.observe("serving.aot.export", time.perf_counter() - t0)
        stored = store.store(key, blob, None, extra_meta={"kind": "program", "key": repr(key_parts)})
        return jitted, ("store" if stored else "bypass")
    except Exception:
        store._count("bypass")
        return jitted, "bypass"


# ---------------------------------------------------------------------- #
# configuration / installation                                           #
# ---------------------------------------------------------------------- #
_ACTIVE: Optional[AOTStore] = None


def active_store() -> Optional[AOTStore]:
    """The installed :class:`AOTStore`, or ``None`` when serving AOT is
    off (the escape-hatch state: ``core/jit.py`` hooks uninstalled)."""
    return _ACTIVE


def enabled() -> bool:
    return _ACTIVE is not None


_XLA_CACHE_WIRED = False
_XLA_CACHE_SAVED: Optional[tuple] = None


def _reset_xla_cache_binding() -> None:
    """jax binds its persistent-cache object on first use; re-point it
    after a config change (no-op on jax versions without the hook)."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def _wire_xla_cache(root: str) -> None:
    """Point jax's persistent compilation cache under the store root so
    XLA executables are reused across processes too (TPU/GPU; a no-op
    store on CPU backends without executable-cache support). Respects a
    user-set ``jax_compilation_cache_dir``; undone on disable."""
    global _XLA_CACHE_WIRED, _XLA_CACHE_SAVED
    try:
        if jax.config.jax_compilation_cache_dir is None:
            _XLA_CACHE_SAVED = (
                jax.config.jax_persistent_cache_min_compile_time_secs,
                jax.config.jax_persistent_cache_min_entry_size_bytes,
            )
            os.makedirs(os.path.join(root, "xla"), exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", os.path.join(root, "xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            _reset_xla_cache_binding()
            _XLA_CACHE_WIRED = True
    except Exception:
        pass  # older jax without these knobs: export layer still works


def _unwire_xla_cache() -> None:
    global _XLA_CACHE_WIRED, _XLA_CACHE_SAVED
    if not _XLA_CACHE_WIRED:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        if _XLA_CACHE_SAVED is not None:
            # the floors are global knobs a user may rely on later —
            # restore, don't leave every sub-second compile cacheable
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", _XLA_CACHE_SAVED[0]
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", _XLA_CACHE_SAVED[1]
            )
        _reset_xla_cache_binding()
    except Exception:
        pass
    _XLA_CACHE_WIRED = False
    _XLA_CACHE_SAVED = None


def configure(cache_dir_: Optional[str] = None, enable: bool = True) -> Optional[AOTStore]:
    """Programmatic switch: install (``enable=True``) or uninstall the
    AOT hooks. Returns the active store (or ``None``)."""
    global _ACTIVE
    if not enable:
        _ACTIVE = None
        _ht_jit.install_aot_hooks(None)
        _unwire_xla_cache()
        return None
    root = cache_dir_ or cache_dir()
    _ACTIVE = AOTStore(root)
    _ht_jit.install_aot_hooks(JitHooks(_ACTIVE))
    _wire_xla_cache(root)
    return _ACTIVE


def _auto_configure() -> None:
    """Import-time gate resolution (see module docstring). The default —
    no serving env set — leaves the hooks uninstalled: tier-1 and every
    non-serving process run the exact pre-serving code paths."""
    mode = _gates.get("HEAT_TPU_SERVING_AOT")
    if _env_falsy(mode):
        return
    if _env_truthy(mode) or (
        _gates.is_set("HEAT_TPU_SERVING_CACHE") and mode in (None, "", "auto")
    ):
        configure()


_auto_configure()
