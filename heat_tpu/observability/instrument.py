"""Hook helpers for instrumenting program caches and hot paths.

The op machinery compiles one XLA program per (op, shape, dtype, split)
configuration and memoizes it in ``functools.lru_cache``-wrapped
builders (``core/_operations.py``). Whether a dispatch hit that cache —
and how long a miss took to build and first-execute (the XLA compile) —
is exactly the signal a perf investigation needs first, so
``observed_program_cache`` wraps those builders:

- disabled telemetry: one bool check, then straight into the cached
  builder — the hot path stays a dict lookup;
- enabled: cache_info deltas classify hit vs miss; a miss records the
  builder wall time and returns a one-shot proxy that times the FIRST
  invocation of the program (where jax.jit actually traces + XLA
  compiles) under ``<name>.compile``.

The wrapper preserves ``cache_clear``/``cache_info`` so
``register_mesh_cache`` and tests keep working on the wrapped object.
"""

from __future__ import annotations

import functools
import time

from typing import Callable

from . import events as _events
from . import telemetry as _telemetry

__all__ = ["nbytes_of", "observed_program_cache"]


def nbytes_of(shape, dtype) -> int:
    """Static byte size of an array from metadata only (trace-safe: never
    touches the buffer)."""
    import numpy as np

    n = 1
    for s in shape:
        n *= int(s)
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        return n * 4


class _TimedFirstCall:
    """Proxy over a freshly built jitted program: the first call — where
    trace + XLA compile happen — is timed under ``<name>.compile``."""

    __slots__ = ("_name", "_prog")

    def __init__(self, name: str, prog: Callable):
        self._name = name
        self._prog = prog

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = self._prog(*args, **kwargs)
        dt = time.perf_counter() - t0
        _telemetry.observe(f"{self._name}.compile", dt)
        _events.emit("program_compile", cache=self._name, seconds=round(dt, 6))
        return out

    def __getattr__(self, attr):  # lower()/etc. pass through untimed
        return getattr(self._prog, attr)


def observed_program_cache(name: str):
    """Decorator for an ``functools.lru_cache``-wrapped program builder:
    counts ``<name>.hit`` / ``<name>.miss``, times the builder on a miss
    (``<name>.build``) and the program's first execution
    (``<name>.compile``). No-op passthrough while telemetry is off —
    programs built then are never retro-instrumented."""

    def deco(cached):
        @functools.wraps(cached)
        def wrapper(*args, **kwargs):
            if not _telemetry._ENABLED:
                return cached(*args, **kwargs)
            misses_before = cached.cache_info().misses
            t0 = time.perf_counter()
            prog = cached(*args, **kwargs)
            build_s = time.perf_counter() - t0
            if cached.cache_info().misses > misses_before:
                _telemetry.inc(f"{name}.miss")
                _telemetry.observe(f"{name}.build", build_s)
                return _TimedFirstCall(name, prog)
            _telemetry.inc(f"{name}.hit")
            return prog

        wrapper.cache_clear = cached.cache_clear
        wrapper.cache_info = cached.cache_info
        wrapper.__wrapped__ = cached
        return wrapper

    return deco
