"""Model-vs-measured attribution — closing the loop the tracer opens
(ISSUE 15).

Every analytic bench row ships a MODEL (the lattice's
``tier_time_model``, the overlap annotation's critical-path ratio, the
staging annotation's depth-2 PCIe bound) and waits for a MEASUREMENT to
judge it. This module performs the join: :func:`attribution` takes a
plan (or its ``plan_id``), finds the spans the tracer recorded for it,
groups measured wall time by step kind and tier, and reports per-leg
``model_error`` — signed relative error ``measured/model - 1`` — so
the first real-TPU round lands with its own diagnosis attached instead
of a bare wall-clock number.

Span semantics it relies on (see ``tracing``):

- spans tagged ``traced=True`` fired during program TRACING (the
  executor's per-lap probes): census material only — they are counted,
  never timed;
- untagged spans are real host wall time (staging windows, dispatcher
  batches, checkpoint slabs);
- spans tagged ``fenced=True`` bracket a fenced end-to-end execution
  (bench wraps its timed runs this way): they feed the ``execute`` leg,
  judged against the plan's modeled wall — the depth-2 critical path
  when the plan carries an overlap/staging annotation, the sequential
  tier sum otherwise.

Plan lookup: the executor and ``plan_staged_passes`` register every
schedule they touch in a small bounded registry here; the planner's
own schedule cache is the fallback.
"""

from __future__ import annotations

import collections
import threading

from typing import Any, Dict, List, Optional

from . import tracing as _tracing

__all__ = ["attribution", "last_reports", "register_plan", "serving_breakdown"]

_PLAN_CAP = 512
_REPORT_CAP = 64

_plan_lock = threading.Lock()
_plans: "collections.OrderedDict[str, Any]" = collections.OrderedDict()

_report_lock = threading.Lock()
_last_reports: "collections.OrderedDict[str, List[Dict[str, Any]]]" = (
    collections.OrderedDict()
)


def last_reports() -> Dict[str, List[Dict[str, Any]]]:
    """The most recent attribution legs per plan_id (bounded) — the
    source ``telemetry.prometheus_text`` renders its per-leg
    ``model_error`` gauges from."""
    with _report_lock:
        return {pid: [dict(l) for l in legs] for pid, legs in _last_reports.items()}

#: measured-leg tiers the tier model prices directly
_MODEL_TIERS = ("ici", "dcn", "pcie")


def register_plan(sched) -> None:
    """Remember a Schedule by plan_id so :func:`attribution` can find
    it later (bounded LRU — attribution is a diagnosis tool, not a
    plan store)."""
    with _plan_lock:
        _plans[sched.plan_id] = sched
        _plans.move_to_end(sched.plan_id)
        while len(_plans) > _PLAN_CAP:
            _plans.popitem(last=False)


def _lookup(plan_id: str):
    with _plan_lock:
        sched = _plans.get(plan_id)
    if sched is not None:
        return sched
    # fallback: the planner's schedule cache (explain()/plan() route
    # every redistribution plan through it)
    from ..redistribution import planner as _planner

    with _planner._plan_lock:
        for s in _planner._plan_cache.values():
            if s.plan_id == plan_id:
                return s
    raise KeyError(
        f"attribution: no Schedule known for plan_id {plan_id!r} — execute "
        "the plan (or call ht.redistribution.explain) with tracing enabled "
        "first, or pass the Schedule object itself"
    )


def _modeled_wall_s(sched, model: Dict[str, Any]) -> float:
    """The plan's modeled end-to-end wall: the depth-2 critical path
    when it carries a staging/overlap annotation (their documented
    convention), else the sequential tier sum."""
    if sched.staging:
        return float(sched.staging["model"]["critical_path_s"])
    total = float(model["total_s"])
    if sched.overlap:
        speedup = float(sched.overlap.get("model_speedup") or 1.0)
        if speedup > 0:
            return total / speedup
    return total


def _edge_bps(edges: Dict[str, Any], edge: str) -> Optional[float]:
    rec = edges.get(edge)
    if rec is None:
        return None
    bps = float(rec["bps"] if isinstance(rec, dict) else rec)
    return bps if bps > 0 else None


def _calibrated_wall_s(sched, cal_model: Dict[str, Any], edges: Dict[str, Any]) -> float:
    """:func:`_modeled_wall_s` under measured prices. A staged plan's
    wall is the depth-2 critical path rebuilt from the calibrated
    pcie/hbm legs (same ``max + min/n`` arithmetic the staging
    annotation pins); everything else follows the constants-column
    convention on the calibrated tier sums."""
    if sched.staging:
        from ..core import tiers as _tiers

        pcie_total = sched.tier_bytes().get("pcie", 0)
        n = max(int(sched.staging.get("n_windows", 1)), 1)
        pcie_bps = _edge_bps(edges, "pcie") or _tiers.bandwidth("pcie")
        hbm_bps = _edge_bps(edges, "hbm") or _tiers.bandwidth("hbm")
        pcie_s = pcie_total / pcie_bps
        hbm_s = pcie_total / hbm_bps
        return max(pcie_s, hbm_s) + min(pcie_s, hbm_s) / n
    total = float(cal_model["total_s"])
    if sched.overlap:
        speedup = float(sched.overlap.get("model_speedup") or 1.0)
        if speedup > 0:
            return total / speedup
    return total


def _resolve_calibration(sched, profile):
    """The (edges, profile_id) the CALIBRATED model column prices
    with, resolved nearest-first: an explicit ``profile=`` envelope,
    the plan's own recorded ``calibration`` annotation, then the
    ambient ``HEAT_TPU_LATTICE_PROFILE`` gate; ``(None, None)`` under
    plain constants (no calibrated column — the report stays
    byte-compatible with PR 15)."""
    if profile is not None:
        return dict(profile["edges"]), profile.get("profile_id")
    ann = getattr(sched, "calibration", None)
    if ann:
        return dict(ann["edges"]), ann.get("profile_id")
    from ..core import tiers as _tiers

    prof = _tiers.active_profile()
    if prof is not None:
        return dict(prof["edges"]), prof.get("profile_id")
    return None, None


def attribution(
    plan,
    span_rows: Optional[List[Dict[str, Any]]] = None,
    profile: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Join measured span times against a plan's own cost model.

    ``plan`` is a Schedule or a ``plan_id`` string; ``span_rows``
    overrides the live span buffer (post-hoc analysis of an exported
    snapshot). Returns::

        {
          "plan_id", "strategy",
          "model":   {ici/dcn[/pcie] bytes + seconds, "wall_s",
                      "calibrated"?},
          "census":  {span kind -> trace-time span count},
          "legs":    [{"step", "tier", "calls", "measured_s",
                       "model_s"?, "model_error"?,
                       "calibrated_model_s"?, "calibrated_error"?}, ...],
        }

    ``model_error`` is signed relative error ``measured/model - 1``
    (+0.30 = 30% slower than modeled). Legs without a priced model
    (compute windows, dispatch phases) report measured time only —
    attribution never invents a bound it cannot defend.

    ISSUE 16: when a lattice profile is in reach — the explicit
    ``profile=`` envelope, the plan's recorded ``calibration``
    annotation, or the ambient ``HEAT_TPU_LATTICE_PROFILE`` gate —
    every priced leg ALSO carries ``calibrated_model_s``/
    ``calibrated_error`` (the same join at the measured prices) and
    ``model["calibrated"]`` records that column's price set; the
    constants column is untouched, so the before/after pair is what
    :func:`~heat_tpu.observability.calibration.calibration_report`
    gates on. No profile anywhere -> the PR 15 report, byte-identical.
    """
    sched = _lookup(plan) if isinstance(plan, str) else plan
    from ..redistribution import planner as _planner

    model = dict(_planner.tier_time_model(sched))
    model["wall_s"] = round(_modeled_wall_s(sched, model), 9)
    if sched.staging:
        model["staging"] = dict(sched.staging["model"])
    cal_edges, cal_pid = _resolve_calibration(sched, profile)
    cal_model: Optional[Dict[str, Any]] = None
    if cal_edges:
        cal_model = dict(_planner.tier_time_model(sched, edges=cal_edges))
        cal_model["wall_s"] = round(
            _calibrated_wall_s(sched, cal_model, cal_edges), 9
        )
        model["calibrated"] = {
            "profile_id": cal_pid,
            **{
                k: round(float(v), 9)
                for k, v in cal_model.items()
                if k.endswith("_s")
            },
        }

    rows = _tracing.spans() if span_rows is None else list(span_rows)
    census: Dict[str, int] = {}
    measured: Dict[Any, Dict[str, Any]] = {}
    fenced: List[float] = []
    for r in rows:
        attrs = r.get("attrs") or {}
        if attrs.get("plan_id") != sched.plan_id:
            continue
        step = attrs.get("step") or r["name"]
        tier = attrs.get("tier")
        if attrs.get("traced"):
            key = f"{r['name']}" + (f":{tier}" if tier else "")
            census[key] = census.get(key, 0) + 1
            continue
        if r.get("dur_s") is None:
            continue
        ent = measured.setdefault(
            (step, tier), {"step": step, "tier": tier, "calls": 0, "total_s": 0.0}
        )
        ent["calls"] += 1
        ent["total_s"] += float(r["dur_s"])
        if attrs.get("fenced"):
            fenced.append(float(r["dur_s"]))

    legs: List[Dict[str, Any]] = []
    for (step, tier), ent in sorted(
        measured.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")
    ):
        leg = {
            "step": step,
            "tier": tier,
            "calls": ent["calls"],
            "measured_s": round(ent["total_s"], 9),
        }
        if step == "execute":
            leg["measured_s"] = round(min(fenced), 9) if fenced else leg["measured_s"]
            model_s = model["wall_s"]
            cal_s = cal_model["wall_s"] if cal_model else None
        else:
            model_s = model.get(f"{tier}_s") if tier in _MODEL_TIERS else None
            cal_s = (
                cal_model.get(f"{tier}_s")
                if cal_model and tier in _MODEL_TIERS
                else None
            )
        if model_s:
            leg["model_s"] = round(float(model_s), 9)
            leg["model_error"] = round(leg["measured_s"] / float(model_s) - 1.0, 4)
        if cal_s:
            leg["calibrated_model_s"] = round(float(cal_s), 9)
            leg["calibrated_error"] = round(
                leg["measured_s"] / float(cal_s) - 1.0, 4
            )
        legs.append(leg)

    report = {
        "plan_id": sched.plan_id,
        "strategy": sched.strategy,
        "model": model,
        "census": census,
        "legs": legs,
    }
    # remember the latest diagnosis per plan (bounded) so telemetry can
    # render the per-leg model_error gauges (ISSUE 16 satellite: the
    # exposition surface for a long-lived serving process)
    with _report_lock:
        _last_reports[sched.plan_id] = legs
        _last_reports.move_to_end(sched.plan_id)
        while len(_last_reports) > _REPORT_CAP:
            _last_reports.popitem(last=False)
    return report


def serving_breakdown(
    span_rows: Optional[List[Dict[str, Any]]] = None
) -> Dict[str, Any]:
    """Where serving time went, per lifecycle phase: p50/p95/p99 and
    totals over the dispatcher's ``serving.*`` spans (submit, queue,
    dispatch, fence, resolve, request, batch). Measured-only — the
    serving path has no single analytic bound to judge against; the
    bench's ``serving_qps`` row records this as its attribution
    detail."""
    from . import telemetry as _telemetry

    rows = _tracing.spans() if span_rows is None else list(span_rows)
    phases: Dict[str, List[float]] = {}
    for r in rows:
        name = r["name"]
        if not name.startswith("serving.") or r.get("dur_s") is None:
            continue
        phases.setdefault(name, []).append(float(r["dur_s"]))
    out: Dict[str, Any] = {}
    for name in sorted(phases):
        samples = sorted(phases[name])
        out[name] = {
            "calls": len(samples),
            "total_s": round(sum(samples), 9),
            "p50_s": round(_telemetry._percentile(samples, 0.50), 9),
            "p95_s": round(_telemetry._percentile(samples, 0.95), 9),
            "p99_s": round(_telemetry._percentile(samples, 0.99), 9),
        }
    return out
