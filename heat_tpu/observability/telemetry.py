"""Structured runtime metrics core.

The reference instruments its continuous benchmarks EXTERNALLY (perun
``@monitor()`` decorators around benchmark scripts, HeAT paper 2007.13552);
the library itself cannot answer "how many collectives did this op launch,
how many bytes did that reshard move, did the program cache hit?" — even
though redistribution cost is exactly what dominates at scale (2112.01075).
This module is the first-party answer: a process-wide registry of

- **counters** (monotonic ints: cache hits/misses, reshard calls, bytes
  accounted via the ``*.bytes`` convention),
- **timers** (count / total / min / max plus a bounded sample reservoir
  for p50/p95),

fed by hook points in the hot layers (``core/_operations.py``,
``core/communication.py``, ``core/dndarray.py``, ``core/jit.py``) and by
the ``record()`` context manager for user-scoped blocks.

Design constraints, in order:

1. **Zero-cost when disabled.** Every hook gates on the module-level
   ``_ENABLED`` bool (one attribute read); no allocation, no lock, no
   string formatting happens on the disabled path. The default is
   disabled; ``HEAT_TPU_TELEMETRY=1`` in the environment enables at
   import, ``enable()``/``disable()`` switch at runtime.
2. **Trace-safe.** Hooks record only host-side Python values — shapes,
   splits, dtypes, wall times — never array *values*, so they are safe
   to hit inside a ``jax.jit``/``ht.jit`` trace (they then fire once per
   compile, not per execution; events carry a ``traced`` field where the
   distinction matters).
3. **Thread-safe AND contention-free under concurrent recorders.** The
   registry is SHARDED per recording thread (ISSUE 9: the serving
   dispatcher records request latencies from its worker while client
   threads bump submit counters): ``inc``/``observe`` touch only the
   calling thread's shard under that shard's own lock — uncontended in
   steady state, so recorders never serialize on one global lock — and
   readers (``snapshot``/``timer_table``) merge the shards. Counter and
   call totals are exact under any interleaving; the p50/p95 sample
   reservoir is bounded PER SHARD (``_SAMPLE_CAP`` each), and dead
   threads' shards fold into one retired accumulator when new threads
   register, so memory stays O(#metrics × #LIVE-recording-threads)
   even under request-handler thread churn.

Energy note (perun-parity deviation): this platform exposes no
in-container energy counter, so the registry records time/bytes/counts
only — see ``heat_tpu.utils.monitor`` for the TDP-envelope estimation
recipe.
"""

from __future__ import annotations

import collections
import contextlib
import json
import re
import threading
import time
import weakref

from typing import Any, Dict, Iterator, Optional

# stdlib-only sibling (the gate registry) — safe to import this early in
# process start, before jax or any heavy core module loads
from ..core import gates as _gates

__all__ = [
    "Registry",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "inc",
    "observe",
    "prometheus_text",
    "record",
    "report",
    "reset",
    "snapshot",
]

# reservoir size per timer: enough for stable p50/p95 on bench-scale call
# counts without unbounded growth on hot-loop instrumentation
_SAMPLE_CAP = 1024


def _env_truthy(value: Optional[str]) -> bool:
    return (value or "").strip().lower() in ("1", "true", "on", "yes")


def _percentile(sorted_samples, q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, max(0, int(round(q * (len(sorted_samples) - 1)))))
    return sorted_samples[idx]


class _Shard:
    """One recording thread's private accumulator. Only the owning thread
    mutates it (under ``lock``, uncontended unless a reader is merging),
    so concurrent recorders never touch each other's state. ``owner`` is
    a weakref to the recording thread: when the thread dies the registry
    folds the shard into its retired accumulator (exact totals survive,
    memory stays O(live threads), not threads-ever)."""

    __slots__ = ("lock", "counters", "timers", "owner")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.counters: Dict[str, int] = {}
        self.timers: Dict[str, dict] = {}
        self.owner = weakref.ref(threading.current_thread())


class Registry:
    """Counter + timer store, sharded per recording thread. The
    module-level singleton backs the public API; ``heat_tpu.utils.monitor``
    holds its own always-on instance (the decorator is explicit opt-in,
    independent of the global switch)."""

    def __init__(self) -> None:
        # guards the shard LIST only; per-shard data is guarded by the
        # shard's own lock (the hot path never takes this one after its
        # thread's first record). `_retired` absorbs the shards of dead
        # threads so totals stay exact while memory stays bounded by the
        # LIVE thread count under churn.
        self._lock = threading.Lock()
        self._shards: list = []
        self._retired = _Shard()
        self._tls = threading.local()

    def _shard(self) -> _Shard:
        sh = getattr(self._tls, "shard", None)
        if sh is None:
            sh = _Shard()
            self._tls.shard = sh
            with self._lock:
                self._prune_locked()
                self._shards.append(sh)
        return sh

    def _prune_locked(self) -> None:
        """Fold shards whose recording thread has exited into the
        retired accumulator (called under ``self._lock`` whenever a new
        thread registers — the only moment the shard list grows)."""
        live = []
        for sh in self._shards:
            owner = sh.owner()
            if owner is not None and owner.is_alive():
                live.append(sh)
            else:
                self._fold_retired(sh)
        self._shards = live

    def _fold_retired(self, sh: _Shard) -> None:
        with sh.lock:
            counters, timers = sh.counters, sh.timers
            sh.counters, sh.timers = {}, {}
        with self._retired.lock:
            for name, value in counters.items():
                self._retired.counters[name] = self._retired.counters.get(name, 0) + value
            for name, ent in timers.items():
                agg = self._retired.timers.get(name)
                if agg is None:
                    self._retired.timers[name] = ent
                else:
                    agg["calls"] += ent["calls"]
                    agg["total_s"] += ent["total_s"]
                    agg["min_s"] = min(agg["min_s"], ent["min_s"])
                    agg["max_s"] = max(agg["max_s"], ent["max_s"])
                    agg["samples"].extend(ent["samples"])  # maxlen caps it

    def _all_shards(self) -> list:
        with self._lock:
            return list(self._shards) + [self._retired]

    def inc(self, name: str, n: int = 1) -> None:
        sh = self._shard()
        with sh.lock:
            sh.counters[name] = sh.counters.get(name, 0) + int(n)

    def observe(self, name: str, seconds: float) -> None:
        seconds = float(seconds)
        sh = self._shard()
        with sh.lock:
            ent = sh.timers.get(name)
            if ent is None:
                ent = {
                    "calls": 0,
                    "total_s": 0.0,
                    "min_s": float("inf"),
                    "max_s": 0.0,
                    "samples": collections.deque(maxlen=_SAMPLE_CAP),
                }
                sh.timers[name] = ent
            ent["calls"] += 1
            ent["total_s"] += seconds
            ent["min_s"] = min(ent["min_s"], seconds)
            ent["max_s"] = max(ent["max_s"], seconds)
            ent["samples"].append(seconds)

    def clear(self) -> None:
        for sh in self._all_shards():
            with sh.lock:
                sh.counters.clear()
                sh.timers.clear()

    def counters(self) -> Dict[str, int]:
        merged: Dict[str, int] = {}
        for sh in self._all_shards():
            with sh.lock:
                items = list(sh.counters.items())
            for name, value in items:
                merged[name] = merged.get(name, 0) + value
        return merged

    def timer_table(self) -> Dict[str, Dict[str, float]]:
        """{name: {calls, total_s, best_s, mean_s, max_s, p50_s, p95_s,
        p99_s}}.

        Merged across thread shards: calls/totals are exact sums,
        min/max exact aggregates, and the percentiles come from the
        union of the per-shard sample reservoirs (each bounded by
        ``_SAMPLE_CAP``)."""
        merged: Dict[str, dict] = {}
        for sh in self._all_shards():
            with sh.lock:
                items = [(k, dict(v), list(v["samples"])) for k, v in sh.timers.items()]
            for name, ent, samples in items:
                agg = merged.get(name)
                if agg is None:
                    agg = {
                        "calls": 0, "total_s": 0.0,
                        "min_s": float("inf"), "max_s": 0.0, "samples": [],
                    }
                    merged[name] = agg
                agg["calls"] += ent["calls"]
                agg["total_s"] += ent["total_s"]
                agg["min_s"] = min(agg["min_s"], ent["min_s"])
                agg["max_s"] = max(agg["max_s"], ent["max_s"])
                agg["samples"].extend(samples)
        table = {}
        for name, agg in merged.items():
            calls = agg["calls"]
            samples = sorted(agg["samples"])
            table[name] = {
                "calls": calls,
                "total_s": agg["total_s"],
                "best_s": agg["min_s"] if calls else 0.0,
                "mean_s": agg["total_s"] / calls if calls else 0.0,
                "max_s": agg["max_s"],
                "p50_s": _percentile(samples, 0.50),
                "p95_s": _percentile(samples, 0.95),
                "p99_s": _percentile(samples, 0.99),
            }
        return table

    def snapshot(self) -> Dict[str, Any]:
        return {"counters": self.counters(), "timers": self.timer_table()}


# ------------------------------------------------------------------ #
# module-level singleton + enable switch                             #
# ------------------------------------------------------------------ #
_REGISTRY = Registry()

# hooks read this attribute directly (one dict lookup + attribute read):
# the whole disabled-path cost of the instrumentation
_ENABLED: bool = _env_truthy(_gates.get("HEAT_TPU_TELEMETRY"))

# record() nesting is per thread: names join with '/'
_NESTING = threading.local()


def enable() -> None:
    """Turn telemetry collection on (also via ``HEAT_TPU_TELEMETRY=1``).
    Span tracing at its default ``HEAT_TPU_TRACE=auto`` follows this
    switch (an explicit ``0``/``1`` pins it independently)."""
    global _ENABLED
    _ENABLED = True
    from . import tracing as _tracing

    _tracing._on_telemetry_switch(True)


def disable() -> None:
    """Turn telemetry collection off. Collected data is kept until
    ``reset()``."""
    global _ENABLED
    _ENABLED = False
    from . import tracing as _tracing

    _tracing._on_telemetry_switch(False)


def enabled() -> bool:
    return _ENABLED


def inc(name: str, n: int = 1) -> None:
    """Increment counter ``name`` by ``n`` (no-op when disabled). Byte
    accounting uses the same mechanism under a ``<name>.bytes`` key."""
    if _ENABLED:
        _REGISTRY.inc(name, n)


def observe(name: str, seconds: float) -> None:
    """Record one duration sample for timer ``name`` (no-op when
    disabled)."""
    if _ENABLED:
        _REGISTRY.observe(name, seconds)


@contextlib.contextmanager
def record(name: str, **fields) -> Iterator[None]:
    """Time the enclosed block under ``name`` and emit a structured event.

    Nested ``record`` blocks compose their names with ``/``::

        with ht.telemetry.record("ingest"):
            with ht.telemetry.record("load"):   # timer key "ingest/load"
                ...

    ``fields`` become attributes of the emitted event (host-side values
    only — the block may run jax work, the fields must not hold tracers).
    A no-op (plain passthrough) when telemetry is disabled.
    """
    if not _ENABLED:
        yield
        return
    stack = getattr(_NESTING, "stack", None)
    if stack is None:
        stack = _NESTING.stack = []
    qualified = "/".join(stack + [name])
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        _REGISTRY.observe(qualified, dt)
        from . import events as _events

        _events.emit("record", name=qualified, seconds=round(dt, 9), **fields)


def snapshot() -> Dict[str, Any]:
    """Point-in-time copy of all counters and timer statistics, plus
    the event ring's health metadata (``events.capacity/buffered/
    dropped`` — a non-zero ``dropped`` means the event buffer is a
    tail, not complete history)."""
    from . import events as _events

    snap = _REGISTRY.snapshot()
    snap["events"] = _events.meta()
    return snap


def report(as_json: bool = False) -> Any:
    """Snapshot of counters + timer stats (p50/p95 included); with
    ``as_json`` a JSON string."""
    snap = snapshot()
    return json.dumps(snap) if as_json else snap


def reset() -> None:
    """Clear all counters, timers and buffered events."""
    _REGISTRY.clear()
    from . import events as _events

    _events.clear()


_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str, suffix: str = "") -> str:
    return "heat_tpu_" + _PROM_SANITIZE.sub("_", name) + suffix


def _prom_num(v: float) -> str:
    # prometheus text format takes any Go-parseable float; plain repr of
    # a python int/float qualifies
    return repr(int(v)) if isinstance(v, bool) or v == int(v) else repr(float(v))


def prometheus_text() -> str:
    """Prometheus text-format exposition of the registry: every counter
    as a ``_total`` counter, every timer as a summary (``quantile``
    labels from the bounded reservoir plus ``_sum``/``_count``), the
    event ring's health, and — when the serving layer is loaded — one
    gauge set per live dispatcher (queue depth, request/batch/shed
    tallies, latency quantiles) labeled by dispatcher name. Pure text,
    no HTTP: mount it behind whatever exposition endpoint the
    deployment already runs (``scripts/metrics_dump.py`` is the CLI
    form)."""
    snap = _REGISTRY.snapshot()
    lines = []
    for name, value in sorted(snap["counters"].items()):
        m = _prom_name(name, "_total")
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_prom_num(value)}")
    for name, st in sorted(snap["timers"].items()):
        m = _prom_name(name, "_seconds")
        lines.append(f"# TYPE {m} summary")
        for q, key in (("0.5", "p50_s"), ("0.95", "p95_s"), ("0.99", "p99_s")):
            lines.append(f'{m}{{quantile="{q}"}} {_prom_num(st[key])}')
        lines.append(f"{m}_sum {_prom_num(st['total_s'])}")
        lines.append(f"{m}_count {_prom_num(st['calls'])}")
    from . import events as _events

    emeta = _events.meta()
    lines.append("# TYPE heat_tpu_events_dropped_total counter")
    lines.append(f"heat_tpu_events_dropped_total {emeta['dropped']}")
    lines.append("# TYPE heat_tpu_events_buffered gauge")
    lines.append(f"heat_tpu_events_buffered {emeta['buffered']}")
    # flight-recorder health (ISSUE 16 satellite): spans already export
    # their drop count via events; the always-on flight ring gets the
    # same treatment so a scraped process shows when its post-mortem
    # tail stopped being complete
    from . import tracing as _tracing

    lines.append("# TYPE heat_tpu_flight_dropped_total counter")
    lines.append(f"heat_tpu_flight_dropped_total {_tracing.flight_dropped()}")
    # per-leg model_error gauges (ISSUE 16 satellite): the latest
    # attribution diagnosis per plan, labeled by plan/step/tier —
    # signed relative error, so a fleet dashboard can watch the cost
    # model drift per deployment (the calibration loop's live signal).
    # The package attr `attribution` is the FUNCTION; the module comes
    # via importlib (same convention as bench.py)
    import importlib

    _attribution = importlib.import_module("heat_tpu.observability.attribution")
    reports = _attribution.last_reports()
    if reports:
        err_rows = []
        for pid, legs in sorted(reports.items()):
            for leg in legs:
                if "model_error" not in leg:
                    continue
                err_rows.append(
                    (pid, leg["step"], leg.get("tier") or "", leg["model_error"],
                     leg.get("calibrated_error"))
                )
        if err_rows:
            lines.append("# TYPE heat_tpu_attribution_model_error gauge")
            for pid, step, tier, err, _cal in err_rows:
                lines.append(
                    'heat_tpu_attribution_model_error{plan_id="%s",step="%s",tier="%s"} %s'
                    % (pid, step, tier, _prom_num(err))
                )
            if any(c is not None for *_x, c in err_rows):
                lines.append("# TYPE heat_tpu_attribution_calibrated_error gauge")
                for pid, step, tier, _err, cal in err_rows:
                    if cal is None:
                        continue
                    lines.append(
                        'heat_tpu_attribution_calibrated_error{plan_id="%s",step="%s",tier="%s"} %s'
                        % (pid, step, tier, _prom_num(cal))
                    )
    # live dispatcher gauges — only when the serving layer is already
    # loaded (never import jax into a light metrics process)
    import sys

    disp_mod = sys.modules.get("heat_tpu.serving.dispatcher")
    if disp_mod is not None:
        rows = [
            (_PROM_SANITIZE.sub("_", d.name), d.stats())
            for d in disp_mod.live_dispatchers()
        ]
        if rows:
            # all samples of one metric grouped under its TYPE line
            for g in (
                "requests", "batches", "rejected", "shed", "rows",
                "padded_rows", "queue_depth_max",
            ):
                lines.append(f"# TYPE heat_tpu_serving_{g} gauge")
                for name, stats in rows:
                    lines.append(
                        'heat_tpu_serving_%s{dispatcher="%s"} %s'
                        % (g, name, _prom_num(stats[g]))
                    )
            lines.append("# TYPE heat_tpu_serving_latency_seconds summary")
            for name, stats in rows:
                for q, key in (("0.5", "p50_s"), ("0.95", "p95_s"), ("0.99", "p99_s")):
                    lines.append(
                        'heat_tpu_serving_latency_seconds{dispatcher="%s",quantile="%s"} %s'
                        % (name, q, _prom_num(stats[key]))
                    )
    return "\n".join(lines) + "\n"


def export_jsonl(path: str) -> int:
    """Write the registry + event buffer as JSON lines (one object per
    counter/timer/event) to ``path``; returns the number of lines."""
    snap = snapshot()
    from . import events as _events

    lines = []
    for name, value in sorted(snap["counters"].items()):
        lines.append({"kind": "counter", "name": name, "value": value})
    for name, stats in sorted(snap["timers"].items()):
        lines.append({"kind": "timer", "name": name, **stats})
    for ev in _events.snapshot():
        lines.append({"kind": "event", **ev})
    with open(path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    return len(lines)
