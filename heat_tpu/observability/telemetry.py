"""Structured runtime metrics core.

The reference instruments its continuous benchmarks EXTERNALLY (perun
``@monitor()`` decorators around benchmark scripts, HeAT paper 2007.13552);
the library itself cannot answer "how many collectives did this op launch,
how many bytes did that reshard move, did the program cache hit?" — even
though redistribution cost is exactly what dominates at scale (2112.01075).
This module is the first-party answer: a process-wide registry of

- **counters** (monotonic ints: cache hits/misses, reshard calls, bytes
  accounted via the ``*.bytes`` convention),
- **timers** (count / total / min / max plus a bounded sample reservoir
  for p50/p95),

fed by hook points in the hot layers (``core/_operations.py``,
``core/communication.py``, ``core/dndarray.py``, ``core/jit.py``) and by
the ``record()`` context manager for user-scoped blocks.

Design constraints, in order:

1. **Zero-cost when disabled.** Every hook gates on the module-level
   ``_ENABLED`` bool (one attribute read); no allocation, no lock, no
   string formatting happens on the disabled path. The default is
   disabled; ``HEAT_TPU_TELEMETRY=1`` in the environment enables at
   import, ``enable()``/``disable()`` switch at runtime.
2. **Trace-safe.** Hooks record only host-side Python values — shapes,
   splits, dtypes, wall times — never array *values*, so they are safe
   to hit inside a ``jax.jit``/``ht.jit`` trace (they then fire once per
   compile, not per execution; events carry a ``traced`` field where the
   distinction matters).
3. **Thread-safe AND contention-free under concurrent recorders.** The
   registry is SHARDED per recording thread (ISSUE 9: the serving
   dispatcher records request latencies from its worker while client
   threads bump submit counters): ``inc``/``observe`` touch only the
   calling thread's shard under that shard's own lock — uncontended in
   steady state, so recorders never serialize on one global lock — and
   readers (``snapshot``/``timer_table``) merge the shards. Counter and
   call totals are exact under any interleaving; the p50/p95 sample
   reservoir is bounded PER SHARD (``_SAMPLE_CAP`` each), and dead
   threads' shards fold into one retired accumulator when new threads
   register, so memory stays O(#metrics × #LIVE-recording-threads)
   even under request-handler thread churn.

Energy note (perun-parity deviation): this platform exposes no
in-container energy counter, so the registry records time/bytes/counts
only — see ``heat_tpu.utils.monitor`` for the TDP-envelope estimation
recipe.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
import weakref

from typing import Any, Dict, Iterator, Optional

# stdlib-only sibling (the gate registry) — safe to import this early in
# process start, before jax or any heavy core module loads
from ..core import gates as _gates

__all__ = [
    "Registry",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "inc",
    "observe",
    "record",
    "report",
    "reset",
    "snapshot",
]

# reservoir size per timer: enough for stable p50/p95 on bench-scale call
# counts without unbounded growth on hot-loop instrumentation
_SAMPLE_CAP = 1024


def _env_truthy(value: Optional[str]) -> bool:
    return (value or "").strip().lower() in ("1", "true", "on", "yes")


def _percentile(sorted_samples, q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, max(0, int(round(q * (len(sorted_samples) - 1)))))
    return sorted_samples[idx]


class _Shard:
    """One recording thread's private accumulator. Only the owning thread
    mutates it (under ``lock``, uncontended unless a reader is merging),
    so concurrent recorders never touch each other's state. ``owner`` is
    a weakref to the recording thread: when the thread dies the registry
    folds the shard into its retired accumulator (exact totals survive,
    memory stays O(live threads), not threads-ever)."""

    __slots__ = ("lock", "counters", "timers", "owner")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.counters: Dict[str, int] = {}
        self.timers: Dict[str, dict] = {}
        self.owner = weakref.ref(threading.current_thread())


class Registry:
    """Counter + timer store, sharded per recording thread. The
    module-level singleton backs the public API; ``heat_tpu.utils.monitor``
    holds its own always-on instance (the decorator is explicit opt-in,
    independent of the global switch)."""

    def __init__(self) -> None:
        # guards the shard LIST only; per-shard data is guarded by the
        # shard's own lock (the hot path never takes this one after its
        # thread's first record). `_retired` absorbs the shards of dead
        # threads so totals stay exact while memory stays bounded by the
        # LIVE thread count under churn.
        self._lock = threading.Lock()
        self._shards: list = []
        self._retired = _Shard()
        self._tls = threading.local()

    def _shard(self) -> _Shard:
        sh = getattr(self._tls, "shard", None)
        if sh is None:
            sh = _Shard()
            self._tls.shard = sh
            with self._lock:
                self._prune_locked()
                self._shards.append(sh)
        return sh

    def _prune_locked(self) -> None:
        """Fold shards whose recording thread has exited into the
        retired accumulator (called under ``self._lock`` whenever a new
        thread registers — the only moment the shard list grows)."""
        live = []
        for sh in self._shards:
            owner = sh.owner()
            if owner is not None and owner.is_alive():
                live.append(sh)
            else:
                self._fold_retired(sh)
        self._shards = live

    def _fold_retired(self, sh: _Shard) -> None:
        with sh.lock:
            counters, timers = sh.counters, sh.timers
            sh.counters, sh.timers = {}, {}
        with self._retired.lock:
            for name, value in counters.items():
                self._retired.counters[name] = self._retired.counters.get(name, 0) + value
            for name, ent in timers.items():
                agg = self._retired.timers.get(name)
                if agg is None:
                    self._retired.timers[name] = ent
                else:
                    agg["calls"] += ent["calls"]
                    agg["total_s"] += ent["total_s"]
                    agg["min_s"] = min(agg["min_s"], ent["min_s"])
                    agg["max_s"] = max(agg["max_s"], ent["max_s"])
                    agg["samples"].extend(ent["samples"])  # maxlen caps it

    def _all_shards(self) -> list:
        with self._lock:
            return list(self._shards) + [self._retired]

    def inc(self, name: str, n: int = 1) -> None:
        sh = self._shard()
        with sh.lock:
            sh.counters[name] = sh.counters.get(name, 0) + int(n)

    def observe(self, name: str, seconds: float) -> None:
        seconds = float(seconds)
        sh = self._shard()
        with sh.lock:
            ent = sh.timers.get(name)
            if ent is None:
                ent = {
                    "calls": 0,
                    "total_s": 0.0,
                    "min_s": float("inf"),
                    "max_s": 0.0,
                    "samples": collections.deque(maxlen=_SAMPLE_CAP),
                }
                sh.timers[name] = ent
            ent["calls"] += 1
            ent["total_s"] += seconds
            ent["min_s"] = min(ent["min_s"], seconds)
            ent["max_s"] = max(ent["max_s"], seconds)
            ent["samples"].append(seconds)

    def clear(self) -> None:
        for sh in self._all_shards():
            with sh.lock:
                sh.counters.clear()
                sh.timers.clear()

    def counters(self) -> Dict[str, int]:
        merged: Dict[str, int] = {}
        for sh in self._all_shards():
            with sh.lock:
                items = list(sh.counters.items())
            for name, value in items:
                merged[name] = merged.get(name, 0) + value
        return merged

    def timer_table(self) -> Dict[str, Dict[str, float]]:
        """{name: {calls, total_s, best_s, mean_s, max_s, p50_s, p95_s}}.

        Merged across thread shards: calls/totals are exact sums,
        min/max exact aggregates, and p50/p95 come from the union of the
        per-shard sample reservoirs (each bounded by ``_SAMPLE_CAP``)."""
        merged: Dict[str, dict] = {}
        for sh in self._all_shards():
            with sh.lock:
                items = [(k, dict(v), list(v["samples"])) for k, v in sh.timers.items()]
            for name, ent, samples in items:
                agg = merged.get(name)
                if agg is None:
                    agg = {
                        "calls": 0, "total_s": 0.0,
                        "min_s": float("inf"), "max_s": 0.0, "samples": [],
                    }
                    merged[name] = agg
                agg["calls"] += ent["calls"]
                agg["total_s"] += ent["total_s"]
                agg["min_s"] = min(agg["min_s"], ent["min_s"])
                agg["max_s"] = max(agg["max_s"], ent["max_s"])
                agg["samples"].extend(samples)
        table = {}
        for name, agg in merged.items():
            calls = agg["calls"]
            samples = sorted(agg["samples"])
            table[name] = {
                "calls": calls,
                "total_s": agg["total_s"],
                "best_s": agg["min_s"] if calls else 0.0,
                "mean_s": agg["total_s"] / calls if calls else 0.0,
                "max_s": agg["max_s"],
                "p50_s": _percentile(samples, 0.50),
                "p95_s": _percentile(samples, 0.95),
            }
        return table

    def snapshot(self) -> Dict[str, Any]:
        return {"counters": self.counters(), "timers": self.timer_table()}


# ------------------------------------------------------------------ #
# module-level singleton + enable switch                             #
# ------------------------------------------------------------------ #
_REGISTRY = Registry()

# hooks read this attribute directly (one dict lookup + attribute read):
# the whole disabled-path cost of the instrumentation
_ENABLED: bool = _env_truthy(_gates.get("HEAT_TPU_TELEMETRY"))

# record() nesting is per thread: names join with '/'
_NESTING = threading.local()


def enable() -> None:
    """Turn telemetry collection on (also via ``HEAT_TPU_TELEMETRY=1``)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    """Turn telemetry collection off. Collected data is kept until
    ``reset()``."""
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def inc(name: str, n: int = 1) -> None:
    """Increment counter ``name`` by ``n`` (no-op when disabled). Byte
    accounting uses the same mechanism under a ``<name>.bytes`` key."""
    if _ENABLED:
        _REGISTRY.inc(name, n)


def observe(name: str, seconds: float) -> None:
    """Record one duration sample for timer ``name`` (no-op when
    disabled)."""
    if _ENABLED:
        _REGISTRY.observe(name, seconds)


@contextlib.contextmanager
def record(name: str, **fields) -> Iterator[None]:
    """Time the enclosed block under ``name`` and emit a structured event.

    Nested ``record`` blocks compose their names with ``/``::

        with ht.telemetry.record("ingest"):
            with ht.telemetry.record("load"):   # timer key "ingest/load"
                ...

    ``fields`` become attributes of the emitted event (host-side values
    only — the block may run jax work, the fields must not hold tracers).
    A no-op (plain passthrough) when telemetry is disabled.
    """
    if not _ENABLED:
        yield
        return
    stack = getattr(_NESTING, "stack", None)
    if stack is None:
        stack = _NESTING.stack = []
    qualified = "/".join(stack + [name])
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        _REGISTRY.observe(qualified, dt)
        from . import events as _events

        _events.emit("record", name=qualified, seconds=round(dt, 9), **fields)


def snapshot() -> Dict[str, Any]:
    """Point-in-time copy of all counters and timer statistics."""
    return _REGISTRY.snapshot()


def report(as_json: bool = False) -> Any:
    """Snapshot of counters + timer stats (p50/p95 included); with
    ``as_json`` a JSON string."""
    snap = snapshot()
    return json.dumps(snap) if as_json else snap


def reset() -> None:
    """Clear all counters, timers and buffered events."""
    _REGISTRY.clear()
    from . import events as _events

    _events.clear()


def export_jsonl(path: str) -> int:
    """Write the registry + event buffer as JSON lines (one object per
    counter/timer/event) to ``path``; returns the number of lines."""
    snap = snapshot()
    from . import events as _events

    lines = []
    for name, value in sorted(snap["counters"].items()):
        lines.append({"kind": "counter", "name": name, "value": value})
    for name, stats in sorted(snap["timers"].items()):
        lines.append({"kind": "timer", "name": name, **stats})
    for ev in _events.snapshot():
        lines.append({"kind": "event", **ev})
    with open(path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    return len(lines)
