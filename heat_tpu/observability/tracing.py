"""Span tracing + the always-on flight recorder (ISSUE 15).

PR 1's counters/timers answer *how many* and *how long in aggregate*;
nothing in the stack can answer "which lap, which tier, which window"
— yet every modeled speedup in the TPU verdict backlog (overlap
1.54–1.60x, wire 0.251x, two-tier 7.5x, staging PCIe bounds) is a claim
about exactly that per-step structure. This module is the instrument:

- **spans** — a structured, parented trace of the hot layers at their
  existing seams (``span()`` context manager + a low-overhead
  ``start_span``/``end_span``/``add_span`` API). Spans carry HOST-SIDE
  attrs only (plan_id, step kind, tier, lap/window index, bucket,
  bytes, world epoch — never array values), so they are trace-safe:
  a span inside a jitted program body fires once per compile and is
  tagged ``traced=True`` (its duration is tracing time; attribution
  uses it for census only).
- **flight recorder** — a small ALWAYS-ON fixed-field ring, independent
  of the trace gate and of telemetry: one bool check + one bounded
  append per record. Its tail is attached to ``WorldChangedError``,
  dispatcher shed events, and chaos kills, so a post-mortem starts with
  the last N things the process actually did.
- **Chrome-trace export** — :func:`export_trace` emits
  trace-event-format JSON (per-thread tracks, ``plan_id``-correlated
  async spans) loadable in Perfetto/chrome://tracing and alignable with
  ``jax.profiler`` device traces via the ``redist_plan_<id>``
  named-scope stamps the executor already emits into HLO metadata.

Gate: ``HEAT_TPU_TRACE`` (declared in ``core/gates.py`` with
``affects_programs=False``) — ``0`` is the hard-off zero-overhead
escape hatch (every probe is one module-bool read), ``1`` forces
collection, ``auto`` (default) follows the telemetry switch
(``HEAT_TPU_TELEMETRY=1`` / ``ht.telemetry.enable()`` turn tracing on
too). The gate changes WHAT IS OBSERVED, never what runs: plans,
plan_ids, programs, and AOT envelope keys are byte-identical at every
value — pinned in tier-1 and diffed in the ci.sh parity leg.

Thread-safety: the span ring and the flight ring each sit behind one
module lock (bounded appends — recorders never block on readers for
long); the active-span stack and ambient-attribute context are
per-thread (``threading.local``), so concurrent recorders never see
each other's parents.

Stdlib-only on purpose (like ``core/gates``): importable before jax
loads, usable from the lightest CLI process.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..core import gates as _gates

__all__ = [
    "TRACE_ENV",
    "Span",
    "add_span",
    "capacity",
    "clear",
    "context",
    "current_span_id",
    "disable",
    "dropped",
    "enable",
    "enabled",
    "end_span",
    "export_trace",
    "flight_capacity",
    "flight_clear",
    "flight_dropped",
    "flight_record",
    "flight_tail",
    "span",
    "spans",
    "start_span",
    "trace_mode",
]

TRACE_ENV = "HEAT_TPU_TRACE"

#: span ring capacity — big enough for a bench row's full lifecycle
#: (every lap/window/batch span of a multi-GB plan execution), bounded
#: so instrumenting a serving hot loop cannot grow memory; overwrites
#: are counted in :func:`dropped` (never silently).
_SPAN_CAP = 16384

#: flight-recorder ring: deliberately small — the point is the LAST N
#: records at the moment something died, not history.
_FLIGHT_CAP = 256

# same epoch convention as events.py: timestamps relative to process
# start, perf_counter domain
_T0 = time.perf_counter()


def trace_mode() -> str:
    """Resolved ``HEAT_TPU_TRACE`` mode (``"0"``/``"1"``/``"auto"``).
    ``0`` = hard off (the zero-overhead escape hatch), ``1`` = force
    collection, ``auto`` (default) = follow the telemetry switch."""
    v = (_gates.get(TRACE_ENV) or "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "true", "force", "yes"):
        return "1"
    return "auto"


def _initial_enabled() -> bool:
    mode = trace_mode()
    if mode == "1":
        return True
    if mode == "0":
        return False
    from . import telemetry as _telemetry

    return _telemetry._ENABLED


class Span:
    """One finished-or-active span. ``attrs`` are host-side values only
    (the trace-safety contract shared with telemetry/events)."""

    __slots__ = ("id", "parent", "name", "thread", "t0", "dur_s", "attrs")

    def __init__(self, id, parent, name, thread, t0, attrs):
        self.id = id
        self.parent = parent
        self.name = name
        self.thread = thread
        self.t0 = t0  # perf_counter domain
        self.dur_s = None  # set by end_span
        self.attrs = attrs

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "parent": self.parent,
            "name": self.name,
            "thread": self.thread,
            "t0_s": round(self.t0 - _T0, 9),
            "dur_s": self.dur_s,
            "attrs": {k: v for k, v in self.attrs.items() if v is not None},
        }

    def __repr__(self) -> str:
        return f"Span({self.id}, {self.name!r}, dur={self.dur_s}, {self.attrs})"


# hooks read this attribute directly — the whole disabled-path cost
_ENABLED: bool = _initial_enabled()

_lock = threading.Lock()
_spans: deque = deque(maxlen=_SPAN_CAP)
_seq = 0
_dropped = 0
_tls = threading.local()

# thread ident -> name, for the export's thread tracks (plain dict:
# single-key writes are GIL-atomic, and a stale name is cosmetic)
_thread_names: Dict[int, str] = {}

_flight_lock = threading.Lock()
_flight: deque = deque(maxlen=_FLIGHT_CAP)
_flight_seq = 0
_flight_dropped = 0


def enable() -> None:
    """Turn span collection on (also via ``HEAT_TPU_TRACE=1``, or
    ``auto`` + the telemetry switch)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    """Turn span collection off. Collected spans are kept until
    :func:`clear`."""
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def _on_telemetry_switch(on: bool) -> None:
    """``telemetry.enable()``/``disable()`` notify here: under the
    default ``auto`` mode, tracing follows the telemetry switch; an
    explicit ``0``/``1`` pins it regardless."""
    global _ENABLED
    if trace_mode() == "auto":
        _ENABLED = bool(on)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _ambient() -> list:
    amb = getattr(_tls, "ambient", None)
    if amb is None:
        amb = _tls.ambient = []
    return amb


def start_span(
    name: str, parent_id: Optional[int] = None, detached: bool = False, **attrs
) -> Optional[Span]:
    """Open a span; returns the token :func:`end_span` closes (``None``
    when tracing is disabled — ``end_span(None)`` is a no-op, so probes
    need no branch). ``parent_id`` overrides the ambient parent (the
    innermost active span on this thread); ``detached=True`` keeps the
    span OFF the thread's active stack — the shape for lifecycles that
    outlive the opening call frame (a dispatcher batch: opened at
    dispatch, closed at resolve, with other spans in between)."""
    global _seq
    if not _ENABLED:
        return None
    th = threading.current_thread()
    ident = th.ident or 0
    if ident not in _thread_names:
        _thread_names[ident] = th.name
    stack = _stack()
    if parent_id is None and stack:
        parent_id = stack[-1].id
    merged: Dict[str, Any] = {}
    for d in _ambient():
        merged.update(d)
    merged.update(attrs)
    with _lock:
        _seq += 1
        sid = _seq
    sp = Span(sid, parent_id, name, ident, time.perf_counter(), merged)
    if not detached:
        stack.append(sp)
    return sp


def end_span(sp: Optional[Span], **attrs) -> None:
    """Close a span opened by :func:`start_span` and commit it to the
    ring. Extra ``attrs`` (an outcome learned at the end — status,
    bytes, error) merge over the opening attrs. Out-of-order closes are
    legal: the span is removed from the thread stack wherever it sits."""
    global _dropped
    if sp is None:
        return
    sp.dur_s = round(time.perf_counter() - sp.t0, 9)
    if attrs:
        sp.attrs.update(attrs)
    stack = getattr(_tls, "stack", None)
    if stack:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is sp:
                del stack[i]
                break
    with _lock:
        if len(_spans) == _SPAN_CAP:
            _dropped += 1
        _spans.append(sp)


@contextlib.contextmanager
def span(name: str, parent_id: Optional[int] = None, **attrs) -> Iterator[Optional[Span]]:
    """Context-manager form: a span around the enclosed block. A plain
    passthrough (one module-bool read) when tracing is disabled."""
    if not _ENABLED:
        yield None
        return
    sp = start_span(name, parent_id=parent_id, **attrs)
    try:
        yield sp
    finally:
        end_span(sp)


def add_span(
    name: str, t0: float, t1: float, parent_id: Optional[int] = None, **attrs
) -> None:
    """Record a span retroactively from two ``time.perf_counter()``
    readings — the low-overhead form for lifecycles whose start was a
    plain timestamp (a request's submit time): no token to carry, one
    call at the point the duration becomes known."""
    global _seq, _dropped
    if not _ENABLED:
        return
    th = threading.current_thread()
    ident = th.ident or 0
    if ident not in _thread_names:
        _thread_names[ident] = th.name
    stack = getattr(_tls, "stack", None)
    if parent_id is None and stack:
        parent_id = stack[-1].id
    merged: Dict[str, Any] = {}
    for d in _ambient():
        merged.update(d)
    merged.update(attrs)
    with _lock:
        _seq += 1
        sp = Span(_seq, parent_id, name, ident, float(t0), merged)
        sp.dur_s = round(float(t1) - float(t0), 9)
        if len(_spans) == _SPAN_CAP:
            _dropped += 1
        _spans.append(sp)


@contextlib.contextmanager
def context(**attrs) -> Iterator[None]:
    """Push ambient attributes for the enclosed block: every span this
    THREAD starts inside inherits them (its own attrs win on conflict).
    The executor wraps a plan execution in ``context(plan_id=...)`` so
    the per-lap probes — three call layers down — carry the plan id
    without threading it through every signature."""
    if not _ENABLED:
        yield
        return
    amb = _ambient()
    amb.append(attrs)
    try:
        yield
    finally:
        amb.pop()


def current_span_id() -> Optional[int]:
    """Id of the innermost active span on this thread (``None`` when
    no span is open) — what ``events.emit`` stamps into its optional
    ``span`` correlation field."""
    stack = getattr(_tls, "stack", None)
    return stack[-1].id if stack else None


def spans() -> List[Dict[str, Any]]:
    """Snapshot of the committed spans, oldest first, as dicts."""
    with _lock:
        return [sp.as_dict() for sp in _spans]


def clear() -> None:
    """Drop every committed span (active stacks are untouched) and
    reset the overwrite counter."""
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


def dropped() -> int:
    """Spans overwritten by ring wrap since the last :func:`clear` —
    a non-zero value means the snapshot is a TAIL, not a history."""
    with _lock:
        return _dropped


def capacity() -> int:
    return _SPAN_CAP


# --------------------------------------------------------------------- #
# probe factories — the hot-seam wrappers                               #
# --------------------------------------------------------------------- #
def lap_probes(
    issue: Callable, consume: Callable, attrs: Optional[Dict[str, Any]] = None
) -> Tuple[Callable, Callable]:
    """Wrap a ``_run_laps`` ``(issue, consume)`` pair with one span per
    lap call — the executor's depth-2 loops stay byte-identical (the
    SL405-checked skeleton is untouched; only the callables it drives
    are decorated). The wrapped calls run at TRACE time inside a jitted
    program body, so the spans fire once per compile and are tagged
    ``traced=True``: census material, not wall time."""
    base = dict(attrs or {})

    def traced_issue(k):
        with span("redist.issue", lap=int(k), traced=True, **base):
            return issue(k)

    def traced_consume(state, result, k):
        with span("redist.consume", lap=int(k), traced=True, **base):
            return consume(state, result, k)

    return traced_issue, traced_consume


def window_probes(
    put: Callable, consume: Callable, plan_id: Optional[str] = None
) -> Tuple[Callable, Callable]:
    """Wrap ``staging.stream_windows``' ``(device_put, consume)`` pair:
    one ``staging.stage_in`` span per window transfer (REAL host wall
    time — the PCIe leg attribution reads) and one ``staging.compute``
    span per window's consume call."""
    state = {"k": 0}

    def traced_put(host_block):
        w = state["k"]
        state["k"] += 1
        with span(
            "staging.stage_in",
            step="stage_in",
            tier="pcie",
            window=w,
            bytes=int(getattr(host_block, "nbytes", 0)),
            plan_id=plan_id,
        ):
            return put(host_block)

    def traced_consume(k, cur, win):
        with span(
            "staging.compute",
            step="compute",
            tier="hbm",
            window=int(k),
            plan_id=plan_id,
        ):
            return consume(k, cur, win)

    return traced_put, traced_consume


# --------------------------------------------------------------------- #
# the flight recorder                                                   #
# --------------------------------------------------------------------- #
# always-on by design (a post-mortem instrument that has to be switched
# on before the crash records nothing); tests may toggle
_FLIGHT_ENABLED = True


def flight_record(kind: str, what: str = "", value=None) -> None:
    """Append one FIXED-FIELD record to the flight ring: ``kind`` (the
    event class), ``what`` (a short string — a reason, a tag), ``value``
    (one number — a step, a count, an epoch). One bool check + one
    bounded append; never allocates beyond the record. Deliberately not
    a span and not an event: this ring survives with the process and is
    cheap enough to leave on everywhere."""
    global _flight_seq, _flight_dropped
    if not _FLIGHT_ENABLED:
        return
    with _flight_lock:
        if len(_flight) >= _FLIGHT_CAP:
            # the bounded deque is about to overwrite its oldest record
            _flight_dropped += 1
        _flight_seq += 1
        _flight.append(
            {
                "seq": _flight_seq,
                "t_s": round(time.perf_counter() - _T0, 6),
                "thread": threading.current_thread().name,
                "kind": kind,
                "what": what,
                "value": value,
            }
        )


def flight_tail(n: int = 64) -> List[Dict[str, Any]]:
    """The last ``n`` flight records, oldest first — what
    ``WorldChangedError``, dispatcher shed paths, and the chaos harness
    attach to their post-mortems."""
    n = int(n)
    if n <= 0:
        return []
    with _flight_lock:
        tail = list(_flight)[-n:]
    return [dict(r) for r in tail]


def flight_clear() -> None:
    with _flight_lock:
        _flight.clear()


def flight_capacity() -> int:
    return _FLIGHT_CAP


def flight_dropped() -> int:
    """How many flight records the bounded ring has overwritten since
    process start — the ring's health gauge (``prometheus_text``
    exports it as ``heat_tpu_flight_dropped_total``): a large number
    on a crashed process means the tail you are reading is recent,
    not complete."""
    with _flight_lock:
        return _flight_dropped


# --------------------------------------------------------------------- #
# Chrome-trace / Perfetto export                                        #
# --------------------------------------------------------------------- #
def export_trace(path: str, span_rows: Optional[List[Dict[str, Any]]] = None) -> int:
    """Write the span buffer as Chrome trace-event-format JSON
    (loadable in Perfetto / chrome://tracing); returns the event count.

    - every finished span becomes one complete (``"X"``) event on its
      thread's track, ``args`` = the span attrs;
    - spans carrying a ``plan_id`` attr additionally emit an async
      begin/end pair (``"b"``/``"e"``) under ``cat="plan"`` with
      ``id=plan_id``, so every lap/window/execute span of one plan
      lines up on one async track — and, on a device profile captured
      in the same session, aligns with the ``redist_plan_<id>``
      named-scope stamps ``jax.profiler`` records in the HLO metadata;
    - thread-name metadata events label the tracks.
    """
    rows = spans() if span_rows is None else list(span_rows)
    events: List[Dict[str, Any]] = []
    seen_threads: Dict[int, str] = {}
    for r in rows:
        tid = int(r.get("thread") or 0)
        if tid not in seen_threads:
            seen_threads[tid] = _thread_names.get(tid, f"thread-{tid}")
    for tid, tname in sorted(seen_threads.items()):
        events.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 0,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    for r in rows:
        if r.get("dur_s") is None:
            continue  # never committed (crashed mid-span): skip
        ts_us = round(float(r["t0_s"]) * 1e6, 3)
        dur_us = round(float(r["dur_s"]) * 1e6, 3)
        args = dict(r.get("attrs") or {})
        args["span_id"] = r["id"]
        if r.get("parent") is not None:
            args["parent_id"] = r["parent"]
        tid = int(r.get("thread") or 0)
        events.append(
            {
                "ph": "X",
                "name": r["name"],
                "cat": r["name"].split(".", 1)[0],
                "pid": 0,
                "tid": tid,
                "ts": ts_us,
                "dur": dur_us,
                "args": args,
            }
        )
        plan_id = args.get("plan_id")
        if plan_id:
            common = {
                "cat": "plan",
                "id": str(plan_id),
                "pid": 0,
                "tid": tid,
                "name": r["name"],
            }
            events.append({"ph": "b", "ts": ts_us, **common})
            events.append({"ph": "e", "ts": round(ts_us + dur_us, 3), **common})
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "heat_tpu.observability.tracing",
            "spans": len(rows),
            "dropped": dropped(),
        },
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)
