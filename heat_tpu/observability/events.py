"""Bounded structured event log.

Counters say *how many*; events say *what, in order*: each hook point
(``comm.shard``, ``comm.reshard``, ``dndarray.resplit``, program-cache
misses, ``ht.jit`` traces, user ``record()`` blocks) appends one dict
with a monotonic sequence number and a timestamp relative to process
start. The buffer is a fixed-size ring (oldest events drop first), so
instrumenting a hot loop cannot grow memory without bound — and every
overwrite is COUNTED (``dropped``, surfaced in
``telemetry.snapshot()['events']``): a wrapped ring must read as "the
tail of a longer story" in a post-mortem, never as complete history.

When span tracing is live (``observability.tracing``), each event
carries an optional ``span`` field — the id of the innermost active
span on the emitting thread — correlating the event stream with the
trace timeline.

Callers gate on ``telemetry.enabled()`` BEFORE building the field dict —
``emit`` itself does not re-check, keeping the enabled path one call
deep. All field values must be host-side Python data (trace-safety
contract, see ``telemetry``)."""

from __future__ import annotations

import threading
import time

from collections import deque
from typing import Any, Dict, List

from . import tracing as _tracing

__all__ = ["capacity", "clear", "dropped", "emit", "meta", "snapshot"]

_CAPACITY = 4096
_T0 = time.perf_counter()

_lock = threading.Lock()
_events: deque = deque(maxlen=_CAPACITY)
_seq = 0
_dropped = 0


def emit(kind: str, **fields: Any) -> None:
    """Append one event. ``kind`` names the hook point; ``fields`` are
    host-side values (ints/floats/strs/tuples)."""
    global _seq, _dropped
    span_id = _tracing.current_span_id() if _tracing._ENABLED else None
    with _lock:
        _seq += 1
        if len(_events) == _CAPACITY:
            _dropped += 1
        ev = {"seq": _seq, "t_s": round(time.perf_counter() - _T0, 6), "event": kind, **fields}
        if span_id is not None:
            ev["span"] = span_id
        _events.append(ev)


def snapshot() -> List[Dict[str, Any]]:
    """Copy of the buffered events, oldest first. A wrapped ring holds
    only the TAIL — check :func:`dropped` (or the ``events`` metadata
    in ``telemetry.snapshot()``) before reading it as history."""
    with _lock:
        return [dict(e) for e in _events]


def clear() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def dropped() -> int:
    """Events overwritten by ring wrap since the last :func:`clear`."""
    with _lock:
        return _dropped


def meta() -> Dict[str, int]:
    """Ring health: ``{"capacity", "buffered", "dropped"}`` — what
    ``telemetry.snapshot()`` surfaces so a post-mortem knows whether
    the buffer is complete or a tail."""
    with _lock:
        return {"capacity": _CAPACITY, "buffered": len(_events), "dropped": _dropped}


def capacity() -> int:
    return _CAPACITY
