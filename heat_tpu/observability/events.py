"""Bounded structured event log.

Counters say *how many*; events say *what, in order*: each hook point
(``comm.shard``, ``comm.reshard``, ``dndarray.resplit``, program-cache
misses, ``ht.jit`` traces, user ``record()`` blocks) appends one dict
with a monotonic sequence number and a timestamp relative to process
start. The buffer is a fixed-size ring (oldest events drop first), so
instrumenting a hot loop cannot grow memory without bound.

Callers gate on ``telemetry.enabled()`` BEFORE building the field dict —
``emit`` itself does not re-check, keeping the enabled path one call
deep. All field values must be host-side Python data (trace-safety
contract, see ``telemetry``)."""

from __future__ import annotations

import threading
import time

from collections import deque
from typing import Any, Dict, List

__all__ = ["capacity", "clear", "emit", "snapshot"]

_CAPACITY = 4096
_T0 = time.perf_counter()

_lock = threading.Lock()
_events: deque = deque(maxlen=_CAPACITY)
_seq = 0


def emit(kind: str, **fields: Any) -> None:
    """Append one event. ``kind`` names the hook point; ``fields`` are
    host-side values (ints/floats/strs/tuples)."""
    global _seq
    with _lock:
        _seq += 1
        _events.append(
            {"seq": _seq, "t_s": round(time.perf_counter() - _T0, 6), "event": kind, **fields}
        )


def snapshot() -> List[Dict[str, Any]]:
    """Copy of the buffered events, oldest first."""
    with _lock:
        return [dict(e) for e in _events]


def clear() -> None:
    with _lock:
        _events.clear()


def capacity() -> int:
    return _CAPACITY
