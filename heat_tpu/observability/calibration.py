"""Self-calibrating cost lattice (ISSUE 16) — probes, profiles, and
the model-error loop closure.

PR 15 shipped the instrument: spans joined against ``tier_time_model``
with signed per-leg ``model_error``. But every price the planner uses
is still a hard-coded constant in ``core.tiers`` (ICI 200e9, DCN 25e9,
PCIe 16e9, disk 0.8e9), so the measurement is reported and then
discarded. This module closes the loop (EQuARX's lesson,
arXiv:2506.17615 — measured behavior beats static models — applied to
the whole planner; arXiv:2112.01075's decomposition arithmetic is only
as good as the per-edge bandwidths it is priced with):

1. **Probe suite** — measure each lattice edge directly, bench.py
   style (repeat, keep the floor, flag wide dispersion as
   ``measurement_suspect``): ``hbm`` via an on-device copy, ``pcie``
   via the depth-2 staging stream (``device_put`` of host windows),
   ``ici``/``dcn`` via tiny collective programs per tier group, and
   ``disk`` via a slab read (the NVMe figure the ROADMAP's runtime
   item 4 prices at ~3 GB/s vs the fsync-inclusive 0.8e9 constant the
   durable-commit path keeps).
2. **Span ingestion** — fold the spans/attribution legs an ORDINARY
   traced run already records (staging windows carry ``tier`` +
   ``bytes`` + real wall; attribution legs carry measured seconds
   against modeled bytes) into per-edge bandwidth estimates: a
   deployment calibrates itself just by running.
3. **Lattice profile** — measurements persist as a versioned
   per-(platform, topology) JSON envelope stamped like the AOT store:
   a ``format`` version, integrity-checked by a sha256 ``profile_id``
   over the canonical measurement content. ``load_profile`` NEVER
   raises: a missing file is a miss, a tampered or version-mismatched
   file is counted, evicted (best-effort unlink), and the constants
   are used — a bad profile can degrade pricing back to the defaults,
   never take the library down.
4. **Loop closure proof** — :func:`calibration_report` re-judges one
   run's spans under both price sets and reports mean |model_error|
   constants-vs-calibrated per leg; ci.sh gates that the calibrated
   error is no larger.

The profile is ACTIVATED through the registry-declared gate
``HEAT_TPU_LATTICE_PROFILE`` (``core.gates``): unset, every price is
the constant and every plan/plan_id/program byte-identical to the
pre-calibration era (``core.tiers.active_profile`` short-circuits
without even importing this module); set, ``tiers.bandwidth()/
transfer_time()/penalty()`` consult the measured edges, the planner
re-prices candidate selection, and the ``profile_id`` is stamped into
plan canonical serialization (``Schedule.calibration``) so a
recalibration is a VISIBLE plan_id invalidation.

Import-light by design: stdlib + the gate registry + ``core.tiers``
only — jax and numpy load lazily inside the probes, so the plan-dump
scripts and ``tiers`` itself can import this module on any container.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core import gates as _gates
from ..core import tiers as _tiers
from ..version import __version__

__all__ = [
    "PROBE_EDGES",
    "build_profile",
    "calibrate",
    "calibration_report",
    "describe_profile",
    "ingest_attribution",
    "ingest_spans",
    "load_profile",
    "probe_collective",
    "probe_disk",
    "probe_hbm",
    "probe_pcie",
    "profile_digest",
    "run_probes",
    "save_profile",
    "stats",
]

#: envelope format version — bumped on any layout change; a mismatched
#: profile is version_mismatch (evicted, constants used), exactly the
#: AOT store's discipline.
_FORMAT = 1

#: every edge the probe suite can measure (== the lattice's edge set).
PROBE_EDGES: Tuple[str, ...] = tuple(sorted(_tiers.EDGES))

#: default probe payload — big enough to amortize dispatch, small
#: enough for the CPU CI container.
_PROBE_BYTES = 32 << 20
_COLLECTIVE_BYTES = 4 << 20
_REPEATS = 3

_stats_lock = threading.Lock()
_stats: Dict[str, int] = {
    "load": 0, "hit": 0, "miss": 0, "corrupt": 0,
    "tampered": 0, "version_mismatch": 0,
}


def stats() -> Dict[str, int]:
    """Profile-loader outcome counters (AOT-store style): ``hit``,
    ``miss`` (no file), ``corrupt`` (unparseable — evicted),
    ``tampered`` (digest mismatch — evicted), ``version_mismatch``
    (format bump — evicted)."""
    with _stats_lock:
        return dict(_stats)


def _count(key: str) -> None:
    with _stats_lock:
        _stats[key] += 1


def reset_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# --------------------------------------------------------------------- #
# the envelope                                                          #
# --------------------------------------------------------------------- #
def profile_digest(platform: str, topology: str, edges: Dict[str, Any]) -> str:
    """sha256 prefix over the canonical measurement content — format,
    platform, topology, and the per-edge records (sorted keys, compact
    separators, same discipline as ``Schedule.canonical_json``). The
    library version is stamped in the envelope but kept OUT of the
    digest: re-saving the same measurements under a new heat_tpu
    release must not silently re-key every plan."""
    content = {
        "format": _FORMAT,
        "platform": platform,
        "topology": topology,
        "edges": edges,
    }
    blob = json.dumps(content, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_profile(
    edges: Dict[str, Any],
    platform: Optional[str] = None,
    topology: Optional[str] = None,
) -> Dict[str, Any]:
    """Assemble the versioned envelope from per-edge records.

    ``edges``: ``{edge: {"bps": float, "method": str, "samples":
    [...], "measurement_suspect": bool}}`` — only measured edges
    appear; unmeasured edges keep their constants at pricing time
    (``tiers.bandwidth`` falls through per edge). ``platform``/
    ``topology`` default to the live jax backend and the ambient
    resolved topology when importable, else ``"unknown"``/``"flat"``.
    """
    clean: Dict[str, Dict[str, Any]] = {}
    for name in sorted(edges):
        if name not in _tiers.EDGES:
            raise ValueError(
                f"build_profile: unknown lattice edge {name!r} "
                f"(one of {PROBE_EDGES})"
            )
        rec = dict(edges[name])
        bps = float(rec["bps"])
        if not bps > 0:
            raise ValueError(f"build_profile: edge {name!r} bps must be > 0, got {bps}")
        rec["bps"] = round(bps, 1)
        if "samples" in rec:
            rec["samples"] = [round(float(s), 1) for s in rec["samples"]]
        rec.setdefault("measurement_suspect", False)
        clean[name] = rec
    if platform is None:
        try:
            import jax

            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
    if topology is None:
        topology = _gates.get("HEAT_TPU_TOPOLOGY", "") or "flat"
    envelope = {
        "format": _FORMAT,
        "kind": "lattice-profile",
        "heat_tpu": __version__,
        "platform": str(platform),
        "topology": str(topology),
        "edges": clean,
        "profile_id": profile_digest(str(platform), str(topology), clean),
    }
    return envelope


def save_profile(profile: Dict[str, Any], path: str) -> str:
    """Persist an envelope atomically (``tmp.{pid}`` + ``os.replace``,
    the AOT store's write discipline) and return the path."""
    path = os.path.expanduser(str(path))
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(profile, f, sort_keys=True, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def _evict(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def load_profile(path: str) -> Optional[Dict[str, Any]]:
    """Load + integrity-check a profile envelope; ``None`` on ANY
    failure — the caller falls back to the constants, never errors.

    - missing file -> ``miss``;
    - unparseable / wrong shape -> ``corrupt``: evicted (best-effort
      unlink) so the next run is a clean miss;
    - ``format`` != current -> ``version_mismatch``: evicted (a stale
      profile must be re-measured, not re-interpreted);
    - recomputed digest != stored ``profile_id`` -> ``tampered``:
      evicted (the sha256 stamp IS the trust boundary — an edited
      price must never silently re-route the planner).
    """
    _count("load")
    path = os.path.expanduser(str(path))
    if not os.path.exists(path):
        _count("miss")
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("not an object")
        edges = doc["edges"]
        if not isinstance(edges, dict) or not edges:
            raise ValueError("no edges")
        for name, rec in edges.items():
            if name not in _tiers.EDGES:
                raise ValueError(f"unknown edge {name!r}")
            if not float(rec["bps"]) > 0:
                raise ValueError(f"edge {name!r} bps not positive")
        fmt = doc["format"]
        platform, topology = str(doc["platform"]), str(doc["topology"])
        pid = str(doc["profile_id"])
    except Exception:
        _count("corrupt")
        _evict(path)
        return None
    if fmt != _FORMAT:
        _count("version_mismatch")
        _evict(path)
        return None
    if profile_digest(platform, topology, edges) != pid:
        _count("tampered")
        _evict(path)
        return None
    _count("hit")
    return doc


# --------------------------------------------------------------------- #
# the probe suite                                                       #
# --------------------------------------------------------------------- #
def _floor_retry(
    one: Callable[[], Tuple[int, float]], repeats: int
) -> Optional[Dict[str, Any]]:
    """bench.py's measurement discipline: run ``one`` (-> moved bytes,
    seconds) ``repeats`` times, keep the BEST bandwidth (the floor of
    the timing noise), and flag the record ``measurement_suspect``
    when the median lands below half the best — a dispersion that wide
    means the number is weather, not hardware."""
    samples: List[float] = []
    for _ in range(max(1, int(repeats))):
        nbytes, dt = one()
        if dt > 0 and nbytes > 0:
            samples.append(nbytes / dt)
    if not samples:
        return None
    best = max(samples)
    median = sorted(samples)[len(samples) // 2]
    return {
        "bps": best,
        "samples": samples,
        "measurement_suspect": bool(len(samples) < 2 or median < 0.5 * best),
    }


def _copy_probe_fn():
    """Program builder for the on-device copy probe.  Deliberately a
    bare ``jax.jit``: the probe measures the raw stream, so it must not
    route through ht.jit's donation/telemetry hooks."""
    import jax

    return jax.jit(lambda a: a + 1.0)


def probe_hbm(
    nbytes: int = _PROBE_BYTES, repeats: int = _REPEATS
) -> Optional[Dict[str, Any]]:
    """The device-memory stream edge: time an on-device elementwise
    copy (one read + one write of the operand — 2x the payload) on a
    warmed jitted program. On TPU this is the HBM stream; on the CPU
    container it is host memcpy bandwidth — either way it is the
    number ``transfer_time(_, "hbm")`` should charge THIS deployment.
    """
    import jax
    import jax.numpy as jnp

    n = max(1, int(nbytes) // 4)
    x = jnp.zeros((n,), dtype=jnp.float32)
    f = _copy_probe_fn()
    f(x).block_until_ready()  # warm the program

    def one() -> Tuple[int, float]:
        t0 = time.perf_counter()
        f(x).block_until_ready()
        return 2 * n * 4, time.perf_counter() - t0

    rec = _floor_retry(one, repeats)
    if rec:
        rec["method"] = "probe:on-device-copy"
    return rec


def probe_pcie(
    nbytes: int = _PROBE_BYTES, repeats: int = _REPEATS
) -> Optional[Dict[str, Any]]:
    """The host->device staging edge, measured the way the depth-2
    staging executor drives it: ``jax.device_put`` of a host-resident
    window, fenced. On TPU this is PCIe DMA; on CPU it is the
    host->device copy jax actually performs — the price a staged
    window really pays here."""
    import jax
    import numpy as np

    n = max(1, int(nbytes) // 4)
    host = np.zeros((n,), dtype=np.float32)
    jax.device_put(host).block_until_ready()  # warm the transfer path

    def one() -> Tuple[int, float]:
        t0 = time.perf_counter()
        jax.device_put(host).block_until_ready()
        return n * 4, time.perf_counter() - t0

    rec = _floor_retry(one, repeats)
    if rec:
        rec["method"] = "probe:device_put-stream"
    return rec


def probe_disk(
    nbytes: int = _PROBE_BYTES,
    repeats: int = _REPEATS,
    directory: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """The host<->persistent-store edge via a SLAB READ — the
    non-durable staging figure (NVMe streams 3+ GB/s) the ROADMAP
    tracks separately from the fsync-inclusive 0.8e9 durable-commit
    constant. The OS page cache is visible to a re-read, which is
    exactly what a staging loop re-reading a hot slab sees; the floor/
    suspect discipline still flags a flapping medium."""
    buf = bytearray(max(1, int(nbytes)))
    fd, path = tempfile.mkstemp(prefix="heat_tpu_disk_probe_", dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(bytes(len(buf)))
            f.flush()
            os.fsync(f.fileno())

        def one() -> Tuple[int, float]:
            t0 = time.perf_counter()
            with open(path, "rb", buffering=0) as f:
                got = f.readinto(buf)
            return int(got or 0), time.perf_counter() - t0

        rec = _floor_retry(one, repeats)
        if rec:
            rec["method"] = "probe:slab-read"
        return rec
    finally:
        _evict(path)


def _all_gather_probe_fn(mesh):
    """Program builder for the wire probe: a tiled all_gather over the
    probe mesh.  Bare ``jax.jit`` on purpose — routing the probe through
    ht.jit's donation/telemetry hooks would perturb the timing."""
    import jax

    from jax.sharding import PartitionSpec as P

    from ..core._jax_compat import shard_map

    return jax.jit(
        shard_map(
            lambda a: jax.lax.all_gather(a, "probe", tiled=True),
            mesh=mesh,
            in_specs=P("probe"),
            out_specs=P(None),
        )
    )


def probe_collective(
    edge: str,
    nbytes: int = _COLLECTIVE_BYTES,
    repeats: int = _REPEATS,
) -> Optional[Dict[str, Any]]:
    """The wire edges, measured with a tiny collective program per
    TIER GROUP (``core.communication.Topology``): ``ici`` runs an
    all_gather across one slice's chips (every chip of a flat mesh),
    ``dcn`` across one chip per slice — the same replica-group
    factorization the hierarchical plans exchange over. ``None`` when
    the mesh cannot express the edge (one device, or a flat topology
    asked for dcn) — the profile simply keeps the constant."""
    if edge not in ("ici", "dcn"):
        raise ValueError(f"probe_collective measures wire edges, got {edge!r}")
    import jax
    import jax.numpy as jnp

    from jax.sharding import Mesh

    from ..core import communication as _comm

    devices = jax.devices()
    topo = _comm.topology_for(len(devices), None)
    if edge == "ici":
        group = topo.chip_axis_groups()[0] if topo.tiered else list(range(len(devices)))
    else:
        if not topo.tiered:
            return None
        group = topo.slice_axis_groups()[0]
    if len(group) < 2:
        return None
    import numpy as np

    mesh_devs = np.array([devices[i] for i in group])
    mesh = Mesh(mesh_devs, ("probe",))
    g = len(group)
    n = max(g, (int(nbytes) // 4 // g) * g)  # g-divisible element count
    x = jnp.zeros((n,), dtype=jnp.float32)

    fn = _all_gather_probe_fn(mesh)
    fn(x).block_until_ready()  # warm the program
    # per-device wire traffic of an all_gather: each chip receives the
    # other (g-1) shards
    wire = (n // g) * 4 * (g - 1)

    def one() -> Tuple[int, float]:
        t0 = time.perf_counter()
        fn(x).block_until_ready()
        return wire, time.perf_counter() - t0

    rec = _floor_retry(one, repeats)
    if rec:
        rec["method"] = f"probe:all_gather[{g}dev]"
    return rec


def run_probes(
    edges: Optional[Sequence[str]] = None,
    nbytes: int = _PROBE_BYTES,
    repeats: int = _REPEATS,
) -> Dict[str, Dict[str, Any]]:
    """Run every requested probe (default: all five edges) and return
    the per-edge records. A probe that cannot run on this container
    (no second device, no slice structure) or that errors simply
    leaves its edge out — pricing falls back to the constant, the
    suite never fails."""
    out: Dict[str, Dict[str, Any]] = {}
    for edge in edges if edges is not None else PROBE_EDGES:
        try:
            if edge == "hbm":
                rec = probe_hbm(nbytes, repeats)
            elif edge == "pcie":
                rec = probe_pcie(nbytes, repeats)
            elif edge == "disk":
                rec = probe_disk(nbytes, repeats)
            elif edge in ("ici", "dcn"):
                rec = probe_collective(edge, min(nbytes, _COLLECTIVE_BYTES), repeats)
            else:
                raise ValueError(f"run_probes: unknown edge {edge!r}")
        except ValueError:
            raise
        except Exception:  # a failed probe is a missing measurement, not a crash
            rec = None
        if rec is not None:
            out[edge] = rec
    return out


# --------------------------------------------------------------------- #
# span / attribution ingestion — calibrate by just running              #
# --------------------------------------------------------------------- #
def ingest_spans(
    span_rows: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, List[float]]:
    """Per-edge bandwidth samples from the spans an ordinary traced
    run records: every REAL-wall span (not a trace-census probe)
    carrying a lattice ``tier`` and a ``bytes`` payload — the staging
    executor's ``stage_in`` windows are the canonical source — yields
    one ``bytes/dur`` sample on its edge."""
    from . import tracing as _tracing

    rows = _tracing.spans() if span_rows is None else list(span_rows)
    samples: Dict[str, List[float]] = {}
    for r in rows:
        attrs = r.get("attrs") or {}
        tier = attrs.get("tier")
        nbytes = attrs.get("bytes")
        dur = r.get("dur_s")
        if attrs.get("traced") or tier not in _tiers.EDGES:
            continue
        if not nbytes or not dur or dur <= 0:
            continue
        samples.setdefault(tier, []).append(float(nbytes) / float(dur))
    return samples


def ingest_attribution(
    reports: Sequence[Dict[str, Any]],
) -> Dict[str, List[float]]:
    """Per-edge bandwidth samples from :func:`~heat_tpu.observability.
    attribution.attribution` reports: a measured tier leg against the
    model's byte count for that tier is one ``tier_bytes/measured_s``
    sample — the per-leg join PR 15 already computes, folded back into
    a price instead of discarded."""
    samples: Dict[str, List[float]] = {}
    for rep in reports:
        model = rep.get("model") or {}
        for leg in rep.get("legs") or []:
            tier = leg.get("tier")
            measured = leg.get("measured_s")
            if tier not in _tiers.EDGES or not measured or measured <= 0:
                continue
            nbytes = model.get(f"{tier}_bytes")
            if nbytes:
                samples.setdefault(tier, []).append(float(nbytes) / float(measured))
    return samples


def _fold_samples(
    probed: Dict[str, Dict[str, Any]],
    ingested: Dict[str, List[float]],
) -> Dict[str, Dict[str, Any]]:
    """Merge probe records with ingested samples: an edge both paths
    measured keeps the probe's record and appends the ingested
    samples to its floor; an edge only the spans saw becomes a
    ``spans`` record under the same floor/suspect discipline."""
    out = {k: dict(v) for k, v in probed.items()}
    for edge, samples in ingested.items():
        samples = [s for s in samples if s > 0]
        if not samples:
            continue
        if edge in out:
            merged = list(out[edge].get("samples") or []) + samples
            best = max(merged)
            median = sorted(merged)[len(merged) // 2]
            out[edge]["samples"] = merged
            out[edge]["bps"] = best
            out[edge]["measurement_suspect"] = bool(median < 0.5 * best)
            out[edge]["method"] = f"{out[edge].get('method', 'probe')}+spans"
        else:
            best = max(samples)
            median = sorted(samples)[len(samples) // 2]
            out[edge] = {
                "bps": best,
                "samples": samples,
                "measurement_suspect": bool(len(samples) < 2 or median < 0.5 * best),
                "method": "spans",
            }
    return out


def calibrate(
    path: Optional[str] = None,
    edges: Optional[Sequence[str]] = None,
    nbytes: int = _PROBE_BYTES,
    repeats: int = _REPEATS,
    span_rows: Optional[List[Dict[str, Any]]] = None,
    include_spans: bool = True,
    platform: Optional[str] = None,
    topology: Optional[str] = None,
) -> Dict[str, Any]:
    """The full calibration pass: run the probe suite, fold in the
    span samples the current trace buffer (or ``span_rows``) carries,
    build the stamped envelope, and persist it to ``path`` when given.
    Returns the envelope (``profile_id`` included) — point
    ``HEAT_TPU_LATTICE_PROFILE`` at the saved path to activate it."""
    probed = run_probes(edges, nbytes, repeats)
    ingested = ingest_spans(span_rows) if include_spans else {}
    if edges is not None:
        ingested = {k: v for k, v in ingested.items() if k in set(edges)}
    folded = _fold_samples(probed, ingested)
    if not folded:
        raise RuntimeError(
            "calibrate: no edge could be measured on this container "
            "(no devices, no spans) — nothing to profile"
        )
    profile = build_profile(folded, platform=platform, topology=topology)
    if path:
        save_profile(profile, path)
    return profile


# --------------------------------------------------------------------- #
# loop-closure proof                                                    #
# --------------------------------------------------------------------- #
def calibration_report(
    plan,
    span_rows: Optional[List[Dict[str, Any]]] = None,
    profile: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Does calibration actually shrink the model error? Re-judge one
    run's spans under BOTH price sets — the constants column
    (``model_error``) and the calibrated column (``calibrated_error``)
    that :func:`~heat_tpu.observability.attribution.attribution` adds
    when a profile is in reach (explicit ``profile=``, the plan's own
    ``calibration`` annotation, or the ambient gate) — and report the
    per-leg pair plus the means. ``improved`` is the CI gate's
    criterion: mean |calibrated error| <= mean |constants error| over
    every leg that carries both columns."""
    import importlib

    # the package attr `attribution` is the FUNCTION (the documented
    # call shape); the module must come via importlib
    _attribution_mod = importlib.import_module(
        "heat_tpu.observability.attribution"
    )

    rep = _attribution_mod.attribution(plan, span_rows, profile=profile)
    legs = [
        {
            "step": leg["step"],
            "tier": leg.get("tier"),
            "model_error": leg["model_error"],
            "calibrated_error": leg["calibrated_error"],
        }
        for leg in rep["legs"]
        if "model_error" in leg and "calibrated_error" in leg
    ]
    cal = (rep["model"].get("calibrated") or {})
    out: Dict[str, Any] = {
        "plan_id": rep["plan_id"],
        "profile_id": cal.get("profile_id"),
        "n_legs": len(legs),
        "legs": legs,
    }
    if legs:
        before = sum(abs(l["model_error"]) for l in legs) / len(legs)
        after = sum(abs(l["calibrated_error"]) for l in legs) / len(legs)
        out["mean_abs_error_constants"] = round(before, 4)
        out["mean_abs_error_calibrated"] = round(after, 4)
        out["improved"] = bool(after <= before)
    return out


def describe_profile(profile: Dict[str, Any]) -> str:
    """Constants-vs-measured table of one envelope — what
    ``scripts/calibrate.py`` prints (the PERF.md baseline->bound->beat
    evidence row)."""
    lines = [
        f"lattice profile {profile['profile_id']}  "
        f"platform={profile['platform']}  topology={profile['topology']}  "
        f"(format {profile['format']}, heat_tpu {profile['heat_tpu']})",
        f"  {'edge':>5}  {'constant':>12}  {'measured':>12}  {'ratio':>7}  method",
    ]
    for edge in PROBE_EDGES:
        const = _tiers.EDGES[edge][2]
        rec = profile["edges"].get(edge)
        if rec is None:
            lines.append(
                f"  {edge:>5}  {const / 1e9:>10.2f}GB  {'(constant)':>12}"
            )
            continue
        bps = float(rec["bps"])
        suspect = "  [suspect]" if rec.get("measurement_suspect") else ""
        lines.append(
            f"  {edge:>5}  {const / 1e9:>10.2f}GB  {bps / 1e9:>10.2f}GB  "
            f"{bps / const:>6.2f}x  {rec.get('method', '?')}{suspect}"
        )
    return "\n".join(lines)
