"""HLO collective inspector.

The multi-chip cost model in docs/PERF.md is only credible because the
compiled programs are *checked*: the MULTICHIP dryrun and
``tests/test_parallel_primitives.py`` count ``all-gather`` /
``collective-permute`` / ``all-to-all`` ops in compiled HLO text. That
assert machinery lived out-of-tree in scripts; this module promotes it
into a public, tested API::

    rep = ht.observability.collective_counts(lambda a: ht.linalg.qr(a), x)
    assert rep.all_gather == 1 and rep.total == 1

``collective_counts`` lowers and compiles the function for the given
example arguments (DNDarray arguments are traced through the same
machinery as ``ht.jit``; already-jitted jax callables lower directly),
then reports per-collective op counts, an estimated byte volume per
collective kind parsed from the result shapes in the module text, and
the compiler's own ``cost_analysis()`` aggregates. Nothing executes on
device — inspection is compile-only, so it is cheap enough for tests
and safe on any mesh (including the forced-CPU test mesh).
"""

from __future__ import annotations

import re

from typing import Any, Callable, Dict, Optional

__all__ = ["COLLECTIVE_OPS", "CollectiveReport", "collective_counts"]

COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-permute",
    "reduce-scatter",
)

# HLO dtype token -> itemsize, for the byte estimate
_ITEMSIZE = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# "f32[8,960,960]" / "u32[]" result-type tokens
_SHAPED = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# "  %x = <result-type> all-gather(" — the result type is everything
# between '=' and the op token: a bare shaped type or a tuple of them.
# Tuple types embed '=' inside /*index=N*/ comments, so the match anchors
# on the SSA lhs at line start (optionally ROOT-prefixed) instead of
# excluding '='.
_COLLECTIVE_LINE = re.compile(
    r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*(.*?)\s*(" + "|".join(COLLECTIVE_OPS) + r")(?:-start)?\(",
    re.M,
)


class CollectiveReport:
    """Per-kind collective counts + byte estimates of one compiled module.

    Attributes
    ----------
    counts : dict — {op name: count} over ``COLLECTIVE_OPS`` (async
        start/done pairs count once, via their ``-start`` form).
    bytes_by_op : dict — estimated output bytes per collective kind,
        summed from the result shapes in the module text (an estimate:
        async forms carry operand aliases in their result tuples).
    flops / bytes_accessed : compiler ``cost_analysis()`` aggregates for
        the WHOLE program, when the backend reports them (else None).
    hlo_text : the compiled module text, for ad-hoc inspection.
    """

    def __init__(self, counts, bytes_by_op, flops, bytes_accessed, hlo_text):
        self.counts: Dict[str, int] = counts
        self.bytes_by_op: Dict[str, int] = bytes_by_op
        self.flops: Optional[float] = flops
        self.bytes_accessed: Optional[float] = bytes_accessed
        self.hlo_text: str = hlo_text

    @property
    def total(self) -> int:
        """Total collective op count."""
        return sum(self.counts.values())

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes_by_op.values())

    # attribute sugar: rep.all_gather / rep.collective_permute ...
    def __getattr__(self, name: str):
        # read via __dict__: during unpickle/deepcopy this runs before
        # __init__, and touching self.counts would recurse
        counts = self.__dict__.get("counts")
        if counts is not None:
            key = name.replace("_", "-")
            if key in counts:
                return counts[key]
        raise AttributeError(name)

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready summary (HLO text omitted)."""
        return {
            "counts": dict(self.counts),
            "total": self.total,
            "bytes_by_op": dict(self.bytes_by_op),
            "total_bytes": self.total_bytes,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
        }

    def __repr__(self) -> str:
        nz = {k: v for k, v in self.counts.items() if v}
        return f"CollectiveReport({nz or 'no collectives'}, ~{self.total_bytes} B)"


def _shaped_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPED.findall(type_str):
        if dtype == "token":
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _ITEMSIZE.get(dtype, 4)
    return total


def _count_ops(text: str) -> Dict[str, int]:
    # " op(" catches sync forms, "op-start(" the async ones; the paired
    # "-done" is not counted (one collective, not two)
    return {
        op: text.count(f" {op}(") + text.count(f"{op}-start(") for op in COLLECTIVE_OPS
    }


def _collective_bytes(text: str) -> Dict[str, int]:
    out = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_LINE.finditer(text):
        out[m.group(3)] += _shaped_bytes(m.group(2))
    return out


def _normalize_cost(compiled):
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(cost, (list, tuple)):  # older jax returned [dict]
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return None, None
    return cost.get("flops"), cost.get("bytes accessed")


def _build_traceable(fn: Callable, args: tuple, kwargs: dict):
    """Normalize ``fn(*args, **kwargs)`` to one traceable program.

    Returns ``(kind, target, traced_in)``:

    - ``("lower", fn, flat_jax_args)`` — ``fn`` already exposes ``.lower``
      (jax.jit / shard_map programs) and no argument is a DNDarray: lower
      it directly on the original arguments.
    - ``("wrap", inner, traced_in)`` — everything else, notably public
      heat_tpu functions over DNDarrays, goes through the same
      trace-to-one-program machinery as ``ht.jit``: DNDarray leaves feed
      their physical arrays as traced inputs, metadata rebuilds at trace
      time, outputs flatten back to physical leaves. ``inner`` is a plain
      function of ``traced_in``.

    Shared by :func:`collective_counts` and the ``ht.analysis.check`` IR
    lint, so both inspect the SAME program a user dispatch would run.
    """
    import jax

    from ..core.dndarray import DNDarray
    from ..core.jit import _is_leaf

    leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_leaf)
    if not any(isinstance(leaf, DNDarray) for leaf in leaves) and hasattr(fn, "lower"):
        return "lower", fn, [leaf for leaf in leaves if isinstance(leaf, jax.Array)]

    is_traced = [isinstance(leaf, (DNDarray, jax.Array)) for leaf in leaves]
    metas = [
        (leaf.gshape, leaf.dtype, leaf.split, leaf.device, leaf.comm)
        if isinstance(leaf, DNDarray)
        else None
        for leaf in leaves
    ]

    def inner(*traced):
        it = iter(traced)
        rebuilt = []
        for leaf, traced_leaf, meta in zip(leaves, is_traced, metas):
            if not traced_leaf:
                rebuilt.append(leaf)
            elif meta is not None:
                rebuilt.append(DNDarray(next(it), *meta))
            else:
                rebuilt.append(next(it))
        a, kw = jax.tree.unflatten(treedef, rebuilt)
        res = fn(*a, **kw)
        out_leaves, _ = jax.tree.flatten(res, is_leaf=_is_leaf)
        return tuple(
            o._phys if isinstance(o, DNDarray) else o for o in out_leaves
        )

    traced_in = [
        leaf._phys if isinstance(leaf, DNDarray) else leaf
        for leaf, t in zip(leaves, is_traced)
        if t
    ]
    return "wrap", inner, traced_in


def _compile(fn: Callable, args: tuple, kwargs: dict):
    """Lower + compile ``fn`` for the example ``args`` without executing."""
    import jax

    kind, target, traced_in = _build_traceable(fn, args, kwargs)
    if kind == "lower":
        return target.lower(*args, **kwargs).compile()
    return jax.jit(target).lower(*traced_in).compile()


def collective_counts(fn: Callable, *args, **kwargs) -> CollectiveReport:
    """Compile ``fn(*args, **kwargs)`` and count its collective ops.

    ``fn`` may be a public heat_tpu function over DNDarrays, an
    ``ht.jit``/plain function, or an already-jitted jax callable; the
    arguments are example inputs fixing shapes/shardings. Returns a
    :class:`CollectiveReport` — e.g. TSQR at p < 16 reports exactly one
    ``all-gather`` and nothing else, the hSVD level-0 sketch reports
    zero collectives (the pinned contracts in tests/ and the MULTICHIP
    dryrun). Compile-only: no device execution, no data read.
    """
    compiled = _compile(fn, args, kwargs)
    text = compiled.as_text()
    flops, bytes_accessed = _normalize_cost(compiled)
    return CollectiveReport(
        counts=_count_ops(text),
        bytes_by_op=_collective_bytes(text),
        flops=flops,
        bytes_accessed=bytes_accessed,
        hlo_text=text,
    )
