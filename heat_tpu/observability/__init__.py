"""First-party runtime observability (reference deviation: the reference
delegates ALL instrumentation to external tools — perun around its
benchmark scripts, nothing inside the library).

Five pieces, one import surface:

- :mod:`~heat_tpu.observability.telemetry` — process-wide counters,
  timers (p50/p95/p99), the ``record()`` context manager, and
  :func:`prometheus_text` exposition; zero-cost when disabled,
  ``HEAT_TPU_TELEMETRY=1`` or ``enable()`` to activate. Also exposed
  as the ``ht.telemetry`` shorthand.
- :mod:`~heat_tpu.observability.events` — bounded structured event log
  fed by the hooks in ``core/`` (shard/reshard bytes, program-cache
  misses, ``ht.jit`` traces); overwrites counted, span-correlated.
- :mod:`~heat_tpu.observability.tracing` — span tracing of the hot
  layers (``ht.tracing.span``), the always-on flight recorder, and
  Chrome-trace/Perfetto export (:func:`export_trace`); gated
  ``HEAT_TPU_TRACE`` with ``affects_programs=False`` — plans, plan_ids,
  programs, and AOT keys are byte-identical at every value.
- :mod:`~heat_tpu.observability.attribution` — the model-vs-measured
  join (:func:`attribution`): measured span time per step kind/tier
  against the plan's ``tier_time_model``/overlap/staging annotations,
  reported as per-leg ``model_error``.
- :mod:`~heat_tpu.observability.hlo` — :func:`collective_counts`, the
  compile-only HLO inspector pinning each op's collective structure
  (the public form of the MULTICHIP dryrun asserts).
- :mod:`~heat_tpu.observability.calibration` — the self-calibrating
  cost lattice (ISSUE 16): per-edge probe suite + span ingestion,
  persisted as stamped per-deployment lattice profiles
  (``HEAT_TPU_LATTICE_PROFILE``), and :func:`calibration_report` — the
  constants-vs-calibrated model-error proof the CI gate rides.

Instrumentation glue for the core layers lives in
:mod:`~heat_tpu.observability.instrument` (not re-exported).
"""

from . import events
from . import hlo
from . import instrument
from . import telemetry
from . import tracing
from . import attribution
from . import calibration

from .calibration import calibration_report

from .hlo import COLLECTIVE_OPS, CollectiveReport, collective_counts
from .telemetry import (
    disable,
    enable,
    enabled,
    export_jsonl,
    inc,
    observe,
    prometheus_text,
    record,
    report,
    reset,
    snapshot,
)
from .tracing import export_trace, flight_tail, span

# `ht.observability.attribution(plan_id)` is the documented call shape:
# the FUNCTION takes the package-attr slot, the module stays reachable
# as `heat_tpu.observability.attribution` via sys.modules/importlib
attribution = attribution.attribution

__all__ = [
    "COLLECTIVE_OPS",
    "CollectiveReport",
    "attribution",
    "calibration_report",
    "collective_counts",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "export_trace",
    "flight_tail",
    "inc",
    "observe",
    "prometheus_text",
    "record",
    "report",
    "reset",
    "snapshot",
    "span",
]
