"""First-party runtime observability (reference deviation: the reference
delegates ALL instrumentation to external tools — perun around its
benchmark scripts, nothing inside the library).

Three pieces, one import surface:

- :mod:`~heat_tpu.observability.telemetry` — process-wide counters,
  timers (p50/p95) and the ``record()`` context manager; zero-cost when
  disabled, ``HEAT_TPU_TELEMETRY=1`` or ``enable()`` to activate. Also
  exposed as the ``ht.telemetry`` shorthand.
- :mod:`~heat_tpu.observability.events` — bounded structured event log
  fed by the hooks in ``core/`` (shard/reshard bytes, program-cache
  misses, ``ht.jit`` traces).
- :mod:`~heat_tpu.observability.hlo` — :func:`collective_counts`, the
  compile-only HLO inspector pinning each op's collective structure
  (the public form of the MULTICHIP dryrun asserts).

Instrumentation glue for the core layers lives in
:mod:`~heat_tpu.observability.instrument` (not re-exported).
"""

from . import events
from . import hlo
from . import instrument
from . import telemetry

from .hlo import COLLECTIVE_OPS, CollectiveReport, collective_counts
from .telemetry import (
    disable,
    enable,
    enabled,
    export_jsonl,
    inc,
    observe,
    record,
    report,
    reset,
    snapshot,
)

__all__ = [
    "COLLECTIVE_OPS",
    "CollectiveReport",
    "collective_counts",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "inc",
    "observe",
    "record",
    "report",
    "reset",
    "snapshot",
]
