"""Rounding and sign operations.

API parity with /root/reference/heat/core/rounding.py (11 exports).
"""

from __future__ import annotations

import jax.numpy as jnp

from typing import Optional, Union

from . import _operations
from . import types
from .dndarray import DNDarray

__all__ = [
    "abs",
    "absolute",
    "ceil",
    "clip",
    "fabs",
    "floor",
    "modf",
    "round",
    "sgn",
    "sign",
    "trunc",
]


def abs(x, out=None, dtype=None) -> DNDarray:
    """Elementwise absolute value (reference: rounding.py abs)."""
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    result = _operations.__local_op(jnp.abs, x, out, no_cast=True)
    if dtype is not None and result.dtype != dtype:
        result = result.astype(dtype, copy=out is None)
    return result


absolute = abs


def ceil(x: DNDarray, out=None) -> DNDarray:
    """Elementwise ceiling."""
    return _operations.__local_op(jnp.ceil, x, out)


def clip(x: DNDarray, min=None, max=None, out=None) -> DNDarray:
    """Clip values to [min, max] (reference: rounding.py clip requires at
    least one bound)."""
    if min is None and max is None:
        raise ValueError("clip requires at least one of min or max")
    if isinstance(min, DNDarray):
        min = min.larray
    if isinstance(max, DNDarray):
        max = max.larray
    return _operations.__local_op(jnp.clip, x, out, no_cast=True, min=min, max=max)


def fabs(x: DNDarray, out=None) -> DNDarray:
    """Float absolute value (casts exact types to float)."""
    return _operations.__local_op(jnp.abs, x, out, no_cast=False)


def floor(x: DNDarray, out=None) -> DNDarray:
    """Elementwise floor."""
    return _operations.__local_op(jnp.floor, x, out)


def modf(x: DNDarray, out=None):
    """Fractional and integral parts (reference: rounding.py modf)."""
    from .sanitation import sanitize_in

    sanitize_in(x)
    frac, integ = jnp.modf(x.larray.astype(types.promote_types(x.dtype, types.float32).jax_type()))
    comm, device, split = x.comm, x.device, x.split
    res_t = types.canonical_heat_type(frac.dtype)
    f = DNDarray(comm.shard(frac, split) if split is not None else frac, x.shape, res_t, split, device, comm)
    i = DNDarray(comm.shard(integ, split) if split is not None else integ, x.shape, res_t, split, device, comm)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a 2-tuple of DNDarrays")
        out[0].larray = f.larray
        out[1].larray = i.larray
        return out
    return f, i


def round(x: DNDarray, decimals: int = 0, out=None, dtype=None) -> DNDarray:
    """Round to ``decimals`` (reference: rounding.py round)."""
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    result = _operations.__local_op(jnp.round, x, out, decimals=decimals)
    if dtype is not None and result.dtype != dtype:
        result = result.astype(dtype, copy=out is None)
    return result


def sgn(x: DNDarray, out=None) -> DNDarray:
    """Elementwise sign (complex: x/|x|)."""
    return _operations.__local_op(jnp.sign, x, out, no_cast=True)


def _sign_complex(a):
    # module-level: a per-call lambda would defeat the cached-jit layer
    return jnp.sign(jnp.real(a)).astype(a.dtype)


def sign(x: DNDarray, out=None) -> DNDarray:
    """Elementwise sign; for complex input the sign of the real part
    (reference: rounding.py sign follows numpy)."""
    if types.heat_type_is_complexfloating(x.dtype):
        return _operations.__local_op(_sign_complex, x, out, no_cast=True)
    return _operations.__local_op(jnp.sign, x, out, no_cast=True)


def trunc(x: DNDarray, out=None) -> DNDarray:
    """Truncate toward zero."""
    return _operations.__local_op(jnp.trunc, x, out)


DNDarray.abs = abs
DNDarray.ceil = ceil
DNDarray.clip = clip
DNDarray.fabs = fabs
DNDarray.floor = floor
DNDarray.round = round
DNDarray.trunc = trunc
