"""Parallel I/O: HDF5, netCDF, CSV.

API parity with /root/reference/heat/core/io.py (``load`` :671 dispatching
by extension :1082-1133, ``load_hdf5`` :57, ``save_hdf5`` :166,
``load_csv`` :722, ``save_csv`` :948, ``supports_hdf5``/``supports_netcdf``).
The reference reads per-rank hyperslabs (each rank its ``comm.chunk``); a
single controller reads the file once and lays the array onto the mesh —
in multi-process mode each host reads its slab and the global array is
assembled via ``jax.make_array_from_process_local_data``. netCDF support
is gated on the library being present (same as the reference).
"""

from __future__ import annotations

import os
import csv as _csv

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple, Union

from . import types
from .communication import Communication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = ["load", "load_csv", "save_csv", "save", "supports_hdf5", "supports_netcdf"]

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4

    __NETCDF = True
except ImportError:
    __NETCDF = False


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference: io.py supports_hdf5)."""
    return __HDF5


def supports_netcdf() -> bool:
    """True if netCDF I/O is available (reference: io.py supports_netcdf)."""
    return __NETCDF


def _from_numpy(data: np.ndarray, dtype, split, device, comm) -> DNDarray:
    from . import factories

    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


if __HDF5:
    __all__.extend(["load_hdf5", "save_hdf5"])

    def load_hdf5(
        path: str,
        dataset: str,
        dtype=types.float32,
        load_fraction: float = 1.0,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load a dataset from an HDF5 file (reference: io.py:57). The
        reference reads one hyperslab per rank; in multi-process mode we
        read one slab per host and assemble, single-controller reads once.
        """
        if not isinstance(path, str):
            raise TypeError(f"path must be str, got {type(path)}")
        if not isinstance(dataset, str):
            raise TypeError(f"dataset must be str, got {type(dataset)}")
        comm = sanitize_comm(comm)
        dtype = types.canonical_heat_type(dtype)
        with h5py.File(path, "r") as handle:
            ds = handle[dataset]
            gshape = tuple(ds.shape)
            if load_fraction < 1.0 and split is not None:
                n = int(gshape[split] * load_fraction)
                sl = [slice(None)] * len(gshape)
                sl[split] = slice(0, n)
                data = ds[tuple(sl)]
            elif jax.process_count() > 1 and split is not None:
                # per-host hyperslab read (the reference's per-rank chunk)
                raise NotImplementedError("multi-host hdf5 ingest lands with the multi-host runtime")
            else:
                data = ds[...]
        return _from_numpy(np.asarray(data), dtype, split, device, comm)

    def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
        """Save a DNDarray to HDF5 (reference: io.py:166)."""
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, got {type(data)}")
        if not isinstance(path, str):
            raise TypeError(f"path must be str, got {type(path)}")
        with h5py.File(path, mode) as handle:
            handle.create_dataset(dataset, data=data.numpy(), **kwargs)


if __NETCDF:
    __all__.extend(["load_netcdf", "save_netcdf"])

    def load_netcdf(path, variable, dtype=types.float32, split=None, device=None, comm=None, **kwargs):
        """Load a variable from a netCDF file (reference: io.py:283)."""
        with netCDF4.Dataset(path, "r") as handle:
            data = np.asarray(handle.variables[variable][...])
        return _from_numpy(data, types.canonical_heat_type(dtype), split, device, comm)

    def save_netcdf(data, path, variable, mode="w", **kwargs):
        """Save a DNDarray to netCDF (reference: io.py:366)."""
        with netCDF4.Dataset(path, mode) as handle:
            arr = data.numpy()
            dims = []
            for i, s in enumerate(arr.shape):
                name = f"{variable}_dim{i}"
                handle.createDimension(name, s)
                dims.append(name)
            var = handle.createVariable(variable, arr.dtype, tuple(dims))
            var[...] = arr


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference: io.py:722 — byte-range splits per rank;
    single controller reads once)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    dtype = types.canonical_heat_type(dtype)
    np_dtype = np.dtype(dtype.jax_type()) if dtype is not types.bfloat16 else np.float32
    data = np.genfromtxt(
        path, delimiter=sep, skip_header=header_lines, dtype=np_dtype, encoding=encoding
    )
    if data.ndim == 1:
        # genfromtxt flattens both single-column and single-row files;
        # disambiguate by counting separators in the first data line
        with open(path, encoding=encoding) as fh:
            for _ in range(header_lines):
                fh.readline()
            first = fh.readline().strip()
        ncols = first.count(sep) + 1 if first else 1
        data = data.reshape(1, -1) if ncols > 1 else data.reshape(-1, 1)
    return _from_numpy(data, dtype, split, device, comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines=None,
    sep: str = ",",
    decimals: int = -1,
    **kwargs,
) -> None:
    """Save a DNDarray to CSV (reference: io.py:948)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, got {type(data)}")
    arr = data.numpy()
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    header = "\n".join(header_lines) if header_lines else ""
    np.savetxt(path, arr, delimiter=sep, fmt=fmt, header=header, comments="")


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference: io.py:1082-1133)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        if not __HDF5:
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        if not __NETCDF:
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return load_netcdf(path, *args, **kwargs)
    if ext == ".csv":
        return load_csv(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save by file extension (reference: io.py:~1050)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        if not __HDF5:
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        if not __NETCDF:
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return save_netcdf(data, path, *args, **kwargs)
    if ext == ".csv":
        return save_csv(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")
