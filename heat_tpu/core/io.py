"""Parallel I/O: HDF5, netCDF, CSV.

API parity with /root/reference/heat/core/io.py (``load`` :671 dispatching
by extension :1082-1133, ``load_hdf5`` :57, ``save_hdf5`` :166,
``load_csv`` :722, ``save_csv`` :948, ``supports_hdf5``/``supports_netcdf``).
The reference reads per-rank hyperslabs (each rank its ``comm.chunk``); a
single controller reads one slab per device and stitches the global array
with ``jax.make_array_from_single_device_arrays`` — in multi-process mode
each host reads only its addressable devices' slabs. netCDF support is
gated on the library being present (same as the reference).
"""

from __future__ import annotations

import os
import csv as _csv

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple, Union

from . import types
from .communication import Communication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = ["load", "load_csv", "save_csv", "save", "supports_hdf5", "supports_netcdf"]

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4

    __NETCDF = True
except ImportError:
    __NETCDF = False


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference: io.py supports_hdf5)."""
    return __HDF5


def supports_netcdf() -> bool:
    """True if netCDF I/O is available (reference: io.py supports_netcdf)."""
    return __NETCDF


def _from_numpy(data: np.ndarray, dtype, split, device, comm) -> DNDarray:
    from . import factories

    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


def _np_storage_dtype(dtype) -> np.dtype:
    """On-disk numpy dtype for a framework dtype: bfloat16 has no
    HDF5/netCDF/CSV representation and is stored as float32 (exact)."""
    return np.dtype(np.float32) if dtype is types.bfloat16 else np.dtype(dtype.jax_type())


def _assemble_sharded(read_slab, gshape, dtype, split, device, comm) -> DNDarray:
    """Assemble a split DNDarray from per-device slab reads without ever
    materializing the global array on the host — the single-controller
    analog of the reference's per-rank hyperslab reads (io.py:57-150).

    ``read_slab(slices) -> np.ndarray`` reads one hyperslab from storage.
    Each device's (padded) block is read, zero-padded to the physical block
    extent, put on ITS device only, and the global jax.Array is stitched
    with ``make_array_from_single_device_arrays``.
    """
    from . import _padding
    from .devices import sanitize_device

    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    gshape = tuple(int(s) for s in gshape)
    split = sanitize_axis(gshape, split)
    jdt = _np_storage_dtype(dtype)

    if split is None:
        # replicated: every host reads the full array once
        data = np.asarray(read_slab(tuple(slice(0, s) for s in gshape)), dtype=jdt)
        return _from_numpy(data, dtype, None, device, comm)

    phys = _padding.phys_shape(gshape, split, comm.size)
    block = phys[split] // comm.size
    n = gshape[split]
    shards = []
    blk_shape = list(gshape)
    blk_shape[split] = block
    proc = jax.process_index()
    for r, dev in enumerate(comm.devices):
        if dev.process_index != proc:
            # multi-host: another host reads this slab — the reference's
            # per-rank hyperslab pattern (io.py:57); each process passes
            # only its addressable shards to make_array_from_single_device_arrays
            continue
        start = r * block
        stop = min(start + block, n)
        if stop > start:
            sl = tuple(
                slice(start, stop) if i == split else slice(0, s) for i, s in enumerate(gshape)
            )
            slab = np.asarray(read_slab(sl), dtype=jdt)
            if slab.shape[split] < block:
                widths = [(0, 0)] * len(gshape)
                widths[split] = (0, block - slab.shape[split])
                slab = np.pad(slab, widths)
        else:
            slab = np.zeros(tuple(blk_shape), dtype=jdt)
        if dtype is types.bfloat16:
            slab = slab.astype(jnp.bfloat16)
        shards.append(jax.device_put(slab, dev))
    arr = jax.make_array_from_single_device_arrays(tuple(phys), comm.sharding(len(gshape), split), shards)
    return DNDarray(arr, gshape, dtype, split, device, comm)


def _multiprocess_gather_for_save(data: DNDarray):
    """Multi-writer safety for saves (plain h5py/netCDF4 handles must not
    write one file from several processes concurrently — the reference
    relies on parallel drivers we don't have: h5py ``driver='mpio'``
    (reference io.py:214) and netCDF4 ``parallel=True`` (io.py:585); a
    plain multi-writer 'w' open truncates per process and corrupts).

    FULL-array gather — every host materializes the whole array. Kept
    only for the netCDF append-region path, whose target geometry cannot
    be decomposed into split-blocks; the main save paths stream bounded
    slabs via ``_multiprocess_save_slabs`` instead (ADVICE r3: the full
    allgather OOMs hosts at the 200 GB north-star scale).

    Returns ``(is_multiprocess, host_array_or_None)``.
    """
    if jax.process_count() == 1:
        return False, None
    arr = data.numpy()  # collective cross-process allgather
    if data.dtype is types.bfloat16:
        arr = np.asarray(arr, dtype=np.float32)
    return True, np.asarray(arr)


def _multiprocess_save_slabs(data: DNDarray):
    """Yield ``(global_slices, host_block)`` for a single-writer
    multi-process save with BOUNDED host memory: ONE split-block is
    allgathered per round (a collective — every process must drain the
    iterator, in step), never the whole array. Only process 0 should
    write the yielded slabs; other processes receive them too (the
    allgather is symmetric) and drop them immediately."""
    from jax.experimental import multihost_utils

    arr = data._phys
    # bf16 upcasts PER SLAB (below) — an up-front astype of the global
    # array would materialize a full-size f32 copy across HBM, defeating
    # the bounded-memory point of the streaming
    cast = data.dtype is types.bfloat16
    split = data.split
    if split is None or arr.is_fully_addressable:
        host = np.asarray(jax.device_get(arr))
        if cast:
            host = host.astype(np.float32)
        if host.shape != tuple(data.shape):
            host = host[tuple(slice(0, s) for s in data.shape)]
        yield tuple(slice(0, s) for s in data.shape), host
        return
    n = data.shape[split]
    block = arr.shape[split] // data.comm.size
    for r in range(data.comm.size):
        start = r * block
        stop = min(start + block, n)
        if stop <= start:
            continue
        idx = [slice(None)] * data.ndim
        idx[split] = slice(start, stop)
        slab = arr[tuple(idx)]  # global slice of the sharded array
        if cast:
            slab = slab.astype(jnp.float32)  # one block, bounded
        host = np.asarray(multihost_utils.process_allgather(slab, tiled=True))
        sl = tuple(
            slice(start, stop) if i == split else slice(0, s)
            for i, s in enumerate(data.shape)
        )
        yield sl, host[tuple(slice(0, s.stop - s.start) for s in sl)]


def _drain(slab_iter) -> None:
    """Finish a collective slab stream unconditionally — every process
    must participate in every per-slab allgather even when the WRITER
    fails mid-stream (an undrained iterator would leave the other
    processes blocked inside process_allgather while the writer's
    exception never propagates)."""
    for _ in slab_iter:
        pass


def _sync_processes(tag: str) -> None:
    """Cross-process barrier so no host proceeds past a save before the
    writer (process 0) has finished — the analog of the reference's
    trailing ``comm.Barrier()`` in its rank-ordered write loops."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _write_shards(data: DNDarray, write_slab) -> None:
    """Write a DNDarray shard-by-shard: ``write_slab(global_slices,
    host_block)`` receives each device's LOGICAL block — the global array is
    never gathered (the reference's rank-ordered writes, io.py:166-260)."""
    if data.split is None:
        arr = data._phys
        if data.dtype is types.bfloat16:
            arr = arr.astype(jnp.float32)
        write_slab(tuple(slice(0, s) for s in data.shape), np.asarray(jax.device_get(arr)))
        return
    split = data.split
    n = data.shape[split]
    block = data._phys.shape[split] // data.comm.size
    for r in range(data.comm.size):
        start = r * block
        stop = min(start + block, n)
        if stop <= start:
            continue
        shard = None
        for s in data._phys.addressable_shards:
            # single-device/replicated shards carry slice(None) indices
            s_start = s.index[split].start if s.index[split].start is not None else 0
            if s_start == start:
                shard = s.data
                break
        if shard is None:
            if jax.process_count() == 1:
                raise RuntimeError(
                    f"no addressable shard found for block {r} (start {start}) — "
                    f"shard indices: {[s.index for s in data._phys.addressable_shards]}"
                )
            continue  # non-addressable in multi-process; another host writes it
        valid = [slice(None)] * data.ndim
        valid[split] = slice(0, stop - start)
        host = np.asarray(jax.device_get(shard[tuple(valid)]))
        if data.dtype is types.bfloat16:
            host = host.astype(np.float32)
        sl = tuple(
            slice(start, stop) if i == split else slice(0, s) for i, s in enumerate(data.shape)
        )
        write_slab(sl, host)


if __HDF5:
    __all__.extend(["load_hdf5", "save_hdf5"])

    def load_hdf5(
        path: str,
        dataset: str,
        dtype=types.float32,
        load_fraction: float = 1.0,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load a dataset from an HDF5 file (reference: io.py:57). The
        reference reads one hyperslab per rank; in multi-process mode we
        read one slab per host and assemble, single-controller reads once.
        """
        if not isinstance(path, str):
            raise TypeError(f"path must be str, got {type(path)}")
        if not isinstance(dataset, str):
            raise TypeError(f"dataset must be str, got {type(dataset)}")
        comm = sanitize_comm(comm)
        dtype = types.canonical_heat_type(dtype)
        with h5py.File(path, "r") as handle:
            ds = handle[dataset]
            gshape = list(ds.shape)
            if load_fraction < 1.0 and split is not None:
                gshape[split] = int(gshape[split] * load_fraction)
            return _assemble_sharded(
                lambda sl: ds[sl], tuple(gshape), dtype, split, device, comm
            )

    def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
        """Save a DNDarray to HDF5 (reference: io.py:166). Single-process:
        one hyperslab write per device shard, global array never gathered.
        Multi-process: collective allgather + single-writer (process 0) —
        see ``_multiprocess_gather_for_save``."""
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, got {type(data)}")
        if not isinstance(path, str):
            raise TypeError(f"path must be str, got {type(path)}")
        np_dtype = kwargs.pop("dtype", _np_storage_dtype(data.dtype))  # h5py casts on write
        if jax.process_count() > 1:
            # bounded-memory single-writer: stream one split-block per
            # collective round (see _multiprocess_save_slabs)
            slabs = _multiprocess_save_slabs(data)
            if jax.process_index() == 0:
                try:
                    with h5py.File(path, mode) as handle:
                        ds = handle.create_dataset(
                            dataset, shape=data.shape, dtype=np_dtype, **kwargs
                        )
                        for sl, host in slabs:
                            ds[sl] = host
                finally:
                    _drain(slabs)  # keep collectives in step on writer error
            else:
                _drain(slabs)  # collective participation, nothing kept
            _sync_processes("heat_tpu.io.save_hdf5")
            return
        with h5py.File(path, mode) as handle:
            ds = handle.create_dataset(dataset, shape=data.shape, dtype=np_dtype, **kwargs)
            _write_shards(data, lambda sl, host: ds.__setitem__(sl, host))


if __NETCDF:
    __all__.extend(["load_netcdf", "save_netcdf"])

    def load_netcdf(path, variable, dtype=types.float32, split=None, device=None, comm=None, **kwargs):
        """Load a variable from a netCDF file (reference: io.py:283 — one
        hyperslab per rank). Split loads read one slab per device; the
        global array is never materialized on the host."""
        with netCDF4.Dataset(path, "r") as handle:
            var = handle.variables[variable]
            gshape = tuple(var.shape)
            return _assemble_sharded(
                lambda sl: np.asarray(var[sl]),
                gshape,
                types.canonical_heat_type(dtype),
                split,
                device,
                comm,
            )

    def save_netcdf(
        data,
        path,
        variable,
        mode="w",
        dimension_names=None,
        is_unlimited=False,
        file_slices=slice(None),
        **kwargs,
    ):
        """Save a DNDarray to netCDF (reference: io.py:366).

        ``mode``: 'w' truncates, 'a'/'r+' opens for update. Appending
        along a dimension (the reference's time-series pattern) works by
        creating the variable once with ``is_unlimited=True`` and then
        writing subsequent steps with ``mode='r+'`` and ``file_slices``
        addressing the new region, e.g. ``file_slices=slice(t, t+1)``.
        """
        if mode not in ("w", "a", "r+"):
            raise ValueError(f"mode must be one of 'w', 'a', 'r+', got {mode!r}")
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, got {type(data)}")
        np_dtype = _np_storage_dtype(data.dtype)
        if dimension_names is None:
            dims = [f"{variable}_dim{i}" for i in range(data.ndim)]
        elif isinstance(dimension_names, str):
            dims = [dimension_names]
        else:
            dims = list(dimension_names)
        if len(dims) != data.ndim:
            raise ValueError(
                f"{len(dims)} dimension names given for {data.ndim} dimensions"
            )
        multi = jax.process_count() > 1
        trivial = (
            file_slices == slice(None)
            or file_slices is Ellipsis
            or (
                isinstance(file_slices, tuple)
                and all(s == slice(None) or s is Ellipsis for s in file_slices)
            )
        )
        host_arr = None
        if multi and trivial:
            slabs = _multiprocess_save_slabs(data)  # bounded-memory stream
        elif multi:
            # append-region addressing: the caller's target geometry does
            # not decompose into split-blocks — full gather (whole-array
            # host memory; appends along an unlimited dim are small)
            _, host_arr = _multiprocess_gather_for_save(data)
        if multi and jax.process_index() != 0:
            # drain the collective slab stream; only process 0 opens the
            # file (plain netCDF4 handles are not multi-writer safe —
            # reference uses parallel=True, io.py:585)
            if trivial:
                _drain(slabs)
            _sync_processes("heat_tpu.io.save_netcdf")
            return
        if multi and trivial:
            try:
                with netCDF4.Dataset(path, mode) as handle:
                    for i, name in enumerate(dims):
                        if name not in handle.dimensions:
                            handle.createDimension(name, None if is_unlimited else data.shape[i])
                    if variable in handle.variables:
                        var = handle.variables[variable]
                    else:
                        var = handle.createVariable(variable, np_dtype, tuple(dims), **kwargs)
                    for sl, host in slabs:
                        var[sl] = host
            finally:
                _drain(slabs)  # keep collectives in step on writer error
            _sync_processes("heat_tpu.io.save_netcdf")
            return
        with netCDF4.Dataset(path, mode) as handle:
            for i, name in enumerate(dims):
                if name not in handle.dimensions:
                    handle.createDimension(name, None if is_unlimited else data.shape[i])
            if variable in handle.variables:
                var = handle.variables[variable]
            else:
                var = handle.createVariable(variable, np_dtype, tuple(dims), **kwargs)
            if multi:
                var[file_slices] = host_arr
            elif trivial:
                # one hyperslab write per device shard, never gathering
                # (the reference's rank-ordered writes, io.py:366)
                _write_shards(data, lambda sl, host: var.__setitem__(sl, host))
            else:
                # append-region addressing: the target region's geometry is
                # the caller's (e.g. a new step along an unlimited dim) —
                # write it in one piece
                arr = data.numpy()
                if data.dtype is types.bfloat16:
                    arr = np.asarray(arr, dtype=np.float32)
                var[file_slices] = arr
        if multi:
            _sync_processes("heat_tpu.io.save_netcdf")


_CSV_ANCHOR_STRIDE = 256  # one recorded line-start offset per 256 lines


def _csv_data_start(path: str, header_lines: int) -> int:
    """Byte offset of the first data row (after ``header_lines`` lines)."""
    if header_lines <= 0:
        return 0
    off = 0
    with open(path, "rb") as fh:
        for _ in range(header_lines):
            line = fh.readline()
            if not line:
                break
            off += len(line)
    return off


def _csv_scan_range(path: str, start: int, stop: int, data_start: int, file_size: int):
    """Scan bytes [start, stop) of the file for line starts — each host
    touches ONLY its range (the reference's per-rank byte-range scan,
    io.py:807-830). Returns (line_count, anchors) where ``anchors``
    records the byte offset of every ``_CSV_ANCHOR_STRIDE``-th line this
    range owns (a line is owned by the range containing the newline that
    precedes it), bounding index memory at ~8 bytes per 256 lines."""
    count = 0
    anchors = []
    # the very first data row has no preceding newline; a header-only /
    # empty file (data_start == file_size) has no first row to seed
    if start == data_start and data_start < file_size:
        anchors.append(data_start)
        count = 1
    chunk_size = 1 << 22
    with open(path, "rb") as fh:
        fh.seek(start)
        pos = start
        remaining = stop - start
        while remaining > 0:
            buf = fh.read(min(chunk_size, remaining))
            if not buf:
                break
            idx = buf.find(b"\n")
            while idx >= 0:
                line_start = pos + idx + 1
                if line_start < file_size:  # trailing newline starts no row
                    if count % _CSV_ANCHOR_STRIDE == 0:
                        anchors.append(line_start)
                    count += 1
                idx = buf.find(b"\n", idx + 1)
            pos += len(buf)
            remaining -= len(buf)
    return count, anchors


def _load_csv_parallel(
    path: str, header_lines: int, sep: str, dtype, encoding: str, device, comm
) -> DNDarray:
    """Multi-process split=0 CSV ingest by byte ranges (the TPU-native
    analog of reference io.py:818-900): every host scans only its byte
    range for line starts, the tiny stride-compressed index is
    allgathered, and each host then reads exactly the byte spans that
    cover its addressable devices' row blocks. No host ever holds the
    whole file. Interior rows must be non-empty and uniform-width (the
    reference's empty-line tolerance is a torch-side repack this path
    trades for bounded memory)."""
    import io as _io

    from jax.experimental import multihost_utils

    file_size = os.path.getsize(path)
    data_start = _csv_data_start(path, header_lines)
    nproc = jax.process_count()
    p = jax.process_index()
    span = file_size - data_start
    start = data_start + p * span // nproc
    stop = data_start + (p + 1) * span // nproc
    count, anchors = _csv_scan_range(path, start, stop, data_start, file_size)

    # exchange (count, n_anchors), then the padded anchor arrays
    meta = multihost_utils.process_allgather(
        np.array([count, len(anchors)], dtype=np.int64)
    ).reshape(nproc, 2)
    counts = meta[:, 0]
    max_anchors = int(meta[:, 1].max())
    padded = np.full(max(max_anchors, 1), -1, dtype=np.int64)
    padded[: len(anchors)] = np.asarray(anchors, dtype=np.int64)
    all_anchors = multihost_utils.process_allgather(padded).reshape(nproc, -1)
    cum = np.concatenate([[0], np.cumsum(counts)])
    n_rows = int(cum[-1])

    # column count from the first data row (every host reads one line)
    with open(path, "rb") as fh:
        fh.seek(data_start)
        first = fh.readline().decode(encoding)
    n_cols = first.rstrip("\r\n").count(sep) + 1 if first.strip() else 1

    def locate(row: int) -> int:
        """Byte offset of global data row ``row``'s line start."""
        if row >= n_rows:
            return file_size
        q = int(np.searchsorted(cum, row, side="right") - 1)
        j = row - int(cum[q])
        a = j // _CSV_ANCHOR_STRIDE
        off = int(all_anchors[q, a])
        skip = j - a * _CSV_ANCHOR_STRIDE
        if skip == 0:
            return off
        with open(path, "rb") as fh:
            fh.seek(off)
            for _ in range(skip):
                fh.readline()
            return fh.tell()

    np_dtype = _np_storage_dtype(dtype)

    def read_slab(sl):
        rstart, rstop = sl[0].start or 0, sl[0].stop
        b0, b1 = locate(rstart), locate(rstop)
        with open(path, "rb") as fh:
            fh.seek(b0)
            raw = fh.read(b1 - b0)
        block = np.genfromtxt(
            _io.BytesIO(raw), delimiter=sep, dtype=np_dtype, encoding=encoding
        ).reshape(rstop - rstart, n_cols)
        return block[(slice(None),) + tuple(sl[1:])]

    return _assemble_sharded(read_slab, (n_rows, n_cols), dtype, 0, device, comm)


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference: io.py:722). split=0 in a multi-process
    world reads per-host byte ranges (see ``_load_csv_parallel``); other
    configurations parse on the controller like the reference's
    split=None/1 full-file passes (io.py:805, 925-946)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    if split not in (None, 0, 1):
        raise ValueError(f"split must be in [None, 0, 1], but is {split}")
    dtype = types.canonical_heat_type(dtype)
    if split == 0 and jax.process_count() > 1:
        return _load_csv_parallel(path, header_lines, sep, dtype, encoding, device, comm)
    np_dtype = _np_storage_dtype(dtype)
    data = np.genfromtxt(
        path, delimiter=sep, skip_header=header_lines, dtype=np_dtype, encoding=encoding
    )
    if data.ndim == 1:
        # genfromtxt flattens both single-column and single-row files;
        # disambiguate by counting separators in the first data line
        with open(path, encoding=encoding) as fh:
            for _ in range(header_lines):
                fh.readline()
            first = fh.readline().strip()
        ncols = first.count(sep) + 1 if first else 1
        data = data.reshape(1, -1) if ncols > 1 else data.reshape(-1, 1)
    return _from_numpy(data, dtype, split, device, comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines=None,
    sep: str = ",",
    decimals: int = -1,
    **kwargs,
) -> None:
    """Save a DNDarray to CSV (reference: io.py:948). Multi-process:
    single-writer (process 0) over a bounded slab stream — one
    split-block allgathered per collective round, never the whole array
    (same policy as save_hdf5)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, got {type(data)}")
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    header = "\n".join(header_lines) if header_lines else ""
    if jax.process_count() > 1:
        if data.split not in (None, 0):
            data = data.resplit(0)  # CSV appends rows; stream row blocks
        slabs = _multiprocess_save_slabs(data)
        if jax.process_index() == 0:
            try:
                with open(path, "w") as fh:
                    if header:
                        fh.write(header + "\n")
                    for _, host in slabs:
                        if host.ndim == 1:
                            host = host.reshape(-1, 1)
                        np.savetxt(fh, host, delimiter=sep, fmt=fmt, comments="")
            finally:
                _drain(slabs)  # keep collectives in step on writer error
        else:
            _drain(slabs)
        _sync_processes("heat_tpu.io.save_csv")
        return
    arr = data.numpy()
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    np.savetxt(path, arr, delimiter=sep, fmt=fmt, header=header, comments="")


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference: io.py:1082-1133)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        if not __HDF5:
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        if not __NETCDF:
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return load_netcdf(path, *args, **kwargs)
    if ext == ".csv":
        return load_csv(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save by file extension (reference: io.py:~1050)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        if not __HDF5:
            raise RuntimeError(f"hdf5 is required for file extension {ext}")
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        if not __NETCDF:
            raise RuntimeError(f"netcdf is required for file extension {ext}")
        return save_netcdf(data, path, *args, **kwargs)
    if ext == ".csv":
        return save_csv(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext}")
