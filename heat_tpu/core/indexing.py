"""Indexing functions.

API parity with /root/reference/heat/core/indexing.py (``nonzero``,
``where``). ``nonzero`` in the reference returns a split=0 result of the
local nonzero plus rank offsets (indexing.py nonzero); the output shape is
data-dependent, so it is evaluated eagerly here.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from . import _operations
from .dndarray import DNDarray
from .sanitation import sanitize_in

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of non-zero elements as an (nnz, ndim) array, split=0 when
    x is distributed (reference: indexing.py nonzero — rank-local results
    plus split offset). Distributed inputs run the gather-free per-shard
    count + balanced-compaction schedule (``parallel.distributed_nonzero``);
    the operand is never all-gathered."""
    sanitize_in(x)
    comm = x.comm
    if (
        x.split is not None
        and x.ndim > 0
        and comm.is_distributed()
        and 0 not in x.gshape  # zero-extent arrays are stored replicated
    ):
        from . import parallel as _parallel

        arr = x if x.split == 0 else x.resplit(0)
        phys, nnz = _parallel.distributed_nonzero(
            arr._phys, int(arr.gshape[0]), comm.mesh, comm.axis_name
        )
        gshape = (nnz, x.ndim)
        if nnz == 0:
            return DNDarray(comm.shard(phys, 0), gshape, types.int64, 0, x.device, comm)
        return DNDarray(phys, gshape, types.int64, 0, x.device, comm)
    idx = jnp.nonzero(x.larray)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 0 else jnp.zeros((0, 0), dtype=types.index_jax_type())
    stacked = stacked.astype(types.index_jax_type())
    split = 0 if x.split is not None else None
    gshape = tuple(int(s) for s in stacked.shape)
    if split is not None:
        stacked = x.comm.shard(stacked, split)
    return DNDarray(stacked, gshape, types.int64, split, x.device, x.comm)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary where / nonzero (reference: indexing.py where)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    sanitize_in(cond)
    x_t = x if isinstance(x, DNDarray) else None
    y_t = y if isinstance(y, DNDarray) else None
    promoted = types.result_type(x, y)
    jt = promoted.jax_type()
    xv = x.larray.astype(jt) if isinstance(x, DNDarray) else x
    yv = y.larray.astype(jt) if isinstance(y, DNDarray) else y
    result = jnp.where(cond.larray, xv, yv)
    split = cond.split
    if split is None:
        for t in (x_t, y_t):
            if t is not None and t.split is not None and t.ndim == result.ndim:
                split = t.split
                break
    gshape = tuple(int(s) for s in result.shape)
    if split is not None and split < result.ndim:
        result = cond.comm.shard(result, split)
    else:
        split = None
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), split, cond.device, cond.comm
    )
