"""Iterative solvers.

API parity with /root/reference/heat/core/linalg/solver.py (``cg`` :14,
``lanczos`` :67). Both are written *on top of* the distributed array API —
exactly like the reference — so they inherit sharding from matmul/sum; the
per-iteration collectives (dot-product all-reduces) are emitted by XLA.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Optional, Tuple

from .. import factories
from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A x = b`` (reference: solver.py:14)."""
    from . import basics

    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b, x0 need to be DNDarrays, got {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    r = b - basics.matmul(A, x0)
    p = r
    rsold = basics.matmul(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = basics.matmul(A, p)
        alpha = rsold / basics.matmul(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = basics.matmul(r, r)
        if float(jnp.sqrt(rsnew.larray)) < 1e-10:
            if out is not None:
                out.larray = x.larray
                return out
            return x
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out.larray = x.larray
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric matrix (reference:
    solver.py:67): returns (V, T) with A ≈ V T Vᵀ after m steps; feeds
    ``cluster.Spectral``.
    """
    from . import basics

    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if not isinstance(m, (int, float, np.integer)):
        raise TypeError(f"m must be int, got {type(m)}")
    m = int(m)
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")

    n = A.shape[0]
    dtype = A.dtype if types.heat_type_is_inexact(A.dtype) else types.float32

    if v0 is None:
        from .. import random as _random

        vr = _random.rand(n, split=A.split, device=A.device, comm=A.comm).astype(dtype)
        v0 = vr / basics.norm(vr)
    else:
        if v0.split != A.split:
            v0 = v0.resplit(A.split)
        v0 = v0.astype(dtype)

    # iteration state on host lists; each step is sharded device math
    alpha = np.zeros(m, dtype=np.float64)
    beta = np.zeros(m, dtype=np.float64)
    vectors = [v0]

    w = basics.matmul(A, v0)
    alpha[0] = float(basics.matmul(w, v0))
    w = w - alpha[0] * v0

    for i in range(1, int(m)):
        beta[i] = float(basics.norm(w))
        if abs(beta[i]) < 1e-10:
            # invariant subspace found: restart with a random orthogonal vector
            from .. import random as _random

            vr = _random.rand(n, split=A.split, device=A.device, comm=A.comm).astype(dtype)
            # Gram-Schmidt against previous vectors
            for v in vectors:
                vr = vr - basics.matmul(vr, v) * v
            vi = vr / basics.norm(vr)
        else:
            vi = w / beta[i]
            # full reorthogonalization against the basis so far — without it
            # the Krylov basis drifts after ~20 steps (reference
            # solver.py:245-255 Gram-Schmidts every new vector)
            for v in vectors:
                vi = vi - basics.matmul(vi, v) * v
            vi = vi / basics.norm(vi)
        vectors.append(vi)
        w = basics.matmul(A, vi)
        alpha[i] = float(basics.matmul(w, vi))
        w = w - alpha[i] * vi - beta[i] * vectors[i - 1]

    from .. import manipulations

    V = manipulations.stack(vectors, axis=1)
    T_np = np.diag(alpha) + np.diag(beta[1:], 1) + np.diag(beta[1:], -1)
    T = factories.array(T_np, dtype=dtype, comm=A.comm, device=A.device)

    if V_out is not None:
        V_out.larray = V.larray
        V = V_out
    if T_out is not None:
        T_out.larray = T.larray
        T = T_out
    return V, T
