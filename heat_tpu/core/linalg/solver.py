"""Iterative solvers.

API parity with /root/reference/heat/core/linalg/solver.py (``cg`` :14,
``lanczos`` :67). The reference iterates in Python with an MPI-synchronized
convergence check each step; on TPU that pattern costs a device→host sync
per iteration. Here each solver is ONE jitted program: ``cg`` runs a
``lax.while_loop`` whose convergence test stays on device, ``lanczos`` a
``lax.scan`` over steps with masked full reorthogonalization against the
pre-allocated Krylov basis. The per-iteration dot-product all-reduces are
emitted by XLA from the sharded matvecs — the same collectives the
reference issues explicitly.

DIRECT solves live elsewhere (ISSUE 19): ``ht.linalg.solve`` is the
blocked-triangular back-substitution over the ring Cholesky/LU factors
in :mod:`.factorizations` (re-exported at the ``ht.linalg`` root), with
``assume_a="pos"`` for s.p.d. systems — prefer it over ``cg`` when the
system is dense and factorable; ``cg`` remains the matrix-free /
iterative option.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from typing import Optional, Tuple

from .. import factories
from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["cg", "lanczos"]


@functools.lru_cache(maxsize=64)
def _cg_program(n: int, jdtype: str, maxit: int, tol: float):
    """One jitted CG solve: while_loop with on-device convergence (no
    host round trip per iteration, unlike the reference's per-step
    ``sqrt(rsnew) < tol`` Python check, solver.py:45)."""
    eps = jnp.asarray(tol, dtype=jdtype) ** 2

    def solve(A, b, x0):
        r0 = b - A @ x0
        rs0 = r0 @ r0

        def cond(state):
            i, x, r, p, rsold = state
            return (i < maxit) & (rsold >= eps)

        def step(state):
            i, x, r, p, rsold = state
            Ap = A @ p
            alpha = rsold / (p @ Ap)
            x = x + alpha * p
            r = r - alpha * Ap
            rsnew = r @ r
            p = r + (rsnew / rsold) * p
            return (i + 1, x, r, p, rsnew)

        _, x, _, _, _ = lax.while_loop(cond, step, (0, x0, r0, r0, rs0))
        return x

    return jax.jit(solve)


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A x = b`` (reference: solver.py:14)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b, x0 need to be DNDarrays, got {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    dtype = types.promote_types(
        types.promote_types(A.dtype, b.dtype),
        types.promote_types(x0.dtype, types.float32),
    )
    jt = dtype.jax_type()
    n = b.shape[0]
    prog = _cg_program(n, np.dtype(jt).name, int(n), 1e-10)
    x = prog(A.larray.astype(jt), b.larray.astype(jt), x0.larray.astype(jt))

    result = DNDarray(
        b.comm.shard(x, b.split), (n,), dtype, b.split, b.device, b.comm
    )
    if out is not None:
        out.larray = result.larray
        return out
    return result


@functools.lru_cache(maxsize=64)
def _lanczos_program(n: int, m: int, jdtype: str, breakdown_tol: float,
                     matvec=None):
    """One jitted Lanczos run: scan over the m steps; each step does the
    matvec, masked full reorthogonalization against the basis so far
    (reference solver.py:245-255 Gram-Schmidts every new vector), and a
    ``lax.cond``-free invariant-subspace restart via a select on a fresh
    random direction (reference draws a random vector on breakdown).

    ``matvec`` generalizes the operator: ``None`` keeps the dense
    ``A @ v`` (trace-identical to before the parameter existed — the
    default program is byte-for-byte the same); otherwise ``A`` may be
    any jit-flattenable pytree of operator components and each step
    applies ``matvec(A, v)`` (graph/spectral.py passes the DBCSR
    Laplacian this way). Callables hash by identity, so callers must
    pass a cached/module-level function, not a fresh lambda per call."""
    tol = breakdown_tol
    mv = (lambda A, x: A @ x) if matvec is None else matvec

    # inner products are CONJUGATED (x^H y) so the same program is the
    # hermitian-Lanczos on native complex inputs (CPU/GPU worlds); on
    # real dtypes conj is the identity and the recursion is unchanged.
    # Norms take .real — v^H v is real by construction, and the sqrt
    # must not promote through a complex dtype.
    def run(A, v0, key):
        V0 = jnp.zeros((n, m), dtype=jdtype).at[:, 0].set(v0)
        w0 = mv(A, v0)
        a0 = jnp.conj(v0) @ w0
        w0 = w0 - a0 * v0
        alpha0 = jnp.zeros((m,), dtype=jdtype).at[0].set(a0)
        beta0 = jnp.zeros((m,), dtype=jdtype)

        def step(carry, i):
            V, w, alpha, beta = carry
            b_i = jnp.sqrt((jnp.conj(w) @ w).real)
            invariant = b_i < tol
            # normal candidate (safe divide) vs random restart direction
            vi = jnp.where(invariant, jax.random.normal(jax.random.fold_in(key, i), (n,), dtype=jdtype), w / jnp.where(invariant, 1.0, b_i).astype(jdtype))
            # full reorthogonalization against columns < i (masked)
            proj = jnp.conj(V).T @ vi
            proj = jnp.where(jnp.arange(m) < i, proj, 0.0)
            vi = vi - V @ proj
            vi = vi / jnp.sqrt((jnp.conj(vi) @ vi).real).astype(jdtype)
            V = lax.dynamic_update_slice_in_dim(V, vi[:, None], i, axis=1)
            w = mv(A, vi)
            a_i = jnp.conj(vi) @ w
            v_prev = lax.dynamic_slice_in_dim(V, i - 1, 1, axis=1)[:, 0]
            w = w - a_i * vi - b_i.astype(jdtype) * v_prev
            alpha = alpha.at[i].set(a_i)
            beta = beta.at[i].set(b_i)
            return (V, w, alpha, beta), None

        (V, _, alpha, beta), _ = lax.scan(step, (V0, w0, alpha0, beta0), jnp.arange(1, m))
        return V, alpha, beta

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _tridiag_program(m: int, jdtype: str):
    """(alpha, beta) -> tridiagonal T, on device (no host round trip)."""

    @jax.jit
    def build(alpha, beta):
        return (
            jnp.diag(alpha)
            + jnp.diag(beta[1:], 1)
            + jnp.diag(beta[1:], -1)
        ).astype(jdtype)

    return build


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric matrix (reference:
    solver.py:67): returns (V, T) with A ≈ V T Vᵀ after m steps; feeds
    ``cluster.Spectral``.
    """
    from . import basics

    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if not isinstance(m, (int, float, np.integer)):
        raise TypeError(f"m must be int, got {type(m)}")
    m = int(m)
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")

    n = A.shape[0]
    dtype = A.dtype if types.heat_type_is_inexact(A.dtype) else types.float32
    jt = dtype.jax_type()

    if v0 is None:
        from .. import random as _random

        vr = _random.rand(n, split=A.split, device=A.device, comm=A.comm).astype(dtype)
        v0 = vr / basics.norm(vr)
    else:
        if v0.split != A.split:
            v0 = v0.resplit(A.split)
        v0 = v0.astype(dtype)

    if m == 1:
        w = basics.matmul(A, v0)
        # conjugated inner product (v0^H A v0) via the .numpy() host
        # funnel: native complex inputs keep their (real-valued, but
        # complex-typed) Rayleigh quotient instead of crashing in
        # float(); real inputs are numerically unchanged
        a0 = np.asarray(basics.vdot(v0, w).numpy())
        alpha = np.array([a0])
        beta = np.zeros(1, dtype=alpha.real.dtype)
        V_arr = v0.larray[:, None]
        T_np = np.diag(alpha) + np.diag(beta[1:], 1) + np.diag(beta[1:], -1)
        T_arr = None
    else:
        prog = _lanczos_program(n, m, np.dtype(jt).name, 1e-10)
        # breakdown-restart directions come from a dedicated fixed stream:
        # drawing from the global heat stream here would (a) consume
        # randomness even in the common no-breakdown case — perturbing any
        # seeded pipeline relative to the reference, which only draws ON
        # breakdown — and (b) block on a ~90 ms host read-back per call
        key = jax.random.key(0x1A2C05)
        V_arr, alpha_d, beta_d = prog(A.larray.astype(jt), v0.larray, key)
        # T assembles ON DEVICE: a host device_get of alpha/beta here would
        # cost a blocking ~100 ms round trip per call over the remote
        # tunnel (and a sync the reference's torch path does not pay)
        T_arr = _tridiag_program(m, np.dtype(jt).name)(alpha_d, beta_d)

    V = DNDarray(
        A.comm.shard(V_arr, A.split if A.split in (0, None) else 0),
        (n, m),
        dtype,
        A.split if A.split in (0, None) else 0,
        A.device,
        A.comm,
    )
    if T_arr is None:
        T = factories.array(T_np, dtype=dtype, comm=A.comm, device=A.device)
    else:
        T = DNDarray(
            A.comm.shard(T_arr, None), (m, m), dtype, None, A.device, A.comm
        )

    if V_out is not None:
        V_out.larray = V.larray
        V = V_out
    if T_out is not None:
        T_out.larray = T.larray
        T = T_out
    return V, T
