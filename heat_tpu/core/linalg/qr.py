"""Distributed QR decomposition.

API parity with /root/reference/heat/core/linalg/qr.py (``qr`` at qr.py:17:
tiled CAQR on ``SquareDiagTiles`` — per-tile-column local torch QR plus
Householder merges of tile rows across ranks, ``__split0_r_calc`` :314,
``__split0_merge_tile_rows`` :482, ``__split0_q_loop`` :667; split=1 panel
broadcast loop ``__split1_qr_loop`` :858).

TPU-native redesign: the split=0 tall-skinny case is **TSQR**
(communication-avoiding QR — the same algorithm family the reference's
CAQR cites at qr.py:49-58) expressed as ONE ``shard_map``:

    per-shard local QR  →  all_gather of the tiny R factors
    →  merge QR of the stacked R's  →  local Q update (MXU matmul)

One grouped-all-gather level at small meshes (p·n² floats); composite
meshes of 16+ devices run a TWO-LEVEL group tree — two grouped
all-gathers carrying (s + p/s)·n² floats (see ``_tsqr_fn``) — everything
else is local MXU work, the whole thing one XLA program. The reference's
``tiles_per_proc`` knob tuned CPU cache blocking; XLA tiles for the MXU
itself, so the knob is accepted for API parity and ignored.

Pad-safety: TSQR runs on the physical (zero-padded) array — zero rows
contribute zero R rows, so R is exact; Q's pad rows are re-masked to zero
afterwards (see ``_padding``).
"""

from __future__ import annotations

import collections
import functools

import numpy as np

import jax
import jax.numpy as jnp

from jax.sharding import NamedSharding, PartitionSpec
from typing import Optional, Tuple, Union

from .. import types
from .. import _padding
from .._jax_compat import shard_map as _shard_map
from ..communication import MeshCommunication
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def _tsqr_group_size(p: int) -> int:
    """Group width for the two-level merge: the largest divisor of p not
    exceeding √p (1 when p is prime — single-level)."""
    best = 1
    s = 2
    while s * s <= p:
        if p % s == 0:
            best = s
        s += 1
    return best


def _tsqr_grouping(p: int, topo=None) -> int:
    """Level-1 group width ``s`` of the TSQR merge tree (1 = flat
    single-level). At a TIERED topology (ISSUE 8) the tree groups
    SLICE-MAJOR: ``s = chips_per_slice``, so every level-0/1 merge —
    the gathers that carry ``s·K²`` bytes per member — stays inside one
    ICI domain, and only the ``n_slices`` group-R factors (``G·K²``
    bytes) cross DCN at level 2. The two-level tree then engages at ANY
    tiered mesh width, not just ≥ 16: crossing DCN with the full
    ``p·K²`` flat gather would pay the ~8× tier penalty on ``(p-1)/p``
    of the bytes for no reason. Flat topologies keep the pre-ISSUE-8
    rule (√p divisor grouping from 16 devices up) so every pinned
    census holds verbatim."""
    if topo is not None:
        S, C = topo
        if S > 1 and C > 1 and S * C == p:
            return C
    return _tsqr_group_size(p) if p >= _TSQR_TWO_LEVEL_MIN_P else 1


# single-level at small meshes (the merge term is noise there and the HLO
# contract stays one all-gather); two-level from this width up
_TSQR_TWO_LEVEL_MIN_P = 16


def _tsqr_ring_active() -> bool:
    """Does TSQR run its collective-matmul merge — the R-factor
    all-gather decomposed into a ppermute ring whose landed blocks are
    stacked as they arrive (``kernels.cmatmul.ring_all_gather``)? Gated
    by ``HEAT_TPU_REDIST_OVERLAP`` (forced by ``=1``, off at ``=0``,
    TPU-only under ``auto``); the assembled stack is element-identical
    to the all-gather's, so Q/R are bit-identical either way."""
    from ...kernels import cmatmul as _cm

    return _cm.ring_enabled()


@functools.lru_cache(maxsize=128)
def _tsqr_fn(
    mesh, axis_name: str, lrows: int, cols: int, jdtype: str, calc_q: bool,
    ring: bool = False, topo=None,
):
    """Compiled TSQR over the mesh for physical shard shape (lrows, cols).

    p < 16 (or prime p): the flat schedule — ONE all-gather of the p R
    factors, one stacked merge QR. p ≥ 16 with a divisor s ≤ √p: the
    TWO-LEVEL tree (docs/PERF.md names the flat merge's (p·r)² growth as
    the mesh-width wall) — R factors all-gather WITHIN each of the p/s
    groups (s·K² bytes), each group merges to a group-R, the p/s group-Rs
    all-gather ACROSS groups (p/s·K² bytes), one final merge: ICI bytes
    and replicated merge FLOPs drop from p·K² / p·K³ to
    (s + p/s)·K² / (s + p/s)·K³ — 4× at p=64, 8× at p=256, exactly the
    point PERF's model said a two-level tree becomes necessary. Q update
    composes the two tiny block factors: Q = Q_local · Q2[j] · Q3[g].

    ``ring=True`` (the collective-matmul form, ISSUE 6): each gather —
    flat, and both levels of the tree — runs as a ppermute ring that
    stacks blocks as they land instead of after the all-gather barrier,
    overlapping the assembly copies (and, on TPU, the local QR epilogue)
    with the wire. Byte-equivalent movement ((size-1)·K·cols per level),
    identical merge inputs, bit-identical Q/R.

    ``topo=(S, C)`` (ISSUE 8): slice-major grouping — level-1 groups
    are exactly the slices (``s = C``), so the heavy gathers never
    cross DCN and only the tiny cross-group gather (G = n_slices
    group-Rs) rides the expensive tier."""
    p = mesh.devices.size
    s = _tsqr_grouping(p, topo)
    two_level = s > 1
    from ...kernels import cmatmul as _cm

    def ring_gather(x, size, pos, perm):
        # only called from the ring branches below
        with _cm.stamp_scope("tsqr"):
            return _cm.ring_all_gather(x, axis_name, size, pos, perm, pipelined=True)

    def kernel(a):
        # a: local shard (lrows, cols)
        q1, r1 = jnp.linalg.qr(a, mode="reduced")
        k = q1.shape[1]
        if not two_level:
            i = jax.lax.axis_index(axis_name)
            if ring:
                # the complete flat p-ring (one source/target per device
                # — the SL502 congruence contract, built in one place)
                rs = ring_gather(r1, p, i, _cm.grouped_ring_perm(1, p))
            else:
                rs = jax.lax.all_gather(r1, axis_name)  # (p, k, cols)
            q2, r = jnp.linalg.qr(rs.reshape(-1, rs.shape[-1]), mode="reduced")
            if not calc_q:
                return r
            q2_i = jax.lax.dynamic_slice_in_dim(q2, i * k, k)
            return q1 @ q2_i, r

        G = p // s
        i = jax.lax.axis_index(axis_name)
        g = i // s   # group id
        j = i % s    # position within group
        # level 1: gather the s member R's within each group
        if ring:
            rs1 = ring_gather(r1, s, j, _cm.grouped_ring_perm(G, s))
        else:
            groups1 = [[gg * s + jj for jj in range(s)] for gg in range(G)]
            rs1 = jax.lax.all_gather(r1, axis_name, axis_index_groups=groups1)
        q2, r_g = jnp.linalg.qr(rs1.reshape(-1, rs1.shape[-1]), mode="reduced")
        k2 = q2.shape[1]
        # level 2: every group's R_g is replicated within the group, so
        # gathering across same-j columns hands every device all G of them
        if ring:
            rs2 = ring_gather(r_g, G, g, _cm.grouped_ring_perm(G, s, across=True))
        else:
            groups2 = [[gg * s + jj for gg in range(G)] for jj in range(s)]
            rs2 = jax.lax.all_gather(r_g, axis_name, axis_index_groups=groups2)
        q3, r = jnp.linalg.qr(rs2.reshape(-1, rs2.shape[-1]), mode="reduced")
        if not calc_q:
            return r
        q2_j = jax.lax.dynamic_slice_in_dim(q2, j * k, k)
        q3_g = jax.lax.dynamic_slice_in_dim(q3, g * k2, k2)
        return q1 @ (q2_j @ q3_g), r

    in_specs = PartitionSpec(axis_name, None)
    if calc_q:
        out_specs = (PartitionSpec(axis_name, None), PartitionSpec(None, None))
    else:
        out_specs = PartitionSpec(None, None)
    return jax.jit(
        _shard_map(
            kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """QR decomposition of a 2-D DNDarray (reference: qr.py:17).

    Returns ``QR(Q, R)`` with Q orthonormal and R upper-triangular
    (``QR(None, R)`` when ``calc_q=False``). split=0 runs TSQR over the
    mesh; split=1/None run XLA's QR on the (sharded) global array.
    ``tiles_per_proc`` is accepted for reference-API parity; XLA performs
    its own MXU tiling.
    """
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-dimensional array, got {a.ndim}")
    if not isinstance(calc_q, bool):
        raise TypeError(f"calc_q must be a bool, got {type(calc_q)}")
    if not isinstance(tiles_per_proc, (int, np.integer)) or isinstance(tiles_per_proc, bool):
        raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    if tiles_per_proc != 1:
        import warnings

        # reference code tunes this against CPU cache blocking; here XLA
        # owns MXU tiling — a silent no-op would surprise ported callers
        warnings.warn(
            "tiles_per_proc is accepted for reference-API parity but has no "
            "effect: XLA performs its own MXU tiling (TSQR replaces tiled CAQR)",
            UserWarning,
            stacklevel=2,
        )
    if not isinstance(overwrite_a, bool):
        raise TypeError(f"overwrite_a must be a bool, got {type(overwrite_a)}")

    dtype = a.dtype
    if types.heat_type_is_exact(dtype):
        dtype = types.float32
    jt = dtype.jax_type()
    m, n = a.shape
    comm: MeshCommunication = a.comm

    # TSQR applies to tall matrices (m >= n): the stacked R merge is then a
    # strict reduction and R comes out (n, n); wide matrices take the
    # gathered XLA path
    use_tsqr = a.split == 0 and comm.is_distributed() and m >= n and n <= 4096

    if use_tsqr:
        phys = a._phys.astype(jt)
        lrows = phys.shape[0] // comm.size
        topo_t = comm.topology
        fn = _tsqr_fn(
            comm.mesh, comm.axis_name, lrows, n, np.dtype(jt).name, calc_q,
            ring=_tsqr_ring_active(),
            topo=(topo_t.n_slices, topo_t.chips_per_slice) if topo_t.tiered else None,
        )
        if calc_q:
            q_phys, r = fn(phys)
            # restore the zero-pad invariant on Q (see module docstring)
            q_phys = _padding.mask_phys(q_phys, (m, q_phys.shape[1]), 0)
            k = int(q_phys.shape[1])
            q_arr = DNDarray(q_phys, (m, k), dtype, 0, a.device, comm)
        else:
            r = fn(phys)
            q_arr = None
        r_arr = DNDarray(
            _place(r, comm.sharding(2, None)), tuple(int(s) for s in r.shape), dtype, None, a.device, comm
        )
        return QR(q_arr, r_arr)

    # split=1 / replicated: XLA QR on the logical global array (GSPMD
    # partitions the panel updates; the reference's split=1 loop at
    # qr.py:858 broadcasts panels rank-by-rank instead)
    arr = a.larray.astype(jt)
    if calc_q:
        q, r = jnp.linalg.qr(arr, mode="reduced")
        q_gshape = tuple(int(s) for s in q.shape)
        r_gshape = tuple(int(s) for s in r.shape)
        q_split = a.split
        q_arr = DNDarray(
            comm.shard(q, q_split) if q_split is not None else q,
            q_gshape,
            dtype,
            q_split,
            a.device,
            comm,
        )
        r_split = 1 if a.split == 1 else None
        r_arr = DNDarray(
            comm.shard(r, r_split) if r_split is not None else r,
            r_gshape,
            dtype,
            r_split,
            a.device,
            comm,
        )
        return QR(q_arr, r_arr)
    r = jnp.linalg.qr(arr, mode="r")
    r_gshape = tuple(int(s) for s in r.shape)
    r_split = 1 if a.split == 1 else None
    r_arr = DNDarray(
        comm.shard(r, r_split) if r_split is not None else r, r_gshape, dtype, r_split, a.device, comm
    )
    return QR(None, r_arr)


DNDarray.qr = qr

from ..communication import register_mesh_cache
from ..communication import place as _place

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_tsqr_fn)
