"""Hierarchical SVD — the north-star operation.

API parity with /root/reference/heat/core/linalg/svdtools.py (``hsvd_rank``
:31, ``hsvd_rtol`` :124, ``hsvd`` :259, ``compute_local_truncated_svd``
:477; algorithm after Iwen/Ong 2016 and Himpe/Leibner/Rave 2018). The
reference runs: transpose if split=0 (:314-318) → per-rank truncated local
SVD → a greedy Send/Recv **merge tree** over shrinking rank sets
(:346-445) → Bcast of the final U.

TPU-native redesign (same math, different schedule):

1. **Level 0** — one ``shard_map``: every device computes the truncated
   SVD of its local column block and scales ``U_loc·Σ_loc``; discarded
   energy is accumulated for the a-posteriori error bound. Output is the
   global matrix ``B = [U_1Σ_1 ∥ … ∥ U_pΣ_p]`` (m × p·r), sharded along
   columns — no host round-trip.
2. **Merge** — instead of a log-depth Send/Recv tree whose node count
   shrinks dynamically (hostile to XLA's static shapes), the merge is ONE
   TSQR of ``B`` (see ``qr.py``) followed by an SVD of the tiny
   (p·r × p·r) R factor: ``B = Q·R``, ``R = U_R Σ V^T`` ⇒ left singular
   vectors ``Q·U_R`` — one all-gather of R factors on ICI plus local MXU
   matmuls. Mathematically this *is* a single-level merge with exact
   arithmetic on the concatenated factors; the truncation error analysis
   of the reference applies unchanged. Under the
   ``HEAT_TPU_REDIST_OVERLAP`` gate the TSQR runs its collective-matmul
   form (ISSUE 6): the R-factor all-gather decomposed into a ppermute
   ring whose blocks are stacked as they land
   (``kernels.cmatmul.ring_all_gather``) — byte-equivalent movement,
   bit-identical factors, so everything below is form-agnostic.
3. rank-budget (``hsvd_rank``) truncates statically; tolerance mode
   (``hsvd_rtol``) picks the final rank from the merged spectrum on host
   (a scalar-sized transfer), keeping all array shapes static under jit.

``maxmergedim``/``no_of_merges`` tuned the reference's tree arity against
MPI message sizes; the TSQR merge has no such knob — they are accepted and
validated for API parity.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec
from typing import Optional, Tuple, Union

from .. import types
from .. import _padding
from .._jax_compat import shard_map as _shard_map
from ..communication import MeshCommunication
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ._lapack import safe_svd, svd_x32_scope

__all__ = ["hsvd", "hsvd_rank", "hsvd_rtol"]


_SKETCH_OVERSAMPLE = 10

#: fixed tile grain of the streaming sketch passes (ISSUE 11): pass 1
#: walks 512-column tiles, pass 2 512-row tiles, the one-view stream
#: 512-column tiles — ALWAYS, in-HBM and staged alike. XLA's gemm
#: kernel choice is shape-dependent (a narrow tail gemm reassociates
#: differently than the same columns inside a wide gemm — measured),
#: so the fixed grain is what makes the out-of-core staged windows
#: (``redistribution.staging``, window extents = grain multiples)
#: replay EXACTLY the in-HBM tile sequence: same-shaped dots on the
#: same data, bit-identical factors by construction. Must equal
#: ``staging.GRAIN``; arrays smaller than one tile keep the single-gemm
#: form (bit-identical to the pre-ISSUE-11 programs).
_PASS_TILE = 512


def _pass1_tiles(g, a):
    """Pass 1 of the 2-pass sketch — ``w = g @ a`` — streamed in fixed
    ``_PASS_TILE``-column tiles. Each output tile is an independent
    same-shaped dot (the contraction axis is untouched), so the result
    is identical whether the loop runs inside one in-HBM program or
    across staged host windows."""
    m, n = a.shape
    T = _PASS_TILE
    nfull = n // T
    if nfull == 0:
        return g @ a
    w0 = jnp.zeros((g.shape[0], nfull * T), dtype=a.dtype)

    def body(k, w):
        blk = jax.lax.dynamic_slice(a, (0, k * T), (m, T))
        return jax.lax.dynamic_update_slice(w, g @ blk, (0, k * T))

    w = jax.lax.fori_loop(0, nfull, body, w0)
    if n % T:
        w = jnp.concatenate([w, g @ a[:, nfull * T :]], axis=1)
    return w


def _pass2_tiles(a, qw, norm_in):
    """Pass 2 — ``z = a @ qw`` in fixed ``_PASS_TILE``-row tiles, with
    the Frobenius accumulation folded into the SAME stream when
    ``norm_in`` (the running carry) is given: the XLA fallback now
    reads A exactly twice, like the TPU fused-kernel schedule. The
    carry is an explicit argument so staged windows thread it through
    in tile order — the scalar addition sequence is identical to the
    in-HBM fori_loop and the error estimate stays bit-identical too."""
    m, n = a.shape
    T = _PASS_TILE
    nfull = m // T
    want_norm = norm_in is not None
    if nfull == 0:
        z = a @ qw
        if want_norm:
            return z, norm_in + jnp.sum(jnp.real(a * jnp.conj(a)))
        return z, None
    z0 = jnp.zeros((nfull * T, qw.shape[1]), dtype=a.dtype)
    if want_norm:
        def body(k, carry):
            z, acc = carry
            blk = jax.lax.dynamic_slice(a, (k * T, 0), (T, n))
            z = jax.lax.dynamic_update_slice(z, blk @ qw, (k * T, 0))
            return z, acc + jnp.sum(jnp.real(blk * jnp.conj(blk)))

        z, acc = jax.lax.fori_loop(0, nfull, body, (z0, norm_in))
    else:
        def body(k, z):
            blk = jax.lax.dynamic_slice(a, (k * T, 0), (T, n))
            return jax.lax.dynamic_update_slice(z, blk @ qw, (k * T, 0))

        z, acc = jax.lax.fori_loop(0, nfull, body, z0), None
    if m % T:
        tail = a[nfull * T :]
        z = jnp.concatenate([z, tail @ qw], axis=0)
        if want_norm:
            acc = acc + jnp.sum(jnp.real(tail * jnp.conj(tail)))
    return z, acc


def _oneview_tiles(g, omega, a, y_in, norm_in):
    """The one-view stream — ``w = g @ a``, ``y += a @ omega``,
    ``norm += |a|²`` from ONE read of ``a`` — in fixed
    ``_PASS_TILE``-column tiles with explicit (y, norm) carries (the
    XLA fallback used to pay three reads; now one, mirroring the fused
    TPU dual-sketch kernel's schedule). Staged host windows call this
    per window, threading the carries — same tile order, bit-identical
    sketches."""
    m, n = a.shape
    T = _PASS_TILE
    nfull = n // T
    if nfull == 0:
        w = g @ a
        y = y_in + a @ omega
        return w, y, norm_in + jnp.sum(jnp.real(a * jnp.conj(a)))
    w0 = jnp.zeros((g.shape[0], nfull * T), dtype=a.dtype)

    def body(k, carry):
        w, y, acc = carry
        blk = jax.lax.dynamic_slice(a, (0, k * T), (m, T))
        om = jax.lax.dynamic_slice(omega, (k * T, 0), (T, omega.shape[1]))
        w = jax.lax.dynamic_update_slice(w, g @ blk, (0, k * T))
        return w, y + blk @ om, acc + jnp.sum(jnp.real(blk * jnp.conj(blk)))

    w, y, acc = jax.lax.fori_loop(0, nfull, body, (w0, y_in, norm_in))
    if n % T:
        tail = a[:, nfull * T :]
        w = jnp.concatenate([w, g @ tail], axis=1)
        y = y + tail @ omega[nfull * T :]
        acc = acc + jnp.sum(jnp.real(tail * jnp.conj(tail)))
    return w, y, acc


def _needs_exact_spectrum(rtol: Optional[float]) -> bool:
    """Tight-rtol rank selection needs singular values below the sketch's
    capture floor: the σ¹-weighted range finder (``_sketched_uds_both``)
    loses directions whose σ sits near √ε·σ_max in f32 (measured: a
    1e-4·σ_max value comes back as ~1e-7), and no SVD of the projected
    factor can recover energy the basis never captured. Below rtol=1e-3
    the full-SVD path is the only spectrum the selection rule can trust
    (ADVICE r3; the reference's compute_local_truncated_svd is always a
    full SVD)."""
    return rtol is not None and float(rtol) < 1e-3


def _warn_merge_knobs(maxmergedim, no_of_merges) -> None:
    """The reference's merge-tree arity knobs tuned MPI message sizes
    (svdtools.py:346-445); the TSQR merge has no such knob. A silent
    no-op would surprise callers porting tuned reference code, so
    non-default values warn once per call site (VERDICT r2 #10)."""
    if maxmergedim is not None or (no_of_merges is not None and no_of_merges != 2):
        import warnings

        warnings.warn(
            "maxmergedim/no_of_merges are accepted for reference-API parity "
            "but have no effect: the TSQR merge (flat, or the two-level tree at composite p>=16) replaces the "
            "reference's Send/Recv merge tree",
            UserWarning,
            stacklevel=3,
        )


def _gram_orthonormalize(z):
    """Orthonormalize the columns of a tall-skinny ``z`` via two rounds of
    Gram eigen-orthonormalization (z ← z·V·Λ^{-1/2}). Unlike Cholesky-QR
    this cannot fail on (near-)rank-deficient sketches — eigh of a PSD
    Gram always succeeds and clamped near-zero directions are simply
    rotated noise columns, which the second round re-orthonormalizes.
    Cost: two reads of the SMALL z (m×l) instead of a latency-bound
    Householder sweep."""
    for _ in range(2):
        # conjugated Gram (z^H z): hermitian PSD for native complex
        # inputs too (CPU/GPU worlds); conj is the identity on reals
        gram = jnp.matmul(jnp.conj(z).T, z, precision="highest")  # (l, l) PSD
        lam, v = jnp.linalg.eigh(gram)                  # ascending
        # relative floor for rank deficiency PLUS an absolute one: an
        # all-zero block (max λ = 0) must yield rsqrt(tiny) — finite — so
        # zeros propagate as zeros instead of 0·inf = NaN
        lam = jnp.maximum(
            jnp.maximum(lam, jnp.finfo(z.dtype).eps * jnp.max(lam) * z.shape[0]),
            jnp.finfo(z.dtype).tiny,
        )
        z = jnp.matmul(z, v, precision="highest") * jax.lax.rsqrt(lam)
    return z


def _cholqr2_refine(v):
    """Re-orthonormalize a NEAR-orthonormal ``v`` by two rounds of
    Cholesky-QR: vᵀv ≈ I is perfectly conditioned, so two rounds reach
    f32 machine orthogonality, and the triangular correction R ≈ I mixes
    columns only negligibly — preserving the column↔σ_i pairing the
    U·Σ·Vᵀ contract needs (a Gram-eigh pass would rotate arbitrarily
    within the σ-clusters). The tiny ridge keeps exact-zero columns
    (σ_i = 0 truncation noise) at zero instead of NaN."""
    eye = jnp.eye(v.shape[1], dtype=v.dtype)
    for _ in range(2):
        # the MXU's default bf16 passes cap orthogonality at ~1e-3; these
        # (l×l)-contraction matmuls are free at full f32 precision.
        # Conjugated forms (v^H v = r r^H, v ← v r^{-H}) so the refine is
        # the complex Cholesky-QR on native complex inputs — an
        # unconjugated complex Gram is not hermitian and its Cholesky
        # NaNs (the pre-PR-5 hsvd split=0 complex failure mode)
        g = jnp.matmul(jnp.conj(v).T, v, precision="highest") + jnp.finfo(v.dtype).eps * eye
        r = jnp.linalg.cholesky(g)  # lower: g = r r^H
        v = jnp.conj(jax.scipy.linalg.solve_triangular(r, jnp.conj(v).T, lower=True)).T
    return v


def _sketched_uds(a_blk, keep: int, sketch_l: int, want_left: bool = True):
    """Randomized truncated SVD in TWO streaming passes over ``a_blk`` —
    the factors of the best rank-``keep`` approximation in O(m·n·l)
    instead of the O(m·n²) full SVD the reference's
    ``compute_local_truncated_svd`` (svdtools.py:477) pays for a small
    rank budget. Passes, not FLOPs, are the budget at the north-star
    size (~2.6 ms per streaming pass over the 2.1 GB shard at HBM
    speed); see ``_sketched_uds_both`` for the schedule, the Gram-eigh
    rationale, and the σ¹-vs-σ³ subspace-quality trade.

    The SVD of the projected z is taken via its (l, l) Gram matrix: XLA's
    bidiagonalization of a tall matrix is a latency-bound column loop,
    while the Gram route is one MXU matmul plus a tiny eigh — and its
    eigenvalues λ_i = σ_i² are EXACTLY the energies the truncation bound
    consumes, so the error estimate loses nothing. Only σ_i below
    ~√ε·σ_max (f32: ~3e-4·σ_max) lose relative accuracy —
    truncation-noise columns in a rank-``keep`` budget (tight-rtol rank
    selection therefore bypasses the sketch, ``_needs_exact_spectrum``).

    ``want_left`` returns U (m, keep); otherwise V (n, keep). BOTH sides
    come from the same two passes, which is how the split=0 (transposed)
    orientation serves either factor without materializing Aᵀ or paying
    the reference's ``U = A·V·Σ⁻¹`` postprocessing pass (svdtools.py:456-467).

    Returns (u (m|n, keep) orthonormal, s (keep,), err_sq (), norm_sq ())."""
    u, v, s, err_sq, norm_sq = _sketched_uds_both(
        a_blk, keep, sketch_l, "left" if want_left else "right"
    )
    return (u if want_left else v), s, err_sq, norm_sq


def _sketched_uds_both(a_blk, keep: int, sketch_l: int, want: str = "left"):
    """Core of ``_sketched_uds`` returning whichever factors ``want``
    ("left" | "right" | "both") asks for — both sides cost the same TWO
    passes; only the tiny (m|n, keep) assembly matmuls differ.

    Round-4 schedule (r3 used three passes — sketch, σ²-filtered column
    image ``z = A(gA)ᵀ``, projection ``b = qzᵀA``): the power pass is
    dropped. ``Q = orth(wᵀ)`` spans the ROW-space sketch, pass 2 projects
    ``z = A·Q``, and the Gram-eigh of z yields both factor sides:
    A ≈ (z·u_z·Σ⁻¹)·Σ·(Q·u_z)ᵀ. This is the classic HMT range finder at
    σ¹ weighting instead of the power iteration's σ³ — the documented
    quality trade (VERDICT r3 #5): exact for matrices of rank ≤ l, the
    standard (1+√(r/oversample))·σ_{r+1}-class bound otherwise, and the
    a-posteriori error estimate below stays EXACT for the returned
    factorization either way (orthonormal Q ⇒ ‖A − AQQᵀ‖² = ‖A‖² − ‖z‖²).

    Passes over A: 2 — the fused Pallas sketch+norm kernel folds the
    Frobenius pass into pass 1 on TPU, and the XLA fallback folds it
    into pass 2's tiled stream (``_pass2_tiles``; ISSUE 11 — the old
    fallback paid a third read). Bound 819/2 ≈ 410 GB/s either way.

    Both passes run the fixed-grain tiled streams (``_pass1_tiles``/
    ``_pass2_tiles``) so the out-of-core staged windows of
    ``redistribution.staging`` replay the exact same tile sequence —
    staged factors are bit-identical to in-HBM by construction.

    Returns (u|None, v|None, s, err_sq, norm_sq)."""
    m, n = a_blk.shape
    key = jax.random.key(0x5BD)  # deterministic, like the reference's SVD
    g = jax.random.normal(key, (sketch_l, m), dtype=a_blk.dtype)
    # pass 1 (+norm fused): the Pallas kernel streams each A tile through
    # VMEM once and feeds BOTH the sketch matmul and the Frobenius
    # accumulation — the tiled XLA form is the fallback and the oracle.
    norm_sq = None
    from ._pallas_sketch import sketch_with_norm

    fused = sketch_with_norm(g, a_blk)
    if fused is not None:
        w, norm_sq = fused               # pass 1 + norm in one stream
    else:
        w = _pass1_tiles(g, a_blk)       # pass 1: (l, n)
    # the range basis must span rows of w CONJUGATED (A ≈ A·Q·Q^H needs
    # Q from the row space of A, i.e. columns of A^H = conj(wᵀ) sketches)
    qw = _gram_orthonormalize(jnp.conj(w).T)  # (n, l) — small O(n·l²), no pass
    if norm_sq is None:
        # pass 2 with the Frobenius accumulation folded into the stream
        zero = jnp.zeros((), dtype=jnp.real(jnp.zeros((), a_blk.dtype)).dtype)
        z, norm_sq = _pass2_tiles(a_blk, qw, zero)
    else:
        z, _ = _pass2_tiles(a_blk, qw, None)  # pass 2: (m, l) projection
    return _projection_tail(z, qw, norm_sq, keep, want)


def _projection_tail(z, qw, norm_sq, keep: int, want: str):
    """Everything after the streaming passes of ``_sketched_uds_both``
    — Gram-eigh of the projection, factor assembly, the exact
    a-posteriori error identity. Factored out so the staged executor
    runs the IDENTICAL tail on its assembled (z, qw, norm)."""
    gram = jnp.matmul(jnp.conj(z).T, z, precision="highest")  # (l, l): λ accuracy
                                         # sets σ² quality; full f32 is free here
    lam, u_z = jnp.linalg.eigh(gram)     # ascending
    lam = jnp.maximum(lam[::-1], 0.0)    # descending energies σ²
    u_z = u_z[:, ::-1]
    lam = lam[:keep]
    s = jnp.sqrt(lam)
    u = v = None
    if want in ("left", "both"):
        inv_s = jnp.where(s > 0, 1.0 / s, 0.0)
        u = jnp.matmul(z, u_z[:, :keep], precision="highest") * inv_s  # (m, keep)
        # the Gram-eigh route loses orthogonality within σ-clusters
        # (measured up to ~5e-1 on flat spectra in f32); Cholesky-QR2
        # restores the isometry contract without rotating columns.
        # σ=0 columns stay exactly zero (truncation noise, documented).
        u = _cholqr2_refine(u)
    if want in ("right", "both"):
        # orthonormal·orthogonal — full precision keeps it at machine eps
        v = jnp.matmul(qw, u_z[:, :keep], precision="highest")  # (n, keep)
    err_sq = jnp.maximum(norm_sq - jnp.sum(lam), 0.0)
    return u, v, s, err_sq, norm_sq


_ONEVIEW_GAP = 9   # k̂ = keep + GAP column-sketch oversample (Tropp one-view)
_ONEVIEW_ERRQ = 10  # extra Ψ rows reserved for the unbiased error estimator


def _one_view_params(keep: int, cap: int, m: Optional[int] = None, n: Optional[int] = None):
    """(k̂, ℓ) for the one-view sketch, or None when it should not run:
    matrix too small for the sketch (the 4·l ≤ cap gate the 2-pass route
    mirrors), or — ON TPU, when (m, n) are given — a signature the fused
    dual kernel cannot serve (k̂/ℓ caps, tile divisibility, VMEM
    footprint): the XLA fallback streams A THREE times, strictly worse
    than the 2-pass default the caller opted out of, so single_pass
    silently reverts to 2-pass instead (code-review r5). k̂ = keep +
    oversample, ℓ = 2k̂ + 1 (Tropp's co-range width); ℓ counts only the
    B-fitting rows, the _ONEVIEW_ERRQ estimator rows ride on top."""
    k_hat = keep + _ONEVIEW_GAP
    l_row = 2 * k_hat + 1
    if 4 * (l_row + _ONEVIEW_ERRQ) > cap:
        return None
    if m is not None and n is not None and jax.default_backend() == "tpu":
        from ._pallas_sketch import dual_sketch_serviceable

        if not dual_sketch_serviceable(l_row + _ONEVIEW_ERRQ, k_hat, m, n):
            return None
    return k_hat, l_row


def _one_view_uds_both(a_blk, keep: int, k_hat: int, sketch_l: int, want: str = "left"):
    """ONE-VIEW (single-pass) randomized truncated SVD (Tropp et al.,
    'Practical sketching algorithms for low-rank matrix approximation'):
    the column sketch ``Y = AΩ`` and the row sketch ``W = ΨA`` both come
    from the SAME streaming read of A — on TPU literally one pass via the
    fused ``dual_sketch_with_norm`` Pallas kernel (w, y, and ‖A‖² from
    each tile in VMEM), so the HBM bound is 819 GB/s where the 2-pass
    schedule of ``_sketched_uds_both`` caps at 410.

    Reconstruction: Q = orth(Y); B = (ΨQ)⁺W via QR + triangular solve;
    A ≈ Q·B; Gram-eigh of B gives both factor sides (same rationale as
    the 2-pass route). Quality trade (documented, opt-in via
    ``hsvd_rank(..., single_pass=True)``): exact for rank ≤ k̂ matrices;
    on decaying spectra the constant is modestly larger than the HMT
    2-pass bound (measured 1.32× vs 1.11× optimal on i^-1.5); on
    HEAVY-TAILED / flat spectra the σ estimates absorb folded residual
    energy (up to ~10× inflation on iid Gaussian inputs) — the intended
    domain is near-low-rank data, and the default 2-pass route is the
    right tool elsewhere.

    The a-posteriori error is an UNBIASED sketched estimator, not the
    2-pass route's exact identity: _ONEVIEW_ERRQ extra Ψ rows ride the
    SAME fused pass (never used to fit B, so no selection bias) and
    E‖Ψ₂(A − QB)‖²_F = q·‖A − QB‖²_F gives the residual directly —
    this stays honest on the heavy-tailed inputs where a norm-minus-
    captured-energy estimate would clamp to a misleading zero.

    ℓ = sketch_l rows fit B; k̂ columns for Ω; ℓ ≥ 2k̂ recommended.
    Returns (u|None, v|None, s, err_sq, norm_sq)."""
    m, n = a_blk.shape
    kg, ko = jax.random.split(jax.random.key(0x5BD1))
    q_err = _ONEVIEW_ERRQ
    g = jax.random.normal(kg, (sketch_l + q_err, m), dtype=a_blk.dtype)
    omega = jax.random.normal(ko, (n, k_hat), dtype=a_blk.dtype)
    from ._pallas_sketch import dual_sketch_with_norm

    fused = dual_sketch_with_norm(g, omega, a_blk)
    if fused is not None:
        w_full, y, norm_sq = fused       # ONE stream over A
    else:
        # XLA fallback/oracle: the same one-read schedule as the fused
        # kernel, as the fixed-grain tiled stream (ISSUE 11 — it used
        # to pay three reads); the staged windows replay it carry for
        # carry, bit-identical
        zero = jnp.zeros((), dtype=jnp.real(jnp.zeros((), a_blk.dtype)).dtype)
        w_full, y, norm_sq = _oneview_tiles(
            g, omega, a_blk, jnp.zeros((m, k_hat), dtype=a_blk.dtype), zero
        )
    return _one_view_tail(w_full, y, norm_sq, g, keep, sketch_l, want)


def _one_view_tail(w_full, y, norm_sq, g, keep: int, sketch_l: int, want: str):
    """Everything after the one-view stream — Q from the column sketch,
    the (ΨQ)⁺W solve, Gram-eigh, factor assembly, the unbiased sketched
    error estimator. Factored out so the staged executor runs the
    IDENTICAL tail on its assembled (w, y, norm)."""
    q_err = _ONEVIEW_ERRQ
    w, w_err = w_full[:sketch_l], w_full[sketch_l:]
    g_err = g[sketch_l:]
    q = _gram_orthonormalize(y)          # (m, k̂) — O(m·k̂²), no pass
    psi_q = jnp.matmul(g[:sketch_l], q, precision="highest")  # (ℓ, k̂)
    qq, rr = jnp.linalg.qr(psi_q)
    # B = (ΨQ)⁺ W solved through the QR factors (Tropp's stable form);
    # conjugated adjoints keep the pseudo-inverse and Gram hermitian on
    # native complex inputs (identity on reals)
    b = jax.scipy.linalg.solve_triangular(
        rr, jnp.matmul(jnp.conj(qq).T, w, precision="highest"), lower=False
    )                                    # (k̂, n)
    gram = jnp.matmul(b, jnp.conj(b).T, precision="highest")
    lam, u_b = jnp.linalg.eigh(gram)
    lam = jnp.maximum(lam[::-1], 0.0)
    u_b = u_b[:, ::-1]
    lam = lam[:keep]
    s = jnp.sqrt(lam)
    u = v = None
    if want in ("left", "both"):
        u = jnp.matmul(q, u_b[:, :keep], precision="highest")
        # Q itself degrades when Y is rank-deficient (exact-rank inputs:
        # the Gram orthonormalization has a null space) — the same
        # CholeskyQR2 refine the 2-pass route applies restores the
        # isometry contract; σ=0 truncation-noise columns stay zero
        u = _cholqr2_refine(u)
    if want in ("right", "both"):
        inv_s = jnp.where(s > 0, 1.0 / s, 0.0)
        v = jnp.matmul(jnp.conj(b).T, u_b[:, :keep], precision="highest") * inv_s
        v = _cholqr2_refine(v)
    # unbiased residual estimate from the held-out sketch rows:
    # Ψ₂A − (Ψ₂Q)B, with the KEPT-rank reconstruction (drop tail modes)
    b_keep = jnp.matmul(
        jnp.conj(u_b[:, :keep]).T, b, precision="highest"
    )                                    # (keep, n) rank-truncated B
    pred = jnp.matmul(
        jnp.matmul(g_err, q, precision="highest") @ u_b[:, :keep],
        b_keep, precision="highest",
    )
    resid = w_err - pred
    err_sq = jnp.sum(jnp.real(resid * jnp.conj(resid))) / q_err
    return u, v, s, err_sq, norm_sq


def _truncate_with_err(res, r_final: int):
    """Shared rank-budget tail: truncate the sketch factors to
    ``r_final`` and fold the a-posteriori relative error — the ONE
    definition every jitted rank program (2-pass, one-view, and their
    staged forms) composes, so the arithmetic cannot drift apart."""
    u, v, s, err_sq, norm_sq = res
    err = jnp.sqrt(err_sq + jnp.sum(s[r_final:] ** 2)) / jnp.maximum(
        jnp.sqrt(norm_sq), 1e-30
    )
    return (
        u[:, :r_final] if u is not None else None,
        v[:, :r_final] if v is not None else None,
        s[:r_final],
        err,
    )


@functools.lru_cache(maxsize=128)
def _one_view_single_rank_fn(keep: int, k_hat: int, sketch_l: int, r_final: int, want: str = "left"):
    """Jitted one-view rank-budget program (the single_pass analog of
    ``_sketched_single_rank_fn``): truncation + approximate error fold
    into one compiled program, one dispatch."""

    def run(arr):
        return _truncate_with_err(
            _one_view_uds_both(arr, keep, k_hat, sketch_l, want), r_final
        )

    return jax.jit(run)


@functools.lru_cache(maxsize=128)
def _sketched_single_fn(keep: int, sketch_l: int, want: str = "left"):
    """Jitted single-device randomized truncated SVD returning the
    ``want``ed factor side(s) — both sides come from the same four
    passes, so the transposed (split=0) orientation never materializes
    Aᵀ (an eager or even traced ``arr.T`` at the north-star size is a
    full strided read+write over A, ~5 ms profiled round 3) and never
    pays the reference's ``U = A·V·Σ⁻¹`` postprocessing pass."""

    def run(arr):
        return _sketched_uds_both(arr, keep, sketch_l, want)

    return jax.jit(run)


@functools.lru_cache(maxsize=128)
def _sketched_single_rank_fn(keep: int, sketch_l: int, r_final: int, want: str = "left"):
    """Rank-budget variant: truncation and the a-posteriori error fold
    into the SAME compiled program, so one call is ONE dispatch — every
    eager op costs ~4 ms over the remote-execution tunnel and a blocking
    read ~90 ms, so op count, not FLOPs, dominates this call."""

    def run(arr):
        return _truncate_with_err(_sketched_uds_both(arr, keep, sketch_l, want), r_final)

    return jax.jit(run)


# --------------------------------------------------------------------- #
# out-of-core staging (ISSUE 11): the host-resident rank-budget sketch  #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=1)
def _staged_stream_fns():
    """Per-window jitted forms of the tiled streams — jax.jit caches per
    window shape, and every window's tile sequence is the in-HBM one."""
    return (
        jax.jit(_pass1_tiles),
        jax.jit(_pass2_tiles),
        jax.jit(_oneview_tiles),
        jax.jit(lambda w: _gram_orthonormalize(jnp.conj(w).T)),
    )


@functools.lru_cache(maxsize=128)
def _staged_rank_tail_fn(keep: int, r_final: int, want: str):
    """Jitted tail of the staged 2-pass rank-budget sketch: the exact
    ``_projection_tail`` + truncation + error arithmetic of
    ``_sketched_single_rank_fn``, on the staged (z, qw, norm)."""

    def run(z, qw, norm_sq):
        return _truncate_with_err(_projection_tail(z, qw, norm_sq, keep, want), r_final)

    return jax.jit(run)


@functools.lru_cache(maxsize=128)
def _staged_oneview_tail_fn(keep: int, sketch_l: int, r_final: int, want: str):
    """Jitted tail of the staged ONE-pass sketch: ``_one_view_tail`` +
    truncation + error, on the staged (w, y, norm)."""

    def run(w_full, y, norm_sq, g):
        return _truncate_with_err(
            _one_view_tail(w_full, y, norm_sq, g, keep, sketch_l, want), r_final
        )

    return jax.jit(run)


def _staged_sketch_rank(host, keep: int, sketch_l: int, r_final: int, want: str,
                        one_view, jt):
    """Rank-budget sketch over a HOST-RESIDENT operand, window by
    window (``redistribution.staging`` — arXiv:2112.09017's host-staged
    schedule): the operand never materializes on device; (8,128)-tile-
    aligned windows stream through the depth-2 double-buffered HBM slab
    (``jax.device_put`` of window k+1 issued under window k's compute),
    the window schedule is planned as a ``host-staging`` Schedule priced
    by the memory-tier lattice and PROVEN to fit ``capacity("hbm")``
    before the first byte moves, and — because the windows replay the
    in-HBM streams' fixed tile grain with explicit carries — the
    returned factors are BIT-IDENTICAL to the in-HBM path on a fitting
    twin (pinned).

    2-pass form: column windows feed ``_pass1_tiles`` (w assembled on
    device), row windows feed ``_pass2_tiles`` (z + the Frobenius carry);
    1-pass (``one_view=(k̂, ℓ)``): column windows feed ``_oneview_tiles``
    with the (y, norm) carries — ONE stream over the host operand.

    Returns device arrays ``(u|None, v|None, s, err)``."""
    from ...redistribution import staging as _staging

    m, n = host.shape
    item = np.dtype(jt).itemsize
    passes = (
        [{"tag": "dual-sketch", "axis": 1}]
        if one_view is not None
        else [{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}]
    )
    # HBM-resident working set held across the window loops: the sketch
    # factors and the assembled projection (w/qw/z or w/y), plus the
    # small tail outputs
    l_rows = (one_view[1] + _ONEVIEW_ERRQ) if one_view is not None else sketch_l
    width = one_view[0] if one_view is not None else sketch_l
    out_bytes = item * (l_rows * n + l_rows * m + 2 * n * width + 2 * m * width)
    sched = _staging.plan_staged_passes((m, n), np.dtype(jt), passes, out_bytes=out_bytes)
    _staging.prove_fits(sched)
    slab = int(sched.staging["slab_bytes"])
    _jit_pass1, _jit_pass2, _jit_oneview, _jit_orth_rows = _staged_stream_fns()

    def _cast(arr):
        return arr.astype(jt) if arr.dtype != np.dtype(jt) else arr

    if one_view is not None:
        k_hat, l_row = one_view
        kg, ko = jax.random.split(jax.random.key(0x5BD1))
        g = jax.random.normal(kg, (l_row + _ONEVIEW_ERRQ, m), dtype=jt)
        omega = jax.random.normal(ko, (n, k_hat), dtype=jt)
        wins = _staging.window_extents((m, n), item, 1, slab)
        chunks = []
        carry = {
            "y": jnp.zeros((m, k_hat), dtype=jt),
            "norm": jnp.zeros((), dtype=jnp.real(jnp.zeros((), jt)).dtype),
        }

        def consume(k, slab_arr, win):
            w_k, carry["y"], carry["norm"] = _jit_oneview(
                g, omega[win[0] : win[1]], _cast(slab_arr), carry["y"], carry["norm"]
            )
            chunks.append(w_k)

        _staging.stream_windows(host, 1, wins, consume, plan_id=sched.plan_id)
        w_full = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=1)
        return _staged_oneview_tail_fn(keep, l_row, r_final, want)(
            w_full, carry["y"], carry["norm"], g
        )

    key = jax.random.key(0x5BD)  # the in-HBM sketch's key — same g, same w
    g = jax.random.normal(key, (sketch_l, m), dtype=jt)
    wins1 = _staging.window_extents((m, n), item, 1, slab)
    chunks = []

    def consume1(k, slab_arr, win):
        chunks.append(_jit_pass1(g, _cast(slab_arr)))

    _staging.stream_windows(host, 1, wins1, consume1, plan_id=sched.plan_id)
    w = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=1)
    qw = _jit_orth_rows(w)

    wins2 = _staging.window_extents((m, n), item, 0, slab)
    zc = []
    carry2 = {"norm": jnp.zeros((), dtype=jnp.real(jnp.zeros((), jt)).dtype)}

    def consume2(k, slab_arr, win):
        z_k, carry2["norm"] = _jit_pass2(_cast(slab_arr), qw, carry2["norm"])
        zc.append(z_k)

    _staging.stream_windows(host, 0, wins2, consume2, plan_id=sched.plan_id)
    z = zc[0] if len(zc) == 1 else jnp.concatenate(zc, axis=0)
    return _staged_rank_tail_fn(keep, r_final, want)(z, qw, carry2["norm"])


def _hsvd_rank_host(host, maxrank: int, compute_sv: bool, safetyshift: int,
                    single_pass: bool):
    """``hsvd_rank`` over a host-tier operand (``staging.HostArray``).

    Staged when the gate allows and the rank-budget sketch is
    admissible; with ``HEAT_TPU_OOC=0`` (or a sketch-inadmissible
    budget — tiny matrices need the full SVD) the operand is
    materialized whole IF it fits ``tiers.capacity("hbm")`` and takes
    the ordinary in-HBM path, else a MemoryError names the numbers."""
    from ...redistribution import staging as _staging
    from ..communication import get_comm
    from ..devices import sanitize_device

    m, n = host.shape
    heat_dt = types.canonical_heat_type(host.dtype)
    if types.heat_type_is_exact(heat_dt):
        heat_dt = types.float32
    jt = heat_dt.jax_type()
    full_rank_cap = min(m, n)
    budget = maxrank + safetyshift
    l = min(budget + _SKETCH_OVERSAMPLE, full_rank_cap)
    admissible = 4 * l <= full_rank_cap

    if not _staging.ooc_engaged(host.nbytes, host_resident=True) or not admissible:
        # escape hatch (HEAT_TPU_OOC=0) or a budget only the full SVD
        # serves (staging streams the sketch passes only): materialize
        # the operand IF the chip can hold it — the shared helper names
        # the numbers otherwise
        what = (
            "hsvd_rank"
            if admissible
            else "hsvd_rank (sketch-inadmissible rank budget needs the full SVD)"
        )
        arr = _staging.materialize(host, what=what).astype(heat_dt)
        return hsvd_rank(
            arr, maxrank, compute_sv=compute_sv, safetyshift=safetyshift,
            single_pass=single_pass,
        )

    comm = get_comm()
    device = sanitize_device(None)
    keep = min(budget, full_rank_cap)
    r_final = max(1, min(maxrank, keep))
    want = "both" if compute_sv else "left"
    ov = _one_view_params(keep, full_rank_cap, m, n) if single_pass else None
    with svd_x32_scope(jt):
        u_t, v_t, s_t, err_dev = _staged_sketch_rank(
            host, keep, sketch_l=l, r_final=r_final, want=want, one_view=ov, jt=jt
        )
    err = _err_scalar(err_dev, comm=comm, device=device)
    U = DNDarray(u_t, (m, r_final), heat_dt, None, device, comm)
    sigma = DNDarray(
        _place(jnp.asarray(s_t), comm.sharding(1, None)),
        (int(s_t.shape[0]),),
        heat_dt,
        None,
        device,
        comm,
    )
    if not compute_sv:
        return U, err
    V = DNDarray(v_t, (n, r_final), heat_dt, None, device, comm)
    return U, sigma, V, err


@functools.lru_cache(maxsize=128)
def _local_svd_fn(
    mesh, axis_name: str, lrows: int, lcols: int, rloc: int, jdtype: str,
    sketch_l: Optional[int] = None, one_view: Optional[tuple] = None,
):
    """Compiled level-0 kernel: per-shard truncated SVD → U·Σ block plus
    discarded-energy scalar (the analog of reference
    ``compute_local_truncated_svd``, svdtools.py:477). With ``sketch_l``
    the block SVD is the randomized range-finder variant; ``one_view``
    = (k̂, ℓ) selects the single-pass sketch per shard (r5)."""

    def kernel(a_blk):
        # a_blk: (lrows, lcols) local column block of A (split=1 layout)
        if one_view is not None or sketch_l is not None:
            keep = min(rloc, min(a_blk.shape))
            if one_view is not None:
                k_hat, l_row = one_view
                u, _, s, err_sq, norm_sq = _one_view_uds_both(
                    a_blk, keep, k_hat, l_row, "left"
                )
            else:
                u, s, err_sq, norm_sq = _sketched_uds(a_blk, keep, sketch_l)
            u_scaled = u * s
            if keep < rloc:
                u_scaled = jnp.pad(u_scaled, ((0, 0), (0, rloc - keep)))
            return u_scaled, err_sq[None], norm_sq[None]
        u, s, _ = jnp.linalg.svd(a_blk, full_matrices=False)
        k = s.shape[0]
        keep = min(rloc, k)
        u_scaled = u[:, :keep] * s[:keep]
        if keep < rloc:
            u_scaled = jnp.pad(u_scaled, ((0, 0), (0, rloc - keep)))
        err_sq = jnp.sum(s[keep:] ** 2)
        # Frobenius partial fused into the same data read (the a-posteriori
        # bound needs ‖A‖_F; a separate eager pass would re-stream A)
        norm_sq = jnp.sum(s * s)
        return u_scaled, err_sq[None], norm_sq[None]

    return jax.jit(
        _shard_map(
            kernel,
            mesh=mesh,
            in_specs=PartitionSpec(None, axis_name),
            out_specs=(
                PartitionSpec(None, axis_name),
                PartitionSpec(axis_name),
                PartitionSpec(axis_name),
            ),
            check_vma=False,
        )
    )



def _err_scalar(val, A=None, comm=None, device=None) -> DNDarray:
    """Wrap the relative-error estimate as a 0-d replicated DNDarray — the
    reference returns a DNDarray too (svdtools.py:449), and keeping it lazy
    avoids a ~90 ms host read-back per call over the execution tunnel.
    ``A`` supplies comm/device; host-staged callers (no DNDarray operand)
    pass them explicitly."""
    comm = A.comm if A is not None else comm
    device = A.device if A is not None else device
    arr = jnp.asarray(val)
    if types.heat_type_is_exact(types.canonical_heat_type(arr.dtype)):
        arr = arr.astype(jnp.float32)
    return DNDarray(
        _place(arr, comm.sharding(0, None)),
        (),
        types.canonical_heat_type(arr.dtype),
        None,
        device,
        comm,
    )


def _merge_svd(B: DNDarray, calc_u: bool = True):
    """SVD of the stacked factor matrix via TSQR + small-R SVD.

    B (m × K) with K = p·r small: resplit to rows, TSQR, then SVD of the
    K×K R on-device (replicated — it is tiny).
    Returns (U as DNDarray split=0 | None, s, total extra err 0.0).
    """
    from .qr import qr as _qr

    m, K = B.shape
    if m >= K:
        Brow = B.resplit(0)
        q, r = _qr(Brow, calc_q=calc_u)
        u_r, s, _ = safe_svd(r.larray, full_matrices=False)
        if not calc_u:
            return None, s
        U = DNDarray(
            _padding.mask_phys(q._phys @ u_r, (m, int(u_r.shape[1])), 0),
            (m, int(u_r.shape[1])),
            q.dtype,
            0,
            B.device,
            B.comm,
        )
        return U, s
    # short-fat stacked matrix: gather (it is small by construction)
    u, s, _ = safe_svd(B.larray, full_matrices=False)
    U = DNDarray(
        B.comm.shard(u, 0), (int(u.shape[0]), int(u.shape[1])), B.dtype, 0, B.device, B.comm
    )
    return U, s


def hsvd_rank(
    A: DNDarray,
    maxrank: int,
    compute_sv: bool = False,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    silent: bool = True,
    single_pass: bool = False,
):
    """Truncated hierarchical SVD with a fixed rank budget (reference:
    svdtools.py:31). Returns ``(U, sigma, V, rel_error_estimate)`` when
    ``compute_sv=True`` else ``(U, rel_error_estimate)``.

    ``single_pass=True`` (r5, no reference analog) selects the ONE-VIEW
    sketch (``_one_view_uds_both``): column and row sketches from a
    single streaming read of A — on TPU one literal HBM pass via the
    fused dual-sketch kernel, doubling the throughput ceiling of the
    default 2-pass schedule. Opt-in because the approximation constant
    is larger than the 2-pass HMT bound and the returned error estimate
    is approximate; exact for matrices of rank ≤ maxrank+safetyshift.

    OUT-OF-CORE (ISSUE 11): ``A`` may be a
    ``ht.redistribution.staging.HostArray`` — a host-RAM- or
    HDF5-resident operand LARGER than HBM. The rank-budget sketch then
    streams (8,128)-aligned windows through a depth-2 double-buffered
    HBM slab (2-pass, or 1-pass with ``single_pass=True``), priced by
    the memory-tier lattice and proven to fit ``capacity("hbm")``
    before running; factors are bit-identical to the in-HBM path on a
    fitting twin. ``HEAT_TPU_OOC=0`` is the escape hatch (HostArray
    operands materialize whole when they fit), ``=1`` forces the
    staged pipeline for device operands too (the CI leg).
    """
    from ...redistribution import staging as _staging

    if isinstance(A, _staging.HostArray):
        if not isinstance(maxrank, (int, np.integer)) or maxrank < 1:
            raise ValueError(f"maxrank must be a positive integer, got {maxrank}")
        _warn_merge_knobs(maxmergedim, None)
        return _hsvd_rank_host(
            A, int(maxrank), compute_sv, int(safetyshift), bool(single_pass)
        )
    sanitize_in(A)
    if A.ndim != 2:
        raise ValueError(f"hsvd requires a 2-dimensional array, got {A.ndim}")
    if not isinstance(maxrank, (int, np.integer)) or maxrank < 1:
        raise ValueError(f"maxrank must be a positive integer, got {maxrank}")
    if maxmergedim is not None and maxmergedim < 2 * (maxrank + safetyshift) + 1:
        raise ValueError(
            "maxmergedim too small for maxrank+safetyshift (reference constraint, svdtools.py)"
        )
    _warn_merge_knobs(maxmergedim, None)
    return _hsvd_impl(
        A,
        maxrank=int(maxrank),
        rtol=None,
        safetyshift=int(safetyshift),
        compute_sv=compute_sv,
        silent=silent,
        single_pass=bool(single_pass),
    )


def hsvd_rtol(
    A: DNDarray,
    rtol: float,
    compute_sv: bool = False,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    no_of_merges: Optional[int] = None,
    silent: bool = True,
    safetyshift: int = 5,
):
    """Hierarchical SVD truncated to a relative error tolerance (reference:
    svdtools.py:124): the returned factorization satisfies
    ‖A − UΣVᵀ‖_F ≤ rtol·‖A‖_F (upper-bound estimate).
    """
    sanitize_in(A)
    if A.ndim != 2:
        raise ValueError(f"hsvd requires a 2-dimensional array, got {A.ndim}")
    if rtol <= 0:
        raise ValueError(f"rtol must be positive, got {rtol}")
    _warn_merge_knobs(maxmergedim, no_of_merges)
    return _hsvd_impl(
        A,
        maxrank=int(maxrank) if maxrank is not None else None,
        rtol=float(rtol),
        safetyshift=int(safetyshift),
        compute_sv=compute_sv,
        silent=silent,
    )


def hsvd(
    A: DNDarray,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    rtol: Optional[float] = None,
    safetyshift: int = 0,
    no_of_merges: Optional[int] = 2,
    compute_sv: bool = False,
    silent: bool = True,
    warnings_off: bool = False,
):
    """General hierarchical SVD entry point (reference: svdtools.py:259)."""
    sanitize_in(A)
    if maxrank is None and rtol is None:
        raise ValueError("at least one of maxrank and rtol must be given")
    _warn_merge_knobs(maxmergedim, no_of_merges)
    return _hsvd_impl(
        A,
        maxrank=int(maxrank) if maxrank is not None else None,
        rtol=rtol,
        safetyshift=int(safetyshift),
        compute_sv=compute_sv,
        silent=silent,
    )


def _hsvd_impl(
    A: DNDarray,
    maxrank: Optional[int],
    rtol: Optional[float],
    safetyshift: int,
    compute_sv: bool,
    silent: bool,
    single_pass: bool = False,
):
    comm: MeshCommunication = A.comm
    dtype = A.dtype
    if types.heat_type_is_exact(dtype):
        dtype = types.float32
    jt = dtype.jax_type()

    # orient split=1 (columns distributed) — reference svdtools.py:314-318.
    # A split=0 array is NOT resharded: its physical row shards ARE the
    # column shards of Aᵀ (P('d',None) → transpose → P(None,'d')), so the
    # orientation is a device-local relabel with no collective and no
    # unpad/repad round trip.
    transposed = A.split == 0
    m, n = (A.shape[1], A.shape[0]) if transposed else A.shape
    full_rank_cap = min(m, n)

    # u_direct/v_direct: factors of the INPUT orientation computed
    # directly by the single-device path — both sides come from the same
    # passes, so neither the reference's transpose (svdtools.py:314-318)
    # nor its ``U = A·V·Σ⁻¹`` postprocessing pass (:456-467) is needed,
    # and the returned factors are orthonormal by construction (the
    # postprocessed product with SKETCHED (σ, v) pairs is not).
    u_direct = None
    v_direct = None
    if A.split is None or not comm.is_distributed():
        arr = A.larray.astype(jt)
        budget = (maxrank + safetyshift) if maxrank is not None else None
        sketch_l = None
        if budget is not None and not _needs_exact_spectrum(rtol):
            l = min(budget + _SKETCH_OVERSAMPLE, full_rank_cap)
            if 4 * l <= full_rank_cap:
                sketch_l = l
        if sketch_l is not None:
            # small rank budget: randomized range finder, O(mnl) not O(mn²)
            keep = min(budget, full_rank_cap)
            want = "both" if compute_sv else "left"
            # host transfers over the execution tunnel cost ~90 ms EACH —
            # rank-budget mode needs no spectrum on host (rank is static),
            # so truncation + error fold into the jitted program (one
            # dispatch) and err stays a lazy 0-d DNDarray
            if rtol is None:
                r_final = max(1, min(maxrank, keep))
                ov = (
                    _one_view_params(keep, full_rank_cap, A.shape[0], A.shape[1])
                    if single_pass
                    else None
                )
                from ...redistribution import staging as _staging

                with svd_x32_scope(jt):
                    if _staging.ooc_mode() == "1":
                        # HEAT_TPU_OOC=1 (the forced CI leg): route the
                        # in-HBM operand through the staged window
                        # pipeline — the fixed-grain tile streams make
                        # the result bit-identical by construction,
                        # and the pinned sweep proves it
                        host = _staging.HostArray(np.asarray(arr))
                        u_t, v_t, s_t, err_dev = _staged_sketch_rank(
                            host, keep, sketch_l=sketch_l, r_final=r_final,
                            want=want, one_view=ov, jt=jt,
                        )
                    elif ov is not None:
                        k_hat, l_row = ov
                        u_t, v_t, s_t, err_dev = _one_view_single_rank_fn(
                            keep, k_hat, l_row, r_final, want
                        )(arr)
                    else:
                        u_t, v_t, s_t, err_dev = _sketched_single_rank_fn(
                            keep, sketch_l, r_final, want
                        )(arr)
                err = _err_scalar(err_dev, A)
                u_direct = DNDarray(u_t, (A.shape[0], r_final), dtype, None, A.device, comm)
                if v_t is not None:
                    v_direct = DNDarray(v_t, (A.shape[1], r_final), dtype, None, A.device, comm)
                s_np = s_t
            else:
                with svd_x32_scope(jt):
                    u_f, v_f, s_dev, err0_sq_dev, norm_sq_dev = _sketched_single_fn(
                        keep, sketch_l, want
                    )(arr)
                s_host, err0_sq, norm_sq = jax.device_get((s_dev, err0_sq_dev, norm_sq_dev))
                a_norm = float(np.sqrt(max(float(norm_sq), 0.0)))
                r_final = _choose_rank(
                    np.asarray(s_host), maxrank, rtol, a_norm, float(err0_sq), full_rank_cap
                )
                err = _err_scalar(
                    float(np.sqrt(float(err0_sq) + np.sum(np.asarray(s_host)[r_final:] ** 2)))
                    / max(a_norm, 1e-30),
                    A,
                )
                u_direct = DNDarray(u_f[:, :r_final], (A.shape[0], r_final), dtype, None, A.device, comm)
                if v_f is not None:
                    v_direct = DNDarray(v_f[:, :r_final], (A.shape[1], r_final), dtype, None, A.device, comm)
                s_np = s_dev[:r_final]
        else:
            # full SVD dominates; BOTH sides fall out of the one call, so
            # no orientation transpose and no postprocessing pass
            u, s, vt = safe_svd(arr, full_matrices=False)
            # one combined transfer for norm + spectrum
            s_host = np.asarray(jax.device_get(s))
            a_norm = float(np.sqrt(np.sum(s_host.astype(np.float64) ** 2)))
            err_sq = 0.0
            r_final = _choose_rank(s_host, maxrank, rtol, a_norm, err_sq, full_rank_cap)
            u_direct = DNDarray(u[:, :r_final], (A.shape[0], r_final), dtype, None, A.device, comm)
            v_direct = DNDarray(vt[:r_final].T, (A.shape[1], r_final), dtype, None, A.device, comm)
            s_np = s[:r_final]
            err = _err_scalar(
                float(np.sqrt(np.sum(s_host[r_final:] ** 2))) / max(a_norm, 1e-30), A
            )
    else:
        p = comm.size
        rloc = min(m, -(-n // p))
        if maxrank is not None:
            rloc = min(rloc, maxrank + safetyshift)
        phys = A._phys.astype(jt)
        if transposed:
            # pad rows become zero pad columns: Frobenius/SVD-neutral
            phys = phys.T
        lcols = phys.shape[1] // p
        sketch_l = None
        if maxrank is not None and not _needs_exact_spectrum(rtol):
            lmin = min(phys.shape[0], lcols)
            l = min(rloc + _SKETCH_OVERSAMPLE, lmin)
            if 4 * l <= lmin:
                sketch_l = l
        one_view = None
        if single_pass and sketch_l is not None:
            one_view = _one_view_params(
                min(rloc, lcols), min(phys.shape[0], lcols), phys.shape[0], lcols
            )
        fn = _local_svd_fn(
            comm.mesh, comm.axis_name, phys.shape[0], lcols, rloc, np.dtype(jt).name,
            sketch_l, one_view,
        )
        with svd_x32_scope(jt):
            b_phys, err_blocks, normsq_blocks = fn(phys)
        B = DNDarray(
            b_phys, (m, int(b_phys.shape[1])), dtype, 1, A.device, comm
        )
        U_merged, s_all = _merge_svd(B, calc_u=True)
        if rtol is None:
            # static rank: err computed on device, ONE scalar read-back
            r_final = max(1, min(maxrank, min(int(s_all.shape[0]), full_rank_cap)))
            err = _err_scalar(
                jnp.sqrt(jnp.sum(err_blocks) + jnp.sum(s_all[r_final:] ** 2))
                / jnp.maximum(jnp.sqrt(jnp.sum(normsq_blocks)), 1e-30),
                A,
            )
        else:
            s_np_all, lvl_sq, nrm_sq = jax.device_get(
                (s_all, jnp.sum(err_blocks), jnp.sum(normsq_blocks))
            )
            s_np_all = np.asarray(s_np_all)
            a_norm = float(np.sqrt(max(float(nrm_sq), 0.0)))
            level_err_sq = float(lvl_sq)
            r_final = _choose_rank(s_np_all, maxrank, rtol, a_norm, level_err_sq, full_rank_cap)
            merge_err_sq = float(np.sum(s_np_all[r_final:] ** 2))
            err = _err_scalar(
                float(np.sqrt(level_err_sq + merge_err_sq)) / max(a_norm, 1e-30), A
            )
        # truncate U to the final rank
        u_trunc = U_merged.larray[:, :r_final]
        U_arr = DNDarray(comm.shard(u_trunc, 0), (m, r_final), dtype, 0, A.device, comm)
        s_np = s_all[:r_final]

    sigma_arr = jnp.asarray(s_np)
    sigma = DNDarray(
        _place(sigma_arr, comm.sharding(1, None)),
        (int(sigma_arr.shape[0]),),
        dtype,
        None,
        A.device,
        comm,
    )

    if u_direct is not None or v_direct is not None:
        # single-device path: factors already in the input orientation
        U_of_A, V_of_A = u_direct, v_direct
    elif transposed:
        # A = U Σ V^H for the original orientation: the left factors of
        # Aᵀ are conj(V) (Aᵀ = conj(V) Σ Uᵀ), so native complex inputs
        # conjugate on the relabel; real inputs swap factors unchanged
        U_of_A = None
        if types.heat_type_is_complexfloating(dtype):
            from .. import complex_math as _cmath

            V_of_A = _cmath.conj(U_arr)
        else:
            V_of_A = U_arr
    else:
        U_of_A = U_arr
        V_of_A = None

    if not compute_sv:
        # reference returns (U, relerr) where U are the left singular
        # vectors of the *input orientation*
        primary = U_of_A if U_of_A is not None else _postprocess_v(A, V_of_A, sigma, left=True)
        return primary, err

    # compute any missing factor via the reference's postprocessing
    # (svdtools.py:456-467): V = Aᵀ U Σ⁻¹ (or U = A V Σ⁻¹) — only the
    # distributed path still needs this; single-device has both sides
    if U_of_A is not None and V_of_A is not None:
        return U_of_A, sigma, V_of_A, err
    if U_of_A is not None:
        V = _postprocess_v(A, U_of_A, sigma, left=False)
        return U_of_A, sigma, V, err
    U = _postprocess_v(A, V_of_A, sigma, left=True)
    return U, sigma, V_of_A, err


def _postprocess_v(A: DNDarray, factor: DNDarray, sigma: DNDarray, left: bool) -> DNDarray:
    """Compute the complementary singular factor: V = Aᵀ U / σ or
    U = A V / σ (reference: svdtools.py:456-467)."""
    from . import basics

    if left:
        prod = basics.matmul(A, factor)  # (m, r)
    else:
        # V = A^H U / σ: the adjoint, not the transpose — native complex
        # inputs conjugate (conj is the identity on reals)
        At = basics.transpose(A, None)
        if types.heat_type_is_complexfloating(A.dtype):
            from .. import complex_math as _cmath

            At = _cmath.conj(At)
        prod = basics.matmul(At, factor)  # (n, r)
    inv_sigma = jnp.where(sigma.larray > 0, 1.0 / sigma.larray, 0.0)
    scaled = prod.larray * inv_sigma
    # A·V·Σ⁻¹ with TRUNCATED (σ, v) pairs is only approximately an
    # isometry (deviation ~ discarded-energy/σ_r — ~1e-1 on flat spectra;
    # the reference ships that deviation, svdtools.py:456-467). Two
    # Cholesky-QR rounds on the skinny (·, r) result restore machine
    # orthogonality without rotating columns; on a sharded operand the
    # (r, r) Gram is XLA's psum, ~2 cheap passes.
    scaled = _cholqr2_refine(scaled)
    return DNDarray(
        prod.comm.shard(scaled, prod.split) if prod.split is not None else scaled,
        prod.shape,
        prod.dtype,
        prod.split,
        prod.device,
        prod.comm,
    )


def _choose_rank(
    s: np.ndarray,
    maxrank: Optional[int],
    rtol: Optional[float],
    a_norm: float,
    prior_err_sq: float,
    cap: int,
) -> int:
    """Final truncation rank: static budget and/or smallest rank whose
    discarded energy keeps the total error below rtol·‖A‖ (reference
    truncation logic in compute_local_truncated_svd / hsvd)."""
    s = np.asarray(s, dtype=np.float64)
    k = min(len(s), cap)
    if rtol is None:
        return max(1, min(maxrank, k))
    budget_sq = (rtol * a_norm) ** 2 - prior_err_sq
    # discarded tail energy for every candidate rank
    tail = np.cumsum((s[::-1] ** 2))[::-1]  # tail[i] = sum_{j>=i} s_j^2
    r = k
    for i in range(k, 0, -1):
        discard = tail[i] if i < len(s) else 0.0
        if discard <= max(budget_sq, 0.0):
            r = i
        else:
            break
    if maxrank is not None:
        r = min(r, maxrank)
    return max(1, r)

from ..communication import register_mesh_cache
from ..communication import place as _place

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_local_svd_fn)
