"""Full SVD.

The reference ships only a stub raising toward hSVD
(/root/reference/heat/core/linalg/svd.py:10). Here ``svd`` is implemented:
replicated arrays use XLA's SVD directly; tall split=0 matrices factor via
TSQR (the TSQR merge's grouped all-gather(s) on ICI) followed by an SVD of the small R —
``A = QR, R = U_R Σ Vᵀ ⇒ U = Q·U_R`` — wide split=1 matrices via the
transposed identity. A capability the reference directs users away from.
"""

from __future__ import annotations

import collections

import numpy as np

import jax
import jax.numpy as jnp

from typing import Tuple

from .. import types
from .. import _padding
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ._lapack import safe_svd, safe_svdvals

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, Vh")


def svd(A: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Singular value decomposition A = U·diag(S)·Vh.

    reduced form only (``full_matrices=False``, the distributed-relevant
    case; the reference's hSVD equivalents are rank-truncated anyway).
    """
    from . import basics
    from .qr import qr as _qr

    sanitize_in(A)
    if A.ndim != 2:
        raise ValueError(f"svd requires a 2-dimensional array, got {A.ndim}")
    if full_matrices:
        raise NotImplementedError("only the reduced SVD (full_matrices=False) is provided")

    dtype = A.dtype
    if types.heat_type_is_exact(dtype):
        dtype = types.float32
    jt = dtype.jax_type()
    m, n = A.shape
    comm = A.comm

    if A.split == 0 and comm.is_distributed() and m >= n:
        q, r = _qr(A if A.dtype == dtype else A.astype(dtype), calc_q=compute_uv)
        if not compute_uv:
            s = safe_svdvals(r.larray)
            return DNDarray(s, (int(s.shape[0]),), dtype, None, A.device, comm)
        u_r, s, vh = safe_svd(r.larray, full_matrices=False)
        u_phys = _padding.mask_phys(q._phys @ u_r, (m, int(u_r.shape[1])), 0)
        U = DNDarray(u_phys, (m, int(u_r.shape[1])), dtype, 0, A.device, comm)
        S = DNDarray(s, (int(s.shape[0]),), dtype, None, A.device, comm)
        Vh = DNDarray(vh, tuple(int(x) for x in vh.shape), dtype, None, A.device, comm)
        return SVD(U, S, Vh)

    if A.split == 1 and comm.is_distributed() and n > m:
        # wide: svd(Aᵀ) and swap factors
        res = svd(basics.transpose(A, None), full_matrices=False, compute_uv=compute_uv)
        if not compute_uv:
            return res
        U_t, S, Vh_t = res
        return SVD(basics.transpose(Vh_t, None), S, basics.transpose(U_t, None))

    arr = A.larray.astype(jt)
    if not compute_uv:
        s = safe_svdvals(arr)
        return DNDarray(s, (int(s.shape[0]),), dtype, None, A.device, comm)
    u, s, vh = safe_svd(arr, full_matrices=False)
    split_u = A.split if A.split == 0 else None
    split_vh = 1 if A.split == 1 else None
    U = DNDarray(
        comm.shard(u, split_u) if split_u is not None else u,
        tuple(int(x) for x in u.shape),
        dtype,
        split_u,
        A.device,
        comm,
    )
    S = DNDarray(s, (int(s.shape[0]),), dtype, None, A.device, comm)
    Vh = DNDarray(
        comm.shard(vh, split_vh) if split_vh is not None else vh,
        tuple(int(x) for x in vh.shape),
        dtype,
        split_vh,
        A.device,
        comm,
    )
    return SVD(U, S, Vh)
