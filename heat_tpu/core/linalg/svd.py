"""Full SVD.

The reference ships only a stub raising toward hSVD
(/root/reference/heat/core/linalg/svd.py:10). Here ``svd`` is a real
composition over the suite's matmul-native primitives (ISSUE 19):

- ``method="qr"`` — tall split-0 operands factor via TSQR (the grouped
  ring all-gather of the R blocks on ICI) followed by an SVD of the
  small replicated R: ``A = QR, R = U_R Σ Vᴴ ⇒ U = Q·U_R``. The
  operand itself is never gathered — only the ``(p, n, n)`` R stack
  moves.
- ``method="polar"`` — the factorization-suite composition
  ``A = U_p H`` (Newton–Schulz :func:`~.factorizations.polar`, a pure
  ppermute-ring program) then ``H = V Σ Vᴴ`` (eigh of the small
  replicated Hermitian factor), giving ``A = (U_p V) Σ Vᴴ``. The
  distributed census is collective-permute ONLY — zero all-gathers of
  anything, which is the pinned contract for operands whose ``n`` is
  past the TSQR merge gate.
- ``method="auto"`` — qr while the TSQR gate admits ``n``
  (``n <= 4096``), polar past it.

``compute_uv=False`` never forms U or V: the TSQR path stops at the
R factor's singular values; host-resident (:class:`HostArray`)
operands stream row windows through the PR-11 depth-2 staged
double-buffer accumulating the Gram matrix ``G = AᴴA`` and return
``sqrt(eigvalsh(G))`` without the operand ever being device-resident.

Documented tolerance (pinned in tests/test_factorizations.py): for
float32 well-conditioned operands both methods match
``jnp.linalg.svd``'s singular values to ``rtol=1e-4`` and reconstruct
``‖A - U Σ Vᴴ‖_F / ‖A‖_F <= 1e-4``; singular VECTORS match up to the
usual per-column unitary phase. The Gram values-only paths square the
condition number — singular values below ``‖A‖·sqrt(eps)`` are noise
there, the price of the single-pass stream.

``full_matrices=True`` raises :class:`FullMatricesNotSupported` — the
orthogonal complement is a dense ``m × m`` replicated factor no
distributed schedule here can afford; use ``hsvd_rank``/``hsvd_rtol``
for rank-truncated factors or ``ht.linalg.eigh`` on the Gram/covariance
matrix when only the column space is needed.
"""

from __future__ import annotations

import collections

import numpy as np

import jax
import jax.numpy as jnp

from .. import types
from .. import _padding
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ._lapack import safe_svd, safe_svdvals

__all__ = ["FullMatricesNotSupported", "svd"]

SVD = collections.namedtuple("SVD", "U, S, Vh")

# the TSQR merge gate (qr.py): past this column count the stacked R
# blocks outgrow the merge and svd switches to the polar composition
_TSQR_MAX_N = 4096

_METHODS = ("auto", "qr", "polar")


class FullMatricesNotSupported(NotImplementedError):
    """``svd(full_matrices=True)`` — the full orthonormal basis is a
    dense ``m × m`` (resp. ``n × n``) REPLICATED factor: for the
    distributed operands this module serves it does not fit any
    schedule the planner could price. Alternatives, by what the caller
    actually needs:

    - rank-truncated factors: ``ht.linalg.hsvd_rank`` /
      ``ht.linalg.hsvd_rtol`` (hierarchical, distributed, streamed);
    - the column-space spectrum: ``ht.linalg.eigh`` on the Gram or
      covariance matrix (matmul-native, ISSUE 19);
    - the reduced factors: ``full_matrices=False`` (this function).
    """


def _values_dnd(s, dtype, ref: DNDarray) -> DNDarray:
    return DNDarray(s, (int(s.shape[0]),), dtype, None, ref.device, ref.comm)


def _gram_svdvals_arr(g, jt):
    """Descending singular values from a replicated Gram matrix."""
    w = jnp.linalg.eigvalsh(g)  # ascending
    return jnp.sqrt(jnp.clip(w[::-1], 0, None)).astype(jt)


def _host_svdvals(host, jt):
    """Values-only SVD of a host-resident operand: one staged pass of
    row windows accumulating the Gram matrix on device (the window
    stream is the hsvd "sketch" pass shape with a rank-n resident), no
    device materialization of the operand. Descending values, local."""
    from ...observability.attribution import register_plan
    from ...redistribution import staging as _staging

    m, n = (int(s) for s in host.shape)
    itemsize = np.dtype(jt).itemsize
    sched = _staging.plan_staged_passes(
        (m, n), jt, [{"tag": "gram", "axis": 0}],
        out_bytes=n * n * itemsize,
    )
    register_plan(sched)
    wins = _staging.window_extents((m, n), itemsize, 0, _staging.slab_bytes())
    acc = jnp.zeros((n, n), jt)

    def consume(_k, slab, _ext):
        nonlocal acc
        w = jnp.asarray(slab).astype(jt)
        acc = acc + jnp.matmul(
            jnp.conjugate(w.T), w, precision="highest"
        )

    _staging.stream_windows(host, 0, wins, consume, plan_id=sched.plan_id)
    return _gram_svdvals_arr(acc, jt)


def svd(
    A,
    full_matrices: bool = False,
    compute_uv: bool = True,
    method: str = "auto",
):
    """Singular value decomposition ``A = U·diag(S)·Vh`` (reduced form).

    ``method`` selects the distributed schedule: ``"qr"`` (TSQR + small
    SVD of R), ``"polar"`` (Newton–Schulz polar + eigh of H — zero
    all-gathers), or ``"auto"`` (qr while ``n`` fits the TSQR merge,
    polar past it). Replicated operands use XLA's SVD directly.
    ``compute_uv=False`` returns only the descending singular values and
    never forms U/V; a host-resident :class:`HostArray` operand is
    served by a staged Gram pass (values only). See the module
    docstring for the documented tolerances and
    :class:`FullMatricesNotSupported` for the ``full_matrices=True``
    contract.
    """
    from . import basics
    from .qr import qr as _qr

    if method not in _METHODS:
        raise ValueError(f"method must be one of {_METHODS}, got {method!r}")

    from ...redistribution.staging import HostArray

    if isinstance(A, HostArray):
        return _svd_host(A, full_matrices, compute_uv, method)

    sanitize_in(A)
    if A.ndim != 2:
        raise ValueError(f"svd requires a 2-dimensional array, got {A.ndim}")

    dtype = A.dtype
    if types.heat_type_is_exact(dtype):
        dtype = types.float32
    jt = dtype.jax_type()
    m, n = (int(s) for s in A.shape)
    comm = A.comm

    # values-only first: no U/V is ever formed on these paths, and
    # full_matrices is meaningless without them
    if not compute_uv:
        if A.split == 1 and comm.is_distributed() and n > m:
            return svd(
                basics.transpose(A, None),
                full_matrices=False, compute_uv=False, method=method,
            )
        if A.split is not None and comm.is_distributed():
            a0 = A if A.split == 0 else A.resplit(0)
            use_qr = method == "qr" or (method == "auto" and n <= _TSQR_MAX_N)
            if m >= n and use_qr:
                _, r = _qr(
                    a0 if a0.dtype == dtype else a0.astype(dtype), calc_q=False
                )
                return _values_dnd(safe_svdvals(r.larray), dtype, A)
            if m >= n:
                # past the TSQR gate (or method="polar"): ring Gram —
                # one ppermute-ring X^H X, eigvalsh of the small result
                from .factorizations import _ring_xhy

                a0 = a0 if a0.dtype == dtype else a0.astype(dtype)
                g = _ring_xhy(a0, a0)
                return _values_dnd(_gram_svdvals_arr(g, jt), dtype, A)
        return _values_dnd(safe_svdvals(A.larray.astype(jt)), dtype, A)

    if full_matrices:
        raise FullMatricesNotSupported(
            "svd(full_matrices=True): the full orthonormal basis is a dense "
            f"replicated ({m}, {m}) factor no distributed schedule here can "
            "hold — use full_matrices=False for the reduced factors, "
            "ht.linalg.hsvd_rank/hsvd_rtol for rank-truncated ones, or "
            "ht.linalg.eigh on the Gram matrix for the spectrum"
        )

    if comm.is_distributed() and A.split is not None:
        if A.split == 1 and n > m:
            # wide: svd(Aᵀ) and swap factors
            u_t, s, vh_t = svd(
                basics.transpose(A, None),
                full_matrices=False, compute_uv=True, method=method,
            )
            return SVD(basics.transpose(vh_t, None), s, basics.transpose(u_t, None))
        a0 = A if A.split == 0 else A.resplit(0)
        a0 = a0 if a0.dtype == dtype else a0.astype(dtype)
        use_qr = method == "qr" or (method == "auto" and n <= _TSQR_MAX_N)
        if use_qr:
            q, r = _qr(a0, calc_q=True)
            u_r, s, vh = safe_svd(r.larray, full_matrices=False)
            k = int(u_r.shape[1])
            u_phys = _padding.mask_phys(q._phys @ u_r, (m, k), 0)
            U = DNDarray(u_phys, (m, k), dtype, 0, A.device, comm)
            S = _values_dnd(s, dtype, A)
            Vh = DNDarray(
                vh, tuple(int(x) for x in vh.shape), dtype, None, A.device, comm
            )
            return SVD(U, S, Vh)
        return _svd_polar(a0, dtype, jt)

    arr = A.larray.astype(jt)
    u, s, vh = safe_svd(arr, full_matrices=False)
    split_u = A.split if A.split == 0 else None
    split_vh = 1 if A.split == 1 else None
    U = DNDarray(
        comm.shard(u, split_u) if split_u is not None else u,
        tuple(int(x) for x in u.shape),
        dtype,
        split_u,
        A.device,
        comm,
    )
    S = _values_dnd(s, dtype, A)
    Vh = DNDarray(
        comm.shard(vh, split_vh) if split_vh is not None else vh,
        tuple(int(x) for x in vh.shape),
        dtype,
        split_vh,
        A.device,
        comm,
    )
    return SVD(U, S, Vh)


def _svd_polar(a0: DNDarray, dtype, jt):
    """The polar composition: ``A = U_p H`` (ppermute-ring Newton–
    Schulz), ``H = V Σ Vᴴ`` (eigh of the small replicated Hermitian
    factor, descending reorder), ``U = U_p V`` (split-0 × replicated —
    a local shard matmul, no collective). Census: collective-permute
    only; the operand is never gathered."""
    from . import basics
    from .factorizations import polar as _polar

    m, n = (int(s) for s in a0.shape)
    comm = a0.comm
    u_p, h = _polar(a0)
    w, v = jnp.linalg.eigh(h.larray)  # ascending
    s = jnp.clip(w[::-1], 0, None).astype(jt)
    v_desc = v[:, ::-1]
    v_dnd = DNDarray(v_desc, (n, n), dtype, None, a0.device, comm)
    U = basics.matmul(u_p, v_dnd, precision="highest")
    if U.split != 0:
        U = U.resplit(0)
    Vh = DNDarray(
        jnp.conjugate(v_desc.T), (n, n), dtype, None, a0.device, comm
    )
    return SVD(U, _values_dnd(s, dtype, a0), Vh)


def _svd_host(host, full_matrices: bool, compute_uv: bool, method: str):
    """HostArray operand: the values-only staged Gram pass when the
    pass structure allows (no U/V), the materialize escape hatch when
    the operand fits HBM anyway, and a typed redirect to hsvd when
    factors of a genuinely out-of-core operand are asked for."""
    from ...redistribution import staging as _staging
    from .. import factories

    dtype = types.canonical_heat_type(host.dtype)
    if types.heat_type_is_exact(dtype):
        dtype = types.float32
    jt = dtype.jax_type()
    if not compute_uv:
        if not _staging.ooc_engaged(host.nbytes, host_resident=True):
            a = _staging.materialize(host, what="svd operand")
            return svd(a, compute_uv=False, method=method)
        s = _host_svdvals(host, jt)
        return factories.array(np.asarray(jax.device_get(s)), split=None)
    if full_matrices:
        raise FullMatricesNotSupported(
            "svd(full_matrices=True) on a host-resident operand: use "
            "full_matrices=False, or ht.linalg.hsvd_rank/hsvd_rtol for "
            "rank-truncated factors"
        )
    if not _staging.ooc_engaged(host.nbytes, host_resident=True):
        a = _staging.materialize(host, what="svd operand")
        return svd(a, compute_uv=True, method=method)
    raise NotImplementedError(
        "svd(compute_uv=True) of a host-resident operand needs a "
        "multi-pass factor stream — use ht.linalg.hsvd_rank/hsvd_rtol "
        "(staged 2-pass hierarchical SVD) for out-of-core factors, or "
        "compute_uv=False for the staged values-only Gram pass"
    )
