"""Dense linear algebra basics.

API parity with /root/reference/heat/core/linalg/basics.py (``matmul`` at
basics.py:421-1097, ``dot`` at :244, ``inv`` at :310, ``det`` at :158,
``norm``/``matrix_norm``/``vector_norm`` at :1113-1389, ``outer`` at
:1390, ``trace`` at :1641, ``transpose`` at :2056, ``tril``/``triu`` at
:2126-2240). The reference implements matmul as an explicit block-cyclic
SUMMA with Ibcast/Isend rings (basics.py:664-1097); here the contraction is
a sharded ``jnp.matmul``/``einsum`` under GSPMD — XLA emits the equivalent
collective schedule over ICI, and the MXU does the block math. The split
rules of the reference (result split by operand splits, basics.py:421-436)
are preserved as output sharding constraints.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import List, Optional, Tuple, Union

from .. import types
from .._operations import __binary_op as _binary_op
from ..communication import sanitize_comm
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ..stride_tricks import sanitize_axis

__all__ = [
    "cross",
    "det",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


@functools.lru_cache(maxsize=128)
def _cmatmul_program(
    mesh, axis_name: str, m: int, lk: int, n: int, jdtype: str, precision,
    pipelined: bool,
):
    """Compiled collective-matmul program for the contraction-split case
    (``a.split == 1``, ``b.split == 0``): ``C = Σ_q A_q B_q`` as a
    ppermute reduce-scatter ring whose per-hop partial block matmul
    (MXU) rides under the in-flight hop (ICI), then a ring gather of
    the reduced row chunks (``kernels.cmatmul.ring_matmul_reduce``).
    Replicated output, consistent across devices (each chunk is summed
    once, in fixed ring order) and bit-identical between the sequential
    and pipelined issue orders."""
    from ...kernels import cmatmul as _cm
    from .._jax_compat import shard_map as _shard_map
    from jax.sharding import PartitionSpec as _P

    p = mesh.devices.size
    # jdtype rides only in the lru_cache key: operands arrive pre-cast

    def kernel(a_loc, b_loc):
        with _cm.stamp_scope("matmul"):
            return _cm.ring_matmul_reduce(
                a_loc, b_loc, axis_name, p, precision=precision, pipelined=pipelined
            )

    mapped = _shard_map(
        kernel,
        mesh=mesh,
        in_specs=(_P(None, axis_name), _P(axis_name, None)),
        out_specs=_P(None, None),
        check_vma=False,
    )

    def fn(a_phys, b_phys):
        out = mapped(a_phys, b_phys)
        return out if out.shape[0] == m else out[:m]

    return jax.jit(fn)


def _collective_matmul_eligible(a: DNDarray, b: DNDarray) -> bool:
    """The collective-matmul form serves exactly the contraction-split
    2-D case — ``a`` column-split against ``b`` row-split, the one
    matmul whose GSPMD schedule is a full-reduction barrier. Gated by
    ``kernels.cmatmul.ring_enabled`` (``HEAT_TPU_REDIST_OVERLAP``)."""
    return (
        a.ndim == 2
        and b.ndim == 2
        and a.split == 1
        and b.split == 0
        and not a._is_planar
        and not b._is_planar
        and a.comm.is_distributed()
    )


def _wrap(result: jax.Array, split: Optional[int], ref: DNDarray) -> DNDarray:
    comm = ref.comm
    gshape = tuple(int(s) for s in result.shape)
    if split is not None and result.ndim > 0:
        split = split % result.ndim
        result = comm.shard(result, split)
    else:
        split = None
    return DNDarray(
        result,
        gshape,
        types.canonical_heat_type(result.dtype),
        split,
        ref.device,
        ref.comm,
    )


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    """Cross product of 3-element vectors (reference: basics.py cross)."""
    sanitize_in(a), sanitize_in(b)
    promoted = types.promote_types(a.dtype, b.dtype).jax_type()
    result = jnp.cross(
        a.larray.astype(promoted), b.larray.astype(promoted), axisa=axisa, axisb=axisb, axisc=axisc
    )
    split = a.split if a.split is not None else b.split
    if split is not None and split >= result.ndim:
        split = None
    return _wrap(result, split, a)


# past this order a distributed 2-D operand's inv/det runs the blocked
# ring-LU suite (factorizations.py) instead of handing the sharded
# logical array to XLA's one-device LU kernel — which GSPMD serves by
# gathering and replicating the whole operand (the SL102/SL106 shape
# the shardlint golden fixture pins)
_BLOCKED_MIN_N = 512


def _blocked_linalg_eligible(a: DNDarray) -> bool:
    return (
        a.ndim == 2
        and not a._is_planar
        and a.split in (0, 1)
        and a.comm.is_distributed()
        and int(a.shape[0]) >= _BLOCKED_MIN_N
    )


def det(a: DNDarray) -> DNDarray:
    """Determinant of (batched) square matrices (reference: basics.py:158
    implements distributed LU with row bcasts).

    Distributed 2-D operands of order >= ``_BLOCKED_MIN_N`` run the
    blocked ring-lookahead LU (``factorizations._lu_factor``) and read
    the determinant off ``sign · prod(diag(U))`` — no gather-and-
    replicate of the operand (ISSUE 19). Smaller or batched operands
    keep XLA's on-device LU."""
    sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError(f"expected square matrix, got shape {a.shape}")
    if _blocked_linalg_eligible(a):
        from .factorizations import _lu_factor

        _pvec, _l, u, sign = _lu_factor(a)
        jt = u.dtype.jax_type()
        result = sign.astype(jt) * jnp.prod(jnp.diagonal(u.larray))
        return _wrap(result, None, a)
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    result = jnp.linalg.det(arr)
    split = a.split if a.split is not None and a.split < a.ndim - 2 else None
    return _wrap(result, split, a)


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> Union[DNDarray, float]:
    """Dot product following numpy semantics (reference: basics.py:244)."""
    sanitize_in(a), sanitize_in(b)
    if a._is_planar or b._is_planar:
        from .. import complex_planar as _cp

        if out is not None:
            raise _cp.policy_error("ht.dot with out= on complex operands")
        return _cp.dot(a, b)
    if a.ndim == 1 and b.ndim == 1:
        # inner product: local mul + sum; all-reduce over split emitted by XLA
        promoted = types.promote_types(a.dtype, b.dtype).jax_type()
        result = jnp.dot(a.larray.astype(promoted), b.larray.astype(promoted))
        ret = _wrap(result, None, a)
        if out is not None:
            out.larray = ret.larray
            return out
        return ret
    if a.ndim == 2 and b.ndim == 2:
        ret = matmul(a, b)
        if out is not None:
            out.larray = ret.larray
            return out
        return ret
    raise NotImplementedError("ht.dot not implemented for given dimensions")


def inv(a: DNDarray) -> DNDarray:
    """Inverse of (batched) square matrices (reference: basics.py:310
    distributed Gauss-Jordan).

    Distributed 2-D operands of order >= ``_BLOCKED_MIN_N`` factor once
    through the blocked ring-lookahead LU and back-substitute the
    identity block-column-wise (``factorizations._solve_factored``) —
    the operand and its inverse stay split the whole way, replacing the
    gather-and-replicate ``jnp.linalg.inv`` path (ISSUE 19; see
    MIGRATING.md). Smaller or batched operands keep XLA's on-device
    kernel."""
    sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError(f"expected square matrix, got shape {a.shape}")
    if _blocked_linalg_eligible(a):
        from .. import factories
        from .factorizations import _lu_factor, _solve_factored

        pvec, l_arr, u_arr, _sign = _lu_factor(a)
        rhs = factories.eye(
            (int(a.shape[0]),) * 2, dtype=l_arr.dtype, split=0,
            device=a.device, comm=a.comm,
        )
        x = _solve_factored("lu", rhs, l_arr, u_arr, pvec)
        return x if x.split == a.split else x.resplit(a.split)
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    result = jnp.linalg.inv(arr)
    return _wrap(result, a.split, a)


def matmul(
    a: DNDarray, b: DNDarray, allow_resplit: bool = False, precision=None
) -> DNDarray:
    """Matrix product of two DNDarrays (reference: basics.py:421).

    Reference schedule: case analysis over (a.split, b.split) with a
    block-cyclic SUMMA ring of Ibcast/Isend (basics.py:664-1097). Here the
    global contraction is handed to XLA with sharded operands; GSPMD
    partitions the einsum and inserts the collectives (the same
    all-gather/reduce-scatter dataflow SUMMA hand-codes), scheduled onto
    ICI with compute/comm overlap.

    Result split follows the reference rules (basics.py:421-436):
    a.split=0 → out split 0; b.split=1 → out split 1;
    a.split=1, b.split=0 → replicated (full reduction).
    """
    sanitize_in(a), sanitize_in(b)
    if a.ndim < 1 or b.ndim < 1:
        raise ValueError("matmul requires at least 1-dimensional operands")

    if a._is_planar or b._is_planar:
        from .. import complex_planar as _cp

        return _cp.matmul(a, b, precision=precision)
    promoted = types.promote_types(a.dtype, b.dtype)

    from ...kernels import cmatmul as _cm

    if _collective_matmul_eligible(a, b) and _cm.ring_enabled():
        # the collective-matmul form (ISSUE 6): the contraction-split
        # product's reduction decomposed into a ppermute ring so each
        # partial block matmul lands under the in-flight hop, instead of
        # GSPMD's full-reduction barrier. HEAT_TPU_REDIST_OVERLAP=0 is
        # the escape hatch back to the barrier schedule below.
        jt = promoted.jax_type()
        comm = a.comm
        fn = _cmatmul_program(
            comm.mesh,
            comm.axis_name,
            int(a.shape[0]),
            int(a._phys.shape[1]) // comm.size,
            int(b.shape[1]),
            np.dtype(jt).name,
            precision,
            True,
        )
        return _wrap(fn(a._phys.astype(jt), b._phys.astype(jt)), None, a)

    arr_a = a.larray.astype(promoted.jax_type())
    arr_b = b.larray.astype(promoted.jax_type())

    # precision: None = chip default (bf16 MXU passes for f32, the same
    # trade torch-CUDA's tf32 default makes); "highest" forces f32-exact
    # accumulation at ~3x the MXU passes. jax.default_matmul_precision
    # also applies as ambient context.
    result = jnp.matmul(arr_a, arr_b, precision=precision)

    # output split per reference rules, generalized to batched dims
    out_ndim = result.ndim
    split = None
    if a.ndim >= 2 and a.split == a.ndim - 2:
        split = out_ndim - 2
    elif b.ndim >= 2 and b.split == b.ndim - 1:
        split = out_ndim - 1
    elif a.split is not None and a.ndim > 2 and a.split < a.ndim - 2:
        split = a.split
    elif b.split is not None and b.ndim > 2 and b.split < b.ndim - 2:
        split = b.split
    return _wrap(result, split, a)


def matrix_norm(
    a: DNDarray,
    axis: Optional[Tuple[int, int]] = None,
    keepdims: bool = False,
    ord: Union[int, str, None] = None,
) -> DNDarray:
    """Matrix norm (reference: basics.py:1113)."""
    sanitize_in(a)
    if axis is None:
        if a.ndim < 2:
            raise ValueError("matrix_norm requires at least 2 dimensions")
        axis = (a.ndim - 2, a.ndim - 1)
    ax = sanitize_axis(a.shape, axis)
    if not isinstance(ax, tuple) or len(ax) != 2:
        raise ValueError("axis must be a 2-tuple")
    arr = a.larray
    if types.heat_type_is_exact(a.dtype):
        arr = arr.astype(jnp.float32)
    result = jnp.linalg.matrix_norm(
        jnp.moveaxis(arr, ax, (-2, -1)), ord=ord if ord is not None else "fro", keepdims=False
    )
    if keepdims:
        result = jnp.expand_dims(jnp.expand_dims(result, ax[0]), ax[1] if ax[1] > ax[0] else ax[1])
        result = jnp.broadcast_to(result, tuple(1 if i in ax else s for i, s in enumerate(a.shape)))
    split = a.split if a.split is not None and a.split not in ax else None
    if split is not None and not keepdims:
        split = split - sum(1 for x in ax if x < split)
    return _wrap(result, split, a)


def norm(
    a: DNDarray,
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
    ord: Union[int, float, str, None] = None,
    keepdim: bool = False,
    axis=None,
    keepdims=None,
) -> DNDarray:
    """Vector or matrix norm (reference: basics.py:1238)."""
    sanitize_in(a)
    if axis is not None:
        dim = axis
    if keepdims is not None:
        keepdim = keepdims
    if dim is None and ord is None:
        return vector_norm(a.flatten() if a.ndim != 1 else a, keepdims=False)
    if isinstance(dim, tuple) and len(dim) == 2:
        return matrix_norm(a, axis=dim, keepdims=keepdim, ord=ord)
    if dim is None and a.ndim == 2 and ord is not None and ord not in (2, -2):
        return matrix_norm(a, keepdims=keepdim, ord=ord)
    return vector_norm(a, axis=dim, keepdims=keepdim, ord=2 if ord is None else ord)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two vectors (reference: basics.py:1390 implements a
    Bcast ring per rank; the sharded broadcast product is the same
    dataflow)."""
    sanitize_in(a), sanitize_in(b)
    if a._is_planar or b._is_planar:
        from .. import complex_planar as _cp

        if out is not None:
            raise _cp.policy_error("ht.outer with out= on complex operands")
        return _cp.outer(a, b, split=split)
    promoted = types.promote_types(a.dtype, b.dtype).jax_type()
    result = jnp.outer(a.larray.astype(promoted), b.larray.astype(promoted))
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    ret = _wrap(result, split, a)
    if out is not None:
        out.larray = ret.larray.astype(out.dtype.jax_type())
        return out
    return ret


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of vector a onto vector b (reference: basics.py)."""
    sanitize_in(a), sanitize_in(b)
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}, {b.ndim}")
    scale = dot(a, b) / dot(b, b)
    return _wrap(scale.larray * b.larray, b.split, b)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None) -> DNDarray:
    """Sum along diagonals (reference: basics.py:1641)."""
    sanitize_in(a)
    if a.ndim < 2:
        raise ValueError("trace requires at least 2 dimensions")
    result = jnp.trace(a.larray, offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    ax = sanitize_axis(a.shape, (axis1, axis2))
    split = a.split if a.split is not None and a.split not in ax else None
    if split is not None:
        split = split - sum(1 for x in ax if x < split)
    ret = _wrap(result, split, a)
    if a.ndim == 2:
        # scalar result: reference returns a Python-scalar-like 0-dim array
        pass
    if out is not None:
        out.larray = ret.larray
        return out
    return ret


def transpose(a: DNDarray, axes: Optional[List[int]] = None) -> DNDarray:
    """Permute array dimensions (reference: basics.py:2056 — local permute
    plus split remap; identical here, with the sharding constraint moved)."""
    sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(sanitize_axis(a.shape, int(ax)) for ax in axes)
        if sorted(axes) != list(range(a.ndim)):
            raise ValueError(f"axes do not match array dimensions, got {axes}")
    if a._is_planar:
        from .. import complex_planar as _cp

        return _cp.transpose(a, axes)
    result = jnp.transpose(a.larray, axes)
    split = axes.index(a.split) if a.split is not None else None
    return _wrap(result, split, a)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower triangle (reference: basics.py:2126)."""
    sanitize_in(m)
    arr = m.larray
    if m.ndim == 1:
        arr = jnp.tile(arr, (arr.shape[0], 1))
        result = jnp.tril(arr, k=k)
        split = 0 if m.split is not None else None
        return _wrap(result, split, m)
    return _wrap(jnp.tril(arr, k=k), m.split, m)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper triangle (reference: basics.py:2183)."""
    sanitize_in(m)
    arr = m.larray
    if m.ndim == 1:
        arr = jnp.tile(arr, (arr.shape[0], 1))
        result = jnp.triu(arr, k=k)
        split = 0 if m.split is not None else None
        return _wrap(result, split, m)
    return _wrap(jnp.triu(arr, k=k), m.split, m)


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product of flattened arrays (reference: basics.py)."""
    sanitize_in(x1), sanitize_in(x2)
    if x1._is_planar or x2._is_planar:
        from .. import complex_planar as _cp

        return _cp.vdot(x1, x2)
    promoted = types.promote_types(x1.dtype, x2.dtype).jax_type()
    result = jnp.vdot(x1.larray.astype(promoted), x2.larray.astype(promoted))
    return _wrap(result, None, x1)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Vector dot product along ``axis`` (reference: basics.py vecdot)."""
    sanitize_in(x1), sanitize_in(x2)
    if axis is None:
        axis = -1
    if x1._is_planar or x2._is_planar:
        from .. import complex_planar as _cp

        return _cp.vecdot(x1, x2, axis=axis, keepdims=keepdims)
    promoted = types.promote_types(x1.dtype, x2.dtype).jax_type()
    prod = jnp.conj(x1.larray.astype(promoted)) * x2.larray.astype(promoted)
    result = jnp.sum(prod, axis=axis, keepdims=keepdims)
    out_ndim = result.ndim
    split = x1.split if x1.split is not None else x2.split
    if split is not None:
        norm_axis = axis % max(prod.ndim, 1)
        if split == norm_axis:
            split = None
        elif not keepdims and split > norm_axis:
            split -= 1
        if split is not None and split >= out_ndim:
            split = None
    return _wrap(result, split, x1)


def vector_norm(
    x: DNDarray,
    axis: Optional[Union[int, Tuple[int, ...]]] = None,
    keepdims: bool = False,
    ord: Union[int, float, None] = 2,
) -> DNDarray:
    """Vector norm (reference: basics.py:1316)."""
    sanitize_in(x)
    arr = x.larray
    if types.heat_type_is_exact(x.dtype):
        arr = arr.astype(jnp.float32)
    ax = sanitize_axis(x.shape, axis)
    result = jnp.linalg.vector_norm(arr, axis=ax, keepdims=keepdims, ord=2 if ord is None else ord)
    if ax is None:
        split = None
    else:
        axes = (ax,) if isinstance(ax, int) else ax
        split = x.split
        if split is not None:
            if split in axes:
                split = None
            elif keepdims:
                pass
            else:
                split = split - sum(1 for a in axes if a < split)
    return _wrap(result, split, x)


DNDarray.transpose = transpose
DNDarray.__matmul__ = lambda self, other: matmul(self, other)

from ..communication import register_mesh_cache as _register_mesh_cache

# collective-matmul programs bake mesh geometry: cleared when
# init_distributed rebuilds the world
_register_mesh_cache(_cmatmul_program)
