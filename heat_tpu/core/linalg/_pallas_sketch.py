"""Pallas TPU kernel: fused row-sketch + Frobenius accumulation.

The hSVD sketch (`svdtools._sketched_uds_both`) is pass-bound: four
streaming reads of A at HBM speed (docs/PERF.md). Two of those passes
touch every element of A independently of each other — the row sketch
``w = g @ A`` and the norm ``‖A‖²_F`` — which XLA does NOT fuse (a dot
and a reduction over the same operand lower to separate reads). This
kernel streams each (TM × TN) tile of A through VMEM once and feeds it
to BOTH consumers:

    per tile:  w[:, tile_n] += g[:, tile_m] @ A_tile      (MXU)
               norm_partial[tile_n] += Σ A_tile²          (VPU)

cutting the sketch to three passes over A (~25% of the north-star op's
runtime at the 2.1 GB shard).

Grid layout is the canonical accumulator pattern: the contraction
dimension (m) is the INNER grid axis, so the ``w`` output block and the
per-column norm partial stay resident in VMEM across all m-steps and are
written back once per n-tile.

Gates: TPU backend, x64 off (platform default), f32 operands, tile-
divisible shapes, l ≤ 32 (the sketch width is ~25). Everything else
falls back to the XLA formulation, which is also the numerical oracle
(tests assert ≤1e-4 relative agreement; the kernel accumulates the dot
in f32 like the DEFAULT-precision XLA path)."""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:  # pragma: no cover — present in all TPU-capable jax builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pl = None
    _VMEM = None

__all__ = ["sketch_with_norm", "dual_sketch_with_norm"]

_L_PAD = 32  # sketch-width rows padded to a full sublane multiple

# one-view sketch widths: the co-range sketch ℓ ≈ 2k̂+1 needs more rows
_L2_PAD = 64   # row-sketch width cap for the dual kernel
_K_PAD = 32    # column-sketch width cap


@functools.lru_cache(maxsize=32)
def _fused_call(m: int, n: int, tm: int, tn: int):
    grid = (n // tn, m // tm)

    def kernel(g_ref, a_ref, w_ref, np_ref):
        i_n = pl.program_id(0)
        i_m = pl.program_id(1)

        @pl.when(i_m == 0)
        def _init_w():
            w_ref[...] = jnp.zeros_like(w_ref)

        # the norm block is CONSTANT across the whole grid (resident in
        # VMEM for the entire run); init exactly once
        @pl.when((i_m == 0) & (i_n == 0))
        def _init_norm():
            np_ref[...] = jnp.zeros_like(np_ref)

        a = a_ref[...]
        w_ref[...] += jnp.dot(g_ref[...], a, preferred_element_type=jnp.float32)
        # broadcast-accumulate over a full (8,128) tile — Mosaic rejects
        # scalar/sub-tile VMEM stores; every entry carries the total
        np_ref[...] = np_ref[...] + jnp.sum(a * a)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_L_PAD, tm), lambda i_n, i_m: (0, i_m), memory_space=_VMEM),
            pl.BlockSpec((tm, tn), lambda i_n, i_m: (i_m, i_n), memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_L_PAD, tn), lambda i_n, i_m: (0, i_n), memory_space=_VMEM),
            pl.BlockSpec((8, 128), lambda i_n, i_m: (0, 0), memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((_L_PAD, n), jnp.float32),
            jax.ShapeDtypeStruct((8, 128), jnp.float32),
        ],
    )


def _pick_tile(extent: int, candidates=(1024, 512, 256, 128)) -> int:
    for c in candidates:
        if extent % c == 0:
            return c
    return 0


@functools.lru_cache(maxsize=32)
def _dual_call(m: int, n: int, tm: int, tn: int):
    """One-view kernel: each (tm × tn) tile of A feeds THREE consumers in
    a single HBM read — the row sketch ``w += g @ A`` (MXU), the column
    sketch ``y += A @ Ω`` (MXU), and the Frobenius partial (VPU). This is
    what makes the single-pass hSVD actually single-pass: XLA lowers the
    two matmuls as two separate streams over A.

    Residency plan (grid = m outer, n inner; VMEM ≈ 16 MB):
    - ``y`` block (tm, K_PAD): the canonical accumulator — n is the inner
      axis, so the block stays resident across its contraction steps;
    - ``w`` (L2_PAD, n): its contraction axis is m (the OUTER axis), so a
      tiled block would be revisited non-consecutively and lose its
      accumulation — instead the WHOLE w lives in VMEM for the entire run
      (constant block index; ≤ 2 MB at the north-star n=8192) and each
      step accumulates into its n-tile slice;
    - the norm tile is the same constant (8, 128) block as sketch_with_norm.
    """
    grid = (m // tm, n // tn)

    def kernel(g_ref, om_ref, a_ref, w_ref, y_ref, np_ref):
        i_m = pl.program_id(0)
        i_n = pl.program_id(1)

        @pl.when((i_m == 0) & (i_n == 0))
        def _init_w_norm():
            w_ref[...] = jnp.zeros_like(w_ref)
            np_ref[...] = jnp.zeros_like(np_ref)

        @pl.when(i_n == 0)
        def _init_y():
            y_ref[...] = jnp.zeros_like(y_ref)

        a = a_ref[...]
        sl = pl.dslice(i_n * tn, tn)
        w_ref[:, sl] += jnp.dot(g_ref[...], a, preferred_element_type=jnp.float32)
        y_ref[...] += jnp.dot(a, om_ref[...], preferred_element_type=jnp.float32)
        np_ref[...] = np_ref[...] + jnp.sum(a * a)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_L2_PAD, tm), lambda i_m, i_n: (0, i_m), memory_space=_VMEM),
            pl.BlockSpec((tn, _K_PAD), lambda i_m, i_n: (i_n, 0), memory_space=_VMEM),
            pl.BlockSpec((tm, tn), lambda i_m, i_n: (i_m, i_n), memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_L2_PAD, n), lambda i_m, i_n: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((tm, _K_PAD), lambda i_m, i_n: (i_m, 0), memory_space=_VMEM),
            pl.BlockSpec((8, 128), lambda i_m, i_n: (0, 0), memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((_L2_PAD, n), jnp.float32),
            jax.ShapeDtypeStruct((m, _K_PAD), jnp.float32),
            jax.ShapeDtypeStruct((8, 128), jnp.float32),
        ],
    )


def dual_sketch_serviceable(l_total: int, k_hat: int, m: int, n: int) -> bool:
    """Shape-level predicate: would ``dual_sketch_with_norm`` serve this
    signature ON THE TPU BACKEND? Callers use it to refuse a
    ``single_pass`` request whose fallback would stream A three times —
    strictly worse than the 2-pass default the user opted out of."""
    if l_total > _L2_PAD or k_hat > _K_PAD:
        return False
    if _L2_PAD * n * 4 > 4 * 1024 * 1024:
        return False
    return bool(_pick_tile(m, (512, 256, 128)) and _pick_tile(n))


def dual_sketch_with_norm(g: jax.Array, omega: jax.Array, a: jax.Array):
    """Fused ``(g @ a, a @ omega, ‖a‖²_F)`` in ONE pass over ``a`` — the
    one-view (single-pass) hSVD's data movement — or None when the gates
    don't hold (the caller's XLA formulation is the fallback and the
    numerical oracle). Traceable; same gate style as sketch_with_norm.
    ``g``: (ℓ, m) row-sketch operator, ``omega``: (n, k̂) column-sketch
    operator, ℓ ≤ 64, k̂ ≤ 32."""
    if pl is None or jax.default_backend() != "tpu" or jax.config.jax_enable_x64:
        return None
    if a.dtype != jnp.float32 or g.dtype != jnp.float32 or omega.dtype != jnp.float32:
        return None
    if g.ndim != 2 or omega.ndim != 2 or a.ndim != 2:
        return None
    if g.shape[1] != a.shape[0] or omega.shape[0] != a.shape[1]:
        return None
    l, m = g.shape
    n, k_hat = omega.shape
    if l > _L2_PAD or k_hat > _K_PAD:
        return None
    # w stays whole in VMEM: bound its footprint (2 MB at n=8192) plus
    # the tile working set well under the ~16 MB budget
    if _L2_PAD * n * 4 > 4 * 1024 * 1024:
        return None
    tm, tn = _pick_tile(m, (512, 256, 128)), _pick_tile(n)
    if not tm or not tn:
        return None
    g_pad = jnp.pad(g, ((0, _L2_PAD - l), (0, 0))) if l < _L2_PAD else g
    om_pad = (
        jnp.pad(omega, ((0, 0), (0, _K_PAD - k_hat))) if k_hat < _K_PAD else omega
    )
    w_pad, y_pad, norm_tile = _dual_call(m, n, tm, tn)(g_pad, om_pad, a)
    return w_pad[:l], y_pad[:, :k_hat], norm_tile[0, 0]


def sketch_with_norm(g: jax.Array, a: jax.Array):
    """Fused ``(g @ a, ‖a‖²_F)`` in ONE pass over ``a``, or None when the
    kernel's gates don't hold (caller falls back to the two-pass XLA
    form). Traceable (pallas_call is a primitive), so it works inside the
    jitted sketch programs."""
    if pl is None or jax.default_backend() != "tpu" or jax.config.jax_enable_x64:
        return None
    if a.dtype != jnp.float32 or g.dtype != jnp.float32:
        return None
    if g.ndim != 2 or a.ndim != 2 or g.shape[1] != a.shape[0]:
        return None
    l, m = g.shape
    n = a.shape[1]
    if l > _L_PAD:
        return None
    tm, tn = _pick_tile(m), _pick_tile(n)
    if not tm or not tn:
        return None
    g_pad = jnp.pad(g, ((0, _L_PAD - l), (0, 0))) if l < _L_PAD else g
    w_pad, norm_tile = _fused_call(m, n, tm, tn)(g_pad, a)
    return w_pad[:l], norm_tile[0, 0]
