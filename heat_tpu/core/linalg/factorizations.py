"""Matmul-native distributed dense factorizations (ISSUE 19).

The paper's thesis (arXiv:2112.09017) is that dense factorizations on
TPU pods should be *recast as matmul chains* — the MXU plus the ICI
all-gather/ppermute rings are the whole machine — rather than ported
from the panel-factor/broadcast CPU playbook. This module is that suite:

- :func:`polar` — Newton–Schulz polar decomposition. Every iteration is
  two ring matmuls (``kernels.cmatmul.ring_matmul_reduce``): the Gram
  sweep ``X^H X`` and the update ``X(1.5 I - 0.5 G)``, with a
  Frobenius-residual convergence carry inside one ``while_loop``. No
  transcendental, no pivoting — the factorization the paper calls out as
  "the" TPU-native primitive.
- :func:`eigh` — symmetric/Hermitian eigendecomposition via polar-based
  spectral divide-and-conquer: ``S = sign(A - μI)`` from the polar
  factor, the two spectral projectors ``(I ∓ S)/2``, subspaces via TSQR
  of projector-range probes, then recursion on the (resplit-0)
  sub-operands. Everything except two tiny host reads of projector
  traces (declared in ``analysis/boundaries``) stays on-device.
- :func:`cholesky` / :func:`lu` / :func:`solve` — blocked right-looking
  factorizations with the panel column assembled by the cmatmul
  all-gather ring and the trailing update as a local MXU matmul under
  the in-flight hops (the lookahead form); block triangular solves ride
  a ppermute ring broadcast (:func:`heat_tpu.kernels.cmatmul.ring_bcast`).
- :func:`svd` composition lives in ``svd.py``: polar + eigh for the
  factored form, Gram eigenvalues for ``compute_uv=False``.

Movement contract: every solver launches ONLY ``collective-permute``
chains, pre-declared as a :class:`~heat_tpu.redistribution.schedule.Schedule`
(``_factorization_plan``) whose ``plan_id`` stamps the kernel's
``redist_plan_<id>`` named scope — shardlint downgrades the planned
movement to info severity, and tests pin program census == plan census.
Sequential (``HEAT_TPU_REDIST_OVERLAP=0``) and pipelined (``=1``) issue
orders are bit-identical: the rings only place, select, or accumulate in
one fixed order (see ``kernels/cmatmul.py``).

Accumulation is pinned f32-exact (``precision="highest"`` on every
internal contraction) per the numcheck SL601 contract.
"""

from __future__ import annotations

import collections
import functools

import numpy as np

import jax
import jax.numpy as jnp

from jax import lax
from jax.scipy.linalg import solve_triangular
from jax.sharding import PartitionSpec as _PS

from typing import Optional, Tuple

from .. import types
from .. import _padding
from .._jax_compat import shard_map as _shard_map
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ...kernels import cmatmul as _cm
from . import basics

__all__ = [
    "Eigh",
    "LU",
    "Polar",
    "cholesky",
    "eigh",
    "golden_factorization_plans",
    "lu",
    "polar",
    "solve",
    "solve_endpoint",
]

Polar = collections.namedtuple("Polar", "U, H")
Eigh = collections.namedtuple("Eigh", "eigenvalues, eigenvectors")
LU = collections.namedtuple("LU", "perm, L, U")

# blocked inv/det rewiring engages above this order (below it the local
# XLA kernels win on launch overhead); eigh recursion resplits
# sub-operands at/above this order — tests shrink it to exercise the
# recursion at toy sizes
_EIGH_RESPLIT_MIN_N = 512
_EIGH_MAX_DEPTH = 16

_POLAR_MAXITER = 64


def _ct(x: jax.Array) -> jax.Array:
    """Conjugate transpose — THE inner-product convention of the suite
    (PR 5 fixed plain-transpose bugs in exactly these contractions)."""
    return jnp.conjugate(jnp.swapaxes(x, -1, -2))


def _ct_dnd(a: DNDarray) -> DNDarray:
    """Conjugate transpose at the DNDarray level, split axis remapped."""
    res = jnp.conjugate(jnp.swapaxes(a.larray, -1, -2))
    split = None
    if a.split is not None:
        split = {0: 1, 1: 0}.get(a.split, a.split)
    return basics._wrap(res, split, a)


def _solver_dtype(a: DNDarray):
    dt = a.dtype
    if types.heat_type_is_exact(dt):
        dt = types.float32
    return dt


def _real_eps(jt) -> float:
    return float(jnp.finfo(np.dtype(jt)).eps)


# ---------------------------------------------------------------------- #
# plans: the pre-declared collective schedules                           #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=256)
def _factorization_plan(kind: str, gshape: Tuple[int, ...], dtype: str,
                        p: int, budget: Optional[int] = None):
    """The :class:`Schedule` a factorization program launches — built
    BEFORE execution, registered with observability, and stamped into
    the kernel's ``redist_plan_<id>`` named scope.

    Census contract (pinned in tests/test_factorizations.py; the HLO
    text counts a ``while_loop`` body's collectives ONCE, which is how
    the iteration-bearing polar plan stays static):

    - ``polar``     : ``5(p-1)`` collective-permutes — norm ring (p-1),
      Gram ring inside the Newton–Schulz body (2(p-1), counted once),
      final ``H = U^H A`` ring (2(p-1)).
    - ``cholesky``  : ``p(p-1)`` — one panel-column gather ring per lap.
    - ``lu``        : ``(2p-1)(p-1)`` — the gather rings plus a
      ``ring_bcast`` of the pivoted U panel row on every non-final lap.
    - ``solve-chol`` / ``solve-lu`` : ``2(p-1)^2`` — one block
      broadcast/gather ring per non-terminal lap of each sweep.
    """
    from ...redistribution import planner as _planner
    from ...redistribution.schedule import Schedule, Step
    from ...redistribution.spec import RedistSpec

    if budget is None:
        budget = _planner.budget_bytes()
    spec = RedistSpec.normalize(gshape, dtype, 0, 0, p)
    t = np.dtype(dtype).itemsize
    steps = []

    def hop(payload, detail, chunk):
        steps.append(Step(
            "ppermute", bytes_moved=int(payload), peak_bytes=2 * int(payload),
            detail=detail, chunk=chunk,
        ))

    if kind == "polar":
        m, n = gshape
        mc = -(-n // p)
        rt = np.dtype(dtype).itemsize // (2 if np.dtype(dtype).kind == "c" else 1)
        for d in range(p - 1):
            hop(rt, "frobenius-norm partial ring", d)
        for d in range(p - 1):
            hop(mc * n * t, "newton-schulz gram reduce-scatter ring "
                            "(while body; HLO census counts once)", d)
        for d in range(p - 1):
            hop(mc * n * t, "newton-schulz gram chunk gather ring (while body)", d)
        for d in range(p - 1):
            hop(mc * n * t, "hermitian factor H=U^H A reduce-scatter ring", d)
        for d in range(p - 1):
            hop(mc * n * t, "hermitian factor H chunk gather ring", d)
        notes = (f"newton-schulz polar ({m}x{n}): every iteration reships the "
                 f"gram ring payload; the schedule prices the static program "
                 f"(while-body collectives once), maxiter={_POLAR_MAXITER}")
    elif kind == "cholesky":
        n = gshape[0]
        nb = -(-n // p)
        for k in range(p):
            for d in range(p - 1):
                hop(nb * nb * t, f"panel column gather ring (lap {k})", k)
        notes = (f"blocked right-looking cholesky ({n}x{n}, nb={nb}): panel "
                 f"column assembled by gather ring, trailing update local MXU "
                 f"under the hops")
    elif kind == "lu":
        n = gshape[0]
        nb = -(-n // p)
        n_pad = nb * p
        for k in range(p):
            for d in range(p - 1):
                hop(nb * nb * t, f"panel column gather ring (lap {k})", k)
        for k in range(p - 1):
            trail = n_pad - (k + 1) * nb
            for d in range(p - 1):
                hop(nb * trail * t, f"pivoted U panel row bcast ring (lap {k})", k)
        notes = (f"blocked right-looking LU ({n}x{n}, nb={nb}): block-local "
                 f"partial pivoting; U panel row broadcast around the ring, "
                 f"trailing update local MXU under the hops")
    elif kind in ("solve-chol", "solve-lu"):
        n, nrhs = gshape
        nb = -(-n // p)
        for k in range(p - 1):
            for d in range(p - 1):
                hop(nb * nrhs * t, f"forward-sweep block ring (lap {k})", k)
        for k in range(p - 1):
            for d in range(p - 1):
                hop(nb * nrhs * t, f"backward-sweep block ring (lap {k})", k)
        notes = (f"block triangular solve ({n}x{n}, nrhs={nrhs}, nb={nb}, "
                 f"{kind.split('-')[1]} factors): broadcast/gather ring per "
                 f"non-terminal lap of each sweep")
    else:
        raise ValueError(f"unknown factorization plan kind {kind!r}")
    return Schedule(spec, f"factorization-{kind}", steps, budget, notes=notes)


def golden_factorization_plans():
    """Named plans at pinned shapes/budget — the determinism fixture
    consumed by ``scripts/redist_plans.py`` (plan_ids must be stable
    across runs and machines)."""
    from ...redistribution import planner as _planner

    b = _planner.DEFAULT_BUDGET_MB << 20
    return [
        ("polar_f32_65536x1024_p8",
         _factorization_plan("polar", (65536, 1024), "float32", 8, budget=b)),
        ("cholesky_f32_8192_p8",
         _factorization_plan("cholesky", (8192, 8192), "float32", 8, budget=b)),
        ("lu_f32_8192_p8",
         _factorization_plan("lu", (8192, 8192), "float32", 8, budget=b)),
        ("solve_chol_f32_8192x256_p8",
         _factorization_plan("solve-chol", (8192, 256), "float32", 8, budget=b)),
        ("solve_lu_f32_8192x256_p8",
         _factorization_plan("solve-lu", (8192, 256), "float32", 8, budget=b)),
    ]


def _runtime_plan(kind, gshape, jt, comm):
    """Build + register the plan a public solver is about to execute."""
    from ...observability.attribution import register_plan
    from ...redistribution import planner as _planner

    sched = _factorization_plan(
        kind, tuple(int(s) for s in gshape), np.dtype(jt).name, comm.size,
        budget=_planner.budget_bytes(),
    )
    register_plan(sched)
    return sched


# ---------------------------------------------------------------------- #
# Newton–Schulz polar                                                    #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _polar_program(mesh, axis_name: str, lrows: int, n: int, jdtype: str,
                   maxiter: int, tol: float, pipelined: bool, plan_id: str):
    """Compiled distributed Newton–Schulz polar iteration for split-0
    physical shards of shape ``(lrows, n)``.

    Every step is a ring matmul: the Gram sweep ``G = X^H X`` is
    ``ring_matmul_reduce`` over the row shards (contraction axis = the
    split axis), the update ``X(1.5 I - 0.5 G)`` a local MXU matmul
    against the replicated ``G``. The convergence carry is
    ``err = ||G - I||_F / sqrt(n)`` measured BEFORE the update (one-step
    lag: the exit iterate is one step better than the test), inside one
    ``while_loop`` — so the HLO collective census is static regardless
    of iteration count. f32-exact accumulation everywhere
    (``precision="highest"``, numcheck SL601)."""
    p = mesh.devices.size
    jt = np.dtype(jdtype)
    rt = np.dtype(jnp.finfo(jt).dtype)
    perm = _cm.grouped_ring_perm(1, p)

    def kernel(a_loc):
        with jax.named_scope(f"redist_plan_{plan_id}"), _cm.stamp_scope("polar"):
            i = lax.axis_index(axis_name)
            # Frobenius norm of the operand: scalar partials around the
            # ring (replicated-identical: one fixed summation order)
            part = jnp.sum(
                jnp.real(jnp.conjugate(a_loc) * a_loc)
            ).astype(rt)
            stacked = _cm.ring_all_gather(part, axis_name, p, i, perm,
                                          pipelined=pipelined)
            nrm = jnp.sqrt(jnp.sum(stacked))
            tiny = jnp.asarray(jnp.finfo(rt).tiny, rt)
            x0 = a_loc / jnp.maximum(nrm, tiny).astype(jt)
            eye = jnp.eye(n, dtype=jt)

            def gram(x):
                g = _cm.ring_matmul_reduce(
                    _ct(x), x, axis_name, p, precision="highest",
                    pipelined=pipelined,
                )
                return g[:n]

            def cond(carry):
                it, _, err = carry
                return jnp.logical_and(it < maxiter, err > tol)

            def body(carry):
                it, x, _ = carry
                g = gram(x)
                err = (jnp.linalg.norm(g - eye) / np.sqrt(n)).astype(rt)
                xn = jnp.matmul(x, 1.5 * eye - 0.5 * g, precision="highest")
                return it + 1, xn, err

            carry0 = (jnp.asarray(0, jnp.int32), x0, jnp.asarray(jnp.inf, rt))
            _, u_loc, _ = lax.while_loop(cond, body, carry0)
            h = _cm.ring_matmul_reduce(
                _ct(u_loc), a_loc, axis_name, p, precision="highest",
                pipelined=pipelined,
            )[:n]
            h = 0.5 * (h + _ct(h))
            return u_loc, h

    mapped = _shard_map(
        kernel, mesh=mesh,
        in_specs=(_PS(axis_name, None),),
        out_specs=(_PS(axis_name, None), _PS(None, None)),
        check_vma=False,
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def _polar_local_program(m: int, n: int, jdtype: str, maxiter: int, tol: float):
    """Single-program twin of :func:`_polar_program`: same scaled
    iteration, same convergence carry, plain matmuls."""
    jt = np.dtype(jdtype)
    rt = np.dtype(jnp.finfo(jt).dtype)

    def fn(a):
        tiny = jnp.asarray(jnp.finfo(rt).tiny, rt)
        nrm = jnp.linalg.norm(a).astype(rt)
        x0 = a / jnp.maximum(nrm, tiny).astype(jt)
        eye = jnp.eye(n, dtype=jt)

        def cond(carry):
            it, _, err = carry
            return jnp.logical_and(it < maxiter, err > tol)

        def body(carry):
            it, x, _ = carry
            g = jnp.matmul(_ct(x), x, precision="highest")
            err = (jnp.linalg.norm(g - eye) / np.sqrt(n)).astype(rt)
            xn = jnp.matmul(x, 1.5 * eye - 0.5 * g, precision="highest")
            return it + 1, xn, err

        carry0 = (jnp.asarray(0, jnp.int32), x0, jnp.asarray(jnp.inf, rt))
        _, u, _ = lax.while_loop(cond, body, carry0)
        h = jnp.matmul(_ct(u), a, precision="highest")
        return u, 0.5 * (h + _ct(h))

    return jax.jit(fn)


def polar(a: DNDarray, side: str = "right", maxiter: int = _POLAR_MAXITER,
          tol: Optional[float] = None) -> Polar:
    """Polar decomposition ``A = U H`` (``side="right"``, ``m >= n``) or
    ``A = H U`` (``side="left"``, ``m <= n``) by the scaled Newton–Schulz
    iteration — U has orthonormal columns/rows, H is Hermitian positive
    semi-definite and replicated.

    Distributed split-0 operands run the ring-matmul program (split-1
    resplits first); the collective schedule is pre-declared and
    registered (see :func:`_factorization_plan`). Convergence: the
    iteration stops when ``||X^H X - I||_F / sqrt(n) <= tol`` (default
    ``50·eps`` of the real dtype) or after ``maxiter`` steps.
    """
    sanitize_in(a)
    if a._is_planar:
        from .. import complex_planar as _cp

        raise _cp.policy_error("ht.linalg.polar on planar complex operands")
    if a.ndim != 2:
        raise ValueError(f"polar requires a 2-dimensional array, got {a.ndim}")
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    m, n = (int(s) for s in a.shape)
    if side == "left":
        if m > n:
            raise ValueError(
                f"side='left' requires m <= n, got {a.shape}; use side='right'"
            )
        u1, h1 = polar(_ct_dnd(a), side="right", maxiter=maxiter, tol=tol)
        return Polar(_ct_dnd(u1), h1)
    if m < n:
        raise ValueError(
            f"side='right' requires m >= n, got {a.shape}; use side='left'"
        )
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    if tol is None:
        tol = 50.0 * _real_eps(jt)
    if a.split == 1:
        a = a.resplit(0)
    comm = a.comm
    if a.split == 0 and comm.is_distributed():
        sched = _runtime_plan("polar", (m, n), jt, comm)
        phys = a._phys.astype(jt)
        lrows = int(phys.shape[0]) // comm.size
        fn = _polar_program(
            comm.mesh, comm.axis_name, lrows, n, np.dtype(jt).name,
            int(maxiter), float(tol), _cm.ring_enabled(), sched.plan_id,
        )
        u_phys, h = fn(phys)
        u_phys = _padding.mask_phys(u_phys, (m, n), 0)
        u_arr = DNDarray(u_phys, (m, n), dtype, 0, a.device, comm)
        h_arr = DNDarray(
            _place(h, comm.sharding(2, None)), (n, n), dtype, None,
            a.device, comm,
        )
        return Polar(u_arr, h_arr)
    fn = _polar_local_program(m, n, np.dtype(jt).name, int(maxiter), float(tol))
    u, h = fn(a.larray.astype(jt))
    return Polar(basics._wrap(u, a.split, a), basics._wrap(h, None, a))


# ---------------------------------------------------------------------- #
# blocked right-looking Cholesky / LU with ring lookahead                #
# ---------------------------------------------------------------------- #
def _pad_seed_diag(w, i, nb, n, n_pad, jt):
    """Column-pad a local row block to the square padded order and seed
    ones on the pad diagonal: the padded matrix is ``diag(A, I)``, whose
    factors are ``diag(L, I)`` / ``diag(L, I)·diag(U, I)`` — pad rows and
    columns never couple into the real block, and the pad identity is
    sliced away by the ``[:, :n]`` epilogue."""
    if n_pad != n:
        w = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    rows = i * nb + jnp.arange(nb)
    cols = jnp.arange(n_pad)
    mask = (rows[:, None] == cols[None, :]) & (cols[None, :] >= n)
    return jnp.where(mask, jnp.asarray(1, jt), w)


@functools.lru_cache(maxsize=64)
def _blocked_factor_program(mesh, axis_name: str, n: int, jdtype: str,
                            kind: str, pipelined: bool, plan_id: str):
    """Compiled blocked right-looking factorization (``kind`` in
    ``cholesky``/``lu``) over split-0 shards, one block row of order
    ``nb = ceil(n/p)`` per device.

    Per lap ``k``: the panel column is assembled by the cmatmul
    all-gather ring (devices above the panel contribute zeros), the
    diagonal block factors locally REPLICATED (every device runs the
    same tiny ``nb×nb`` kernel on the same bits — no broadcast needed),
    the off-diagonal L blocks come from ONE triangular solve against the
    whole gathered column, and the trailing update is a local MXU matmul
    riding under the next lap's hops. LU adds block-local partial
    pivoting (pivot search confined to the ``nb`` rows of the diagonal
    block — the paper's trade: no cross-device pivot swaps, documented
    growth-factor caveat) and a :func:`ring_bcast` of the pivoted U
    panel row."""
    p = mesh.devices.size
    jt = np.dtype(jdtype)
    nb = -(-n // p)
    n_pad = nb * p
    perm = _cm.grouped_ring_perm(1, p)

    def chol_kernel(a_loc):
        with jax.named_scope(f"redist_plan_{plan_id}"), _cm.stamp_scope("cholesky"):
            i = lax.axis_index(axis_name)
            w = _pad_seed_diag(a_loc, i, nb, n, n_pad, jt)
            lout = jnp.zeros((nb, n_pad), jt)
            for k in range(p):
                contrib = jnp.where(
                    i >= k, w[:, k * nb:(k + 1) * nb], jnp.zeros((nb, nb), jt)
                )
                col = _cm.ring_all_gather(contrib, axis_name, p, i, perm,
                                          pipelined=pipelined)
                lkk = jnp.linalg.cholesky(col[k])
                s = col.reshape(p * nb, nb)
                # the whole block column in one solve: X·L_kk^H = S, rows
                # above the panel are zero by the gather gate
                lcol = _ct(solve_triangular(lkk, _ct(s), lower=True))
                my_l = lax.dynamic_slice_in_dim(lcol, i * nb, nb, axis=0)
                my_l = jnp.where(i == k, lkk, my_l)
                lout = lout.at[:, k * nb:(k + 1) * nb].set(my_l)
                if k + 1 < p:
                    trail = lcol[(k + 1) * nb:]
                    w = w.at[:, (k + 1) * nb:].add(
                        -jnp.matmul(my_l, _ct(trail), precision="highest")
                    )
            return lout

    def lu_kernel(a_loc):
        with jax.named_scope(f"redist_plan_{plan_id}"), _cm.stamp_scope("lu"):
            i = lax.axis_index(axis_name)
            w = _pad_seed_diag(a_loc, i, nb, n, n_pad, jt)
            lout = jnp.zeros((nb, n_pad), jt)
            uout = jnp.zeros((nb, n_pad), jt)
            perm_loc = jnp.arange(nb, dtype=jnp.int32)
            detsign = jnp.asarray(1, jnp.int32)
            for k in range(p):
                contrib = jnp.where(
                    i >= k, w[:, k * nb:(k + 1) * nb], jnp.zeros((nb, nb), jt)
                )
                col = _cm.ring_all_gather(contrib, axis_name, p, i, perm,
                                          pipelined=pipelined)
                lu_pk, piv, pk = lax.linalg.lu(col[k])
                lkk = jnp.tril(lu_pk, -1) + jnp.eye(nb, dtype=jt)
                ukk = jnp.triu(lu_pk)
                detsign = detsign * jnp.prod(
                    jnp.where(piv != jnp.arange(nb, dtype=piv.dtype), -1, 1)
                ).astype(jnp.int32)
                # block-local pivoting: device k permutes its rows (and the
                # already-written L columns + provenance) before the panel
                # column is consumed
                w = jnp.where(i == k, w[pk, :], w)
                lout = jnp.where(i == k, lout[pk, :], lout)
                perm_loc = jnp.where(i == k, perm_loc[pk], perm_loc)
                s = col.reshape(p * nb, nb)
                # zero the diagonal block before the right-solve, then write
                # L_kk exactly — no rounding junk on the unit panel
                sz = lax.dynamic_update_slice(
                    s, jnp.zeros((nb, nb), jt), (k * nb, 0)
                )
                lcol = _ct(solve_triangular(_ct(ukk), _ct(sz), lower=True))
                lcol = lax.dynamic_update_slice(lcol, lkk, (k * nb, 0))
                my_l = lax.dynamic_slice_in_dim(lcol, i * nb, nb, axis=0)
                lout = lout.at[:, k * nb:(k + 1) * nb].set(my_l)
                uout = jnp.where(
                    i == k, uout.at[:, k * nb:(k + 1) * nb].set(ukk), uout
                )
                if k + 1 < p:
                    cand_u = solve_triangular(
                        lkk, w[:, (k + 1) * nb:], lower=True, unit_diagonal=True
                    )
                    urow = _cm.ring_bcast(cand_u, axis_name, p, k, perm,
                                          pipelined=pipelined)
                    uout = jnp.where(
                        i == k, uout.at[:, (k + 1) * nb:].set(cand_u), uout
                    )
                    w = w.at[:, (k + 1) * nb:].add(
                        -jnp.matmul(my_l, urow, precision="highest")
                    )
            gperm = i * nb + perm_loc
            return lout, uout, gperm, detsign

    if kind == "cholesky":
        mapped = _shard_map(
            chol_kernel, mesh=mesh, in_specs=(_PS(axis_name, None),),
            out_specs=_PS(axis_name, None), check_vma=False,
        )

        def fn(a_phys):
            return mapped(a_phys)[:, :n]

    elif kind == "lu":
        mapped = _shard_map(
            lu_kernel, mesh=mesh, in_specs=(_PS(axis_name, None),),
            out_specs=(_PS(axis_name, None), _PS(axis_name, None),
                       _PS(axis_name), _PS()),
            check_vma=False,
        )

        def fn(a_phys):
            lout, uout, gperm, detsign = mapped(a_phys)
            return lout[:, :n], uout[:, :n], gperm, detsign

    else:
        raise ValueError(f"unknown factorization kind {kind!r}")
    return jax.jit(fn)


def _check_square(a: DNDarray, what: str):
    if a._is_planar:
        from .. import complex_planar as _cp

        raise _cp.policy_error(f"{what} on planar complex operands")
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"{what} requires a square 2-D matrix, got {a.shape}")


def cholesky(a: DNDarray) -> DNDarray:
    """Cholesky factor ``L`` (lower triangular, ``A = L L^H``) of a
    Hermitian positive-definite matrix.

    Distributed split-0/1 operands run the blocked right-looking ring
    program (``p(p-1)`` collective-permutes, pre-declared plan); local
    operands use XLA's kernel. Only the lower triangle of ``A`` is read.
    """
    sanitize_in(a)
    _check_square(a, "ht.linalg.cholesky")
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    if a.split == 1:
        a = a.resplit(0)
    comm = a.comm
    n = int(a.shape[0])
    if a.split == 0 and comm.is_distributed():
        sched = _runtime_plan("cholesky", (n, n), jt, comm)
        fn = _blocked_factor_program(
            comm.mesh, comm.axis_name, n, np.dtype(jt).name, "cholesky",
            _cm.ring_enabled(), sched.plan_id,
        )
        l_phys = fn(a._phys.astype(jt))
        l_phys = _padding.mask_phys(l_phys, (n, n), 0)
        return DNDarray(l_phys, (n, n), dtype, 0, a.device, comm)
    result = jnp.linalg.cholesky(a.larray.astype(jt))
    return basics._wrap(result, a.split, a)


def _lu_factor(a: DNDarray):
    """Factor ``A[perm] = L U`` → ``(perm, L, U, sign)`` with ``sign``
    the (replicated, int32) parity of the permutation — the internal
    form :func:`lu`, :func:`solve` and the ``det`` rewiring share.
    Pivoting is block-local (within each device's ``ceil(n/p)`` rows) in
    the distributed form."""
    sanitize_in(a)
    _check_square(a, "ht.linalg.lu")
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    if a.split == 1:
        a = a.resplit(0)
    comm = a.comm
    n = int(a.shape[0])
    if a.split == 0 and comm.is_distributed():
        sched = _runtime_plan("lu", (n, n), jt, comm)
        fn = _blocked_factor_program(
            comm.mesh, comm.axis_name, n, np.dtype(jt).name, "lu",
            _cm.ring_enabled(), sched.plan_id,
        )
        l_phys, u_phys, perm_phys, sign = fn(a._phys.astype(jt))
        l_phys = _padding.mask_phys(l_phys, (n, n), 0)
        u_phys = _padding.mask_phys(u_phys, (n, n), 0)
        perm_phys = _padding.mask_phys(perm_phys, (n,), 0)
        return (
            DNDarray(perm_phys, (n,), types.int32, 0, a.device, comm),
            DNDarray(l_phys, (n, n), dtype, 0, a.device, comm),
            DNDarray(u_phys, (n, n), dtype, 0, a.device, comm),
            sign,
        )
    lu_p, piv, pk = lax.linalg.lu(a.larray.astype(jt))
    nloc = lu_p.shape[-1]
    l_arr = jnp.tril(lu_p, -1) + jnp.eye(nloc, dtype=jt)
    u_arr = jnp.triu(lu_p)
    sign = jnp.prod(
        jnp.where(piv != jnp.arange(nloc, dtype=piv.dtype), -1, 1)
    ).astype(jnp.int32)
    return (
        basics._wrap(pk.astype(jnp.int32), a.split, a),
        basics._wrap(l_arr, a.split, a),
        basics._wrap(u_arr, a.split, a),
        sign,
    )


def lu(a: DNDarray) -> LU:
    """LU factorization with partial pivoting: ``LU(perm, L, U)`` such
    that ``A[perm] = L @ U`` (``L`` unit lower, ``U`` upper triangular).

    The distributed form pivots BLOCK-LOCALLY — the pivot search is
    confined to each device's block row, so no pivot row ever crosses
    the wire (the matmul-native trade; element growth can exceed the
    global-pivoting bound on adversarial operands). ``perm`` is the
    row-provenance vector: row ``r`` of ``L @ U`` is row ``perm[r]`` of
    ``A``."""
    perm, l_arr, u_arr, _ = _lu_factor(a)
    return LU(perm, l_arr, u_arr)


# ---------------------------------------------------------------------- #
# block triangular solves                                                #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _blocked_solve_program(mesh, axis_name: str, n: int, nrhs: int,
                           jdtype: str, kind: str, pipelined: bool,
                           plan_id: str):
    """Compiled block back/forward-substitution against distributed
    factors (``kind`` in ``chol``/``lu``), split-0 RHS of ``nrhs``
    columns.

    Forward sweep: each lap's diagonal solve happens on the owning
    device and the solved block rides a :func:`ring_bcast` to the
    devices still accumulating (every other device's candidate solve is
    discarded — SPMD congruence at the cost of one tiny redundant
    ``nb×nb`` solve, never a wrong bit). Backward sweep: Cholesky's
    ``L^H x = y`` runs gather-sum form (each device keeps its own
    solution block; the partial products ride ONE all-gather ring per
    lap, summed in fixed stack order), LU's ``U x = y`` the descending
    broadcast form. Census: ``2(p-1)^2`` collective-permutes either way.
    """
    p = mesh.devices.size
    jt = np.dtype(jdtype)
    nb = -(-n // p)
    n_pad = nb * p
    perm = _cm.grouped_ring_perm(1, p)
    zero = jnp.zeros((), jnp.int32)

    def chol_kernel(l_loc, b_loc):
        with jax.named_scope(f"redist_plan_{plan_id}"), _cm.stamp_scope("solve"):
            i = lax.axis_index(axis_name)
            big_l = _pad_seed_diag(l_loc, i, nb, n, n_pad, jt)
            diag_i = lax.dynamic_slice(big_l, (zero, i * nb), (nb, nb))
            acc = b_loc
            yout = jnp.zeros((nb, nrhs), jt)
            for k in range(p):
                cand = solve_triangular(diag_i, acc, lower=True)
                if k + 1 < p:
                    y_k = _cm.ring_bcast(cand, axis_name, p, k, perm,
                                         pipelined=pipelined)
                else:
                    y_k = cand
                yout = jnp.where(i == k, cand, yout)
                if k + 1 < p:
                    acc = acc - jnp.matmul(
                        big_l[:, k * nb:(k + 1) * nb], y_k, precision="highest"
                    )
            xout = jnp.zeros((nb, nrhs), jt)
            for k in range(p - 1, -1, -1):
                if k + 1 < p:
                    contrib = jnp.where(
                        i > k,
                        jnp.matmul(_ct(big_l[:, k * nb:(k + 1) * nb]), xout,
                                   precision="highest"),
                        jnp.zeros((nb, nrhs), jt),
                    )
                    stacked = _cm.ring_all_gather(contrib, axis_name, p, i,
                                                  perm, pipelined=pipelined)
                    ssum = jnp.sum(stacked, axis=0)
                else:
                    ssum = jnp.zeros((nb, nrhs), jt)
                cand = solve_triangular(_ct(diag_i), yout - ssum, lower=False)
                xout = jnp.where(i == k, cand, xout)
            return xout

    def lu_kernel(l_loc, u_loc, perm_loc, b_loc):
        with jax.named_scope(f"redist_plan_{plan_id}"), _cm.stamp_scope("solve"):
            i = lax.axis_index(axis_name)
            big_l = l_loc if n_pad == n else jnp.pad(l_loc, ((0, 0), (0, n_pad - n)))
            big_u = _pad_seed_diag(u_loc, i, nb, n, n_pad, jt)
            diag_l = lax.dynamic_slice(big_l, (zero, i * nb), (nb, nb))
            diag_u = lax.dynamic_slice(big_u, (zero, i * nb), (nb, nb))
            # apply the block-local row permutation to the RHS; pad slots
            # clamp to row 0 (garbage confined to pad rows: the factors'
            # pad columns are zero against real rows, and the output pad
            # is re-masked by the wrapper)
            loc = jnp.clip(perm_loc.astype(jnp.int32) - i * nb, 0, nb - 1)
            acc = b_loc[loc]
            yout = jnp.zeros((nb, nrhs), jt)
            for k in range(p):
                cand = solve_triangular(diag_l, acc, lower=True,
                                        unit_diagonal=True)
                if k + 1 < p:
                    y_k = _cm.ring_bcast(cand, axis_name, p, k, perm,
                                         pipelined=pipelined)
                else:
                    y_k = cand
                yout = jnp.where(i == k, cand, yout)
                if k + 1 < p:
                    acc = acc - jnp.matmul(
                        big_l[:, k * nb:(k + 1) * nb], y_k, precision="highest"
                    )
            xout = jnp.zeros((nb, nrhs), jt)
            acc2 = yout
            for k in range(p - 1, -1, -1):
                cand = solve_triangular(diag_u, acc2, lower=False)
                if k > 0:
                    x_k = _cm.ring_bcast(cand, axis_name, p, k, perm,
                                         pipelined=pipelined)
                else:
                    x_k = cand
                xout = jnp.where(i == k, cand, xout)
                if k > 0:
                    acc2 = acc2 - jnp.matmul(
                        big_u[:, k * nb:(k + 1) * nb], x_k, precision="highest"
                    )
            return xout

    if kind == "chol":
        mapped = _shard_map(
            chol_kernel, mesh=mesh,
            in_specs=(_PS(axis_name, None), _PS(axis_name, None)),
            out_specs=_PS(axis_name, None), check_vma=False,
        )
    elif kind == "lu":
        mapped = _shard_map(
            lu_kernel, mesh=mesh,
            in_specs=(_PS(axis_name, None), _PS(axis_name, None),
                      _PS(axis_name), _PS(axis_name, None)),
            out_specs=_PS(axis_name, None), check_vma=False,
        )
    else:
        raise ValueError(f"unknown solve kind {kind!r}")
    return jax.jit(mapped)


def _apply_factor_local(kind, b_arr, l_arr, u_arr=None, perm_arr=None):
    """Local (replicated) triangular-solve chain — shared by the local
    :func:`solve` path, the serving endpoint and the staged HostArray
    stream on 1-device worlds."""
    if kind == "chol":
        y = solve_triangular(l_arr, b_arr, lower=True)
        return solve_triangular(_ct(l_arr), y, lower=False)
    y = solve_triangular(l_arr, b_arr[perm_arr], lower=True, unit_diagonal=True)
    return solve_triangular(u_arr, y, lower=False)


def _solve_factored(kind, b: DNDarray, l_arr: DNDarray,
                    u_arr: Optional[DNDarray] = None,
                    pvec: Optional[DNDarray] = None) -> DNDarray:
    """Run the distributed block triangular solve against pre-computed
    factors. ``b`` may be 1-D or 2-D; output split 0."""
    comm = l_arr.comm
    n = int(l_arr.shape[0])
    jt = l_arr.dtype.jax_type()
    b0 = b if b.split == 0 else b.resplit(0)
    vec = b0.ndim == 1
    b_phys = b0._phys.astype(jt)
    if vec:
        b_phys = b_phys[:, None]
    nrhs = int(b_phys.shape[1])
    sched = _runtime_plan("solve-" + kind, (n, nrhs), jt, comm)
    fn = _blocked_solve_program(
        comm.mesh, comm.axis_name, n, nrhs, np.dtype(jt).name, kind,
        _cm.ring_enabled(), sched.plan_id,
    )
    if kind == "chol":
        x_phys = fn(l_arr._phys.astype(jt), b_phys)
    else:
        x_phys = fn(l_arr._phys.astype(jt), u_arr._phys.astype(jt),
                    pvec._phys, b_phys)
    x_phys = _padding.mask_phys(x_phys, (n, nrhs), 0)
    if vec:
        return DNDarray(x_phys[:, 0], (n,), l_arr.dtype, 0, b.device, comm)
    return DNDarray(x_phys, (n, nrhs), l_arr.dtype, 0, b.device, comm)


def solve(a: DNDarray, b, assume_a: str = "gen"):
    """Solve ``A x = b`` for square ``A``.

    ``assume_a="gen"`` factors through the blocked :func:`lu`,
    ``assume_a="pos"`` through :func:`cholesky` — for distributed
    operands both chains are blocked ring programs with pre-declared
    collective plans (NO gather-and-replicate of the operand; see
    docs/MIGRATING.md). ``b`` may be a vector, a matrix of RHS columns,
    or a :class:`~heat_tpu.redistribution.staging.HostArray` of RHS
    columns — the host form streams column windows through the staged
    double-buffer (PR 11) and returns a HostArray of solutions.
    """
    from ...redistribution import staging as _staging

    if isinstance(b, _staging.HostArray):
        return _solve_host_rhs(a, b, assume_a=assume_a)
    sanitize_in(a)
    sanitize_in(b)
    _check_square(a, "ht.linalg.solve")
    if b._is_planar:
        from .. import complex_planar as _cp

        raise _cp.policy_error("ht.linalg.solve on planar complex operands")
    if assume_a not in ("gen", "pos"):
        raise ValueError(f"assume_a must be 'gen' or 'pos', got {assume_a!r}")
    n = int(a.shape[0])
    if b.ndim not in (1, 2) or int(b.shape[0]) != n:
        raise ValueError(
            f"b must be (n,) or (n, nrhs) with n={n}, got {b.shape}"
        )
    comm = a.comm
    distributed = comm.is_distributed() and (
        a.split is not None or b.split is not None
    )
    if distributed:
        if assume_a == "pos":
            l_arr = cholesky(a)
            return _solve_factored("chol", b, l_arr)
        pvec, l_arr, u_arr, _sign = _lu_factor(a)
        return _solve_factored("lu", b, l_arr, u_arr, pvec)
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    arr_a = a.larray.astype(jt)
    arr_b = b.larray.astype(jt)
    if assume_a == "pos":
        c = jnp.linalg.cholesky(arr_a)
        res = _apply_factor_local("chol", arr_b if b.ndim == 2 else arr_b[:, None], c)
        res = res if b.ndim == 2 else res[:, 0]
    else:
        res = jnp.linalg.solve(arr_a, arr_b)
    return basics._wrap(res, b.split if b.split is not None else a.split, a)


# ---------------------------------------------------------------------- #
# symmetric eigensolver: polar-based spectral divide-and-conquer         #
# ---------------------------------------------------------------------- #
def _projector_rank(p_arr: jax.Array) -> int:
    """Host read of a spectral projector's rank (= its trace, an
    integer up to polar convergence error). This is the ONE data-
    dependent boundary of the eigensolver — declared in
    ``analysis/boundaries.DATA_DEPENDENT_BOUNDARIES`` so commcheck
    reports the sync as a known algorithmic decision point, not a
    stray host round-trip."""
    tr = jnp.real(jnp.trace(p_arr))
    return int(np.round(float(np.asarray(jax.device_get(tr)))))


def _range_probe(n: int, k: int, depth: int, branch: int, jt) -> jax.Array:
    """Deterministic Gaussian range probe for the projector subspace —
    keyed by (n, k, depth, branch) so every run, device and issue order
    draws the same bits (the suite's bit-identity contract extends
    through the randomized range finder)."""
    key = jax.random.key(0xE16)
    for t in (n, k, depth, branch):
        key = jax.random.fold_in(key, t)
    rt = np.dtype(jnp.finfo(np.dtype(jt)).dtype)
    om = jax.random.normal(key, (n, k), rt)
    if np.dtype(jt).kind == "c":
        om = om + 1j * jax.random.normal(jax.random.fold_in(key, 7), (n, k), rt)
    return om.astype(jt)


def _eigh_local(a: DNDarray):
    w, v = jnp.linalg.eigh(a.larray)
    return w, basics._wrap(v, a.split, a)


def _ring_xhy(x: DNDarray, y: DNDarray) -> jax.Array:
    """Replicated ``X^H Y`` for split-0 operands via the cmatmul ring
    program — the contraction axis IS the split axis, so this is the
    collective-matmul case. Used unconditionally by the eigensolver's
    Rayleigh-Ritz compression: the overlap knob only picks the ring's
    sequential vs pipelined issue order (bit-identical), never the
    GSPMD barrier reduction (whose summation order differs)."""
    comm = x.comm
    jt = x.dtype.jax_type()
    kx, ky = int(x.shape[1]), int(y.shape[1])
    fn = basics._cmatmul_program(
        comm.mesh, comm.axis_name, kx, int(x._phys.shape[0]) // comm.size,
        ky, np.dtype(jt).name, "highest", _cm.ring_enabled(),
    )
    return fn(_ct(x._phys.astype(jt)), y._phys.astype(jt))


def _eigh_branch(a: DNDarray, proj: DNDarray, k: int, depth: int, branch: int):
    """One side of the spectral split: subspace basis from TSQR of
    projector-range probes (one refinement pass), Rayleigh-Ritz
    compression ``Q^H A Q`` (a ring matmul when overlap is on — the
    contraction-split case), then recursion or a local solve."""
    from .qr import qr as _qr

    jt = a.dtype.jax_type()
    n = int(a.shape[0])
    om = basics._wrap(_range_probe(n, k, depth, branch, jt), None, a)
    q = _qr(basics.matmul(proj, om, precision="highest"), calc_q=True).Q
    q = _qr(basics.matmul(proj, q, precision="highest"), calc_q=True).Q
    bq = basics.matmul(a, q, precision="highest")
    a_sub = _ring_xhy(q, bq)
    sub_l = 0.5 * (a_sub + _ct(a_sub))
    if k >= _EIGH_RESPLIT_MIN_N and a.comm.is_distributed():
        # recurse on the split-0 sub-operand — the resplit rides the
        # redistribution planner like any other movement
        sub = basics._wrap(sub_l, None, a).resplit(0)
        w, v = _eigh_dc(sub, depth + 1)
        u = basics.matmul(q, v)
    else:
        w, v = jnp.linalg.eigh(sub_l)
        u = basics.matmul(q, basics._wrap(v, None, a))
    return w, u


def _eigh_dc(a: DNDarray, depth: int):
    """Spectral divide-and-conquer on a Hermitian split-0 operand:
    shift by the diagonal median, ``S = sign(A - μI)`` via
    :func:`polar`, split the spectrum across the two projectors
    ``(I ∓ S)/2``, solve each side in its subspace, merge sorted."""
    comm = a.comm
    n = int(a.shape[0])
    if (not comm.is_distributed()) or a.split != 0 or n < 4 \
            or depth >= _EIGH_MAX_DEPTH:
        return _eigh_local(a)
    jt = a.dtype.jax_type()
    mu = jnp.median(jnp.real(jnp.diagonal(a.larray))).astype(jt)
    eye = jnp.eye(n, dtype=jt)
    shifted = basics._wrap(a.larray - mu * eye, 0, a)
    s_u, _ = polar(shifted)
    proj_lo = basics._wrap(0.5 * (eye - s_u.larray), 0, a)
    k = _projector_rank(proj_lo.larray)
    if k <= 0 or k >= n:
        # degenerate split (spectrum clustered at the shift): the
        # documented fallback is the local solve
        return _eigh_local(a)
    w1, u1 = _eigh_branch(a, proj_lo, k, depth, 0)
    proj_hi = basics._wrap(0.5 * (eye + s_u.larray), 0, a)
    w2, u2 = _eigh_branch(a, proj_hi, n - k, depth, 1)
    w_all = jnp.concatenate([w1, w2])
    order = jnp.argsort(w_all)
    v_phys = jnp.concatenate([u1._phys, u2._phys], axis=1)[:, order]
    v = DNDarray(v_phys, (n, n), a.dtype, 0, a.device, comm)
    return w_all[order], v


def eigh(a: DNDarray, UPLO: str = "L") -> Eigh:
    """Eigendecomposition of a Hermitian matrix: ``Eigh(eigenvalues,
    eigenvectors)``, eigenvalues ascending (replicated), eigenvectors
    split 0 in the distributed form.

    Distributed operands run polar-based spectral divide-and-conquer —
    the whole solve is matmul chains (Newton–Schulz polar + TSQR +
    Rayleigh-Ritz), recursing through the redistribution planner on
    sub-operands of order ``>= _EIGH_RESPLIT_MIN_N``. Only the ``UPLO``
    triangle of ``A`` is read."""
    sanitize_in(a)
    _check_square(a, "ht.linalg.eigh")
    if UPLO not in ("L", "U"):
        raise ValueError(f"UPLO must be 'L' or 'U', got {UPLO!r}")
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    if a.split == 1:
        a = a.resplit(0)
    comm = a.comm
    if a.split == 0 and comm.is_distributed():
        arr = a.larray.astype(jt)
        if UPLO == "L":
            herm = jnp.tril(arr) + _ct(jnp.tril(arr, -1))
        else:
            herm = jnp.triu(arr) + _ct(jnp.triu(arr, 1))
        a_h = basics._wrap(herm, 0, a)
        if a_h.dtype != dtype:
            a_h = DNDarray(a_h._phys, a_h.shape, dtype, a_h.split, a.device, comm)
        w, v = _eigh_dc(a_h, 0)
        return Eigh(basics._wrap(w, None, a), v)
    w, v = jnp.linalg.eigh(a.larray.astype(jt), UPLO=UPLO)
    return Eigh(basics._wrap(w, None, a), basics._wrap(v, a.split, a))


# ---------------------------------------------------------------------- #
# HostArray RHS: the staged-window solve stream                          #
# ---------------------------------------------------------------------- #
def _solve_host_rhs(a: DNDarray, b, assume_a: str = "gen"):
    """Solve against a host-resident RHS panel: factor once, then
    stream column windows of ``b`` through the depth-2 staged
    double-buffer (PR 11), solving each window with the blocked
    program and writing the solutions back to host memory. Returns a
    :class:`HostArray` of solutions. When the RHS fits HBM comfortably
    (``ooc_engaged`` false) the escape hatch materializes and takes the
    ordinary :func:`solve` path."""
    from ...observability.attribution import register_plan
    from ...redistribution import staging as _staging

    sanitize_in(a)
    _check_square(a, "ht.linalg.solve")
    if assume_a not in ("gen", "pos"):
        raise ValueError(f"assume_a must be 'gen' or 'pos', got {assume_a!r}")
    n = int(a.shape[0])
    if len(b.shape) != 2 or int(b.shape[0]) != n:
        raise ValueError(
            f"HostArray b must be (n, nrhs) with n={n}, got {b.shape}"
        )
    comm = a.comm
    if not _staging.ooc_engaged(b.nbytes, host_resident=True):
        bd = basics._wrap(
            jnp.asarray(_staging.materialize(b, what="solve rhs")),
            0 if comm.is_distributed() else None, a,
        )
        return solve(a, bd, assume_a=assume_a)
    dtype = _solver_dtype(a)
    jt = dtype.jax_type()
    nrhs = int(b.shape[1])
    distributed = comm.is_distributed() and a.split is not None
    if assume_a == "pos":
        kind = "chol"
        if distributed:
            l_arr, u_arr, pvec = cholesky(a), None, None
        else:
            l_loc = jnp.linalg.cholesky(a.larray.astype(jt))
            u_loc = perm_loc = None
    else:
        kind = "lu"
        if distributed:
            pvec, l_arr, u_arr, _sign = _lu_factor(a)
        else:
            lu_p, piv, pk = lax.linalg.lu(a.larray.astype(jt))
            l_loc = jnp.tril(lu_p, -1) + jnp.eye(n, dtype=jt)
            u_loc = jnp.triu(lu_p)
            perm_loc = pk
    itemsize = np.dtype(jt).itemsize
    sched = _staging.plan_staged_passes(
        (n, nrhs), jt, [{"tag": "solve", "axis": 1, "writeback": True}],
        out_bytes=0, mesh_size=comm.size,
    )
    register_plan(sched)
    wins = _staging.window_extents((n, nrhs), itemsize, 1, _staging.slab_bytes())
    out = np.empty((n, nrhs), np.dtype(jt))

    def consume(_k, slab, ext):
        start, stop = ext
        win = jnp.asarray(slab).astype(jt)
        if distributed:
            bd = basics._wrap(win, 0, a)
            if kind == "chol":
                x = _solve_factored("chol", bd, l_arr)
            else:
                x = _solve_factored("lu", bd, l_arr, u_arr, pvec)
            out[:, start:stop] = np.asarray(jax.device_get(x.larray))
        else:
            x = _apply_factor_local(kind, win, l_loc, u_loc, perm_loc)
            out[:, start:stop] = np.asarray(jax.device_get(x))

    _staging.stream_windows(b, 1, wins, consume, plan_id=sched.plan_id)
    return _staging.HostArray(out)


# ---------------------------------------------------------------------- #
# serving endpoint                                                       #
# ---------------------------------------------------------------------- #
def solve_endpoint(fac, buckets=(8, 32, 128), name: str = "solve",
                   donate: bool = False):
    """A serving :class:`Endpoint` over pre-computed factors: batches of
    RHS vectors ``(b, n)`` are solved by the triangular chain against
    the resident factors (``fac`` is the :func:`cholesky` L or the
    :func:`lu` namedtuple). Programs are AOT-cached per bucket; the
    dispatcher's HBM admission check is armed with the memcheck-priced
    static peak."""
    from ...analysis import memcheck as _memcheck
    from ...serving.dispatcher import program_endpoint as _program_endpoint

    if isinstance(fac, LU):
        kind = "lu"
        l_arr = fac.L
        extras = (fac.L.larray, fac.U.larray, fac.perm.larray)
    elif isinstance(fac, DNDarray):
        kind = "chol"
        l_arr = fac
        extras = (fac.larray,)
    else:
        raise TypeError(
            f"fac must be a cholesky factor DNDarray or an LU namedtuple, "
            f"got {type(fac)}"
        )
    n = int(l_arr.shape[0])
    jt = l_arr.dtype.jax_type()

    def build():
        if kind == "chol":
            def run(batch, l_loc):
                x = _apply_factor_local("chol", batch.astype(l_loc.dtype).T, l_loc)
                return x.T
        else:
            def run(batch, l_loc, u_loc, perm_loc):
                x = _apply_factor_local(
                    "lu", batch.astype(l_loc.dtype).T, l_loc, u_loc, perm_loc
                )
                return x.T
        return jax.jit(run)  # shardlint: ignore[SL202] -- serving program body; the endpoint cache owns wrapping/donation (aot_cache precedent)

    peak = None
    try:
        rep = _memcheck(build(), jnp.zeros((max(buckets), n), jt), *extras)
        peak = rep.context.get("static_peak_bytes")
    except Exception:
        peak = None
    return _program_endpoint(
        build, (n,), np.dtype(jt), buckets,
        key=("linalg.solve_endpoint", kind, n, np.dtype(jt).name),
        extra_args=extras, donate=donate, name=name, static_peak_bytes=peak,
    )


from ..communication import place as _place
from ..communication import register_mesh_cache as _register_mesh_cache

# compiled factorization programs bake mesh geometry: cleared when
# init_distributed rebuilds the world
_register_mesh_cache(_polar_program)
_register_mesh_cache(_blocked_factor_program)
_register_mesh_cache(_blocked_solve_program)
