"""Distributed linear algebra (reference: /root/reference/heat/core/linalg/)."""

from .basics import *
from .qr import *
from .solver import *
from .svd import *
from .svdtools import *
from .factorizations import *
