"""Distributed linear algebra (reference: /root/reference/heat/core/linalg/)."""

from .basics import *
