"""Local LAPACK-style helpers with TPU-toolchain workarounds.

The current TPU compiler SIGABRTs (XLA ``TransposeFolding``:
``Check failed: buffer != nullptr``) when lowering ``jnp.linalg.svd``
traced in x64 mode — the int64 index iotas of the QDWH/Jacobi expansion
trigger the bug; the identical f32 computation traced with x64 disabled
compiles fine.

Since round 3 x64 is OFF on TPU by platform policy
(devices._apply_x64_policy), so the default configuration never hits the
bug and ``svd_x32_scope`` is a no-op. The scope stays ONLY for the
explicitly-forced ``ht.use_x64(True)``-on-TPU configuration, where a
32-bit SVD operand would otherwise be traced in x64 mode and crash the
compiler. CPU worlds (x64 on) lower the same traces fine and are left
untouched.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

__all__ = ["safe_svd", "safe_svdvals", "svd_x32_scope"]


def svd_x32_scope(dtype):
    """Context manager disabling x64 tracing for 32-bit SVD lowering on
    TPU — active ONLY in the forced x64-on-TPU configuration (see module
    docstring); a no-op everywhere else."""
    if (
        jnp.dtype(dtype).itemsize <= 4
        and jax.config.jax_enable_x64
        and jax.default_backend() == "tpu"
    ):
        return jax.enable_x64(False)
    return contextlib.nullcontext()


def safe_svd(a: jax.Array, full_matrices: bool = False):
    """jnp.linalg.svd with the TPU x64-lowering workaround."""
    with svd_x32_scope(a.dtype):
        return jnp.linalg.svd(a, full_matrices=full_matrices)


def safe_svdvals(a: jax.Array) -> jax.Array:
    """Singular values only."""
    with svd_x32_scope(a.dtype):
        return jnp.linalg.svd(a, compute_uv=False)
