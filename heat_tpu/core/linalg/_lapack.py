"""Local LAPACK-style helpers with TPU-toolchain workarounds.

The current TPU compiler SIGABRTs (XLA ``TransposeFolding``:
``Check failed: buffer != nullptr``) when lowering ``jnp.linalg.svd``
traced in x64 mode — the int64 index iotas of the QDWH/Jacobi expansion
trigger the bug; the identical f32 computation traced with x64 disabled
compiles fine. heat_tpu enables x64 globally for float64/int64 API parity,
so every SVD callsite goes through ``svd_x32_scope``: a scoped
``jax.enable_x64(False)`` when the operand is 32-bit (the TPU-relevant
case). 64-bit operands keep x64 (they run on CPU, whose compiler is fine).
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

__all__ = ["safe_svd", "safe_svdvals", "svd_x32_scope"]


def svd_x32_scope(dtype):
    """Context manager disabling x64 tracing for 32-bit SVD lowering."""
    if jnp.dtype(dtype).itemsize <= 4:
        return jax.enable_x64(False)
    return contextlib.nullcontext()


def safe_svd(a: jax.Array, full_matrices: bool = False):
    """jnp.linalg.svd with the TPU x64-lowering workaround."""
    with svd_x32_scope(a.dtype):
        return jnp.linalg.svd(a, full_matrices=full_matrices)


def safe_svdvals(a: jax.Array) -> jax.Array:
    """Singular values only."""
    with svd_x32_scope(a.dtype):
        return jnp.linalg.svd(a, compute_uv=False)
