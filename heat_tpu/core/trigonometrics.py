"""Trigonometric and hyperbolic functions.

API parity with /root/reference/heat/core/trigonometrics.py (24 exports,
all pure-local elementwise via ``__local_op`` — sharding preserved, no
communication).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "acos",
    "acosh",
    "asin",
    "asinh",
    "atan",
    "atan2",
    "atanh",
    "arccos",
    "arccosh",
    "arcsin",
    "arcsinh",
    "arctan",
    "arctan2",
    "arctanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def acos(x: DNDarray, out=None) -> DNDarray:
    """Elementwise arccosine."""
    return _operations.__local_op(jnp.arccos, x, out)


arccos = acos


def acosh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise inverse hyperbolic cosine."""
    return _operations.__local_op(jnp.arccosh, x, out)


arccosh = acosh


def asin(x: DNDarray, out=None) -> DNDarray:
    """Elementwise arcsine."""
    return _operations.__local_op(jnp.arcsin, x, out)


arcsin = asin


def asinh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise inverse hyperbolic sine."""
    return _operations.__local_op(jnp.arcsinh, x, out)


arcsinh = asinh


def atan(x: DNDarray, out=None) -> DNDarray:
    """Elementwise arctangent."""
    return _operations.__local_op(jnp.arctan, x, out)


arctan = atan


def atan2(t1, t2) -> DNDarray:
    """Quadrant-aware arctangent of t1/t2."""
    from . import types

    def _op(a, b):
        if jnp.issubdtype(a.dtype, jnp.integer):
            a = a.astype(jnp.float32)
        if jnp.issubdtype(b.dtype, jnp.integer):
            b = b.astype(jnp.float32)
        return jnp.arctan2(a, b)

    return _operations.__binary_op(_op, t1, t2)


arctan2 = atan2


def atanh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise inverse hyperbolic tangent."""
    return _operations.__local_op(jnp.arctanh, x, out)


arctanh = atanh


def cos(x: DNDarray, out=None) -> DNDarray:
    """Elementwise cosine."""
    return _operations.__local_op(jnp.cos, x, out)


def cosh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise hyperbolic cosine."""
    return _operations.__local_op(jnp.cosh, x, out)


def deg2rad(x: DNDarray, out=None) -> DNDarray:
    """Degrees to radians."""
    return _operations.__local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x: DNDarray, out=None) -> DNDarray:
    """Radians to degrees."""
    return _operations.__local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x: DNDarray, out=None) -> DNDarray:
    """Elementwise sine."""
    return _operations.__local_op(jnp.sin, x, out)


def sinh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise hyperbolic sine."""
    return _operations.__local_op(jnp.sinh, x, out)


def tan(x: DNDarray, out=None) -> DNDarray:
    """Elementwise tangent."""
    return _operations.__local_op(jnp.tan, x, out)


def tanh(x: DNDarray, out=None) -> DNDarray:
    """Elementwise hyperbolic tangent."""
    return _operations.__local_op(jnp.tanh, x, out)


DNDarray.cos = cos
DNDarray.sin = sin
DNDarray.tan = tan
DNDarray.cosh = cosh
DNDarray.sinh = sinh
DNDarray.tanh = tanh
