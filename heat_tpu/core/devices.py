"""Device abstraction for heat_tpu.

API parity with the reference device module
(/root/reference/heat/core/devices.py: ``Device`` at devices.py:17, ``cpu``
singleton at :97, ``get_device``/``sanitize_device``/``use_device`` at
:137-190), redesigned for JAX: a ``Device`` names a *platform* whose devices
form the mesh, not a single rank-local accelerator. GPU round-robin
assignment by MPI rank (reference devices.py:114-120) has no analog — the
single controller owns every device of the platform.
"""

from __future__ import annotations

import jax

from typing import Any, Optional, Union

__all__ = [
    "Device",
    "complex_mode",
    "cpu",
    "get_device",
    "sanitize_device",
    "supports_complex",
    "use_complex",
    "use_device",
    "use_x64",
]


class Device:
    """A platform on which heat_tpu arrays live.

    Parameters
    ----------
    device_type : str
        Platform name: ``'cpu'``, ``'gpu'`` or ``'tpu'``.
    device_id : int
        Principal device index (kept for reference-API parity; the mesh
        spans all devices of the platform).
    jax_platform : str
        The JAX platform string backing this device.
    """

    def __init__(self, device_type: str, device_id: int = 0, jax_platform: Optional[str] = None):
        self.__device_type = str(device_type)
        self.__device_id = int(device_id)
        self.__jax_platform = jax_platform if jax_platform is not None else str(device_type)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def jax_platform(self) -> str:
        return self.__jax_platform

    # reference-API name (devices.py:76 exposes torch_device)
    @property
    def torch_device(self) -> str:
        return f"{self.__jax_platform}:{self.__device_id}"

    def jax_devices(self):
        """All JAX devices of this platform (the mesh population)."""
        return jax.devices(self.__jax_platform)

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.__device_type}:{self.__device_id}"

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            try:
                other = sanitize_device(other)
                return self == other
            except (ValueError, TypeError):
                return False
        return NotImplemented

    def __hash__(self):
        return hash(str(self))


cpu = Device("cpu", 0, "cpu")
"""The standard CPU device spanning all host devices."""

# populated lazily: probing platforms initializes the XLA backend, which
# must not happen at import time or jax.distributed.initialize (multi-host
# bootstrap, communication.init_distributed) can never run afterwards
_registry = {"cpu": cpu}
_detected = False
__default_device: Optional[Device] = None


# 64-bit (x64) policy. JAX's x64 flag is global and poisons TPU traces
# (the TPU compiler has no 64-bit arithmetic and SIGABRTs on some x64-mode
# lowerings, see linalg/_lapack.py), so the framework decides it PER
# PLATFORM at first backend use instead of blanket-enabling it at import:
# CPU/GPU get full float64/int64 parity with the reference; TPU runs with
# x64 off and 64-bit dtype requests degrade to 32-bit (types.degrade64).
# ``use_x64`` overrides explicitly.
_x64_choice: "Optional[bool]" = None


def use_x64(flag: "Optional[bool]" = None) -> bool:
    """Set (or, with ``flag=None``, query) the 64-bit dtype mode.

    ``use_x64(True)`` enables real float64/int64 arrays everywhere —
    including TPU, where 64-bit arithmetic is emulated and some linalg
    lowerings are fragile (safe_svd guards the known compiler bug).
    ``use_x64(False)`` degrades every 64-bit dtype request to its 32-bit
    counterpart (the TPU default). Returns the active mode.

    A pure query resolves the platform policy first, which initializes
    the backend — in a multi-host program, call ``init_distributed``
    BEFORE querying (the same ordering every backend-touching call has).
    An explicit set is recorded without touching the backend and
    overrides the platform policy whenever it is (or was) decided."""
    global _x64_choice
    if flag is not None:
        _x64_choice = bool(flag)
        _set_x64(_x64_choice)
    else:
        _ensure_detected()  # an undecided policy would report JAX's default
    return bool(jax.config.jax_enable_x64)


def _set_x64(enable: bool) -> None:
    from . import types as _types

    # No warnings-filter games: internal code never requests a 64-bit jax
    # dtype in degrade mode (it routes through types.index_jax_type /
    # wide_jax_type), so JAX's truncation warnings stay untouched for the
    # user's own calls (ADVICE r3: a process-global filter suppressed
    # them for ALL code in the process).
    jax.config.update("jax_enable_x64", bool(enable))
    _types._DEGRADE_64 = not enable


def _apply_x64_policy(backend: str) -> None:
    if _x64_choice is None:
        _set_x64(backend in ("cpu", "gpu"))


# Complex platform policy (VERDICT r4 #3, planar decomposition in r5).
# The reference's complex surface (complex_math.py:1-110) works on every
# device class; the TPU backend of this environment rejects ANY complex
# work with a raw ``UNIMPLEMENTED: TPU backend error`` — and (measured)
# even one merely ENQUEUED complex op leaves the runtime permanently
# failing, so support cannot be probed dynamically. Mirroring the x64
# policy above, the framework decides PER PLATFORM NAME and runs in one
# of three modes (``complex_mode``):
#   "native" — cpu/gpu default: ordinary complex jax arrays.
#   "planar" — default on accelerator plugins: complex DNDarrays store
#              split real/imaginary f32 planes and the documented complex
#              surface runs as plane arithmetic (core/complex_planar.py);
#              anything outside it raises the actionable policy error.
#   "refuse" — the round-4 fail-fast behavior: complex creation raises.
# ``use_complex(True)`` forces native (for a TPU runtime that does
# implement complex), ``use_complex("planar")`` / ``use_complex(False)``
# force planar / refuse (also on cpu, where the test suite exercises the
# accelerator behavior).
_complex_choice: "Optional[object]" = None


def use_complex(flag: "Optional[object]" = None) -> bool:
    """Set (or, with ``flag=None``, query) the complex-dtype policy.

    ``True`` forces native complex arrays, ``"planar"`` forces the planar
    (split real/imaginary plane) representation, ``False`` forces
    refusal at creation time, ``"auto"`` restores platform resolution
    (native on cpu/gpu, planar on accelerator plugins). Returns whether
    NATIVE complex is active; see ``complex_mode`` for the full mode."""
    global _complex_choice
    if flag is not None:
        if flag not in (True, False, "planar", "auto"):
            raise ValueError(f"use_complex expects True/False/'planar'/'auto', got {flag!r}")
        # normalize truthy/falsy ints (1/0, np.bool_) to real booleans so
        # complex_mode's identity checks see them
        if flag == "auto":
            _complex_choice = None
        elif flag == "planar":
            _complex_choice = "planar"
        else:
            _complex_choice = bool(flag)
    return supports_complex()


def complex_mode() -> str:
    """Active complex policy: ``"native"``, ``"planar"`` or ``"refuse"``
    (see the policy note above). Resolving the policy initializes the
    backend, like every platform policy here."""
    if _complex_choice is True:
        return "native"
    if _complex_choice is False:
        return "refuse"
    if _complex_choice == "planar":
        return "planar"
    _ensure_detected()
    try:
        backend = jax.default_backend()
    except RuntimeError:
        backend = "cpu"
    return "native" if backend in ("cpu", "gpu") else "planar"


def supports_complex() -> bool:
    """Whether NATIVE complex arrays are allowed on the default backend
    (see ``use_complex``/``complex_mode``)."""
    return complex_mode() == "native"


def _ensure_detected() -> None:
    """Probe accelerator platforms and pick the default device, once, on
    first use (NOT at import — see note on ``_registry``). Also decides
    the platform's x64 policy (see ``use_x64``)."""
    global _detected, __default_device
    if _detected:
        return
    _detected = True
    for platform in ("tpu", "gpu"):
        try:
            devs = jax.devices(platform)
        except RuntimeError:
            continue
        if devs:
            _registry[platform] = Device(platform, 0, platform)
    # axon exposes TPUs under a plugin platform name; register as 'tpu'
    if "tpu" not in _registry:
        try:
            _default = jax.devices()
            if _default and _default[0].platform not in ("cpu", "gpu"):
                _registry["tpu"] = Device("tpu", 0, _default[0].platform)
        except RuntimeError:
            pass
    # default device follows the default JAX backend (TPU when present)
    try:
        _backend = jax.default_backend()
    except RuntimeError:
        _backend = "cpu"
    if __default_device is None:
        if _backend == "cpu":
            __default_device = cpu
        elif _backend == "gpu":
            __default_device = _registry.get("gpu", cpu)
        else:
            __default_device = _registry.get("tpu", _registry.get(_backend, cpu))
    # the x64 policy is about the BACKEND, not the chosen default device —
    # it must apply even when use_device() pre-set the default
    _apply_x64_policy("cpu" if _backend == "cpu" else ("gpu" if _backend == "gpu" else "tpu"))


def __getattr__(name: str):
    """Lazy ``tpu``/``gpu`` singletons (module attributes only exist when
    the platform does — reference-API parity — but probing is deferred)."""
    if name in ("tpu", "gpu"):
        _ensure_detected()
        if name in _registry:
            return _registry[name]
        raise AttributeError(f"no {name} platform available")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_device() -> Device:
    """The currently globally set default device (reference: devices.py:137)."""
    _ensure_detected()
    return __default_device


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Sanitize a device or device identifier (reference: devices.py:149)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        _ensure_detected()
        name = device.strip().lower()
        if ":" in name:
            name, _, idx = name.partition(":")
            try:
                int(idx)
            except ValueError:
                raise ValueError(f"unknown device {device}")
        if name in _registry:
            return _registry[name]
        if name in ("cuda",):
            if "gpu" in _registry:
                return _registry["gpu"]
        raise ValueError(f"unknown device {device}")
    raise ValueError(f"unknown device {device}")


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the globally used default device (reference: devices.py:171)."""
    global __default_device
    __default_device = sanitize_device(device)
