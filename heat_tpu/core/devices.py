"""Device abstraction for heat_tpu.

API parity with the reference device module
(/root/reference/heat/core/devices.py: ``Device`` at devices.py:17, ``cpu``
singleton at :97, ``get_device``/``sanitize_device``/``use_device`` at
:137-190), redesigned for JAX: a ``Device`` names a *platform* whose devices
form the mesh, not a single rank-local accelerator. GPU round-robin
assignment by MPI rank (reference devices.py:114-120) has no analog — the
single controller owns every device of the platform.
"""

from __future__ import annotations

import jax

from typing import Any, Optional, Union

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """A platform on which heat_tpu arrays live.

    Parameters
    ----------
    device_type : str
        Platform name: ``'cpu'``, ``'gpu'`` or ``'tpu'``.
    device_id : int
        Principal device index (kept for reference-API parity; the mesh
        spans all devices of the platform).
    jax_platform : str
        The JAX platform string backing this device.
    """

    def __init__(self, device_type: str, device_id: int = 0, jax_platform: Optional[str] = None):
        self.__device_type = str(device_type)
        self.__device_id = int(device_id)
        self.__jax_platform = jax_platform if jax_platform is not None else str(device_type)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def jax_platform(self) -> str:
        return self.__jax_platform

    # reference-API name (devices.py:76 exposes torch_device)
    @property
    def torch_device(self) -> str:
        return f"{self.__jax_platform}:{self.__device_id}"

    def jax_devices(self):
        """All JAX devices of this platform (the mesh population)."""
        return jax.devices(self.__jax_platform)

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.__device_type}:{self.__device_id}"

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            try:
                other = sanitize_device(other)
                return self == other
            except (ValueError, TypeError):
                return False
        return NotImplemented

    def __hash__(self):
        return hash(str(self))


cpu = Device("cpu", 0, "cpu")
"""The standard CPU device spanning all host devices."""

# populated lazily: probing platforms initializes the XLA backend, which
# must not happen at import time or jax.distributed.initialize (multi-host
# bootstrap, communication.init_distributed) can never run afterwards
_registry = {"cpu": cpu}
_detected = False
__default_device: Optional[Device] = None


def _ensure_detected() -> None:
    """Probe accelerator platforms and pick the default device, once, on
    first use (NOT at import — see note on ``_registry``)."""
    global _detected, __default_device
    if _detected:
        return
    _detected = True
    for platform in ("tpu", "gpu"):
        try:
            devs = jax.devices(platform)
        except RuntimeError:
            continue
        if devs:
            _registry[platform] = Device(platform, 0, platform)
    # axon exposes TPUs under a plugin platform name; register as 'tpu'
    if "tpu" not in _registry:
        try:
            _default = jax.devices()
            if _default and _default[0].platform not in ("cpu", "gpu"):
                _registry["tpu"] = Device("tpu", 0, _default[0].platform)
        except RuntimeError:
            pass
    if __default_device is None:
        # default device follows the default JAX backend (TPU when present)
        try:
            _backend = jax.default_backend()
        except RuntimeError:
            _backend = "cpu"
        if _backend == "cpu":
            __default_device = cpu
        elif _backend == "gpu":
            __default_device = _registry.get("gpu", cpu)
        else:
            __default_device = _registry.get("tpu", _registry.get(_backend, cpu))


def __getattr__(name: str):
    """Lazy ``tpu``/``gpu`` singletons (module attributes only exist when
    the platform does — reference-API parity — but probing is deferred)."""
    if name in ("tpu", "gpu"):
        _ensure_detected()
        if name in _registry:
            return _registry[name]
        raise AttributeError(f"no {name} platform available")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_device() -> Device:
    """The currently globally set default device (reference: devices.py:137)."""
    _ensure_detected()
    return __default_device


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Sanitize a device or device identifier (reference: devices.py:149)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        _ensure_detected()
        name = device.strip().lower()
        if ":" in name:
            name, _, idx = name.partition(":")
            try:
                int(idx)
            except ValueError:
                raise ValueError(f"unknown device {device}")
        if name in _registry:
            return _registry[name]
        if name in ("cuda",):
            if "gpu" in _registry:
                return _registry["gpu"]
        raise ValueError(f"unknown device {device}")
    raise ValueError(f"unknown device {device}")


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the globally used default device (reference: devices.py:171)."""
    global __default_device
    __default_device = sanitize_device(device)
