"""Planar complex arrays for backends without native complex support.

Reference parity: ``/root/reference/heat/core/complex_math.py:1-110`` runs
on every torch device class. The TPU backend behind this environment has
NO complex implementation — any enqueued complex op leaves the runtime
permanently failing (see the complex policy note in ``core/devices.py``),
so support cannot be probed or degraded at the XLA level. VERDICT r4 #3
named two honest resolutions: fail fast, or planar-decompose. Round 5
implements both, selected by ``devices.complex_mode()``:

- ``"native"`` (cpu/gpu default): complex DNDarrays are ordinary complex
  jax arrays — nothing in this module runs.
- ``"planar"`` (default on unsupporting accelerator backends): a complex
  DNDarray stores a FLOAT32 physical array with a trailing plane axis of
  extent 2 (``[..., 0]`` = real, ``[..., 1]`` = imaginary) and the
  complex operator surface executes as plane arithmetic inside ordinary
  f32 XLA programs — VPU/MXU-native, sharded by the same split machinery
  (the plane axis is never split, its sharding spec entry is ``None``).
  ``complex128`` requests degrade to ``complex64`` (planes are f32),
  mirroring the x64 platform policy.
- ``"refuse"`` keeps the round-4 fail-fast behavior
  (``types.check_complex_platform``).

Supported planar surface — everything OUTSIDE it raises the actionable
``policy_error`` instead of computing silently wrong results
(``DNDarray.larray``/``_phys`` refuse planar arrays, so even unported
code paths fail loudly):

- factories: ``array``/``zeros``/``ones``/``full``/``empty``/``eye``/
  ``arange``/``linspace`` (+ ``*_like``), ``astype`` both directions
- export: ``numpy()``, printing, ``item()``, ``tolist()``, ``complex()``
- ``complex_math``: ``angle``/``conj``/``conjugate``/``imag``/``real``
- arithmetic: ``+ - * /``, ``==``, ``!=``, ``isclose``/``allclose``,
  ``reciprocal``, ``square``, ``abs``
- transcendental: ``exp``, ``sqrt``, ``log``/``log2``/``log10``,
  ``sin``/``cos``/``tan``, ``sinh``/``cosh``/``tanh``
- predicates: ``isnan``/``isinf``/``isfinite`` (element is nan/inf when
  either plane is — numpy semantics)
- ``**`` (principal-branch ``exp(b·log a)`` with numpy's zero-base
  conventions), ``var``/``std`` (real-valued complex variance)
- reductions: ``sum``/``nansum``/``mean``, ``prod`` (log-depth
  pairwise complex-multiply tree), ``cumsum``
- structural: basic-key ``__getitem__``, ``reshape``/``ravel``/
  ``flatten``, ``transpose``/``swapaxes``, ``squeeze``/``expand_dims``,
  ``flip``/``fliplr``/``flipud``/``rot90``, ``roll``, ``concatenate``/
  ``stack``, ``copy``, ``resplit`` (the plane axis is a passenger: each
  acts on the logical axes of the plane view and re-shards)
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional

from . import types
from . import _padding
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = []

# plane dtype is fixed: f32 planes <=> logical complex64 (see module doc)
PLANE_JT = jnp.float32


def policy_error(what: str) -> TypeError:
    """The actionable refusal for ops outside the planar surface — same
    contract as ``types.check_complex_platform``: name the policy, the
    reason, and the way out."""
    try:
        backend = jax.default_backend()
    except RuntimeError:  # pragma: no cover - backend init failure
        backend = "unknown"
    return TypeError(
        f"{what} is outside the planar-complex surface: complex arrays on "
        f"the '{backend}' backend run in planar (split real/imaginary "
        "plane) form because its XLA backend has no complex "
        "implementation, and only the documented operator surface is "
        "planar-decomposed. Run this part of the workload on the CPU "
        "platform, or keep real and imaginary parts as separate real "
        "arrays. See docs/MIGRATING.md, 'Complex platform policy'."
    )


def active() -> bool:
    """True when complex dtypes planar-decompose on this backend."""
    from . import devices

    return devices.complex_mode() == "planar"


def is_planar(x) -> bool:
    return isinstance(x, DNDarray) and x._is_planar


def wrap(phys: jax.Array, gshape, split, device, comm) -> DNDarray:
    """Construct a planar DNDarray from a padded plane array of shape
    ``phys_shape(gshape, split) + (2,)``."""
    return DNDarray(phys, tuple(gshape), types.complex64, split, device, comm)


# --------------------------------------------------------------------- #
# plane helpers (used inside traced programs)                           #
# --------------------------------------------------------------------- #
def _re(p):
    return p[..., 0]


def _im(p):
    return p[..., 1]


def _pk(r, i):
    return jnp.stack([r, i], axis=-1)


def _cmul(a, b):
    return _pk(_re(a) * _re(b) - _im(a) * _im(b), _re(a) * _im(b) + _im(a) * _re(b))


def _cdiv(a, b):
    d = _re(b) * _re(b) + _im(b) * _im(b)
    return _pk((_re(a) * _re(b) + _im(a) * _im(b)) / d, (_im(a) * _re(b) - _re(a) * _im(b)) / d)


def _cnan(p):
    return jnp.isnan(_re(p)) | jnp.isnan(_im(p))


def _cexp(p):
    e = jnp.exp(_re(p))
    return _pk(e * jnp.cos(_im(p)), e * jnp.sin(_im(p)))


def _csqrt(p):
    # polar form; atan2's (-pi, pi] range halves onto the principal branch
    r = jnp.sqrt(jnp.hypot(_re(p), _im(p)))
    th = 0.5 * jnp.arctan2(_im(p), _re(p))
    return _pk(r * jnp.cos(th), r * jnp.sin(th))


def _clog(p):
    return _pk(jnp.log(jnp.hypot(_re(p), _im(p))), jnp.arctan2(_im(p), _re(p)))


def _csin(p):
    return _pk(jnp.sin(_re(p)) * jnp.cosh(_im(p)), jnp.cos(_re(p)) * jnp.sinh(_im(p)))


def _ccos(p):
    return _pk(jnp.cos(_re(p)) * jnp.cosh(_im(p)), -jnp.sin(_re(p)) * jnp.sinh(_im(p)))


def _csinh(p):
    return _pk(jnp.sinh(_re(p)) * jnp.cos(_im(p)), jnp.cosh(_re(p)) * jnp.sin(_im(p)))


def _ccosh(p):
    return _pk(jnp.cosh(_re(p)) * jnp.cos(_im(p)), jnp.sinh(_re(p)) * jnp.sin(_im(p)))


def _cpow(a, b):
    # principal-branch complex power via exp(b·log a), with numpy's
    # conventions at the edges it routes here: x**0 = 1 for EVERY base
    # (including nan/inf), 0**0 = 1, 0**(positive real) = 0, nan+nanj
    # for other zero-base exponents. Integral scalar exponents never
    # reach this path (binary() routes them through exact repeated
    # multiplication); non-finite bases with non-integral exponents
    # follow the exp/log composition rather than npy_cpow's full
    # special-case table — the documented deviation.
    r = _cexp(_cmul(b, _clog(a)))
    azero = ((_re(a) == 0) & (_im(a) == 0))[..., None]
    bzero = ((_re(b) == 0) & (_im(b) == 0))[..., None]
    # npy_cpow zeroes 0**b for ANY b with positive real part (imag free)
    bposreal = (_re(b) > 0)[..., None]
    one_p = _pk(jnp.ones_like(r[..., 0]), jnp.zeros_like(r[..., 0]))
    r = jnp.where(
        azero,
        jnp.where(bposreal, jnp.zeros_like(r), jnp.full_like(r, jnp.nan)),
        r,
    )
    return jnp.where(bzero, one_p, r)


def _cisclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    # numpy semantics on the complex modulus: |a-b| <= atol + rtol*|b|,
    # exact equality covering infinities, optional nan==nan
    dist = jnp.hypot(_re(a) - _re(b), _im(a) - _im(b))
    mag = jnp.hypot(_re(b), _im(b))
    close = dist <= atol + rtol * mag
    exact = (_re(a) == _re(b)) & (_im(a) == _im(b))
    res = jnp.where(jnp.isfinite(dist), close, exact)
    if equal_nan:
        res = res | (_cnan(a) & _cnan(b))
    return res


# tables: jnp callable (as dispatched by the op wrappers) -> (name, kind);
# name -> plane implementation. ``kind`` is "planar" (result keeps the
# plane axis) or "real" (result is an ordinary real/bool DNDarray).
_BINARY_FNS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": _cmul,
    "div": _cdiv,
    "eq": lambda a, b: (_re(a) == _re(b)) & (_im(a) == _im(b)),
    "ne": lambda a, b: (_re(a) != _re(b)) | (_im(a) != _im(b)),
    "isclose": _cisclose,
    "pow": _cpow,
}

_BINARY = {
    jnp.add: ("add", "planar"),
    jnp.subtract: ("sub", "planar"),
    jnp.multiply: ("mul", "planar"),
    jnp.divide: ("div", "planar"),
    jnp.true_divide: ("div", "planar"),
    jnp.equal: ("eq", "real"),
    jnp.not_equal: ("ne", "real"),
    jnp.isclose: ("isclose", "real"),
    jnp.power: ("pow", "planar"),
}

_UNARY_FNS = {
    "angle": lambda p: jnp.arctan2(_im(p), _re(p)),
    "real": _re,
    "imag": _im,
    "conj": lambda p: _pk(_re(p), -_im(p)),
    "neg": lambda p: -p,
    "pos": lambda p: p,
    "abs": lambda p: jnp.hypot(_re(p), _im(p)),
    "exp": _cexp,
    "sqrt": _csqrt,
    "log": _clog,
    "log2": lambda p: _clog(p) / np.float32(np.log(2.0)),
    "log10": lambda p: _clog(p) / np.float32(np.log(10.0)),
    "square": lambda p: _cmul(p, p),
    "sin": _csin,
    "cos": _ccos,
    "tan": lambda p: _cdiv(_csin(p), _ccos(p)),
    "sinh": _csinh,
    "cosh": _ccosh,
    "tanh": lambda p: _cdiv(_csinh(p), _ccosh(p)),
    "reciprocal": lambda p: _cdiv(_pk(jnp.ones_like(_re(p)), jnp.zeros_like(_re(p))), p),
    "isnan": _cnan,
    "isinf": lambda p: jnp.isinf(_re(p)) | jnp.isinf(_im(p)),
    "isfinite": lambda p: jnp.isfinite(_re(p)) & jnp.isfinite(_im(p)),
    "round": lambda p, **kw: jnp.round(p, **kw),
    "rint": lambda p: jnp.rint(p),
}

_UNARY = {
    jnp.angle: ("angle", "real"),
    jnp.real: ("real", "real"),
    jnp.imag: ("imag", "real"),
    jnp.conj: ("conj", "planar"),
    jnp.conjugate: ("conj", "planar"),
    jnp.negative: ("neg", "planar"),
    jnp.positive: ("pos", "planar"),
    jnp.abs: ("abs", "real"),
    jnp.absolute: ("abs", "real"),
    jnp.exp: ("exp", "planar"),
    jnp.sqrt: ("sqrt", "planar"),
    jnp.log: ("log", "planar"),
    jnp.log2: ("log2", "planar"),
    jnp.log10: ("log10", "planar"),
    jnp.square: ("square", "planar"),
    jnp.sin: ("sin", "planar"),
    jnp.cos: ("cos", "planar"),
    jnp.tan: ("tan", "planar"),
    jnp.sinh: ("sinh", "planar"),
    jnp.cosh: ("cosh", "planar"),
    jnp.tanh: ("tanh", "planar"),
    jnp.reciprocal: ("reciprocal", "planar"),
    jnp.isnan: ("isnan", "real"),
    jnp.isinf: ("isinf", "real"),
    jnp.isfinite: ("isfinite", "real"),
    jnp.round: ("round", "planar"),
    jnp.rint: ("rint", "planar"),
}

_REDUCE = {jnp.sum: "sum", jnp.nansum: "nansum", jnp.mean: "mean", jnp.prod: "prod"}


def _cprod_axis(p, axis: int):
    """Complex product along one logical axis as a log-depth pairwise
    ``_cmul`` tree (the complex analog of a pairwise reduce; exact
    complex multiplication, vectorized across the other axes — no
    sequential scan)."""
    n = p.shape[axis]
    if n == 0:
        # empty product = multiplicative identity 1+0j (numpy semantics)
        shape = list(p.shape)
        shape[axis] = 1
        return jnp.zeros(tuple(shape), p.dtype).at[..., 0].set(1.0)
    while n > 1:
        half = n // 2
        lo = jax.lax.slice_in_dim(p, 0, half, axis=axis)
        hi = jax.lax.slice_in_dim(p, half, 2 * half, axis=axis)
        merged = _cmul(lo, hi)
        if n % 2:
            tail = jax.lax.slice_in_dim(p, 2 * half, n, axis=axis)
            merged = jnp.concatenate([merged, tail], axis=axis)
        p = merged
        n = p.shape[axis]
    return p


# --------------------------------------------------------------------- #
# conversions                                                           #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=512)
def _to_planar_prog(comm, ndim, split):
    def fn(arr):
        r = arr.astype(PLANE_JT)
        return jnp.stack([r, jnp.zeros_like(r)], axis=-1)

    return comm.jit_sharded(fn, ndim + 1, split)


@functools.lru_cache(maxsize=512)
def _combine_prog(comm, ndim, split):
    def fn(re, im):
        return jnp.stack([re.astype(PLANE_JT), im.astype(PLANE_JT)], axis=-1)

    return comm.jit_sharded(fn, ndim + 1, split)


def to_planar(x: DNDarray) -> DNDarray:
    """Real/integer DNDarray -> planar complex (zero imaginary plane).
    A NATIVE complex DNDarray (created on a supporting backend before the
    mode was switched to planar) stages through the host so both planes
    survive — astype(f32) on it would silently drop the imaginary part."""
    if is_planar(x):
        return x
    if types.heat_type_is_complexfloating(x.dtype):
        return from_host_complex(x.numpy().astype(np.complex64), x.split, x.device, x.comm)
    prog = _to_planar_prog(x.comm, x.ndim, x.split)
    return wrap(prog(x._phys), x.gshape, x.split, x.device, x.comm)


def combine(re: DNDarray, im: DNDarray) -> DNDarray:
    """Two aligned real DNDarrays -> one planar complex DNDarray."""
    if re.split != im.split or re.gshape != im.gshape:
        raise ValueError("real and imaginary parts must share shape and split")
    prog = _combine_prog(re.comm, re.ndim, re.split)
    return wrap(prog(re._phys, im._phys), re.gshape, re.split, re.device, re.comm)


def from_host_complex(np_data: np.ndarray, split, device, comm) -> DNDarray:
    """Host complex ndarray -> planar DNDarray (plane split on HOST, so
    no complex buffer ever reaches the device)."""
    planes = np.stack([np_data.real, np_data.imag], axis=-1).astype(np.float32)
    gshape = tuple(int(s) for s in np_data.shape)
    split = sanitize_axis(gshape, split)
    # comm.shard pads the (logical) split axis and lays out with the
    # trailing plane axis replicated — split < ndim so the pad/spec
    # geometry is identical to a real array of one extra dimension
    phys = comm.shard(jnp.asarray(planes), split)
    return wrap(phys, gshape, split, device, comm)


def host_complex(x: DNDarray) -> np.ndarray:
    """Planar DNDarray -> host complex64 ndarray (pad sliced off)."""
    arr = x._planar_phys
    if jax.process_count() > 1 and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        host = np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    else:
        host = np.asarray(jax.device_get(arr))
    host = host[tuple(slice(0, s) for s in x.gshape)]  # plane axis kept
    return assemble_host(host)


def assemble_host(planes: np.ndarray) -> np.ndarray:
    """Plane pairs -> complex64 on host. Componentwise assignment, NOT
    ``re + 1j*im``: the arithmetic form corrupts non-finite pairs
    ((inf, nan) -> nan+nanj via numpy's complex multiply/add rules)."""
    out = np.empty(planes.shape[:-1], np.complex64)
    out.real = planes[..., 0]
    out.imag = planes[..., 1]
    return out


# --------------------------------------------------------------------- #
# dispatch: binary                                                      #
# --------------------------------------------------------------------- #
def _pad_plane_operand(p, out_lnd: int, split: int, pext: int):
    """Align a plane-array operand's split-dim extent to the physical
    extent (same contract as ``_operations._pad_operand``, shifted around
    the trailing plane axis)."""
    lnd = p.ndim - 1
    dim = split - (out_lnd - lnd)
    if dim < 0:
        return p
    ext = p.shape[dim]
    if ext in (1, pext):
        return p
    widths = [(0, 0)] * p.ndim
    widths[dim] = (0, pext - ext)
    return jnp.pad(p, widths)


@functools.lru_cache(maxsize=2048)
def _binary_prog(name, comm, out_lnd, split, n, pext, kind, kw):
    fn = _BINARY_FNS[name]

    def run(p1, p2):
        if split is not None:
            p1 = _pad_plane_operand(p1, out_lnd, split, pext)
            p2 = _pad_plane_operand(p2, out_lnd, split, pext)
        r = fn(p1, p2, **dict(kw))
        if split is not None and pext != n:
            # restore the zero-pad invariant (e.g. isclose(0,0) -> True)
            r = _padding.mask_tail(r, split, n)
        return r

    out_ndim = out_lnd + (1 if kind == "planar" else 0)
    return comm.jit_sharded(run, out_ndim, split)


def _as_planar_operand(t, ref: DNDarray):
    """Normalize a binary operand to (plane_array_or_planar_DNDarray,
    logical_shape, split)."""
    if isinstance(t, DNDarray):
        return to_planar(t)
    if isinstance(t, (int, float, complex, bool, np.number)):
        c = complex(t)
        return jnp.asarray([c.real, c.imag], dtype=PLANE_JT)  # logical ()
    # array-likes (incl. host complex ndarrays / native complex on a
    # supporting sibling backend): stage through the host factory path
    from . import factories

    return to_planar(factories.array(np.asarray(t), device=ref.device, comm=ref.comm))


@functools.lru_cache(maxsize=256)
def _int_pow_prog(comm, lnd, split, n, pext, exponent):
    """Exact integer power by repeated complex multiplication (binary
    exponentiation, unrolled at trace time) — numpy computes integral
    powers this way, and exp(b·log a) would lose f32 accuracy and the
    non-finite special values (code-review r5)."""

    def run(p):
        one = _pk(jnp.ones_like(_re(p)), jnp.zeros_like(_re(p)))
        # seed the accumulator with the first odd-bit factor, not 1:
        # _cmul(one, (inf, 0)) would taint the imag plane with 0*inf=nan
        acc, base, k = None, p, abs(exponent)
        while k:
            if k & 1:
                acc = base if acc is None else _cmul(acc, base)
            k >>= 1
            if k:
                base = _cmul(base, base)
        if acc is None:  # exponent 0: every base -> 1 (numpy rule)
            acc = one
        if exponent < 0:
            acc = _cdiv(one, acc)
        if split is not None and pext != n:
            # e=0 writes ones (and negative e infs) into the pad tail
            acc = _padding.mask_tail(acc, split, n)
        return acc

    return comm.jit_sharded(run, lnd + 1, split)


def binary(op, t1, t2, out=None, where=None, fn_kwargs: Optional[dict] = None) -> DNDarray:
    """Planar replacement for ``_operations.__binary_op``."""
    if (
        op is jnp.power
        and isinstance(t1, DNDarray)
        and isinstance(t2, (int, float, np.integer, np.floating))
        and not isinstance(t2, bool)
        and float(t2).is_integer()
        and abs(int(t2)) <= 64
        and out is None
        and where is None
    ):
        x = to_planar(t1)
        n, pext = (None, None)
        if x.split is not None:
            n = x.gshape[x.split]
            pext = x._planar_phys.shape[x.split]
        prog = _int_pow_prog(x.comm, x.ndim, x.split, n, pext, int(t2))
        return wrap(prog(x._planar_phys), x.gshape, x.split, x.device, x.comm)
    entry = _BINARY.get(op)
    opname = getattr(op, "__name__", str(op))
    if entry is None:
        raise policy_error(f"operator '{opname}' on complex operands")
    if out is not None or where is not None:
        raise policy_error(f"'{opname}' with out=/where= on complex operands")
    name, kind = entry
    try:
        kw = tuple(sorted((fn_kwargs or {}).items()))
        hash(kw)
    except TypeError:
        raise policy_error(f"'{opname}' with non-hashable kwargs on complex operands")

    ref = t1 if isinstance(t1, DNDarray) else t2
    o1 = _as_planar_operand(t1, ref)
    o2 = _as_planar_operand(t2, ref)

    shape1 = tuple(o1.gshape) if isinstance(o1, DNDarray) else ()
    shape2 = tuple(o2.gshape) if isinstance(o2, DNDarray) else ()
    out_shape = broadcast_shape(shape1, shape2)
    out_lnd = len(out_shape)

    def _out_split(o):
        if not isinstance(o, DNDarray) or o.split is None:
            return None
        return o.split + (out_lnd - o.ndim)

    s1, s2 = _out_split(o1), _out_split(o2)
    if s1 is not None and s2 is not None and s1 != s2:
        # align the non-dominant operand to o1's split (the same
        # redistribution __binary_op performs for real operands)
        tgt = s1 - (out_lnd - o2.ndim)
        o2 = o2.resplit(tgt if tgt >= 0 else None)
        s2 = _out_split(o2)
    split = s1 if s1 is not None else s2
    if split is not None and out_shape[split] <= 1:
        split = None

    comm, device = ref.comm, ref.device
    n = out_shape[split] if split is not None else 0
    pext = _padding.pad_extent(n, comm.size) if split is not None else 0

    def _feed(o):
        if not isinstance(o, DNDarray):
            return o  # scalar plane pair (2,)
        if split is not None and o.split is not None and _out_split(o) == split:
            if o.gshape[o.split] == 1 and o._planar_phys.shape[o.split] != 1:
                return _planar_view(o)
            return o._planar_phys
        return _planar_view(o)

    prog = _binary_prog(name, comm, out_lnd, split, n, pext, kind, kw)
    result = prog(_feed(o1), _feed(o2))
    if kind == "planar":
        return wrap(result, out_shape, split, device, comm)
    return DNDarray(result, out_shape, types.canonical_heat_type(result.dtype), split, device, comm)


def _planar_view(x: DNDarray) -> jax.Array:
    """Unpadded logical plane array, shape ``gshape + (2,)``."""
    return _padding.unpad(x._planar_phys, tuple(x.gshape) + (2,), x.split)


# --------------------------------------------------------------------- #
# dispatch: unary / reduce / cum                                        #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=2048)
def _unary_prog(name, comm, lnd, split, n, pext, kind, kw):
    fn = _UNARY_FNS[name]

    def run(p):
        r = fn(p, **dict(kw))
        if split is not None and pext != n:
            r = _padding.mask_tail(r, split, n)
        return r

    out_ndim = lnd + (1 if kind == "planar" else 0)
    return comm.jit_sharded(run, out_ndim, split)


def local(op, x: DNDarray, out=None, kwargs: Optional[dict] = None) -> DNDarray:
    """Planar replacement for ``_operations.__local_op``."""
    entry = _UNARY.get(op)
    opname = getattr(op, "__name__", str(op))
    if entry is None:
        raise policy_error(f"operator '{opname}' on a complex array")
    if out is not None:
        raise policy_error(f"'{opname}' with out= on a complex array")
    name, kind = entry
    try:
        kw = tuple(sorted((kwargs or {}).items()))
        hash(kw)
    except TypeError:
        raise policy_error(f"'{opname}' with non-hashable kwargs on a complex array")

    n, pext = (None, None)
    if x.split is not None:
        n = x.gshape[x.split]
        pext = x._planar_phys.shape[x.split]
    prog = _unary_prog(name, x.comm, x.ndim, x.split, n, pext, kind, kw)
    result = prog(x._planar_phys)
    if kind == "planar":
        return wrap(result, x.gshape, x.split, x.device, x.comm)
    return DNDarray(
        result, x.gshape, types.canonical_heat_type(result.dtype), x.split, x.device, x.comm
    )


@functools.lru_cache(maxsize=1024)
def _reduce_prog(name, comm, lnd, split, n, pext, axes, keepdims, out_split, out_n, out_pext, count):
    def run(p):
        if name == "prod":
            if split is not None and split in axes and pext != n:
                # the zero pad would multiply in: refill with 1+0j
                iota = jax.lax.broadcasted_iota(jnp.int32, p.shape[:-1], split)
                one_p = _pk(jnp.ones_like(p[..., 0]), jnp.zeros_like(p[..., 0]))
                p = jnp.where((iota < n)[..., None], p, one_p)
            for ax in axes:
                p = _cprod_axis(p, ax)
            r = p if keepdims else jnp.squeeze(p, axis=axes)
        else:
            if name == "nansum":
                p = jnp.where(_cnan(p)[..., None], jnp.zeros_like(p), p)
            # pad planes are zero -> sum-safe without a neutral refill
            r = jnp.sum(p, axis=axes, keepdims=keepdims)
            if name == "mean":
                r = r / np.float32(count)
        if out_split is not None and out_pext != out_n:
            r = _padding.mask_tail(r, out_split, out_n)
        return r

    return comm.jit_sharded(run, (lnd - (0 if keepdims else len(axes))) + 1, out_split)


def reduce(op, x: DNDarray, axis=None, keepdims: bool = False, out=None, kwargs=None) -> DNDarray:
    """Planar replacement for ``_operations.__reduce_op`` (sum-family +
    mean; the pad-zero invariant makes the plane sums pad-safe, mean
    divides by the LOGICAL element count)."""
    name = _REDUCE.get(op)
    opname = getattr(op, "__name__", str(op))
    if name is None:
        raise policy_error(f"reduction '{opname}' on a complex array")
    if out is not None or kwargs:
        raise policy_error(f"'{opname}' with out=/kwargs on a complex array")
    axis = sanitize_axis(x.shape, axis)
    lnd = x.ndim
    axes = tuple(range(lnd)) if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))

    if keepdims:
        out_shape = tuple(1 if i in axes else s for i, s in enumerate(x.gshape))
    else:
        out_shape = tuple(s for i, s in enumerate(x.gshape) if i not in axes)
    split = x.split
    if split is None or split in axes:
        out_split = None
    elif keepdims:
        out_split = split
    else:
        out_split = split - sum(1 for a in axes if a < split)
    if out_split is not None and out_shape[out_split] <= 1:
        out_split = None

    n, pext = (None, None)
    if split is not None:
        n = x.gshape[split]
        pext = x._planar_phys.shape[split]
    out_n = out_shape[out_split] if out_split is not None else 0
    out_pext = _padding.pad_extent(out_n, x.comm.size) if out_split is not None else 0
    count = int(np.prod([x.gshape[a] for a in axes])) if axes else 1

    prog = _reduce_prog(
        name, x.comm, lnd, split, n, pext, axes, keepdims, out_split, out_n, out_pext, count
    )
    result = prog(x._planar_phys)
    res = wrap(result, out_shape, out_split, x.device, x.comm)
    return res


@functools.lru_cache(maxsize=512)
def _cumsum_prog(comm, lnd, split, n, pext, axis):
    def run(p):
        r = jnp.cumsum(p, axis=axis)
        if split is not None and pext != n:
            # cumsum carries sums into the pad tail along the split axis
            r = _padding.mask_tail(r, split, n)
        return r

    return comm.jit_sharded(run, lnd + 1, split)


def cum(op, x: DNDarray, axis: int, out=None, dtype=None) -> DNDarray:
    """Planar replacement for ``_operations.__cum_op`` (cumsum only —
    cumprod needs a complex-multiply scan and is outside the surface)."""
    if op is not jnp.cumsum:
        raise policy_error(f"cumulative '{getattr(op, '__name__', op)}' on a complex array")
    if out is not None or (dtype is not None and not types.heat_type_is_complexfloating(types.canonical_heat_type(dtype))):
        raise policy_error("cumsum with out=/real dtype= on a complex array")
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operation over flattened array: ravel first")
    n, pext = (None, None)
    if x.split is not None:
        n = x.gshape[x.split]
        pext = x._planar_phys.shape[x.split]
    prog = _cumsum_prog(x.comm, x.ndim, x.split, n, pext, axis)
    return wrap(prog(x._planar_phys), x.gshape, x.split, x.device, x.comm)


def var(x: DNDarray, axis=None, ddof: int = 0, keepdims: bool = False) -> DNDarray:
    """Complex variance, numpy semantics: ``mean(|x - mean(x)|²)`` — a
    REAL result, so ``std`` flows through the real sqrt automatically and
    the squared-modulus accumulation runs on the ordinary real path."""
    axis = sanitize_axis(x.shape, axis)
    mu = reduce(jnp.mean, x, axis=axis, keepdims=True)
    absd = local(jnp.abs, binary(jnp.subtract, x, mu))  # real f32 DNDarray
    axes = tuple(range(x.ndim)) if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    count = int(np.prod([x.gshape[a] for a in axes])) if axes else 1
    s = (absd * absd).sum(axis=axis, keepdims=keepdims)
    return s / float(count - ddof)


# --------------------------------------------------------------------- #
# structural ops: the plane axis is a passenger — every op below acts   #
# on the logical axes of the plane view and re-shards the result        #
# --------------------------------------------------------------------- #
def _restructure(ref: DNDarray, res_view: jax.Array, out_split) -> DNDarray:
    gshape = tuple(int(s) for s in res_view.shape[:-1])
    if out_split is not None and (
        not gshape or out_split >= len(gshape) or gshape[out_split] <= 1
    ):
        out_split = None
    return wrap(ref.comm.shard(res_view, out_split), gshape, out_split, ref.device, ref.comm)


def reshape(x: DNDarray, shape, new_split) -> DNDarray:
    return _restructure(x, jnp.reshape(_planar_view(x), tuple(shape) + (2,)), new_split)


def transpose(x: DNDarray, axes) -> DNDarray:
    perm = tuple(axes) + (x.ndim,)
    out_split = axes.index(x.split) if x.split is not None else None
    return _restructure(x, jnp.transpose(_planar_view(x), perm), out_split)


def expand_dims(x: DNDarray, axis: int) -> DNDarray:
    split = x.split
    if split is not None and axis <= split:
        split += 1
    return _restructure(x, jnp.expand_dims(_planar_view(x), axis), split)


def squeeze(x: DNDarray, axes) -> DNDarray:
    split = x.split
    if split is not None:
        split = None if split in axes else split - sum(1 for ax in axes if ax < split)
    return _restructure(x, jnp.squeeze(_planar_view(x), axis=tuple(axes)), split)


def flatten(x: DNDarray) -> DNDarray:
    split = 0 if x.split is not None else None
    return _restructure(x, jnp.reshape(_planar_view(x), (-1, 2)), split)


def flip(x: DNDarray, axis) -> DNDarray:
    axes = tuple(range(x.ndim)) if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    return _restructure(x, jnp.flip(_planar_view(x), axis=axes), x.split)


def roll(x: DNDarray, shift, axis) -> DNDarray:
    v = _planar_view(x)
    if axis is None:
        r = jnp.roll(v.reshape(-1, 2), shift, axis=0).reshape(v.shape)
    else:
        # normalize against the LOGICAL rank: a negative axis on the plane
        # view would roll the real/imag plane axis itself
        axis = sanitize_axis(x.shape, axis)
        r = jnp.roll(v, shift, axis=axis)
    return _restructure(x, r, x.split)


def rot90(x: DNDarray, k: int, axes) -> DNDarray:
    split = x.split
    if split is not None and k % 2 == 1 and split in axes:
        split = axes[0] if split == axes[1] else axes[1]
    return _restructure(x, jnp.rot90(_planar_view(x), k=k, axes=axes), split)


def concat(arrays, axis: int) -> DNDarray:
    ref = next(a for a in arrays if is_planar(a))
    views = [_planar_view(to_planar(a)) for a in arrays]
    split = next((a.split for a in arrays if isinstance(a, DNDarray) and a.split is not None), None)
    return _restructure(ref, jnp.concatenate(views, axis=axis), split)


def stack_new_axis(arrays, axis: int) -> DNDarray:
    ref = next(a for a in arrays if is_planar(a))
    lnd = ref.ndim
    axis = axis % (lnd + 1)
    views = [_planar_view(to_planar(a)) for a in arrays]
    split = ref.split
    if split is not None and axis <= split:
        split += 1
    return _restructure(ref, jnp.stack(views, axis=axis), split)


def copy(x: DNDarray) -> DNDarray:
    # jax arrays are immutable: sharing the buffer IS a deep copy
    return wrap(x._planar_phys, x.gshape, x.split, x.device, x.comm)


# --------------------------------------------------------------------- #
# linear algebra: complex matmul as THREE real MXU matmuls (Gauss).     #
# (A_r + iA_i)(B_r + iB_i): P1=A_rB_r, P2=A_iB_i, P3=(A_r+A_i)(B_r+B_i) #
# -> C_r = P1-P2, C_i = P3-P1-P2 — 25% fewer MXU passes than the naive  #
# four-product form, all on the real systolic array.                    #
#                                                                       #
# PRECISION POLICY (VERDICT r5 live defect): the Gauss form recovers    #
# C_i by CANCELLATION (P3 - P1 - P2), so error is relative to |P1|+|P2|,#
# not to |C_i|. At JAX's TPU default precision the three products run   #
# as bf16 MXU passes (~1e-2 relative), which the cancellation amplifies #
# into garbage imaginary parts on ordinary inputs. Planar matmul (and   #
# the dot/@ family routing through it) therefore DEFAULTS to            #
# precision="highest" — exact f32 products, ~3x the MXU passes — and    #
# callers opt INTO speed with an explicit precision= argument instead   #
# of silently losing the imaginary part (docs/MIGRATING.md "Complex     #
# platform policy"). The elementwise family (vdot/vecdot/outer) runs    #
# VPU f32 multiplies and needs no override.                             #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=256)
def _matmul_prog(comm, out_ndim, out_split, precision):
    def run(av, bv):
        ar, ai = av[..., 0], av[..., 1]
        br, bi = bv[..., 0], bv[..., 1]
        p1 = jnp.matmul(ar, br, precision=precision)
        p2 = jnp.matmul(ai, bi, precision=precision)
        p3 = jnp.matmul(ar + ai, br + bi, precision=precision)
        r = jnp.stack([p1 - p2, p3 - p1 - p2], axis=-1)
        if out_split is not None:
            # inputs are logical views: restore the physical pad extent
            r = _padding.pad_logical(r, out_split, comm.size)
        return r

    return comm.jit_sharded(run, out_ndim + 1, out_split)


def matmul(a, b, precision=None) -> DNDarray:
    """Planar complex ``matmul`` (mirrors the real path's split rules,
    linalg/basics.py:matmul). ``precision`` defaults to ``"highest"``:
    the Gauss decomposition recovers the imaginary part by cancellation,
    which bf16 MXU products turn into catastrophic relative error (see
    the policy note above)."""
    if precision is None:
        precision = "highest"
    a = to_planar(a)
    b = to_planar(b)
    res = jax.eval_shape(
        jnp.matmul,
        jax.ShapeDtypeStruct(tuple(a.gshape), PLANE_JT),
        jax.ShapeDtypeStruct(tuple(b.gshape), PLANE_JT),
    )
    out_shape = tuple(int(s) for s in res.shape)
    out_ndim = len(out_shape)
    split = None
    if a.ndim >= 2 and a.split == a.ndim - 2:
        split = out_ndim - 2
    elif b.ndim >= 2 and b.split == b.ndim - 1:
        split = out_ndim - 1
    elif a.split is not None and a.ndim > 2 and a.split < a.ndim - 2:
        split = a.split
    elif b.split is not None and b.ndim > 2 and b.split < b.ndim - 2:
        split = b.split
    # a 1-D operand drops its dimension from the output: the rules above
    # can land outside [0, out_ndim) (e.g. 2-D split=0 @ 1-D -> -1, which
    # the plane view would resolve to the plane axis)
    if split is not None and (split < 0 or split >= out_ndim or out_shape[split] <= 1):
        split = None
    prog = _matmul_prog(a.comm, out_ndim, split, precision)
    return wrap(prog(_planar_view(a), _planar_view(b)), out_shape, split, a.device, a.comm)


def dot(a: DNDarray, b: DNDarray) -> DNDarray:
    """numpy ``dot`` semantics (NO conjugation) for planar operands."""
    if a.ndim == 1 and b.ndim == 1:
        return reduce(jnp.sum, binary(jnp.multiply, a, b))
    if a.ndim == 2 and b.ndim == 2:
        return matmul(a, b)
    raise policy_error("ht.dot beyond 1-D/2-D on complex operands")


def vdot(a: DNDarray, b: DNDarray) -> DNDarray:
    """numpy ``vdot``: conjugate the FIRST flattened operand."""
    af = flatten(to_planar(a)) if a.ndim > 1 else to_planar(a)
    bf = flatten(to_planar(b)) if b.ndim > 1 else to_planar(b)
    return reduce(jnp.sum, binary(jnp.multiply, local(jnp.conj, af), bf))


def vecdot(a: DNDarray, b: DNDarray, axis: int = -1, keepdims: bool = False) -> DNDarray:
    """numpy ``vecdot``: conjugated product summed along ``axis``."""
    prod = binary(jnp.multiply, local(jnp.conj, to_planar(a)), to_planar(b))
    return reduce(jnp.sum, prod, axis=axis, keepdims=keepdims)


def outer(a: DNDarray, b: DNDarray, split=None) -> DNDarray:
    """numpy ``outer`` (no conjugation) of flattened planar vectors."""
    af = flatten(to_planar(a)) if a.ndim != 1 else to_planar(a)
    bf = flatten(to_planar(b)) if b.ndim != 1 else to_planar(b)
    res = binary(jnp.multiply, expand_dims(af, 1), expand_dims(bf, 0))
    if split is None and (a.split is not None or b.split is not None):
        split = 0
    if split is not None and res.split != split:
        res = res.resplit(split)
    return res


# --------------------------------------------------------------------- #
# factories                                                             #
# --------------------------------------------------------------------- #
def array_factory(obj, split, is_split, ndmin, order, device, comm) -> DNDarray:
    """Planar branch of ``factories.array``: stage the data through a
    HOST complex ndarray (complex never reaches the device) and shard the
    planes. ``complex128`` degrades to ``complex64``."""
    if isinstance(obj, DNDarray):
        np_data = host_complex(obj) if obj._is_planar else np.asarray(obj.numpy())
    elif isinstance(obj, jax.Array):
        np_data = np.asarray(jax.device_get(obj))
    else:
        np_data = np.asarray(obj, order=order)
    np_data = np.asarray(np_data, dtype=np.complex64, order=order)
    if np_data.ndim < ndmin:
        np_data = np_data.reshape((1,) * (ndmin - np_data.ndim) + np_data.shape)
    if is_split is not None:
        if jax.process_count() > 1:
            raise policy_error("is_split assembly of complex arrays in multi-process mode")
        split = is_split  # single process: the local shard IS the array
    return from_host_complex(np_data, split, device, comm)



def create(op_key: str, shape, split, device, comm, args=()) -> DNDarray:
    """Planar branch of ``factories._create``: build the real plane with
    the ordinary f32 creator, the imaginary plane as a constant."""
    from . import factories

    if any(isinstance(a, complex) and a.imag != 0 for a in args) and op_key != "full":
        raise policy_error(f"'{op_key}' with complex-valued arguments")
    if op_key == "full":
        fill = complex(args[0])
        re = factories._create("full", shape, types.float32, split, device, comm, (fill.real,))
        im = factories._create("full", shape, types.float32, split, device, comm, (fill.imag,))
        return combine(re, im)
    real_args = tuple(a.real if isinstance(a, complex) else a for a in args)
    re = factories._create(op_key, shape, types.float32, split, device, comm, real_args)
    return to_planar(re)
