"""Statistical operations.

API parity with /root/reference/heat/core/statistics.py (20 exports).
Distribution notes from the reference: ``mean``/``var`` (statistics.py:892/
:1851) combine local moments with an Allreduce (Welford-style merge in
``__moment_w_axis`` :1224); ``argmax``/``argmin`` use custom MPI reduction
ops carrying a value∥index payload (:1369); ``percentile`` (:1407) runs a
distributed sort plus halo exchange. On TPU all of these are single jnp
reductions over the sharded global array — XLA emits the same combine
collectives — so the hand-built merge machinery disappears.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple, Union

from . import types
from . import _operations
from .dndarray import DNDarray
from .sanitation import sanitize_in
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]


def _argmax_i64(a, axis=None, keepdims=False):
    # module-level (NOT a per-call lambda): the cached-jit layer keys
    # programs on op identity, so a fresh callable per call would
    # retrace+recompile every invocation
    return jnp.argmax(a, axis=axis, keepdims=keepdims).astype(types.index_jax_type())


def _argmin_i64(a, axis=None, keepdims=False):
    return jnp.argmin(a, axis=axis, keepdims=keepdims).astype(types.index_jax_type())


def argmax(x: DNDarray, axis: Optional[int] = None, out=None, **kwargs) -> DNDarray:
    """Indices of maximum values (reference: statistics.py argmax — MPI
    value∥index custom op; here a sharded jnp.argmax)."""
    return _operations.__reduce_op(
        _argmax_i64,
        x,
        axis=axis,
        out=out,
        keepdims=kwargs.get("keepdims", False),
    )


def argmin(x: DNDarray, axis: Optional[int] = None, out=None, **kwargs) -> DNDarray:
    """Indices of minimum values."""
    return _operations.__reduce_op(
        _argmin_i64,
        x,
        axis=axis,
        out=out,
        keepdims=kwargs.get("keepdims", False),
    )


def average(x: DNDarray, axis=None, weights: Optional[DNDarray] = None, returned: bool = False):
    """Weighted average (reference: statistics.py average)."""
    sanitize_in(x)
    if weights is None:
        result = mean(x, axis)
        if returned:
            from . import factories

            n = x.size if axis is None else np.prod([x.shape[a] for a in (
                (axis,) if isinstance(axis, int) else tuple(axis)
            )])
            weights_sum = factories.full_like(result, float(n))
            return result, weights_sum
        return result
    sanitize_in(weights)
    axis_s = sanitize_axis(x.shape, axis)
    w = weights.larray
    arr = x.larray
    if types.heat_type_is_exact(x.dtype):
        arr = arr.astype(jnp.float32)
    if w.ndim != arr.ndim and axis_s is not None and isinstance(axis_s, int):
        if w.shape != (x.shape[axis_s],):
            raise ValueError("Length of weights not compatible with specified axis.")
        shape = [1] * arr.ndim
        shape[axis_s] = w.shape[0]
        w = w.reshape(shape)
    wsum = jnp.sum(w * jnp.ones_like(arr), axis=axis_s)
    if bool(jnp.any(wsum == 0)):
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    result = jnp.sum(arr * w, axis=axis_s) / wsum
    res = _wrap_reduce(result, x, axis_s, False)
    if returned:
        wret = _wrap_reduce(jnp.broadcast_to(wsum, result.shape), x, axis_s, False)
        return res, wret
    return res


def _wrap_reduce(result: jax.Array, x: DNDarray, axis, keepdims: bool) -> DNDarray:
    """Split bookkeeping for a reduction result computed outside
    __reduce_op."""
    split = x.split
    if split is None or axis is None:
        out_split = None
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if split in axes:
            out_split = None
        elif keepdims:
            out_split = split
        else:
            out_split = split - sum(1 for a in axes if a < split)
    gshape = tuple(int(s) for s in result.shape)
    if out_split is not None and result.ndim > 0:
        result = x.comm.shard(result, out_split)
    else:
        out_split = None
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), out_split, x.device, x.comm
    )


def bincount(x: DNDarray, weights: Optional[DNDarray] = None, minlength: int = 0) -> DNDarray:
    """Count occurrences of non-negative ints (reference: statistics.py
    bincount — local bincount + Allreduce; the sharded sum here)."""
    sanitize_in(x)
    if x.ndim != 1:
        raise ValueError("bincount expects a 1-d array")
    arr = x.larray
    if arr.size and int(jnp.min(arr)) < 0:
        raise ValueError("bincount requires non-negative input values")
    w = weights.larray if isinstance(weights, DNDarray) else weights
    # jnp.bincount requires static length: compute it eagerly
    if arr.shape[0] == 0:
        length = minlength
    else:
        length = int(builtins_max(int(jnp.max(arr)) + 1, minlength)) if arr.size else minlength
    result = jnp.bincount(arr, weights=w, length=length if length > 0 else None)
    gshape = tuple(int(s) for s in result.shape)
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), None, x.device, x.comm
    )


import builtins

builtins_max = builtins.max


def bucketize(input: DNDarray, boundaries, out_int32: bool = False, right: bool = False, out=None) -> DNDarray:
    """Index of the bucket each element falls into (reference:
    statistics.py bucketize, torch semantics)."""
    sanitize_in(input)
    b = boundaries.larray if isinstance(boundaries, DNDarray) else jnp.asarray(np.asarray(boundaries))
    # torch semantics: right=False -> x <= boundaries[i] (numpy side='left' is
    # boundaries[i-1] < x), right=True -> boundaries[i-1] <= x < boundaries[i]
    result = jnp.searchsorted(b, input.larray, side="left" if not right else "right")
    result = result.astype(jnp.int32 if out_int32 else types.index_jax_type())
    ret = _wrap_reduce(result, input, None, False)
    ret._DNDarray__split = input.split
    if input.split is not None:
        ret._set_phys(input.comm.shard(result, input.split))
    if out is not None:
        out.larray = ret.larray
        return out
    return ret


def cov(m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Covariance matrix estimate (reference: statistics.py cov)."""
    sanitize_in(m)
    if ddof is not None and not isinstance(ddof, int):
        raise TypeError("ddof must be integer")
    arr = m.larray.astype(jnp.float64 if m.dtype is types.float64 else jnp.float32)
    if y is not None:
        sanitize_in(y)
        yarr = y.larray.astype(arr.dtype)
        result = jnp.cov(arr, yarr, rowvar=rowvar, bias=bias, ddof=ddof)
    else:
        result = jnp.cov(arr, rowvar=rowvar, bias=bias, ddof=ddof)
    gshape = tuple(int(s) for s in result.shape)
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), None, m.device, m.comm
    )


def digitize(x: DNDarray, bins, right: bool = False) -> DNDarray:
    """Indices of the bins each value belongs to (numpy semantics;
    reference: statistics.py digitize)."""
    sanitize_in(x)
    b = bins.larray if isinstance(bins, DNDarray) else jnp.asarray(np.asarray(bins))
    result = jnp.digitize(x.larray, b, right=right).astype(types.index_jax_type())
    ret = _wrap_reduce(result, x, None, False)
    if x.split is not None:
        ret._DNDarray__split = x.split
        ret._set_phys(x.comm.shard(result, x.split))
    return ret


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins in [min, max] (torch semantics;
    reference: statistics.py histc)."""
    sanitize_in(input)
    arr = input.larray
    if types.heat_type_is_exact(input.dtype):
        arr = arr.astype(jnp.float32)
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo = float(jnp.min(arr)) if arr.size else 0.0
        hi = float(jnp.max(arr)) if arr.size else 0.0
    if lo == hi:
        lo, hi = lo - 1e-6, hi + 1e-6
    mask = (arr >= lo) & (arr <= hi)
    hist, _ = jnp.histogram(jnp.where(mask, arr, jnp.asarray(np.nan, arr.dtype)), bins=bins, range=(lo, hi))
    result = hist.astype(arr.dtype)
    gshape = tuple(int(s) for s in result.shape)
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), None, input.device, input.comm
    )


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """NumPy-style histogram; returns (hist, bin_edges) (reference:
    statistics.py histogram — ``normed`` rejected the same way,
    statistics.py:716)."""
    if normed is not None:
        raise NotImplementedError("'normed' is not supported")
    sanitize_in(a)
    arr = a.larray
    w = weights.larray if isinstance(weights, DNDarray) else weights
    hist, edges = jnp.histogram(arr, bins=bins, range=range, weights=w, density=density)
    h = DNDarray(
        hist, tuple(int(s) for s in hist.shape), types.canonical_heat_type(hist.dtype), None, a.device, a.comm
    )
    e = DNDarray(
        edges, tuple(int(s) for s in edges.shape), types.canonical_heat_type(edges.dtype), None, a.device, a.comm
    )
    return h, e


def __moments(x: DNDarray, axis, power: int):
    """(m2, m_power): central moments from one mean/centering pass (the
    single-pass replacement for the reference's Welford merge,
    statistics.py:1224)."""
    arr = x.larray
    if types.heat_type_is_exact(x.dtype):
        arr = arr.astype(jnp.float32)
    mu = jnp.mean(arr, axis=axis, keepdims=True)
    centered = arr - mu
    m2 = jnp.mean(centered**2, axis=axis)
    mk = jnp.mean(centered**power, axis=axis)
    return m2, mk


def _axis_count(x: DNDarray, axis) -> int:
    """Number of elements reduced over ``axis``."""
    if axis is None:
        return x.size
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return int(np.prod([x.shape[a] for a in axes]))


def kurtosis(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Kurtosis (Fisher's definition subtracts 3) (reference:
    statistics.py kurtosis)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    m2, m4 = __moments(x, axis, 4)
    n = _axis_count(x, axis)
    if unbiased:
        g2 = m4 / (m2**2)
        result = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1))
        if Fischer:
            pass  # bias-corrected excess kurtosis already excess
        else:
            result = result + 3
    else:
        result = m4 / (m2**2)
        if Fischer:
            result = result - 3
    return _wrap_reduce(jnp.asarray(result), x, axis, False)


def max(x: DNDarray, axis=None, out=None, keepdims=None) -> DNDarray:
    """Maximum along axis (reference: statistics.py max)."""
    return _operations.__reduce_op(
        jnp.max, x, axis=axis, out=out, keepdims=bool(keepdims) if keepdims else False
    )


def maximum(x1: DNDarray, x2: DNDarray, out=None) -> DNDarray:
    """Elementwise maximum (reference: statistics.py maximum)."""
    return _operations.__binary_op(jnp.maximum, x1, x2, out)


def mean(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Arithmetic mean (reference: statistics.py:892 — local moments +
    Allreduce combine; here one sharded jnp.mean). ``keepdims`` is a
    numpy-style superset of the reference signature, matching this
    module's var/std/min/max/median."""
    sanitize_in(x)
    if x._is_planar:
        from . import complex_planar as _cp

        return _cp.reduce(jnp.mean, x, axis=axis, keepdims=bool(keepdims))
    axis = sanitize_axis(x.shape, axis)
    arr = x.larray
    if types.heat_type_is_exact(x.dtype):
        arr = arr.astype(jnp.float32)
    result = jnp.mean(arr, axis=axis, keepdims=bool(keepdims))
    return _wrap_reduce(jnp.asarray(result), x, axis, bool(keepdims))


def median(x: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Median = 50th percentile (reference: statistics.py:1018)."""
    return percentile(x, 50.0, axis=axis, keepdims=keepdims)


def min(x: DNDarray, axis=None, out=None, keepdims=None) -> DNDarray:
    """Minimum along axis."""
    return _operations.__reduce_op(
        jnp.min, x, axis=axis, out=out, keepdims=bool(keepdims) if keepdims else False
    )


def minimum(x1: DNDarray, x2: DNDarray, out=None) -> DNDarray:
    """Elementwise minimum."""
    return _operations.__binary_op(jnp.minimum, x1, x2, out)


def percentile(
    x: DNDarray,
    q,
    axis: Optional[int] = None,
    out=None,
    interpolation: str = "linear",
    keepdims: bool = False,
) -> DNDarray:
    """q-th percentile (reference: statistics.py:1407 — distributed sort +
    halo + Allgather of index maps).

    When the reduction axis is the split axis, this runs the gather-free
    ``ht.sort`` (odd-even ppermute network, ``core.parallel``) and then
    fetches only the two bracketing ranks per q — the TPU analog of the
    reference's sorted-halo rank lookup. Other axes use XLA's lane-local
    percentile on the sharded array."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if interpolation not in ("linear", "lower", "higher", "midpoint", "nearest"):
        raise ValueError(f"unknown interpolation {interpolation}")
    # q stays a HOST value: the bracketing ranks must be static (they
    # shape the program), and round-tripping a python float through
    # jnp.asarray turns it into a tracer under ht.jit (jax inserts a
    # convert op for the unavailable f64), breaking np.asarray below
    if isinstance(q, (DNDarray, jax.Array)):
        q_dev = q.larray if isinstance(q, DNDarray) else q
        if isinstance(q_dev, jax.core.Tracer):
            raise TypeError(
                "percentile: q must be statically known (host value); a "
                "traced q would make the output shape data-dependent"
            )
        # declared host boundary "percentile-q" (analysis/boundaries.py):
        # the ONLY whitelisted sync in core/ — pinned by tier-1
        q_host = np.asarray(jax.device_get(q_dev), dtype=np.float64)
    else:
        q_host = np.asarray(q, dtype=np.float64)
    scalar_q = q_host.ndim == 0
    qv = np.atleast_1d(q_host)
    if np.any(qv < 0.0) or np.any(qv > 100.0):
        raise ValueError("percentiles must be in the range [0, 100]")
    eff_axis = axis
    if eff_axis is None and x.ndim == 1:
        eff_axis = 0
    sorted_x = None
    if (
        eff_axis is not None
        and x.split == eff_axis
        and x.comm.size > 1
        and x.dtype not in (types.complex64, types.complex128)
    ):
        from . import manipulations

        sorted_x = manipulations._sorted_values(x, eff_axis)
    if sorted_x is not None:
        sarr = sorted_x.larray
        if types.heat_type_is_exact(x.dtype):
            sarr = sarr.astype(jnp.float32)
        n = x.gshape[eff_axis]
        pos = qv / 100.0 * (n - 1)
        lo = np.floor(pos).astype(np.int64)
        hi = np.ceil(pos).astype(np.int64)
        # ranks are host-static: only two cross-shard row fetches per q
        vlo = jnp.take(sarr, jnp.asarray(lo), axis=eff_axis)
        vhi = jnp.take(sarr, jnp.asarray(hi), axis=eff_axis)
        if interpolation == "lower":
            res = vlo
        elif interpolation == "higher":
            res = vhi
        elif interpolation == "midpoint":
            res = (vlo + vhi) / 2
        elif interpolation == "nearest":
            nearest = np.rint(pos).astype(np.int64)
            res = jnp.take(sarr, jnp.asarray(nearest), axis=eff_axis)
        else:  # linear
            frac = jnp.asarray(pos - lo, dtype=sarr.dtype)
            fshape = [1] * sarr.ndim
            fshape[eff_axis] = len(qv)
            res = vlo + frac.reshape(fshape) * (vhi - vlo)
        if jnp.issubdtype(sarr.dtype, jnp.floating):
            # NaNs sort to the tail, so a lane contains one iff its last
            # logical element is NaN — propagate like numpy does
            vlast = jnp.expand_dims(jnp.take(sarr, n - 1, axis=eff_axis), eff_axis)
            res = jnp.where(jnp.isnan(vlast), jnp.nan, res)
        # numpy/jnp put the q dim first
        result = jnp.moveaxis(res, eff_axis, 0)
        if scalar_q:
            result = jnp.squeeze(result, axis=0)
        if keepdims:
            # axis=None only reaches here for 1-D input (eff_axis 0)
            result = jnp.expand_dims(
                result, (axis if axis is not None else 0) + (0 if scalar_q else 1)
            )
    else:
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        # q rides in the widest available float (NOT arr.dtype: a bf16 q
        # would round 99.9 to 100.0 and return the maximum)
        result = jnp.percentile(
            arr, jnp.asarray(q_host, dtype=types.wide_jax_type("f")), axis=axis,
            method=interpolation, keepdims=keepdims,
        )
    # result has leading q dims when q is a vector
    ret = _wrap_reduce(jnp.asarray(result), x, axis, keepdims) if scalar_q else DNDarray(
        result,
        tuple(int(s) for s in result.shape),
        types.canonical_heat_type(result.dtype),
        None,
        x.device,
        x.comm,
    )
    if out is not None:
        out.larray = ret.larray
        return out
    return ret


def skew(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True) -> DNDarray:
    """Sample skewness (reference: statistics.py skew)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    m2, m3 = __moments(x, axis, 3)
    n = _axis_count(x, axis)
    g1 = m3 / (m2**1.5)
    if unbiased:
        result = g1 * np.sqrt(n * (n - 1)) / (n - 2)
    else:
        result = g1
    return _wrap_reduce(jnp.asarray(result), x, axis, False)


def std(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Standard deviation (reference: statistics.py std)."""
    v = var(x, axis, ddof, **kwargs)
    from . import exponential

    return exponential.sqrt(v)


def var(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance (reference: statistics.py:1851 — Welford merge across
    ranks; here one sharded reduction)."""
    sanitize_in(x)
    if not isinstance(ddof, int):
        raise ValueError(f"ddof must be integer, is {type(ddof)}")
    if ddof < 0:
        raise ValueError(f"Expected ddof >= 0, got {ddof}")
    bessel = kwargs.get("bessel", None)
    if bessel is not None:
        ddof = 1 if bessel else 0
    axis = sanitize_axis(x.shape, axis)
    keepdims = kwargs.get("keepdims", False)
    if x._is_planar:
        from . import complex_planar as _cp

        return _cp.var(x, axis=axis, ddof=ddof, keepdims=bool(keepdims))
    arr = x.larray
    if types.heat_type_is_exact(x.dtype):
        arr = arr.astype(jnp.float32)
    result = jnp.var(arr, axis=axis, ddof=ddof, keepdims=keepdims)
    return _wrap_reduce(jnp.asarray(result), x, axis, keepdims)


DNDarray.argmax = argmax
DNDarray.argmin = argmin
DNDarray.average = average
DNDarray.max = max
DNDarray.min = min
DNDarray.mean = mean
DNDarray.median = median
DNDarray.percentile = percentile
DNDarray.std = std
DNDarray.var = var
DNDarray.kurtosis = kurtosis
DNDarray.skew = skew
