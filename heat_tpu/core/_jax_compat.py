"""jax version compatibility.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level namespace, renaming ``check_rep`` to ``check_vma`` on the way,
and ``lax.pcast`` arrived with the varying-mesh-axes (VMA) type system.
This repo targets the new spellings; the wrappers below keep the library
importable and correct on runtimes that still ship the experimental
forms (observed: jax 0.4.x containers):

- ``shard_map``: kwarg-mapped passthrough (all internal call sites use
  keyword form ``mesh=/in_specs=/out_specs=[/check_vma=]`` only);
- ``pcast``: identity where VMA tracking does not exist — pre-VMA jax
  has no replicated/varying distinction to cast between, and every
  internal use runs under ``check_vma=False``/``check_rep=False``.
"""

from __future__ import annotations

__all__ = ["pcast", "shard_map"]

try:  # jax with top-level shard_map (check_vma spelling)
    from jax import shard_map as _new_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return _new_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

except ImportError:  # jax 0.4.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        # always False: without pcast the ring programs' device-varying
        # scan carries cannot be annotated, so pre-VMA replication
        # tracking mis-infers them (results are unaffected; the checker
        # is advisory)
        kw["check_rep"] = False
        return _exp_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


try:  # jax with the VMA type system
    from jax.lax import pcast
except ImportError:  # pre-VMA jax: nothing to cast between

    def pcast(x, axis_name, *, to=None):
        return x
