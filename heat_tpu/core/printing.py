"""Rank-aware printing of DNDarrays.

API parity with /root/reference/heat/core/printing.py (``local_printing``
at printing.py:30, ``global_printing`` at :62, ``print0`` at :100,
``set_printoptions`` at :150, gather-based ``_torch_data`` at :208).
Under a single controller the "gather to rank 0" disappears — the global
array is addressable; large arrays are summarized via numpy printoptions
so no full device-to-host transfer happens for huge arrays.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["get_printoptions", "global_printing", "local_printing", "print0", "set_printoptions"]

# printing profiles mirroring torch defaults (reference printing.py:14-28)
__PRINT_OPTIONS = {
    "precision": 4,
    "threshold": 1000,
    "edgeitems": 3,
    "linewidth": 120,
    "sci_mode": None,
}

LOCAL_PRINT = False


def get_printoptions() -> dict:
    """View of the current print options (reference: printing.py:44)."""
    return dict(__PRINT_OPTIONS)


def local_printing() -> None:
    """Print the process-local data only (reference: printing.py:30)."""
    global LOCAL_PRINT
    LOCAL_PRINT = True


def global_printing() -> None:
    """Print the global array (default; reference: printing.py:62)."""
    global LOCAL_PRINT
    LOCAL_PRINT = False


def print0(*args, **kwargs) -> None:
    """Print from the controlling process only (reference: printing.py:100).
    Single-controller: a plain print."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def set_printoptions(
    precision=None,
    threshold=None,
    edgeitems=None,
    linewidth=None,
    profile=None,
    sci_mode=None,
) -> None:
    """Configure printing (reference: printing.py:150)."""
    if profile is not None:
        if profile == "default":
            __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
        elif profile == "short":
            __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
        elif profile == "full":
            __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
        else:
            raise ValueError(f"unknown profile {profile}")
    if precision is not None:
        __PRINT_OPTIONS["precision"] = int(precision)
    if threshold is not None:
        __PRINT_OPTIONS["threshold"] = threshold
    if edgeitems is not None:
        __PRINT_OPTIONS["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        __PRINT_OPTIONS["linewidth"] = int(linewidth)
    if sci_mode is not None:
        __PRINT_OPTIONS["sci_mode"] = bool(sci_mode)


def __str__(dndarray) -> str:
    """String representation: torch-style metadata plus summarized data
    (reference: printing.py:187 __str__)."""
    from . import types

    opts = __PRINT_OPTIONS
    summarized = False
    if dndarray._is_planar:
        # planar complex: format the host complex64 assembly through the
        # shared block below (dtype.kind 'c' passes the biufc check).
        # Large arrays edge-slice the PLANE VIEW on device first — a full
        # numpy() here would allgather the whole array to render ~6 items
        if dndarray.size > opts["threshold"] and dndarray.ndim > 0:
            data = _planar_summarized(dndarray, opts["edgeitems"])
            summarized = True
        else:
            data = dndarray.numpy()
    elif LOCAL_PRINT:
        arr = dndarray.larray
        data = np.asarray(arr.addressable_shards[0].data) if arr.addressable_shards else np.asarray(arr)
    else:
        # summarize without materializing huge arrays on host
        if dndarray.size > opts["threshold"] and dndarray.ndim > 0:
            data = _summarized_numpy(dndarray, opts["edgeitems"])
            summarized = True
        else:
            data = dndarray.numpy()
    if data.dtype.kind not in "biufc":  # e.g. ml_dtypes bfloat16
        data = data.astype(np.float32)
    # a pre-sliced edge block must still render with ellipses
    threshold = 1 if summarized and data.size > 1 else opts["threshold"]
    with np.printoptions(
        precision=opts["precision"],
        threshold=threshold,
        edgeitems=opts["edgeitems"],
        linewidth=opts["linewidth"],
        suppress=not opts["sci_mode"] if opts["sci_mode"] is not None else True,
    ):
        body = np.array2string(data, separator=", ")
    dtype_name = dndarray.dtype.__name__
    return f"DNDarray({body}, dtype=ht.{dtype_name}, device={dndarray.device}, split={dndarray.split})"


def _edge_take(arr, shape, edgeitems: int):
    """Select the displayed edge slices of ``arr`` along each dim of the
    LOGICAL ``shape`` (trailing extra axes ride along) — the one place
    the edge-selection rule lives. Host ndarrays stay on host (a complex
    host array must never round-trip through the device in planar mode)."""
    on_host = isinstance(arr, np.ndarray)
    for d, s in enumerate(shape):
        if s > 2 * edgeitems + 1:
            ix = np.r_[0 : edgeitems + 1, s - edgeitems : s]
        else:
            ix = np.arange(s)
        arr = np.take(arr, ix, axis=d) if on_host else jnp.take(arr, jnp.asarray(ix), axis=d)
    return arr


def _planar_summarized(dndarray, edgeitems: int) -> np.ndarray:
    """Edge slices of a planar complex array, selected from the plane
    view ON DEVICE (same selection as ``_summarized_numpy``; only the
    displayed items reach the host) and assembled to complex64. In a
    multi-process world the plane array spans non-addressable devices,
    which ``np.asarray`` cannot fetch — fall back to the allgathering
    ``numpy()`` export there."""
    from . import complex_planar as _cp

    view = _cp._planar_view(dndarray)  # (gshape..., 2)
    if jax.process_count() > 1 and not view.is_fully_addressable:
        return _edge_take(dndarray.numpy(), dndarray.shape, edgeitems)
    sub = _edge_take(view, dndarray.shape, edgeitems)
    return _cp.assemble_host(np.asarray(sub))


def _summarized_numpy(dndarray, edgeitems: int) -> np.ndarray:
    """Fetch only the displayed edge slices to host (the analog of the
    reference's threshold-summarized gather, printing.py:208)."""
    return np.asarray(_edge_take(dndarray.larray, dndarray.shape, edgeitems))
