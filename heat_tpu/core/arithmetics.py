"""Arithmetic operations.

API parity with /root/reference/heat/core/arithmetics.py (39 exports, all
built on the generic wrappers of ``_operations``). Each op is a jnp/XLA
kernel on the sharded global array; reductions over the split axis lower to
all-reduce over the mesh (reference: ``__reduce_op`` path,
_operations.py:466-471), ``diff`` needs the same neighbor exchange the
reference performs explicitly (arithmetics.py `diff`) — emitted by XLA from
the shifted-slice formulation.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Union

from . import types
from . import _operations
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "copysign",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divmod",
    "divide",
    "floordiv",
    "floor_divide",
    "fmod",
    "gcd",
    "hypot",
    "invert",
    "lcm",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nan_to_num",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise addition (reference: arithmetics.py add)."""
    return _operations.__binary_op(jnp.add, t1, t2, out, where)


def _check_int_or_bool(t, name):
    for t_ in (t,):
        if isinstance(t_, DNDarray) and types.heat_type_is_inexact(t_.dtype):
            raise TypeError(f"operation {name} not supported for float dtype {t_.dtype}")


def bitwise_and(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise AND of integer/boolean arrays."""
    _check_int_or_bool(t1, "bitwise_and"), _check_int_or_bool(t2, "bitwise_and")
    return _operations.__binary_op(jnp.bitwise_and, t1, t2, out, where)


def bitwise_or(t1, t2, out=None, where=None) -> DNDarray:
    _check_int_or_bool(t1, "bitwise_or"), _check_int_or_bool(t2, "bitwise_or")
    return _operations.__binary_op(jnp.bitwise_or, t1, t2, out, where)


def bitwise_xor(t1, t2, out=None, where=None) -> DNDarray:
    _check_int_or_bool(t1, "bitwise_xor"), _check_int_or_bool(t2, "bitwise_xor")
    return _operations.__binary_op(jnp.bitwise_xor, t1, t2, out, where)


def bitwise_not(t, out=None) -> DNDarray:
    """Elementwise NOT; alias ``invert``."""
    _check_int_or_bool(t, "bitwise_not")
    return _operations.__local_op(jnp.bitwise_not, t, out, no_cast=True)


invert = bitwise_not


def copysign(t1, t2, out=None, where=None) -> DNDarray:
    """Magnitude of t1 with sign of t2."""
    return _operations.__binary_op(jnp.copysign, t1, t2, out, where)


def cumprod(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product along ``axis`` (reference: __cum_op with Multiply
    + Exscan; here a sharded jnp.cumprod)."""
    return _operations.__cum_op(jnp.cumprod, a, axis, out=out, dtype=dtype)


cumproduct = cumprod


def cumsum(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along ``axis``."""
    return _operations.__cum_op(jnp.cumsum, a, axis, out=out, dtype=dtype)


def diff(a: DNDarray, n: int = 1, axis: int = -1) -> DNDarray:
    """n-th discrete difference along ``axis`` (reference arithmetics.py
    diff performs explicit split-axis neighbor comm; the shifted-slice
    difference makes XLA emit the same halo exchange)."""
    from .stride_tricks import sanitize_axis

    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"order must be non-negative but was {n}")
    axis = sanitize_axis(a.shape, axis)
    result = jnp.diff(a.larray, n=n, axis=axis)
    # gshape is the LOGICAL shape — record it before shard() pads the
    # split extent, or the pad rows leak into the logical view
    gshape = tuple(int(s) for s in result.shape)
    split = a.split
    if split is not None:
        result = a.comm.shard(result, split)
    return DNDarray(
        result,
        gshape,
        types.canonical_heat_type(result.dtype),
        split,
        a.device,
        a.comm,
    )


def div(t1, t2, out=None, where=None) -> DNDarray:
    """True division (reference: arithmetics.py div)."""
    return _operations.__binary_op(jnp.true_divide, t1, t2, out, where)


divide = div


def divmod(t1, t2, out1=None, out2=None, out=None, where=None):
    """Elementwise (floordiv, mod) pair."""
    if out is None:
        out = (out1, out2)
    if not isinstance(out, tuple) or len(out) != 2:
        raise ValueError("out must be a tuple of two DNDarrays")
    d = floordiv(t1, t2, out[0], where)
    m = mod(t1, t2, out[1], where)
    return d, m


def floordiv(t1, t2, out=None, where=None) -> DNDarray:
    """Floor division."""
    return _operations.__binary_op(jnp.floor_divide, t1, t2, out, where)


floor_divide = floordiv


def fmod(t1, t2, out=None, where=None) -> DNDarray:
    """C-style remainder (sign of dividend)."""
    return _operations.__binary_op(jnp.fmod, t1, t2, out, where)


def gcd(t1, t2, out=None, where=None) -> DNDarray:
    """Greatest common divisor of integer arrays."""
    return _operations.__binary_op(jnp.gcd, t1, t2, out, where)


def hypot(t1, t2, out=None, where=None) -> DNDarray:
    """Hypotenuse sqrt(t1**2 + t2**2)."""
    return _operations.__binary_op(jnp.hypot, t1, t2, out, where)


def lcm(t1, t2, out=None, where=None) -> DNDarray:
    """Least common multiple of integer arrays."""
    return _operations.__binary_op(jnp.lcm, t1, t2, out, where)


def left_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Bitwise left shift."""
    _check_int_or_bool(t1, "left_shift")
    return _operations.__binary_op(jnp.left_shift, t1, t2, out, where)


def mod(t1, t2, out=None, where=None) -> DNDarray:
    """Python-style modulo (sign of divisor); alias ``remainder``."""
    return _operations.__binary_op(jnp.mod, t1, t2, out, where)


remainder = mod


def mul(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise multiplication."""
    return _operations.__binary_op(jnp.multiply, t1, t2, out, where)


multiply = mul


def nan_to_num(a: DNDarray, nan=0.0, posinf=None, neginf=None, out=None) -> DNDarray:
    """Replace NaN/inf with finite numbers."""
    return _operations.__local_op(
        jnp.nan_to_num, a, out, no_cast=True, nan=nan, posinf=posinf, neginf=neginf
    )


def nanprod(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Product ignoring NaNs (reference: arithmetics.py nanprod)."""
    return _operations.__reduce_op(jnp.nanprod, a, axis=axis, out=out, keepdims=keepdims)


def nansum(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Sum ignoring NaNs."""
    return _operations.__reduce_op(jnp.nansum, a, axis=axis, out=out, keepdims=keepdims)


def neg(a: DNDarray, out=None) -> DNDarray:
    """Elementwise negation."""
    return _operations.__local_op(jnp.negative, a, out, no_cast=True)


negative = neg


def pos(a: DNDarray, out=None) -> DNDarray:
    """Elementwise unary plus."""
    return _operations.__local_op(jnp.positive, a, out, no_cast=True)


positive = pos


def pow(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise power."""
    # fast-path: small integral scalar exponents keep dtype (numpy semantics)
    if isinstance(t2, (int, float)) and float(t2).is_integer():
        t2 = int(t2)
    return _operations.__binary_op(jnp.power, t1, t2, out, where)


power = pow


def prod(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Product over ``axis`` (reference: __reduce_op with MPI.PROD)."""
    return _operations.__reduce_op(jnp.prod, a, axis=axis, out=out, keepdims=keepdims)


def right_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Bitwise right shift."""
    _check_int_or_bool(t1, "right_shift")
    return _operations.__binary_op(jnp.right_shift, t1, t2, out, where)


def sub(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise subtraction."""
    return _operations.__binary_op(jnp.subtract, t1, t2, out, where)


subtract = sub


def sum(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Sum over ``axis`` (reference: __reduce_op + Allreduce when the split
    axis is reduced, _operations.py:466-471 — XLA emits that all-reduce)."""
    return _operations.__reduce_op(jnp.sum, a, axis=axis, out=out, keepdims=keepdims)


# ------------------------------------------------------------------ #
# DNDarray operator / method attachment (reference attaches these     #
# throughout arithmetics.py)                                          #
# ------------------------------------------------------------------ #
DNDarray.__add__ = lambda self, other: add(self, other)
DNDarray.__radd__ = lambda self, other: add(other, self)
DNDarray.__iadd__ = lambda self, other: add(self, other)
DNDarray.__sub__ = lambda self, other: sub(self, other)
DNDarray.__rsub__ = lambda self, other: sub(other, self)
DNDarray.__isub__ = lambda self, other: sub(self, other)
DNDarray.__mul__ = lambda self, other: mul(self, other)
DNDarray.__rmul__ = lambda self, other: mul(other, self)
DNDarray.__imul__ = lambda self, other: mul(self, other)
DNDarray.__truediv__ = lambda self, other: div(self, other)
DNDarray.__rtruediv__ = lambda self, other: div(other, self)
DNDarray.__itruediv__ = lambda self, other: div(self, other)
DNDarray.__floordiv__ = lambda self, other: floordiv(self, other)
DNDarray.__rfloordiv__ = lambda self, other: floordiv(other, self)
DNDarray.__mod__ = lambda self, other: mod(self, other)
DNDarray.__rmod__ = lambda self, other: mod(other, self)
DNDarray.__pow__ = lambda self, other: pow(self, other)
DNDarray.__rpow__ = lambda self, other: pow(other, self)
DNDarray.__neg__ = lambda self: neg(self)
DNDarray.__pos__ = lambda self: pos(self)
def _dunder_abs(self):
    from . import rounding

    return rounding.abs(self)


DNDarray.__abs__ = _dunder_abs
DNDarray.__invert__ = lambda self: invert(self)
DNDarray.__and__ = lambda self, other: bitwise_and(self, other)
DNDarray.__rand__ = lambda self, other: bitwise_and(other, self)
DNDarray.__or__ = lambda self, other: bitwise_or(self, other)
DNDarray.__ror__ = lambda self, other: bitwise_or(other, self)
DNDarray.__xor__ = lambda self, other: bitwise_xor(self, other)
DNDarray.__rxor__ = lambda self, other: bitwise_xor(other, self)
DNDarray.__lshift__ = lambda self, other: left_shift(self, other)
DNDarray.__rshift__ = lambda self, other: right_shift(self, other)
DNDarray.__divmod__ = lambda self, other: divmod(self, other)

DNDarray.add = add
DNDarray.sub = sub
DNDarray.mul = mul
DNDarray.div = div
DNDarray.pow = pow
DNDarray.mod = mod
DNDarray.sum = sum
DNDarray.prod = prod
DNDarray.nansum = nansum
DNDarray.nanprod = nanprod
DNDarray.cumsum = cumsum
DNDarray.cumprod = cumprod
