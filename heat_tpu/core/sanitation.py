"""Input validation and distribution matching.

API parity with /root/reference/heat/core/sanitation.py
(``sanitize_distribution`` at sanitation.py:31, ``sanitize_in`` at :158,
``sanitize_out`` at :254). Distribution matching in the reference issues
explicit redistribution comm (dndarray.redistribute_); here it is a
resharding ``jax.device_put`` the XLA compiler lowers to collectives.
"""

from __future__ import annotations

import numpy as np

from typing import Optional, Tuple, Union

__all__ = [
    "sanitize_distribution",
    "sanitize_in",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_infinity",
    "sanitize_out",
    "sanitize_sequence",
    "scalar_to_1d",
]


def sanitize_distribution(*args, target, diff_map=None):
    """Reshard every DNDarray in ``args`` to ``target``'s split layout
    (reference: sanitation.py:31 redistributes to target.lshape_map; here a
    sharding change suffices — GSPMD layouts are canonical).

    Returns the single resharded array or a tuple of them.
    """
    from .dndarray import DNDarray

    sanitize_in(target)
    out = []
    tsplit = target.split
    for arg in args:
        sanitize_in(arg)
        # align split to target's (accounting for broadcast dim offset)
        new_split = None if tsplit is None else tsplit - (target.ndim - arg.ndim)
        if (
            tsplit is None
            or arg.split is None
            or new_split < 0
            or arg.gshape[new_split] == 1
            or arg.split == new_split
        ):
            out.append(arg)
        else:
            out.append(arg.resplit(new_split))
    if len(out) == 1:
        return out[0]
    return tuple(out)


def sanitize_in(x) -> None:
    """Verify ``x`` is a DNDarray (reference: sanitation.py:158)."""
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_in_tensor(x) -> None:
    """Verify ``x`` is a jax array."""
    import jax

    if not isinstance(x, jax.Array):
        raise TypeError(f"input needs to be a jax.Array, but was {type(x)}")


def sanitize_lshape(array, tensor) -> None:
    """Verify that a local tensor is a plausible shard of ``array``
    (reference: sanitation.py:212)."""
    gshape = array.gshape
    lshape = tuple(tensor.shape)
    if len(lshape) != len(gshape):
        raise ValueError(f"tensor dims {len(lshape)} do not match array dims {len(gshape)}")
    split = array.split
    if split is None:
        if lshape != gshape:
            raise ValueError(f"tensor shape {lshape} does not match global shape {gshape}")
        return
    for i, (ls, gs) in enumerate(zip(lshape, gshape)):
        if i == split:
            if ls > gs:
                raise ValueError(f"local split extent {ls} exceeds global {gs}")
        elif ls != gs:
            raise ValueError(f"tensor shape {lshape} incompatible with global shape {gshape}")


def sanitize_out(out, output_shape, output_split, output_device, output_comm=None):
    """Verify that ``out`` is consistent with the expected output
    (reference: sanitation.py:254). Reshards/rebinds ``out`` metadata where
    the reference would redistribute.
    """
    from .dndarray import DNDarray

    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out buffer to be a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {tuple(out.shape)}")
    return out


def sanitize_sequence(seq) -> list:
    """Check that ``seq`` is a list/tuple and return it as a list
    (reference: sanitation.py:322)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    raise TypeError(f"seq must be a list or a tuple, got {type(seq)}")


def scalar_to_1d(x):
    """Turn a scalar DNDarray into a 1-D DNDarray with one element
    (reference: sanitation.py:341)."""
    from .dndarray import DNDarray

    if x.ndim != 0:
        return x
    return DNDarray(
        x.larray.reshape(1),
        gshape=(1,),
        dtype=x.dtype,
        split=None,
        device=x.device,
        comm=x.comm,
        balanced=True,
    )


def sanitize_infinity(x):
    """Largest representable value for the input's dtype — float for
    inexact dtypes, int for integers, True for bool (reference:
    sanitation.py:176, a +inf stand-in usable in integer comparisons).
    Dispatches through ``types.finfo``/``types.iinfo`` (the canonical
    dtype-extreme helpers)."""
    from . import types

    dtype = types.canonical_heat_type(x.dtype)
    if dtype is types.bool:
        return True
    if types.heat_type_is_inexact(dtype):
        return float(types.finfo(dtype).max)
    return int(types.iinfo(dtype).max)


def assert_evenly_sharded(x, label: str = "") -> None:
    """Scale-safety invariant: every local device holds exactly phys/p
    bytes of ``x`` — the array is truly distributed, never replicated or
    gathered to one device. Shared by the driver dryrun and the test
    suite so both enforce the same invariant."""
    comm = x.comm
    shards = x._phys.addressable_shards
    local = sum(1 for d in comm.devices if d.process_index == __import__("jax").process_index())
    assert len(shards) == local, f"{label}: {len(shards)} shards for {local} local devices"
    expect = x._phys.nbytes // comm.size
    for s in shards:
        assert s.data.nbytes == expect, (
            f"{label}: device {s.device} holds {s.data.nbytes} bytes, expected {expect}"
        )
