"""Pseudo-random number generation.

API parity with /root/reference/heat/core/random.py (15 exports). The
reference hand-implements a counter-based Threefry-2x32/64 cipher in torch
ops (``__threefry32/64`` random.py:874/:976) precisely so that results are
reproducible regardless of the number of MPI ranks (``__counter_sequence``
:55-198 gives each rank its slice of the global 128-bit counter stream).
JAX's native PRNG *is* counter-based Threefry, and with
``jax_threefry_partitionable`` (on by default) a draw jitted with sharded
``out_shardings`` makes each device generate ONLY its slice of the counter
stream — the exact design the reference emulates by hand. Draws here are
therefore scale-safe (no device ever materializes the global array) and
mesh-size independent (the same (seed, counter) produces the same global
values on any mesh). A global (seed, counter) pair advances per draw.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple, Type, Union

from . import types
from .communication import Communication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "ranf",
    "randint",
    "random_integer",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
]

# global PRNG state: (seed, counter) — the analog of the reference's
# __seed/__counter globals (random.py:40-52)
__seed: int = None
__counter: int = 0


def __init_seed() -> None:
    global __seed, __counter
    if __seed is None:
        import time

        __seed = int(time.time() * 1000) % (2**32)
        __counter = 0


def _next_key(numel: int) -> jax.Array:
    """Fold the current counter into the seed key and advance the counter
    by the number of elements drawn (the reference's counter-slice logic,
    random.py:55-198, without the per-rank arithmetic). Both 32-bit words
    of the counter are folded, so the stream only cycles after 2**64
    elements — a mod-2**31 fold would silently repeat at large scale."""
    global __counter
    __init_seed()
    key = jax.random.PRNGKey(__seed)
    key = jax.random.fold_in(key, np.uint32(__counter & 0xFFFFFFFF))
    key = jax.random.fold_in(key, np.uint32((__counter >> 32) & 0xFFFFFFFF))
    __counter += int(numel)
    return key


def _wrap(values: jax.Array, dtype, split, device, comm) -> DNDarray:
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    split = sanitize_axis(values.shape, split)
    gshape = tuple(int(s) for s in values.shape)
    values = comm.shard(values, split)
    return DNDarray(values, gshape, dtype, split, device, comm)


@functools.lru_cache(maxsize=512)
def _cached_sampler(mesh, axis_name: str, op_key: str, shape, jdtype: str, split):
    """jit-compiled sampler with sharded output: partitionable Threefry
    gives every device exactly its counter slice (the analog of the
    reference's per-rank ``__counter_sequence``, random.py:55-198) — no
    device materializes the full array. Distribution hyperparameters
    (mean/std, low/high) are TRACED arguments, so an annealed std does not
    recompile."""
    from . import _padding
    from jax.sharding import NamedSharding, PartitionSpec

    size = mesh.devices.size
    if split is not None and (not shape or shape[split] == 0):
        split = None
    if split is None or not shape:
        spec = PartitionSpec()
    else:
        spec = PartitionSpec(*(axis_name if i == split else None for i in range(len(shape))))
    sharding = NamedSharding(mesh, spec)

    def build(key, *args):
        if op_key == "uniform":
            logical = jax.random.uniform(key, shape, dtype=jdtype)
        elif op_key == "normal":
            mean, std = args
            logical = jax.random.normal(key, shape, dtype=jdtype) * std + mean
        elif op_key == "randint":
            low, high = args
            logical = jax.random.randint(key, shape, low, high, dtype=jdtype)
        else:
            raise ValueError(op_key)
        return _padding.pad_logical(logical, split, size)

    # build() has NO committed array inputs (the PRNG key is uncommitted),
    # so out_shardings is what pins placement — it must stay even on a
    # 1-device mesh (a .cpu() comm or Split sub-communicator is not the
    # default device); creation dispatch is not a hot path
    return jax.jit(build, out_shardings=sharding)


def _draw(op_key: str, shape, dtype, split, device, comm, args=()) -> DNDarray:
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    split = sanitize_axis(shape, split)
    numel = int(np.prod(shape)) if shape else 1
    key = _next_key(numel)
    sampler = _cached_sampler(
        comm.mesh,
        comm.axis_name,
        op_key,
        tuple(shape),
        np.dtype(dtype.jax_type()).name,
        split,
    )
    if op_key == "normal":
        args = (jnp.asarray(args[0], dtype=dtype.jax_type()),
                jnp.asarray(args[1], dtype=dtype.jax_type()))
    elif op_key == "randint":
        args = (jnp.asarray(args[0]), jnp.asarray(args[1]))
    data = sampler(key, *args)
    return DNDarray(data, tuple(shape), dtype, split, device, comm)


def get_state() -> Tuple[str, int, int, int, float]:
    """Return the internal state of the generator (reference:
    random.py get_state): ('Threefry', seed, counter, 0, 0.0)."""
    __init_seed()
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple[str, int, int, int, float]) -> None:
    """Set the internal state (reference: random.py set_state)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise ValueError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def seed(seed: Optional[int] = None) -> None:
    """Seed the generator (reference: random.py seed)."""
    global __seed, __counter
    if seed is None:
        import time

        seed = int(time.time() * 1000) % (2**32)
    __seed = int(seed)
    __counter = 0


def normal(
    mean=0.0,
    std=1.0,
    shape: Optional[Tuple[int, ...]] = None,
    dtype: Type[types.datatype] = types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Normal distribution with given mean and std (reference: random.py
    normal; Kundu transform at random.py:246 — jax.random.normal here)."""
    if shape is None:
        shape = getattr(mean, "shape", None) or getattr(std, "shape", None) or ()
    shape = sanitize_shape(shape) if shape != () else ()
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float16, types.bfloat16, types.float32, types.float64):
        raise ValueError("dtype must be a float type")
    if isinstance(mean, DNDarray) or isinstance(std, DNDarray):
        # array-valued moments: draw standard normal sharded, scale eagerly
        # (elementwise ops preserve the sharding; pad re-zeroed below)
        base = _draw("normal", shape, dtype, split, device, comm, (0.0, 1.0))
        m = mean.larray if isinstance(mean, DNDarray) else mean
        s = std.larray if isinstance(std, DNDarray) else std
        values = base.larray * s + m
        return _wrap(values, dtype, base.split, base.device, base.comm)
    return _draw("normal", shape, dtype, split, device, comm, (float(mean), float(std)))


def permutation(x) -> DNDarray:
    """Random permutation of arange(n) or shuffle of a copy of x along
    axis 0 (reference: random.py permutation)."""
    if isinstance(x, (int, np.integer)):
        return randperm(int(x))
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected int or DNDarray, got {type(x)}")
    key = _next_key(x.shape[0] if x.ndim else 1)
    perm = jax.random.permutation(key, x.shape[0])
    values = jnp.take(x.larray, perm, axis=0)
    return _wrap(values, x.dtype, x.split, x.device, x.comm)


def rand(
    *args,
    dtype: Type[types.datatype] = types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform [0, 1) samples of the given shape (reference: random.py
    rand)."""
    shape = sanitize_shape(args) if args else ()
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float16, types.bfloat16, types.float32, types.float64):
        raise ValueError(f"dtype must be a float type, got {dtype}")
    return _draw("uniform", shape, dtype, split, device, comm)


def randint(
    low: int,
    high: Optional[int] = None,
    size: Optional[Union[int, Tuple[int, ...]]] = None,
    dtype: Optional[Type[types.datatype]] = types.int32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Random integers in [low, high) (reference: random.py randint)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    shape = sanitize_shape(size) if size != () else ()
    if low >= high:
        raise ValueError(f"low >= high ({low} >= {high})")
    dtype = types.canonical_heat_type(dtype if dtype is not None else types.int32)
    if dtype not in (types.int8, types.int16, types.int32, types.int64, types.uint8):
        raise ValueError(f"dtype must be an integer type, got {dtype}")
    return _draw("randint", shape, dtype, split, device, comm, (int(low), int(high)))


random_integer = randint


def randn(
    *args,
    dtype: Type[types.datatype] = types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Standard-normal samples of the given shape (reference: random.py
    randn)."""
    shape = sanitize_shape(args) if args else ()
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float16, types.bfloat16, types.float32, types.float64):
        raise ValueError(f"dtype must be a float type, got {dtype}")
    return _draw("normal", shape, dtype, split, device, comm, (0.0, 1.0))


def random(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference: random.py random)."""
    return random_sample(shape, dtype, split, device, comm)


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference: random.py random_sample)."""
    if shape is None:
        shape = (1,)
    shape = sanitize_shape(shape)
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


ranf = random_sample
sample = random_sample


def randperm(
    n: int,
    dtype: Type[types.datatype] = types.int64,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Random permutation of arange(n) (reference: random.py randperm)."""
    if not isinstance(n, (int, np.integer)):
        raise TypeError(f"n must be an integer, got {type(n)}")
    dtype = types.canonical_heat_type(dtype)
    key = _next_key(n)
    values = jax.random.permutation(key, int(n)).astype(dtype.jax_type())
    return _wrap(values, dtype, split, device, comm)


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference: random.py standard_normal)."""
    if shape is None:
        shape = (1,)
    shape = sanitize_shape(shape)
    return randn(*shape, dtype=dtype, split=split, device=device, comm=comm)

from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_cached_sampler)
