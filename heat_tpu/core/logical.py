"""Logical operations and predicates.

API parity with /root/reference/heat/core/logical.py (14 exports).
``all``/``any``/``allclose`` in the reference perform a local test plus an
``Allreduce`` with LAND/LOR; the jnp reduction over the sharded array emits
the identical collective.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Optional, Union

from . import _operations
from . import types
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """True where all elements (along ``axis``) evaluate to True
    (reference: logical.py all — local test + LAND Allreduce)."""
    return _operations.__reduce_op(jnp.all, x, axis=axis, out=out, keepdims=keepdims)


def allclose(x: DNDarray, y: DNDarray, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Scalar verdict: all elements of x and y within tolerances
    (reference: logical.py allclose)."""
    close = isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return bool(jnp.all(close.larray))


def any(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """True where any element evaluates to True (LOR reduction)."""
    return _operations.__reduce_op(jnp.any, x, axis=axis, out=out, keepdims=keepdims)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Elementwise tolerance comparison."""
    return _operations.__binary_op(
        jnp.isclose, x, y, fn_kwargs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan}
    )


def isfinite(x: DNDarray) -> DNDarray:
    """Elementwise finiteness test."""
    return _operations.__local_op(jnp.isfinite, x, None, no_cast=True)


def isinf(x: DNDarray) -> DNDarray:
    """Elementwise infinity test."""
    return _operations.__local_op(jnp.isinf, x, None, no_cast=True)


def isnan(x: DNDarray) -> DNDarray:
    """Elementwise NaN test."""
    return _operations.__local_op(jnp.isnan, x, None, no_cast=True)


def isneginf(x: DNDarray, out=None) -> DNDarray:
    """Elementwise -inf test."""
    return _operations.__local_op(jnp.isneginf, x, out, no_cast=True)


def isposinf(x: DNDarray, out=None) -> DNDarray:
    """Elementwise +inf test."""
    return _operations.__local_op(jnp.isposinf, x, out, no_cast=True)


def logical_and(t1, t2) -> DNDarray:
    """Elementwise logical AND."""
    return _operations.__binary_op(jnp.logical_and, t1, t2)


def logical_not(t: DNDarray, out=None) -> DNDarray:
    """Elementwise logical NOT."""
    return _operations.__local_op(jnp.logical_not, t, out, no_cast=True)


def logical_or(t1, t2) -> DNDarray:
    """Elementwise logical OR."""
    return _operations.__binary_op(jnp.logical_or, t1, t2)


def logical_xor(t1, t2) -> DNDarray:
    """Elementwise logical XOR."""
    return _operations.__binary_op(jnp.logical_xor, t1, t2)


def signbit(x: DNDarray, out=None) -> DNDarray:
    """True where the sign bit is set."""
    return _operations.__local_op(jnp.signbit, x, out, no_cast=True)


DNDarray.all = all
DNDarray.any = any
DNDarray.allclose = allclose
DNDarray.isclose = isclose
