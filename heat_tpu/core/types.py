"""Type system for heat_tpu.

NumPy-style dtype class hierarchy mapped onto JAX dtypes, with a
torch-like ("intuitive") promotion lattice. API parity with the reference
type system (/root/reference/heat/core/types.py: ``datatype`` hierarchy at
types.py:64-414, ``canonical_heat_type`` at :494, ``promote_types`` at :838,
``result_type`` at :870, ``finfo``/``iinfo`` at :952/:1007), re-designed for
TPU: the canonical carrier is a ``jax.numpy`` dtype, and ``bfloat16`` /
``float16`` are first-class members of the lattice (the reference comments
them out) because they are the native MXU formats.
"""

from __future__ import annotations

import builtins
import numpy as np
import jax
import jax.numpy as jnp

from typing import Any, Iterable, Type, Union

__all__ = [
    "datatype",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "bool",
    "bool_",
    "floating",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int_",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "flexible",
    "complex",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "check_complex_platform",
    "heat_type_is_realfloating",
    "issubdtype",
    "can_cast",
    "promote_types",
    "result_type",
    "iscomplex",
    "isreal",
    "finfo",
    "iinfo",
]


class datatype:
    """Generic base class for heat_tpu data types.

    Instantiation casts the operand to the respective type, e.g.
    ``ht.float32(x)`` returns a ``DNDarray`` of dtype float32
    (reference semantics: types.py:64-156).
    """

    _jax_type: Any = None
    _char: str = None

    def __new__(cls, *value, device=None, comm=None):
        from . import factories

        if cls._jax_type is None:
            raise TypeError(f"cannot create '{cls}' instances")

        value_count = len(value)
        if value_count not in (0, 1):
            raise TypeError(f"function takes at most 1 argument ({value_count} given)")
        payload = value[0] if value_count else 0

        return factories.array(payload, dtype=cls, device=device, comm=comm)

    @classmethod
    def jax_type(cls):
        """The corresponding ``jax.numpy`` dtype."""
        return cls._jax_type

    # name kept for reference-API familiarity; returns the jax dtype here
    torch_type = jax_type

    @classmethod
    def char(cls) -> str:
        """Single-character type code."""
        return cls._char


class bool(datatype):
    """1-byte boolean."""

    _jax_type = jnp.bool_
    _char = "?"


class number(datatype):
    """Abstract base for all numeric types."""


class integer(number):
    """Abstract base for integer types."""


class signedinteger(integer):
    """Abstract base for signed integers."""


class int8(signedinteger):
    _jax_type = jnp.int8
    _char = "b"


class int16(signedinteger):
    _jax_type = jnp.int16
    _char = "h"


class int32(signedinteger):
    _jax_type = jnp.int32
    _char = "i"


class int64(signedinteger):
    _jax_type = jnp.int64
    _char = "l"


class unsignedinteger(integer):
    """Abstract base for unsigned integers."""


class uint8(unsignedinteger):
    _jax_type = jnp.uint8
    _char = "B"


class floating(number):
    """Abstract base for floating-point types."""


class float16(floating):
    """IEEE half precision. TPU-first extension over the reference."""

    _jax_type = jnp.float16
    _char = "e"


class bfloat16(floating):
    """Brain floating point — the native MXU input format.

    Not present in the reference type system; first-class here because
    matmul/conv throughput on TPU doubles in bf16.
    """

    _jax_type = jnp.bfloat16
    _char = "E"


class float32(floating):
    _jax_type = jnp.float32
    _char = "f"


class float64(floating):
    _jax_type = jnp.float64
    _char = "d"


class flexible(datatype):
    """Abstract base for types with flexible/variable size."""


class complex(number):
    """Abstract base for complex floating types."""


class complex64(complex):
    _jax_type = jnp.complex64
    _char = "F"


class complex128(complex):
    _jax_type = jnp.complex128
    _char = "D"


# aliases (reference: types.py:414-428)
bool_ = bool
ubyte = uint8
byte = int8
short = int16
int = int32
int_ = int32
long = int64
half = float16
float = float32
float_ = float32
double = float64
cfloat = complex64
csingle = complex64
cdouble = complex128

_complexfloating = (complex64, complex128)
_inexact = (float16, bfloat16, float32, float64, *_complexfloating)
_exact = (uint8, int8, int16, int32, int64)

# type mappings for type strings, numpy dtypes and builtin types
__type_mappings = {
    # type strings
    "?": bool,
    "B": uint8,
    "b": int8,
    "h": int16,
    "i": int32,
    "l": int64,
    "e": float16,
    "E": bfloat16,
    "f": float32,
    "d": float64,
    "F": complex64,
    "D": complex128,
    "b1": bool,
    "u": uint8,
    "u1": uint8,
    "i1": int8,
    "i2": int16,
    "i4": int32,
    "i8": int64,
    "f2": float16,
    "f4": float32,
    "f8": float64,
    "c8": complex64,
    "c16": complex128,
    "bfloat16": bfloat16,
    # numpy scalar types
    np.bool_: bool,
    np.uint8: uint8,
    np.int8: int8,
    np.int16: int16,
    np.int32: int32,
    np.int64: int64,
    np.float16: float16,
    np.float32: float32,
    np.float64: float64,
    np.complex64: complex64,
    np.complex128: complex128,
    # builtins
    builtins.bool: bool,
    builtins.int: int32,
    builtins.float: float32,
    builtins.complex: complex64,
}

# numpy-dtype-name → heat type (covers jnp dtypes incl. bfloat16)
__name_mappings = {
    "bool": bool,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}


# 64-bit degradation policy: on platforms without 64-bit arithmetic (TPU —
# JAX's x64 mode stays off there by default, see devices._apply_x64_policy)
# requesting a 64-bit dtype yields its 32-bit counterpart, HONESTLY: both
# the device buffer and the array's dtype metadata degrade together, the
# way bf16-era accelerator stacks treat f64. Flipped by the platform
# policy / ht.use_x64(); never active in x64 mode.
_DEGRADE_64 = False


_DEGRADE_MAP = {float64: float32, int64: int32, complex128: complex64}


def degrade64(t: Type["datatype"]) -> Type["datatype"]:
    """Apply the 64→32-bit platform degradation to a heat type (no-op in
    x64 mode)."""
    if _DEGRADE_64:
        return _DEGRADE_MAP.get(t, t)
    return t


def index_jax_type():
    """Physical dtype for index-valued outputs (argmax/argmin/nonzero/
    sort indices …): ``jnp.int64`` when x64 is live, ``int32`` in degrade
    mode. Internal code must request THIS instead of ``jnp.int64`` —
    asking jax for int64 with x64 off truncates anyway and emits a per-op
    UserWarning, and silencing that globally would also swallow the
    user's own genuine-truncation warnings (ADVICE r3)."""
    return jnp.int32 if _DEGRADE_64 else jnp.int64


def wide_jax_type(kind: str):
    """Widest available accumulator dtype of the given kind ('i' or 'f'):
    64-bit when x64 is live, 32-bit in degrade mode (same rationale as
    ``index_jax_type``)."""
    if kind == "i":
        return jnp.int32 if _DEGRADE_64 else jnp.int64
    return jnp.float32 if _DEGRADE_64 else jnp.float64


def canonical_heat_type(a_type: Union[str, Type[datatype], Any]) -> Type[datatype]:
    """Canonicalize a builtin Python type, type string, numpy/jax dtype or
    heat type into the canonical heat_tpu type (reference: types.py:494).
    Applies the 64→32-bit platform degradation (see ``degrade64``).
    """
    # already a heat type
    try:
        if issubclass(a_type, datatype):
            return degrade64(a_type)
    except TypeError:
        pass

    mapped = __type_mappings.get(a_type)
    if mapped is not None:
        return degrade64(mapped)

    # numpy / jax dtype objects and their string names
    try:
        name = np.dtype(a_type).name
        mapped = __name_mappings.get(name)
        if mapped is not None:
            return degrade64(mapped)
    except TypeError:
        pass

    raise TypeError(f"data type {a_type} is not understood")


def heat_type_of(obj: Any) -> Type[datatype]:
    """Infer the canonical heat type of an arbitrary object — DNDarray,
    jax/numpy array, scalar, or (nested) iterable (reference: types.py:567).
    """
    # heat arrays / objects exposing dtype
    dtype = getattr(obj, "dtype", None)
    if dtype is not None:
        return canonical_heat_type(dtype)

    if isinstance(obj, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
        return canonical_heat_type(type(obj))

    if isinstance(obj, str):
        raise TypeError(f"data type of {obj} is not understood")

    if isinstance(obj, Iterable):
        for elem in obj:
            return heat_type_of(elem)
        raise TypeError(f"data type of empty iterable {obj} is not understood")

    raise TypeError(f"data type of {obj} is not understood")


def heat_type_is_exact(ht_dtype: Type[datatype]) -> builtins.bool:
    """True if ``ht_dtype`` is an integer type."""
    return ht_dtype in _exact


def heat_type_is_inexact(ht_dtype: Type[datatype]) -> builtins.bool:
    """True if ``ht_dtype`` is floating or complex."""
    return ht_dtype in _inexact


def heat_type_is_realfloating(ht_dtype: Type[datatype]) -> builtins.bool:
    """True if ``ht_dtype`` is a real floating type."""
    return ht_dtype in (float16, bfloat16, float32, float64)


def heat_type_is_complexfloating(ht_dtype: Type[datatype]) -> builtins.bool:
    """True if ``ht_dtype`` is complex."""
    return ht_dtype in _complexfloating


def check_complex_platform(ht_dtype: Type[datatype]) -> None:
    """Fail fast when a complex array is requested under the ``refuse``
    complex policy (the round-4 behavior; the TPU behind this environment
    dies with a raw ``UNIMPLEMENTED: TPU backend error`` at first
    transfer otherwise — VERDICT r4 #3). Under the default ``planar``
    mode on unsupporting backends this is a no-op — the creation paths
    branch to the planar representation instead
    (``core/complex_planar.py``); cpu/gpu native mode always passes and
    pays only a tuple-membership test here.

    Reference parity: complex_math.py:1-110 runs on every torch device
    class; on this platform the honest contract is the planar surface,
    or (opt-in) an actionable error at creation time rather than an
    opaque crash at use time."""
    if ht_dtype in _complexfloating:
        from . import devices as _devices

        if _devices.complex_mode() == "refuse":
            raise TypeError(
                f"{ht_dtype.__name__} arrays are refused by the complex "
                f"platform policy: the '{jax.default_backend()}' XLA "
                "backend rejects complex buffers with UNIMPLEMENTED at "
                "first materialization, and ht.use_complex(False) forces "
                "refusal instead of the planar representation. Use "
                "ht.use_complex('planar') for split real/imaginary plane "
                "execution, run the complex part of the workload on the "
                "CPU platform, or keep real and imaginary parts as "
                "separate real arrays. See docs/MIGRATING.md, 'Complex "
                "platform policy'."
            )


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """NumPy-style type-hierarchy test on heat types."""

    def _resolve(arg):
        try:
            if issubclass(arg, datatype):
                return arg
        except TypeError:
            pass
        return canonical_heat_type(arg)

    return issubclass(_resolve(arg1), _resolve(arg2))


_SAFE_EXTRA = {
    # "intuitive" additions over numpy-safe: integer → same/larger float,
    # mirroring torch/XLA semantics (reference: types.py:695 allows int32→float32)
    (int32, float32),
    (int64, float32),
    (int64, float64),
    (int32, float16),
    (int32, bfloat16),
    (int64, float16),
    (int64, bfloat16),
    (int32, complex64),
    (int64, complex64),
    (int64, complex128),
}


def can_cast(
    from_: Union[str, Type[datatype], Any],
    to: Union[str, Type[datatype], Any],
    casting: str = "intuitive",
) -> builtins.bool:
    """Whether a cast between data types can occur per the casting rule
    (reference: types.py:673). Casting rules: ``no``, ``safe``, ``same_kind``,
    ``unsafe``, ``intuitive`` (safe plus int→float of the same width).
    """
    if not isinstance(casting, str):
        raise TypeError(f"expected string, found {type(casting)}")
    if casting not in ("no", "safe", "same_kind", "unsafe", "intuitive"):
        raise ValueError(f"casting must be one of 'no', 'safe', 'same_kind', 'unsafe', 'intuitive', not {casting}")

    # scalar value-based casting
    if isinstance(from_, (builtins.int, builtins.float, builtins.complex)) and not isinstance(
        from_, builtins.bool
    ):
        to_t = canonical_heat_type(to)
        return np.can_cast(from_, np.dtype(to_t.jax_type()))

    from_t = canonical_heat_type(from_)
    to_t = canonical_heat_type(to)

    if casting == "unsafe":
        return True
    if casting == "no":
        return from_t == to_t

    f_np = np.dtype(np.float32 if from_t is bfloat16 else from_t.jax_type())
    t_np = np.dtype(np.float32 if to_t is bfloat16 else to_t.jax_type())
    if casting == "same_kind":
        return np.can_cast(f_np, t_np, casting="same_kind") or (from_t, to_t) in _SAFE_EXTRA
    # safe / intuitive
    safe = np.can_cast(f_np, t_np, casting="safe")
    if from_t is bfloat16:
        safe = to_t in (bfloat16, float32, float64, complex64, complex128)
    if casting == "safe":
        return safe
    return safe or (from_t, to_t) in _SAFE_EXTRA


def promote_types(
    type1: Union[str, Type[datatype], Any], type2: Union[str, Type[datatype], Any]
) -> Type[datatype]:
    """Smallest type to which both may be safely cast, following the
    JAX/torch lattice (int ∨ float → that float), not NumPy's value-widening
    (reference: types.py:838 uses torch.promote_types — same semantics).
    """
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1.jax_type(), t2.jax_type()))


def result_type(*arrays_and_types: Any) -> Type[datatype]:
    """Resulting type from applying the promotion lattice over all operands
    (arrays, heat types, scalars) (reference: types.py:870). Python scalars
    participate as weak types (int + float32-array stays float32); jnp's
    lattice handles bfloat16 natively (bf16 ∨ f16 → f32).
    """
    if not arrays_and_types:
        raise ValueError("at least one array or dtype is required")

    def _to_jax_operand(obj):
        dtype = getattr(obj, "dtype", None)
        if dtype is not None:
            # arrays participate with their (strong) dtype
            return np.dtype(canonical_heat_type(dtype).jax_type())
        if isinstance(obj, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
            return obj  # weak scalar
        return np.dtype(canonical_heat_type(obj).jax_type())

    return canonical_heat_type(jnp.result_type(*(_to_jax_operand(o) for o in arrays_and_types)))


def _iscomplex_local(a):
    # module-level: per-call closures would defeat the cached-jit layer
    if jnp.iscomplexobj(a):
        return jnp.imag(a) != 0
    return jnp.zeros(a.shape, dtype=jnp.bool_)


def _isreal_local(a):
    if jnp.iscomplexobj(a):
        return jnp.imag(a) == 0
    return jnp.ones(a.shape, dtype=jnp.bool_)


def iscomplex(x):
    """Elementwise test for non-zero imaginary part (reference: complex_math)."""
    from . import _operations

    return _operations.__local_op(_iscomplex_local, x, None, no_cast=True)


def isreal(x):
    """Elementwise test for zero imaginary part."""
    from . import _operations

    return _operations.__local_op(_isreal_local, x, None, no_cast=True)


class finfo:
    """Machine limits for floating point types (reference: types.py:952)."""

    def __new__(cls, dtype: Type[datatype]):
        try:
            dtype = canonical_heat_type(dtype)
        except TypeError:
            raise TypeError(f"data type {dtype} not inexact, not supported")
        if dtype not in _inexact:
            raise TypeError(f"data type {dtype} not inexact, not supported")
        return super().__new__(cls)._init(dtype)

    def _init(self, dtype):
        info = jnp.finfo(dtype.jax_type())
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        return self


class iinfo:
    """Machine limits for integer types (reference: types.py:1007)."""

    def __new__(cls, dtype: Type[datatype]):
        try:
            dtype = canonical_heat_type(dtype)
        except TypeError:
            raise TypeError(f"data type {dtype} not exact, not supported")
        if dtype not in (*_exact, bool):
            raise TypeError(f"data type {dtype} not exact, not supported")
        return super().__new__(cls)._init(dtype)

    def _init(self, dtype):
        info = jnp.iinfo(dtype.jax_type())
        self.bits = info.bits
        self.max = builtins.int(info.max)
        self.min = builtins.int(info.min)
        return self
