"""Signal processing: distributed convolution.

API parity with /root/reference/heat/core/signal.py (``convolve``). The
reference implements 1-D convolution by exchanging halos of size
``v.size//2`` between neighboring ranks (signal.py:125-127: ``get_halo`` +
``array_with_halos``) followed by a local conv1d — the canonical stencil
pattern. On TPU the sharded ``lax.conv_general_dilated`` makes XLA emit
exactly that edge exchange (a collective-permute of the boundary) itself.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import types
from .dndarray import DNDarray
from .sanitation import sanitize_in

__all__ = ["convolve"]


def convolve(a: DNDarray, v: DNDarray, mode: str = "full") -> DNDarray:
    """1-D convolution of ``a`` with kernel ``v`` (reference:
    signal.py convolve; modes full/same/valid)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("only 1-dimensional input arrays are allowed")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"unsupported mode {mode!r}, use full/same/valid")
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("mode 'same' cannot be used with even-sized kernel")
    if a.shape[0] < v.shape[0]:
        a, v = v, a

    promoted = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(promoted):
        compute = types.promote_types(promoted, types.float32)
    else:
        compute = promoted
    arr = a.larray.astype(compute.jax_type())
    ker = v.larray.astype(compute.jax_type())

    result = jnp.convolve(arr, ker, mode=mode)
    if types.heat_type_is_exact(promoted):
        result = jnp.round(result).astype(promoted.jax_type())

    split = a.split
    gshape = tuple(int(s) for s in result.shape)
    if split is not None:
        result = a.comm.shard(result, split)
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), split, a.device, a.comm
    )
