"""Signal processing: distributed convolution.

API parity with /root/reference/heat/core/signal.py (``convolve``). The
reference implements 1-D convolution by exchanging halos of size
``v.size//2`` between neighboring ranks (signal.py:125-127: ``get_halo`` +
``array_with_halos``) followed by a local conv1d — the canonical stencil
pattern. Here the same dataflow is ONE jitted ``shard_map`` program: each
shard ``ppermute``s its head to the previous neighbor (the halo exchange)
and runs a local valid-mode convolution; all three modes reduce to the
same program over a zero-extended logical input. Kernels larger than the
shard block fall back to the sharded global convolution (the reference
raises in that regime; we stay correct).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._jax_compat import shard_map

from . import types
from .dndarray import DNDarray

__all__ = ["convolve"]


@functools.lru_cache(maxsize=128)
def _conv_program(mesh: Mesh, axis_name: str, n_phys: int, k: int, jdtype: str):
    """One-shot stencil program: right-halo exchange (k-1 rows from the
    next shard via ``ppermute``) + local valid conv. Shard r produces
    outputs [r·B, (r+1)·B) of the zero-extended convolution."""
    p = mesh.devices.size

    def body(x, w):
        x = x.reshape(-1)  # (B,) local block
        w = w.reshape(-1)  # (k,) replicated
        if p > 1 and k > 1:
            head = x[: k - 1]
            halo = lax.ppermute(head, axis_name, [(i + 1, i) for i in range(p - 1)])
            ext = jnp.concatenate([x, halo])
        elif k > 1:
            ext = jnp.concatenate([x, jnp.zeros((k - 1,), dtype=x.dtype)])
        else:
            ext = x
        # TPU matmul default is bf16 accumulation — the reference computes
        # in full precision, so request it explicitly
        return jnp.convolve(ext, w, mode="valid", precision=lax.Precision.HIGHEST)

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis_name), P()), out_specs=P(axis_name))
    return jax.jit(fn)


def convolve(a: DNDarray, v: DNDarray, mode: str = "full") -> DNDarray:
    """1-D convolution of ``a`` with kernel ``v`` (reference:
    signal.py convolve; modes full/same/valid)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("only 1-dimensional input arrays are allowed")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"unsupported mode {mode!r}, use full/same/valid")
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("mode 'same' cannot be used with even-sized kernel")
    if a.shape[0] < v.shape[0]:
        a, v = v, a

    n, k = a.shape[0], v.shape[0]
    promoted = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(promoted):
        compute = types.promote_types(promoted, types.float32)
    else:
        compute = promoted
    arr = a.larray.astype(compute.jax_type())
    ker = v.larray.astype(compute.jax_type())

    # zero-extension turning every mode into sliding valid windows:
    # out[g] = sum_s a_ext[g+s] * v[k-1-s] = full[g + (k-1) - left], so
    # 'same' needs left = k - 1 - (k-1)//2 = k//2 — the operand swap above
    # can make k even even though even *kernels* were rejected pre-swap
    # (reference signal.py:195 handles the post-swap even case the same way)
    left = {"full": k - 1, "same": k // 2, "valid": 0}[mode]
    right = {"full": k - 1, "same": k - 1 - k // 2, "valid": 0}[mode]
    out_len = n + left + right - (k - 1)

    comm = a.comm
    split = a.split
    block = -(-(n + left + right) // comm.size)
    if split is not None and comm.size > 1 and k - 1 <= block:
        work = jnp.pad(arr, (left, right)) if (left or right) else arr
        phys = comm.shard(work, 0)
        prog = _conv_program(
            comm.mesh, comm.axis_name, int(phys.shape[0]), int(k),
            np.dtype(compute.jax_type()).name,
        )
        result = prog(phys, ker)[:out_len]
    else:
        result = jnp.convolve(arr, ker, mode=mode, precision=lax.Precision.HIGHEST)

    if types.heat_type_is_exact(promoted):
        result = jnp.round(result).astype(promoted.jax_type())

    gshape = (int(out_len),)
    if split is not None:
        result = comm.shard(result, 0)
    return DNDarray(
        result, gshape, types.canonical_heat_type(result.dtype), split, a.device, a.comm
    )

from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_conv_program)
