"""Elementwise comparison operations.

API parity with /root/reference/heat/core/relational.py (12 exports, all
via ``_operations.__binary_op``); results are boolean DNDarrays sharded
like the dominant operand.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "eq",
    "equal",
    "ge",
    "greater",
    "greater_equal",
    "gt",
    "le",
    "less",
    "less_equal",
    "lt",
    "ne",
    "not_equal",
]


def eq(t1, t2) -> DNDarray:
    """Elementwise ``t1 == t2`` (reference: relational.py eq)."""
    return _operations.__binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True if both arrays have the same shape and equal elements
    (reference: relational.py equal returns a scalar verdict)."""
    from .dndarray import DNDarray as _D

    if not isinstance(t1, _D) and not isinstance(t2, _D):
        raise TypeError("at least one operand must be a DNDarray")
    s1 = tuple(t1.shape) if isinstance(t1, _D) else ()
    s2 = tuple(t2.shape) if isinstance(t2, _D) else ()
    if isinstance(t1, _D) and isinstance(t2, _D) and s1 != s2:
        try:
            _ = jnp.broadcast_shapes(s1, s2)
        except ValueError:
            return False
    result = _operations.__binary_op(jnp.equal, t1, t2)
    return bool(jnp.all(result.larray))


def ge(t1, t2) -> DNDarray:
    """Elementwise ``t1 >= t2``."""
    return _operations.__binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    """Elementwise ``t1 > t2``."""
    return _operations.__binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    """Elementwise ``t1 <= t2``."""
    return _operations.__binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    """Elementwise ``t1 < t2``."""
    return _operations.__binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    """Elementwise ``t1 != t2``."""
    return _operations.__binary_op(jnp.not_equal, t1, t2)


not_equal = ne

DNDarray.__eq__ = lambda self, other: eq(self, other)
DNDarray.__ne__ = lambda self, other: ne(self, other)
DNDarray.__lt__ = lambda self, other: lt(self, other)
DNDarray.__le__ = lambda self, other: le(self, other)
DNDarray.__gt__ = lambda self, other: gt(self, other)
DNDarray.__ge__ = lambda self, other: ge(self, other)
DNDarray.__hash__ = None
