"""One memory-tier cost lattice — every byte's price, one table (ISSUE 11).

Before this module the codebase priced the SAME physical object — "how
long does moving N bytes across boundary X take, and does the operand
fit on the near side?" — five separate ways:

- VMEM lane-fill amplification (PR 5, ``kernels.relayout.lane_fill``):
  the vmem↔hbm edge, expressed as a divisor on effective bytes;
- HBM copy bytes (PR 3, the planner's ``effective_bytes`` volume term):
  the same edge at full lanes;
- ICI vs DCN wire pricing (PR 8, ``communication.ICI_BPS``/``DCN_BPS``/
  ``DCN_PENALTY``): the two cross-chip edges;
- the static peak-HBM budget (PR 10, ``analysis.memcheck``'s
  ``HEAT_TPU_HBM_BYTES``): the hbm tier's CAPACITY;
- and the out-of-core item needed a SIXTH hand-rolled price for the
  host↔hbm PCIe hop.

This module makes the lattice first-class: an ordered chain of memory
tiers (``vmem → hbm → host``) and wire edges hanging off hbm
(``ici``, ``dcn``), with ONE ``bandwidth(edge)`` / ``transfer_time(
nbytes, edge)`` / ``penalty(edge)`` pricing function and ONE
``capacity(tier)`` budget, so any placement decision — a redistribution
step, an out-of-core staging window, a pipeline hand-off, a codec
choice — costs movement the same way and proves fit the same way.
arXiv:2112.01075's portable-collective decomposition generalizes across
any bandwidth-mismatched edge pair (PR 8 proved it for ici/dcn; the
host tier lands in ``redistribution.staging`` as the first new client),
and arXiv:2112.09017's host-staged TPU linear algebra is exactly the
``pcie`` edge streamed under compute.

REFACTOR CONTRACT: the constants and arithmetic here are the SAME
numbers the former call sites used (``ICI_BPS`` 200e9, ``DCN_BPS``
25e9, ``penalty("dcn")`` = 8, ``capacity("hbm")`` =
``HEAT_TPU_HBM_BYTES`` else 16 GiB) — re-derived, not re-tuned — so
every existing golden plan, plan_id, and SL301 verdict is byte-
identical to the pre-lattice era. Pinned by tier-1 parity tests and the
ci.sh determinism diffs.

Dependency-free by design (os only): the planner, the analyzers, and
the pure-Python plan dump scripts all import it without touching jax.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from . import gates as _gates

__all__ = [
    "DCN_BPS",
    "DEFAULT_HBM_BYTES",
    "DEFAULT_HOST_BYTES",
    "DEFAULT_VMEM_BYTES",
    "DISK_BPS",
    "EDGES",
    "HBM_BPS",
    "HBM_ENV",
    "HOST_ENV",
    "ICI_BPS",
    "MEMORY_TIERS",
    "PCIE_BPS",
    "TIERS",
    "VMEM_ENV",
    "active_profile",
    "bandwidth",
    "capacity",
    "describe",
    "edge_between",
    "penalty",
    "profile_annotation",
    "profile_id",
    "reload_profile",
    "transfer_time",
]

# --------------------------------------------------------------------- #
# the lattice                                                           #
# --------------------------------------------------------------------- #
#: every tier a byte can live on or cross, nearest (fastest) first. The
#: first three are MEMORY tiers (they hold operands and have a
#: capacity); ``ici``/``dcn`` are WIRE tiers (they only carry bytes
#: between the hbm tiers of different chips/slices).
TIERS: Tuple[str, ...] = ("vmem", "hbm", "host", "ici", "dcn")

#: the tiers with a capacity — an operand RESIDES on one of these.
MEMORY_TIERS: Tuple[str, ...] = ("vmem", "hbm", "host")

#: per-chip HBM stream bandwidth (v5e ~819 GB/s) — the vmem↔hbm edge
#: every local relayout copy pays; ``kernels.relayout.lane_fill`` is
#: this edge's efficiency term (1/lane_fill = the amplification a
#: narrow-minor tiled layout costs on it).
HBM_BPS = 819e9

#: host↔HBM PCIe bandwidth (v5e: PCIe Gen3 x16, ~16 GB/s per chip) —
#: the edge the out-of-core staging executor streams
#: (``redistribution.staging``); ~51x slower than the HBM stream, which
#: is why staged schedules are PCIe-bound and must hide the transfer
#: under compute (depth-2 double buffering).
PCIE_BPS = 16e9

#: per-chip bidirectional ICI bandwidth (v5e) — the intra-slice wire
#: every earlier PR priced. ``core.communication.ICI_BPS`` re-exports
#: this value.
ICI_BPS = 200e9

#: per-chip DCN bandwidth across slices (~8x slower than ICI) —
#: ``core.communication.DCN_BPS`` re-exports this value; no DCN
#: hardware is attached to the CPU container, the constant feeds the
#: analytic-model + HLO-census methodology (PR 8).
DCN_BPS = 25e9

#: host↔persistent-store bandwidth for a DURABLE commit (ISSUE 13: the
#: checkpoint writer's edge). Deliberately the fsync-inclusive figure —
#: ~0.8 GB/s is what a single-stream persistent-disk-class store (PD /
#: network filesystem) sustains once the commit protocol (write, fsync,
#: rename) is counted; raw NVMe page-cache streaming reaches 3+ GB/s
#: but a checkpoint is only as durable as its fsync, so pricing the
#: cache-speed figure would make every recovery-time budget optimistic
#: by ~4x. The ROADMAP disk-tier item tracks the NVMe streaming figure
#: separately for non-durable staging reads (``HostArray.from_hdf5``).
DISK_BPS = 0.8e9

#: edge name -> (near tier, far tier, bytes/s). Edge names are what
#: ``Step.tier`` carries in the Schedule IR ("ici"/"dcn" since PR 8,
#: "pcie" for the staging steps of ISSUE 11; "disk" prices the
#: checkpoint commit path of ISSUE 13).
EDGES: Dict[str, Tuple[str, str, float]] = {
    "hbm": ("vmem", "hbm", HBM_BPS),
    "pcie": ("hbm", "host", PCIE_BPS),
    "ici": ("hbm", "hbm", ICI_BPS),
    "dcn": ("hbm", "hbm", DCN_BPS),
    "disk": ("host", "disk", DISK_BPS),
}

# --------------------------------------------------------------------- #
# capacities                                                            #
# --------------------------------------------------------------------- #
#: v5e per-chip VMEM (the Pallas kernels' working set).
DEFAULT_VMEM_BYTES = 128 << 20
#: v5e per-chip HBM — the SL301 budget default (PR 10) and the staging
#: slab ceiling (ISSUE 11).
DEFAULT_HBM_BYTES = 16 << 30
#: pinned-host-RAM assumption per chip when ``HEAT_TPU_HOST_BYTES`` is
#: unset: a v5e-8 host exposes ~192 GiB over 8 chips; 48 GiB per chip
#: is the conservative two-slot figure the 20 GB hsvd scenario uses.
DEFAULT_HOST_BYTES = 48 << 30

VMEM_ENV = "HEAT_TPU_VMEM_BYTES"
#: same env the memcheck SL301 budget always read — ``capacity("hbm")``
#: IS that budget now (``analysis.memcheck.hbm_budget_bytes`` delegates
#: here).
HBM_ENV = "HEAT_TPU_HBM_BYTES"
HOST_ENV = "HEAT_TPU_HOST_BYTES"

_CAPACITY: Dict[str, Tuple[str, int]] = {
    "vmem": (VMEM_ENV, DEFAULT_VMEM_BYTES),
    "hbm": (HBM_ENV, DEFAULT_HBM_BYTES),
    "host": (HOST_ENV, DEFAULT_HOST_BYTES),
}


def capacity(tier: str) -> int:
    """Per-device byte capacity of a MEMORY tier (``vmem``/``hbm``/
    ``host``), env-overridable (``HEAT_TPU_{VMEM,HBM,HOST}_BYTES``).
    ``capacity("hbm")`` is the SL301 budget (``analysis.memcheck``), the
    serving admission limit, and the staging slab ceiling — one number,
    read one way (the exact parsing semantics ``hbm_budget_bytes`` has
    always had: unparseable values fall back to the default)."""
    if tier not in _CAPACITY:
        raise ValueError(
            f"capacity: {tier!r} is not a memory tier (one of {MEMORY_TIERS}; "
            "wire tiers 'ici'/'dcn' carry bytes, they do not hold them)"
        )
    env, default = _CAPACITY[tier]
    raw = _gates.get(env, "")
    try:
        b = int(raw) if raw.strip() else default
    except ValueError:
        b = default
    return max(1, b)


# --------------------------------------------------------------------- #
# measured lattice profiles (ISSUE 16)                                  #
# --------------------------------------------------------------------- #
# ``HEAT_TPU_LATTICE_PROFILE`` names a calibration profile recorded by
# ``observability.calibration`` (probe suite or span ingestion). Unset
# (the default) short-circuits to the constants above WITHOUT importing
# the calibration module, so the dependency-free contract of this
# module — and byte-identity of every plan/plan_id — holds exactly.
# The cache keys on the raw gate value: flipping the gate mid-process
# takes effect on the next pricing call, and a repeated read of the
# same path costs one string compare.
_profile_cache: Tuple[Optional[str], Optional[dict]] = (None, None)


def active_profile() -> Optional[dict]:
    """The loaded lattice-profile envelope named by
    ``HEAT_TPU_LATTICE_PROFILE``, or ``None`` when the gate is unset or
    the file is missing/tampered/version-mismatched (the loader evicts
    and falls back — a bad profile is NEVER an error, it is the
    constants)."""
    global _profile_cache
    raw = _gates.get("HEAT_TPU_LATTICE_PROFILE", "") or ""
    cached_raw, cached_profile = _profile_cache
    if raw == cached_raw:
        return cached_profile
    if not raw.strip():
        _profile_cache = (raw, None)
        return None
    from ..observability import calibration as _calibration

    profile = _calibration.load_profile(raw.strip())
    _profile_cache = (raw, profile)
    return profile


def reload_profile() -> Optional[dict]:
    """Drop the one-entry profile cache and re-resolve the gate — the
    in-process recalibration hook (``calibrate`` re-saving to the SAME
    path would otherwise keep serving the old prices until the process
    restarts; the cache is keyed on the gate's raw value, not the file
    content). Returns what :func:`active_profile` now sees."""
    global _profile_cache
    _profile_cache = (None, None)
    return active_profile()


def profile_id() -> Optional[str]:
    """The active profile's stamped id (sha256 prefix of its canonical
    measurement content), or ``None`` under the constants — the token
    the planner folds into plan canonical serialization so a
    recalibration is a visible plan_id invalidation."""
    profile = active_profile()
    return profile["profile_id"] if profile else None


def profile_annotation() -> Optional[dict]:
    """The ``calibration`` annotation a plan priced under the active
    profile must carry (``{"profile_id", "edges": {edge -> bytes/s}}``
    — the FULL resolved price map, measured edges and constant
    fallbacks alike, so ``verify_plan`` can recompute every derived
    number from the recorded prices alone), or ``None`` under the
    constants — the conditional-key contract of the Schedule IR."""
    pid = profile_id()
    if pid is None:
        return None
    return {
        "profile_id": pid,
        "edges": {e: bandwidth(e) for e in sorted(EDGES)},
    }


# --------------------------------------------------------------------- #
# edge pricing                                                          #
# --------------------------------------------------------------------- #
def bandwidth(edge: str) -> float:  # shardlint: ignore[SL402] -- no program cache here: the profile dict IS the gate-resolved value, re-resolved on every call
    """Bytes/s of a lattice edge (``hbm``/``pcie``/``ici``/``dcn``/
    ``disk``) — the measured per-edge price when a lattice profile is
    active (``HEAT_TPU_LATTICE_PROFILE``), the hard-coded constant
    otherwise."""
    if edge not in EDGES:
        raise ValueError(f"bandwidth: unknown lattice edge {edge!r} (one of {tuple(EDGES)})")
    profile = active_profile()
    if profile is not None:
        rec = profile["edges"].get(edge)
        if rec is not None and rec.get("bps"):
            return float(rec["bps"])
    return EDGES[edge][2]


def transfer_time(nbytes: int, edge: str) -> float:
    """Seconds to move ``nbytes`` across ``edge`` at the lattice
    bandwidth — THE pricing function every analytic model routes
    through (``planner.tier_time_model``, the staging window model, the
    ``*_hostram`` bench rows)."""
    return max(int(nbytes), 0) / bandwidth(edge)


def sparse_transfer_time(nnz: int, itemsize: int, edge: str) -> float:
    """Seconds to move a sparse operand of ``nnz`` stored elements
    across ``edge``: each element ships its value (``itemsize`` bytes)
    plus its int32 column index, the CSR/BCSR wire mass that actually
    crosses a lattice edge (the indptr/brick-row metadata is O(rows)
    and amortizes to nothing at any nnz worth pricing). The nnz-weighted
    twin of :func:`transfer_time` the planner and memcheck use when a
    DCSR/DBCSR operand crosses an edge — pricing the DENSE shape
    instead would overstate a 1%%-occupancy operand by 100x and break
    serving admission."""
    return transfer_time(max(int(nnz), 0) * (int(itemsize) + 4), edge)


def penalty(edge: str) -> int:
    """Integer cost-model penalty of one ``edge`` byte relative to one
    ICI byte (= ``ICI_BPS / bandwidth(edge)``, floored, min 1) — the
    multiplier that lets the planner's byte-equivalent cost scalar keep
    ONE unit across tiers. ``penalty("dcn")`` == the former
    ``communication.DCN_PENALTY`` == 8 exactly; ``penalty("pcie")`` ==
    12 prices a staging window's wire in the same scalar. Under a
    lattice profile BOTH sides of the ratio are measured (the numerator
    is ``bandwidth("ici")``, not the constant), so the scalar keeps
    meaning "one edge byte in ici bytes" on calibrated meshes too —
    identical to the constant arithmetic when no profile is active."""
    return max(1, int(bandwidth("ici") / bandwidth(edge)))


def edge_between(a: str, b: str) -> Optional[str]:
    """The lattice edge joining two adjacent memory tiers (``vmem``/
    ``hbm`` -> ``"hbm"``, ``hbm``/``host`` -> ``"pcie"``), or ``None``
    when the tiers are not adjacent — a placement engine walks the
    chain edge by edge (a host->vmem move is pcie THEN hbm; pricing the
    hops separately is what makes the staging schedule's depth-2
    overlap model composable)."""
    pair = {a, b}
    for name, (near, far, _) in EDGES.items():
        if near != far and {near, far} == pair:
            return name
    return None


def describe() -> str:  # shardlint: ignore[SL402] -- renders a report; nothing cached under a key
    """Human-readable lattice table: tiers, capacities, edges,
    bandwidths, penalties — what ``ht.core.tiers`` looks like to a
    placement decision."""
    pid = profile_id()
    head = "memory-tier lattice (vmem -> hbm -> host; ici/dcn off hbm"
    head += f"; profile {pid}):" if pid else "; constants):"
    lines = [head]
    for tier in MEMORY_TIERS:
        env, _ = _CAPACITY[tier]
        lines.append(f"  {tier:>5}: capacity {capacity(tier)} B  ({env})")
    for name, (near, far, default_bps) in EDGES.items():
        bps = bandwidth(name)
        mark = "" if bps == default_bps else f"  [measured; constant {default_bps / 1e9:.1f}]"
        lines.append(
            f"  edge {name:>4}: {near}<->{far}  {bps / 1e9:.1f} GB/s  "
            f"(penalty {penalty(name)}x vs ici){mark}"
        )
    return "\n".join(lines)
