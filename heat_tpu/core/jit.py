"""Fused-program surface: ``ht.jit``.

The reference framework is eager: every ``heat.*`` call runs its own
kernels (torch eager + MPI). This repo's eager path is already compiled
per op, but a CHAIN of public ops still pays one XLA program dispatch per
op — measured at ~3.4x the cost of the equivalent single fused program
for a 6-op elementwise chain (bench.py ``op_chain``). The reference has
no answer to this; on TPU the answer is the same one JAX gives:
trace the whole user function into ONE XLA program.

``ht.jit(fn)`` wraps a function of DNDarrays (any pytree of DNDarrays,
jax arrays and static Python values) so that every ``heat_tpu`` op inside
it is traced — metadata propagation (gshape/split/dtype) runs once at
trace time, the array math fuses into a single program, and XLA inserts
the collectives implied by the shardings. Works because every public op
routes device math through ``jnp``/``lax`` on the physical array and
keeps host control flow metadata-only.

Limitations (clear errors, not wrong answers):

- Ops whose OUTPUT SHAPE depends on data (``unique``, ``nonzero``,
  boolean-mask indexing) cannot be traced — they need a host read of
  counts. Calling them under ``ht.jit`` raises jax's concretization
  error, re-raised with a pointer here. Use them eagerly, outside.
- DNDarrays closed over (not passed as arguments) are baked into the
  program as constants; pass arrays as arguments. The wrapper WARNS at
  first trace when the function's closure cells hold a DNDarray — the
  constant pins its buffer in HBM for the cache entry's lifetime and
  ignores later updates to the Python variable.
- Non-array hashable arguments (Python ints/floats/bools/strings) are
  STATIC: part of the program cache key, baked into the trace — unlike
  ``jax.jit``, which traces scalars as weak-typed arrays. A
  per-call-varying scalar (a learning rate, a threshold) therefore
  retraces and recompiles on every new value and grows the wrapper's
  cache without bound; pass such scalars as 0-d jax/numpy arrays
  (``jnp.float32(lr)``) to trace them instead.
- The traced function must be functional on its DNDarray arguments:
  in-place ``x[i] = v`` on an ARGUMENT mutates the Python wrapper at
  trace time only, it does not feed back to the caller's array.
"""

from __future__ import annotations

import functools
import re
import time
import warnings

import numpy as np

import jax

from typing import Any, Callable, Dict, List, Optional

from .dndarray import DNDarray
from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry

# __all__ stays ["jit"]: the executable_* introspection helpers below
# are the analyzer's module-level readers (heat_tpu.analysis.memcheck),
# not part of the star-exported array API surface.
__all__ = ["jit"]


# ---------------------------------------------------------------------- #
# serving AOT hooks (ISSUE 9)                                            #
# ---------------------------------------------------------------------- #
# ``heat_tpu.serving.aot_cache`` installs an object here when the
# persistent AOT program cache is enabled (HEAT_TPU_SERVING_AOT /
# HEAT_TPU_SERVING_CACHE). The wrapper consults it on an ht-level cache
# MISS: ``load(...)`` may return a ready ``(callable, out_box)`` entry
# rebuilt from a serialized ``jax.export`` artifact (cold start becomes
# load-not-compile), and after a fresh first dispatch ``store(...)``
# persists the newly compiled program. With the hooks uninstalled (the
# default, and the HEAT_TPU_SERVING_AOT=0 escape hatch) every code path
# below is byte-identical to the pre-serving wrapper.
_AOT_HOOKS = None


def install_aot_hooks(hooks) -> None:
    """Install (or with ``None`` uninstall) the serving AOT cache hooks.
    ``hooks`` must provide ``load(fn, treedef, specs, donate_user,
    donate_positions, jit_kwargs)`` returning an entry or ``None``, and
    ``store(fn, treedef, specs, donate_user, donate_positions,
    jit_kwargs, jitted, traced_in, out_box)`` (both must never raise)."""
    global _AOT_HOOKS
    _AOT_HOOKS = hooks


def aot_hooks():
    """The installed serving AOT hooks object, or ``None``."""
    return _AOT_HOOKS


# every live ht.jit wrapper, so the elastic runtime's eviction sweep
# (heat_tpu.resilience.elastic.invalidate_caches) can drop program
# entries compiled against a world that no longer exists. Entries are
# keyed on comm IDENTITY (_DndSpec), so a re-resolved world can never
# HIT a stale entry — the sweep reclaims the memory.
import weakref

_LIVE_WRAPPERS: "weakref.WeakSet" = weakref.WeakSet()


def clear_wrapper_caches() -> int:
    """Drop every live ``ht.jit`` wrapper's program cache; returns the
    total number of evicted entries."""
    n = 0
    for w in list(_LIVE_WRAPPERS):
        cache = getattr(w, "_ht_jit_cache", None)
        if cache:
            n += len(cache)
            cache.clear()
    return n


def _is_leaf(x) -> bool:
    return isinstance(x, DNDarray)


# ---------------------------------------------------------------------- #
# executable introspection (ISSUE 10)                                    #
# ---------------------------------------------------------------------- #
# The analyzer's memory pass (heat_tpu.analysis.memcheck) needs two
# facts only the COMPILED executable knows: did XLA actually honor the
# declared donations (input_output_alias), and what does the compiler's
# own buffer assignment say the program needs (memory_analysis). Both
# readers live here, next to the donation bookkeeping they audit.

# "{0}: (2, {}, may-alias)" entries inside the module header's
# input_output_alias={...} block
_ALIAS_ENTRY = re.compile(
    r"\{\s*([0-9,\s]*)\}\s*:\s*\(\s*(\d+)\s*,\s*\{([0-9,\s]*)\}\s*,\s*([a-z-]+)\s*\)"
)


def executable_input_output_aliases(compiled_or_text) -> List[Dict[str, Any]]:
    """Parsed ``input_output_alias`` map of a compiled module: one
    ``{"output_index", "param_number", "param_index", "kind"}`` entry
    per aliased buffer, empty when the executable aliases nothing —
    which is exactly how XLA reports a donation it could not use
    ("donation silently dropped", rule SL302). ``param_number`` indexes
    the module's flat parameters, i.e. the traced leaf positions
    ``ht.jit``'s donation mapping produces."""
    text = (
        compiled_or_text
        if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text()
    )
    start = text.find("input_output_alias={")
    if start < 0:
        return []
    i = start + len("input_output_alias=")
    depth = 0
    end = i
    for k in range(i, len(text)):
        if text[k] == "{":
            depth += 1
        elif text[k] == "}":
            depth -= 1
            if depth == 0:
                end = k + 1
                break
    out = []
    for m in _ALIAS_ENTRY.finditer(text[i:end]):
        out.append(
            {
                "output_index": tuple(
                    int(v) for v in m.group(1).split(",") if v.strip()
                ),
                "param_number": int(m.group(2)),
                "param_index": tuple(
                    int(v) for v in m.group(3).split(",") if v.strip()
                ),
                "kind": m.group(4),
            }
        )
    return out


def executable_memory_stats(compiled) -> Optional[Dict[str, int]]:
    """The compiler's own per-device buffer assignment of a compiled
    executable (``Compiled.memory_analysis()``), normalized to plain
    ints: argument/output/temp/alias bytes. ``None`` when the backend
    does not report it — callers treat the stats as a cross-check, never
    a requirement."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
    }
    out: Dict[str, int] = {}
    for key, attr in fields.items():
        v = getattr(ma, attr, None)
        if v is None:
            return None
        out[key] = int(v)
    # what the buffer assignment says one device needs live at once:
    # arguments + outputs + transients, minus the aliased reuse
    out["peak_bytes"] = max(
        0,
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"],
    )
    return out


class _DndSpec:
    """Hashable trace signature of a DNDarray argument: everything the
    metadata path can branch on must be part of the program cache key."""

    __slots__ = ("gshape", "dtype", "split", "device", "comm")

    def __init__(self, d: DNDarray):
        self.gshape = d.shape
        self.dtype = d.dtype
        self.split = d.split
        self.device = d.device
        self.comm = d.comm

    def _key(self):
        return (self.gshape, self.dtype, self.split, str(self.device), id(self.comm))

    def __eq__(self, other):
        return isinstance(other, _DndSpec) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def rebuild(self, phys) -> DNDarray:
        return DNDarray(phys, self.gshape, self.dtype, self.split, self.device, self.comm)

    @classmethod
    def from_meta(cls, gshape, dtype, split, device, comm) -> "_DndSpec":
        """Rebuild a spec from stored metadata (serving AOT cache: output
        specs are persisted structurally — gshape/dtype/split — and get
        their device/comm from the loading process's input arrays)."""
        spec = cls.__new__(cls)
        spec.gshape = tuple(gshape)
        spec.dtype = dtype
        spec.split = split
        spec.device = device
        spec.comm = comm
        return spec


def _leaf_spec(leaf):
    """(kind, spec) — kind decides traced-vs-static; spec keys the cache."""
    if isinstance(leaf, DNDarray):
        return ("dnd", _DndSpec(leaf))
    if isinstance(leaf, jax.Array):
        # weak_type participates in jax.jit's own retrace key; omitting it
        # here would let two jax-level traces share one ht-level cache entry
        return ("jax", (leaf.shape, str(leaf.dtype), bool(leaf.aval.weak_type)))
    if isinstance(leaf, np.ndarray):
        return ("np", (leaf.shape, str(leaf.dtype)))
    # everything else is static: part of the cache key, baked into the trace
    try:
        hash(leaf)
    except TypeError:
        raise TypeError(
            f"ht.jit argument of type {type(leaf).__name__} is neither an array "
            "nor hashable — pass arrays (DNDarray/jax/numpy) or hashable statics"
        ) from None
    return ("static", leaf)


def _holds_dndarray(v) -> bool:
    """True when ``v`` is, or is a container (pytree) holding, a
    DNDarray — either way tracing bakes the buffer in as a constant."""
    try:
        leaves = jax.tree.leaves(v, is_leaf=_is_leaf)
    except Exception:
        return False
    return any(isinstance(leaf, DNDarray) for leaf in leaves)


def _warn_closure_captures(fn) -> None:
    """Warn when ``fn`` captures DNDarrays — via closure cells or global
    loads, directly or inside containers: they bake into the compiled
    program as constants, pinning their HBM buffers for the cache
    entry's lifetime and ignoring later rebinds of the Python variable
    (VERDICT r4 #7). Runs at each new-signature trace (compile-time
    cost, never per dispatch)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return
    captured = []
    for name, cell in zip(code.co_freevars, fn.__closure__ or ()):
        try:
            v = cell.cell_contents
        except ValueError:  # empty cell
            continue
        if _holds_dndarray(v):
            captured.append(name)
    # actual global LOADS only — co_names also lists attribute accesses,
    # which would false-positive on e.g. `x.T` shadowing a global `T`
    import dis

    g = getattr(fn, "__globals__", {})
    global_loads = {
        ins.argval
        for ins in dis.get_instructions(code)
        if ins.opname in ("LOAD_GLOBAL", "LOAD_NAME")
    }
    for name in sorted(global_loads):
        if name in g and _holds_dndarray(g[name]):
            captured.append(name)
    # default argument values bake in exactly the same way when the
    # caller omits them (they never reach the leaf flattening)
    for v in (fn.__defaults__ or ()):
        if _holds_dndarray(v):
            captured.append("<default argument>")
    for name, v in (fn.__kwdefaults__ or {}).items():
        if _holds_dndarray(v):
            captured.append(f"<default of {name!r}>")
    for name in captured:
        warnings.warn(
            f"ht.jit: {fn.__name__!r} closes over DNDarray {name!r} — it "
            "will be baked into the compiled program as a CONSTANT, "
            "pinning its device buffer for the cache entry's lifetime "
            "and ignoring later updates to the variable. Pass it as an "
            "argument instead.",
            stacklevel=4,
        )


def jit(fn: Optional[Callable] = None, **jit_kwargs) -> Callable:
    """Trace ``fn`` (a function over DNDarrays) into one fused XLA program.

    Usable as ``ht.jit(fn)`` or ``@ht.jit``. Additional keyword arguments
    are forwarded to ``jax.jit``.

    ``donate_argnums`` uses USER-VISIBLE positional argument indices (like
    ``jax.jit``): the wrapper maps each donated argument to the flattened
    physical leaves it contributes and donates exactly those buffers, so
    large pipelines can reuse their input HBM. Donated DNDarrays are
    invalidated by the call (same contract as jax). ``donate_argnames``
    and donating keyword arguments are not supported.

    Examples
    --------
    >>> @ht.jit
    ... def gram_norms(x):
    ...     g = ht.matmul(x, ht.transpose(x))
    ...     return ht.sqrt(ht.sum(g * g, axis=1))
    >>> y = gram_norms(a)       # one compiled program, one dispatch
    """
    if fn is None:
        return lambda f: jit(f, **jit_kwargs)
    if "donate_argnames" in jit_kwargs:
        raise TypeError(
            "ht.jit supports donate_argnums (positional) only, not donate_argnames"
        )
    donate_user = jit_kwargs.pop("donate_argnums", ())
    if isinstance(donate_user, int):
        donate_user = (donate_user,)
    donate_user = tuple(int(i) for i in donate_user)

    cache: dict = {}

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_leaf)
        specs = tuple(_leaf_spec(leaf) for leaf in leaves)
        key = (treedef, specs)

        entry = cache.get(key)
        is_new_entry = entry is None
        from_aot = False
        donate_positions = ()
        if entry is None:
            if donate_user:
                # map USER positional args to the flattened traced-leaf
                # positions they contribute (statics carry no buffer and
                # are skipped) — this is the alignment the r4 limitation
                # note said was missing
                if any(u < 0 or u >= len(args) for u in donate_user):
                    raise ValueError(
                        f"donate_argnums {donate_user} out of range for "
                        f"{len(args)} positional arguments"
                    )
                spans, off = [], 0
                for a in args:
                    n = len(jax.tree.flatten(a, is_leaf=_is_leaf)[0])
                    spans.append(range(off, off + n))
                    off += n
                traced_pos, t = {}, 0
                for i, (kind, _) in enumerate(specs):
                    if kind != "static":
                        traced_pos[i] = t
                        t += 1
                donate_positions = tuple(
                    traced_pos[i]
                    for u in donate_user
                    for i in spans[u]
                    if i in traced_pos
                )
            aot = _AOT_HOOKS
            if aot is not None:
                entry = aot.load(fn, treedef, specs, donate_user, donate_positions, jit_kwargs)
                from_aot = entry is not None
                if from_aot:
                    cache[key] = entry
        if entry is None:
            out_box = []

            def inner(*traced):
                # NOTE: closes over `specs` (metadata) only — never over
                # `leaves`, which would pin the first call's device buffers
                # in HBM for the lifetime of the cache entry
                it = iter(traced)
                rebuilt = []
                for kind, spec in specs:
                    if kind == "dnd":
                        rebuilt.append(spec.rebuild(next(it)))
                    elif kind in ("jax", "np"):
                        rebuilt.append(next(it))
                    else:
                        rebuilt.append(spec)
                a, kw = jax.tree.unflatten(treedef, rebuilt)
                try:
                    res = fn(*a, **kw)
                except (
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.TracerIntegerConversionError,
                ) as e:
                    raise TypeError(
                        "ht.jit: an op inside the traced function needs the array's "
                        "VALUES on the host (data-dependent output shape — unique/"
                        "nonzero/boolean-mask indexing — or a float()/int() read). "
                        "Run that op eagerly, outside ht.jit. Original: " + str(e)
                    ) from None
                out_leaves, out_treedef = jax.tree.flatten(res, is_leaf=_is_leaf)
                phys_out, out_meta = [], []
                for o in out_leaves:
                    if isinstance(o, DNDarray):
                        out_meta.append(_DndSpec(o))
                        phys_out.append(o._phys)
                    else:
                        out_meta.append(None)
                        phys_out.append(o)
                out_box.append((out_treedef, out_meta))
                return tuple(phys_out)

            if donate_user:
                jitted_inner = jax.jit(
                    inner, donate_argnums=donate_positions, **jit_kwargs
                )
                if _telemetry._ENABLED:
                    # donation decision: how many traced buffers actually
                    # get donated for this signature (statics drop out)
                    _telemetry.inc("ht.jit.donated_buffers", len(donate_positions))
                    _obs_events.emit(
                        "ht.jit.donation", fn=getattr(fn, "__name__", "<fn>"),
                        requested_args=len(donate_user),
                        donated_buffers=len(donate_positions),
                    )
            else:
                jitted_inner = jax.jit(inner, **jit_kwargs)
            _warn_closure_captures(fn)
            entry = (jitted_inner, out_box)
            cache[key] = entry

        jitted, out_box = entry
        traced_in = [
            leaf._phys if isinstance(leaf, DNDarray) else leaf
            for leaf, (kind, _) in zip(leaves, specs)
            if kind != "static"
        ]
        if _telemetry._ENABLED:
            _telemetry.inc("ht.jit.cache.miss" if is_new_entry else "ht.jit.cache.hit")
            if is_new_entry:
                # first dispatch of a new signature = trace + XLA compile
                # (+ one execution); later hits pay only program dispatch.
                # An AOT-loaded entry never traces the user function —
                # the census stays honest: ht.jit.compile counts FULL
                # trace+compiles only, a served cold start records under
                # serving.aot.first_dispatch instead
                t0 = time.perf_counter()
                phys_out = jitted(*traced_in)
                dt = time.perf_counter() - t0
                if from_aot:
                    _telemetry.observe("serving.aot.first_dispatch", dt)
                    _obs_events.emit(
                        "serving.aot.dispatch", fn=getattr(fn, "__name__", "<fn>"),
                        leaves=len(leaves), seconds=round(dt, 6),
                    )
                else:
                    _telemetry.observe("ht.jit.compile", dt)
                    _obs_events.emit(
                        "ht.jit.trace", fn=getattr(fn, "__name__", "<fn>"),
                        leaves=len(leaves), seconds=round(dt, 6),
                    )
            else:
                phys_out = jitted(*traced_in)
        else:
            phys_out = jitted(*traced_in)
        if is_new_entry and not from_aot and _AOT_HOOKS is not None:
            # persist the freshly compiled program (serving AOT cache):
            # runs AFTER the first dispatch so the hooks can read concrete
            # input avals/shardings off ``traced_in``; must never raise
            _AOT_HOOKS.store(
                fn, treedef, specs, donate_user, donate_positions,
                jit_kwargs, jitted, traced_in, out_box,
            )
        if not out_box:
            # cache hit on a program jax.jit compiled earlier but whose
            # out-metadata box was lost — cannot happen (box fills on first
            # trace, same entry), guarded for safety
            raise RuntimeError("ht.jit internal: missing output metadata")
        # [-1]: if jax.jit retraced under this same ht-level key (its own
        # key is finer), the LAST trace's metadata describes this call
        out_treedef, out_meta = out_box[-1]
        rebuilt_out = [
            m.rebuild(p) if m is not None else p for m, p in zip(out_meta, phys_out)
        ]
        return jax.tree.unflatten(out_treedef, rebuilt_out)

    wrapper._ht_jit_cache = cache  # introspection/testing hook
    # donation bookkeeping for ht.analysis.check (rule SL105): which
    # user-visible positional args this wrapper donates at dispatch
    wrapper._ht_jit_donate_argnums = donate_user

    def _numcheck(*args, **kwargs):
        """Precision-flow analysis (analyzer pass 6) of the program this
        wrapper compiles for the given example arguments — compile-only
        introspection, nothing dispatches and no cache entry is made.
        ``wrapped.numcheck(x)`` == ``ht.analysis.numcheck(fn, x)`` on
        the undecorated function, so the SL604 source scan sees the
        user's code, not the wrapper."""
        from ..analysis.numcheck import numcheck as _nc

        return _nc(fn, *args, **kwargs)

    wrapper.numcheck = _numcheck
    _LIVE_WRAPPERS.add(wrapper)
    return wrapper
