"""Array creation functions.

API parity with /root/reference/heat/core/factories.py (``arange`` at
factories.py:41, ``array`` at :149, ``empty``/``eye``/``full``/``linspace``/
``logspace``/``meshgrid``/``ones``/``zeros`` and ``*_like`` variants,
``from_partitioned``/``from_partition_dict`` at :821/:866). The reference's
``__factory`` (factories.py:697) allocates only the rank-local chunk; here
creation happens as a (cached) jit with ``out_shardings`` so each device
materializes only its own shard — no host round-trip, no full-array
allocation on any single device.
"""

from __future__ import annotations

import functools
import numpy as np

import jax
import jax.numpy as jnp

from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple, Type, Union

from . import types
from .communication import Communication, MeshCommunication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_partitioned",
    "from_partition_dict",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


# --------------------------------------------------------------------- #
# sharded creation machinery                                            #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=512)
def _cached_creator(mesh, axis_name: str, op_key: str, shape, jdtype, split, args):
    """jit-compiled creator with sharded output; each device materializes
    only its own (possibly padded) shard — the analog of the reference
    ``__factory``'s local-chunk allocation (factories.py:697). Keyed on the
    (hashable) Mesh itself so cache entries die with their mesh."""
    from . import _padding
    from jax.sharding import NamedSharding, PartitionSpec

    size = mesh.devices.size
    if split is not None and shape[split] == 0:
        # zero-extent split axis: store replicated (see MeshCommunication.shard)
        split = None
    if split is None or not shape:
        spec = PartitionSpec()
    else:
        spec = PartitionSpec(*(axis_name if i == split else None for i in range(len(shape))))
    sharding = NamedSharding(mesh, spec)

    # NOTE: sequence builders must stay symbolic (lax.iota). jnp.arange/
    # linspace/eye with static args evaluate eagerly even under trace and
    # the resulting array is embedded into the HLO as a full constant —
    # a 100M-element ht.arange then ships a 400 MB compile request.
    def _iota_1d(n):
        wide = types.wide_jax_type('i' if jnp.issubdtype(jnp.dtype(jdtype), jnp.integer) else 'f')
        return jax.lax.iota(wide, n)

    def build():
        if op_key == "zeros":
            logical = jnp.zeros(shape, dtype=jdtype)
        elif op_key == "ones":
            logical = jnp.ones(shape, dtype=jdtype)
        elif op_key == "empty":
            logical = jnp.empty(shape, dtype=jdtype)
        elif op_key == "full":
            logical = jnp.full(shape, args[0], dtype=jdtype)
        elif op_key == "arange":
            start, stop, step = args
            logical = (_iota_1d(shape[0]) * step + start).astype(jdtype)
        elif op_key == "linspace":
            start, stop, num, endpoint = args
            div = (num - 1) if endpoint else num
            delta = (stop - start) / div if div > 0 else 0.0
            logical = jax.lax.iota(types.wide_jax_type('f'), num) * delta + start
            if endpoint and num > 1:
                # pin the final sample to stop exactly (np.linspace semantics;
                # iota*delta accumulates one rounding step at the endpoint)
                logical = logical.at[-1].set(stop)
            logical = logical.astype(jdtype)
        elif op_key == "eye":
            rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)
            logical = (rows == cols).astype(jdtype)
        else:
            raise ValueError(op_key)
        return _padding.pad_logical(logical, split, size)

    # build() has NO committed array inputs (the PRNG key is uncommitted),
    # so out_shardings is what pins placement — it must stay even on a
    # 1-device mesh (a .cpu() comm or Split sub-communicator is not the
    # default device); creation dispatch is not a hot path
    return jax.jit(build, out_shardings=sharding)


def _create(op_key: str, shape, dtype, split, device, comm, args=()) -> DNDarray:
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    shape = sanitize_shape(shape)
    split = sanitize_axis(shape, split)
    dtype = types.canonical_heat_type(dtype)
    # must precede the creator: a complex buffer merely ENQUEUED on an
    # unsupporting backend poisons the process at the next sync
    if types.heat_type_is_complexfloating(types.degrade64(dtype)):
        from . import complex_planar as _cp

        if _cp.active():
            return _cp.create(op_key, shape, split, device, comm, args)
        types.check_complex_platform(types.degrade64(dtype))
    creator = _cached_creator(
        comm.mesh,
        comm.axis_name,
        op_key,
        tuple(shape),
        np.dtype(dtype.jax_type()).name,
        split,
        tuple(args),
    )
    data = creator()
    return DNDarray(data, tuple(shape), dtype, split, device, comm)


# --------------------------------------------------------------------- #
# public factories                                                      #
# --------------------------------------------------------------------- #
def arange(
    *args,
    dtype: Optional[Type[types.datatype]] = None,
    split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """Evenly spaced values in [start, stop) (reference: factories.py:41).
    Integer inputs default to int32, floats to float32 — the canonical
    heat types.
    """
    num_args = len(args)
    if num_args == 0 or num_args > 3:
        raise TypeError(f"function takes 1 to 3 positional arguments, got {num_args}")
    start, stop, step = 0, args[0], 1
    if num_args >= 2:
        start, stop = args[0], args[1]
    if num_args == 3:
        step = args[2]

    all_ints = all(isinstance(a, (int, np.integer)) for a in (start, stop, step))
    if dtype is None:
        dtype = types.int32 if all_ints else types.float32
    dtype = types.canonical_heat_type(dtype)

    num = int(np.ceil((stop - start) / step)) if step != 0 else 0
    if step == 0:
        raise ValueError("step must not be zero")
    num = max(0, num)

    return _create("arange", (num,), dtype, split, device, comm, args=(start, stop, step))


def array(
    obj: Any,
    dtype: Optional[Type[types.datatype]] = None,
    copy: Optional[bool] = None,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """Create a DNDarray from array-like data (reference: factories.py:149).

    ``split=`` shards existing global data; ``is_split=`` declares the data
    to be the process-local shard of a pre-distributed array (reference
    factories.py:409-456 stitches shards via neighbor handshakes +
    Allreduce). Under a single controller both produce the same global
    array; in multi-process mode ``is_split`` assembles per-host shards via
    ``jax.make_array_from_process_local_data``.
    """
    if order not in ("C", "F"):
        raise ValueError(f"invalid order {order}")
    if split is not None and is_split is not None:
        raise ValueError(f"split and is_split are mutually exclusive, got split={split}, is_split={is_split}")

    device = sanitize_device(device)
    comm = sanitize_comm(comm)

    # extract data from existing containers
    if isinstance(obj, DNDarray):
        if split is None and is_split is None:
            split = obj.split
        if obj._is_planar:
            # planar complex input: host round-trip (compat path; the
            # planar factory re-shards the planes)
            from . import complex_planar as _cp

            obj = _cp.host_complex(obj)
        else:
            obj = obj.larray
    if isinstance(obj, (types.datatype,)):
        raise TypeError("cannot create array from a heat type")

    # infer heat dtype before numpy widens python scalars to 64-bit
    if dtype is None:
        try:
            dtype = types.heat_type_of(obj)
        except TypeError:
            dtype = None
    else:
        dtype = types.canonical_heat_type(dtype)
    if dtype is not None and types.heat_type_is_complexfloating(types.degrade64(dtype)):
        # before ANY jax op: transfers are async, so an unsupported
        # complex buffer merely enqueued here would poison the process
        # at the next sync instead of raising the policy error. Under the
        # planar policy the whole creation routes to plane form.
        from . import complex_planar as _cp

        if _cp.active():
            return _cp.array_factory(obj, split, is_split, ndmin, order, device, comm)
        types.check_complex_platform(types.degrade64(dtype))

    if isinstance(obj, jax.Array):
        data = obj
        if dtype is not None and data.dtype != dtype.jax_type():
            data = data.astype(dtype.jax_type())
    else:
        try:
            np_dtype = None if dtype is None else np.dtype(dtype.jax_type())
        except TypeError:
            np_dtype = None
        np_data = np.asarray(obj, dtype=np_dtype, order=order)
        if dtype is None:
            dtype = types.canonical_heat_type(np_data.dtype)
            if types.heat_type_is_complexfloating(types.degrade64(dtype)):
                from . import complex_planar as _cp

                if _cp.active():
                    return _cp.array_factory(np_data, split, is_split, ndmin, order, device, comm)
                types.check_complex_platform(types.degrade64(dtype))
            np_data = np_data.astype(np.dtype(dtype.jax_type()), copy=False)
        data = jnp.asarray(np_data)

    if dtype is None:
        dtype = types.canonical_heat_type(data.dtype)
        if types.heat_type_is_complexfloating(types.degrade64(dtype)):
            from . import complex_planar as _cp

            if _cp.active():
                return _cp.array_factory(data, split, is_split, ndmin, order, device, comm)
        types.check_complex_platform(types.degrade64(dtype))

    # pad dimensions (numpy semantics: prepend)
    if data.ndim < ndmin:
        data = data.reshape((1,) * (ndmin - data.ndim) + tuple(data.shape))

    if is_split is not None:
        if jax.process_count() > 1:
            sharding = comm.sharding(data.ndim, is_split)
            data = jax.make_array_from_process_local_data(sharding, np.asarray(data))
            gshape = tuple(int(s) for s in data.shape)
            return DNDarray(data, gshape, dtype, is_split, device, comm)
        split = sanitize_axis(data.shape, is_split)

    split = sanitize_axis(data.shape, split)
    gshape = tuple(int(s) for s in data.shape)
    data = comm.shard(data, split)
    return DNDarray(data, gshape, dtype, split, device, comm)


def asarray(
    obj: Any,
    dtype: Optional[Type[types.datatype]] = None,
    copy: Optional[bool] = None,
    order: str = "C",
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
) -> DNDarray:
    """Convert to DNDarray without copying when possible
    (reference: factories.py:461)."""
    if isinstance(obj, DNDarray) and copy is not True:
        if dtype is None or obj.dtype == types.canonical_heat_type(dtype):
            return obj
    return array(obj, dtype=dtype, copy=copy, is_split=is_split, device=device)


def empty(
    shape,
    dtype=types.float32,
    split=None,
    device=None,
    comm=None,
    order: str = "C",
) -> DNDarray:
    """Uninitialized array (reference: factories.py:520)."""
    return _create("empty", shape, dtype, split, device, comm)


def empty_like(a: DNDarray, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, empty, dtype, split, device, comm)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order: str = "C") -> DNDarray:
    """2-D array with ones on the diagonal (reference: factories.py:618).
    ``order`` is accepted for API parity; XLA owns physical layout (see
    memory.sanitize_memory_layout)."""
    if order not in ("C", "F"):
        raise ValueError(f"order must be 'C' or 'F', got {order!r}")
    if isinstance(shape, (int, np.integer)):
        gshape = (int(shape), int(shape))
    else:
        shape = tuple(shape)
        if len(shape) == 1:
            gshape = (int(shape[0]), int(shape[0]))
        else:
            gshape = (int(shape[0]), int(shape[1]))
    return _create("eye", gshape, dtype, split, device, comm)


def __factory_like(a, factory: Callable, dtype, split, device, comm, **kwargs) -> DNDarray:
    """Create an array matching ``a``'s metadata (reference: factories.py:751)."""
    shape = tuple(a.shape) if hasattr(a, "shape") else tuple(np.shape(a))
    if dtype is None:
        try:
            dtype = types.heat_type_of(a)
        except TypeError:
            dtype = types.float32
    if split is None:
        split = getattr(a, "split", None)
    if device is None:
        device = getattr(a, "device", None)
    if comm is None:
        comm = getattr(a, "comm", None)
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Array filled with ``fill_value`` (reference: factories.py:971)."""
    if dtype is None:
        dtype = types.heat_type_of(fill_value)
    dtype = types.canonical_heat_type(dtype)
    fv = fill_value
    if isinstance(fv, (bool, int, float, complex)):
        arg = fv
    else:
        arg = np.asarray(fv).item()
    return _create("full", shape, dtype, split, device, comm, args=(arg,))


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    shape = tuple(a.shape)
    if dtype is None:
        dtype = a.dtype if isinstance(a, DNDarray) else types.heat_type_of(a)
    if split is None:
        split = getattr(a, "split", None)
    return full(
        shape,
        fill_value,
        dtype=dtype,
        split=split,
        device=device if device is not None else getattr(a, "device", None),
        comm=comm if comm is not None else getattr(a, "comm", None),
    )


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """``num`` evenly spaced samples over [start, stop] (reference:
    factories.py:1078)."""
    num = int(num)
    if num <= 0:
        raise ValueError(f"number of samples expected to be positive, got {num}")
    if dtype is None:
        dtype = types.float32
    result = _create(
        "linspace", (num,), dtype, split, device, comm, args=(float(start), float(stop), num, endpoint)
    )
    if retstep:
        if num == 1:
            step = float("nan")
        else:
            div = (num - 1) if endpoint else num
            step = (float(stop) - float(start)) / div
        return result, step
    return result


def logspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    base: float = 10.0,
    dtype=None,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Samples on a log scale (reference: factories.py:1162)."""
    from . import arithmetics

    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    from .dndarray import DNDarray as _D

    powered = jnp.power(base, y.larray)
    result = _D(
        y.comm.shard(powered, y.split),
        y.shape,
        y.dtype,
        y.split,
        y.device,
        y.comm,
    )
    if dtype is not None:
        return result.astype(types.canonical_heat_type(dtype))
    return result


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from coordinate vectors (reference:
    factories.py:1225)."""
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    if not arrays:
        return []
    arrs = [asarray(a) for a in arrays]
    split_idx = next((i for i, a in enumerate(arrs) if a.split is not None), None)
    outs = jnp.meshgrid(*[a.larray for a in arrs], indexing=indexing)
    device = arrs[0].device
    comm = arrs[0].comm
    results = []
    # which output dim each input maps to (xy swaps the first two)
    for i, o in enumerate(outs):
        out_split = None
        if split_idx is not None and len(arrs) > 0:
            dim = split_idx
            if indexing == "xy" and len(arrs) >= 2:
                dim = 1 if split_idx == 0 else 0 if split_idx == 1 else split_idx
            out_split = dim
            o = comm.shard(o, out_split)
        results.append(
            DNDarray(o, tuple(int(s) for s in o.shape), types.canonical_heat_type(o.dtype), out_split, device, comm)
        )
    return results


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Array of ones (reference: factories.py:1308)."""
    return _create("ones", shape, dtype, split, device, comm)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, ones, dtype, split, device, comm)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Array of zeros (reference: factories.py:1405)."""
    return _create("zeros", shape, dtype, split, device, comm)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, zeros, dtype, split, device, comm)


def from_partitioned(x, comm: Optional[Communication] = None) -> DNDarray:
    """Build a DNDarray from an object exposing the ``__partitioned__``
    protocol (reference: factories.py:821)."""
    parted = getattr(x, "__partitioned__", None)
    if parted is None:
        raise AttributeError("object does not expose __partitioned__")
    if callable(parted):
        parted = parted()
    return from_partition_dict(parted, comm)


def from_partition_dict(parted: dict, comm: Optional[Communication] = None) -> DNDarray:
    """Build a DNDarray from a partition dict (reference: factories.py:866)."""
    comm = sanitize_comm(comm)
    gshape = tuple(int(s) for s in parted["shape"])
    tiling = tuple(int(t) for t in parted["partition_tiling"])
    nonunit = [i for i, t in enumerate(tiling) if t > 1]
    if len(nonunit) > 1:
        raise RuntimeError(f"only one split axis supported, found tiling {tiling}")
    split = nonunit[0] if nonunit else None
    getter = parted.get("get", lambda v: v)

    out = np.empty(gshape, dtype=None)
    parts = parted["partitions"]
    sample = None
    for key, part in sorted(parts.items()):
        data = getter(part["data"])
        if data is None:
            raise RuntimeError(f"partition {key} has no data")
        data = np.asarray(data)
        if sample is None:
            sample = data
            out = np.empty(gshape, dtype=data.dtype)
        start = tuple(int(s) for s in part["start"])
        sl = tuple(slice(st, st + sh) for st, sh in zip(start, data.shape))
        out[sl] = data
    return array(out, split=split, comm=comm)

from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_cached_creator)
