"""Explicit SPMD primitives: halo exchange and ring pipelines.

The reference realizes its stencil and ring-pipeline patterns with
hand-rolled MPI point-to-point schedules:

- halo exchange — ``DNDarray.get_halo`` (reference dndarray.py:386-454)
  Isend/Irecvs boundary slices between prev/next populated ranks; consumed
  by ``signal.convolve`` (signal.py:125-127) and ``statistics.percentile``
  (statistics.py:1615);
- ring pipeline — ``spatial.distance._dist`` (reference distance.py:208-477)
  keeps a stationary block per rank and circulates a moving block rank→rank
  for ``(size+1)//2`` iterations, exploiting symmetry when X ≡ Y. This is
  exactly the ring-attention schedule.

Here both are ONE jitted ``shard_map`` program each, built on
``lax.ppermute`` over the mesh axis — the TPU-native form where the
neighbor exchange rides ICI and XLA overlaps it with local compute. These
primitives operate on *physical* (padded) arrays; callers own the
logical/pad bookkeeping (see ``_padding``).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._jax_compat import pcast, shard_map

from typing import Callable, Optional, Tuple

from . import types
from ..kernels.sort import block_sort as _local_block_sort, _mode as _sort_kernel_mode

__all__ = ["halo_exchange", "ring_pairwise", "distributed_sort", "distributed_topk"]


# ---------------------------------------------------------------------- #
# distributed top-k                                                      #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _topk_program(mesh: Mesh, axis_name: str, ndim: int, split: int, k: int, largest: bool, idx_dtype: str):
    """shard_map top-k along the sharded axis: each shard reduces its
    block to its local k candidates (with GLOBAL positions), the tiny
    (p·k) candidate set is all-gathered over ICI, and the final top-k
    runs replicated — the reference's iterative rank-merge
    (manipulations.py:3981) without moving anything but candidates."""
    p = mesh.devices.size
    spec = P(*(axis_name if i == split else None for i in range(ndim)))
    out_spec = P(*(None for _ in range(ndim)))
    idt = jnp.dtype(idx_dtype)

    def body(x):
        r = lax.axis_index(axis_name)
        moved = jnp.moveaxis(x, split, -1)
        B = moved.shape[-1]
        kk = min(k, B)
        work = moved if largest else -moved
        lv, li = lax.top_k(work, kk)
        gi = li.astype(idt) + r.astype(idt) * jnp.asarray(B, idt)
        # candidate sets are tiny: gather them everywhere
        cv = lax.all_gather(lv, axis_name, axis=0)   # (p, ..., kk)
        ci = lax.all_gather(gi, axis_name, axis=0)
        cv = jnp.moveaxis(cv, 0, -2).reshape(moved.shape[:-1] + (p * kk,))
        ci = jnp.moveaxis(ci, 0, -2).reshape(moved.shape[:-1] + (p * kk,))
        fv, fsel = lax.top_k(cv, k)
        fi = jnp.take_along_axis(ci, fsel, axis=-1)
        if not largest:
            fv = -fv
        return jnp.moveaxis(fv, -1, split), jnp.moveaxis(fi, -1, split)

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=(out_spec, out_spec), check_vma=False)
    return jax.jit(fn)


def distributed_topk(
    phys: jax.Array,
    mesh: Mesh,
    axis_name: str,
    split: int,
    k: int,
    largest: bool = True,
):
    """Gather-free top-k along the sharded axis of a physical array.
    Caller pre-fills pad rows with the appropriate sentinel (∓inf /
    type-min/max). Returns replicated (values, global positions)."""
    idx_dtype = "int32" if phys.shape[split] < 2**31 else "int64"
    prog = _topk_program(mesh, axis_name, phys.ndim, split, int(k), bool(largest), idx_dtype)
    return prog(phys)


# ---------------------------------------------------------------------- #
# halo exchange                                                          #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=256)
def _halo_program(mesh: Mesh, axis_name: str, ndim: int, split: int, halo_prev: int, halo_next: int):
    """shard_map program attaching prev/next halos to every shard along
    ``split``. Boundary shards receive zero halos (``ppermute`` zero-fills
    pairs with no source — the analog of the reference's "no neighbor"
    case)."""
    p = mesh.devices.size
    spec = P(*(axis_name if i == split else None for i in range(ndim)))

    def body(x):
        parts = []
        if halo_prev > 0:
            # each shard's trailing rows travel to its next neighbor, i.e.
            # shard r receives the tail of shard r-1 as its prev-halo
            tail = lax.slice_in_dim(x, x.shape[split] - halo_prev, x.shape[split], axis=split)
            parts.append(lax.ppermute(tail, axis_name, [(i, i + 1) for i in range(p - 1)]))
        parts.append(x)
        if halo_next > 0:
            head = lax.slice_in_dim(x, 0, halo_next, axis=split)
            parts.append(lax.ppermute(head, axis_name, [(i + 1, i) for i in range(p - 1)]))
        return jnp.concatenate(parts, axis=split) if len(parts) > 1 else parts[0]

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(fn)


def halo_exchange(
    phys: jax.Array,
    mesh: Mesh,
    axis_name: str,
    split: int,
    halo_prev: int,
    halo_next: int,
) -> jax.Array:
    """Attach halos of ``halo_prev``/``halo_next`` rows along ``split`` to
    every shard of the physical array ``phys`` (block size B → B+hp+hn).

    Returns a physical array sharded the same way whose per-device block is
    ``[prev-halo | local block | next-halo]``; outermost halos are zero.
    The halo sizes must not exceed the block size (the reference raises the
    same way when ``halo_size`` exceeds the smallest chunk,
    dndarray.py:386-454).
    """
    p = mesh.devices.size
    block = phys.shape[split] // p
    if max(halo_prev, halo_next) > block:
        raise ValueError(
            f"halo size ({halo_prev}/{halo_next}) exceeds the shard block size ({block})"
        )
    if halo_prev == 0 and halo_next == 0:
        return phys
    return _halo_program(mesh, axis_name, phys.ndim, split, int(halo_prev), int(halo_next))(phys)


# ---------------------------------------------------------------------- #
# ring pipeline                                                          #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _ring_program(
    mesh: Mesh,
    axis_name: str,
    metric_key: str,
    x_shape: Tuple[int, ...],
    y_shape: Tuple[int, ...],
    jdtype: str,
    steps: int,
):
    """shard_map ring: stationary local X block, moving Y block circulated
    ``steps`` times with ``ppermute`` (reference distance.py:262-359). The
    result column block written at step t is the one Y block originated at
    device (r + t) mod p."""
    p = mesh.devices.size
    metric = _METRICS[metric_key]
    by = y_shape[0] // p

    def body(x_loc, y_loc):
        r = lax.axis_index(axis_name)
        # the scan carry is updated with device-varying blocks each step, so
        # its initial value must be marked varying over the mesh axis
        out = pcast(jnp.zeros((x_loc.shape[0], p * by), dtype=jdtype), axis_name, to="varying")

        def step(carry, t):
            y_cur, acc = carry
            blk = metric(x_loc, y_cur).astype(jdtype)  # (bx, by) — MXU matmul inside
            src = (r + t) % p
            acc = lax.dynamic_update_slice(acc, blk, (0, src * by))
            # rotate: device i receives the block currently on device i+1
            y_nxt = lax.ppermute(y_cur, axis_name, [((i + 1) % p, i) for i in range(p)])
            return (y_nxt, acc), None

        (_, out), _ = lax.scan(step, (y_loc, out), jnp.arange(steps))
        return out

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None)),
        out_specs=P(axis_name, None),
    )
    return jax.jit(fn)


def _euclidean(x, y):
    # quadratic-expansion form: the inner product rides the MXU
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1, keepdims=True).T
    return jnp.sqrt(jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0))

def _sqeuclidean(x, y):
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1, keepdims=True).T
    return jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0)

def _euclidean_direct(x, y):
    d = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(d * d, axis=-1))

def _sqeuclidean_direct(x, y):
    d = x[:, None, :] - y[None, :, :]
    return jnp.sum(d * d, axis=-1)

def _manhattan(x, y):
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


_METRICS = {
    "euclidean": _euclidean,
    "sqeuclidean": _sqeuclidean,
    "euclidean_direct": _euclidean_direct,
    "sqeuclidean_direct": _sqeuclidean_direct,
    "manhattan": _manhattan,
}


# ---------------------------------------------------------------------- #
# distributed sort                                                       #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _oddeven_sort_values_program(mesh: Mesh, axis_name: str, ndim: int, split: int, sort_impl: str = "0"):
    """Values-only variant of the odd-even sort: no index operand rides the
    ``ppermute``s, halving per-round collective volume (the hot
    percentile/median path needs only sorted values). Tie consistency
    between partners comes from concatenating in GLOBAL RANK ORDER on both
    sides (lower-ranked partner's block first) + a stable sort — both
    partners then order the identical sequence identically."""
    p = mesh.devices.size
    spec = P(*(axis_name if i == split else None for i in range(ndim)))

    def body(v):
        r = lax.axis_index(axis_name)
        B = v.shape[split]
        (v,) = _local_block_sort((v,), dimension=split, num_keys=1, is_stable=True, impl=sort_impl)
        for t in range(p):
            start = t % 2
            pairs = [(a, a + 1) for a in range(start, p - 1, 2)]
            if not pairs:
                continue
            perm = [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs]
            pv = lax.ppermute(v, axis_name, perm)
            last = pairs[-1][1]
            in_pair = (r >= start) & (r <= last)
            is_low = in_pair & (((r - start) % 2) == 0)
            a_blk = jnp.where(is_low, v, pv)
            b_blk = jnp.where(is_low, pv, v)
            (mv,) = _local_block_sort(
                (jnp.concatenate([a_blk, b_blk], axis=split),),
                dimension=split,
                num_keys=1,
                is_stable=True,
                impl=sort_impl,
            )
            lo = lax.slice_in_dim(mv, 0, B, axis=split)
            hi = lax.slice_in_dim(mv, B, 2 * B, axis=split)
            v = jnp.where(in_pair, jnp.where(is_low, lo, hi), v)
        return v

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _oddeven_sort_program(mesh: Mesh, axis_name: str, ndim: int, split: int, idx_dtype: str, sort_impl: str = "0"):
    """shard_map odd-even block merge-split sort along ``split``.

    The reference's distributed sort (manipulations.py:2428) is a
    sample-sort: local sort, splitter election, Alltoallv partition
    exchange. Alltoallv's variable counts are the wrong shape for XLA —
    bucket sizes are data-dependent. The TPU-native formulation is the
    odd-even block merge-split network (Baudet–Stevenson): after one local
    sort, ``p`` rounds of a STATIC neighbor pattern where paired shards
    exchange blocks over ICI (``ppermute``), jointly sort the 2B rows, and
    keep the low/high half. Every shape is static, every round compiles to
    one collective-permute + one fused local sort, and the network is
    provably sorted after ``p`` rounds for any input.

    Ties are broken by the global position index carried as a second sort
    key, so both partners compute the *same* total order of their union —
    without this, equal keys could be duplicated or dropped at the block
    boundary (the two partners concatenate in different orders).

    Returns (values, indices): indices are the pre-sort global positions
    along ``split`` (argsort semantics). Other dims are batch lanes.
    """
    p = mesh.devices.size
    spec = P(*(axis_name if i == split else None for i in range(ndim)))
    idt = jnp.dtype(idx_dtype)

    def body(v):
        r = lax.axis_index(axis_name)
        B = v.shape[split]
        # global position of every local row along the split axis
        i = lax.broadcasted_iota(idt, v.shape, split) + r.astype(idt) * jnp.asarray(B, idt)
        v, i = _local_block_sort((v, i), dimension=split, num_keys=2, is_stable=False, impl=sort_impl)
        for t in range(p):
            start = t % 2
            pairs = [(a, a + 1) for a in range(start, p - 1, 2)]
            if not pairs:
                continue
            perm = [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs]
            pv = lax.ppermute(v, axis_name, perm)
            pi = lax.ppermute(i, axis_name, perm)
            mv, mi = _local_block_sort(
                (jnp.concatenate([v, pv], axis=split), jnp.concatenate([i, pi], axis=split)),
                dimension=split,
                num_keys=2,
                is_stable=False,
                impl=sort_impl,
            )
            lo_v = lax.slice_in_dim(mv, 0, B, axis=split)
            hi_v = lax.slice_in_dim(mv, B, 2 * B, axis=split)
            lo_i = lax.slice_in_dim(mi, 0, B, axis=split)
            hi_i = lax.slice_in_dim(mi, B, 2 * B, axis=split)
            last = pairs[-1][1]
            in_pair = (r >= start) & (r <= last)
            is_low = in_pair & (((r - start) % 2) == 0)
            v = jnp.where(in_pair, jnp.where(is_low, lo_v, hi_v), v)
            i = jnp.where(in_pair, jnp.where(is_low, lo_i, hi_i), i)
        return v, i

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=(spec, spec))
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _columnsort_program(mesh: Mesh, axis_name: str, ndim: int, split: int, idx_dtype: Optional[str], sort_impl: str = "0"):
    """Leighton columnsort along ``split``: the O(1)-collective-round
    distributed sort (VERDICT r4 #2 — replaces the O(p)-round odd-even
    schedule at scale).

    The reference's sample sort (manipulations.py:2428) does local sort →
    splitter election → ONE Alltoallv. Alltoallv's variable counts are
    data-dependent shapes XLA cannot compile, and sample-sort bucket sizes
    are adversarially unbounded (sorted input sends a whole shard to one
    bucket). Columnsort keeps the one-shot-exchange structure with fully
    STATIC shapes and a determinism guarantee no splitter scheme has:

      1. sort each shard                     (local)
      2. "deal" rows round-robin to shards   (one tiled ``all_to_all``)
      3. sort each shard                     (local)
      4. inverse deal                        (one tiled ``all_to_all``)
      5. sort each shard                     (local)
      6-8. boundary cleanup: each shard jointly sorts the half-shard
           windows it shares with its ring neighbors (two half-shard
           ``ppermute``s + two local sorts, replacing the shift/unshift
           columns of the textbook form; ring ends keep their already-
           sorted halves, so no ±inf fill columns are materialized)

    Total: 2 all-to-alls + 2 half-shard permutes ≈ 3 shard-volumes of ICI
    bytes and 4 collective rounds, independent of p — vs the odd-even
    network's p rounds × p shard-volumes. Provably sorted for ANY input
    when B ≥ 2(p-1)² and p | B (Leighton '85); ``distributed_sort`` gates
    on exactly that and keeps odd-even as the small-shard fallback.

    Ties: the global pre-sort position rides as a second lexicographic
    sort key (``num_keys=2``), making every element distinct — the same
    total order the odd-even program uses, and the argsort contract.
    """
    p = mesh.devices.size
    spec = P(*(axis_name if i == split else None for i in range(ndim)))
    idt = jnp.dtype(idx_dtype) if idx_dtype is not None else None
    nk = 2 if idt is not None else 1

    def body(v):
        rk = lax.axis_index(axis_name)
        a = jnp.moveaxis(v, split, 0)
        B = a.shape[0]
        arrs = [a]
        if idt is not None:
            gi = lax.broadcasted_iota(idt, a.shape, 0) + rk.astype(idt) * jnp.asarray(B, idt)
            arrs.append(gi)

        def srt(ts):
            return list(_local_block_sort(tuple(ts), dimension=0, num_keys=nk, is_stable=True, impl=sort_impl))

        def deal(ts):
            out = []
            for t in ts:
                m = t.reshape((B // p, p) + t.shape[1:])
                m = jnp.moveaxis(m, 1, 0).reshape((B,) + t.shape[1:])
                out.append(lax.all_to_all(m, axis_name, 0, 0, tiled=True))
            return out

        def undeal(ts):
            out = []
            for t in ts:
                y = lax.all_to_all(t, axis_name, 0, 0, tiled=True)
                y = y.reshape((p, B // p) + t.shape[1:])
                out.append(jnp.moveaxis(y, 0, 1).reshape((B,) + t.shape[1:]))
            return out

        arrs = srt(arrs)                    # 1: local sort
        arrs = srt(deal(arrs))              # 2-3: deal + sort
        arrs = srt(undeal(arrs))            # 4-5: undeal + sort
        # 6-8: each shard owns final rows [r·B, (r+1)·B); the half-shard
        # window shared with each neighbor is jointly re-sorted on both
        # sides (identical input → identical order, no send-back hop)
        h = B // 2
        fwd = [(i, i + 1) for i in range(p - 1)]
        bwd = [(i + 1, i) for i in range(p - 1)]
        tops = [lax.slice_in_dim(t, 0, B - h, axis=0) for t in arrs]
        bots = [lax.slice_in_dim(t, B - h, B, axis=0) for t in arrs]
        recv_prev = [lax.ppermute(t, axis_name, fwd) for t in bots]
        recv_next = [lax.ppermute(t, axis_name, bwd) for t in tops]
        sc_own = srt([jnp.concatenate([rp, tp], axis=0) for rp, tp in zip(recv_prev, tops)])
        sc_next = srt([jnp.concatenate([bt, rn], axis=0) for bt, rn in zip(bots, recv_next)])
        first, last = rk == 0, rk == p - 1
        new = []
        for top, bot, so, sn in zip(tops, bots, sc_own, sc_next):
            # ring ends: ppermute zero-fills the missing neighbor, so keep
            # the already-sorted boundary halves verbatim instead
            up = jnp.where(first, top, lax.slice_in_dim(so, h, B, axis=0))
            dn = jnp.where(last, bot, lax.slice_in_dim(sn, 0, h, axis=0))
            new.append(jnp.concatenate([up, dn], axis=0))
        res = tuple(jnp.moveaxis(t, 0, split) for t in new)
        return res[0] if idt is None else res

    out_specs = spec if idt is None else (spec, spec)
    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=out_specs)
    return jax.jit(fn)


def _columnsort_applicable(p: int, B: int) -> bool:
    """Leighton's validity bound (B ≥ 2(p-1)², p | B) plus profitability:
    at p ≤ 2 the odd-even network is already ≤ 2 rounds."""
    return p > 2 and B % p == 0 and B >= 2 * (p - 1) ** 2


def distributed_sort(
    phys: jax.Array,
    mesh: Mesh,
    axis_name: str,
    split: int,
    with_indices: bool = True,
):
    """Ascending sort of the physical array ``phys`` along its sharded
    axis ``split`` without gathering — the explicit-SPMD replacement for
    the reference's sample-sort + Alltoallv (manipulations.py:2428).

    Large shards (B ≥ 2(p-1)², p | B) take the columnsort program — the
    one-shot-exchange structure of the reference's sample sort with O(1)
    collective rounds and ~3 shard-volumes of ICI bytes, but fully static
    shapes; anything smaller falls back to the odd-even block merge-split
    network (p rounds, provably sorted at any shape).

    The caller owns pad semantics: pad rows must already hold a
    maximal sentinel (NaN for floats, type-max for ints) so they sink to
    the global tail — the canonical pad location. Returns physical
    (values, indices), indices being pre-sort global positions (pads get
    positions ≥ the logical extent, so callers can re-zero them); with
    ``with_indices=False``, returns only values via a program whose
    collectives carry half the volume.
    """
    p = mesh.devices.size
    B = -(-phys.shape[split] // p)  # physical rows per shard
    if _columnsort_applicable(p, B):
        idx_dtype = None if not with_indices else (
            "int32" if phys.shape[split] < 2**31 else "int64"
        )
        prog = _columnsort_program(
            mesh, axis_name, phys.ndim, split, idx_dtype, _sort_kernel_mode()
        )
        return prog(phys)
    if not with_indices:
        return _oddeven_sort_values_program(
            mesh, axis_name, phys.ndim, split, _sort_kernel_mode()
        )(phys)
    idx_dtype = "int32" if phys.shape[split] < 2**31 else "int64"
    prog = _oddeven_sort_program(
        mesh, axis_name, phys.ndim, split, idx_dtype, _sort_kernel_mode()
    )
    return prog(phys)


def ring_pairwise(
    x_phys: jax.Array,
    y_phys: jax.Array,
    mesh: Mesh,
    axis_name: str,
    metric: str = "euclidean",
    symmetric: bool = False,
) -> jax.Array:
    """All-pairs ``metric`` between row blocks of ``x_phys`` and
    ``y_phys`` (both physical, split along axis 0) via an explicit
    ``ppermute`` ring. Output is physical, split along axis 0, with the
    column extent equal to ``y_phys``'s padded row extent.

    ``symmetric=True`` (valid only for X ≡ Y with a symmetric metric) runs
    ``p//2 + 1`` ring steps instead of ``p`` and fills the uncomputed
    blocks from the transpose — the reference's symmetry-skipping of half
    the ring (distance.py:300-359). The transposed fill is a logical-level
    ``where`` whose cross-shard movement XLA lowers to an all-to-all.
    """
    if metric not in _METRICS:
        raise ValueError(f"unknown metric {metric!r}; options: {sorted(_METRICS)}")
    p = mesh.devices.size
    steps = (p // 2 + 1) if (symmetric and p > 1) else p
    prog = _ring_program(
        mesh,
        axis_name,
        metric,
        tuple(x_phys.shape),
        tuple(y_phys.shape),
        np.dtype(jnp.result_type(x_phys.dtype, y_phys.dtype)).name,
        steps,
    )
    out = prog(x_phys, y_phys)
    if steps < p:
        # block (r, c) was computed iff (c - r) mod p < steps; the rest is
        # D[c, r].T by symmetry
        bx = x_phys.shape[0] // p
        by = y_phys.shape[0] // p
        row_blk = jax.lax.broadcasted_iota(jnp.int32, out.shape, 0) // bx
        col_blk = jax.lax.broadcasted_iota(jnp.int32, out.shape, 1) // by
        computed = ((col_blk - row_blk) % p) < steps
        out = jnp.where(computed, out, out.T)
    return out

# ---------------------------------------------------------------------- #
# distributed stream compaction (bool-mask select / nonzero / unique)    #
# ---------------------------------------------------------------------- #
# The reference serves data-dependent-shape ops with rank-local results
# (nonzero: indexing.py local nonzero + split-offset; unique:
# manipulations.py:3202 local unique + allgather of the small sets; mask
# getitem: dndarray.py:827 rank-local selection). Uneven rank-local
# shapes don't exist under GSPMD's even-block invariant, so the TPU-native
# schedule is: (1) a per-shard count+compact program (static shapes,
# candidates padded to the shard extent), (2) ONE tiny host read of the
# per-shard counts — the same world-sync the reference's Allgather of
# local sizes performs, (3) a balanced-redistribution program that
# all-gathers only the C = max-count candidate PREFIXES (bounded by the
# output size, never the input) and assembles even split=0 blocks. No
# full all-gather of the operand ever appears in the HLO.


# per-device budget for the balanced gather's (p, cap, ...) intermediate;
# beyond it the gather runs in bounded rounds (tests shrink this to force
# the chunked path on small inputs)
_GATHER_BUDGET_BYTES = 64 << 20


def _host_counts(counts: jax.Array) -> np.ndarray:
    """Read the tiny per-shard count vector to the host — the one world
    sync these schedules need (the analog of the reference's size
    Allgather). Cross-process worlds cannot ``device_get`` a globally
    sharded array; the allgather of a (p,) int vector is negligible."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(counts, tiled=True))
    return np.asarray(jax.device_get(counts))


@functools.lru_cache(maxsize=64)
def _mask_compact_program(
    mesh: Mesh, axis_name: str, blk_shape, rows: bool, jdtype: str
):
    """Per-shard count + fixed-capacity compaction. ``blk_shape`` is the
    local block; ``rows=True`` selects axis-0 rows by a 1-D mask block,
    else flattened elements by a same-shape mask block. The mask must
    already be False in pad slots. Outputs: candidates padded to the
    block extent (selected entries front-packed, garbage beyond the
    count) and the per-shard count."""
    L = blk_shape[0] if rows else int(np.prod(blk_shape))
    spec_x = P(*(axis_name if i == 0 else None for i in range(len(blk_shape))))
    spec_m = P(axis_name) if rows else spec_x
    out_trailing = blk_shape[1:] if rows else ()
    spec_c = P(*((axis_name,) + (None,) * len(out_trailing)))

    def body(x_blk, m_blk):
        if rows:
            flat_m = m_blk
            data = x_blk
        else:
            flat_m = m_blk.reshape(-1)
            data = x_blk.reshape(-1)
        c = jnp.sum(flat_m.astype(jnp.int32))
        idx = jnp.nonzero(flat_m, size=L, fill_value=L)[0]
        pad_row = jnp.zeros((1,) + data.shape[1:], dtype=data.dtype)
        cand = jnp.concatenate([data, pad_row])[idx]
        return cand, c.reshape(1)

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec_x, spec_m),
        out_specs=(spec_c, P(axis_name)), check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _balanced_gather_program(
    mesh: Mesh, axis_name: str, cand_blk_shape, cap: int, b_out: int, jdtype: str,
    chunk: int,
):
    """Assemble even split=0 blocks of the compacted stream: all-gather
    the first ``cap`` candidates of every shard (cap = max per-shard
    count ≤ output size) plus the count vector, compute exclusive
    prefixes, and let each output shard take its ``b_out`` rows. The
    total count arrives as a RUNTIME scalar — only (cap, b_out, chunk)
    shape the program, so the p distinct totals per block size share one
    compilation.

    ``chunk=0`` gathers all ``cap`` candidate rows at once — peak
    per-device memory (p, cap, ...), fine for sparse selections. For
    DENSE selections (cap approaching the local block extent) that
    buffer is ~the whole operand replicated per device, so
    ``_compact_gather`` switches to ``chunk>0``: the gather runs in
    ``ceil(cap/chunk)`` rounds of (p, chunk, ...) — same total ICI
    bytes, bounded live memory."""
    trailing = cand_blk_shape[1:]
    spec_c = P(*((axis_name,) + (None,) * len(trailing)))

    def prefix_index(cnt_blk):
        counts = lax.all_gather(cnt_blk, axis_name).reshape(-1)   # (p,)
        cum = jnp.cumsum(counts)
        r = lax.axis_index(axis_name)
        g = r * b_out + jax.lax.broadcasted_iota(jnp.int32, (b_out,), 0)
        q = jnp.searchsorted(cum, g, side="right").astype(jnp.int32)
        qc = jnp.minimum(q, counts.shape[0] - 1)
        li = g - (cum[qc] - counts[qc])
        return g, qc, li

    if chunk <= 0 or chunk >= cap:
        def body(cand_blk, cnt_blk, n_total):
            g, qc, li = prefix_index(cnt_blk)
            allc = lax.all_gather(cand_blk[:cap], axis_name)      # (p, cap, ...)
            flat = allc.reshape((-1,) + trailing)
            rows_out = flat[jnp.clip(qc * cap + li, 0, flat.shape[0] - 1)]
            keep = (g < n_total).reshape((-1,) + (1,) * len(trailing))
            return jnp.where(keep, rows_out, jnp.zeros_like(rows_out))
    else:
        rounds = -(-cap // chunk)

        def body(cand_blk, cnt_blk, n_total):
            g, qc, li = prefix_index(cnt_blk)
            padded = cand_blk[:cap]
            if rounds * chunk > cap:
                pad = jnp.zeros((rounds * chunk - cap,) + trailing, dtype=padded.dtype)
                padded = jnp.concatenate([padded, pad])
            out0 = jnp.zeros((b_out,) + trailing, dtype=cand_blk.dtype)

            def round_body(i, out):
                c0 = i * chunk
                blkc = lax.dynamic_slice_in_dim(padded, c0, chunk, axis=0)
                allc = lax.all_gather(blkc, axis_name)            # (p, chunk, ...)
                flat = allc.reshape((-1,) + trailing)
                lin = li - c0
                sel = (lin >= 0) & (lin < chunk)
                rows = flat[
                    jnp.clip(qc * chunk + jnp.clip(lin, 0, chunk - 1), 0, flat.shape[0] - 1)
                ]
                selb = sel.reshape((-1,) + (1,) * len(trailing))
                return jnp.where(selb, rows, out)

            out = lax.fori_loop(0, rounds, round_body, out0)
            keep = (g < n_total).reshape((-1,) + (1,) * len(trailing))
            return jnp.where(keep, out, jnp.zeros_like(out))

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec_c, P(axis_name), P()), out_specs=spec_c,
        check_vma=False,
    )
    return jax.jit(fn)


def _compact_gather(cand, counts, mesh, axis_name, empty_trailing):
    """Shared postlude of the compaction schedules: read the tiny count
    vector (the one host sync), size the capacity/output block, and run
    the balanced gather. Returns ``(result_phys, n_total)``."""
    p = mesh.devices.size
    counts_host = _host_counts(counts)
    n_total = int(counts_host.sum())
    if n_total == 0:
        return jnp.zeros((0,) + tuple(empty_trailing), dtype=cand.dtype), 0
    cap = int(counts_host.max())
    b_out = -(-n_total // p)
    # bound the gathered intermediate: one-shot all-gather is (p, cap, ...)
    # per device — for dense selections that is ~the whole operand
    # replicated. Above the budget, run the gather in rounds of
    # (p, chunk, ...) instead (same ICI bytes, bounded live memory).
    row_bytes = max(int(np.prod(cand.shape[1:])), 1) * cand.dtype.itemsize
    chunk = 0
    if p * cap * row_bytes > _GATHER_BUDGET_BYTES:
        chunk = max(_GATHER_BUDGET_BYTES // (p * row_bytes), 1)
    gather = _balanced_gather_program(
        mesh, axis_name,
        tuple(s // p if i == 0 else s for i, s in enumerate(cand.shape)),
        cap, b_out, np.dtype(cand.dtype).name, chunk,
    )
    return gather(cand, counts, jnp.int32(n_total)), n_total


def compact_select(
    data_phys: jax.Array,
    mask_phys: jax.Array,
    mesh: Mesh,
    axis_name: str,
    rows: bool,
):
    """Gather-free selection of masked elements (or axis-0 rows) from a
    split=0 physical array into an even split=0 physical result.

    Returns ``(result_phys, n_selected)`` — the count read-back is the
    one small host sync (the analog of the reference's size Allgather).
    """
    p = mesh.devices.size
    prog = _mask_compact_program(
        mesh, axis_name,
        tuple(s // p if i == 0 else s for i, s in enumerate(data_phys.shape)),
        rows, np.dtype(data_phys.dtype).name,
    )
    cand, counts = prog(data_phys, mask_phys)
    return _compact_gather(
        cand, counts, mesh, axis_name,
        tuple(data_phys.shape[1:]) if rows else (),
    )


@functools.lru_cache(maxsize=64)
def _nonzero_compact_program(mesh: Mesh, axis_name: str, blk_shape, n_split: int, jdtype: str):
    """Per-shard nonzero: count + front-packed GLOBAL coordinates
    (reference indexing.py nonzero returns rank-local results shifted by
    the split offset — same coordinates, even blocks here)."""
    L = int(np.prod(blk_shape))
    b0 = blk_shape[0]
    ndim = len(blk_shape)
    spec = P(*(axis_name if i == 0 else None for i in range(ndim)))

    def body(x_blk):
        r = lax.axis_index(axis_name)
        valid0 = (r * b0 + jax.lax.broadcasted_iota(jnp.int32, (b0,), 0)) < n_split
        m = (x_blk != 0) & jnp.broadcast_to(
            valid0.reshape((b0,) + (1,) * (ndim - 1)), blk_shape
        )
        flat = m.reshape(-1)
        c = jnp.sum(flat.astype(jnp.int32))
        idx = jnp.nonzero(flat, size=L, fill_value=0)[0]
        coords = list(jnp.unravel_index(idx, blk_shape))
        coords[0] = coords[0] + (r * b0).astype(coords[0].dtype)
        cand = jnp.stack(coords, axis=1).astype(types.index_jax_type())  # (L, ndim)
        return cand, c.reshape(1)

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec,),
        out_specs=(P(axis_name, None), P(axis_name)), check_vma=False,
    )
    return jax.jit(fn)


def distributed_nonzero(phys: jax.Array, n_split: int, mesh: Mesh, axis_name: str):
    """Gather-free nonzero of a split=0 physical array → even split=0
    physical (nnz, ndim) int64 coordinates plus the count (one small host
    sync for the per-shard counts)."""
    p = mesh.devices.size
    blk = tuple(s // p if i == 0 else s for i, s in enumerate(phys.shape))
    cand, counts = _nonzero_compact_program(
        mesh, axis_name, blk, n_split, np.dtype(phys.dtype).name
    )(phys)
    return _compact_gather(cand, counts, mesh, axis_name, (phys.ndim,))


def _sorted_dedup(flat, valid):
    """Shared dedup core of the unique schedules: lexicographic
    ``lax.sort`` over (invalid-flag, value) sinks every invalid slot past
    the valid ones, then duplicate-marking compacts the survivors to the
    front. NaNs sort last among valid entries and collapse to ONE (the
    ``differs`` mask treats NaN==NaN as equal), matching ``np.unique``'s
    equal_nan semantics (numpy ≥ 1.21).

    Returns (compacted values — garbage past the count, count)."""
    L = flat.shape[0]
    invalid = (~valid).astype(jnp.int8)
    inv_s, s = lax.sort((invalid, flat), num_keys=2, is_stable=True)
    first = jax.lax.broadcasted_iota(jnp.int32, (L,), 0) == 0
    prev = jnp.concatenate([s[:1], s[:-1]])
    differs = s != prev
    if jnp.issubdtype(s.dtype, jnp.floating):
        differs = differs & ~(jnp.isnan(s) & jnp.isnan(prev))
    keep = (inv_s == 0) & (first | differs)
    c = jnp.sum(keep.astype(jnp.int32))
    idx = jnp.nonzero(keep, size=L, fill_value=L)[0]
    return jnp.concatenate([s, s[:1]])[idx], c


@functools.lru_cache(maxsize=64)
def _local_unique_program(mesh: Mesh, axis_name: str, blk_shape, n_split: int, jdtype: str):
    """Per-shard sorted unique with fixed capacity (see ``_sorted_dedup``
    for the dedup semantics)."""
    b0 = blk_shape[0]
    spec = P(*(axis_name if i == 0 else None for i in range(len(blk_shape))))

    def body(x_blk):
        r = lax.axis_index(axis_name)
        valid0 = (r * b0 + jax.lax.broadcasted_iota(jnp.int32, (b0,), 0)) < n_split
        valid = jnp.broadcast_to(
            valid0.reshape((b0,) + (1,) * (len(blk_shape) - 1)), blk_shape
        ).reshape(-1)
        cand, c = _sorted_dedup(x_blk.reshape(-1), valid)
        return cand, c.reshape(1)

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=(P(axis_name), P(axis_name)), check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _unique_merge_program(mesh: Mesh, axis_name: str, p: int, cap: int, jdtype: str):
    """Merge the per-shard unique candidate prefixes: all-gather the
    (p·cap) candidate set, re-sort with validity keys, deduplicate —
    replicated output (the reference Bcasts its merged set the same way).

    Memory note: the merged unique set is REPLICATED by contract (as in
    the reference), so for inputs whose values are mostly distinct the
    (p·cap) gather is ~the whole operand per device — that is the
    output's own footprint, not avoidable by chunking. ``unique`` is a
    small-alphabet/sparse-result op at scale."""

    def body(cand_blk, cnt_blk):
        allc = lax.all_gather(cand_blk[:cap], axis_name).reshape(-1)   # (p*cap,)
        counts = lax.all_gather(cnt_blk, axis_name).reshape(-1)
        pos = jax.lax.broadcasted_iota(jnp.int32, (p * cap,), 0)
        valid = (pos % cap) < counts[pos // cap]
        return _sorted_dedup(allc, valid)

    fn = shard_map(
        body, mesh=mesh, in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(), P()), check_vma=False,
    )
    return jax.jit(fn)


def _sorted_dedup_rows(mat, valid):
    """Rows analog of :func:`_sorted_dedup`: lexicographic ``lax.sort``
    over (invalid-flag, col_0, …, col_{R-1}) — column 0 is the primary
    key, invalid rows sink past every valid one — then duplicate-marking
    compacts the surviving FIRST occurrences to the front. ``mat`` is
    the (L, R) SORTABLE-uint bit view of the rows
    (``kernels.sort.to_sortable`` per element), so unsigned comparison
    IS value order and the collapsed tie classes (−0.0 with +0.0, every
    NaN payload) dedupe exactly like the framework's flat unique.

    Returns (compacted rows — garbage past the count, count)."""
    L, R = mat.shape
    invalid = (~valid).astype(jnp.int8)
    sorted_ops = lax.sort(
        (invalid,) + tuple(mat[:, j] for j in range(R)),
        num_keys=R + 1,
        is_stable=True,
    )
    inv_s = sorted_ops[0]
    s = jnp.stack(sorted_ops[1:], axis=1)  # (L, R) rows back together
    first = jax.lax.broadcasted_iota(jnp.int32, (L,), 0) == 0
    prev = jnp.concatenate([s[:1], s[:-1]], axis=0)
    differs = jnp.any(s != prev, axis=1)
    keep = (inv_s == 0) & (first | differs)
    c = jnp.sum(keep.astype(jnp.int32))
    idx = jnp.nonzero(keep, size=L, fill_value=L)[0]
    pad = jnp.zeros((1, R), dtype=s.dtype)
    return jnp.concatenate([s, pad], axis=0)[idx], c


@functools.lru_cache(maxsize=64)
def _local_unique_rows_program(
    mesh: Mesh, axis_name: str, blk_shape, n_split: int, jdtype: str
):
    """Per-shard sorted ROWS-unique with fixed capacity — the axis-mode
    counterpart of ``_local_unique_program`` (ISSUE 11 satellite: the
    gather-free ``unique(axis=)``)."""
    b0 = blk_shape[0]

    def body(x_blk):
        r = lax.axis_index(axis_name)
        valid = (r * b0 + jax.lax.broadcasted_iota(jnp.int32, (b0,), 0)) < n_split
        cand, c = _sorted_dedup_rows(x_blk, valid)
        return cand, c.reshape(1)

    fn = shard_map(
        body, mesh=mesh, in_specs=(P(axis_name, None),),
        out_specs=(P(axis_name, None), P(axis_name)), check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _unique_rows_merge_program(mesh: Mesh, axis_name: str, p: int, cap: int, jdtype: str):
    """Merge the per-shard unique ROW-candidate prefixes: all-gather the
    (p·cap, R) candidate rows — the candidate set, never the operand —
    re-sort lexicographically with validity keys, deduplicate;
    replicated output like the flat merge."""

    def body(cand_blk, cnt_blk):
        allc = lax.all_gather(cand_blk[:cap], axis_name)     # (p, cap, R)
        allc = allc.reshape(p * cap, cand_blk.shape[1])
        counts = lax.all_gather(cnt_blk, axis_name).reshape(-1)
        pos = jax.lax.broadcasted_iota(jnp.int32, (p * cap,), 0)
        valid = (pos % cap) < counts[pos // cap]
        return _sorted_dedup_rows(allc, valid)

    fn = shard_map(
        body, mesh=mesh, in_specs=(P(axis_name, None), P(axis_name)),
        out_specs=(P(), P()), check_vma=False,
    )
    return jax.jit(fn)


def distributed_unique_rows(
    phys: jax.Array, n_split: int, mesh: Mesh, axis_name: str
):
    """Sorted unique ROWS of a split=0 (n, R) SORTABLE-uint matrix
    without gathering the operand (the sorted-split formulation the
    VERDICT backlog asked for): per-shard lexicographic sorted-unique
    compaction, one tiny count sync, and a merge over only the
    candidate prefixes. The operand itself never crosses the mesh —
    the only all-gathers carry the (p·cap, R) candidate set.

    Returns the merged unique rows (replicated, sliced to the true
    count)."""
    p = mesh.devices.size
    blk = (phys.shape[0] // p, phys.shape[1])
    cand, counts = _local_unique_rows_program(
        mesh, axis_name, blk, n_split, np.dtype(phys.dtype).name
    )(phys)
    counts_host = _host_counts(counts)
    cap = max(int(counts_host.max()), 1)
    merged, total = _unique_rows_merge_program(
        mesh, axis_name, p, cap, np.dtype(phys.dtype).name
    )(cand, counts)
    return merged[: int(jax.device_get(total))]


def distributed_unique(
    phys: jax.Array, n_split: int, mesh: Mesh, axis_name: str
):
    """Sorted unique of a split=0 physical array without gathering the
    operand: local sorted-unique per shard, then a merge over only the
    candidate prefixes (reference manipulations.py:3202's
    local-unique + Allgather + re-unique, with static shapes).

    Returns the merged unique values as a replicated jax array (sliced
    to the true count — one small host sync for the two counts)."""
    p = mesh.devices.size
    blk = tuple(s // p if i == 0 else s for i, s in enumerate(phys.shape))
    cand, counts = _local_unique_program(
        mesh, axis_name, blk, n_split, np.dtype(phys.dtype).name
    )(phys)
    counts_host = _host_counts(counts)
    cap = max(int(counts_host.max()), 1)
    merged, total = _unique_merge_program(
        mesh, axis_name, p, cap, np.dtype(phys.dtype).name
    )(cand, counts)
    return merged[: int(jax.device_get(total))]


__all__ += [
    "compact_select", "distributed_unique", "distributed_unique_rows",
    "distributed_nonzero",
]


from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_halo_program)
register_mesh_cache(_topk_program)
register_mesh_cache(_ring_program)
register_mesh_cache(_oddeven_sort_program)
register_mesh_cache(_oddeven_sort_values_program)
register_mesh_cache(_columnsort_program)
register_mesh_cache(_mask_compact_program)
register_mesh_cache(_balanced_gather_program)
register_mesh_cache(_nonzero_compact_program)
register_mesh_cache(_local_unique_program)
register_mesh_cache(_unique_merge_program)
register_mesh_cache(_local_unique_rows_program)
register_mesh_cache(_unique_rows_merge_program)
