"""Generic distributed operation wrappers.

API parity with /root/reference/heat/core/_operations.py: ``__binary_op``
(_operations.py:22), ``__cum_op`` (:204), ``__local_op`` (:305),
``__reduce_op`` (:378). The reference versions interleave type promotion
with explicit redistribution (`sanitize_distribution`) and MPI collectives
(`Allreduce` when the reduction axis includes the split,
_operations.py:466-471; `Exscan` for cumulative ops). Here the local torch
kernel becomes a jnp/XLA op on the global sharded array: GSPMD inserts the
equivalent collectives (a reduction over the sharded axis lowers to the
same all-reduce over ICI), so these wrappers shrink to type promotion,
split bookkeeping and sharding constraints.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional, Union

from . import types
from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = []


def _as_dndarray(x, reference: DNDarray) -> DNDarray:
    """Promote scalars / array-likes to DNDarray on the reference's comm."""
    from . import factories

    if isinstance(x, DNDarray):
        return x
    return factories.array(
        x, device=reference.device, comm=reference.comm, split=None
    )


def __binary_op(
    operation: Callable,
    t1: Union[DNDarray, int, float],
    t2: Union[DNDarray, int, float],
    out: Optional[DNDarray] = None,
    where: Optional[DNDarray] = None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic elementwise binary operation (reference: _operations.py:22).

    Promotes types on the torch/XLA lattice, broadcasts, resolves the
    output split by the dominant-operand rule (reference
    _operations.py:147-168) and applies ``operation`` to the global arrays;
    distribution matching is a resharding constraint instead of explicit
    redistribution.
    """
    fn_kwargs = fn_kwargs or {}

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"at least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    ref = t1 if isinstance(t1, DNDarray) else t2

    # scalar fast-path: keep weak typing so int + float32-array stays float32
    scalar1 = not isinstance(t1, DNDarray)
    scalar2 = not isinstance(t2, DNDarray)

    promoted = types.result_type(t1, t2)
    jt = promoted.jax_type()

    a1 = t1 if scalar1 else t1.larray
    a2 = t2 if scalar2 else t2.larray
    if scalar1 and not isinstance(t1, (int, float, complex, bool)):
        a1 = jnp.asarray(np.asarray(t1))
        scalar1 = False
    if scalar2 and not isinstance(t2, (int, float, complex, bool)):
        a2 = jnp.asarray(np.asarray(t2))
        scalar2 = False

    if not scalar1:
        a1 = a1.astype(jt)
    if not scalar2:
        a2 = a2.astype(jt)

    shape1 = () if scalar1 else tuple(t1.shape) if isinstance(t1, DNDarray) else tuple(a1.shape)
    shape2 = () if scalar2 else tuple(t2.shape) if isinstance(t2, DNDarray) else tuple(a2.shape)
    output_shape = broadcast_shape(shape1, shape2)
    out_ndim = len(output_shape)

    # dominant split resolution in output coordinates
    def _out_split(t, shape):
        if not isinstance(t, DNDarray) or t.split is None:
            return None
        return t.split + (out_ndim - t.ndim)

    s1 = _out_split(t1, shape1)
    s2 = _out_split(t2, shape2)
    if s1 is not None and s2 is not None and s1 != s2:
        # align t2 to t1's split (reference redistributes the non-dominant operand)
        t2 = t2.resplit(s1 - (out_ndim - t2.ndim)) if 0 <= s1 - (out_ndim - t2.ndim) else t2
        a2 = t2.larray.astype(jt)
        s2 = _out_split(t2, shape2)
    output_split = s1 if s1 is not None else s2
    # a broadcast dimension of extent 1 cannot carry the split
    if output_split is not None and output_shape[output_split] == 1:
        output_split = None

    result = operation(a1, a2, **fn_kwargs)

    if where is not None:
        w = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray.astype(result.dtype) if out is not None else jnp.zeros_like(result)
        result = jnp.where(w, result, base)

    comm = ref.comm
    device = ref.device
    if output_split is not None:
        result = comm.shard(result, output_split)

    res_type = types.canonical_heat_type(result.dtype)
    if out is not None:
        from .sanitation import sanitize_out

        from . import _padding

        sanitize_out(out, output_shape, output_split, device)
        buffered = _padding.unpad(result, output_shape, output_split).astype(out.dtype.jax_type())
        out.larray = buffered
        return out

    return DNDarray(result, output_shape, res_type, output_split, device, comm)


def __cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative op (reference: _operations.py:204 — local cumop +
    ``Exscan`` + combine). A jnp cumulative op on the sharded array lowers
    to the same scan-with-carry across shards.
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operation over flattened array: ravel first")

    arr = x.larray
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        arr = arr.astype(dtype.jax_type())
    result = operation(arr, axis=axis)
    res_type = types.canonical_heat_type(result.dtype)
    comm = x.comm
    if x.split is not None:
        result = comm.shard(result, x.split)

    if out is not None:
        from .sanitation import sanitize_out

        from . import _padding

        sanitize_out(out, x.shape, x.split, x.device)
        out.larray = _padding.unpad(result, x.shape, x.split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, x.shape, res_type, x.split, x.device, comm)


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic pure-local elementwise op (reference: _operations.py:305) —
    no communication; sharding is preserved by XLA elementwise semantics.
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    arr = x.larray
    if not no_cast and types.heat_type_is_exact(x.dtype):
        promoted = types.promote_types(x.dtype, types.float32)
        arr = arr.astype(promoted.jax_type())

    result = operation(arr, **kwargs)
    res_type = types.canonical_heat_type(result.dtype)
    split = x.split if result.ndim == x.ndim else None
    output_shape = tuple(int(s) for s in result.shape)
    if split is not None:
        result = x.comm.shard(result, split)

    if out is not None:
        from .sanitation import sanitize_out
        from . import _padding

        sanitize_out(out, output_shape, split, x.device)
        out.larray = _padding.unpad(result, output_shape, split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, output_shape, res_type, split, x.device, x.comm)


def __reduce_op(
    partial_op: Callable,
    x: DNDarray,
    axis: Optional[Union[int, tuple]] = None,
    neutral=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference: _operations.py:378 — local partial
    reduce followed by ``Allreduce`` when ``split in axis``,
    _operations.py:466-471). The jnp reduction over the sharded global
    array makes XLA emit that same all-reduce over the mesh.
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)

    kwargs.pop("out", None)
    result = partial_op(x.larray, axis=axis, keepdims=keepdims, **kwargs)
    if not isinstance(result, jax.Array):
        result = jnp.asarray(result)

    # output split bookkeeping
    split = x.split
    if split is None:
        output_split = None
    elif axis is None:
        output_split = None
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if split in axes:
            output_split = None
        elif keepdims:
            output_split = split
        else:
            output_split = split - sum(1 for a in axes if a < split)

    comm = x.comm
    output_shape = tuple(int(s) for s in result.shape)
    if output_split is not None:
        result = comm.shard(result, output_split)

    res_type = types.canonical_heat_type(result.dtype)

    if out is not None:
        from .sanitation import sanitize_out

        from . import _padding

        sanitize_out(out, output_shape, output_split, x.device)
        out.larray = _padding.unpad(result, output_shape, output_split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, output_shape, res_type, output_split, x.device, comm)
