"""Generic distributed operation wrappers.

API parity with /root/reference/heat/core/_operations.py: ``__binary_op``
(_operations.py:22), ``__cum_op`` (:204), ``__local_op`` (:305),
``__reduce_op`` (:378). The reference versions interleave type promotion
with explicit redistribution (`sanitize_distribution`) and MPI collectives
(`Allreduce` when the reduction axis includes the split,
_operations.py:466-471; `Exscan` for cumulative ops).

TPU execution model: every wrapper routes through a CACHED JITTED CALLABLE
operating on the PHYSICAL (padded) arrays — one compiled XLA program per
(op, shape, dtype, split) configuration, with dtype casts, pad-neutral
refills and the zero-pad restore all fused into the same program and the
output sharding pinned via ``out_shardings``. Uneven shapes therefore pay
no per-op unpad→op→repad round trip, and a dispatch is one jitted call on
an already-sharded array. The reference's collectives appear implicitly: a
reduction over the sharded axis lowers to the same all-reduce over ICI.

Irregular cases (``where=``, non-hashable kwargs, ops that change rank
unexpectedly) fall back to an eager logical-array path with identical
semantics.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional, Union

from . import types
from . import _padding
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis
from ..observability.instrument import observed_program_cache

__all__ = []


def _as_dndarray(x, reference: DNDarray) -> DNDarray:
    """Promote scalars / array-likes to DNDarray on the reference's comm."""
    from . import factories

    if isinstance(x, DNDarray):
        return x
    return factories.array(
        x, device=reference.device, comm=reference.comm, split=None
    )


def _kw_key(kwargs: Optional[dict]):
    """Hashable snapshot of an op's kwargs, or None when not cacheable."""
    if not kwargs:
        return ()
    try:
        items = tuple(sorted(kwargs.items()))
        hash(items)
        return items
    except TypeError:
        return None


def _kw_split(kwargs: Optional[dict]):
    """Partition kwargs into (static_items, dyn_names, dyn_dtypes) +
    dyn_values. Float/complex scalars and arrays become TRACED arguments —
    baking them into the program cache key would recompile per value
    (e.g. ``ht.clip(x, max=hi)`` in a loop) and leak dead executables.
    Ints/bools/strings stay static: jnp ops require them at trace time
    (axis, decimals, mode). Returns None when uncacheable."""
    static = []
    dyn_names = []
    dyn_vals = []
    try:
        for k in sorted(kwargs or {}):
            v = kwargs[k]
            if v is None or isinstance(v, (bool, int, str, bytes)):
                static.append((k, v))
            elif isinstance(v, (float, complex)):
                dyn_names.append(k)
                dyn_vals.append(v)
            elif isinstance(v, (np.ndarray, jax.Array)):
                dyn_names.append(k)
                dyn_vals.append(v)
            elif isinstance(v, tuple):
                hash(v)
                static.append((k, v))
            else:
                return None
    except TypeError:
        return None
    dyn_dtypes = tuple(np.result_type(v).name for v in dyn_vals)
    return (tuple(static), tuple(dyn_names), dyn_dtypes), tuple(dyn_vals)


_mask_tail = _padding.mask_tail


def _pad_operand(arr, out_ndim: int, split: int, pext: int):
    """Align an operand's split-dim extent to the physical extent. A
    replicated operand carries the logical extent; pad it (shapes are
    static under trace, so this resolves at compile time). Extent-1
    dims broadcast as-is."""
    ndim = getattr(arr, "ndim", 0)
    dim = split - (out_ndim - ndim)
    if dim < 0:
        return arr
    ext = arr.shape[dim]
    if ext in (1, pext):
        return arr
    widths = [(0, 0)] * ndim
    widths[dim] = (0, pext - ext)
    return jnp.pad(arr, widths)


# neutral elements for pad refill when a reduction touches the split axis;
# "min"/"max" resolve against the input dtype inside the traced program
_REDUCE_NEUTRAL = {}


def _register_neutrals():
    table = [
        (("sum", "nansum"), 0),
        (("prod", "nanprod"), 1),
        (("min", "amin", "nanmin"), "max"),
        (("max", "amax", "nanmax"), "min"),
        (("all",), True),
        (("any",), False),
    ]
    for names, neutral in table:
        for name in names:
            fn = getattr(jnp, name, None)
            if fn is not None:
                _REDUCE_NEUTRAL[fn] = neutral


_register_neutrals()


def _resolve_neutral(tag, dtype):
    if tag == "max":
        return jnp.inf if jnp.issubdtype(dtype, jnp.inexact) else jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer) else True
    if tag == "min":
        return -jnp.inf if jnp.issubdtype(dtype, jnp.inexact) else jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer) else False
    return tag


# --------------------------------------------------------------------- #
# cached jitted executors                                               #
# --------------------------------------------------------------------- #
@observed_program_cache("op.binary")
@functools.lru_cache(maxsize=4096)
def _binary_callable(op, comm, out_ndim, split, n, pext, cast, scalar1, scalar2, kw):
    """One compiled program: cast → align pads → op → restore zero pad.
    ``scalar1/2`` record which operands arrived as Python scalars — those
    keep their weak dtype so promotion matches eager numpy/jnp semantics."""
    def fn(a, b):
        if cast is not None:
            jt = jnp.dtype(cast)
            if not scalar1:
                a = a.astype(jt)
            if not scalar2:
                b = b.astype(jt)
        if split is not None:
            a = _pad_operand(a, out_ndim, split, pext)
            b = _pad_operand(b, out_ndim, split, pext)
        r = op(a, b, **dict(kw))
        if split is not None and pext != n:
            r = _mask_tail(r, split, n)
        return r

    return comm.jit_sharded(fn, out_ndim, split)


@observed_program_cache("op.unary")
@functools.lru_cache(maxsize=4096)
def _unary_callable(op, comm, ndim, split, n, pext, cast, static_kw, dyn_names):
    def fn(arr, *dyn):
        kwargs = dict(static_kw)
        kwargs.update(zip(dyn_names, dyn))
        if cast is not None:
            arr = arr.astype(jnp.dtype(cast))
        r = op(arr, **kwargs)
        if split is not None and pext != n:
            r = _mask_tail(r, split, n)
        return r

    return comm.jit_sharded(fn, ndim, split)


@observed_program_cache("op.reduce")
@functools.lru_cache(maxsize=4096)
def _reduce_callable(op, comm, split, n, pext, axes, keepdims, neutral, out_ndim, out_split, out_n, out_pext, kw):
    def fn(arr):
        if split is not None and pext != n and neutral is not None:
            arr = _mask_tail(arr, split, n, _resolve_neutral(neutral, arr.dtype))
        r = op(arr, axis=axes, keepdims=keepdims, **dict(kw))
        if not isinstance(r, jax.Array) and not hasattr(r, "ndim"):
            r = jnp.asarray(r)
        if out_split is not None and out_pext != out_n:
            r = _mask_tail(r, out_split, out_n)
        return r

    return comm.jit_sharded(fn, out_ndim, out_split)


@observed_program_cache("op.cum")
@functools.lru_cache(maxsize=1024)
def _cum_callable(op, comm, ndim, split, n, pext, axis, cast):
    def fn(arr):
        if cast is not None:
            arr = arr.astype(jnp.dtype(cast))
        r = op(arr, axis=axis)
        if split is not None and pext != n:
            r = _mask_tail(r, split, n)
        return r

    return comm.jit_sharded(fn, ndim, split)


@functools.lru_cache(maxsize=4096)
def _local_probe_keeps_shape(op, shape, dtype, cast, static_kw, dyn_names, dyn_dtypes, dyn_shapes) -> bool:
    """True iff ``op`` maps an array of (shape, dtype[, cast]) to the same
    shape — the condition for running it on the physical array."""
    def probe(a, *dyn):
        kwargs = dict(static_kw)
        kwargs.update(zip(dyn_names, dyn))
        if cast is not None:
            a = a.astype(jnp.dtype(cast))
        return op(a, **kwargs)

    try:
        structs = [
            jax.ShapeDtypeStruct(sh, jnp.dtype(dt))
            for sh, dt in zip(dyn_shapes, dyn_dtypes)
        ]
        res = jax.eval_shape(probe, jax.ShapeDtypeStruct(shape, jnp.dtype(dtype)), *structs)
    except Exception:
        return False
    return hasattr(res, "shape") and tuple(res.shape) == tuple(shape)


def _phys_meta(x: DNDarray):
    """(logical n, physical ext) along the split axis, or (None, None)."""
    if x.split is None:
        return None, None
    return x.gshape[x.split], x._phys.shape[x.split]


# --------------------------------------------------------------------- #
# wrappers                                                              #
# --------------------------------------------------------------------- #
def __binary_op(
    operation: Callable,
    t1: Union[DNDarray, int, float],
    t2: Union[DNDarray, int, float],
    out: Optional[DNDarray] = None,
    where: Optional[DNDarray] = None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic elementwise binary operation (reference: _operations.py:22).

    Promotes types on the torch/XLA lattice, broadcasts, resolves the
    output split by the dominant-operand rule (reference
    _operations.py:147-168) and executes ONE cached jitted program on the
    physical arrays; distribution matching is a resharding constraint
    instead of explicit redistribution.
    """
    fn_kwargs = fn_kwargs or {}

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"at least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    ref = t1 if isinstance(t1, DNDarray) else t2

    scalar1 = not isinstance(t1, DNDarray)
    scalar2 = not isinstance(t2, DNDarray)

    promoted = types.result_type(t1, t2)
    # complex platform policy at the PROMOTION point: a real array times a
    # complex python scalar would otherwise enqueue a complex program
    # before the output DNDarray's constructor check — and one enqueued
    # complex op poisons the unsupporting backend for the whole process.
    # Under the planar policy the whole op routes to plane arithmetic.
    if types.heat_type_is_complexfloating(types.degrade64(promoted)):
        from . import complex_planar as _cp

        if _cp.is_planar(t1) or _cp.is_planar(t2) or _cp.active():
            return _cp.binary(operation, t1, t2, out=out, where=where, fn_kwargs=fn_kwargs)
        types.check_complex_platform(types.degrade64(promoted))
    jt = promoted.jax_type()

    # non-DNDarray array-likes become concrete arrays up front
    a1 = t1 if scalar1 else None
    a2 = t2 if scalar2 else None
    if scalar1 and not isinstance(t1, (int, float, complex, bool)):
        a1 = jnp.asarray(np.asarray(t1))
        scalar1 = False
    if scalar2 and not isinstance(t2, (int, float, complex, bool)):
        a2 = jnp.asarray(np.asarray(t2))
        scalar2 = False

    shape1 = () if a1 is not None and scalar1 else tuple(t1.shape) if isinstance(t1, DNDarray) else tuple(np.shape(a1))
    shape2 = () if a2 is not None and scalar2 else tuple(t2.shape) if isinstance(t2, DNDarray) else tuple(np.shape(a2))
    output_shape = broadcast_shape(shape1, shape2)
    out_ndim = len(output_shape)

    def _out_split(t):
        if not isinstance(t, DNDarray) or t.split is None:
            return None
        return t.split + (out_ndim - t.ndim)

    s1 = _out_split(t1)
    s2 = _out_split(t2)
    if s1 is not None and s2 is not None and s1 != s2:
        # align t2 to t1's split (reference redistributes the non-dominant operand)
        tgt = s1 - (out_ndim - t2.ndim)
        if tgt >= 0:
            t2 = t2.resplit(tgt)
        s2 = _out_split(t2)
    output_split = s1 if s1 is not None else s2
    # a broadcast dimension of extent 1 cannot carry the split; a
    # zero-extent output is stored replicated (comm.shard convention),
    # so pinning a split sharding on it would conflict
    if output_split is not None and output_shape[output_split] <= 1:
        output_split = None

    comm = ref.comm
    device = ref.device
    kw = _kw_key(fn_kwargs)

    if where is None and kw is not None:
        # fast path: one jitted program over physical operands
        n = output_shape[output_split] if output_split is not None else 0
        pext = _padding.pad_extent(n, comm.size) if output_split is not None else 0

        def _operand(t, a, is_scalar):
            if is_scalar or not isinstance(t, DNDarray):
                return a
            if output_split is not None and t.split is not None:
                if t.split + (out_ndim - t.ndim) == output_split:
                    # a logical extent-1 dim must BROADCAST; its physical
                    # pad extent would pair row-by-row instead
                    if t.gshape[t.split] == 1 and t._phys.shape[t.split] != 1:
                        return t.larray
                    return t._phys
            # replicated operand, operand split off the output split, or
            # output_split nulled (extent-1): the physical pad would either
            # fail to broadcast or leak pad rows — feed the logical view
            return t.larray

        x1 = _operand(t1, a1, scalar1)
        x2 = _operand(t2, a2, scalar2)
        prog = _binary_callable(
            operation, comm, out_ndim, output_split, n, pext, np.dtype(jt).name,
            scalar1, scalar2, kw,
        )
        result = prog(x1, x2)
        res_type = types.canonical_heat_type(result.dtype)
        if out is not None:
            from .sanitation import sanitize_out

            sanitize_out(out, output_shape, output_split, device)
            if out.split == output_split:
                out._set_phys(result.astype(out.dtype.jax_type()))
            else:
                out.larray = _padding.unpad(result, output_shape, output_split).astype(
                    out.dtype.jax_type()
                )
            return out
        return DNDarray(result, output_shape, res_type, output_split, device, comm)

    # eager fallback (where= masking, or uncacheable kwargs)
    b1 = a1 if scalar1 else (t1.larray.astype(jt) if isinstance(t1, DNDarray) else a1.astype(jt))
    b2 = a2 if scalar2 else (t2.larray.astype(jt) if isinstance(t2, DNDarray) else a2.astype(jt))
    result = operation(b1, b2, **fn_kwargs)

    if where is not None:
        w = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray.astype(result.dtype) if out is not None else jnp.zeros_like(result)
        result = jnp.where(w, result, base)

    if output_split is not None:
        result = comm.shard(result, output_split)

    res_type = types.canonical_heat_type(result.dtype)
    if out is not None:
        from .sanitation import sanitize_out

        sanitize_out(out, output_shape, output_split, device)
        out.larray = _padding.unpad(result, output_shape, output_split).astype(out.dtype.jax_type())
        return out

    return DNDarray(result, output_shape, res_type, output_split, device, comm)


def __cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative op (reference: _operations.py:204 — local cumop +
    ``Exscan`` + combine). A jnp cumulative op on the sharded array lowers
    to the same scan-with-carry across shards. Pad rows sit at the global
    tail, so the logical prefix of the cumulation is unaffected; the output
    pad is re-zeroed inside the program.
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    if isinstance(x, DNDarray) and x._is_planar:
        from . import complex_planar as _cp

        return _cp.cum(operation, x, axis, out=out, dtype=dtype)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative operation over flattened array: ravel first")

    cast = None
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        cast = np.dtype(dtype.jax_type()).name

    comm = x.comm
    n, pext = _phys_meta(x)
    prog = _cum_callable(operation, comm, x.ndim, x.split, n, pext, axis, cast)
    result = prog(x._phys)
    res_type = types.canonical_heat_type(result.dtype)

    if out is not None:
        from .sanitation import sanitize_out

        sanitize_out(out, x.shape, x.split, x.device)
        if out.split == x.split:
            out._set_phys(result.astype(out.dtype.jax_type()))
        else:
            out.larray = _padding.unpad(result, x.shape, x.split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, x.shape, res_type, x.split, x.device, comm)


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic pure-local elementwise op (reference: _operations.py:305) —
    no communication; sharding is preserved by XLA elementwise semantics.
    Runs as one cached jitted program on the physical array (cast and
    zero-pad restore fused in).
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    if isinstance(x, DNDarray) and x._is_planar:
        from . import complex_planar as _cp

        return _cp.local(operation, x, out, kwargs)
    cast = None
    if not no_cast and types.heat_type_is_exact(x.dtype):
        promoted = types.promote_types(x.dtype, types.float32)
        cast = np.dtype(promoted.jax_type()).name

    ks = _kw_split(kwargs)
    if ks is None:
        # uncacheable kwargs: eager logical path
        return _local_op_eager(operation, x, out, cast, **kwargs)
    (static_kw, dyn_names, dyn_dtypes), dyn_vals = ks

    comm = x.comm
    n, pext = _phys_meta(x)
    dyn_shapes = tuple(tuple(np.shape(v)) for v in dyn_vals)
    if not _local_probe_keeps_shape(
        operation, tuple(x._phys.shape), np.dtype(x._phys.dtype).name, cast,
        static_kw, dyn_names, dyn_dtypes, dyn_shapes,
    ):
        return _local_op_eager(operation, x, out, cast, **kwargs)

    prog = _unary_callable(operation, comm, x.ndim, x.split, n, pext, cast, static_kw, dyn_names)
    result = prog(x._phys, *dyn_vals)
    res_type = types.canonical_heat_type(result.dtype)

    if out is not None:
        from .sanitation import sanitize_out

        sanitize_out(out, x.shape, x.split, x.device)
        if out.split == x.split:
            out._set_phys(result.astype(out.dtype.jax_type()))
        else:
            out.larray = _padding.unpad(result, x.shape, x.split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, x.shape, res_type, x.split, x.device, x.comm)


def _local_op_eager(operation, x, out, cast, **kwargs):
    arr = x.larray
    if cast is not None:
        arr = arr.astype(jnp.dtype(cast))
    result = operation(arr, **kwargs)
    res_type = types.canonical_heat_type(result.dtype)
    split = x.split if result.ndim == x.ndim else None
    output_shape = tuple(int(s) for s in result.shape)
    if split is not None:
        result = x.comm.shard(result, split)
    if out is not None:
        from .sanitation import sanitize_out

        sanitize_out(out, output_shape, split, x.device)
        out.larray = _padding.unpad(result, output_shape, split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, output_shape, res_type, split, x.device, x.comm)


def __reduce_op(
    partial_op: Callable,
    x: DNDarray,
    axis: Optional[Union[int, tuple]] = None,
    neutral=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference: _operations.py:378 — local partial
    reduce followed by ``Allreduce`` when ``split in axis``,
    _operations.py:466-471). The jnp reduction over the sharded physical
    array makes XLA emit that same all-reduce over ICI; pad rows are
    refilled with the op's neutral element inside the compiled program
    when the reduction touches the split axis.
    """
    from .sanitation import sanitize_in

    sanitize_in(x)
    if isinstance(x, DNDarray) and x._is_planar:
        from . import complex_planar as _cp

        return _cp.reduce(partial_op, x, axis=axis, keepdims=keepdims, out=out, kwargs=kwargs)
    axis = sanitize_axis(x.shape, axis)

    kwargs.pop("out", None)
    kw = _kw_key(kwargs)

    # output split bookkeeping
    split = x.split
    if split is None or axis is None:
        output_split = None
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if split in axes:
            output_split = None
        elif keepdims:
            output_split = split
        else:
            output_split = split - sum(1 for a in axes if a < split)

    comm = x.comm
    n, pext = _phys_meta(x)
    touches_split = split is not None and (
        axis is None or split in ((axis,) if isinstance(axis, int) else tuple(axis))
    )
    if neutral is None:
        neutral = _REDUCE_NEUTRAL.get(partial_op)

    if kw is None or (touches_split and pext != n and neutral is None):
        # eager logical fallback: unknown neutral with a real pad region
        result = partial_op(x.larray, axis=axis, keepdims=keepdims, **kwargs)
        if not isinstance(result, jax.Array):
            result = jnp.asarray(result)
        output_shape = tuple(int(s) for s in result.shape)
        if output_split is not None:
            result = comm.shard(result, output_split)
        res_type = types.canonical_heat_type(result.dtype)
        if out is not None:
            from .sanitation import sanitize_out

            sanitize_out(out, output_shape, output_split, x.device)
            out.larray = _padding.unpad(result, output_shape, output_split).astype(out.dtype.jax_type())
            return out
        return DNDarray(result, output_shape, res_type, output_split, x.device, comm)

    # fast path: compute output geometry statically
    in_shape = x.gshape
    if axis is None:
        output_shape = (1,) * x.ndim if keepdims else ()
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if keepdims:
            output_shape = tuple(1 if i in axes else s for i, s in enumerate(in_shape))
        else:
            output_shape = tuple(s for i, s in enumerate(in_shape) if i not in axes)
    out_ndim = len(output_shape)
    out_n = output_shape[output_split] if output_split is not None else 0
    out_pext = _padding.pad_extent(out_n, comm.size) if output_split is not None else 0

    axes_key = axis if (axis is None or isinstance(axis, int)) else tuple(axis)
    prog = _reduce_callable(
        partial_op, comm, split, n, pext, axes_key, keepdims,
        neutral if (touches_split and pext != n) else None,
        out_ndim, output_split, out_n, out_pext, kw,
    )
    result = prog(x._phys)
    res_type = types.canonical_heat_type(result.dtype)

    if out is not None:
        from .sanitation import sanitize_out

        sanitize_out(out, output_shape, output_split, x.device)
        if out.split == output_split:
            out._set_phys(result.astype(out.dtype.jax_type()))
        else:
            out.larray = _padding.unpad(result, output_shape, output_split).astype(out.dtype.jax_type())
        return out
    return DNDarray(result, output_shape, res_type, output_split, x.device, comm)

from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_binary_callable)
register_mesh_cache(_unary_callable)
register_mesh_cache(_reduce_callable)
register_mesh_cache(_cum_callable)
