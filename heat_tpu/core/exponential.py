"""Exponential and logarithmic functions.

API parity with /root/reference/heat/core/exponential.py (11 exports, all
pure-local elementwise).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "exp",
    "expm1",
    "exp2",
    "log",
    "log2",
    "log10",
    "log1p",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "square",
]


def exp(x: DNDarray, out=None) -> DNDarray:
    """Elementwise e**x."""
    return _operations.__local_op(jnp.exp, x, out)


def expm1(x: DNDarray, out=None) -> DNDarray:
    """Elementwise e**x - 1 (accurate near zero)."""
    return _operations.__local_op(jnp.expm1, x, out)


def exp2(x: DNDarray, out=None) -> DNDarray:
    """Elementwise 2**x."""
    return _operations.__local_op(jnp.exp2, x, out)


def log(x: DNDarray, out=None) -> DNDarray:
    """Elementwise natural logarithm."""
    return _operations.__local_op(jnp.log, x, out)


def log2(x: DNDarray, out=None) -> DNDarray:
    """Elementwise base-2 logarithm."""
    return _operations.__local_op(jnp.log2, x, out)


def log10(x: DNDarray, out=None) -> DNDarray:
    """Elementwise base-10 logarithm."""
    return _operations.__local_op(jnp.log10, x, out)


def log1p(x: DNDarray, out=None) -> DNDarray:
    """Elementwise log(1+x) (accurate near zero)."""
    return _operations.__local_op(jnp.log1p, x, out)


def logaddexp(t1, t2) -> DNDarray:
    """log(exp(t1) + exp(t2)) without overflow."""
    return _operations.__binary_op(jnp.logaddexp, t1, t2)


def logaddexp2(t1, t2) -> DNDarray:
    """log2(2**t1 + 2**t2) without overflow."""
    return _operations.__binary_op(jnp.logaddexp2, t1, t2)


def sqrt(x: DNDarray, out=None) -> DNDarray:
    """Elementwise square root."""
    return _operations.__local_op(jnp.sqrt, x, out)


def square(x: DNDarray, out=None) -> DNDarray:
    """Elementwise square."""
    return _operations.__local_op(jnp.square, x, out, no_cast=True)


DNDarray.exp = exp
DNDarray.log = log
DNDarray.sqrt = sqrt
DNDarray.square = square
DNDarray.exp2 = exp2
DNDarray.expm1 = expm1
DNDarray.log2 = log2
DNDarray.log10 = log10
DNDarray.log1p = log1p
