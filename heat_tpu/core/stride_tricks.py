"""Shape/axis sanitization helpers.

API parity with /root/reference/heat/core/stride_tricks.py
(``broadcast_shape``/``broadcast_shapes`` at stride_tricks.py:12/70,
``sanitize_axis`` at :115). Pure geometry — no device code.
"""

from __future__ import annotations

import numpy as np

from typing import Optional, Tuple, Union

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape"]


def broadcast_shape(shape_a: Tuple[int, ...], shape_b: Tuple[int, ...]) -> Tuple[int, ...]:
    """Broadcast shape of two operands per NumPy rules; raises ValueError on
    incompatibility (reference: stride_tricks.py:12)."""
    return broadcast_shapes(shape_a, shape_b)


def broadcast_shapes(*shapes: Tuple[int, ...]) -> Tuple[int, ...]:
    """Broadcast shape of N operands (reference: stride_tricks.py:70)."""
    try:
        return tuple(np.broadcast_shapes(*shapes))
    except ValueError:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}")


def sanitize_axis(
    shape: Tuple[int, ...], axis: Optional[Union[int, Tuple[int, ...]]]
) -> Optional[Union[int, Tuple[int, ...]]]:
    """Check axis validity against ``shape`` and normalize negatives
    (reference: stride_tricks.py:115)."""
    ndim = len(shape)

    if axis is None:
        return None

    if isinstance(axis, (list, tuple)):
        axes = tuple(int(a) for a in axis)
        out = []
        for a in axes:
            if not isinstance(a, (int, np.integer)):
                raise TypeError(f"axis must be None or int or tuple of ints, got {type(a)}")
            if a < -ndim or a >= max(ndim, 1):
                raise ValueError(f"axis {a} is out of bounds for {ndim}-dimensional array")
            out.append(a % ndim if ndim > 0 else 0)
        if len(set(out)) != len(out):
            raise ValueError("duplicate axes given")
        return tuple(out)

    if isinstance(axis, np.ndarray) and axis.ndim == 0:
        axis = int(axis)
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if ndim == 0:
        if axis not in (0, -1):
            raise ValueError(f"axis {axis} is out of bounds for 0-dimensional array")
        return 0
    if axis < -ndim or axis >= ndim:
        raise ValueError(f"axis {axis} is out of bounds for {ndim}-dimensional array")
    return axis % ndim


def sanitize_shape(shape: Union[int, Tuple[int, ...]], lval: int = 0) -> Tuple[int, ...]:
    """Verify and normalize a shape-like into a tuple of non-negative ints
    (reference: stride_tricks.py:186)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    shape = tuple(shape)
    out = []
    for dim in shape:
        if isinstance(dim, np.ndarray) and dim.ndim == 0:
            dim = dim.item()
        if not isinstance(dim, (int, np.integer)):
            raise TypeError(f"expected shape dimension to be integral, got {type(dim)}")
        dim = int(dim)
        if dim < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
        out.append(dim)
    return tuple(out)
