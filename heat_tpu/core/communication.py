"""Communication backend for heat_tpu.

The reference backs its distributed arrays with mpi4py: a 2063-line
``MPICommunication`` wrapping every MPI collective with torch-buffer
handling (/root/reference/heat/core/communication.py:115-1994). On TPU the
model is inverted: a **single controller** drives an entire slice; data
movement is expressed as GSPMD shardings on ``jax.Array`` plus XLA
collectives (``psum``/``all_gather``/``ppermute``/``all_to_all``) inside
``shard_map`` where the schedule *is* the algorithm. Consequently this
module provides

- ``MeshCommunication``: the communicator equivalent — wraps a 1-D
  ``jax.sharding.Mesh`` over the device population, computes chunk/
  sharding geometry (the analog of ``MPICommunication.chunk`` at
  communication.py:156 and ``counts_displs_shape`` at :215), and builds
  ``NamedSharding`` specs from a heat ``split`` axis;
- resharding helpers that subsume Heat's explicit collectives: what the
  reference does with ``Allgatherv`` (split→None, dndarray.py:1406) or
  ``Alltoallv`` (split→split) is here a ``jax.device_put`` onto a new
  sharding, lowered by XLA to the same collectives over ICI;
- module-level singletons ``MPI_WORLD``-style plus ``get_comm``/``use_comm``
  (reference communication.py:2008-2059).

Derived MPI datatypes for non-contiguous buffers, CUDA-awareness sniffing
and host-staging (reference communication.py:15-25, 245-456) have no
equivalent — XLA owns layout and transport.
"""

from __future__ import annotations

import dataclasses
import math
import re

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from typing import List, Optional, Tuple, Union

from . import gates as _gates
from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry
from ..observability.instrument import nbytes_of as _nbytes_of

__all__ = [
    "Communication",
    "DCN_BPS",
    "DCN_PENALTY",
    "ICI_BPS",
    "MeshCommunication",
    "MPICommunication",
    "MPI_WORLD",
    "MPI_SELF",
    "TOPOLOGY_ENV",
    "Topology",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "init_distributed",
    "topology_for",
]


# --------------------------------------------------------------------- #
# two-tier topology (ISSUE 8)                                           #
# --------------------------------------------------------------------- #
from . import tiers as _tiers

#: per-chip bidirectional ICI bandwidth (v5e, docs/PERF.md multi-chip
#: analytic model) — the intra-slice tier every earlier PR priced.
#: Since ISSUE 11 the number lives in the one memory-tier cost lattice
#: (``core.tiers``); re-exported here for the established import sites.
ICI_BPS = _tiers.ICI_BPS

#: per-chip DCN bandwidth across slices (~8x slower than ICI): the
#: inter-slice tier multi-slice deployments add. No DCN hardware is
#: attached to this container — the constant feeds the same analytic
#: model + HLO-census methodology the multichip work is pinned with.
DCN_BPS = _tiers.DCN_BPS

#: cost-model penalty of a DCN-tier byte relative to an ICI-tier byte
#: (= ICI_BPS / DCN_BPS = ``tiers.penalty("dcn")``). The redistribution
#: planner prices tier="dcn" collective steps with this multiplier so
#: the byte-equivalent cost scalar keeps one unit.
DCN_PENALTY = _tiers.penalty("dcn")

#: ``HEAT_TPU_TOPOLOGY``: ``auto`` (default — read ``slice_index`` off
#: the resolved world's devices; single-slice and CPU worlds stay flat),
#: ``SxC`` (e.g. ``2x8``: force a simulated two-tier factorization of an
#: S*C-device mesh — slices are assigned to contiguous mesh positions,
#: matching the slice-major device order ``_resolve_devices`` sorts
#: into), or ``flat``/``1xN`` (explicitly one ICI domain).
TOPOLOGY_ENV = "HEAT_TPU_TOPOLOGY"

_TOPOLOGY_RE = re.compile(r"^(\d+)\s*[xX]\s*(\d+)$")


@dataclasses.dataclass(frozen=True)
class Topology:
    """Two-tier factorization of a 1-D device mesh: ``n_slices`` ICI
    domains of ``chips_per_slice`` chips each, DCN between them.

    The mesh axis is slice-major (``_resolve_devices`` sorts by
    ``(slice_index, process, id)``), so slice ``s`` owns the contiguous
    mesh positions ``[s*chips_per_slice, (s+1)*chips_per_slice)`` and a
    mesh edge ``a -> b`` stays on ICI iff ``slice_of(a) == slice_of(b)``.
    ``n_slices == 1`` is the flat single-tier world every PR before
    ISSUE 8 assumed.
    """

    n_slices: int
    chips_per_slice: int

    @property
    def size(self) -> int:
        return self.n_slices * self.chips_per_slice

    @property
    def tiered(self) -> bool:
        """More than one slice — the DCN tier exists."""
        return self.n_slices > 1

    def slice_of(self, index: int) -> int:
        """Slice owning mesh position ``index``."""
        return int(index) // self.chips_per_slice

    def crosses(self, a: int, b: int) -> bool:
        """Does the mesh edge ``a -> b`` traverse DCN?"""
        return self.slice_of(a) != self.slice_of(b)

    def spans(self, indices) -> bool:
        """Does a replica group of mesh positions span more than one
        slice (i.e. would a flat collective over it ride DCN)?"""
        slices = {self.slice_of(i) for i in indices}
        return len(slices) > 1

    # ---------------------------------------------------------------- #
    # subgroup helpers (the shard_map axis_index_groups arguments)      #
    # ---------------------------------------------------------------- #
    def chip_axis_groups(self) -> List[List[int]]:
        """Intra-slice groups: one group of ``chips_per_slice``
        neighbors per slice — collectives over these never cross DCN."""
        C = self.chips_per_slice
        return [[s * C + c for c in range(C)] for s in range(self.n_slices)]

    def slice_axis_groups(self) -> List[List[int]]:
        """Inter-slice groups: the ``chips_per_slice`` groups of
        same-chip-position peers across slices — the minimal-width DCN
        exchange pattern (each group carries exactly one chip per
        slice)."""
        C = self.chips_per_slice
        return [[s * C + c for s in range(self.n_slices)] for c in range(C)]

    def bandwidth(self, tier: str) -> float:
        """Per-chip bytes/s of ``tier`` (``"ici"``/``"dcn"``) — the
        lattice edge price (``core.tiers.bandwidth``)."""
        if tier not in ("ici", "dcn"):
            raise KeyError(tier)
        return _tiers.bandwidth(tier)

    @classmethod
    def parse(cls, text: str) -> Optional["Topology"]:
        """``"2x8"`` -> Topology(2, 8); ``None`` for unparseable text."""
        m = _TOPOLOGY_RE.match(text.strip())
        if not m:
            return None
        s, c = int(m.group(1)), int(m.group(2))
        if s < 1 or c < 1:
            return None
        return cls(s, c)

    def __str__(self) -> str:
        return f"{self.n_slices}x{self.chips_per_slice}"


def _detect_slices(mesh_size: int) -> Topology:
    """``auto`` resolution: group the RESOLVED world's devices by
    ``slice_index`` (TPU pods expose it on multi-slice deployments).

    Reads only ``MPI_WORLD``'s already-resolved device list — never
    probes the platform itself, so the pure-Python contexts that plan
    without touching a device (``scripts/redist_plans.py``, golden-plan
    tests) stay device-free and the one-shot ``init_distributed`` lazy
    window is preserved. By the time any plan EXECUTES, the world is
    resolved and a real multi-slice deployment reports its tiers.
    """
    devs = MPI_WORLD._devices_  # None until the world resolves
    if not devs or len(devs) != mesh_size:
        return Topology(1, mesh_size)
    counts: dict = {}
    for d in devs:
        counts.setdefault(getattr(d, "slice_index", 0) or 0, 0)
        counts[getattr(d, "slice_index", 0) or 0] += 1
    sizes = set(counts.values())
    if len(counts) <= 1 or len(sizes) != 1:
        # single slice, or ragged slices the 2-tier factorization does
        # not model: flat (the ragged case cannot arise on real pods)
        return Topology(1, mesh_size)
    return Topology(len(counts), next(iter(sizes)))


def topology_for(mesh_size: int, override=None) -> Topology:
    """The :class:`Topology` governing a ``mesh_size``-device mesh.

    ``override`` wins when given: a :class:`Topology`, an ``"SxC"``
    string, or ``"flat"``. Otherwise ``HEAT_TPU_TOPOLOGY`` decides —
    ``auto`` (default) reads ``slice_index`` off the resolved world's
    devices (flat on CPU/single-slice), a forced ``SxC`` simulates that
    factorization. A forced product that does not equal ``mesh_size``
    resolves FLAT: a 2x8 setting over an 8-device test mesh must not
    invent a topology the devices cannot realize (the forced-topology CI
    leg uses 2x4 on the 8-device mesh for exactly this reason).
    """
    mesh_size = int(mesh_size)
    if override is not None:
        if isinstance(override, Topology):
            t = override
        elif str(override).strip().lower() in ("flat", "1", "none"):
            return Topology(1, mesh_size)
        else:
            t = Topology.parse(str(override))
            if t is None:
                raise ValueError(
                    f"unparseable topology {override!r} (expected 'SxC', "
                    "'flat', or a Topology)"
                )
        return t if t.size == mesh_size and t.tiered else Topology(1, mesh_size)
    raw = _gates.get(TOPOLOGY_ENV, "auto").strip().lower()
    if raw in ("", "auto"):
        return _detect_slices(mesh_size)
    if raw in ("flat", "1", "none", "off", "0"):
        return Topology(1, mesh_size)
    t = Topology.parse(raw)
    if t is None or t.size != mesh_size or not t.tiered:
        return Topology(1, mesh_size)
    return t


class Communication:
    """Base class for communicators (reference: communication.py:83)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def __init__(self) -> None:
        raise NotImplementedError()

    def chunk(self, shape, split) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        raise NotImplementedError()


def _platform_devices(device=None) -> list:
    from . import devices as _devices

    dev = _devices.sanitize_device(device)
    return dev.jax_devices()


def place(array: jax.Array, sharding) -> jax.Array:
    """``jax.device_put`` that stays correct under tracing. Inside a
    ``jax.jit`` trace (``ht.jit``, fused programs) ``jax.device_put`` on a
    Tracer is NOT a binding layout constraint — observed on jax 0.9: the
    requested sharding is silently ignored and GSPMD propagation picks its
    own layout, leaving DNDarray ``split`` metadata out of sync with the
    physical sharding. Under a trace this lowers to
    ``with_sharding_constraint`` (which IS binding); eagerly it is a plain
    ``device_put``."""
    if isinstance(array, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(array, sharding)
    return jax.device_put(array, sharding)


def jit_sharded_mesh(fn, mesh, sharding_thunk):
    """``jax.jit`` with ``out_shardings`` from ``sharding_thunk()`` — except
    on a ONE-device mesh, where the pin is a semantic no-op (committed
    array inputs already determine placement) and is dropped: passing
    ``out_shardings`` moves pjit dispatch off the C++ fast path (~114
    µs/call host-side vs ~9 µs measured on the v5e tunnel), which dominates
    short elementwise programs on the single chip. Callers whose programs
    have NO committed array inputs must not use this helper.
    """
    if mesh.devices.size == 1:
        return jax.jit(fn)
    return jax.jit(fn, out_shardings=sharding_thunk())


class MeshCommunication(Communication):
    """Single-controller communicator over a 1-D JAX device mesh.

    The mesh axis (default ``'d'``) is the axis heat's ``split`` dimension
    is sharded over. ``size`` is the number of shards (devices), the role
    MPI ranks play in the reference; ``rank`` is the *process* index and is
    0 on a single host — per-rank divergent control flow does not exist in
    this model.
    """

    # _ht_epoch: the elastic runtime's world-epoch stamp (ISSUE 13,
    # heat_tpu.resilience.elastic) — set only on communicators the
    # runtime binds; unset on every other comm, so the executor's
    # fence stays a getattr-default no-op
    __slots__ = ("_devices_", "_mesh", "axis_name", "_self_like", "_ht_epoch")

    def __init__(self, devices=None, axis_name: str = "d"):
        # device resolution is LAZY when no explicit devices are given:
        # probing the platform initializes the XLA backend, which must not
        # happen at import time (the world singletons are built then) or
        # jax.distributed.initialize can never run afterwards
        self.axis_name = axis_name
        if devices is None:
            self._devices_ = None
            self._mesh = None
        else:
            self._devices_ = list(devices)
            self._mesh = Mesh(np.array(self._devices_), (axis_name,))

    def _resolve_devices(self) -> list:
        # topology-aware order: group devices by (slice, host) so that the
        # 1-D mesh axis places same-slice neighbors adjacently — ring
        # collectives (ppermute halo/sort/attention schedules) then take
        # p−2 ICI hops and cross DCN only at slice boundaries, instead of
        # hopping DCN on every step of an arbitrary interleaving. TPU pods
        # expose ``slice_index`` on multi-slice deployments; single-slice
        # and CPU worlds sort to their existing order.
        devs = _platform_devices(None)
        return sorted(
            devs,
            key=lambda d: (
                getattr(d, "slice_index", 0) or 0,
                d.process_index,
                d.id,
            ),
        )

    def _ensure(self) -> None:
        if self._devices_ is None:
            self._devices_ = list(self._resolve_devices())
            self._mesh = Mesh(np.array(self._devices_), (self.axis_name,))

    @property
    def _devices(self) -> list:
        self._ensure()
        return self._devices_

    @property
    def mesh(self) -> Mesh:
        self._ensure()
        return self._mesh

    @property
    def size(self) -> int:
        """Number of shards (mesh size) — the analog of MPI comm size."""
        return len(self._devices)

    @property
    def rank(self) -> int:
        """Index of the controlling process (0 on a single host)."""
        return jax.process_index()

    def is_distributed(self) -> bool:
        return self.size > 1

    @property
    def devices(self) -> list:
        return list(self._devices)

    @property
    def topology(self) -> Topology:
        """The two-tier :class:`Topology` governing this mesh
        (``HEAT_TPU_TOPOLOGY``; flat on single-slice/CPU worlds). For
        the world communicator ``auto`` groups the resolved devices by
        ``slice_index``; sub-communicators of a tiered world resolve
        flat unless the env forces their factorization (a Split
        sub-group has no guaranteed slice alignment)."""
        return topology_for(self.size)

    # ------------------------------------------------------------------ #
    # chunk geometry                                                     #
    # ------------------------------------------------------------------ #
    def chunk(
        self, shape, split: Optional[int], rank: Optional[int] = None, w_size: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Calculate the shard of ``shape`` along ``split`` owned by device
        ``rank`` (default: device 0 of this process).

        Reference semantics (communication.py:156) give the first
        ``size % w`` ranks one extra element; XLA's GSPMD uses ceil-division
        blocks with a possibly short/empty tail. We follow the XLA
        convention so that ``chunk`` agrees exactly with the placement of
        ``jax.Array`` shards on the mesh.

        Returns (offset, local_shape, slices).
        """
        shape = tuple(int(s) for s in shape)
        size = self.size if w_size is None else w_size
        if rank is None:
            rank = 0
        if split is None or size == 1:
            return 0, shape, tuple(slice(0, s) for s in shape)
        split = split % len(shape)
        n = shape[split]
        block = -(-n // size)  # ceil division
        start = min(rank * block, n)
        end = min(start + block, n)
        lshape = list(shape)
        lshape[split] = end - start
        slices = tuple(
            slice(start, end) if i == split else slice(0, s) for i, s in enumerate(shape)
        )
        return start, tuple(lshape), slices

    def counts_displs_shape(
        self, shape, split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """Per-device counts and displacements along ``split`` plus the
        local shape of device 0 (reference: communication.py:215).
        """
        shape = tuple(int(s) for s in shape)
        n = shape[split]
        size = self.size
        block = -(-n // size)
        counts = tuple(max(0, min(n - r * block, block)) for r in range(size))
        displs = tuple(min(r * block, n) for r in range(size))
        _, lshape, _ = self.chunk(shape, split)
        return counts, displs, lshape

    def lshape_map(self, gshape, split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of every device's local shard shape — the
        analog of ``DNDarray.create_lshape_map`` (reference dndarray.py:646)
        computed from geometry instead of an Allreduce.
        """
        gshape = tuple(int(s) for s in gshape)
        out = np.tile(np.array(gshape, dtype=np.int64), (self.size, 1))
        if split is not None and len(gshape) > 0:
            counts, _, _ = self.counts_displs_shape(gshape, split % len(gshape))
            out[:, split % len(gshape)] = np.array(counts, dtype=np.int64)
        return out

    # ------------------------------------------------------------------ #
    # sharding construction                                              #
    # ------------------------------------------------------------------ #
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """PartitionSpec placing ``split`` on the mesh axis."""
        if split is None or ndim == 0:
            return PartitionSpec()
        split = split % ndim
        return PartitionSpec(*(self.axis_name if i == split else None for i in range(ndim)))

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """NamedSharding for an ``ndim``-dimensional array split along
        ``split`` — the declarative replacement for the reference's entire
        buffer-distribution machinery.
        """
        return NamedSharding(self.mesh, self.spec(ndim, split))

    def jit_sharded(self, fn, ndim: int, split: Optional[int]):
        """``jax.jit(fn)`` with the output sharding pinned for this mesh.
        ONLY for programs whose array inputs are committed to this mesh's
        devices (every op wrapper: the physical operands pin placement).
        Zero-array-input builders (factories/random) must keep
        ``out_shardings`` unconditionally instead.
        """
        return jit_sharded_mesh(fn, self.mesh, lambda: self.sharding(ndim, split))

    def shard(self, array: jax.Array, split: Optional[int]) -> jax.Array:
        """Lay a LOGICAL ``array`` out on the mesh according to ``split``,
        zero-padding the split dimension up to a mesh multiple first
        (see ``_padding``). Returns the physical array.

        This one call subsumes the reference's ``resplit_`` collectives
        (dndarray.py:1406-1535): split→None lowers to all-gather, None→split
        to a local slice, split→split to an all-to-all — all emitted by XLA.
        """
        from . import _padding

        if _telemetry._ENABLED:
            # metadata only (trace-safe); under a trace this fires once
            # per compile, which the event records
            nbytes = _nbytes_of(array.shape, array.dtype)
            _telemetry.inc("comm.shard.calls")
            _telemetry.inc("comm.shard.bytes", nbytes)
            _obs_events.emit(
                "comm.shard",
                shape=tuple(int(s) for s in array.shape),
                split=split,
                bytes=nbytes,
                traced=isinstance(array, jax.core.Tracer),
            )
        if split is not None:
            split = split % max(array.ndim, 1)
            if array.shape[split] == 0:
                # zero-extent split axis: nothing to distribute, store replicated
                return place(array, self.sharding(array.ndim, None))
            array = _padding.pad_logical(array, split, self.size)
        return place(array, self.sharding(array.ndim, split))

    def reshard_phys(
        self, phys: jax.Array, gshape, old_split: Optional[int], new_split: Optional[int]
    ) -> jax.Array:
        """Move a physical array from one split layout to another (the
        whole of the reference's split→split Isend/Irecv tiling,
        dndarray.py:1406). Routed through the redistribution planner
        (``heat_tpu.redistribution``): the movement is normalized to a
        :class:`~heat_tpu.redistribution.spec.RedistSpec`, planned under
        the peak-memory budget, and executed as the planned collective
        schedule (``HEAT_TPU_REDIST_PLANNER=0`` restores the legacy
        single device_put)."""
        if _telemetry._ENABLED:
            # the moved volume is the LOGICAL payload (every byte crosses
            # the mesh on a split change; pad rows are manufactured)
            moved = _nbytes_of(gshape, phys.dtype)
            _telemetry.inc("comm.reshard.calls")
            _telemetry.inc("comm.reshard.bytes", moved)
            _obs_events.emit(
                "comm.reshard",
                gshape=tuple(int(s) for s in gshape),
                old_split=old_split,
                new_split=new_split,
                bytes_moved=moved,
                traced=isinstance(phys, jax.core.Tracer),
            )
        from ..redistribution import executor as _redist_exec

        return _redist_exec.resplit_phys(self, phys, gshape, old_split, new_split)

    # ------------------------------------------------------------------ #
    # communicator management                                            #
    # ------------------------------------------------------------------ #
    def Split(self, color=0, key=0):
        """MPI ``Comm.Split`` with faithful semantics, adapted to the
        single-controller model (reference wraps mpi4py's Split). In MPI
        every rank passes its own ``(color, key)``; ranks sharing a color
        form a sub-communicator ordered by ``(key, old rank)``. Here ONE
        controller owns every device, so the caller passes the full
        per-device vectors:

        - ``color``: int → all devices share it (an MPI all-same-color
          Split, i.e. a dup): returns one ``MeshCommunication``.
        - ``color``: sequence of ints, one per device → returns a dict
          ``{color: MeshCommunication}``, each group's devices ordered by
          ``(key[i], i)``; ``key`` may be a scalar or a per-device
          sequence. Devices with negative color (MPI_UNDEFINED analog)
          join no group.
        """
        size = self.size
        if isinstance(color, (int, np.integer)):
            return MeshCommunication(self._devices, self.axis_name)
        colors = [int(c) for c in color]
        if len(colors) != size:
            raise ValueError(f"color vector must have one entry per device ({size}), got {len(colors)}")
        if isinstance(key, (int, np.integer)):
            keys = [int(key)] * size
        else:
            keys = [int(k) for k in key]
            if len(keys) != size:
                raise ValueError(f"key vector must have one entry per device ({size}), got {len(keys)}")
        groups = {}
        for i, c in enumerate(colors):
            if c < 0:
                continue
            groups.setdefault(c, []).append(i)
        return {
            c: MeshCommunication(
                [self._devices[i] for i in sorted(idx, key=lambda i: (keys[i], i))],
                self.axis_name,
            )
            for c, idx in groups.items()
        }

    def __repr__(self) -> str:
        # must NOT resolve devices: a debug print before init_distributed
        # would otherwise initialize the backend and consume the one-shot
        # lazy window
        if self._devices_ is None:
            return f"MeshCommunication(unresolved, axis={self.axis_name!r})"
        return f"MeshCommunication(size={self.size}, axis={self.axis_name!r}, platform={self._devices_[0].platform if self._devices_ else '-'})"


# reference-compatible alias: programs written against the reference name
MPICommunication = MeshCommunication


# lru-cached program builders whose entries bake mesh geometry in
# (out_shardings, shard_map meshes, comm identity). A world rebuild
# (init_distributed) must clear them or pre-init configurations would
# silently reuse programs placed on the defunct single-host mesh.
_MESH_KEYED_CACHES = []


def register_mesh_cache(cached_fn) -> None:
    """Register a functools.lru_cache-wrapped program builder keyed (in
    part) on a mesh/comm; cleared when the world communicator changes."""
    _MESH_KEYED_CACHES.append(cached_fn)


def _clear_mesh_caches() -> None:
    for fn in _MESH_KEYED_CACHES:
        fn.cache_clear()


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> MeshCommunication:
    """Multi-host bootstrap — the single-controller replacement for the
    reference's ``mpirun -n N`` world creation (communication.py:2012).

    Where Heat relies on MPI to spawn one rank per process and wires them
    with mpi4py, the TPU runtime runs ONE controller per host:
    ``jax.distributed.initialize`` connects the hosts (args can also come
    from the cluster environment: TPU pods auto-detect all four), after
    which ``jax.devices()`` spans every host's chips and the world
    communicator's mesh covers the full slice — collectives ride ICI
    within a slice and DCN across slices. Call this ONCE, before any array
    creation, on every host; each host then runs the SAME program
    (SPMD single-controller-per-host, not rank-divergent control flow).

    Returns the rebuilt world communicator (also installed as the global
    default, so ``ht.array(..., split=0)`` shards over all hosts).
    """
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "must be called before" in str(e):
            raise RuntimeError(
                "init_distributed must run before any array/device use: the "
                "world and device registry are lazy precisely so that "
                "`import heat_tpu as ht; ht.core.communication."
                "init_distributed(...)` works as the FIRST call — something "
                "touched the backend earlier in this process"
            ) from e
        raise

    # rebuild the world IN PLACE: star-imported copies of MPI_WORLD
    # (heat_tpu.MPI_WORLD, pre-init local references) must all observe the
    # new global device set — rebinding the module global would leave them
    # pointing at the stale single-host world
    MPI_WORLD.__init__()
    MPI_SELF.__init__()
    # compiled programs built before init baked the old mesh into their
    # out_shardings / shard_map meshes — drop them
    _clear_mesh_caches()

    global __default_comm
    __default_comm = MPI_WORLD
    return MPI_WORLD


class _SelfCommunication(MeshCommunication):
    """Single-device communicator — the analog of MPI_COMM_SELF."""

    def __init__(self):
        super().__init__(None)  # lazy, like the world

    def _resolve_devices(self) -> list:
        import jax as _jax

        devs = _platform_devices(None)
        # in a multi-process world jax.devices()[0] belongs to process 0;
        # MPI_COMM_SELF must be THIS process's device
        proc = _jax.process_index()
        local = [d for d in devs if d.process_index == proc]
        return (local or devs)[:1]


def _build_world() -> MeshCommunication:
    return MeshCommunication()


MPI_WORLD: MeshCommunication = _build_world()
"""Communicator spanning all devices of the default platform
(reference: communication.py:2012)."""

MPI_SELF: MeshCommunication = _SelfCommunication()
"""Single-device communicator (reference: communication.py:2013)."""

__default_comm = MPI_WORLD


def get_comm() -> MeshCommunication:
    """Retrieve the globally set default communicator
    (reference: communication.py:2019)."""
    return __default_comm


def use_comm(comm: Optional[MeshCommunication] = None) -> None:
    """Set the globally used default communicator
    (reference: communication.py:2049)."""
    global __default_comm
    if comm is None:
        comm = MPI_WORLD
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    __default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> MeshCommunication:
    """Sanitize a communicator or return the global default."""
    if comm is None:
        return get_comm()
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    return comm
