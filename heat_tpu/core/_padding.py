"""Pad-and-mask machinery for uneven shards.

GSPMD requires the sharded dimension extent to be divisible by the mesh
axis size; the reference instead gives the first ``size % w`` MPI ranks one
extra element (communication.py:156). The TPU-native resolution is the
standard pad-and-mask idiom: the *physical* array carries a zero-filled
tail along ``split`` rounded up to a mesh multiple, while all metadata
(``gshape``) stays logical. Invariant maintained throughout the framework:
**the pad region of every DNDarray's physical array is zero.** Sum-like
contractions (matmul, sum) are then pad-safe for free; other reductions
refill the pad with their neutral element first; exports slice the pad off.

Divisible shapes take none of these paths — zero overhead on the shapes
benchmarks use.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple

__all__ = [
    "phys_shape",
    "pad_extent",
    "pad_logical",
    "unpad",
    "mask_phys",
    "mask_tail",
    "valid_mask",
]


def pad_extent(n: int, size: int) -> int:
    """Physical extent: n rounded up to a multiple of ``size``."""
    if size <= 1 or n == 0:
        return n
    return -(-n // size) * size


def phys_shape(gshape: Tuple[int, ...], split: Optional[int], size: int) -> Tuple[int, ...]:
    """Physical (padded) shape for a logical global shape."""
    if split is None or not gshape:
        return tuple(gshape)
    out = list(gshape)
    out[split] = pad_extent(out[split], size)
    return tuple(out)


def pad_logical(arr: jax.Array, split: Optional[int], size: int, fill=0) -> jax.Array:
    """Zero-pad a logical array along ``split`` up to the physical extent."""
    if split is None:
        return arr
    n = arr.shape[split]
    target = pad_extent(n, size)
    if target == n:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[split] = (0, target - n)
    return jnp.pad(arr, widths, constant_values=fill)


def unpad(arr: jax.Array, gshape: Tuple[int, ...], split: Optional[int]) -> jax.Array:
    """Slice the logical region out of a physical array."""
    if split is None:
        return arr
    n = gshape[split]
    if arr.shape[split] == n:
        return arr
    sl = [slice(None)] * arr.ndim
    sl[split] = slice(0, n)
    return arr[tuple(sl)]


def valid_mask(phys: jax.Array, gshape: Tuple[int, ...], split: Optional[int]) -> Optional[jax.Array]:
    """Boolean mask of the logical region, or None when nothing is padded."""
    if split is None:
        return None
    n = gshape[split]
    if phys.shape[split] == n:
        return None
    iota = jax.lax.broadcasted_iota(jnp.int32, phys.shape, split)
    return iota < n


def mask_phys(phys: jax.Array, gshape: Tuple[int, ...], split: Optional[int], fill=0) -> jax.Array:
    """Overwrite the pad region with ``fill`` (restores the zero-pad
    invariant, or installs a reduction-neutral element)."""
    if split is None or phys.shape[split] == gshape[split]:
        return phys
    return mask_tail(phys, split, gshape[split], fill)


def mask_tail(arr: jax.Array, split: int, n: int, fill=0) -> jax.Array:
    """Fill positions >= ``n`` along ``split`` (the pad region) with
    ``fill`` — traceable, fuses into a surrounding jitted program. The
    n-based core of ``mask_phys`` for callers that track the logical
    extent directly."""
    iota = jax.lax.broadcasted_iota(jnp.int32, arr.shape, split)
    return jnp.where(iota < n, arr, jnp.asarray(fill, dtype=arr.dtype))
