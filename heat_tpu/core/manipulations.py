"""Array manipulation operations.

API parity with /root/reference/heat/core/manipulations.py (37 exports;
the comm-heaviest module of the reference with 26 collective call-sites:
``concatenate`` at manipulations.py:390 harmonizes splits + redistributes,
``reshape`` at :1994 repartitions via Alltoallv with a ``new_split`` kw,
``sort`` at :2428 is a distributed sample-sort with an Alltoallv partition
exchange, ``unique`` at :3202, ``topk`` at :3981, ``roll`` at :2156,
``pad`` at :1328). Here each op computes on the logical global array and
re-establishes the output sharding; XLA emits the data movement (the
all-to-all a reshape-with-new-split needs) over ICI. ``sort`` along the
split axis runs ``core.parallel.distributed_sort`` — an odd-even block
merge-split network of ``ppermute`` exchanges (gather-free); off-split
sorts are lane-local XLA sorts.
"""

from __future__ import annotations

import functools as _functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Iterable, List, Optional, Sequence, Tuple, Union

from . import types
from . import _operations
from .communication import sanitize_comm
from .dndarray import DNDarray
from .sanitation import sanitize_in, sanitize_sequence
from .stride_tricks import broadcast_shape, sanitize_axis, sanitize_shape

__all__ = [
    "balance",
    "broadcast_arrays",
    "broadcast_to",
    "collect",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "unique",
    "vsplit",
    "vstack",
]


def _wrap(result: jax.Array, split: Optional[int], ref: DNDarray, dtype=None) -> DNDarray:
    """Construct an output DNDarray: capture logical shape, shard, wrap."""
    gshape = tuple(int(s) for s in result.shape)
    if split is not None and result.ndim > 0:
        split = split % result.ndim
        result = ref.comm.shard(result, split)
    else:
        split = None
    return DNDarray(
        result,
        gshape,
        dtype if dtype is not None else types.canonical_heat_type(result.dtype),
        split,
        ref.device,
        ref.comm,
    )


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Out-of-place balance (reference: manipulations.py balance). GSPMD
    layouts are canonical — returns the array (or a copy)."""
    sanitize_in(array)
    if copy:
        from . import memory

        return memory.copy(array)
    return array


def broadcast_arrays(*arrays: DNDarray) -> List[DNDarray]:
    """Broadcast arrays against each other (reference: manipulations.py
    broadcast_arrays)."""
    if not arrays:
        return []
    for a in arrays:
        sanitize_in(a)
    target = broadcast_shape(*[a.shape for a in arrays]) if len(arrays) > 1 else arrays[0].shape
    return [broadcast_to(a, target) for a in arrays]


def broadcast_to(x: DNDarray, shape: Tuple[int, ...]) -> DNDarray:
    """Broadcast to a new shape (reference: manipulations.py broadcast_to)."""
    sanitize_in(x)
    shape = sanitize_shape(shape)
    result = jnp.broadcast_to(x.larray, shape)
    split = x.split
    if split is not None:
        split = split + (len(shape) - x.ndim)
    return _wrap(result, split, x, dtype=x.dtype)


def collect(arr: DNDarray, target_rank: int = 0) -> DNDarray:
    """Gather the whole array onto one device (reference: manipulations.py
    collect / dndarray.collect_)."""
    sanitize_in(arr)
    out = arr.__copy__()
    out.collect_(target_rank)
    return out


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns (reference: manipulations.py
    column_stack)."""
    arrays = sanitize_sequence(arrays)
    ref = arrays[0]
    result = jnp.column_stack([a.larray for a in arrays])
    split = ref.split if ref.ndim >= 2 else (0 if ref.split is not None else None)
    return _wrap(result, split, ref)


@_functools.lru_cache(maxsize=1024)
def _concat_program(comm, metas, axis, out_split, jdtype):
    """One compiled program for concatenate: per-input unpad + cast →
    concatenate → output pad, out-sharding pinned (the reference's split
    harmonization + redistribution, manipulations.py:390, fused)."""
    from . import _padding

    def fn(*phys):
        logicals = [
            _padding.unpad(p_, gshape, split).astype(jnp.dtype(jdtype))
            for p_, (gshape, split) in zip(phys, metas)
        ]
        r = jnp.concatenate(logicals, axis=axis)
        return _padding.pad_logical(r, out_split, comm.size)

    ndim = len(metas[0][0])
    return comm.jit_sharded(fn, ndim, out_split)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference: manipulations.py:390
    — split harmonization + redistribution; here jnp.concatenate on the
    logical arrays + one resharding)."""
    arrays = sanitize_sequence(arrays)
    if len(arrays) < 1:
        raise ValueError("need at least one array to concatenate")
    for a in arrays:
        sanitize_in(a)
    ref = arrays[0]
    axis = sanitize_axis(ref.shape, axis)
    if any(a._is_planar for a in arrays):
        from . import complex_planar as _cp

        return _cp.concat(arrays, axis)
    out_dtype = arrays[0].dtype
    for a in arrays[1:]:
        out_dtype = types.promote_types(out_dtype, a.dtype)
    jt = out_dtype.jax_type()
    split = next((a.split for a in arrays if a.split is not None), None)
    if (
        split is not None
        and all(x.ndim == ref.ndim for x in arrays)
        and all(x.size != 0 for x in arrays)
    ):
        out_shape = list(ref.shape)
        out_shape[axis] = sum(a.shape[axis] for a in arrays)
        metas = tuple((a.gshape, a.split) for a in arrays)
        prog = _concat_program(ref.comm, metas, axis, split, np.dtype(jt).name)
        phys = prog(*[a._phys for a in arrays])
        return DNDarray(phys, tuple(out_shape), out_dtype, split, ref.device, ref.comm)
    result = jnp.concatenate([a.larray.astype(jt) for a in arrays], axis=axis)
    return _wrap(result, split, ref, dtype=out_dtype)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract or construct a diagonal (reference: manipulations.py diag)."""
    sanitize_in(a)
    if a.ndim == 1:
        result = jnp.diag(a.larray, k=offset)
        split = a.split
        return _wrap(result, split, a, dtype=a.dtype)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Return the diagonal along dim1/dim2 (reference: manipulations.py
    diagonal)."""
    sanitize_in(a)
    if a.ndim < 2:
        raise ValueError("diagonal requires at least 2 dimensions")
    result = jnp.diagonal(a.larray, offset=offset, axis1=dim1, axis2=dim2)
    ax = sanitize_axis(a.shape, (dim1, dim2))
    split = a.split
    if split is not None:
        if split in ax:
            split = result.ndim - 1
        else:
            split = split - sum(1 for x in ax if x < split)
    return _wrap(result, split, a, dtype=a.dtype)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 2 (reference: manipulations.py dsplit)."""
    return split(x, indices_or_sections, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a new axis (reference: manipulations.py expand_dims)."""
    sanitize_in(a)
    axis = sanitize_axis(tuple(a.shape) + (1,), axis)
    if a._is_planar:
        from . import complex_planar as _cp

        return _cp.expand_dims(a, axis)
    result = jnp.expand_dims(a.larray, axis)
    split = a.split
    if split is not None and axis <= split:
        split += 1
    return _wrap(result, split, a, dtype=a.dtype)


def flatten(a: DNDarray) -> DNDarray:
    """Collapse into one dimension (reference: manipulations.py flatten —
    resplits to 0)."""
    sanitize_in(a)
    if a._is_planar:
        from . import complex_planar as _cp

        return _cp.flatten(a)
    result = jnp.ravel(a.larray)
    split = 0 if a.split is not None else None
    return _wrap(result, split, a, dtype=a.dtype)


def flip(a: DNDarray, axis: Optional[Union[int, Tuple[int, ...]]] = None) -> DNDarray:
    """Reverse element order along axis (reference: manipulations.py flip)."""
    sanitize_in(a)
    axis = sanitize_axis(a.shape, axis)
    if a._is_planar:
        from . import complex_planar as _cp

        return _cp.flip(a, axis)
    result = jnp.flip(a.larray, axis=axis)
    return _wrap(result, a.split, a, dtype=a.dtype)


def fliplr(a: DNDarray) -> DNDarray:
    """Flip along axis 1."""
    if a.ndim < 2:
        raise IndexError("expected at least 2-dimensional input")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """Flip along axis 0."""
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split horizontally (reference: manipulations.py hsplit)."""
    if x.ndim < 2:
        return split(x, indices_or_sections, axis=0)
    return split(x, indices_or_sections, axis=1)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack horizontally (reference: manipulations.py hstack)."""
    arrays = sanitize_sequence(arrays)
    axis = 0 if arrays[0].ndim == 1 else 1
    return concatenate(arrays, axis=axis)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference: manipulations.py moveaxis)."""
    sanitize_in(x)
    if isinstance(source, int):
        source = (source,)
    if isinstance(destination, int):
        destination = (destination,)
    source = [sanitize_axis(x.shape, s) for s in source]
    destination = [sanitize_axis(x.shape, d) for d in destination]
    if len(source) != len(destination):
        raise ValueError("source and destination must have the same number of elements")
    perm = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        perm.insert(dest, src)
    from .linalg import transpose

    return transpose(x, perm)


def pad(
    array: DNDarray,
    pad_width,
    mode: str = "constant",
    constant_values=0,
) -> DNDarray:
    """Pad the array (reference: manipulations.py:1328)."""
    sanitize_in(array)
    if mode not in ("constant",):
        raise NotImplementedError(f"pad mode {mode!r} not supported (reference supports constant)")
    # normalize pad_width like numpy/reference
    if isinstance(pad_width, int):
        widths = [(pad_width, pad_width)] * array.ndim
    else:
        pw = list(pad_width)
        if len(pw) and isinstance(pw[0], int):
            if len(pw) == 1:
                widths = [(pw[0], pw[0])] * array.ndim
            elif len(pw) == 2 and array.ndim == 1:
                widths = [tuple(pw)]
            else:
                raise ValueError(f"invalid pad_width {pad_width}")
        else:
            widths = [tuple(p) if not isinstance(p, int) else (p, p) for p in pw]
            if len(widths) == 1:
                widths = widths * array.ndim
            elif len(widths) < array.ndim:
                # reference pads trailing dimensions
                widths = [(0, 0)] * (array.ndim - len(widths)) + widths
    result = jnp.pad(array.larray, widths, constant_values=constant_values)
    return _wrap(result, array.split, array, dtype=array.dtype)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten (view semantics where possible; reference:
    manipulations.py ravel)."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference: manipulations.py redistribute).
    GSPMD layouts are canonical — validates and returns a copy."""
    sanitize_in(arr)
    out = arr.__copy__()
    out.redistribute_(lshape_map=lshape_map, target_map=target_map)
    return out


def repeat(a, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference: manipulations.py repeat)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if isinstance(repeats, DNDarray):
        repeats = repeats.larray
    elif isinstance(repeats, (list, tuple, np.ndarray)):
        repeats = jnp.asarray(np.asarray(repeats))
    result = jnp.repeat(a.larray, repeats, axis=axis)
    if axis is None:
        split = 0 if a.split is not None else None
    else:
        split = a.split
    return _wrap(result, split, a, dtype=a.dtype)


@_functools.lru_cache(maxsize=1024)
def _reshape_program(comm, in_gshape, in_split, out_shape, out_split):
    """LEGACY reshape-with-repartition program (one monolithic
    unpad → reshape → pad with the output sharding pinned — XLA chose
    the collective, a full all-gather for the split-1 case). Kept as the
    ``HEAT_TPU_REDIST_PLANNER=0`` escape hatch; the live path plans a
    bounded-footprint schedule via ``heat_tpu.redistribution``."""
    from . import _padding

    def fn(phys):
        logical = _padding.unpad(phys, in_gshape, in_split)
        r = jnp.reshape(logical, out_shape)
        return _padding.pad_logical(r, out_split, comm.size)

    return comm.jit_sharded(fn, len(out_shape), out_split)


def _normalize_reshape_args(a, shape, new_split):
    """Shared shape/-1/``new_split`` resolution for :func:`reshape` AND
    ``ht.redistribution.explain(reshape=...)`` — ONE resolver, so the
    plan ``explain`` shows is built from exactly the (shape, new_split)
    the public call executes."""
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = list(shape)
    # resolve -1 placeholder
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError("can only specify one unknown dimension")
    if neg:
        known = int(np.prod([s for s in shape if s != -1])) if len(shape) > 1 else 1
        if known == 0 or a.size % known != 0:
            raise ValueError(f"cannot reshape array of size {a.size} into shape {tuple(shape)}")
        shape[neg[0]] = a.size // known
    shape = sanitize_shape(tuple(shape))
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {tuple(shape)}")
    if new_split is None:
        new_split = a.split
        if new_split is not None and new_split >= len(shape):
            # fewer output dims than the old split axis: clamp to the last
            new_split = len(shape) - 1
    return shape, sanitize_axis(shape, new_split)


def reshape(a: DNDarray, *shape, **kwargs) -> DNDarray:
    """Reshape without changing data (reference: manipulations.py:1994 —
    Alltoallv repartition with ``new_split`` kw; one jitted
    reshape+repartition program, the all-to-all emitted by XLA)."""
    sanitize_in(a)
    new_split = kwargs.pop("new_split", None)
    if kwargs:
        raise TypeError(f"reshape got unexpected keyword arguments {list(kwargs)}")
    shape, new_split = _normalize_reshape_args(a, shape, new_split)
    if a._is_planar:
        from . import complex_planar as _cp

        return _cp.reshape(a, tuple(shape), new_split)
    if new_split is not None and len(shape) > 0 and a.ndim > 0 and a.size != 0:
        # zero-SIZE arrays take the eager path: XLA stores them replicated,
        # which a pinned out_sharding cannot express
        from .. import redistribution as _redist

        if _redist.planner_enabled():
            # planner-routed repartition (cost-modeled schedule: split-0
            # pivot / lane-packed pivot / chunked all-to-all instead of
            # the monolithic gather — narrow-minor-dim targets run their
            # relayout copies on packed full-lane buffers via
            # heat_tpu.kernels.relayout, HEAT_TPU_RELAYOUT_KERNEL
            # gating the tiled-copy kernel);
            # ht.redistribution.explain(a, reshape=shape, new_split=...)
            # shows the chosen plan
            phys = _redist.reshape_phys(
                a.comm, a._phys, a.gshape, a.split, tuple(shape), new_split
            )
        else:
            prog = _reshape_program(a.comm, a.gshape, a.split, tuple(shape), new_split)
            phys = prog(a._phys)
        return DNDarray(phys, tuple(shape), a.dtype, new_split, a.device, a.comm)
    result = jnp.reshape(a.larray, shape)
    return _wrap(result, new_split, a, dtype=a.dtype)


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place resplit (reference: manipulations.py:3479)."""
    sanitize_in(arr)
    return arr.resplit(axis)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Roll elements along axis (reference: manipulations.py:2156 — ring
    Isend/Irecv; here jnp.roll, the ppermute emitted by XLA)."""
    sanitize_in(x)
    if x._is_planar:
        from . import complex_planar as _cp

        return _cp.roll(x, shift, axis)
    result = jnp.roll(x.larray, shift, axis=axis)
    return _wrap(result, x.split, x, dtype=x.dtype)


def rot90(m: DNDarray, k: int = 1, axes: Sequence[int] = (0, 1)) -> DNDarray:
    """Rotate 90° in the axes plane (reference: manipulations.py rot90)."""
    sanitize_in(m)
    axes = tuple(axes)
    if len(axes) != 2 or axes[0] == axes[1]:
        raise ValueError("len(axes) must be 2 with distinct elements")
    ax = sanitize_axis(m.shape, axes)
    if m._is_planar:
        from . import complex_planar as _cp

        return _cp.rot90(m, k, ax)
    result = jnp.rot90(m.larray, k=k, axes=axes)
    split = m.split
    if split is not None and k % 2 == 1 and split in ax:
        # the two plane axes swap extents
        split = ax[0] if split == ax[1] else ax[1]
    return _wrap(result, split, m, dtype=m.dtype)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack rows (reference: manipulations.py row_stack)."""
    return vstack(arrays)


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape (reference: manipulations.py shape)."""
    sanitize_in(a)
    return a.gshape


def _takes_distributed_sort(a: DNDarray, axis: int) -> bool:
    return (
        a.split is not None
        and axis == a.split
        and a.comm.size > 1
        and a.dtype not in (types.complex64, types.complex128)
    )


def _sort_sentinel_fill(a: DNDarray, axis: int) -> jax.Array:
    """Physical array with pad rows set to the dtype's maximal sentinel so
    they sink to the global tail (= canonical pad location) during a
    distributed sort. NaN sorts after +inf in XLA's total order; real NaNs
    stay ahead of pads (position tie-break / stable order)."""
    from . import _padding

    phys = a._phys
    if phys.shape[axis] == a.gshape[axis]:
        return phys
    jt = a.dtype.jax_type()
    if jnp.issubdtype(jt, jnp.floating):
        sentinel = jnp.nan
    elif jnp.issubdtype(jt, jnp.bool_):
        sentinel = True
    else:
        sentinel = jnp.iinfo(jt).max
    return _padding.mask_phys(phys, a.gshape, axis, fill=sentinel)


def _sorted_values(a: DNDarray, axis: int):
    """Gather-free sorted VALUES along the split axis, or None when the
    layout doesn't admit it. Runs the half-traffic values-only program
    (no index operand in the ppermutes) — the percentile/median hot path."""
    if not _takes_distributed_sort(a, axis):
        return None
    from . import _padding
    from . import parallel

    phys = _sort_sentinel_fill(a, axis)
    sv = parallel.distributed_sort(
        phys, a.comm.mesh, a.comm.axis_name, axis, with_indices=False
    )
    sv = _padding.mask_phys(sv, a.gshape, axis, 0)
    return DNDarray(sv, a.gshape, a.dtype, axis, a.device, a.comm)


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along an axis; returns (values, indices) (reference:
    manipulations.py:2428 — distributed sample-sort with Alltoallv).

    When the sort axis IS the split axis and the mesh has >1 device, this
    runs ``parallel.distributed_sort`` — an odd-even block merge-split
    network of ``ppermute`` exchanges that never gathers the array (the
    explicit-SPMD replacement for the reference's Alltoallv sample-sort).
    Otherwise (non-split axis: every lane is shard-local) XLA's sort on
    the sharded array is already collective-free.
    """
    sanitize_in(a)
    if a._is_planar:
        from . import complex_planar as _cp

        raise _cp.policy_error("ht.sort on a complex array (complex has no total order)")
    axis = sanitize_axis(a.shape, axis)
    if axis is None:
        axis = a.ndim - 1
    if _takes_distributed_sort(a, axis):
        from . import _padding
        from . import parallel

        phys = _sort_sentinel_fill(a, axis)
        sv, si = parallel.distributed_sort(phys, a.comm.mesh, a.comm.axis_name, axis)
        sv = _padding.mask_phys(sv, a.gshape, axis, 0)
        si = _padding.mask_phys(si.astype(types.index_jax_type()), a.gshape, axis, 0)
        vals = DNDarray(sv, a.gshape, a.dtype, axis, a.device, a.comm)
        idx = DNDarray(si, a.gshape, types.canonical_heat_type(si.dtype), axis, a.device, a.comm)
        if descending:
            vals, idx = flip(vals, axis), flip(idx, axis)
    elif a.dtype in (types.complex64, types.complex128):
        # lax.sort has no complex key support — the two-pass path stays
        arr = a.larray
        indices = jnp.argsort(arr, axis=axis, descending=descending, stable=True)
        values = jnp.take_along_axis(arr, indices, axis=axis)
        vals = _wrap(values, a.split, a, dtype=a.dtype)
        idx = _wrap(indices.astype(types.index_jax_type()), a.split, a)
    else:
        # the fused values+argsort local sort (heat_tpu.kernels.sort):
        # ONE pass returning values AND stable argsort indices together —
        # argsort + take_along_axis costs a second sort-sized gather pass
        # (measured 3.2x the sort floor on v5e), and stable-DESCENDING
        # rides the same single pass on the complemented key transform
        # (the old two-pass "keep tie order" route is gone). Kernel paths
        # (radix / blocked columnsort) engage behind capability gates
        # with lax.sort as the oracle; HEAT_TPU_SORT_KERNEL=0 forces the
        # oracle everywhere.
        from .. import kernels as _kernels

        values, indices = _kernels.local_sort(
            a.larray, axis=axis, descending=descending
        )
        vals = _wrap(values, a.split, a, dtype=a.dtype)
        idx = _wrap(indices.astype(types.index_jax_type()), a.split, a)
    if out is not None:
        out.larray = vals.larray
        return out, idx
    return vals, idx


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference: manipulations.py split)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.numpy()
    if isinstance(indices_or_sections, (list, tuple, np.ndarray)):
        sections = [int(i) for i in np.asarray(indices_or_sections).ravel()]
        parts = jnp.split(x.larray, sections, axis=axis)
    else:
        n = int(indices_or_sections)
        if x.shape[axis] % n != 0:
            raise ValueError("array split does not result in an equal division")
        parts = jnp.split(x.larray, n, axis=axis)
    return [_wrap(p, x.split, x, dtype=x.dtype) for p in parts]


def squeeze(x: DNDarray, axis: Optional[Union[int, Tuple[int, ...]]] = None) -> DNDarray:
    """Remove size-1 dimensions (reference: manipulations.py squeeze)."""
    sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        axes = tuple(i for i, s in enumerate(x.shape) if s == 1)
    else:
        axes = (axis,) if isinstance(axis, int) else axis
        for ax in axes:
            if x.shape[ax] != 1:
                raise ValueError(
                    f"Dimension along axis {ax} is not 1 for shape {x.shape}"
                )
    if x._is_planar:
        from . import complex_planar as _cp

        return _cp.squeeze(x, axes)
    result = jnp.squeeze(x.larray, axis=axes)
    split = x.split
    if split is not None:
        if split in axes:
            split = None
        else:
            split = split - sum(1 for ax in axes if ax < split)
    return _wrap(result, split, x, dtype=x.dtype)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join arrays along a new axis (reference: manipulations.py stack)."""
    arrays = sanitize_sequence(arrays)
    if len(arrays) < 2:
        raise ValueError(f"stack expects at least 2 arrays, got {len(arrays)}")
    for a in arrays:
        sanitize_in(a)
    ref = arrays[0]
    for a in arrays[1:]:
        if tuple(a.shape) != tuple(ref.shape):
            raise ValueError(
                f"all input arrays must have the same shape, got {a.shape} != {ref.shape}"
            )
    if any(a._is_planar for a in arrays):
        from . import complex_planar as _cp

        if out is not None:
            raise _cp.policy_error("stack with out= on complex arrays")
        return _cp.stack_new_axis(arrays, axis)
    out_dtype = ref.dtype
    for a in arrays[1:]:
        out_dtype = types.promote_types(out_dtype, a.dtype)
    jt = out_dtype.jax_type()
    result = jnp.stack([a.larray.astype(jt) for a in arrays], axis=axis)
    split = ref.split
    if split is not None:
        norm_axis = axis % result.ndim
        if norm_axis <= split:
            split += 1
    ret = _wrap(result, split, ref, dtype=out_dtype)
    if out is not None:
        out.larray = ret.larray
        return out
    return ret


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference: manipulations.py swapaxes)."""
    from .linalg import transpose

    axis1 = sanitize_axis(x.shape, axis1)
    axis2 = sanitize_axis(x.shape, axis2)
    perm = list(range(x.ndim))
    perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
    return transpose(x, perm)


def tile(x: DNDarray, reps: Sequence[int]) -> DNDarray:
    """Construct by repeating x (reference: manipulations.py tile)."""
    sanitize_in(x)
    if isinstance(reps, DNDarray):
        reps = reps.numpy().tolist()
    reps = [int(r) for r in (reps if isinstance(reps, (list, tuple, np.ndarray)) else [reps])]
    result = jnp.tile(x.larray, reps)
    split = x.split
    if split is not None:
        split = split + (result.ndim - x.ndim)
    return _wrap(result, split, x, dtype=x.dtype)


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """k largest/smallest elements along dim; returns (values, indices)
    (reference: manipulations.py:3981 — iterative merge across ranks).

    Along the split axis this runs ``parallel.distributed_topk``: local
    per-shard top-k, all_gather of the tiny (p·k) candidate set, final
    merge — no global gather. Off-split dims are shard-local XLA top_k.
    """
    sanitize_in(a)
    dim = sanitize_axis(a.shape, dim)
    split = a.split
    if (
        split is not None
        and dim == split
        and a.comm.size > 1
        and k <= a.gshape[dim]
        and a.dtype not in (types.complex64, types.complex128)
    ):
        from . import _padding
        from . import parallel

        phys = a._phys
        n = a.gshape[dim]
        jt = a.dtype.jax_type()
        if phys.shape[dim] != n:
            # pads must lose: fill with the worst value for the direction
            sentinel = _operations._resolve_neutral("min" if largest else "max", jt)
            phys = _padding.mask_phys(phys, a.gshape, dim, fill=sentinel)
        fv, fi = parallel.distributed_topk(phys, a.comm.mesh, a.comm.axis_name, dim, k, largest)
        gshape = tuple(k if i == dim else s for i, s in enumerate(a.gshape))
        vals = DNDarray(fv, gshape, a.dtype, None, a.device, a.comm)
        idx = DNDarray(
            fi.astype(types.index_jax_type()), gshape, types.canonical_heat_type(jnp.int64), None, a.device, a.comm
        )
    else:
        arr = a.larray
        moved = jnp.moveaxis(arr, dim, -1)
        if largest:
            values, indices = jax.lax.top_k(moved, k)
        else:
            values, indices = jax.lax.top_k(-moved, k)
            values = -values
        values = jnp.moveaxis(values, -1, dim)
        indices = jnp.moveaxis(indices, -1, dim)
        vals = _wrap(values, split, a, dtype=a.dtype)
        idx = _wrap(indices.astype(types.index_jax_type()), split, a)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a (values, indices) tuple of DNDarrays")
        out[0].larray = vals.larray
        out[1].larray = idx.larray
        return out
    return vals, idx


def _lex_searchsorted_rows(sorted_rows, queries):
    """Index of each query ROW in a lexicographically sorted row set
    (every query must be present): a vectorized lower-bound binary
    search — ``log2(nu)`` steps of O(n·R) work, the rows edition of the
    flat path's ``searchsorted``. The naive pairwise-equality tensor
    would be O(n·nu·R) — an OOM in exactly the large-operand regime
    this subsystem targets. Comparison runs on the SORTABLE-uint bit
    view, so unsigned order is value order."""
    nu = int(sorted_rows.shape[0])
    n = queries.shape[0]
    lo = jnp.zeros((n,), dtype=jnp.int32)
    hi = jnp.full((n,), nu, dtype=jnp.int32)

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        pivot = jnp.take(sorted_rows, jnp.minimum(mid, nu - 1), axis=0)  # (n, R)
        diff = pivot != queries
        has = jnp.any(diff, axis=1)
        first = jnp.argmax(diff, axis=1)
        pv = jnp.take_along_axis(pivot, first[:, None], axis=1)[:, 0]
        qv = jnp.take_along_axis(queries, first[:, None], axis=1)[:, 0]
        lt = has & (pv < qv)  # pivot <lex query
        searching = lo < hi
        lo = jnp.where(searching & lt, mid + 1, lo)
        hi = jnp.where(searching & ~lt, mid, hi)
        return lo, hi

    steps = max(nu.bit_length(), 1)
    lo, _ = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _unique_axis_distributed(a: DNDarray, axis: int, return_inverse: bool):
    """Gather-free distributed ``unique(axis=)`` — the sorted-split
    rows formulation (``parallel.distributed_unique_rows``): move the
    requested axis to the front, resplit to rows, bit-view each slice
    through the ``kernels.sort`` monotone transform, and run per-shard
    lexicographic sorted-unique + candidate-prefix merge. Only the
    small candidate set is ever gathered. Returns ``NotImplemented``
    when the formulation cannot serve (untransformable dtype, slices
    wider than 256 elements) — the caller falls back to the eager path."""
    from . import parallel as _parallel
    from ..kernels import sort as _ksort

    rest = tuple(s for i, s in enumerate(a.gshape) if i != axis)
    R = 1
    for s in rest:
        R *= int(s)
    if R == 0 or R > 256:
        return NotImplemented
    arr = a if axis == 0 else moveaxis(a, axis, 0)
    if arr.split != 0:
        arr = arr.resplit(0)
    phys = arr._phys
    is_bool = phys.dtype == jnp.bool_
    if is_bool:
        phys = phys.astype(jnp.uint8)
    if not _ksort.transformable(phys.dtype):
        return NotImplemented
    n = int(arr.gshape[0])
    u = _ksort.to_sortable(phys.reshape(phys.shape[0], R))  # local flatten
    merged_u = _parallel.distributed_unique_rows(
        u, n, arr.comm.mesh, arr.comm.axis_name
    )
    vals_flat = _ksort.from_sortable(merged_u, phys.dtype)
    if is_bool:
        vals_flat = vals_flat.astype(jnp.bool_)
    nu = int(vals_flat.shape[0])
    vals = vals_flat.reshape((nu,) + rest)
    if axis != 0:
        vals = jnp.moveaxis(vals, 0, axis)
    out = _wrap(vals, 0 if a.split is not None else None, a, dtype=a.dtype)
    if not return_inverse:
        return out
    # inverse: each LOGICAL slice's position in the lex-sorted unique
    # set, found shard-wise by the rows lower-bound binary search
    # against the small replicated set (no collective; O(n·R·log nu)
    # like the flat path's searchsorted — bit-view, so NaN/−0 classes
    # match their collapsed representative)
    u_log = _ksort.to_sortable(
        (arr.larray.astype(jnp.uint8) if is_bool else arr.larray).reshape(n, R)
    )
    inv_phys = _lex_searchsorted_rows(merged_u, u_log).astype(types.index_jax_type())
    inv = _wrap(jnp.asarray(inv_phys), 0 if a.split is not None else None, a)
    return out, inv


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """Unique elements (reference: manipulations.py:3202 — local unique +
    allgather of the small sets + re-unique).

    Distributed unique is gather-free in BOTH modes: flat unique is a
    per-shard sorted-unique compaction, one tiny count sync, and a merge
    over only the candidate prefixes (``parallel.distributed_unique``);
    ``axis`` mode (slices-unique) runs the same sorted-split formulation
    on ROWS (ISSUE 11 satellite / VERDICT backlog) — slices are
    bit-viewed through the ``kernels.sort`` monotone transform, sorted
    lexicographically per shard, deduplicated, and only the candidate
    prefixes are gathered (``parallel.distributed_unique_rows``) — the
    operand itself is never all-gathered, and tier-1 pins the census.
    Tie semantics match the framework's flat unique (−0.0 with +0.0
    collapse; all NaN payloads collapse to the canonical quiet NaN —
    ``jnp.unique`` behavior). The single-device path, untransformable
    dtypes (complex; f64 without x64), and very wide slices (> 256
    elements — the lexicographic sort keys one operand per element) use
    eager ``jnp.unique`` (data-dependent output shape)."""
    sanitize_in(a)
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
        if a.ndim == 1:
            axis = None  # 1-D slices ARE the elements: np.unique semantics
    comm = a.comm
    if (
        axis is not None
        and a.split is not None
        and comm.is_distributed()
        and 0 not in a.gshape
    ):
        out = _unique_axis_distributed(a, axis, return_inverse)
        if out is not NotImplemented:
            return out
    if (
        axis is None
        and a.split is not None
        and comm.is_distributed()
        and 0 not in a.gshape  # zero-extent arrays are stored replicated
    ):
        from . import parallel as _parallel

        arr = a if a.split == 0 else a.resplit(0)
        phys = arr._phys
        is_bool = phys.dtype == jnp.bool_
        if is_bool:
            phys = phys.astype(jnp.uint8)
        values = _parallel.distributed_unique(
            phys, int(arr.gshape[0]), comm.mesh, comm.axis_name
        )
        if is_bool:
            values = values.astype(jnp.bool_)
        vals = _wrap(values, 0, a, dtype=a.dtype)
        if return_inverse:
            # searchsorted into the small replicated unique set — binary
            # search per element, computed shard-wise under GSPMD (the
            # replicated u needs no collective)
            q = a.larray.reshape(-1)
            inv_phys = jnp.searchsorted(values.astype(phys.dtype), q)
            if jnp.issubdtype(values.dtype, jnp.floating):
                # NaN queries: searchsorted compares False against
                # everything and returns len(values), but the unique set
                # collapses NaNs into ONE slot sorted LAST — remap so the
                # inverse reconstructs like np.unique's (ADVICE r3)
                inv_phys = jnp.where(jnp.isnan(q), values.shape[0] - 1, inv_phys)
            inv_phys = inv_phys.astype(types.index_jax_type())
            # the inverse is as long as the (flattened) input and computed
            # shard-wise from it: carry the input's distribution instead
            # of declaring a replicated wrapper over a sharded buffer
            inv = _wrap(jnp.asarray(inv_phys), 0 if a.split is not None else None, a)
            return vals, inv
        return vals
    if return_inverse:
        values, inverse = jnp.unique(a.larray, return_inverse=True, axis=axis)
    else:
        values = jnp.unique(a.larray, axis=axis)
    split = 0 if a.split is not None else None
    vals = _wrap(values, split, a, dtype=a.dtype)
    if return_inverse:
        inv = _wrap(jnp.asarray(inverse), None, a)
        return vals, inv
    return vals


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split vertically (reference: manipulations.py vsplit)."""
    return split(x, indices_or_sections, axis=0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack vertically (reference: manipulations.py vstack)."""
    arrays = sanitize_sequence(arrays)
    arrays = [a if a.ndim > 1 else reshape(a, (1, a.shape[0]) if a.ndim == 1 else (1,)) for a in arrays]
    return concatenate(arrays, axis=0)


# method attachment (reference attaches these on DNDarray)
DNDarray.flip = flip
DNDarray.tile = tile
DNDarray.repeat = repeat
DNDarray.sort = sort
DNDarray.topk = topk
DNDarray.unique = unique
DNDarray.concatenate = lambda self, others, axis=0: concatenate([self] + list(others), axis)
DNDarray.moveaxis = moveaxis
DNDarray.swapaxes = swapaxes
DNDarray.broadcast_to = broadcast_to

from .communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_reshape_program)
register_mesh_cache(_concat_program)
