"""heat_tpu core: distributed array, type system, operator surface.

Mirrors the reference layout /root/reference/heat/core/__init__.py — the
flat ``ht.*`` namespace re-exports every surface module.
"""

from .base import *
from .communication import *
from .constants import *
from .devices import *
from .types import *
from .dndarray import *
from .factories import *
from .arithmetics import *
from .complex_math import *
from .exponential import *
from .indexing import *
from .jit import *
from .io import *
from .logical import *
from .manipulations import *
from .memory import *
from .printing import *
from .relational import *
from .rounding import *
from .sanitation import *
from .signal import *
from .statistics import *
from .stride_tricks import *
from .tiling import *
from .trigonometrics import *

from . import gates
from . import random
from . import tiers
from . import tiling

from . import linalg
from .linalg import *

from ..version import __version__


def __getattr__(name):
    """Lazy ``tpu``/``gpu`` device singletons (see devices module)."""
    if name in ("tpu", "gpu"):
        from . import devices as _devices

        return getattr(_devices, name)
    raise AttributeError(f"module 'heat_tpu.core' has no attribute {name!r}")
