"""Memory layout utilities.

API parity with /root/reference/heat/core/memory.py (``copy`` at
memory.py:13, ``sanitize_memory_layout`` at :42). XLA owns physical
layout on TPU, so C/F-order stride permutation is metadata-only here.
"""

from __future__ import annotations

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(a: DNDarray) -> DNDarray:
    """Deep copy of ``a`` (reference: memory.py:13)."""
    from .sanitation import sanitize_in

    sanitize_in(a)
    if a._is_planar:
        from . import complex_planar as _cp

        return _cp.copy(a)
    return DNDarray(
        jnp.array(a.larray), a.gshape, a.dtype, a.split, a.device, a.comm, balanced=True
    )


def sanitize_memory_layout(x, order: str = "C"):
    """Return data in the requested memory layout (reference: memory.py:42).
    XLA chooses physical tiling on TPU — this validates and returns as-is.
    """
    if order not in ("C", "F", "K"):
        raise ValueError(f"expected order to be 'C', 'F' or 'K', got {order}")
    return x
