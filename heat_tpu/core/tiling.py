"""Tile decompositions.

API parity with /root/reference/heat/core/tiling.py (``SplitTiles`` :16,
``SquareDiagTiles`` :331). The reference builds these as the addressing
layer of its rank-divergent algorithms (``resplit_`` consumes SplitTiles;
the tiled CAQR consumes SquareDiagTiles). In this framework resharding
and QR are expressed declaratively (GSPMD + TSQR), so no internal
algorithm needs a tile map — but algorithms USERS built on the reference
tiles do, so both classes are fully functional tile VIEWS here:

* indexing a tile (or a slice of tiles) returns its values;
* assigning to a tile writes through to the underlying DNDarray (the
  write is a global setitem — XLA turns it into the same local-shard
  scatter the reference's rank-local write performs);
* the geometry surface (``lshape_map``, ``tile_locations``,
  ``tile_ends_g``, ``tile_map``, ``get_start_stop``,
  ``local_get``/``local_set``, ``local_to_global``) matches the
  reference names.

Single-controller note: the reference's "local" accessors address the
calling rank's band; here every device's band is addressable from the one
controller, so ``local_*`` take the device rank explicitly (default 0) —
the same signature shift ``DNDarray.lloc`` documents.
"""

from __future__ import annotations

import numpy as np

from typing import List, Optional, Tuple, Union

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _starts(extents: np.ndarray) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(extents)])


class SplitTiles:
    """Tiles along every dimension with the split-axis boundaries of every
    device (reference: tiling.py:16). ``tile_dimensions[d]`` holds the tile
    extents along dim d; one tile boundary set per device along each dim.
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        size = arr.comm.size
        # per-dim tile extents: the split dim follows the chunk geometry,
        # other dims are chunked the same way "theoretically" (reference
        # computes torch chunk sizes per dim)
        dims = []
        for d in range(arr.ndim):
            counts = [
                arr.comm.chunk(arr.gshape, d, rank=r)[1][d] for r in range(size)
            ]
            dims.append(np.array(counts, dtype=np.int64))
        self.__tile_dimensions = dims
        self.__tile_locations = self.set_tile_locations(
            split=arr.split, tile_dims=dims, arr=arr
        )

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, ndim) local-shape map (reference: tiling.py:146)."""
        return self.__arr.lshape_map

    @property
    def tile_dimensions(self) -> List[np.ndarray]:
        return self.__tile_dimensions

    @property
    def tile_ends_g(self) -> np.ndarray:
        """Global END index of every tile along every dim, shape
        (ndim, size) (reference: tiling.py:164)."""
        return np.stack([np.cumsum(t) for t in self.__tile_dimensions])

    @property
    def tile_locations(self) -> np.ndarray:
        return self.__tile_locations

    @staticmethod
    def set_tile_locations(split: Optional[int], tile_dims: List[np.ndarray], arr: DNDarray) -> np.ndarray:
        """Device owning each tile (reference: tiling.py set_tile_locations)."""
        shape = tuple(len(t) for t in tile_dims)
        locations = np.zeros(shape, dtype=np.int64)
        if split is None:
            return locations
        size = arr.comm.size
        idx = [slice(None)] * len(shape)
        for r in range(size):
            idx[split] = r
            locations[tuple(idx)] = r
        return locations

    def __tile_slices(self, key) -> Tuple[slice, ...]:
        """Global slices covering the requested tile (or tile-slice) key."""
        starts = [_starts(t) for t in self.__tile_dimensions]
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for d in range(self.__arr.ndim):
            if d < len(key):
                k = key[d]
                if isinstance(k, slice):
                    lo, hi, step = k.indices(len(self.__tile_dimensions[d]))
                    if step != 1:
                        raise ValueError("tile slices must be contiguous (step 1)")
                    slices.append(slice(int(starts[d][lo]), int(starts[d][hi])))
                else:
                    k = int(k)
                    slices.append(slice(int(starts[d][k]), int(starts[d][k + 1])))
            else:
                slices.append(slice(None))
        return tuple(slices)

    def __getitem__(self, key) -> Optional[np.ndarray]:
        """Tile values as numpy (the reference returns the rank-local torch
        slice; under a single controller every tile is addressable)."""
        # slice on device first: only the tile travels to host
        return np.asarray(self.__arr.larray[self.__tile_slices(key)])

    def __setitem__(self, key, value) -> None:
        """Assign to a tile — writes through to the underlying DNDarray
        (reference: tiling.py:299 writes the rank-local slice)."""
        self.__arr[self.__tile_slices(key)] = value


class SquareDiagTiles:
    """Square tiles along the diagonal of a 2-D array (reference:
    tiling.py:331): the addressing scheme of the reference's tiled QR
    (``tiles_per_proc`` partitions each device's band). Fully indexable
    and writable; see the module docstring for the single-controller
    reading of the ``local_*`` accessors.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("Arr must be 2 dimensional")
        if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
            raise ValueError(f"tiles_per_proc must be a positive int, got {tiles_per_proc}")
        self.__arr = arr
        size = arr.comm.size
        m, n = arr.gshape
        split = arr.split if arr.split is not None else 0

        # per-device extents along the split dim
        counts = [arr.comm.chunk(arr.gshape, split, rank=r)[1][split] for r in range(size)]
        row_per_proc = []
        row_starts = [0]
        for c in counts:
            per = max(1, tiles_per_proc)
            base = c // per
            rem = c % per
            sizes = [base + (1 if i < rem else 0) for i in range(per)]
            sizes = [s for s in sizes if s > 0]
            row_per_proc.append(len(sizes))
            for s in sizes:
                row_starts.append(row_starts[-1] + s)
        # square tiles: column boundaries mirror row boundaries up to n
        col_bounds = [b for b in row_starts if b <= n]
        if col_bounds[-1] != n:
            col_bounds.append(n)

        self.__split = split
        self.__row_starts = np.array(row_starts, dtype=np.int64)
        self.__col_starts = np.array(col_bounds, dtype=np.int64)
        self.__tile_rows_per_process = row_per_proc
        self.__tile_columns = len(self.__col_starts) - 1
        self.__tile_rows = len(self.__row_starts) - 1

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, 2) local-shape map (reference: tiling.py:737)."""
        return self.__arr.lshape_map

    @property
    def last_diagonal_process(self) -> int:
        """Rank of the last device holding part of the diagonal
        (reference: tiling.py:745)."""
        m, n = self.__arr.gshape
        diag_end = min(m, n)
        # device whose band contains row/col diag_end - 1
        tile = int(np.searchsorted(self.__row_starts, diag_end - 1, side="right") - 1)
        return int(self.tile_map[min(tile, self.__tile_rows - 1), 0])

    @property
    def tile_columns(self) -> int:
        """Number of tile columns (reference: tiling.py tile_columns)."""
        return self.__tile_columns

    @property
    def tile_columns_per_process(self) -> List[int]:
        """Reference tiling.py:766 — every process sees all tile columns
        (column tiles are not owner-partitioned in the split=0 layout)."""
        return [self.__tile_columns] * self.__arr.comm.size

    @property
    def tile_map(self) -> np.ndarray:
        """(tile_rows, tile_columns) device owning each tile (reference:
        tiling.py:773 stores (start_row, start_col, rank) triples; the
        rank plane is the load-bearing part)."""
        size = self.__arr.comm.size
        owners = np.zeros((self.__tile_rows, self.__tile_columns), dtype=np.int64)
        # a row tile belongs to the device whose band contains it
        bands = np.cumsum([0] + self.__tile_rows_per_process)
        for r in range(size):
            owners[bands[r]: bands[r + 1], :] = r
        return owners

    @property
    def tile_rows(self) -> int:
        """Number of tile rows."""
        return self.__tile_rows

    @property
    def tile_rows_per_process(self) -> List[int]:
        return list(self.__tile_rows_per_process)

    @property
    def row_indices(self) -> List[int]:
        return self.__row_starts[:-1].tolist()

    @property
    def col_indices(self) -> List[int]:
        return self.__col_starts[:-1].tolist()

    def get_tile_size(self, key: Tuple[int, int]) -> Tuple[int, int]:
        """(rows, cols) of tile ``key``."""
        i, j = key
        return (
            int(self.__row_starts[i + 1] - self.__row_starts[i]),
            int(self.__col_starts[j + 1] - self.__col_starts[j]),
        )

    def get_start_stop(self, key: Tuple[int, int]) -> Tuple[int, int, int, int]:
        """(row start, row stop, col start, col stop) of tile ``key``
        (reference: tiling.py:822)."""
        rs, re, cs, ce = self.__tile_bounds(key)
        return rs, re, cs, ce

    def __tile_bounds(self, key) -> Tuple[int, int, int, int]:
        if not isinstance(key, tuple):
            key = (key, slice(None))
        i, j = key
        if isinstance(i, slice):
            lo, hi, step = i.indices(self.__tile_rows)
            if step != 1:
                raise ValueError("tile slices must be contiguous (step 1)")
            rs, re = int(self.__row_starts[lo]), int(self.__row_starts[hi])
        else:
            i = int(i)
            rs, re = int(self.__row_starts[i]), int(self.__row_starts[i + 1])
        if isinstance(j, slice):
            lo, hi, step = j.indices(self.__tile_columns)
            if step != 1:
                raise ValueError("tile slices must be contiguous (step 1)")
            cs, ce = int(self.__col_starts[lo]), int(self.__col_starts[hi])
        else:
            j = int(j)
            cs, ce = int(self.__col_starts[j]), int(self.__col_starts[j + 1])
        return rs, re, cs, ce

    def __getitem__(self, key) -> np.ndarray:
        rs, re, cs, ce = self.__tile_bounds(key)
        return np.asarray(self.__arr.larray[rs:re, cs:ce])

    def __setitem__(self, key, value) -> None:
        """Assign to a tile — writes through to the underlying DNDarray
        (reference: tiling.py:1206)."""
        rs, re, cs, ce = self.__tile_bounds(key)
        self.__arr[rs:re, cs:ce] = value

    # ------------------------------------------------------------------ #
    # local (per-device band) accessors                                  #
    # ------------------------------------------------------------------ #
    def local_to_global(self, key: Tuple[int, int], rank: int = 0) -> Tuple[int, int]:
        """Map a device-local tile index to the global tile index
        (reference: tiling.py:1018; the rank is explicit here — see the
        module docstring)."""
        i, j = key
        base = int(np.sum(self.__tile_rows_per_process[:rank]))
        return base + int(i), int(j)

    def local_get(self, key: Tuple[int, int], rank: int = 0) -> np.ndarray:
        """Values of device ``rank``'s local tile ``key`` (reference:
        tiling.py:935)."""
        return self[self.local_to_global(key, rank)]

    def local_set(self, key: Tuple[int, int], value, rank: int = 0) -> None:
        """Assign device ``rank``'s local tile ``key`` (reference:
        tiling.py:955)."""
        self[self.local_to_global(key, rank)] = value

    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """Adopt the row/column boundaries of another tile map so the two
        arrays can be addressed tile-by-tile together — the reference
        aligns Q's tiles to A's before the tiled QR sweep
        (tiling.py:1080). Boundaries are clipped to this array's extents.
        """
        if not isinstance(tiles_to_match, SquareDiagTiles):
            raise TypeError(
                f"tiles_to_match must be SquareDiagTiles, got {type(tiles_to_match)}"
            )
        m, n = self.__arr.gshape
        rows = [b for b in tiles_to_match.__row_starts.tolist() if b <= m]
        if rows[-1] != m:
            rows.append(m)
        cols = [b for b in tiles_to_match.__col_starts.tolist() if b <= n]
        if cols[-1] != n:
            cols.append(n)
        self.__row_starts = np.array(rows, dtype=np.int64)
        self.__col_starts = np.array(cols, dtype=np.int64)
        self.__tile_rows = len(rows) - 1
        self.__tile_columns = len(cols) - 1
        # rows-per-process: recount against the matched boundaries
        size = self.__arr.comm.size
        counts = [
            self.__arr.comm.chunk(self.__arr.gshape, self.__split, rank=r)[1][self.__split]
            for r in range(size)
        ]
        band_ends = np.cumsum(counts)
        self.__tile_rows_per_process = [
            int(
                np.sum(
                    (self.__row_starts[:-1] >= (band_ends[r - 1] if r else 0))
                    & (self.__row_starts[:-1] < band_ends[r])
                )
            )
            for r in range(size)
        ]
