"""Tile decompositions.

API parity with /root/reference/heat/core/tiling.py (``SplitTiles`` :16 —
per-rank theoretical chunk grid consumed by ``resplit_``;
``SquareDiagTiles`` :331 — square diagonal tiles with ``tiles_per_proc``
consumed by the tiled QR). In this framework resharding and QR are
expressed declaratively (GSPMD + TSQR), so the tile maps are not load-
bearing — they are provided as geometry objects for API parity and for
algorithms users may have built on them.
"""

from __future__ import annotations

import numpy as np

from typing import List, Optional, Tuple, Union

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """Tiles along every dimension with the split-axis boundaries of every
    device (reference: tiling.py:16). ``tile_dimensions[d]`` holds the tile
    extents along dim d; one tile boundary set per device along each dim.
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        size = arr.comm.size
        # per-dim tile extents: the split dim follows the chunk geometry,
        # other dims are chunked the same way "theoretically" (reference
        # computes torch chunk sizes per dim)
        dims = []
        for d in range(arr.ndim):
            counts = [
                arr.comm.chunk(arr.gshape, d, rank=r)[1][d] for r in range(size)
            ]
            dims.append(np.array(counts, dtype=np.int64))
        self.__tile_dimensions = dims
        self.__tile_locations = self.set_tile_locations(
            split=arr.split, tile_dims=dims, arr=arr
        )

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_dimensions(self) -> List[np.ndarray]:
        return self.__tile_dimensions

    @property
    def tile_locations(self) -> np.ndarray:
        return self.__tile_locations

    @staticmethod
    def set_tile_locations(split: Optional[int], tile_dims: List[np.ndarray], arr: DNDarray) -> np.ndarray:
        """Device owning each tile (reference: tiling.py set_tile_locations)."""
        shape = tuple(len(t) for t in tile_dims)
        locations = np.zeros(shape, dtype=np.int64)
        if split is None:
            return locations
        size = arr.comm.size
        idx = [slice(None)] * len(shape)
        for r in range(size):
            idx[split] = r
            locations[tuple(idx)] = r
        return locations

    def __getitem__(self, key) -> Optional[np.ndarray]:
        """Tile data as numpy for the requested tile index (geometry demo;
        the reference returns the local torch slice)."""
        starts = [np.concatenate([[0], np.cumsum(t)]) for t in self.__tile_dimensions]
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for d in range(self.__arr.ndim):
            if d < len(key):
                k = key[d]
                slices.append(slice(int(starts[d][k]), int(starts[d][k + 1])))
            else:
                slices.append(slice(None))
        # slice on device first: only the tile travels to host
        return np.asarray(self.__arr.larray[tuple(slices)])


class SquareDiagTiles:
    """Square tiles along the diagonal of a 2-D array (reference:
    tiling.py:331): used by the reference's tiled QR; provided here as a
    geometry object (``tiles_per_proc`` partitions each device's band).
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("Arr must be 2 dimensional")
        if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
            raise ValueError(f"tiles_per_proc must be a positive int, got {tiles_per_proc}")
        self.__arr = arr
        size = arr.comm.size
        m, n = arr.gshape
        split = arr.split if arr.split is not None else 0

        # per-device extents along the split dim
        counts = [arr.comm.chunk(arr.gshape, split, rank=r)[1][split] for r in range(size)]
        row_per_proc = []
        row_starts = [0]
        for c in counts:
            per = max(1, tiles_per_proc)
            base = c // per
            rem = c % per
            sizes = [base + (1 if i < rem else 0) for i in range(per)]
            sizes = [s for s in sizes if s > 0]
            row_per_proc.append(len(sizes))
            for s in sizes:
                row_starts.append(row_starts[-1] + s)
        # square tiles: column boundaries mirror row boundaries up to n
        col_bounds = [b for b in row_starts if b <= n]
        if col_bounds[-1] != n:
            col_bounds.append(n)

        self.__row_starts = np.array(row_starts, dtype=np.int64)
        self.__col_starts = np.array(col_bounds, dtype=np.int64)
        self.__tile_rows_per_process = row_per_proc
        self.__tile_columns = len(self.__col_starts) - 1
        self.__tile_rows = len(self.__row_starts) - 1

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_columns(self) -> int:
        """Number of tile columns (reference: tiling.py tile_columns)."""
        return self.__tile_columns

    @property
    def tile_rows(self) -> int:
        """Number of tile rows."""
        return self.__tile_rows

    @property
    def tile_rows_per_process(self) -> List[int]:
        return list(self.__tile_rows_per_process)

    @property
    def row_indices(self) -> List[int]:
        return self.__row_starts[:-1].tolist()

    @property
    def col_indices(self) -> List[int]:
        return self.__col_starts[:-1].tolist()

    def get_tile_size(self, key: Tuple[int, int]) -> Tuple[int, int]:
        """(rows, cols) of tile ``key``."""
        i, j = key
        return (
            int(self.__row_starts[i + 1] - self.__row_starts[i]),
            int(self.__col_starts[j + 1] - self.__col_starts[j]),
        )

    def __getitem__(self, key) -> np.ndarray:
        if not isinstance(key, tuple):
            key = (key, slice(None))
        i, j = key
        rs, re = int(self.__row_starts[i]), int(self.__row_starts[i + 1])
        if isinstance(j, slice):
            return np.asarray(self.__arr.larray[rs:re])
        cs, ce = int(self.__col_starts[j]), int(self.__col_starts[j + 1])
        return np.asarray(self.__arr.larray[rs:re, cs:ce])
