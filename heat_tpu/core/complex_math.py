"""Complex number operations.

API parity with /root/reference/heat/core/complex_math.py (5 exports).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from . import types
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x: DNDarray, deg: bool = False, out=None) -> DNDarray:
    """Argument of the complex values (reference: complex_math.py angle)."""
    result = _operations.__local_op(jnp.angle, x, out, no_cast=True)
    if deg:
        from . import trigonometrics

        result = trigonometrics.rad2deg(result, out=out)
    return result


def conj(x: DNDarray, out=None) -> DNDarray:
    """Complex conjugate."""
    return _operations.__local_op(jnp.conj, x, out, no_cast=True)


conjugate = conj


def imag(x: DNDarray) -> DNDarray:
    """Imaginary part; zeros for real input (reference: complex_math.py imag)."""
    if types.heat_type_is_complexfloating(x.dtype):
        return _operations.__local_op(jnp.imag, x, None, no_cast=True)
    from . import factories

    return factories.zeros_like(x)


def real(x: DNDarray) -> DNDarray:
    """Real part; the array itself for real input."""
    if types.heat_type_is_complexfloating(x.dtype):
        return _operations.__local_op(jnp.real, x, None, no_cast=True)
    return x


DNDarray.conj = conj
