"""The env-gate registry — every ``HEAT_TPU_*`` switch declared ONCE.

Since PR 4 every subsystem has shipped behind an environment gate
(kernel dispatch, planner routing, overlap issue order, wire codec,
topology, out-of-core staging, serving AOT, telemetry, capacity
overrides), and every PR since 5 has carried the same review line: "the
gate is a component of every plan/program/AOT cache key". That
convention was enforced BY HAND at 60+ read sites — and the PR 9/10
hardening lists were dominated by exactly the omission class it guards
against: a cache key missing one gate component silently serves a stale
compiled program, the worst failure mode a serving stack can have.

This module makes the convention *provable*:

- every gate is declared once, as a :class:`GateSpec` — name, legal
  values, default, whether its value changes the PROGRAMS the library
  builds (``affects_programs``), which cache layers must key on it
  (``scopes``: ``plan`` / ``program`` / ``aot``), and the conventional
  parameter names its resolved value travels under (``key_params`` —
  what the SL402 staleness rule checks cache keys against);
- :func:`get` is the ONE ``os.environ`` read site for gates in the
  whole tree — rule SL403 (``heat_tpu.analysis.effectcheck``) makes a
  raw ``os.environ`` read of a ``HEAT_TPU_*`` name an error-severity
  finding anywhere outside this module;
- the AOT cache's gate stamp set DERIVES from the registry
  (:func:`aot_fingerprint` — byte-compatible with the PR 9 hand-filter
  at every gate combination), and :func:`program_gate_roster` stamps
  the registered program-affecting gate NAMES into every stored AOT
  envelope, so registering a new program-affecting gate in a later
  version invalidates old envelopes (``version_mismatch``) instead of
  ever serving a stale hit.

Reading a gate::

    from heat_tpu.core import gates
    raw = gates.get("HEAT_TPU_REDIST_OVERLAP")      # Optional[str], os.environ semantics
    raw = gates.get("HEAT_TPU_TOPOLOGY", "auto")    # with a default

``get`` intentionally returns the RAW environment string (or the
default): the per-gate mode/byte/path parsing stays at the accessor the
subsystem has always exported (``planner.overlap_mode``,
``staging.ooc_mode``, ``tiers.capacity``, ...), declared here in each
spec's ``accessors`` so the analyzer knows which function reads which
gate. Behavior is therefore byte-identical to the pre-registry readers
at every gate value — the golden plans, plan_ids, program cache keys
and AOT envelope keys are pinned unchanged in tier-1.

Stdlib-only on purpose: this module is imported by
``observability.telemetry`` at process start, before jax or any heavy
core module loads.
"""

from __future__ import annotations

import os

from typing import Dict, Iterable, Optional, Tuple

__all__ = [
    "GATES",
    "GateSpec",
    "PREFIX",
    "accessor_gates",
    "affecting_programs",
    "aot_fingerprint",
    "declare",
    "get",
    "is_set",
    "program_gate_roster",
    "scope_gates",
    "snapshot",
]

PREFIX = "HEAT_TPU_"

#: the PR 9 stamp-filter exclusions, kept for UNREGISTERED names only:
#: a set env var the registry does not know is conservatively key
#: material (exactly the old prefix scan), unless it rides one of these
#: prefixes — which the old scan excluded too. Registered gates are
#: classified by their own ``affects_programs`` flag instead.
_UNREGISTERED_EXCLUDE = ("HEAT_TPU_SERVING", "HEAT_TPU_TELEMETRY")

#: the repo-wide accepted spellings of the boolean gate values (what the
#: subsystem accessors and telemetry's ``_env_truthy`` have always
#: parsed) — :meth:`GateSpec.recognizes` accepts them for any gate whose
#: legal values include the corresponding canonical form.
_FALSY_SPELLINGS = ("0", "off", "false", "no")
_TRUTHY_SPELLINGS = ("1", "on", "true", "force", "yes")

#: cache layers a gate can be key material for. ``plan``: the planner's
#: schedule cache (resolved value in the plan key / plan_id); ``program``:
#: the executor/builder lru program caches (resolved value a builder
#: parameter); ``aot``: the persistent serving envelope keys (raw value
#: in the gate fingerprint).
SCOPES = ("plan", "program", "aot")


class GateSpec:
    """One declared environment gate.

    Attributes
    ----------
    name : the full ``HEAT_TPU_*`` environment variable name.
    default : the raw default applied when the variable is unset —
        documentation of the escape-hatch/auto resolution, never
        substituted by :func:`get` unless the caller passes it.
    values : legal RESOLVED values for mode gates (documentation +
        ``check_value``), or ``None`` for free-form gates (ints, paths).
    kind : ``"mode"`` | ``"int"`` | ``"bytes"`` | ``"path"``.
    affects_programs : True when the gate's value changes the plans or
        compiled programs the library builds — such gates are AOT key
        material and SL402 subjects. (Serving/telemetry switches change
        no program bytes and are False.)
    scopes : which cache layers key on the gate (subset of
        :data:`SCOPES`).
    key_params : conventional parameter names the gate's RESOLVED value
        travels under between the resolution site and the cached
        builders (``pipelined``, ``wire``, ``topo``...) — what rule
        SL402 accepts as "this builder keys on the gate".
    accessors : function names (terminal, as called) that read/resolve
        this gate — the analyzer's map from a call site to a gate.
    help : one-line contract.
    """

    __slots__ = (
        "name", "default", "values", "kind", "affects_programs",
        "scopes", "key_params", "accessors", "help",
    )

    def __init__(self, name, default, values=None, kind="mode",
                 affects_programs=True, scopes=(), key_params=(),
                 accessors=(), help=""):
        if not name.startswith(PREFIX):
            raise ValueError(f"gate name must start with {PREFIX!r}, got {name!r}")
        bad = set(scopes) - set(SCOPES)
        if bad:
            raise ValueError(f"unknown cache scopes {sorted(bad)} for {name}")
        self.name = name
        self.default = default
        self.values = tuple(values) if values is not None else None
        self.kind = kind
        self.affects_programs = bool(affects_programs)
        self.scopes = frozenset(scopes)
        self.key_params = tuple(key_params)
        self.accessors = tuple(accessors)
        self.help = help

    def check_value(self, resolved: str) -> bool:
        """Is ``resolved`` a legal resolved value? Free-form gates accept
        anything."""
        return self.values is None or resolved in self.values

    def recognizes(self, raw: Optional[str]) -> bool:
        """Does the raw environment spelling resolve to a declared legal
        value? Accepts the repo-wide truthy/falsy spelling families
        (``on``/``force``/``yes`` → ``1``, ``off``/``no`` → ``0``) and
        the empty string (which every accessor resolves to its default).
        A False here means the accessor will silently fall through to
        its default arm — worth surfacing in diagnostics."""
        if self.values is None or raw is None:
            return True
        v = raw.strip().lower()
        if v == "" or v in self.values:
            return True
        if "0" in self.values and v in _FALSY_SPELLINGS:
            return True
        if "1" in self.values and v in _TRUTHY_SPELLINGS:
            return True
        return False

    def __repr__(self) -> str:
        return (
            f"GateSpec({self.name}, default={self.default!r}, "
            f"affects_programs={self.affects_programs}, "
            f"scopes={sorted(self.scopes)})"
        )


GATES: Dict[str, GateSpec] = {}


def declare(spec: GateSpec) -> GateSpec:
    """Register a gate. Re-declaring a name replaces the entry (the
    testing hook: tests register throwaway gates and pop them back out
    of :data:`GATES`)."""
    GATES[spec.name] = spec
    return spec


# --------------------------------------------------------------------- #
# the declarations — one per gate, the whole surface                    #
# --------------------------------------------------------------------- #
declare(GateSpec(
    "HEAT_TPU_SORT_KERNEL", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("program", "aot"),
    key_params=("impl", "path", "engine"),
    accessors=("sort_kernel_mode",),
    help="sort-kernel dispatch: 0 = lax.sort oracle everywhere, 1 = force "
         "the radix/columnsort engines, auto = TPU autotune",
))
declare(GateSpec(
    "HEAT_TPU_RELAYOUT_KERNEL", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("program", "aot"),
    key_params=("impl", "impl_in", "impl_out"),
    accessors=("kernel_mode", "relayout_kernel_mode"),
    help="lane-packing relayout kernel dispatch: 0 = XLA formulation, "
         "1 = force the Pallas tiled copy, auto = TPU autotune",
))
declare(GateSpec(
    "HEAT_TPU_SPMM_KERNEL", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("program", "aot"),
    key_params=("impl", "path"),
    accessors=("spmm_kernel_mode",),
    help="block-sparse SpMM/SDDMM dispatch: 0 = gather-free XLA "
         "segment-sum oracle, 1 = force the Pallas brick kernel "
         "(interpret mode off-TPU), auto = TPU autotune",
))
declare(GateSpec(
    "HEAT_TPU_REDIST_PLANNER", default="1", values=("0", "1"),
    affects_programs=True, scopes=("program", "aot"),
    key_params=(),
    accessors=("planner_enabled",),
    help="planner routing: 0 restores the legacy one-collective relayout "
         "paths (a binary route switch — programs differ wholesale, so the "
         "route, not a value, is the key material)",
))
declare(GateSpec(
    "HEAT_TPU_REDIST_BUDGET_MB", default=str(256), kind="int",
    affects_programs=True, scopes=("plan", "program", "aot"),
    key_params=("budget", "budget_bytes", "b"),
    accessors=("budget_bytes",),
    help="per-device transient budget (MiB) the planner chunks under; "
         "resolved bytes are the `budget` component of every plan and "
         "executor program key",
))
declare(GateSpec(
    "HEAT_TPU_REDIST_OVERLAP", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("program", "aot"),
    key_params=("pipelined", "overlap"),
    accessors=("overlap_mode", "_overlap_active", "ring_enabled"),
    help="depth-2 software-pipelined issue order: 0 = sequential oracle, "
         "1 = force, auto = follow the plan's overlap annotation; resolved "
         "bool is the `pipelined` component of every executor program key",
))
declare(GateSpec(
    "HEAT_TPU_WIRE_QUANT", default="auto", values=("0", "1", "int8", "bf16", "auto"),
    affects_programs=True, scopes=("plan", "program", "aot"),
    key_params=("wire", "quant", "qmode", "codec", "mode"),
    accessors=("wire_quant_mode", "wire_quant_gate"),
    help="wire codec on transient exchanges: 0 = full-width exact-bit, "
         "1 = force int8, bf16 = force the cast codec, auto = int8 on TPU; "
         "resolved codec is the `quant` plan-key and `wire` program-key "
         "component",
))
declare(GateSpec(
    "HEAT_TPU_TOPOLOGY", default="auto", values=None, kind="mode",
    affects_programs=True, scopes=("plan", "program", "aot"),
    key_params=("topo", "topology"),
    accessors=("topology_for", "resolve_topology"),
    help="two-tier topology: auto = slice_index off the resolved world, "
         "SxC = forced factorization, flat = one ICI domain; resolved "
         "(S, C) is the `topology` plan-key and `topo` program-key "
         "component",
))
declare(GateSpec(
    "HEAT_TPU_OOC", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("plan", "aot"),
    key_params=("staged", "engaged"),
    accessors=("ooc_mode", "ooc_engaged"),
    help="out-of-core staging: 0 = materialize (escape hatch), 1 = force "
         "the staged window pipeline, auto = stage host-resident operands. "
         "A route switch like REDIST_PLANNER — staged plans are a distinct "
         "plan family, no lru program builder keys on the raw mode",
))
declare(GateSpec(
    "HEAT_TPU_OOC_SLAB_MB", default=str(256), kind="int",
    affects_programs=True, scopes=("plan", "aot"),
    key_params=("slab", "slab_bytes"),
    accessors=("slab_bytes",),
    help="HBM slab budget (MiB) for the depth-2 staging windows; resolved "
         "bytes are the staged plan's budget component",
))
declare(GateSpec(
    "HEAT_TPU_VMEM_BYTES", default=str(128 << 20), kind="bytes",
    affects_programs=True, scopes=("aot",),
    key_params=("vmem_bytes",),
    accessors=("capacity",),
    help="vmem tier capacity override (core.tiers)",
))
declare(GateSpec(
    "HEAT_TPU_HBM_BYTES", default=str(16 << 30), kind="bytes",
    affects_programs=True, scopes=("plan", "aot"),
    key_params=("hbm_bytes", "hbm_cap", "budget"),
    accessors=("capacity", "hbm_budget_bytes"),
    help="hbm tier capacity override — the SL301 budget, serving admission "
         "limit, and staging slab ceiling (one number, read one way)",
))
declare(GateSpec(
    "HEAT_TPU_HOST_BYTES", default=str(48 << 30), kind="bytes",
    affects_programs=True, scopes=("aot",),
    key_params=("host_bytes",),
    accessors=("capacity",),
    help="host tier capacity override (core.tiers)",
))
declare(GateSpec(
    "HEAT_TPU_SERVING_AOT", default="auto", values=("0", "1", "auto"),
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("enabled", "active_store"),
    help="persistent AOT program cache switch: 0 = hooks never install "
         "(escape hatch), 1 = on, auto = on iff HEAT_TPU_SERVING_CACHE "
         "names a directory. Changes WHERE programs come from, never "
         "their bytes — not key material",
))
declare(GateSpec(
    "HEAT_TPU_SERVING_CACHE", default="~/.cache/heat_tpu/aot", kind="path",
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("cache_dir",),
    help="AOT store root (trust boundary: same write permissions as the "
         "deployment's code). A path, never program-bytes key material",
))
declare(GateSpec(
    "HEAT_TPU_TELEMETRY", default="0", values=("0", "1"),
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("enabled",),
    help="telemetry registry switch — records host-side values only, "
         "changes no program bytes",
))
declare(GateSpec(
    "HEAT_TPU_TRACE", default="auto", values=("0", "1", "auto"),
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("trace_mode", "enabled"),
    help="span tracer + flight-recorder export switch "
         "(observability.tracing): 0 = hard off (the zero-overhead "
         "escape hatch — every probe is one module-bool read), 1 = "
         "collect, auto = follow the telemetry switch. Records "
         "host-side spans only — plans, plan_ids, programs, and AOT "
         "envelope keys are byte-identical at every value "
         "(affects_programs=False by construction, diffed in CI)",
))
declare(GateSpec(
    "HEAT_TPU_RESILIENCE", default="auto", values=("0", "1", "auto"),
    affects_programs=True, scopes=("aot",),
    key_params=(),
    accessors=("resilience_mode", "resilience_enabled"),
    help="elastic fault-tolerant runtime switch (heat_tpu.resilience): "
         "0 = exact pre-resilience paths everywhere (escape hatch — no "
         "checkpoint hooks, no world-epoch guards, no drain fences), "
         "1 = force (the chaos CI leg), auto = engage where the caller "
         "hands the runtime a checkpoint config or watcher. "
         "Conservatively program-affecting: the elastic runtime re-enters "
         "cached programs across world re-resolutions under the epoch "
         "discipline this gate installs, and AOT envelopes exported "
         "before the resilience runtime predate the restore contract's "
         "world re-binding — the roster bump (version_mismatch for "
         "pre-resilience envelopes) is the designed invalidation",
))
declare(GateSpec(
    "HEAT_TPU_CKPT_DIR", default="~/.cache/heat_tpu/ckpt", kind="path",
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("ckpt_dir",),
    help="checkpoint store root (heat_tpu.resilience.checkpoint). TRUST "
         "BOUNDARY like the AOT store: envelopes are integrity-checked "
         "(per-entry sha256) but restore unpickles nothing — still, the "
         "directory must carry the same write permissions as the "
         "deployment's code. A path, never program-bytes key material",
))
declare(GateSpec(
    "HEAT_TPU_LATTICE_PROFILE", default="", kind="path",
    affects_programs=True, scopes=("plan", "aot"),
    key_params=("profile_id", "calibration"),
    accessors=("active_profile", "profile_id"),
    help="measured lattice-profile JSON path (ISSUE 16, "
         "observability.calibration): unset/empty = the hard-coded "
         "core.tiers constants, byte-identical plans/plan_ids/programs "
         "to the pre-calibration era (diffed in CI). Set = bandwidth()/"
         "transfer_time()/penalty() consult the profile's measured "
         "per-edge prices, the planner re-prices candidate selection, "
         "and the profile_id is stamped into plan canonical "
         "serialization — recalibration is a VISIBLE plan_id "
         "invalidation, never silent drift. Unlike the other path "
         "gates this one IS program-affecting: measured prices change "
         "which plan the planner picks. A tampered or "
         "version-mismatched profile is evicted and the constants are "
         "used (never an error)",
))
declare(GateSpec(
    "HEAT_TPU_NUMCHECK_ACC_DIM", default=str(1024), kind="int",
    affects_programs=False, scopes=(),
    key_params=(),
    accessors=("acc_dim_threshold",),
    help="analyzer pass 6 (numcheck) SL601 reduction-extent threshold: "
         "a dot_general/reduce_sum/scan carry accumulating in bf16/f16 "
         "over a contraction/reduction extent >= this value fires "
         "low-precision-accumulation (warning; >= 65536 escalates to "
         "error regardless). Read-only analyzer tuning — changes which "
         "findings a report carries, never any plan, plan_id, program, "
         "or AOT key (affects_programs=False by construction)",
))


# --------------------------------------------------------------------- #
# the accessor                                                          #
# --------------------------------------------------------------------- #
def get(name: str, default: Optional[str] = None) -> Optional[str]:
    """The RAW environment value of a registered gate — the one
    sanctioned ``os.environ`` read for ``HEAT_TPU_*`` names (rule SL403
    flags any other). Semantics are exactly ``os.environ.get(name,
    default)``; per-gate parsing stays with the subsystem accessors
    declared in the spec. Unknown names raise — a read of an undeclared
    gate is the bug the registry exists to prevent."""
    if name not in GATES:
        raise KeyError(
            f"gates.get: {name!r} is not a declared gate — declare it in "
            "heat_tpu/core/gates.py (name, default, affects_programs, "
            "cache scopes) before reading it"
        )
    return os.environ.get(name, default)


def is_set(name: str) -> bool:
    """Is the registered gate explicitly set in the environment?"""
    if name not in GATES:
        raise KeyError(f"gates.is_set: {name!r} is not a declared gate")
    return name in os.environ


# --------------------------------------------------------------------- #
# derivations — what the cache layers key on                            #
# --------------------------------------------------------------------- #
def affecting_programs() -> Tuple[GateSpec, ...]:
    """The registered gates whose value changes the programs the library
    builds, sorted by name — the AOT stamp population."""
    return tuple(
        GATES[name] for name in sorted(GATES) if GATES[name].affects_programs
    )


def scope_gates(scope: str) -> Tuple[GateSpec, ...]:
    """Registered gates that are key material for one cache layer
    (``plan`` / ``program`` / ``aot``), sorted by name."""
    if scope not in SCOPES:
        raise ValueError(f"unknown cache scope {scope!r} (one of {SCOPES})")
    return tuple(
        GATES[name] for name in sorted(GATES) if scope in GATES[name].scopes
    )


def aot_fingerprint() -> Tuple[Tuple[str, str], ...]:
    """``(name, raw value)`` of every gate that must distinguish
    persistent AOT cache keys: registered program-affecting gates that
    are SET in the environment, plus any set ``HEAT_TPU_*`` variable the
    registry does not know (an unknown gate is conservatively key
    material, exactly like the PR 9 prefix scan it replaces — minus the
    scan's serving/telemetry exclusions, which are now the registered
    ``affects_programs=False`` entries). Byte-compatible with the old
    hand-filter at every gate combination; empty at defaults."""
    out = []
    for k, v in os.environ.items():
        if not k.startswith(PREFIX):
            continue
        spec = GATES.get(k)
        if spec is not None:
            if spec.affects_programs:
                out.append((k, v))
        elif not k.startswith(_UNREGISTERED_EXCLUDE):
            out.append((k, v))
    return tuple(sorted(out))


def program_gate_roster() -> str:
    """Comma-joined sorted NAMES of the registered program-affecting
    gates — stamped into every AOT envelope's meta (not its key), so a
    version that registers a new program-affecting gate refuses every
    envelope written under the old roster (``version_mismatch``: the old
    artifacts may predate the gate's subsystem entirely) instead of ever
    serving one stale."""
    return ",".join(s.name for s in affecting_programs())


def accessor_gates() -> Dict[str, Tuple[str, ...]]:
    """``{accessor function name: (gate names...)}`` over every declared
    spec — the analyzer's (SL402) map from a call site to the gates it
    may read. A name shared by several accessors maps to all of them
    (the checker is conservative)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for name in sorted(GATES):
        for acc in GATES[name].accessors:
            out[acc] = out.get(acc, ()) + (name,)
    return out


def snapshot() -> Dict[str, Dict[str, object]]:
    """Declaration + current raw value of every gate — introspection for
    tests and the warmup/diagnostics CLIs."""
    out = {}
    for name, spec in sorted(GATES.items()):
        raw = os.environ.get(name)
        out[name] = {
            "default": spec.default,
            "values": spec.values,
            "kind": spec.kind,
            "affects_programs": spec.affects_programs,
            "scopes": sorted(spec.scopes),
            "key_params": spec.key_params,
            "raw": raw,
            "set": name in os.environ,
            # a set-but-unrecognized raw value resolves to the accessor's
            # default arm — surfaced here so diagnostics can say so
            "recognized": spec.recognizes(raw),
            "help": spec.help,
        }
    return out
