"""The distributed n-dimensional array of heat_tpu.

API parity with /root/reference/heat/core/dndarray.py (class ``DNDarray`` at
dndarray.py:38): a global array with a ``split`` axis, device, communicator
and balance metadata. The representation is TPU-native: instead of a
per-rank local ``torch.Tensor`` plus MPI metadata, a ``DNDarray`` wraps ONE
global ``jax.Array`` carrying a GSPMD ``NamedSharding`` derived from
``split`` over the communicator's device mesh. Consequences:

- ``resplit_`` (reference dndarray.py:1406: Allgatherv / local slice /
  tile-wise Isend-Irecv) is a single resharding ``jax.device_put``; XLA
  emits the equivalent collectives over ICI.
- ``redistribute_`` (reference dndarray.py:1207: pairwise Send/Recv to an
  arbitrary ragged layout) is a no-op: GSPMD layouts are canonically
  balanced, so ``balanced`` is always True and ``balance_`` returns
  immediately (reference dndarray.py:500).
- in-place metadata methods keep their reference names but rebind the
  wrapped (immutable) jax.Array on the Python object.
- ``larray`` (reference: the rank-local torch tensor, dndarray.py:139) is
  the process-local view; under single-controller it is the global array.
"""

from __future__ import annotations

import math
import numpy as np

import jax
import jax.numpy as jnp

from typing import Any, Iterable, List, Optional, Tuple, Union

from . import types
from .communication import Communication, MeshCommunication, sanitize_comm
from .devices import Device
from .stride_tricks import sanitize_axis
from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry

__all__ = ["DNDarray"]

Communication_t = Communication


class LocalIndex:
    """Marker wrapper for indexing the process-local array directly
    (reference: dndarray.py:28 ``LocalIndex``)."""

    def __init__(self, obj):
        self.obj = obj


class _LocalAccessor:
    """``DNDarray.lloc`` accessor (reference dndarray.py ``lloc``): index
    the process-local data directly. Single-controller: the local data IS
    the logical global array, so this delegates to the DNDarray indexing
    machinery — same bounds discipline (IndexError on out-of-range basic
    keys, like the reference's torch-backed lloc), same DNDarray-value
    unwrapping, same fused physical-scatter fast path for basic keys."""

    __slots__ = ("_dnd",)

    def __init__(self, dnd: "DNDarray"):
        self._dnd = dnd

    def __getitem__(self, key):
        d = self._dnd
        if not isinstance(key, (DNDarray, jax.Array, np.ndarray)):
            basic = d._DNDarray__normalize_basic_key(key)
            if basic is not None:
                return d.larray[basic]
        if isinstance(key, DNDarray):
            key = key.larray
        elif isinstance(key, tuple):
            key = tuple(k.larray if isinstance(k, DNDarray) else k for k in key)
        return d.larray[key]

    def __setitem__(self, key, value):
        self._dnd[key] = value


class DNDarray:
    """Distributed n-dimensional array over a TPU/CPU device mesh.

    Parameters
    ----------
    array : jax.Array
        The global array data (sharded or replicated on the mesh).
    gshape : tuple of int
        Global shape.
    dtype : datatype
        heat_tpu type.
    split : int or None
        Axis the array is sharded along, or None for replicated.
    device : Device
        Platform the array resides on.
    comm : Communication
        Communicator (device mesh).
    balanced : bool
        Kept for reference-API parity; GSPMD layouts are always balanced.
    """

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype: type,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: bool = True,
    ):
        self.__array = array
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = types.degrade64(dtype)
        # complex platform policy: the ONE choke point every creation
        # passes through. mode "refuse" fails actionably at construction
        # (not with a raw backend UNIMPLEMENTED at first use); mode
        # "planar" requires the planar physical layout — float planes
        # with a trailing plane axis of 2 (see core/complex_planar.py)
        self.__planar = False
        if types.heat_type_is_complexfloating(self.__dtype):
            from . import devices as _dev

            mode = _dev.complex_mode()
            if mode == "planar":
                planar_ok = (
                    jnp.issubdtype(array.dtype, jnp.floating)
                    and array.ndim == len(self.__gshape) + 1
                    and array.shape[-1] == 2
                )
                if not planar_ok:
                    from . import complex_planar as _cp

                    raise _cp.policy_error(
                        "constructing a complex DNDarray from native complex data"
                    )
                self.__planar = True
                self.__dtype = types.complex64  # planes are f32
            else:
                types.check_complex_platform(self.__dtype)
        self.__split = split if split is None else int(split) % max(len(gshape), 1)
        self.__device = device
        self.__comm = comm
        self.__balanced = True
        self.__lshape_map = None
        self.__halo_next = None
        self.__halo_prev = None
        self.__halos = None
        self.__partitions_dict__ = None

    # ------------------------------------------------------------------ #
    # properties                                                         #
    # ------------------------------------------------------------------ #
    @property
    def balanced(self) -> bool:
        """GSPMD shardings are always (near-)balanced (reference
        dndarray.py:221 tracks raggedness; no analog here)."""
        return True

    @property
    def comm(self) -> Communication:
        return self.__comm

    @comm.setter
    def comm(self, comm: Communication):
        self.__comm = sanitize_comm(comm)

    @property
    def device(self) -> Device:
        return self.__device

    @device.setter
    def device(self, device):
        from .devices import sanitize_device

        device = sanitize_device(device)
        if device != self.__device:
            raise NotImplementedError("use DNDarray.cpu()/to() to move arrays between platforms")

    @property
    def dtype(self):
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def halo_next(self):
        return self.__halo_next

    @property
    def halo_prev(self):
        return self.__halo_prev

    @property
    def larray(self) -> jax.Array:
        """The process-local LOGICAL data. Single-controller: the global
        jax.Array with any pad sliced off (per-device physical shards are
        ``_phys.addressable_shards``). Planar complex arrays refuse this
        accessor — their physical layout is plane-split (see
        ``core/complex_planar.py``), so any unported code path that would
        read it fails loudly instead of computing on wrong shapes."""
        if self.__planar:
            from . import complex_planar as _cp

            raise _cp.policy_error("this operation (it reads the local array directly)")
        from . import _padding

        return _padding.unpad(self.__array, self.__gshape, self.__split)

    @larray.setter
    def larray(self, array: jax.Array):
        """Rebind local data from a LOGICAL array (reference
        dndarray.py:150: warns that local shapes must stay consistent —
        same caveat applies)."""
        if self.__planar:
            from . import complex_planar as _cp

            raise _cp.policy_error("rebinding the local array of a complex DNDarray")
        if not isinstance(array, jax.Array):
            array = jnp.asarray(array)
        self.__gshape = tuple(int(s) for s in array.shape)
        self.__dtype = types.canonical_heat_type(array.dtype)
        if self.__split is not None and self.__split >= len(self.__gshape):
            self.__split = None
        self.__array = self.__comm.shard(array, self.__split)
        self._invalidate_caches()

    @property
    def _phys(self) -> jax.Array:
        """The physical (padded) global array. Pad region is zero by
        framework invariant (see ``_padding``). Planar complex arrays
        refuse this accessor (plane-split layout, see ``larray``);
        planar-aware code uses ``_planar_phys``."""
        if self.__planar:
            from . import complex_planar as _cp

            raise _cp.policy_error("this operation (it reads the physical array directly)")
        return self.__array

    @property
    def _is_planar(self) -> bool:
        """True when this is a planar complex array (f32 planes with a
        trailing plane axis — ``core/complex_planar.py``)."""
        return self.__planar

    @property
    def _planar_phys(self) -> jax.Array:
        """The padded plane array of a planar complex DNDarray, shape
        ``phys_shape(gshape, split) + (2,)``."""
        if not self.__planar:
            raise TypeError("_planar_phys on a non-planar DNDarray")
        return self.__array

    def _set_phys(self, array: jax.Array) -> None:
        """Rebind the physical array (shape must equal the physical shape;
        pad region must be zero)."""
        if self.__planar:
            from . import complex_planar as _cp

            raise _cp.policy_error("rebinding the physical array of a complex DNDarray")
        self.__array = array
        self.__dtype = types.canonical_heat_type(array.dtype)
        self._invalidate_caches()

    def _invalidate_caches(self) -> None:
        """Drop caches derived from the physical array (lshape map, halo
        arrays) — must run on every rebind of the underlying buffer, else
        ``array_with_halos``/``halo_prev``/``halo_next`` serve stale data."""
        self.__lshape_map = None
        self.__halos = None
        self.__halo_prev = None
        self.__halo_next = None

    @property
    def lloc(self) -> "_LocalAccessor":
        """Local-index accessor (reference dndarray.py lloc): read/write
        the process-local (physical) data without global translation."""
        return _LocalAccessor(self)

    @property
    def nbytes(self) -> int:
        """Total bytes of the global array (reference dndarray.py:176)."""
        return self.__gnumel() * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def gnbytes(self) -> int:
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        """Bytes of the device-0 shard, consistent with chunk geometry."""
        return self.lnumel * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def gnumel(self) -> int:
        return self.__gnumel()

    def __gnumel(self) -> int:
        return int(np.prod(self.__gshape)) if self.__gshape else 1

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape))

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of the shard on device 0 (reference: the rank-local shape,
        dndarray.py:295)."""
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split)
        return lshape

    @property
    def lshape_map(self) -> np.ndarray:
        """(comm.size, ndim) map of all shard shapes (reference
        dndarray.py:303; computed from geometry — no Allreduce)."""
        if self.__lshape_map is None:
            self.__lshape_map = self.__comm.lshape_map(self.__gshape, self.__split)
        return self.__lshape_map.copy()

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def numdims(self) -> int:
        return self.ndim

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def size(self) -> int:
        return self.__gnumel()

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def stride(self) -> Tuple[int, ...]:
        """C-order element strides of the global array (reference
        dndarray.py:332 returns torch strides)."""
        strides = [1] * self.ndim
        for i in range(self.ndim - 2, -1, -1):
            strides[i] = strides[i + 1] * self.__gshape[i + 1]
        return tuple(strides)

    @property
    def strides(self) -> Tuple[int, ...]:
        itemsize = np.dtype(self.__dtype.jax_type()).itemsize
        return tuple(s * itemsize for s in self.stride)

    @property
    def T(self) -> "DNDarray":
        from .linalg import transpose

        return transpose(self, None)

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def array_with_halos(self) -> jax.Array:
        """Physical array with per-shard halos attached (reference
        dndarray.py:359: the rank-local tensor including halos). Runs ONE
        jitted shard_map ``ppermute`` edge exchange (``parallel.
        halo_exchange``); each device's block becomes
        ``[prev-halo | block | next-halo]`` with zero outermost halos.
        Requires a prior ``get_halo`` call; without one (or with
        halo_size=0) returns the physical array unchanged."""
        return self.__cat_halo()

    @property
    def __partitioned__(self) -> dict:
        """Partition interface (reference dndarray.py:188-203)."""
        if self.__partitions_dict__ is None:
            self.__partitions_dict__ = self.create_partition_interface()
        return self.__partitions_dict__

    # ------------------------------------------------------------------ #
    # conversions / data access                                          #
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to ``dtype`` (reference dndarray.py:456). Pad-safe: casts
        preserve zero."""
        dtype = types.canonical_heat_type(dtype)
        target_complex = types.heat_type_is_complexfloating(types.degrade64(dtype))
        if self.__planar or target_complex:
            from . import complex_planar as _cp

            if self.__planar and target_complex:
                # complex -> complex: planes unchanged (c128 degrades)
                if not copy:
                    return self
                return _cp.wrap(self.__array, self.__gshape, self.__split, self.__device, self.__comm)
            if self.__planar:
                # complex -> real: take the real plane (the same silent
                # imag-discard the native .astype path performs)
                real_phys = self.__array[..., 0].astype(dtype.jax_type())
                if not copy:
                    self.__array = real_phys
                    self.__dtype = dtype
                    self.__planar = False
                    self._invalidate_caches()
                    return self
                return DNDarray(real_phys, self.__gshape, dtype, self.__split, self.__device, self.__comm)
            if _cp.active():
                # real -> complex under the planar policy: zero imag plane
                res = _cp.to_planar(self)
                if not copy:
                    self.__array = res._planar_phys
                    self.__dtype = types.complex64
                    self.__planar = True
                    self._invalidate_caches()
                    return self
                return res
            # native/refuse modes: refuse raises, native falls through
            types.check_complex_platform(types.degrade64(dtype))
        casted = self.__array.astype(dtype.jax_type())
        if not copy:
            self.__array = casted
            self.__dtype = dtype
            self._invalidate_caches()
            return self
        return DNDarray(casted, self.__gshape, dtype, self.__split, self.__device, self.__comm)

    def __host_logical(self) -> np.ndarray:
        """Global LOGICAL array on the host (bf16 upcast to f32, pad
        sliced off). In multi-process mode the array spans non-addressable
        devices; the host copy comes from a cross-process allgather (the
        analog of the reference's Allgatherv in resplit(None)). Shared by
        numpy()/cpu() so no caller can forget the pad slice."""
        if self.__planar:
            from . import complex_planar as _cp

            return _cp.host_complex(self)
        arr = self.__array
        if self.__dtype is types.bfloat16:
            arr = arr.astype(jnp.float32)
        if jax.process_count() > 1 and not arr.is_fully_addressable:
            from jax.experimental import multihost_utils

            host = np.asarray(multihost_utils.process_allgather(arr, tiled=True))
        else:
            host = np.asarray(jax.device_get(arr))
        if host.shape != tuple(self.__gshape):
            host = host[tuple(slice(0, s) for s in self.__gshape)]
        return host

    def numpy(self) -> np.ndarray:
        """Global array as numpy (reference dndarray.py:1168: resplit(None)
        + local numpy; here a device-to-host gather, pad sliced on host)."""
        return self.__host_logical()

    def __array__(self, dtype=None) -> np.ndarray:
        out = self.numpy()
        if dtype is not None:
            out = out.astype(dtype)
        return out

    def tolist(self, keepsplit: bool = False) -> list:
        """Global array as (nested) Python list (reference dndarray.py:...)."""
        return self.numpy().tolist()

    def item(self):
        """The single element as a Python scalar (reference dndarray.py:1143)."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to Python scalars")
        return self.numpy().reshape(()).item()

    def __bool__(self) -> bool:
        return bool(self.__cast_scalar(bool))

    def __float__(self) -> float:
        return self.__cast_scalar(float)

    def __int__(self) -> int:
        return self.__cast_scalar(int)

    def __complex__(self) -> complex:
        return self.__cast_scalar(complex)

    def __cast_scalar(self, cast):
        if self.size != 1:
            raise TypeError(f"only size-1 arrays can be converted to Python scalars, got shape {self.shape}")
        return cast(self.numpy().reshape(()).item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------ #
    # distribution management                                            #
    # ------------------------------------------------------------------ #
    def is_distributed(self) -> bool:
        """True if data live on more than one device (reference
        dndarray.py:480)."""
        return self.__split is not None and self.__comm.is_distributed()

    def is_balanced(self, force_check: bool = False) -> bool:
        return True

    def balance_(self) -> None:
        """Balance shards (reference dndarray.py:500). GSPMD layouts are
        canonical — nothing to do (the counter records that a caller
        ported from the reference still expected a data movement here)."""
        if _telemetry._ENABLED:
            _telemetry.inc("dndarray.balance.noop")
        return None

    def create_lshape_map(self, force_check: bool = False) -> np.ndarray:
        return self.lshape_map

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device counts and displacements along split (reference
        dndarray.py:~290)."""
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray. Cannot calculate counts and displacements.")
        counts, displs, _ = self.__comm.counts_displs_shape(self.__gshape, self.__split)
        return counts, displs

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution along a new split axis (reference
        dndarray.py:1406: Allgatherv / slice / tiled Isend-Irecv chains).
        Routed through the redistribution planner
        (``ht.redistribution``): the move executes as a cost-modeled
        collective schedule — direct/chunked all-to-all, ppermute ring,
        or the explicit replicate all-gather — under the configured
        peak-memory budget. ``ht.redistribution.explain(self, axis)``
        shows the plan this call will run."""
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        if _telemetry._ENABLED:
            _telemetry.inc("dndarray.resplit.calls")
            _obs_events.emit(
                "dndarray.resplit", gshape=self.__gshape,
                old_split=self.__split, new_split=axis, in_place=True,
            )
        self.__array = self.__comm.reshard_phys(self.__array, self.__gshape, self.__split, axis)
        self.__split = axis
        self._invalidate_caches()
        return self

    def resplit(self, axis: Optional[int] = None) -> "DNDarray":
        """Out-of-place resplit (reference manipulations.py:3479).
        Planner-routed like :meth:`resplit_`; see
        ``ht.redistribution.explain``."""
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return DNDarray(
                self.__array, self.__gshape, self.__dtype, self.__split, self.__device, self.__comm
            )
        if _telemetry._ENABLED:
            _telemetry.inc("dndarray.resplit.calls")
            _obs_events.emit(
                "dndarray.resplit", gshape=self.__gshape,
                old_split=self.__split, new_split=axis, in_place=False,
            )
        arr = self.__comm.reshard_phys(self.__array, self.__gshape, self.__split, axis)
        return DNDarray(arr, self.__gshape, self.__dtype, axis, self.__device, self.__comm)

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """Arbitrary re-layout along split (reference dndarray.py:1207).
        GSPMD owns physical layout; only canonical layouts exist, so this
        is a no-op that validates its arguments."""
        if self.__split is None:
            return None
        if target_map is not None:
            target_map = np.asarray(target_map)
            if tuple(target_map.shape) != (self.__comm.size, self.ndim):
                raise ValueError(
                    f"target_map must have shape {(self.__comm.size, self.ndim)}, got {tuple(target_map.shape)}"
                )
            if int(target_map[:, self.__split].sum()) != self.__gshape[self.__split]:
                raise ValueError("target_map does not conserve the global split extent")
        return None

    def collect_(self, target_rank: int = 0) -> None:
        """Gather the whole array to one device (reference dndarray.py:572).
        Realized as replication onto the target device."""
        if not isinstance(target_rank, int):
            raise TypeError(f"target rank must be int, got {type(target_rank)}")
        if target_rank >= self.__comm.size:
            raise ValueError("target rank is out of bounds")
        from . import _padding

        if _telemetry._ENABLED:
            _telemetry.inc("dndarray.collect.calls")
            _obs_events.emit(
                "dndarray.collect", gshape=self.__gshape,
                old_split=self.__split, target_rank=target_rank,
            )
        device = self.__comm.devices[target_rank]
        logical = _padding.unpad(self.__array, self.__gshape, self.__split)
        self.__array = jax.device_put(logical, jax.sharding.SingleDeviceSharding(device))
        self.__split = None
        self._invalidate_caches()

    def fill_diagonal(self, value) -> "DNDarray":
        """Fill the main diagonal (reference dndarray.py:~600)."""
        if self.ndim != 2:
            raise ValueError("Only 2D arrays supported")
        n = min(self.__gshape)
        idx = jnp.arange(n)
        new = self.larray.at[idx, idx].set(jnp.asarray(value, dtype=self.__array.dtype))
        self.__array = self.__comm.shard(new, self.__split)
        self._invalidate_caches()
        return self

    # ------------------------------------------------------------------ #
    # halos (reference dndarray.py:386-454)                              #
    # ------------------------------------------------------------------ #
    def get_halo(self, halo_size: int, prev: bool = True, next: bool = True) -> None:
        """Fetch halos of size ``halo_size`` from neighboring shards along
        the split axis (reference dndarray.py:386: Isend/Irecv with the
        prev/next populated rank). Runs ONE jitted shard_map ``ppermute``
        edge exchange over the mesh (``parallel.halo_exchange``) and caches
        the halo'ed physical array for ``array_with_halos``; per-device
        halo views are exposed through ``halo_prev``/``halo_next``.

        Divergence from the reference: the exchange is between physically
        adjacent shards (GSPMD blocks), so a fully-padded tail shard hands
        its zero pad onward instead of being skipped — consumers of the
        zero-pad invariant (e.g. ``signal.convolve``) are built for that.
        """
        if not isinstance(halo_size, int):
            raise TypeError(f"halo_size needs to be of Python type integer, {type(halo_size)} given")
        if halo_size < 0:
            raise ValueError(f"halo_size needs to be a positive integer, {halo_size} given")
        if not self.is_distributed() or halo_size == 0:
            self.__halo_prev = None
            self.__halo_next = None
            self.__halos = None
            return
        split = self.__split
        populated = self.lshape_map[:, split]
        nonempty = [r for r in range(self.__comm.size) if populated[r] > 0]
        if len(nonempty) > 1 and halo_size > int(populated[np.array(nonempty)].min()):
            raise ValueError("halo_size exceeds the smallest local shard extent")

        from . import parallel

        hp = halo_size if prev else 0
        hn = halo_size if next else 0
        halod = parallel.halo_exchange(
            self.__array, self.__comm.mesh, self.__comm.axis_name, split, hp, hn
        )
        self.__halos = (hp, hn, halod)

        # per-device halo views (reference: the rank-local halo tensors)
        size = self.__comm.size
        ext = halod.shape[split] // size  # hp + block + hn
        halo_prev: List[Optional[jax.Array]] = [None] * size
        halo_next: List[Optional[jax.Array]] = [None] * size
        for r in range(size):
            base = r * ext
            if hp and r > 0:
                sl = [slice(None)] * self.ndim
                sl[split] = slice(base, base + hp)
                halo_prev[r] = halod[tuple(sl)]
            if hn and r < size - 1:
                sl = [slice(None)] * self.ndim
                sl[split] = slice(base + ext - hn, base + ext)
                halo_next[r] = halod[tuple(sl)]
        self.__halo_prev = halo_prev
        self.__halo_next = halo_next

    def __cat_halo(self) -> jax.Array:
        """Physical array with per-shard halos from the last ``get_halo``
        (reference dndarray.py:359). Without one, the physical array."""
        if self.__halos is None:
            return self.__array
        return self.__halos[2]

    # ------------------------------------------------------------------ #
    # partition interface (reference dndarray.py:188/679)                #
    # ------------------------------------------------------------------ #
    def create_partition_interface(self) -> dict:
        """Cross-framework ``__partitioned__`` dict (reference
        dndarray.py:679, modeled on the Dask/daal4py protocol)."""
        lshape_map = self.lshape_map
        split = self.__split
        size = self.__comm.size
        tiling = [1] * self.ndim
        if split is not None:
            tiling[split] = size
        partitions = {}
        for r in range(size):
            offset, lshape, _ = self.__comm.chunk(self.__gshape, split, rank=r)
            start = [0] * self.ndim
            if split is not None:
                start[split] = offset
            pos = [0] * self.ndim
            if split is not None:
                pos[split] = r
            partitions[tuple(pos)] = {
                "start": tuple(start),
                "shape": tuple(int(x) for x in lshape),
                "data": None,
                "location": [r],
                "dtype": self.__dtype.jax_type(),
                "device": str(self.__comm.devices[r]) if r < len(self.__comm.devices) else None,
            }
        # populate data refs from addressable shards
        dev_to_pos = {id(d): r for r, d in enumerate(self.__comm.devices)}
        for shard in self.__array.addressable_shards:
            r = dev_to_pos.get(id(shard.device))
            if r is None:
                continue
            for pos, part in partitions.items():
                if part["location"] == [r]:
                    part["data"] = shard.data
        return {
            "shape": self.__gshape,
            "partition_tiling": tuple(tiling),
            "partitions": partitions,
            "locals": [tuple(p) for p in partitions],
            "get": lambda x: x,
        }

    # ------------------------------------------------------------------ #
    # indexing                                                           #
    # ------------------------------------------------------------------ #
    def __process_key(self, key):
        """Normalize an indexing key; returns (key, output_split)."""
        from .dndarray import DNDarray as _D

        def conv(k):
            if isinstance(k, _D):
                return k.larray
            if isinstance(k, (list, np.ndarray)):
                return jnp.asarray(k)
            return k

        if isinstance(key, tuple):
            key = tuple(conv(k) for k in key)
        else:
            key = conv(key)

        split = self.__split
        if split is None:
            return key, None

        # determine what happens to the split axis
        keys = key if isinstance(key, tuple) else (key,)
        # expand ellipsis
        n_explicit = sum(1 for k in keys if k is not None and k is not Ellipsis)
        keys_expanded: List[Any] = []
        for k in keys:
            if k is Ellipsis:
                keys_expanded.extend([slice(None)] * (self.ndim - n_explicit))
            else:
                keys_expanded.append(k)
        while len([k for k in keys_expanded if k is not None]) < self.ndim:
            keys_expanded.append(slice(None))

        # walk input dims → output dims
        out_split = None
        in_dim = 0
        out_dim = 0
        saw_advanced = False
        for k in keys_expanded:
            if k is None:
                out_dim += 1
                continue
            if isinstance(k, (int, np.integer)) or (hasattr(k, "ndim") and getattr(k, "ndim", 1) == 0 and not isinstance(k, slice)):
                if in_dim == split:
                    out_split = None
                    saw_advanced = True  # dim dropped; replicate result
                in_dim += 1
                continue
            if isinstance(k, slice):
                if in_dim == split:
                    out_split = out_dim
                in_dim += 1
                out_dim += 1
                continue
            # advanced index (array/bool mask)
            if in_dim == split:
                saw_advanced = True
                out_split = None
            adv_ndim = getattr(k, "ndim", 1)
            if getattr(k, "dtype", None) is not None and k.dtype == jnp.bool_:
                in_dim += adv_ndim
            else:
                in_dim += 1
            out_dim += 1
        return key, out_split

    def __getitem__(self, key) -> Union["DNDarray", Any]:
        """Global indexing (reference dndarray.py:827-1084: rank-local
        slicing plus comm; here jnp indexing + a sharding constraint)."""
        if self.__planar:
            from . import complex_planar as _cp

            if isinstance(key, (LocalIndex, DNDarray, jax.Array, np.ndarray)):
                raise _cp.policy_error("advanced indexing on a complex array")
            basic = self.__normalize_basic_key(key)
            if basic is None:
                raise _cp.policy_error("advanced indexing on a complex array")
            # basic keys cover the logical dims; the plane axis rides along
            result = _cp._planar_view(self)[basic]
            gshape = tuple(int(s) for s in result.shape[:-1])
            # preserve the split when the key slices (not drops) its axis:
            # re-sharding replicated would all-gather the selection
            out_split = None
            if self.__split is not None and isinstance(basic[self.__split], slice):
                out_split = self.__split - sum(
                    1 for k in basic[: self.__split] if isinstance(k, int)
                )
                if out_split >= len(gshape) or gshape[out_split] <= 1:
                    out_split = None
            return DNDarray(
                self.__comm.shard(result, out_split), gshape, types.complex64,
                out_split, self.__device, self.__comm,
            )
        if isinstance(key, LocalIndex):
            return self.__array[key.obj]
        if isinstance(key, DNDarray) and key.dtype == types.bool:
            # boolean mask → data-dependent output shape. Distributed
            # arrays run the gather-free per-shard count + balanced
            # compaction (parallel.compact_select) — the reference's
            # rank-local mask selection (dndarray.py:827-1084) with even
            # blocks; the operand is never all-gathered. Everything else
            # evaluates eagerly on the logical array.
            comm = self.__comm
            if (
                self.__split is not None
                and comm.is_distributed()
                and self.ndim > 0
                and 0 not in self.__gshape  # zero-extent arrays are stored
                # replicated (comm.shard), which the shard_map path rejects
            ):
                from . import parallel as _parallel

                elements = tuple(key.gshape) == tuple(self.__gshape)
                rows = (
                    not elements
                    and key.ndim == 1
                    and self.ndim > 1
                    and key.gshape[0] == self.__gshape[0]
                )
                if elements or rows:
                    arr = self if self.__split == 0 else self.resplit(0)
                    if key.split == 0 and tuple(key._phys.shape[:1]) == tuple(arr._phys.shape[:1]):
                        mask_phys = key._phys
                    else:
                        mask_phys = comm.shard(key.larray, 0)
                    data_phys, n_sel = _parallel.compact_select(
                        arr._phys, mask_phys, comm.mesh, comm.axis_name, rows
                    )
                    gshape = (n_sel,) + (tuple(self.__gshape[1:]) if rows else ())
                    if n_sel == 0:
                        data_phys = comm.shard(data_phys, 0)
                    return DNDarray(
                        data_phys, gshape, self.__dtype, 0, self.__device, comm
                    )
            result = self.larray[key.larray]
            out_split = 0 if self.__split is not None and result.ndim > 0 else None
            gshape = tuple(int(s) for s in result.shape)
            if out_split is not None:
                result = self.__comm.shard(result, out_split)
            return DNDarray(result, gshape, self.__dtype, out_split, self.__device, self.__comm)
        key, out_split = self.__process_key(key)
        result = self.larray[key]
        if not isinstance(result, jax.Array):
            result = jnp.asarray(result)
        gshape = tuple(int(s) for s in result.shape)
        if out_split is not None and out_split < result.ndim and result.shape[out_split] >= 1:
            result = self.__comm.shard(result, out_split)
        else:
            out_split = None
        return DNDarray(result, gshape, self.__dtype, out_split, self.__device, self.__comm)

    def __normalize_basic_key(self, key):
        """Resolve an int/slice/Ellipsis key against the LOGICAL shape, or
        None when the key is advanced (arrays, masks, newaxis). Explicit
        bounds matter: a bare ``slice(None)`` on the split dim would span
        the physical pad region."""
        keys = key if isinstance(key, tuple) else (key,)
        # bool is an int subclass but numpy gives it broadcast (not index)
        # semantics — route it to the advanced path
        if any(
            isinstance(k, (bool, np.bool_))
            or not (k is Ellipsis or isinstance(k, (int, np.integer, slice)))
            for k in keys
        ):
            return None
        n_explicit = sum(1 for k in keys if k is not Ellipsis)
        if n_explicit > self.ndim:
            raise IndexError(
                f"too many indices for array: array is {self.ndim}-dimensional, "
                f"but {n_explicit} were indexed"
            )
        out = []
        dim = 0
        for k in keys:
            if k is Ellipsis:
                for _ in range(self.ndim - n_explicit):
                    out.append(slice(0, self.__gshape[dim], 1))
                    dim += 1
                continue
            if isinstance(k, (int, np.integer)):
                k = int(k)
                if k < 0:
                    k += self.__gshape[dim]
                if not 0 <= k < self.__gshape[dim]:
                    raise IndexError(
                        f"index {k} out of bounds for axis {dim} with size {self.__gshape[dim]}"
                    )
                out.append(k)
            else:
                start, stop, step = k.indices(self.__gshape[dim])
                if len(range(start, stop, step)) == 0:
                    # empty selection; also covers the clamped start=-1 a
                    # below-range negative-step start produces, which jax
                    # would reinterpret as "the last element"
                    out.append(slice(0, 0, 1))
                elif step < 0 and stop < 0:
                    # slice.indices yields stop=-1 for "past the front";
                    # jax would reinterpret that as size-1 — use None
                    out.append(slice(start, None, step))
                else:
                    out.append(slice(start, stop, step))
            dim += 1
        while dim < self.ndim:
            out.append(slice(0, self.__gshape[dim], 1))
            dim += 1
        return tuple(out)

    def __setitem__(self, key, value) -> None:
        """Global assignment (reference dndarray.py:1537). Rebinds the
        functional update ``at[key].set`` under the original sharding.

        Basic keys (ints/slices) scatter directly on the PHYSICAL array —
        one fused update preserving the sharding, no unpad/repad round
        trip (normalized bounds keep the pad region untouched). Advanced
        keys fall back to the logical path.
        """
        if self.__planar:
            from . import complex_planar as _cp

            raise _cp.policy_error("item assignment on a complex array")
        if isinstance(key, LocalIndex):
            self.__array = self.__array.at[key.obj].set(jnp.asarray(value))
            self._invalidate_caches()
            return
        if isinstance(value, DNDarray):
            value = value.larray
        value = jnp.asarray(value, dtype=self.__dtype.jax_type()) if not isinstance(value, jax.Array) else value.astype(self.__dtype.jax_type())
        if not isinstance(key, (DNDarray, jax.Array, np.ndarray)):
            basic = self.__normalize_basic_key(key)
            if basic is not None:
                self.__array = self.__array.at[basic].set(value)
                self._invalidate_caches()
                return
        if isinstance(key, DNDarray):
            key = key.larray
        elif isinstance(key, tuple):
            key = tuple(k.larray if isinstance(k, DNDarray) else k for k in key)

        # advanced-key fast paths on the PHYSICAL array: the pad lives at
        # the global tail, so logical index i IS physical index i — an
        # integer-array or bool-mask scatter that only names logical
        # positions can run in place, skipping the unpad→set→reshard round
        # trip of the general path
        phys = self.__array
        if (
            isinstance(key, (jax.Array, np.ndarray))
            and getattr(key, "dtype", None) is not None
        ):
            if key.dtype == jnp.bool_ and tuple(key.shape) == self.__gshape:
                if phys.shape != tuple(self.__gshape):
                    widths = [
                        (0, p - g) for p, g in zip(phys.shape, self.__gshape)
                    ]
                    key = jnp.pad(jnp.asarray(key), widths)  # pad rows: False
                if np.ndim(value) == 0:
                    # scalar fill: a sharded where() — no boolean-index
                    # expansion (host-concrete nonzero), so it works even
                    # when shards span other processes
                    self.__array = jnp.where(
                        key, jnp.asarray(value, dtype=phys.dtype), phys
                    )
                    self._invalidate_caches()
                    return
                if not phys.is_fully_addressable:
                    # at[mask].set with a value ARRAY expands the mask via
                    # a concrete host-side nonzero, which cannot see
                    # non-addressable shards — fail loudly instead of
                    # crashing inside JAX (ADVICE r2)
                    raise NotImplementedError(
                        "boolean-mask assignment with a per-element value array "
                        "is not supported in a multi-process world; use a "
                        "scalar value or ht.where"
                    )
                self.__array = phys.at[key].set(value)
                self._invalidate_caches()
                return
            if (
                jnp.issubdtype(key.dtype, jnp.integer)
                and self.ndim >= 1
                and phys.shape[1:] == tuple(self.__gshape[1:])
            ):
                # non-indexed dims must be pad-free (split in {None, 0}) or
                # the value's broadcast would span the pad region
                n0 = self.__gshape[0]
                # widen to signed: an unsigned key would promote -n0 into
                # its own domain (valid all-False → silent drop) and a
                # narrow int8/int16 key cannot hold the physical-extent
                # sentinel
                k = jnp.asarray(key).astype(types.index_jax_type())
                # out-of-range logical indices must NOT land in the pad
                # region (physically in-bounds would corrupt the zero-pad
                # invariant TSQR etc. rely on): remap anything outside
                # [-n0, n0) past the PHYSICAL extent and drop it — the
                # same silent-drop the logical at[] path had, without a
                # host-side bounds check (a ~90 ms sync over the tunnel)
                valid = (k >= -n0) & (k < n0)
                k = jnp.where(valid, jnp.where(k < 0, k + n0, k), phys.shape[0])
                self.__array = phys.at[k].set(value, mode="drop")
                self._invalidate_caches()
                return

        new = self.larray.at[key].set(value)
        self.__array = self.__comm.shard(new, self.__split)
        self._invalidate_caches()

    # ------------------------------------------------------------------ #
    # misc protocol                                                      #
    # ------------------------------------------------------------------ #
    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    def __str__(self) -> str:
        from . import printing

        return printing.__str__(self)

    def __copy__(self) -> "DNDarray":
        return DNDarray(
            self.__array, self.__gshape, self.__dtype, self.__split, self.__device, self.__comm
        )

    def __deepcopy__(self, memo) -> "DNDarray":
        new = DNDarray(
            jnp.array(self.__array), self.__gshape, self.__dtype, self.__split, self.__device, self.__comm
        )
        memo[id(self)] = new
        return new

    def copy(self) -> "DNDarray":
        from . import memory

        return memory.copy(self)

    def flatten(self) -> "DNDarray":
        from . import manipulations

        return manipulations.flatten(self)

    def ravel(self) -> "DNDarray":
        from . import manipulations

        return manipulations.ravel(self)

    def reshape(self, *shape, **kwargs) -> "DNDarray":
        from . import manipulations

        return manipulations.reshape(self, *shape, **kwargs)

    def squeeze(self, axis=None) -> "DNDarray":
        from . import manipulations

        return manipulations.squeeze(self, axis)

    def expand_dims(self, axis) -> "DNDarray":
        from . import manipulations

        return manipulations.expand_dims(self, axis)

    def transpose(self, axes=None) -> "DNDarray":
        from .linalg import transpose

        return transpose(self, axes)

    def cpu(self) -> "DNDarray":
        """Copy to CPU platform (reference dndarray.py: cpu())."""
        from .devices import cpu as cpu_device
        from .communication import MeshCommunication

        if self.__device.device_type == "cpu":
            return self
        comm = MeshCommunication(cpu_device.jax_devices()[: max(1, self.__comm.size)])
        # shared gather helper: cross-process allgather + pad slice (the
        # cpu comm re-pads for ITS size, which may differ from the source)
        arr = jnp.asarray(self.__host_logical())
        if self.__dtype is types.bfloat16:
            arr = arr.astype(jnp.bfloat16)
        arr = comm.shard(arr, self.__split)
        return DNDarray(arr, self.__gshape, self.__dtype, self.__split, cpu_device, comm)

    def __getattr__(self, name):
        raise AttributeError(f"'DNDarray' object has no attribute '{name}'")
