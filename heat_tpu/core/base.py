"""scikit-learn-style estimator API.

API parity with /root/reference/heat/core/base.py (``BaseEstimator`` :13,
``ClassificationMixin`` :96, ``TransformMixin`` :143, ``ClusteringMixin``
:184, ``RegressionMixin`` :215, ``is_*`` helpers :260-309). Pure Python —
identical role here; estimators built on the ``ht.*`` array API inherit
distribution for free.
"""

from __future__ import annotations

import inspect

from typing import Any, Dict, List, TypeVar

from .dndarray import DNDarray

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_clusterer",
    "is_regressor",
    "is_transformer",
]

self_t = TypeVar("self_t")


class BaseEstimator:
    """Abstract base for all estimators: hyperparameter get/set and repr
    (reference: base.py:13)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return sorted(
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        )

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        """Hyperparameters of this estimator (reference: base.py get_params)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self: self_t, **params: Dict[str, Any]) -> self_t:
        """Set hyperparameters (reference: base.py set_params)."""
        if not params:
            return self
        own = self.get_params(deep=True)
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in own:
                raise ValueError(f"invalid parameter {key} for estimator {self}")
            if delim:
                getattr(self, key).set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in sorted(self.get_params(deep=False).items()))
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """Mixin for all classifiers (reference: base.py:96)."""

    def fit(self, x: DNDarray, y: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray, y: DNDarray) -> DNDarray:
        """Fit then predict on the same data."""
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


class TransformMixin:
    """Mixin for all transformations (reference: base.py:143)."""

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def fit_transform(self, x: DNDarray) -> DNDarray:
        """Fit then transform the same data."""
        return self.fit(x).transform(x)

    def transform(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


class ClusteringMixin:
    """Mixin for all clustering algorithms (reference: base.py:184)."""

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray) -> DNDarray:
        """Fit then return cluster labels."""
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """Mixin for all regression estimators (reference: base.py:215)."""

    def fit(self, x: DNDarray, y: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray, y: DNDarray) -> DNDarray:
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


def is_classifier(estimator: object) -> bool:
    """True if ``estimator`` is a classifier (reference: base.py:260)."""
    return isinstance(estimator, ClassificationMixin)


def is_estimator(estimator: object) -> bool:
    """True if ``estimator`` is an estimator."""
    return isinstance(estimator, BaseEstimator)


def is_clusterer(estimator: object) -> bool:
    """True if ``estimator`` is a clusterer."""
    return isinstance(estimator, ClusteringMixin)


def is_regressor(estimator: object) -> bool:
    """True if ``estimator`` is a regressor."""
    return isinstance(estimator, RegressionMixin)


def is_transformer(estimator: object) -> bool:
    """True if ``estimator`` is a transformer."""
    return isinstance(estimator, TransformMixin)
