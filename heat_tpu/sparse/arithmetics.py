"""Elementwise arithmetic on DCSR matrices.

Parity with /root/reference/heat/sparse/arithmetics.py (``add`` at :16,
``mul`` at :54, exported into ``ht.sparse`` as ``sparse_add``/``sparse_mul``
by the package __init__, plus the ``+``/``*`` dunders)."""

from __future__ import annotations

from . import _operations
from .dcsr_matrix import DCSR_matrix

__all__ = ["add", "mul"]


def add(t1: DCSR_matrix, t2) -> DCSR_matrix:
    """Elementwise addition; result pattern is the union of both operands'
    sparsity patterns (reference arithmetics.py:16)."""
    return _operations.binary_op_csr("add", t1, t2)


def mul(t1: DCSR_matrix, t2) -> DCSR_matrix:
    """Elementwise (Hadamard) multiplication; result pattern is the
    intersection (reference arithmetics.py:54). A scalar operand scales the
    values in place of a pattern op."""
    return _operations.binary_op_csr("mul", t1, t2)


DCSR_matrix.__add__ = lambda self, other: add(self, other)
DCSR_matrix.__radd__ = lambda self, other: add(self, other)
DCSR_matrix.__mul__ = lambda self, other: mul(self, other)
DCSR_matrix.__rmul__ = lambda self, other: mul(self, other)
