"""Sparse linear algebra: SpMV / SpMM / SDDMM for the sparse formats.

The reference's sparse package stops at elementwise ops; a TPU framework
whose sparse type cannot multiply is a shell, so this EXCEEDS reference
parity. Two engines, dispatched on the operand type:

* ``DCSR_matrix`` — the segment-sum formulation over the scalar-entry
  components (the gather/segment-sum pair is XLA's native
  sparse-contraction idiom, what ``jax.experimental.sparse`` BCOO
  lowers to)::

      rows  = searchsorted(indptr, iota(nnz), 'right') - 1   (cached)
      y     = segment_sum(data * x[indices], rows, m)

* ``DBCSR_matrix`` — the brick engine (kernels/spmm.py): dense
  (8,128)x(128,k) brick matmuls behind ``HEAT_TPU_SPMM_KERNEL``,
  shard_map-local on a real mesh (0 collectives).

A split dense operand is resharded to replicated through
``comm.reshard_phys`` FIRST — a planner-stamped plan (shardlint
info-downgrades it), never an implicit GSPMD reshard inside the
contraction program. Sub-f32 data accumulates in f32 and casts back at
the end (SL601-clean by construction).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Union

from ..core import types
from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix
from .dbcsr_matrix import DBCSR_matrix

__all__ = ["matmul", "sddmm"]


def _acc_name(jt) -> str:
    """Accumulation dtype name: f32 for sub-f32 data (SL601)."""
    if jnp.dtype(jt) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return "float32"
    return np.dtype(jt).name


@functools.lru_cache(maxsize=256)
def _spmm_program(comm, m: int, out_ndim: int, out_split, jdtype: str):
    """(rows, phys indices, phys data, x) -> y physical: one compiled
    segment-sum SpMM over the PADDED nnz-sharded components, output
    sharding pinned. Pad entries are contribution-free (data pad is zero
    by framework invariant), so no unpad pass runs; ``rows`` is the
    per-matrix cached COO row map (pad rows map past m and are dropped
    by segment_sum). jit retraces per operand shape, so neither nnz nor
    the dense column count needs a cache key. Accumulation runs in
    ``acc`` (f32 for bf16/f16 inputs), the result casts to ``jdtype``."""
    from ..core import _padding

    def run(rows, indices, data, x):
        jt = jnp.dtype(jdtype)
        acc = jnp.dtype(_acc_name(jt))
        gathered = x.astype(acc)[indices]         # (nnz,) or (nnz, k)
        if gathered.ndim == 1:
            contrib = data.astype(acc) * gathered
        else:
            contrib = data.astype(acc)[:, None] * gathered
        y = jax.ops.segment_sum(contrib, rows, num_segments=m).astype(jt)
        return _padding.pad_logical(y, out_split, comm.size)

    return comm.jit_sharded(run, out_ndim, out_split)


def _dense_operand(A, x) -> jax.Array:
    """Normalize the dense operand to a replicated logical jax array.
    A split DNDarray moves through the redistribution planner (a
    plan-stamped reshard), never through an implicit GSPMD reshard
    inside the contraction program."""
    if isinstance(x, DNDarray):
        if x.split is not None and x.comm.is_distributed():
            return x.comm.reshard_phys(x.larray, x.gshape, x.split, None)
        from ..core import _padding

        return _padding.unpad(x.larray, x.gshape, x.split)
    if isinstance(x, jax.Array):
        return x
    return jnp.asarray(np.asarray(x))


def _check_operand(A, xarr):
    if xarr.ndim not in (1, 2):
        raise ValueError(f"dense operand must be 1-D or 2-D, got {xarr.ndim}-D")
    m, n = A.shape
    if xarr.shape[0] != n:
        raise ValueError(
            f"dimension mismatch: A is {A.shape}, dense operand has leading dim {xarr.shape[0]}"
        )


def matmul(
    A: Union[DCSR_matrix, DBCSR_matrix],
    x: Union[DNDarray, jax.Array, np.ndarray],
) -> DNDarray:
    """``A @ x`` for a distributed sparse matrix and a dense
    vector/matrix.

    Returns a DNDarray of shape (m,) or (m, k), split along axis 0 when
    ``A`` is row-distributed (matching A's distribution rule).
    """
    if isinstance(A, DBCSR_matrix):
        return _matmul_bcsr(A, x)
    if not isinstance(A, DCSR_matrix):
        raise TypeError(f"A must be a DCSR_matrix or DBCSR_matrix, got {type(A)}")
    xarr = _dense_operand(A, x)
    _check_operand(A, xarr)
    m, n = A.shape
    out_dtype = types.promote_types(A.dtype, types.canonical_heat_type(xarr.dtype))
    jt = out_dtype.jax_type()
    comm = A.comm
    split = 0 if A.split == 0 else None
    gshape = (m,) if xarr.ndim == 1 else (m, int(xarr.shape[1]))
    _, phys_indices, phys_data = A._phys_components
    if A.gnnz == 0 or int(phys_indices.shape[0]) == 0:
        # all-zero matrix: no stored elements to contract — the zero
        # result comes straight from the factories (segment_sum over a
        # zero-length operand would still compile a program per shape)
        from ..core import factories as _factories

        return _factories.zeros(
            gshape, dtype=out_dtype, split=split, device=A.device, comm=comm
        )
    prog = _spmm_program(comm, m, len(gshape), split, np.dtype(jt).name)
    phys = prog(A._rows, phys_indices, phys_data, xarr)
    return DNDarray(phys, gshape, out_dtype, split, A.device, comm)


def _matmul_bcsr(A: DBCSR_matrix, x) -> DNDarray:
    """Brick-engine SpMM: decide the path, run the (shard_map-local)
    brick program, wrap the canonical physical output."""
    from ..kernels import spmm as _spmm

    xarr = _dense_operand(A, x)
    _check_operand(A, xarr)
    m, n = A.shape
    out_dtype = types.promote_types(A.dtype, types.canonical_heat_type(xarr.dtype))
    jt = out_dtype.jax_type()
    comm = A.comm
    split = 0 if A.split == 0 else None
    out_ndim = xarr.ndim
    gshape = (m,) if out_ndim == 1 else (m, int(xarr.shape[1]))
    x2d = xarr if out_ndim == 2 else xarr[:, None]
    k = int(x2d.shape[1])
    bdata, bcol, brow, bmask = A._phys_components
    B = A.slab_bricks
    path = _spmm.decide("spmm", B, k, np.dtype(jt).name)
    prog = _spmm.spmm_bcsr_program(
        comm, m, A.nb, B, split, out_ndim, np.dtype(jt).name, path
    )
    phys = prog(bdata, bcol, brow, bmask, x2d)
    return DNDarray(phys, gshape, out_dtype, split, A.device, comm)


def sddmm(
    S: DBCSR_matrix,
    u: Union[DNDarray, jax.Array, np.ndarray],
    v: Union[DNDarray, jax.Array, np.ndarray],
) -> DBCSR_matrix:
    """Sampled dense-dense matmul: ``C = S ∘ (u @ vᵀ)`` computed ONLY on
    the stored bricks of ``S`` (pattern preserved, pad bricks stay
    zero). ``u`` is (m, d), ``v`` is (n, d); the result is a
    DBCSR_matrix sharing S's slab structure."""
    from ..kernels import spmm as _spmm

    if not isinstance(S, DBCSR_matrix):
        raise TypeError(f"S must be a DBCSR_matrix, got {type(S)}")
    uarr = _dense_operand(S, u)
    varr = _dense_operand(S, v)
    m, n = S.shape
    if uarr.ndim != 2 or varr.ndim != 2:
        raise ValueError("sddmm operands must be 2-D (m, d) and (n, d)")
    if uarr.shape[0] != m or varr.shape[0] != n:
        raise ValueError(
            f"dimension mismatch: S is {S.shape}, u is {tuple(uarr.shape)}, "
            f"v is {tuple(varr.shape)}"
        )
    if uarr.shape[1] != varr.shape[1]:
        raise ValueError(
            f"sddmm inner dims differ: {uarr.shape[1]} vs {varr.shape[1]}"
        )
    out_dtype = types.promote_types(
        S.dtype,
        types.promote_types(
            types.canonical_heat_type(uarr.dtype),
            types.canonical_heat_type(varr.dtype),
        ),
    )
    jt = out_dtype.jax_type()
    comm = S.comm
    split = 0 if S.split == 0 else None
    sdata, bcol, brow, bmask = S._phys_components
    B = S.slab_bricks
    d = int(uarr.shape[1])
    path = _spmm.decide("sddmm", B, d, np.dtype(jt).name)
    prog = _spmm.sddmm_bcsr_program(
        comm, S.mb, S.nb, B, split, np.dtype(jt).name, path
    )
    new_bdata = prog(sdata, bcol, brow, uarr, varr)
    return DBCSR_matrix(
        new_bdata, bcol, brow, bmask, S._slab_meta, S.gnnz, S.nbricks,
        S.shape, out_dtype, S.split, S.device, comm,
    )


from ..core.communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_spmm_program)
