"""Sparse linear algebra: SpMV / SpMM for ``DCSR_matrix``.

The reference's sparse package stops at elementwise ops; a TPU framework
whose sparse type cannot multiply is a shell, so this EXCEEDS reference
parity. The formulation is segment-sum based — the gather/segment-sum
pair is XLA's native sparse-contraction idiom (what
``jax.experimental.sparse`` BCOO lowers to) and runs on the sharded
component arrays:

    rows  = searchsorted(indptr, iota(nnz), 'right') - 1   (cached)
    y     = segment_sum(data * x[indices], rows, m)

For a matrix operand the multiply broadcasts over the dense columns.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Union

from ..core import types
from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix

__all__ = ["matmul"]


@functools.lru_cache(maxsize=256)
def _spmm_program(comm, m: int, out_ndim: int, out_split, jdtype: str):
    """(rows, phys indices, phys data, x) -> y physical: one compiled
    segment-sum SpMM over the PADDED nnz-sharded components, output
    sharding pinned. Pad entries are contribution-free (data pad is zero
    by framework invariant), so no unpad pass runs; ``rows`` is the
    per-matrix cached COO row map (pad rows map past m and are dropped
    by segment_sum). jit retraces per operand shape, so neither nnz nor
    the dense column count needs a cache key."""
    from ..core import _padding

    def run(rows, indices, data, x):
        jt = jnp.dtype(jdtype)
        gathered = x.astype(jt)[indices]          # (nnz,) or (nnz, k)
        if gathered.ndim == 1:
            contrib = data.astype(jt) * gathered
        else:
            contrib = data.astype(jt)[:, None] * gathered
        y = jax.ops.segment_sum(contrib, rows, num_segments=m)
        return _padding.pad_logical(y, out_split, comm.size)

    return comm.jit_sharded(run, out_ndim, out_split)


def matmul(A: DCSR_matrix, x: Union[DNDarray, jax.Array, np.ndarray]) -> DNDarray:
    """``A @ x`` for a distributed CSR matrix and a dense vector/matrix.

    Returns a DNDarray of shape (m,) or (m, k), split along axis 0 when
    ``A`` is row-distributed (matching A's distribution rule).
    """
    if not isinstance(A, DCSR_matrix):
        raise TypeError(f"A must be a DCSR_matrix, got {type(A)}")
    if isinstance(x, DNDarray):
        xarr = x.larray
    else:
        xarr = jnp.asarray(np.asarray(x)) if not isinstance(x, jax.Array) else x
    if xarr.ndim not in (1, 2):
        raise ValueError(f"dense operand must be 1-D or 2-D, got {xarr.ndim}-D")
    m, n = A.shape
    if xarr.shape[0] != n:
        raise ValueError(
            f"dimension mismatch: A is {A.shape}, dense operand has leading dim {xarr.shape[0]}"
        )
    out_dtype = types.promote_types(A.dtype, types.canonical_heat_type(xarr.dtype))
    jt = out_dtype.jax_type()
    comm = A.comm
    split = 0 if A.split == 0 else None
    gshape = (m,) if xarr.ndim == 1 else (m, int(xarr.shape[1]))
    _, phys_indices, phys_data = A._phys_components
    prog = _spmm_program(comm, m, len(gshape), split, np.dtype(jt).name)
    phys = prog(A._rows, phys_indices, phys_data, xarr)
    return DNDarray(phys, gshape, out_dtype, split, A.device, comm)

from ..core.communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_spmm_program)
