"""Distributed compressed sparse row matrix.

API parity with /root/reference/heat/sparse/dcsr_matrix.py (``DCSR_matrix``
at dcsr_matrix.py:18): a CSR matrix distributed along axis 0. The reference
stores one ``torch.sparse_csr_tensor`` per MPI rank, chunked by ROWS; local
nnz is whatever falls into the rank's row block, so skewed matrices give
skewed memory/compute. The TPU-native representation is single-controller
and global:

- ``indptr`` — (m+1,) int32, replicated (rows+1 is small relative to nnz);
- ``indices``/``data`` — (gnnz,) sharded EVENLY over the mesh along the
  nnz axis (zero-padded to a mesh multiple, the framework's pad-and-mask
  idiom). Even-nnz sharding load-balances elementwise kernels perfectly —
  the analog of the reference's row-block distribution without its skew.
- COO row indices are derived symbolically (``searchsorted(indptr, iota)``)
  inside kernels — no materialized per-rank row bookkeeping.

Row-chunk views (``lindptr``/``lindices``/``ldata``, the reference's
rank-local tensors at dcsr_matrix.py:148-207) are served for device 0's
row block, computed from the same chunk geometry the dense DNDarray uses.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple, Union

from ..core import types
from ..core.communication import Communication, place as _place, sanitize_comm
from ..core.devices import Device
from ..core.dndarray import DNDarray
from ..core import _padding

__all__ = ["DCSR_matrix"]


class DCSR_matrix:
    """Distributed CSR matrix (reference dcsr_matrix.py:18).

    Parameters
    ----------
    indptr : jax.Array
        Global row pointer, shape (gshape[0] + 1,), replicated.
    indices : jax.Array
        Global column indices, shape (gnnz,) logical; physically padded and
        sharded along the nnz axis when ``split == 0``.
    data : jax.Array
        Global values, same layout as ``indices``.
    gnnz : int
        Global number of stored elements.
    gshape : tuple of int
    dtype : datatype
    split : 0 or None
        Row distribution (only axis 0, as in the reference); None stores
        everything replicated.
    device, comm, balanced : as in DNDarray.
    """

    def __init__(
        self,
        indptr: jax.Array,
        indices: jax.Array,
        data: jax.Array,
        gnnz: int,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: bool = True,
    ):
        if split not in (None, 0):
            raise ValueError(f"DCSR_matrix only supports split=0 or None, got {split}")
        self.__indptr = indptr
        self.__indices = indices
        self.__data = data
        self.__rows_cache = None
        self.__gnnz = int(gnnz)
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = bool(balanced)

    # ------------------------------------------------------------------ #
    # global components                                                  #
    # ------------------------------------------------------------------ #
    def __matmul__(self, other):
        """``A @ x`` — SpMV/SpMM (heat_tpu extension; see sparse.linalg)."""
        from . import linalg as _slinalg

        return _slinalg.matmul(self, other)

    @property
    def indptr(self) -> jax.Array:
        """Global indptr (reference dcsr_matrix.py:155: Allgather of local
        indptrs; here it is stored global)."""
        return self.__indptr

    gindptr = indptr

    @property
    def indices(self) -> jax.Array:
        """Global column indices (reference dcsr_matrix.py:179)."""
        return _padding.unpad(self.__indices, (self.__gnnz,), 0 if self.__split == 0 else None)

    gindices = indices

    @property
    def data(self) -> jax.Array:
        """Global values (reference dcsr_matrix.py:126)."""
        return _padding.unpad(self.__data, (self.__gnnz,), 0 if self.__split == 0 else None)

    gdata = data

    @property
    def _rows(self) -> jax.Array:
        """COO row index per (padded) stored element — constant per
        matrix, derived once and cached (iterative SpMV would otherwise
        re-pay an O(nnz log m) searchsorted per multiply)."""
        if self.__rows_cache is None:
            from ._operations import rows_from_indptr

            rows = rows_from_indptr(self.__indptr, int(self.__indices.shape[0]))
            # keep the nnz-axis layout of indices/data: an unsharded row
            # map would add O(gnnz) resident bytes per device
            if self.__split == 0:
                rows = _place(rows, self.__comm.sharding(1, 0))
            if isinstance(rows, jax.core.Tracer):
                # first touch happened under a trace: caching the tracer
                # would leak it past the trace's lifetime
                return rows
            self.__rows_cache = rows
        return self.__rows_cache

    @property
    def _phys_components(self):
        """(indptr, physical indices, physical data) — padded nnz-sharded
        arrays for compiled kernels (pad entries hold zeros: framework
        invariant, contribution-free under segment_sum)."""
        return self.__indptr, self.__indices, self.__data

    @property
    def component_nbytes(self) -> int:
        """Total bytes of the stored (nnz-padded) components — what the
        operand actually occupies, the number memcheck and the sparse
        transfer pricing use instead of the dense ``m * n`` shape."""
        return sum(
            int(np.prod(c.shape, dtype=np.int64)) * np.dtype(c.dtype).itemsize
            for c in self._phys_components
        )

    @property
    def larray(self):
        """The (indptr, indices, data) triple of device 0's row block —
        the analog of the reference's local torch.sparse_csr_tensor
        (dcsr_matrix.py:119)."""
        return (self.lindptr, self.lindices, self.ldata)

    # ------------------------------------------------------------------ #
    # local (device-0 row block) views                                   #
    # ------------------------------------------------------------------ #
    def _row_block(self, rank: int = 0) -> Tuple[int, int]:
        offset, lshape, _ = self.__comm.chunk(self.__gshape, self.__split, rank=rank)
        return offset, offset + lshape[0]

    @property
    def lindptr(self) -> jax.Array:
        """Local indptr of device 0's row block (reference :172)."""
        if self.__split is None:
            return self.__indptr
        r0, r1 = self._row_block()
        blk = self.__indptr[r0 : r1 + 1]
        return blk - blk[0]

    @property
    def lindices(self) -> jax.Array:
        """Local column indices of device 0's row block (reference :201)."""
        if self.__split is None:
            return self.indices
        r0, r1 = self._row_block()
        lo, hi = int(self.__indptr[r0]), int(self.__indptr[r1])
        return self.indices[lo:hi]

    @property
    def ldata(self) -> jax.Array:
        """Local values of device 0's row block (reference :148)."""
        if self.__split is None:
            return self.data
        r0, r1 = self._row_block()
        lo, hi = int(self.__indptr[r0]), int(self.__indptr[r1])
        return self.data[lo:hi]

    # ------------------------------------------------------------------ #
    # metadata                                                           #
    # ------------------------------------------------------------------ #
    @property
    def balanced(self) -> bool:
        """Row distribution is chunk-canonical, so constructions mark True;
        the stored flag is honored for reference-API parity."""
        return self.__balanced

    @property
    def comm(self) -> Communication:
        return self.__comm

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def nnz(self) -> int:
        """Global number of stored elements (reference :215)."""
        return self.__gnnz

    @property
    def gnnz(self) -> int:
        return self.__gnnz

    @property
    def lnnz(self) -> int:
        """nnz of device 0's row block (reference :229)."""
        if self.__split is None:
            return self.__gnnz
        r0, r1 = self._row_block()
        return int(self.__indptr[r1]) - int(self.__indptr[r0])

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def lshape(self) -> Tuple[int, ...]:
        if self.__split is None:
            return self.__gshape
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split)
        return lshape

    @property
    def split(self) -> Optional[int]:
        return self.__split

    def is_distributed(self) -> bool:
        return self.__split is not None and self.__comm.is_distributed()

    # ------------------------------------------------------------------ #
    # methods                                                            #
    # ------------------------------------------------------------------ #
    def global_indptr(self) -> DNDarray:
        """Global indptr as a DNDarray (reference dcsr_matrix.py:64:
        Exscan of local nnz; here the stored indptr is already global)."""
        if self.__split is None:
            raise ValueError("This method works only for distributed matrices")
        idx_t = types.canonical_heat_type(self.__indptr.dtype)
        return DNDarray(
            _place(self.__indptr, self.__comm.sharding(1, None)),
            (self.__gshape[0] + 1,),
            idx_t,
            None,
            self.__device,
            self.__comm,
        )

    def counts_displs_nnz(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device nnz counts/displacements by ROW block (reference
        :276) — the geometry the reference's Allgatherv would use."""
        if self.__split is None:
            raise ValueError("Non-distributed DCSR_matrix. Cannot calculate counts and displacements.")
        ptr = np.asarray(jax.device_get(self.__indptr))
        counts, displs = [], []
        for r in range(self.__comm.size):
            r0, r1 = self._row_block(rank=r)
            displs.append(int(ptr[r0]))
            counts.append(int(ptr[r1]) - int(ptr[r0]))
        return tuple(counts), tuple(displs)

    def astype(self, dtype, copy: bool = True) -> "DCSR_matrix":
        """Cast values to ``dtype`` (reference :292)."""
        dtype = types.canonical_heat_type(dtype)
        data = self.__data.astype(dtype.jax_type())
        if not copy:
            self.__data = data
            self.__dtype = dtype
            return self
        return DCSR_matrix(
            self.__indptr, self.__indices, data, self.__gnnz, self.__gshape,
            dtype, self.__split, self.__device, self.__comm,
        )

    def todense(self, order: str = "C", out: Optional[DNDarray] = None) -> DNDarray:
        from . import manipulations

        return manipulations.to_dense(self, order=order, out=out)

    to_dense = todense

    def __repr__(self) -> str:
        ptr = np.asarray(jax.device_get(self.__indptr))
        idx = np.asarray(jax.device_get(self.indices))
        dat = np.asarray(jax.device_get(self.data))
        return (
            f"(indptr: {ptr}, indices: {idx}, data: {dat}, "
            f"dtype=ht.{self.__dtype.__name__}, device={self.__device}, split={self.__split})"
        )
