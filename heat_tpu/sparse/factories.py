"""DCSR_matrix factories.

Parity with /root/reference/heat/sparse/factories.py (``sparse_csr_matrix``
at factories.py:23): construct from scipy CSR, torch sparse CSR, dense
array-likes or a DNDarray, with ``split``/``is_split`` semantics. Under the
single-controller model ``is_split=0`` means "these are the per-device row
blocks" — the global matrix is stitched by concatenating components and
offsetting indptrs (the reference's neighbor handshake at factories.py:
100-180 collapses to host arithmetic)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Iterable, Optional, Type

from ..core import types
from ..core.communication import Communication, place as _place, sanitize_comm
from ..core.devices import Device, sanitize_device
from .dcsr_matrix import DCSR_matrix

__all__ = ["sparse_csr_matrix"]


def _shard_nnz(comm, arr: jax.Array, split: Optional[int]) -> jax.Array:
    """Lay an nnz-axis component out on the mesh (padded even blocks)."""
    return comm.shard(arr, 0 if split == 0 else None)


def _from_components(indptr, indices, data, gshape, split, device, comm) -> DCSR_matrix:
    """Build a DCSR_matrix from LOGICAL global CSR components."""
    indptr = jnp.asarray(indptr, dtype=jnp.int32)
    indices = jnp.asarray(indices, dtype=jnp.int32)
    gnnz = int(indices.shape[0])
    dtype = types.canonical_heat_type(data.dtype)
    return DCSR_matrix(
        _place(indptr, comm.sharding(1, None)),
        _shard_nnz(comm, indices, split),
        _shard_nnz(comm, data, split),
        gnnz,
        tuple(int(s) for s in gshape),
        dtype,
        split,
        device,
        comm,
        True,
    )


def _to_scipy_csr(obj, dtype_np=None):
    """Normalize any supported input to a scipy CSR matrix on host."""
    import scipy.sparse as sp

    if sp.issparse(obj):
        return obj.tocsr()
    # torch sparse CSR (the reference's primary input type)
    try:
        import torch

        if isinstance(obj, torch.Tensor):
            if obj.layout == torch.sparse_csr:
                return sp.csr_matrix(
                    (
                        obj.values().numpy(),
                        obj.col_indices().numpy(),
                        obj.crow_indices().numpy(),
                    ),
                    shape=tuple(obj.shape),
                )
            obj = obj.numpy()
    except ImportError:
        pass
    from ..core.dndarray import DNDarray

    if isinstance(obj, DNDarray):
        obj = obj.numpy()
    dense = np.asarray(obj, dtype=dtype_np)
    if dense.ndim != 2:
        raise ValueError(f"sparse_csr_matrix requires 2-D input, got {dense.ndim}-D")
    return sp.csr_matrix(dense)


def sparse_csr_matrix(
    obj: Iterable,
    dtype: Optional[Type[types.datatype]] = None,
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device: Optional[Device] = None,
    comm: Optional[Communication] = None,
) -> DCSR_matrix:
    """Create a DCSR_matrix (reference factories.py:23).

    ``obj`` may be a scipy CSR matrix, a torch sparse-CSR tensor, a dense
    array-like, a DNDarray — or, with ``is_split=0``, a list of per-device
    row blocks in any of those forms.
    """
    if split is not None and split != 0:
        raise ValueError(f"split must be 0 or None, got {split}")
    if is_split is not None and is_split != 0:
        raise ValueError(f"is_split must be 0 or None, got {is_split}")
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    device = sanitize_device(device)
    comm = sanitize_comm(comm)

    dtype_np = np.dtype(types.canonical_heat_type(dtype).jax_type()) if dtype is not None else None

    if is_split is not None and isinstance(obj, (list, tuple)):
        import scipy.sparse as sp

        blocks = [_to_scipy_csr(o, dtype_np) for o in obj]
        csr = sp.vstack(blocks).tocsr()
        split = 0
    else:
        csr = _to_scipy_csr(obj, dtype_np)
        if is_split is not None:
            split = 0  # single block of an already-distributed matrix

    if dtype is None:
        dtype = types.canonical_heat_type(csr.data.dtype if csr.nnz else np.float32)
    data = jnp.asarray(csr.data, dtype=dtype.jax_type())
    return _from_components(
        csr.indptr.astype(np.int32), csr.indices.astype(np.int32), data,
        csr.shape, split, device, comm,
    )
