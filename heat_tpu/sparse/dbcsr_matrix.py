"""Distributed block-CSR matrix with TPU-native (8, 128) bricks.

``DCSR_matrix`` (dcsr_matrix.py) is the API-parity format: scalar nnz
entries sharded evenly over the mesh, SpMV by per-element gather +
segment-sum. That layout is register-hostile on a TPU — every stored
element turns into a 4-byte gather and a 1-lane FLOP. ``DBCSR_matrix``
is the compute format: the stored unit is a full **(8, 128) brick** —
one f32 VREG tile (8 sublanes x 128 lanes), the same quantum the MXU
and VPU consume — so SpMM runs as dense (8,128)x(128,k) brick matmuls
with zero layout waste (see kernels/spmm.py; arXiv:2112.09017's "dense
enough for the hardware" framing applied to sparsity).

Layout (split=0 over brick ROWS, the only distribution — matching the
reference's row-chunk rule):

- the dense shape is padded up to ``(mb*8, nb*128)`` (``mb = ceil(m/8)``,
  ``nb = ceil(n/128)``) and block-compressed host-side; pad rows/cols
  are zero, the framework's pad-and-mask invariant at brick granularity;
- each device owns the bricks intersecting its canonical dense row block
  ``[r*c, (r+1)*c)`` (``c = pad_extent(m, p)/p`` — the SAME chunk
  geometry dense split-0 DNDarrays use, so SpMM outputs land in
  canonical layout with **zero collectives**, see spmm.py). A brick row
  straddling two devices' blocks is stored by BOTH (at most one per
  boundary); the per-entry ``bmask`` marks which of a brick's 8 rows the
  holding device owns, so straddled rows are never double-counted;
- per-device slabs are padded to the mesh-max brick count ``B`` with
  zero bricks (``bmask`` all-false): physical components are EVEN —
  ``bdata`` (p*B, 8, 128), ``bcol``/``brow`` (p*B,), ``bmask`` (p*B, 8)
  — sharded on the slab axis, no skew regardless of structure.

Metadata: ``gnnz`` is the TRUE scalar nnz, ``nbricks`` the global
distinct stored bricks, ``occupancy = gnnz / (nbricks * 1024)`` the
fraction of stored brick slots holding a true nonzero — the density
model PERF.md's sparse section prices bandwidth with.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Tuple

from ..core import types
from ..core import _padding
from ..core.communication import Communication, place as _place, sanitize_comm
from ..core.devices import Device, sanitize_device
from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix

__all__ = ["DBCSR_matrix", "sparse_dbcsr_matrix", "to_dbcsr", "BRICK_SHAPE"]

#: the stored block: 8 sublanes x 128 lanes — one f32 VREG tile
BRICK_SHAPE = (8, 128)


class DBCSR_matrix:
    """Distributed block-CSR matrix with fixed (8, 128) bricks.

    Construct via :func:`sparse_dbcsr_matrix` / :func:`to_dbcsr`; the
    raw constructor takes pre-built physical slab components.
    """

    def __init__(
        self,
        bdata: jax.Array,
        bcol: jax.Array,
        brow: jax.Array,
        bmask: jax.Array,
        slab_meta: Tuple[Tuple[int, int, int], ...],
        gnnz: int,
        nbricks: int,
        gshape: Tuple[int, int],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
    ):
        if split not in (None, 0):
            raise ValueError(f"DBCSR_matrix only supports split=0 or None, got {split}")
        self.__bdata = bdata
        self.__bcol = bcol
        self.__brow = brow
        self.__bmask = bmask
        self.__slab_meta = tuple(tuple(int(v) for v in t) for t in slab_meta)
        self.__gnnz = int(gnnz)
        self.__nbricks = int(nbricks)
        self.__gshape = (int(gshape[0]), int(gshape[1]))
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm

    # ------------------------------------------------------------------ #
    # geometry                                                           #
    # ------------------------------------------------------------------ #
    @property
    def mb(self) -> int:
        """Brick rows: ceil(m / 8)."""
        return -(-max(self.__gshape[0], 1) // BRICK_SHAPE[0])

    @property
    def nb(self) -> int:
        """Brick columns: ceil(n / 128)."""
        return -(-max(self.__gshape[1], 1) // BRICK_SHAPE[1])

    @property
    def slab_bricks(self) -> int:
        """B — bricks per device slab (mesh max, pad-evened)."""
        p = self.__comm.size if self.__split == 0 else 1
        return int(self.__bdata.shape[0]) // max(p, 1)

    @property
    def _phys_components(self):
        """(bdata, bcol, brow, bmask) physical slab arrays for compiled
        kernels. Pad bricks carry zero data and an all-false mask —
        contribution-free under the masked segment-sum."""
        return self.__bdata, self.__bcol, self.__brow, self.__bmask

    @property
    def _slab_meta(self) -> Tuple[Tuple[int, int, int], ...]:
        """Per-device (g0, g1, n_real): brick-row range [g0, g1) held by
        the device and its real (non-pad) brick count."""
        return self.__slab_meta

    # ------------------------------------------------------------------ #
    # metadata                                                           #
    # ------------------------------------------------------------------ #
    @property
    def comm(self) -> Communication:
        return self.__comm

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def ndim(self) -> int:
        return 2

    @property
    def shape(self) -> Tuple[int, int]:
        return self.__gshape

    @property
    def gshape(self) -> Tuple[int, int]:
        return self.__gshape

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def nnz(self) -> int:
        """TRUE scalar nnz (not brick slots)."""
        return self.__gnnz

    gnnz = nnz

    @property
    def nbricks(self) -> int:
        """Global distinct stored bricks (boundary duplicates counted once)."""
        return self.__nbricks

    @property
    def occupancy(self) -> float:
        """Fraction of stored brick slots that hold a true nonzero —
        the brick-density term of the nnz-bandwidth cost model."""
        slots = self.__nbricks * BRICK_SHAPE[0] * BRICK_SHAPE[1]
        return self.__gnnz / slots if slots else 0.0

    @property
    def component_nbytes(self) -> int:
        """Per-mesh resident bytes of the physical components (what
        memcheck prices a DBCSR operand at — brick-padded, not dense)."""
        return sum(
            int(np.prod(a.shape, dtype=np.int64)) * np.dtype(a.dtype).itemsize
            for a in self._phys_components
        )

    def is_distributed(self) -> bool:
        return self.__split is not None and self.__comm.is_distributed()

    # ------------------------------------------------------------------ #
    # ops                                                                #
    # ------------------------------------------------------------------ #
    def __matmul__(self, other):
        """``A @ x`` — brick SpMM (kernels/spmm.py via sparse.linalg)."""
        from . import linalg as _slinalg

        return _slinalg.matmul(self, other)

    def astype(self, dtype, copy: bool = True) -> "DBCSR_matrix":
        dtype = types.canonical_heat_type(dtype)
        bdata = self.__bdata.astype(dtype.jax_type())
        if not copy:
            self.__bdata = bdata
            self.__dtype = dtype
            return self
        return DBCSR_matrix(
            bdata, self.__bcol, self.__brow, self.__bmask, self.__slab_meta,
            self.__gnnz, self.__nbricks, self.__gshape, dtype, self.__split,
            self.__device, self.__comm,
        )

    # ------------------------------------------------------------------ #
    # conversions                                                        #
    # ------------------------------------------------------------------ #
    def _to_scipy_bsr(self):
        """Reassemble the global scipy BSR host-side: each device
        contributes the bricks of the rows it FIRST covers (boundary
        bricks are deduplicated by ownership order)."""
        import scipy.sparse as sp

        bdata = np.asarray(jax.device_get(self.__bdata))
        if bdata.dtype.itemsize < 4 and bdata.dtype.kind not in "iub":
            # scipy kernels reject ml_dtypes (bfloat16/float16 bricks):
            # assemble in f32, exact for every sub-f32 value
            bdata = bdata.astype(np.float32)
        bcol = np.asarray(jax.device_get(self.__bcol))
        brow = np.asarray(jax.device_get(self.__brow))
        B = self.slab_bricks
        rows_parts, cols_parts, data_parts = [], [], []
        prev_end = 0
        for r, (g0, g1, nreal) in enumerate(self.__slab_meta):
            lo, hi = r * B, r * B + nreal
            sl_rows = brow[lo:hi]
            keep = sl_rows >= prev_end  # rows [g0, prev_end) owned upstream
            rows_parts.append(sl_rows[keep])
            cols_parts.append(bcol[lo:hi][keep])
            data_parts.append(bdata[lo:hi][keep])
            prev_end = max(prev_end, g1)
        browg = np.concatenate(rows_parts) if rows_parts else np.zeros(0, np.int32)
        bcolg = np.concatenate(cols_parts) if cols_parts else np.zeros(0, np.int32)
        bdatag = (
            np.concatenate(data_parts)
            if data_parts
            else np.zeros((0,) + BRICK_SHAPE, np.dtype(self.__dtype.jax_type()))
        )
        mb, nb = self.mb, self.nb
        indptr = np.zeros(mb + 1, dtype=np.int64)
        np.add.at(indptr, browg + 1, 1)
        indptr = np.cumsum(indptr)
        return sp.bsr_matrix(
            (bdatag, bcolg, indptr),
            shape=(mb * BRICK_SHAPE[0], nb * BRICK_SHAPE[1]),
            blocksize=BRICK_SHAPE,
        )

    def to_dcsr(self) -> DCSR_matrix:
        """Back to the scalar-entry API format (true nonzeros only)."""
        from .factories import _from_components

        csr = self._to_scipy_bsr().tocsr()
        csr.eliminate_zeros()
        m, n = self.__gshape
        csr.resize((m, n))
        csr = csr.tocsr()
        data = jnp.asarray(csr.data, dtype=self.__dtype.jax_type())
        return _from_components(
            csr.indptr.astype(np.int32), csr.indices.astype(np.int32), data,
            (m, n), self.__split, self.__device, self.__comm,
        )

    def todense(self) -> DNDarray:
        from ..core import factories as _factories

        m, n = self.__gshape
        dense = self._to_scipy_bsr().toarray()[:m, :n]
        return _factories.array(
            dense, dtype=self.__dtype, split=self.__split,
            device=self.__device, comm=self.__comm,
        )

    to_dense = todense

    def __repr__(self) -> str:
        return (
            f"DBCSR_matrix(shape={self.__gshape}, bricks={self.__nbricks} of "
            f"{BRICK_SHAPE}, nnz={self.__gnnz}, occupancy={self.occupancy:.3f}, "
            f"dtype=ht.{self.__dtype.__name__}, split={self.__split})"
        )


# --------------------------------------------------------------------- #
# factories                                                             #
# --------------------------------------------------------------------- #
def _slab_layout(m: int, mb: int, p: int) -> Tuple[Tuple[int, int], ...]:
    """Per-device brick-row range [g0, g1): the bricks intersecting the
    device's canonical dense row block [r*c, (r+1)*c)."""
    c = _padding.pad_extent(m, p) // p if p > 1 else max(m, 1)
    out = []
    for r in range(p):
        lo, hi = r * c, min((r + 1) * c, mb * BRICK_SHAPE[0])
        if hi <= lo:
            out.append((mb, mb))
            continue
        g0 = min(lo // BRICK_SHAPE[0], mb)
        g1 = min(-(-hi // BRICK_SHAPE[0]), mb)
        out.append((g0, g1))
    return tuple(out)


def sparse_dbcsr_matrix(
    obj,
    dtype=None,
    split: Optional[int] = None,
    device: Optional[Device] = None,
    comm: Optional[Communication] = None,
) -> DBCSR_matrix:
    """Create a DBCSR_matrix from scipy sparse, a dense array-like, a
    DNDarray, or a DCSR_matrix. ``split=0`` distributes brick rows by
    the canonical dense chunk geometry; ``None`` replicates."""
    from .factories import _to_scipy_csr
    import scipy.sparse as sp

    if split is not None and split != 0:
        raise ValueError(f"split must be 0 or None, got {split}")
    device = sanitize_device(device)
    comm = sanitize_comm(comm)

    if isinstance(obj, DCSR_matrix):
        if split is None and obj.split == 0:
            split = 0
        csr = sp.csr_matrix(
            (
                np.asarray(jax.device_get(obj.data)),
                np.asarray(jax.device_get(obj.indices)),
                np.asarray(jax.device_get(obj.indptr)),
            ),
            shape=obj.shape,
        )
        if dtype is None:
            dtype = obj.dtype
    else:
        dtype_np = (
            np.dtype(types.canonical_heat_type(dtype).jax_type())
            if dtype is not None else None
        )
        csr = _to_scipy_csr(obj, dtype_np)

    m, n = int(csr.shape[0]), int(csr.shape[1])
    if dtype is None:
        dtype = types.canonical_heat_type(csr.data.dtype if csr.nnz else np.float32)
    else:
        dtype = types.canonical_heat_type(dtype)
    jt = dtype.jax_type()
    gnnz = int(csr.nnz)

    mb = -(-max(m, 1) // BRICK_SHAPE[0])
    nb = -(-max(n, 1) // BRICK_SHAPE[1])
    csr = csr.astype(np.dtype(jt)).copy()
    csr.resize((mb * BRICK_SHAPE[0], nb * BRICK_SHAPE[1]))
    bsr = csr.tobsr(blocksize=BRICK_SHAPE)
    bsr.sort_indices()
    bindptr = bsr.indptr.astype(np.int64)
    bcol_g = bsr.indices.astype(np.int32)
    bdata_g = np.asarray(bsr.data)
    nbricks = int(bcol_g.shape[0])
    brow_g = np.repeat(
        np.arange(mb, dtype=np.int32), np.diff(bindptr).astype(np.int64)
    )

    p = comm.size if split == 0 else 1
    c = _padding.pad_extent(m, p) // p if p > 1 else max(m, 1)
    ranges = _slab_layout(m, mb, p)
    counts = [int(bindptr[g1] - bindptr[g0]) for g0, g1 in ranges]
    B = max(1, max(counts) if counts else 1)

    bdata = np.zeros((p * B, *BRICK_SHAPE), dtype=np.dtype(jt))
    bcol = np.zeros((p * B,), dtype=np.int32)
    brow = np.zeros((p * B,), dtype=np.int32)
    bmask = np.zeros((p * B, BRICK_SHAPE[0]), dtype=bool)
    slab_meta = []
    for r, (g0, g1) in enumerate(ranges):
        s0, s1 = int(bindptr[g0]), int(bindptr[g1])
        nreal = s1 - s0
        lo = r * B
        bdata[lo : lo + nreal] = bdata_g[s0:s1]
        bcol[lo : lo + nreal] = bcol_g[s0:s1]
        rows_r = brow_g[s0:s1]
        brow[lo : lo + nreal] = rows_r
        # which of each brick's 8 dense rows fall in THIS device's block
        dense_rows = rows_r[:, None] * BRICK_SHAPE[0] + np.arange(
            BRICK_SHAPE[0], dtype=np.int32
        )
        blk_lo, blk_hi = r * c, (r + 1) * c
        bmask[lo : lo + nreal] = (dense_rows >= blk_lo) & (dense_rows < blk_hi)
        slab_meta.append((g0, g1, nreal))

    slab_split = 0 if split == 0 else None
    return DBCSR_matrix(
        _place(jnp.asarray(bdata), comm.sharding(3, slab_split)),
        _place(jnp.asarray(bcol), comm.sharding(1, slab_split)),
        _place(jnp.asarray(brow), comm.sharding(1, slab_split)),
        _place(jnp.asarray(bmask), comm.sharding(2, slab_split)),
        tuple(slab_meta),
        gnnz,
        nbricks,
        (m, n),
        dtype,
        split,
        device,
        comm,
    )


def to_dbcsr(A, split: Optional[int] = None) -> DBCSR_matrix:
    """Convert a DCSR_matrix / DNDarray / array-like to DBCSR, keeping
    the source's distribution unless ``split`` overrides it."""
    if isinstance(A, DCSR_matrix):
        return sparse_dbcsr_matrix(
            A, split=A.split if split is None else split,
            device=A.device, comm=A.comm,
        )
    if isinstance(A, DNDarray):
        return sparse_dbcsr_matrix(
            A, split=A.split if split is None else split,
            device=A.device, comm=A.comm,
        )
    return sparse_dbcsr_matrix(A, split=split)
