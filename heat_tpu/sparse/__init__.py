"""Sparse layer of heat_tpu.

Parity with /root/reference/heat/sparse/__init__.py: ``DCSR_matrix``,
``sparse_csr_matrix``, ``sparse_add``/``sparse_mul``, ``to_dense``/
``to_sparse``. The rest EXCEEDS the reference, whose sparse type has no
multiplication: ``matmul`` (SpMV/SpMM), the TPU-native block-CSR format
``DBCSR_matrix`` with fixed (8, 128) VREG bricks
(``sparse_dbcsr_matrix``/``to_dbcsr``), and ``sddmm`` on the brick
format (pattern-preserving sampled dense-dense matmul)."""

from .dcsr_matrix import DCSR_matrix
from .dbcsr_matrix import BRICK_SHAPE, DBCSR_matrix, sparse_dbcsr_matrix, to_dbcsr
from .factories import sparse_csr_matrix
from .arithmetics import add, mul
from .arithmetics import add as sparse_add, mul as sparse_mul
from .manipulations import to_dense, to_sparse
from .linalg import matmul, sddmm

__all__ = [
    "BRICK_SHAPE",
    "DBCSR_matrix",
    "DCSR_matrix",
    "sparse_csr_matrix",
    "sparse_dbcsr_matrix",
    "to_dbcsr",
    "add",
    "mul",
    "sparse_add",
    "sparse_mul",
    "to_dense",
    "to_sparse",
    "matmul",
    "sddmm",
]
