"""Sparse layer of heat_tpu.

Parity with /root/reference/heat/sparse/__init__.py: ``DCSR_matrix``,
``sparse_csr_matrix``, ``sparse_add``/``sparse_mul``, ``to_dense``/
``to_sparse``. ``matmul`` (SpMV/SpMM) EXCEEDS the reference, whose
sparse type has no multiplication."""

from .dcsr_matrix import DCSR_matrix
from .factories import sparse_csr_matrix
from .arithmetics import add, mul
from .arithmetics import add as sparse_add, mul as sparse_mul
from .manipulations import to_dense, to_sparse
from .linalg import matmul

__all__ = [
    "DCSR_matrix",
    "sparse_csr_matrix",
    "add",
    "mul",
    "sparse_add",
    "sparse_mul",
    "to_dense",
    "to_sparse",
    "matmul",
]
