"""Elementwise binary machinery for DCSR matrices.

Replaces /root/reference/heat/sparse/_operations.py (``__binary_op_csr`` at
_operations.py:17, which calls torch's sparse CSR kernels per rank). The
TPU formulation must be static-shape: the union/intersection pattern of two
sparse operands is data-dependent, so the kernel works on a fixed
``n1 + n2`` candidate set (pad-and-mask idiom) inside ONE jit:

1. linearize both operands to keys ``row * ncols + col``;
2. sort the concatenated candidates (each key appears at most twice, once
   per operand — CSR patterns are duplicate-free);
3. merge adjacent equal keys, summing each operand's contribution;
4. combine (add → a + b, union pattern; mul → a * b, intersection);
5. compact kept entries to the front with a cumsum scatter and rebuild the
   indptr with a masked bincount.

The result count reaches the host as one scalar; everything else stays on
device. Sorting rides XLA's parallel sort — nnz-sharded inputs keep every
device busy, unlike the reference's per-row-block kernels.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core import types
from .dcsr_matrix import DCSR_matrix

__all__ = []


@functools.lru_cache(maxsize=256)
def _binary_csr_kernel(op_key: str, n1: int, n2: int, m: int, ncols: int, jdtype: str):
    n = n1 + n2
    # linearized keys must not overflow: int64 once m*ncols exceeds int32
    key_dt = types.wide_jax_type('i') if m * ncols > np.iinfo(np.int32).max else jnp.int32

    @jax.jit
    def kernel(cols1, data1, rows1, cols2, data2, rows2):
        keys = jnp.concatenate(
            [
                rows1.astype(key_dt) * ncols + cols1.astype(key_dt),
                rows2.astype(key_dt) * ncols + cols2.astype(key_dt),
            ]
        )
        a = jnp.concatenate([data1, jnp.zeros((n2,), dtype=data1.dtype)])
        b = jnp.concatenate([jnp.zeros((n1,), dtype=data2.dtype), data2])
        order = jnp.argsort(keys)
        k = keys[order]
        a = a[order]
        b = b[order]
        # duplicate keys are adjacent; fold the previous slot's contribution
        # into the current one (each key appears at most twice)
        dup = jnp.concatenate([jnp.zeros((1,), bool), k[1:] == k[:-1]])
        a_m = a + jnp.where(dup, jnp.roll(a, 1), 0)
        b_m = b + jnp.where(dup, jnp.roll(b, 1), 0)
        if op_key == "add":
            val = a_m + b_m
            # union pattern: keep the LAST slot of each key group
            keep = jnp.concatenate([k[1:] != k[:-1], jnp.ones((1,), bool)])
        elif op_key == "mul":
            val = a_m * b_m
            # intersection pattern: keep only merged (both-present) slots
            keep = dup
        else:
            raise ValueError(op_key)
        count = jnp.sum(keep)
        # stable compaction: kept entry i lands at position cumsum-1;
        # dropped entries park out of range and are discarded by mode="drop"
        pos = jnp.cumsum(keep) - 1
        dest = jnp.where(keep, pos, n + jnp.arange(n))
        out_keys = jnp.zeros((n,), dtype=k.dtype).at[dest].set(k, mode="drop")
        out_vals = jnp.zeros((n,), dtype=val.dtype).at[dest].set(val, mode="drop")
        valid = jnp.arange(n) < count
        out_rows = jnp.where(valid, out_keys // ncols, 0)
        out_cols = jnp.where(valid, out_keys % ncols, 0)
        counts = jnp.zeros((m + 1,), dtype=jnp.int32).at[out_rows + 1].add(
            valid.astype(jnp.int32)
        )
        indptr = jnp.cumsum(counts)
        return indptr.astype(jnp.int32), out_cols.astype(jnp.int32), out_vals, count

    return kernel


def rows_from_indptr(indptr: jax.Array, nnz: int) -> jax.Array:
    """COO row index per stored element, derived symbolically (static
    shapes): rows[i] = searchsorted(indptr, i, 'right') - 1."""
    return (
        jnp.searchsorted(indptr, jnp.arange(nnz, dtype=indptr.dtype), side="right") - 1
    ).astype(jnp.int32)


def binary_op_csr(op_key: str, t1: DCSR_matrix, t2) -> DCSR_matrix:
    """Elementwise binary op on two DCSR matrices (or matrix × scalar for
    mul). Reference: _operations.py:17."""
    if np.isscalar(t2) or isinstance(t2, (int, float)):
        if op_key == "mul":
            # promote like dense arithmetic: int matrix x float scalar -> float
            scalar_type = types.canonical_heat_type(type(t2))
            out_type = types.promote_types(t1.dtype, scalar_type)
            jdt = out_type.jax_type()
            data = t1.data.astype(jdt) * jnp.asarray(t2, dtype=jdt)
            from .factories import _from_components

            return _from_components(
                t1.indptr, t1.indices, data, t1.shape, t1.split, t1.device, t1.comm
            )
        raise TypeError(
            "sparse add with a scalar densifies the matrix; convert with to_dense first "
            "(matches the reference's unsupported-op behavior)"
        )
    if not isinstance(t2, DCSR_matrix):
        raise TypeError(f"expected DCSR_matrix or scalar, got {type(t2)}")
    if t1.shape != t2.shape:
        raise ValueError(f"shapes do not match: {t1.shape} vs {t2.shape}")

    out_type = types.promote_types(t1.dtype, t2.dtype)
    jdt = out_type.jax_type()
    m, ncols = t1.shape
    n1, n2 = t1.gnnz, t2.gnnz

    rows1 = rows_from_indptr(t1.indptr, n1)
    rows2 = rows_from_indptr(t2.indptr, n2)
    kernel = _binary_csr_kernel(op_key, n1, n2, m, ncols, np.dtype(jdt).name)
    indptr, cols_p, vals_p, count = kernel(
        t1.indices, t1.data.astype(jdt), rows1, t2.indices, t2.data.astype(jdt), rows2
    )
    nnz = int(count)
    from .factories import _from_components

    return _from_components(
        indptr, cols_p[:nnz], vals_p[:nnz], (m, ncols),
        t1.split if t1.split is not None else t2.split,
        t1.device, t1.comm,
    )
